"""Benchmark: Llama training throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
structured extras ("mfu_2048", "tok_s_8192", "mfu_8192", "params_b",
"device_kind", and "error" on failure) so the driver's parse never depends on
prose inside the unit string.

Architecture (hard-won across rounds):

- **Supervisor/child split.** Round 2's evidence was erased by one transient
  ``UNAVAILABLE: TPU backend setup/compile error`` at ``jax.devices()`` —
  and JAX caches a failed backend for the life of the process, so in-process
  retry is useless. ``python bench.py`` therefore supervises: it launches
  itself with ``--child`` in a subprocess, retries retryable failures
  (UNAVAILABLE / init / DEADLINE / hangs) with bounded backoff, steps down a
  config ladder on RESOURCE_EXHAUSTED, and after the final failure emits a
  parseable error JSON instead of a traceback.

- **Model scale.** BASELINE.md frames the target as 7B-class FSDP training;
  334M (rounds 1-2) is too small to predict that regime. The child benches a
  ~1.06B-param Llama (hidden 2048, inter 5632, 18 layers) at seq 2048 AND
  8192. On a 16GB chip (v5e) the 1B + Adam working set only fits with bf16
  params + bf16 optimizer moments (the PaLM-style TPU recipe); with >=30GB
  HBM the child keeps fp32 masters. The choice is recorded in the unit
  string.

- **Timing.** The axon remote runtime's ``block_until_ready`` does not
  actually block, and the first post-warmup step pays a second compile
  (donated-buffer layout), so the loop warms up twice and the barrier is a
  host fetch of the final loss — which transitively waits on every chained
  step.

Attention runs the Pallas flash kernel (ops/pallas_flash.py) under the
"dots" remat policy at seq 2048 (keep matmul outputs, recompute elementwise —
the winner of benchmarks/ablate.py's sweep) and the leaner "flash" policy at
seq 8192 where dots residuals no longer fit.
"""

import argparse
import json
import os
import random
import subprocess
import sys
import threading
import time

import numpy as np

METRIC = "llama_fsdp_train_tokens_per_sec_per_chip"
MFU_TARGET = 0.45  # BASELINE.md contract: >=45% MFU

# Round-3 postmortem: the driver's own timeout killed bench.py with an EMPTY
# tail because all evidence was buffered until exit. Two rules now hold:
#   1. EVERY probe / attempt / partial measurement is emitted *immediately* as
#      a complete result-shaped JSON line (metric/value/unit/vs_baseline), so
#      any kill point leaves the latest state as the last line of the tail.
#   2. The supervisor deadline must fit inside the driver's budget. Default
#      16 min, overridable via BENCH_DEADLINE_S.
try:
    DEADLINE_S = int(os.environ.get("BENCH_DEADLINE_S", "") or 16 * 60)
except ValueError:  # a malformed knob must not erase all evidence at import
    DEADLINE_S = 16 * 60


def _emit(value: float, unit: str, vs_baseline: float, **extra) -> dict:
    """Print one self-contained evidence row NOW (flushed).

    Heartbeats and partials use the same schema as the final row so the
    driver's last-JSON-line parse always lands on something valid.
    """
    row = {"metric": METRIC, "value": value, "unit": unit, "vs_baseline": vs_baseline}
    row.update(extra)
    print(json.dumps(row), flush=True)
    return row

# Substrings (case-insensitive) in stderr that mean "try again, the backend
# may come back" — exactly the failure class that erased round 2's numbers.
RETRYABLE = (
    "unavailable",
    "unable to initialize backend",
    "backend setup/compile error",
    "deadline_exceeded",
    "aborted",
    "connection reset",
    "socket closed",
    "failed to connect",
)

# bf16 peak FLOP/s per chip by device_kind substring (lowercase).
PEAK_FLOPS = [
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5", 197e12),  # v5e / "v5 lite"
    ("v4", 275e12),
    ("v3", 61.25e12),  # per core
    ("v2", 22.5e12),
]


def _peak_flops(device_kind: str) -> float:
    kind = (device_kind or "").lower()
    for sub, flops in PEAK_FLOPS:
        if sub in kind:
            return flops
    return 197e12  # unknown TPU: assume v5e-class


def _hbm_bytes() -> int:
    """Per-device HBM limit; conservative 16GB when the backend won't say."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
        limit = int(stats.get("bytes_limit", 0)) if stats else 0
        if limit > 0:
            return limit
    except Exception:
        pass
    return 16 * 1024**3


def _build_config(seq: int, oom_level: int, big_hbm: bool):
    """~1.06B-param Llama. The OOM ladder shrinks batch/remat, never the
    model — the >=1B scale is the point of the bench."""
    import jax.numpy as jnp

    from accelerate_tpu.models import LlamaConfig

    if seq <= 2048:
        batch = 2 if oom_level == 0 else 1
        policy = "dots" if oom_level < 2 else "flash"
    else:
        batch = 1
        policy = "flash" if oom_level < 2 else "minimal"
    if big_hbm and oom_level == 0:
        batch *= 2
    cfg = LlamaConfig(
        vocab_size=32000,
        hidden_size=2048,
        intermediate_size=5632,
        num_hidden_layers=18,
        num_attention_heads=16,
        num_key_value_heads=16,
        max_position_embeddings=seq,
        dtype=jnp.bfloat16,
        remat=True,
        remat_policy=policy,
        attention_impl="flash",
    )
    return cfg, batch


def _measure(seq: int, iters: int, oom_level: int, on_chip: bool, fp8: bool = False):
    import tempfile

    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaForCausalLM, cross_entropy_loss
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import (
        FullyShardedDataParallelPlugin,
        TelemetryKwargs,
        set_seed,
    )

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    set_seed(0)

    hbm = _hbm_bytes()
    big_hbm = hbm >= 30 * 1024**3
    if on_chip:
        cfg, batch = _build_config(seq, oom_level, big_hbm)
    else:
        from accelerate_tpu.models import LlamaConfig

        cfg, batch, seq = LlamaConfig.tiny(dtype=jnp.bfloat16), 4, 128
    if fp8:
        import dataclasses as _dc

        # Native f8-operand dots in every projection (ops/fp8.py); the
        # BASELINE.md comparable is the torchao Float8Linear +25% row.
        cfg = _dc.replace(cfg, fp8=True, fp8_format="HYBRID")

    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(batch, seq + 1), dtype=np.int32)

    # Telemetry rides along in non-blocking mode (sync_timing=False): per-step
    # dispatch walls converge to the true step time once the device queue
    # backs up, and the async pipeline the bench measures stays untouched.
    # The summary (mean/p50/p90 step time, recompiles, peak HBM) lands in the
    # emitted rows so future rounds get a comparable perf trajectory.
    acc = Accelerator(
        mixed_precision="bf16",
        fsdp_plugin=FullyShardedDataParallelPlugin(),
        kwargs_handlers=[
            TelemetryKwargs(
                straggler_probe_every=0,
                log_every=0,
                output_dir=tempfile.mkdtemp(prefix="bench_telemetry_"),
                tracing=bool(os.environ.get("BENCH_TRACE_OUT")),
                # Device-time attribution rides along (lagged one step, zero
                # extra device syncs). capture_cost stays off: the AOT
                # cost_analysis compile would inflate warmup_s, the bench's
                # cold-start headline, and without an auto-plan there is no
                # bandwidth pricing to feed anyway.
                profile={"capture_cost": False},
            )
        ],
    )
    model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
    # 16GB chips cannot hold 1B fp32 masters + fp32 Adam moments + grads;
    # use the bf16-everything TPU recipe there and fp32 masters when HBM allows.
    precision = "fp32-masters" if big_hbm else "bf16-params+opt"
    if on_chip and not big_hbm:
        model.params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), model.params)
        tx = optax.adamw(3e-4, weight_decay=0.1, mu_dtype=jnp.bfloat16)
    else:
        tx = optax.adamw(3e-4, weight_decay=0.1)
    model, _ = acc.prepare(model, tx)
    n_params = model.num_parameters()

    def loss_fn(params, b):
        logits = module.apply({"params": params}, b["x"])
        return cross_entropy_loss(logits, b["y"])

    step = acc.prepare_train_step(loss_fn, max_grad_norm=1.0)
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(acc.mesh, PartitionSpec(("dp_replicate", "dp_shard")))
    b = {
        "x": jax.device_put(ids[:, :-1], sharding),
        "y": jax.device_put(ids[:, 1:], sharding),
    }

    state = acc.train_state
    # Two warmups: initial compile + the donated-buffer-layout recompile.
    # Timed: warmup_s is the compile stall a cold start pays (the compile
    # manager's manifest warmup moves exactly this off the training clock).
    t_w = time.perf_counter()
    for _ in range(2):
        state, metrics = step(state, b)
        float(np.asarray(metrics["loss"]))
    warmup_s = time.perf_counter() - t_w

    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, b)
    loss = float(np.asarray(metrics["loss"]))  # host fetch = the real barrier
    dt = (time.perf_counter() - t0) / iters
    assert np.isfinite(loss), f"non-finite loss {loss}"

    telemetry = acc.telemetry.summary() if acc.telemetry is not None else None
    trace_out = os.environ.get("BENCH_TRACE_OUT")
    if (trace_out and acc.telemetry is not None
            and getattr(acc.telemetry, "tracing", None) is not None):
        acc.telemetry.tracing.export_chrome_trace(trace_out)

    devices = jax.devices()
    n_devices = len(devices)
    kind = getattr(devices[0], "device_kind", "") or devices[0].platform
    tok_s_chip = batch * seq / dt / n_devices
    # MFU: ~6*N FLOPs/token for fwd+bwd + attention term 12*L*H*S per token.
    attn_flops_per_token = 12 * cfg.num_hidden_layers * cfg.hidden_size * seq
    flops_per_token = 6 * n_params + attn_flops_per_token
    peak = _peak_flops(kind) if on_chip else 1e12
    mfu = tok_s_chip * flops_per_token / peak
    return {
        "tok_s": tok_s_chip,
        "seq": seq,
        "mfu": mfu,
        "n_params": n_params,
        "batch": batch,
        "device_kind": kind,
        "precision": precision,
        "remat_policy": cfg.remat_policy,
        "warmup_s": warmup_s,
        "telemetry": telemetry,
    }


def child(oom_level: int, budget_s: float = 1e9) -> int:
    t_child0 = time.monotonic()

    def remaining() -> float:
        return budget_s - (time.monotonic() - t_child0)

    import jax

    from accelerate_tpu.utils.environment import honor_jax_platforms_env

    honor_jax_platforms_env()

    platform = jax.devices()[0].platform
    on_chip = platform in ("tpu", "axon")
    _emit(0.0, f"HEARTBEAT: child up, platform={platform}, measuring seq 2048", 0.0,
          event="child_start", phase="seq2048", oom_level=oom_level)
    r2k = _measure(2048, 30 if on_chip else 3, oom_level, on_chip)

    def unit_2k(extra: str = "") -> str:
        return (
            f"tokens/s/chip (bf16 compute, {r2k['precision']}, "
            f"{r2k['n_params'] / 1e9:.2f}B params, seq {r2k['seq']} batch {r2k['batch']}, "
            f"flash+{r2k['remat_policy']}-remat, MFU {r2k['mfu']:.3f}{extra})"
        )

    result = {
        "mfu_2048": round(r2k["mfu"], 4),
        "params_b": round(r2k["n_params"] / 1e9, 3),
        "device_kind": r2k["device_kind"],
        "platform": platform,
        # Cold-start compile stall (the 2-step warmup loop, dominated by the
        # XLA compiles) — the number the compile manager's AOT warmup and
        # persistent cache exist to shrink across rounds.
        "warmup_s_2048": round(r2k["warmup_s"], 2),
    }
    if r2k.get("telemetry"):
        # Step-time distribution + recompile/HBM/executable accounting from
        # the telemetry subsystem (telemetry.py) — BENCH_*.json carries it so
        # future rounds can compare trajectories, not just the headline mean.
        t = r2k["telemetry"]
        result["telemetry"] = {
            k: t.get(k)
            for k in (
                "steps",
                "step_time_mean_s",
                "step_time_p50_s",
                "step_time_p90_s",
                "data_wait_mean_s",
                "recompiles",
                "peak_hbm_bytes",
                "executables",
            )
        }
        # Checkpoint cost block (save_s, verify_s, retries, ... —
        # telemetry.py summary): rows carry it so checkpoint-cost
        # regressions show up in the perf trajectory alongside step times.
        if t.get("checkpoint"):
            ck = t["checkpoint"]
            result["telemetry"]["checkpoint"] = {
                k: ck.get(k)
                for k in ("saves", "save_s", "verify_s", "retries",
                          "torn_skipped", "rollbacks")
            }
        # Auto-parallelism plan block (planner.py via telemetry.note_plan):
        # predicted vs measured step time / peak HBM + calibration state —
        # rows carry it so cost-model drift shows up in the perf trajectory.
        if t.get("plan"):
            pl = t["plan"]
            result["telemetry"]["plan"] = {
                k: pl.get(k)
                for k in ("layout", "predicted_step_s", "measured_step_p50_s",
                          "step_time_ratio", "predicted_hbm_gib",
                          "measured_peak_hbm_gib", "hbm_ratio", "calibrated",
                          "mfu_effective")
            }
        # Device-time attribution block (profiler.py via telemetry summary):
        # term means (compute / exposed comm / data wait / skew / dispatch),
        # comm-compute overlap ratio, and per-axis achieved-bandwidth
        # residuals — rows carry it so WHERE the step time went travels with
        # HOW MUCH it was across rounds.
        if t.get("profile"):
            pr = t["profile"]
            result["telemetry"]["profile"] = {
                k: pr.get(k)
                for k in ("steps", "cost_captured", "overlap_ratio_mean",
                          "terms_mean_s", "tick_terms_mean_s",
                          "bandwidth_residuals")
            }
        # Training-chaos block (fault_tolerance.py via flush_telemetry):
        # injected-fault and step-watchdog counters ride along so a
        # chaos-enabled bench round shows its fault/stall activity next to
        # the step times it perturbed.
        if t.get("faults"):
            result["telemetry"]["faults"] = {
                k: t["faults"].get(k) for k in ("injected", "by_site")
            }
        # SDC block (sdc.py via telemetry summary): integrity-vote and
        # probe counters ride next to the faults block so an sdc-enabled
        # round shows whether the sentinel voted, convicted, or repaired
        # alongside the step times the digests rode on.
        if t.get("sdc"):
            result["telemetry"]["sdc"] = {
                k: t["sdc"].get(k)
                for k in ("vote_every", "votes", "mismatches", "probes",
                          "repairs", "quarantines")
            }
        if t.get("watchdog"):
            wd = t["watchdog"]
            result["telemetry"]["watchdog"] = {
                k: wd.get(k)
                for k in ("policy", "warnings", "stalls", "escalations",
                          "straggler_events", "heartbeats")
            }
        # Serving block (TTFT/TPOT/occupancy/tokens-per-s — serving.py via
        # telemetry.record_serving): rows carry it like the checkpoint and
        # compile blocks so serving-throughput regressions show up in the
        # perf trajectory.
        if t.get("serving"):
            sv = t["serving"]
            result["telemetry"]["serving"] = {
                k: sv.get(k)
                for k in ("requests_completed", "tokens_per_s", "ttft_p50_s",
                          "ttft_p95_s", "tpot_mean_s", "mean_occupancy",
                          "steady_recompiles", "decode_executables",
                          "faults")
            }
        # Autoscale block (autoscale.py via telemetry.record_autoscale):
        # decision/resize counters ride next to the faults block so an
        # elastic round shows how often (and why) the topology moved
        # alongside the latencies it produced.
        if t.get("autoscale"):
            au = t["autoscale"]
            result["telemetry"]["autoscale"] = {
                k: au.get(k)
                for k in ("samples", "decisions", "holds", "grows", "shrinks",
                          "resplits", "dead_device_shrinks", "resizes",
                          "aborts", "flap_damped", "active_devices")
            }
        # Tracing block (tracing.py via telemetry summary): span counts ride
        # along when --trace-out armed a TraceRecorder, so a traced round's
        # rows say how much span traffic the exported Perfetto file holds.
        if t.get("tracing"):
            tb = t["tracing"]
            result["telemetry"]["tracing"] = {
                k: tb.get(k)
                for k in ("spans", "dropped_spans", "by_kind", "requests",
                          "flows")
            }
            if os.environ.get("BENCH_TRACE_OUT"):
                result["telemetry"]["tracing"]["trace_out"] = (
                    os.environ["BENCH_TRACE_OUT"])
    # Stream the seq-2048 row the moment it exists — a kill during the 8192
    # phase must not erase it (round-3 postmortem).
    _emit(round(r2k["tok_s"], 1), unit_2k("; seq-8192 pending"),
          round(r2k["mfu"] / MFU_TARGET, 3), event="partial", **result)
    extra = ""
    if on_chip:
        # seq-8192 phase: a failure here must not erase the seq-2048 result,
        # so handle it internally and report partial data only as a last
        # resort — OOM steps the config ladder, transient backend errors
        # retry in place (the supervisor can't help without discarding the
        # 2048 numbers).
        err8k = None
        lvl, transient_left = oom_level, 2
        while lvl < 3:
            try:
                r8k = _measure(8192, 15, lvl, on_chip)
                result["tok_s_8192"] = round(r8k["tok_s"], 1)
                result["mfu_8192"] = round(r8k["mfu"], 4)
                result["warmup_s_8192"] = round(r8k["warmup_s"], 2)
                extra = f"; seq-8192: {r8k['tok_s']:.0f} tok/s/chip MFU {r8k['mfu']:.3f}"
                err8k = None
                break
            except Exception as e:  # noqa: BLE001 - recorded, not swallowed
                err8k = f"{type(e).__name__}: {e}"
                msg = str(e).lower()
                _emit(round(r2k["tok_s"], 1), unit_2k("; seq-8192 retrying"),
                      round(r2k["mfu"] / MFU_TARGET, 3), event="seq8192_retry",
                      seq8192_error=err8k[:500], **result)
                if "resource_exhausted" in msg:
                    lvl += 1
                elif any(pat in msg for pat in RETRYABLE) and transient_left > 0:
                    transient_left -= 1
                    time.sleep(20)
                else:
                    break
        if err8k is not None:
            result["seq8192_error"] = err8k[:500]

    if on_chip and remaining() > 150:
        # fp8 phase (budget-gated, never fatal): same 1B model, native f8
        # dots. Streams its own partial so a later kill can't erase it.
        try:
            _emit(round(r2k["tok_s"], 1), unit_2k("; fp8 measuring"),
                  round(r2k["mfu"] / MFU_TARGET, 3), event="fp8_start", **result)
            rf8 = _measure(2048, 10, oom_level, on_chip, fp8=True)
            result["tok_s_fp8_2048"] = round(rf8["tok_s"], 1)
            result["fp8_speedup"] = round(rf8["tok_s"] / r2k["tok_s"], 3)
            _emit(round(r2k["tok_s"], 1),
                  unit_2k(extra + f"; fp8: {rf8['tok_s']:.0f} tok/s/chip "
                          f"({result['fp8_speedup']:.2f}x)"),
                  round(r2k["mfu"] / MFU_TARGET, 3), event="partial", **result)
        except Exception as e:  # noqa: BLE001 - recorded, not swallowed
            result["fp8_error"] = f"{type(e).__name__}: {e}"[:300]

    if on_chip and remaining() > 300:
        # int8 weight-only decode phase (budget-gated, never fatal): the
        # generate_bench.py headline, folded in so the driver's own bench
        # run lands the row even when no interactive session sees the chip.
        try:
            import jax.numpy as jnp

            from accelerate_tpu import Model, generate
            from accelerate_tpu.generation import clear_generation_cache
            from accelerate_tpu.models import LlamaForCausalLM
            from accelerate_tpu.utils.quantization import quantize_model_for_decode

            cfg_d, _ = _build_config(2048, 0, False)
            module_d = LlamaForCausalLM(cfg_d)
            rng = np.random.default_rng(0)
            prompt = rng.integers(0, cfg_d.vocab_size, size=(1, 64), dtype=np.int32)
            dm = Model.from_flax(module_d, jax.random.key(0), prompt)
            dm.params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), dm.params)
            new_tokens = 32
            rows = {}
            # int8 model is built LAZILY after the bf16 row and the budget
            # check: quantizing eagerly would hold a second 1B param copy in
            # HBM through the bf16 compile, and waste the work when the
            # budget break fires first.
            variants = (("bf16", lambda: dm),
                        ("int8", lambda: quantize_model_for_decode(dm)))
            for name, make in variants:
                if name == "int8" and remaining() < 120:
                    break
                m = make()
                clear_generation_cache()
                np.asarray(generate(m, prompt, max_new_tokens=new_tokens))  # compile
                t0 = time.perf_counter()
                np.asarray(generate(m, prompt, max_new_tokens=new_tokens))
                rows[name] = new_tokens / (time.perf_counter() - t0)
            # Every measured row reaches the stream, budget break or not.
            if "bf16" in rows:
                result["decode_tok_s_bf16"] = round(rows["bf16"], 1)
            if "int8" in rows:
                result["decode_tok_s_int8"] = round(rows["int8"], 1)
                result["int8_decode_speedup"] = round(rows["int8"] / rows["bf16"], 3)
            if rows:
                msg = "; ".join(f"{k} decode {v:.0f} tok/s" for k, v in rows.items())
                _emit(round(r2k["tok_s"], 1), unit_2k(extra + "; " + msg),
                      round(r2k["mfu"] / MFU_TARGET, 3), event="partial", **result)
        except Exception as e:  # noqa: BLE001 - recorded, not swallowed
            result["int8_decode_error"] = f"{type(e).__name__}: {e}"[:300]

    _emit(round(r2k["tok_s"], 1), unit_2k(extra),
          round(r2k["mfu"] / MFU_TARGET, 3), event="final", **result)
    return 0


def _parse_json_line(line: str):
    line = line.strip()
    if not line.startswith("{"):
        return None
    try:
        obj = json.loads(line)
    except ValueError:
        return None
    if isinstance(obj, dict) and obj.get("metric") == METRIC:
        return obj
    return None


def _backend_probe(timeout_s: int = 90, env: dict | None = None) -> tuple[bool, str]:
    """Cheap pre-flight: can a fresh process see a device at all?

    A dead axon relay makes ``jax.devices()`` hang forever, so without this
    probe every child attempt burns its full 20-minute timeout (observed in
    round 3: three doomed children = one hour of budget on a relay that was
    down the whole time). Probing costs <=90 s and lets the supervisor spend
    the budget *waiting for the relay to come back* instead.

    Returns ``(ok, error_text)``; error_text is "timeout" for a hang (the
    relay-down signature, worth waiting out) and the probe's stderr for a
    fast deterministic failure (broken install — NOT worth waiting out).
    """
    # Same knob the child re-asserts: the axon site hook pins jax_platforms
    # at interpreter start, so an env request (CPU smoke runs) must go
    # through jax.config or the probe hangs on a dead relay it was told to
    # avoid.
    probe = (
        "import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
        "p and jax.config.update('jax_platforms', p); "
        "jax.devices(); print('ok')"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True, text=True,
            timeout=timeout_s, env=env,
        )
        if r.returncode == 0 and "ok" in (r.stdout or ""):
            return True, ""
        return False, (r.stderr or r.stdout or "")[-2000:]
    except subprocess.TimeoutExpired:
        return False, "timeout"


def _run_child_streaming(cmd, timeout_s: float, env: dict | None = None):
    """Run the child, forwarding its JSON evidence lines to stdout THE MOMENT
    they appear (round-3 postmortem: ``subprocess.run(capture_output=True)``
    buffered everything, so the driver's kill left an empty tail).

    Returns ``(returncode_or_None_on_timeout, best_row_or_None, stderr_tail)``.
    """
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, bufsize=1,
        env=env,
    )
    best = {"row": None}
    stderr_buf = []

    def _pump_out():
        for line in proc.stdout:
            row = _parse_json_line(line)
            if row is not None:
                print(line.rstrip("\n"), flush=True)
                if row.get("event") in ("partial", "final", "seq8192_retry"):
                    best["row"] = row
            else:
                sys.stderr.write(line)

    def _pump_err():
        for line in proc.stderr:
            stderr_buf.append(line)

    t_out = threading.Thread(target=_pump_out, daemon=True)
    t_err = threading.Thread(target=_pump_err, daemon=True)
    t_out.start()
    t_err.start()
    try:
        rc = proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        rc = None
    t_out.join(timeout=10)
    t_err.join(timeout=10)
    return rc, best["row"], "".join(stderr_buf)[-6000:]


def supervise() -> int:
    """Run the child with retries so one transient backend failure can never
    again erase a round's perf evidence (round-2 postmortem). All progress is
    streamed as evidence rows; the wall clock is capped at BENCH_DEADLINE_S
    (default 16 min) so this fits inside the driver's own timeout."""
    deadline = time.monotonic() + DEADLINE_S
    oom_level = 0
    last_err = ""
    best_partial = None
    attempt = 0
    max_attempts = 6
    # Per-backend probe cap: a relay that stays dead through PROBE_CAP probes
    # is not coming back inside this budget — fall back to the CPU mesh
    # ladder and keep producing evidence rows instead of an error row.
    PROBE_CAP = 3
    probe_fails = 0
    fallback_env = None
    fallback_reason = ""
    _emit(0.0, f"HEARTBEAT: supervisor up, deadline {DEADLINE_S}s", 0.0, event="start")
    while attempt < max_attempts:
        attempt += 1
        remaining = deadline - time.monotonic()
        if remaining < 90:
            last_err = last_err or "supervisor wall-clock budget exhausted"
            break
        alive, probe_err = _backend_probe(
            timeout_s=min(75, int(remaining / 2)), env=fallback_env
        )
        if not alive:
            if probe_err != "timeout" and not any(
                pat in probe_err.lower() for pat in RETRYABLE
            ):
                # Fast deterministic failure (bad plugin/config): retrying the
                # same backend cannot help, but the CPU mesh ladder usually
                # still can — measured rows with the reason attached beat an
                # error row. Only a deterministic failure ON the CPU fallback
                # itself is terminal.
                last_err = f"backend probe failed deterministically:\n{probe_err}"
                if fallback_env is not None:
                    break
                fallback_reason = (
                    f"device backend failed deterministically "
                    f"({probe_err[:120]})"
                )
                fallback_env = {
                    **os.environ,
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                }
                attempt -= 1
                _emit(0.0, "HEARTBEAT: falling back to the CPU mesh ladder",
                      0.0, event="cpu_fallback", reason=fallback_reason)
                continue
            probe_fails += 1
            last_err = f"attempt {attempt}: backend probe failed ({probe_err[:200]})"
            attempt -= 1
            if fallback_env is None and probe_fails >= PROBE_CAP:
                # Dead relay: switch every later probe + child to the CPU
                # mesh ladder. Slower numbers, but measured rows with the
                # reason attached beat an error row after a burned budget.
                fallback_reason = (
                    f"device backend unreachable after {probe_fails} probes "
                    f"({probe_err[:120]})"
                )
                fallback_env = {
                    **os.environ,
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                }
                _emit(0.0, "HEARTBEAT: falling back to the CPU mesh ladder",
                      0.0, event="cpu_fallback", reason=fallback_reason)
                continue
            # Hang or retryable error: relay down — wait it out (cheap)
            # rather than burn a child timeout. Probe failures don't consume
            # child attempts; the wall-clock deadline bounds this. The sleep
            # is jittered so restarted gangs don't re-probe in lockstep.
            _emit(0.0, f"HEARTBEAT: relay down, waiting ({probe_err[:120]})", 0.0,
                  event="probe_fail", attempt=attempt, probe_fails=probe_fails)
            base = min(45, max(5, remaining - 90))
            time.sleep(base * (0.5 + random.random()))
            continue
        probe_fails = 0
        _emit(0.0, f"HEARTBEAT: probe ok, launching child attempt {attempt}", 0.0,
              event="probe_ok", attempt=attempt, oom_level=oom_level)
        child_kill = max(60.0, (deadline - time.monotonic()) - 45)
        # The child's self-budget sits 30 s INSIDE the kill timeout so a
        # phase that overruns its gate still reaches the final _emit before
        # the supervisor kills it (a kill would demote a fully-measured run
        # to an error-annotated partial).
        child_budget = max(45.0, child_kill - 30.0)
        cmd = [sys.executable, os.path.abspath(__file__), "--child",
               f"--oom-level={oom_level}", f"--budget-s={child_budget:.0f}"]
        rc, row, err_tail = _run_child_streaming(
            cmd, timeout_s=child_kill, env=fallback_env
        )
        if row is not None:
            if fallback_env is not None:
                row["fallback"] = "cpu-mesh-ladder"
                row["fallback_reason"] = fallback_reason
            best_partial = row
        if rc == 0 and row is not None and row.get("event") == "final":
            if fallback_env is not None:
                # Re-emit the final row with the fallback provenance attached
                # so the driver's last-line parse sees why the numbers are
                # CPU-mesh numbers.
                print(json.dumps(row), flush=True)
            return 0  # the final row is already on stdout
        if rc is None:
            last_err = f"attempt {attempt}: child hit supervisor deadline"
            if best_partial is not None:
                break  # partial evidence beats another doomed attempt
            continue
        last_err = err_tail or f"child exited rc={rc} without a final row"
        low = last_err.lower()
        if "resource_exhausted" in low and oom_level < 2:
            oom_level += 1  # immediate retry one rung down the config ladder
            continue
        if any(pat in low for pat in RETRYABLE):
            time.sleep(20)
            continue
        break  # deterministic failure: don't burn the budget
    if best_partial is None and fallback_env is None \
            and deadline - time.monotonic() > 150:
        # Every device-backend attempt died without a single measured row and
        # there is still budget: one last-ditch CPU-mesh child. Its ladder
        # rows are slow but real — the round keeps perf evidence either way.
        fallback_reason = f"all device-backend attempts failed ({last_err[:120]})"
        fallback_env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        }
        _emit(0.0, "HEARTBEAT: last-ditch CPU mesh ladder child", 0.0,
              event="cpu_fallback", reason=fallback_reason)
        child_kill = max(60.0, (deadline - time.monotonic()) - 45)
        cmd = [sys.executable, os.path.abspath(__file__), "--child",
               "--oom-level=0", f"--budget-s={max(45.0, child_kill - 30.0):.0f}"]
        rc, row, err_tail = _run_child_streaming(
            cmd, timeout_s=child_kill, env=fallback_env
        )
        if row is not None:
            row["fallback"] = "cpu-mesh-ladder"
            row["fallback_reason"] = fallback_reason
            best_partial = row
            if rc == 0 and row.get("event") == "final":
                print(json.dumps(row), flush=True)
                return 0
        else:
            last_err = f"{last_err}\ncpu fallback also failed: " \
                       f"{(err_tail or f'rc={rc}')[-400:]}"
    if best_partial is not None:
        # Re-emit the best measured row as the last line so the driver's
        # last-line parse lands on real numbers, annotated with what failed.
        best_partial["error_after_partial"] = last_err[-1500:]
        print(json.dumps(best_partial), flush=True)
        return 0
    _emit(0.0, "ERROR: benchmark failed after retries (see error field)", 0.0,
          error=last_err[-2500:])
    return 1


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--child", action="store_true")
    parser.add_argument("--oom-level", type=int, default=0)
    parser.add_argument("--budget-s", type=float, default=1e9)
    parser.add_argument("--trace-out", type=str, default=None,
                        help="enable request tracing and dump the child's "
                             "Chrome/Perfetto trace JSON to this path")
    args = parser.parse_args()
    if args.trace_out:
        # Children inherit os.environ, so the supervisor's flag reaches every
        # retry attempt without widening the --child argv contract.
        os.environ["BENCH_TRACE_OUT"] = args.trace_out
    if args.child:
        return child(args.oom_level, args.budget_s)
    return supervise()


if __name__ == "__main__":
    sys.exit(main())
