"""Benchmark: Llama training throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures tokens/sec/chip for an FSDP-prepared Llama decoder train step in bf16
(the BASELINE.json headline: FSDP2 Llama tokens/sec/chip, target ≥45% MFU).
``vs_baseline`` reports achieved_MFU / 0.45 — ≥1.0 means the MFU target is met.

Timing notes (hard-won): the axon remote runtime's ``block_until_ready`` does
not actually block, and the first post-warmup step pays a second compile
(donated-buffer layout), so the loop warms up twice and the barrier is a host
fetch of the final loss — which transitively waits on every chained step.

Attention runs the Pallas flash kernel (ops/pallas_flash.py) under the
"dots" remat policy (keep every matmul output + the kernel's O(S) residuals,
recompute only elementwise ops) at batch 4 — the winner of
benchmarks/ablate.py's policy x batch sweep: 51.5k tok/s/chip vs 46.8k for
the flash-only policy at batch 8, vs 24.7k for naive attention under plain
remat (same 334M model, seq 2048).
"""

import json
import time

import numpy as np


def _pick_config(platform: str, seq: int):
    import jax.numpy as jnp

    from accelerate_tpu.models import LlamaConfig

    if platform in ("tpu", "axon"):
        # ~334M params: fits one v5e chip (16GB HBM) with Adam fp32 states.
        return (
            LlamaConfig(
                vocab_size=32000,
                hidden_size=1024,
                intermediate_size=4096,
                num_hidden_layers=16,
                num_attention_heads=8,
                num_key_value_heads=8,
                max_position_embeddings=seq,
                dtype=jnp.bfloat16,
                remat=True,
                remat_policy="dots",
                attention_impl="flash",
            ),
            # benchmarks/ablate.py sweep: "dots" wants the smaller batch
            # (more VMEM headroom per step beats batch-level parallelism).
            4 if seq <= 2048 else 1,  # batch
        )
    return LlamaConfig.tiny(dtype=jnp.bfloat16), 4


def _measure(platform: str, seq: int, iters: int):
    import jax
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaForCausalLM, cross_entropy_loss
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    set_seed(0)
    cfg, batch = _pick_config(platform, seq)
    if platform not in ("tpu", "axon"):
        seq = 128
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(batch, seq + 1), dtype=np.int32)

    acc = Accelerator(mixed_precision="bf16", fsdp_plugin=FullyShardedDataParallelPlugin())
    model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
    model, _ = acc.prepare(model, optax.adamw(3e-4, weight_decay=0.1))
    n_params = model.num_parameters()

    def loss_fn(params, b):
        logits = module.apply({"params": params}, b["x"])
        return cross_entropy_loss(logits, b["y"])

    step = acc.prepare_train_step(loss_fn, max_grad_norm=1.0)
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(acc.mesh, PartitionSpec(("dp_replicate", "dp_shard")))
    b = {
        "x": jax.device_put(ids[:, :-1], sharding),
        "y": jax.device_put(ids[:, 1:], sharding),
    }

    state = acc.train_state
    # Two warmups: initial compile + the donated-buffer-layout recompile.
    for _ in range(2):
        state, metrics = step(state, b)
        float(np.asarray(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, b)
    loss = float(np.asarray(metrics["loss"]))  # host fetch = the real barrier
    dt = (time.perf_counter() - t0) / iters
    assert np.isfinite(loss), f"non-finite loss {loss}"

    n_devices = len(jax.devices())
    tok_s_chip = batch * seq / dt / n_devices
    # MFU: ~6*N FLOPs/token for fwd+bwd + attention term 12*L*H*S per token.
    attn_flops_per_token = 12 * cfg.num_hidden_layers * cfg.hidden_size * seq
    flops_per_token = 6 * n_params + attn_flops_per_token
    peak_flops = {"tpu": 197e12, "axon": 197e12}.get(platform, 1e12)  # v5e bf16
    mfu = tok_s_chip * flops_per_token / peak_flops
    return tok_s_chip, mfu, n_params


def main():
    import jax

    platform = jax.devices()[0].platform
    on_chip = platform in ("tpu", "axon")
    tok, mfu, n_params = _measure(platform, 2048, 30 if on_chip else 3)
    extra = ""
    if on_chip:
        tok8k, mfu8k, _ = _measure(platform, 8192, 15)
        extra = f"; seq-8192: {tok8k:.0f} tok/s/chip MFU {mfu8k:.3f}"

    print(
        json.dumps(
            {
                "metric": "llama_fsdp_train_tokens_per_sec_per_chip",
                "value": round(tok, 1),
                "unit": (
                    f"tokens/s/chip (bf16, {n_params/1e6:.0f}M params, seq 2048, "
                    f"flash+dots-remat, MFU {mfu:.3f}{extra})"
                ),
                "vs_baseline": round(mfu / 0.45, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
