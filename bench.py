"""Benchmark: Llama training throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures tokens/sec/chip for an FSDP-prepared Llama decoder train step in bf16
(the BASELINE.json headline: FSDP2 Llama tokens/sec/chip, target ≥45% MFU).
``vs_baseline`` reports achieved_MFU / 0.45 — ≥1.0 means the MFU target is met.
Model size auto-scales down when HBM is small (CPU fallback uses the tiny
config so the script always completes).
"""

import json
import time

import numpy as np


def _pick_config(platform: str):
    import jax.numpy as jnp

    from accelerate_tpu.models import LlamaConfig

    if platform in ("tpu", "axon"):
        # ~410M params: fits one v5e chip (16GB HBM) with Adam fp32 states.
        return (
            LlamaConfig(
                vocab_size=32000,
                hidden_size=1024,
                intermediate_size=4096,
                num_hidden_layers=16,
                num_attention_heads=8,
                num_key_value_heads=8,
                max_position_embeddings=2048,
                dtype=jnp.bfloat16,
                remat=True,
            ),
            8,     # batch
            2048,  # seq
        )
    return LlamaConfig.tiny(dtype=jnp.bfloat16), 4, 128


def main():
    import jax

    platform = jax.devices()[0].platform
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaForCausalLM, cross_entropy_loss
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

    set_seed(0)
    cfg, batch, seq = _pick_config(platform)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(batch, seq + 1), dtype=np.int32)

    acc = Accelerator(mixed_precision="bf16", fsdp_plugin=FullyShardedDataParallelPlugin())
    model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
    model, _ = acc.prepare(model, optax.adamw(3e-4, weight_decay=0.1))
    n_params = model.num_parameters()

    def loss_fn(params, b):
        logits = module.apply({"params": params}, b["x"])
        return cross_entropy_loss(logits, b["y"])

    step = acc.prepare_train_step(loss_fn, max_grad_norm=1.0)
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(acc.mesh, PartitionSpec(("dp_replicate", "dp_shard")))
    b = {
        "x": jax.device_put(ids[:, :-1], sharding),
        "y": jax.device_put(ids[:, 1:], sharding),
    }

    state = acc.train_state
    # Warmup/compile.
    state, metrics = step(state, b)
    jax.block_until_ready(metrics["loss"])

    iters = 20 if platform in ("tpu", "axon") else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, b)
    jax.block_until_ready(metrics["loss"])
    dt = (time.perf_counter() - t0) / iters

    n_devices = len(jax.devices())
    tokens_per_step = batch * seq
    tok_s_chip = tokens_per_step / dt / n_devices

    # MFU: ~6*N FLOPs/token for fwd+bwd + attention term 12*L*H*S per token.
    attn_flops_per_token = 12 * cfg.num_hidden_layers * cfg.hidden_size * seq
    flops_per_token = 6 * n_params + attn_flops_per_token
    peak_flops = {"tpu": 197e12, "axon": 197e12}.get(platform, 1e12)  # v5e bf16
    mfu = tok_s_chip * flops_per_token / peak_flops

    print(
        json.dumps(
            {
                "metric": "llama_fsdp_train_tokens_per_sec_per_chip",
                "value": round(tok_s_chip, 1),
                "unit": f"tokens/s/chip (bf16, {n_params/1e6:.0f}M params, seq {seq}, MFU {mfu:.3f})",
                "vs_baseline": round(mfu / 0.45, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
