# Suite partitioning mirroring the reference's Makefile:17-75 CI jobs.
# Everything runs on a virtual 8-device CPU mesh — no TPU needed.

ENV = XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
PYTEST = $(ENV) python -m pytest -q

.PHONY: chip_evidence test test_smoke test_core test_models test_parallel test_big_modeling \
        test_cli test_examples test_checkpointing test_hub test_tpu quality bench \
        telemetry-smoke warmup-smoke faulttol-smoke serving-smoke plan-smoke \
        reshard-smoke disagg-smoke chaos-smoke chaos-train-smoke publish-smoke \
        autoscale-smoke trace-smoke gameday-smoke sdc-smoke profile-smoke \
        fleet-smoke spec-smoke smoke-all

# Parallel across available cores (pytest-xdist): launched subprocess tests
# draw fresh rendezvous ports per gang (utils/other.py get_free_port), so
# workers never collide — the role of the reference's unique-port trick
# (test_utils/testing.py:810-820). Single-core boxes gain nothing from -n;
# the persistent XLA compile cache was tried for them and reverted (see
# tests/conftest.py: ring-attention executables SIGABRT on cache replay).
test:
	$(PYTEST) -n auto tests/

test_serial:
	$(PYTEST) tests/

# Smoke tier (<10 min serial on one core): one representative file per
# subsystem — runtime/mesh, collectives, data, training loop, flagship model,
# generation, checkpoint roundtrip, review regressions. The full suite is the
# bar; this is the budget-constrained pre-commit gate.
test_smoke:
	$(PYTEST) tests/test_state_and_mesh.py tests/test_operations.py \
	    tests/test_training.py tests/test_llama.py tests/test_megatron.py \
	    tests/test_review_regressions.py

# Runtime + ops + data + training loop (excludes models/examples/big-model).
test_core:
	$(PYTEST) tests/test_state_and_mesh.py tests/test_operations.py \
	    tests/test_data_loader.py tests/test_training.py tests/test_zero.py \
	    tests/test_local_sgd.py tests/test_tracking.py tests/test_native.py

test_models:
	$(PYTEST) tests/test_llama.py tests/test_bert.py tests/test_gpt2.py \
	    tests/test_t5.py tests/test_moe.py tests/test_opt.py tests/test_neox.py \
	    tests/test_vit.py tests/test_resnet.py tests/test_whisper.py \
	    tests/test_generation.py

test_parallel:
	$(PYTEST) tests/test_pp.py tests/test_attention.py tests/test_inference.py \
	    tests/test_fp8.py tests/test_quantization.py

test_big_modeling:
	$(PYTEST) tests/test_big_modeling.py

test_checkpointing:
	$(PYTEST) tests/test_checkpointing.py

test_cli:
	$(PYTEST) tests/test_cli.py

test_examples:
	$(PYTEST) tests/test_examples.py

test_hub:
	$(PYTEST) tests/test_hub.py

# TPU kernel tier: compiled-mode Pallas/fp8/int8/train-step health on the
# real chip (~2-3 min). Serial on purpose — only one process may hold the
# chip tunnel. Skips cleanly (with the reason) when no chip is reachable.
test_tpu:
	ACCELERATE_TEST_USE_TPU=1 python -m pytest -q -rs tests/tpu/

bench:
	python bench.py

# Observability gate: 20-step toy loop with telemetry on, then assert the
# per-rank JSONL report is well-formed (schema, recompile counting, summary
# percentiles). Seconds on the CPU mesh; see docs/usage_guides/observability.md.
telemetry-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.telemetry_smoke

# Compile-manager gate: ragged toy loop (8 raw shapes) under pow2 bucketing
# compiles <= 4 executables; a restart warms every shapes-manifest signature
# before step 0 and telemetry reports 0 recompiles afterwards. See
# docs/usage_guides/performance.md "Taming recompiles".
warmup-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.warmup_smoke

# Continuous-batching gate: 32 mixed-length requests through a tiny Llama on
# the CPU mesh must all complete with continuations bit-equal to static
# generate(), keep the decode steady state at ONE executable (zero
# post-warmup recompiles), and beat static-batch generate()'s aggregate
# tokens/s on the same request set. See docs/usage_guides/serving.md.
serving-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.serving_smoke

# Disaggregated-serving gate: an open-loop Poisson trace of mixed-length
# requests replays through the colocated engine and through the two-mesh
# router (planner-sized prefill/decode slices on the 8-device CPU mesh,
# streamed KV-page handoff). All requests must complete with rows bit-equal
# between the paths, the disagg decode steady state must stay ONE executable
# (zero post-warmup recompiles), the stats block must report real handoff
# traffic, and disagg p95 TTFT must be STRICTLY lower than colocated at the
# same offered load. See docs/usage_guides/serving.md "Disaggregated serving".
disagg-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.disagg_smoke

# Serving-under-fire gate: a 32-request Poisson trace (tick-driven, fully
# deterministic) replays through the disagg engine fault-free and twice under
# an identical FaultInjector spec (one dead prefill lane, a poisoned KV page,
# rate-driven handoff transfer errors). No hang (idle-tick guard armed),
# every request ends with an explicit status, ok rows are bit-equal to the
# fault-free run, decode stays ONE executable with 0 steady recompiles, chaos
# p95 TTFT stays within 5x fault-free, and the second chaos run reproduces
# the first's fault schedule/statuses/rows exactly. See
# docs/usage_guides/serving.md "Serving under faults".
chaos-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.chaos_smoke

# Request-tracing gate: a seeded 24-request chaos trace through the disagg
# engine with a TraceRecorder attached. Every poll() row carries a complete
# span tree, explain()'s critical-path terms sum to the measured TTFT,
# the exported Chrome trace parses with cross-lane KV-handoff flow events,
# a second seeded run replays a bit-identical tick-domain trace, decode
# stays ONE executable with 0 steady recompiles, and throughput stays
# within 5% of tracing-off. See docs/usage_guides/observability.md
# "Tracing a request".
trace-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.trace_smoke

# Training-under-fire gate: a 10-step toy loop replays one seeded chaos
# schedule twice (torn checkpoint write -> save retry, two nonfinite_grad
# steps -> sentinel rollback, a slow_step straggler -> watchdog
# training_stalled event naming the rank). Both chaos runs must draw a
# bit-identical fault log, the chaos final loss must be bit-equal to a
# fault-free run (rollback restored exact state + data order), and the
# telemetry recompile counter must not move after the two-step warmup —
# including across the rollback replay. See
# docs/usage_guides/fault_tolerance.md "Training under fire".
chaos-train-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.chaos_train_smoke

# Weight-publication gate: a training run commits verified checkpoints
# (steps 3 and 5) while a live engine drains a deterministic Poisson trace
# in the same process; the WeightPublisher hot-swaps both — a canary
# promote, then a seeded canary_window/slo_regression rollback that stays
# quarantined. Zero dropped/shed/failed requests across both swaps, ONE
# decode executable with 0 steady recompiles, version tags flip only
# post-swap (v0 rows bit-equal to a publish-free reference), the
# post-rollback probe is bit-equal to loading checkpoint 3 directly, and a
# second seeded run replays the whole thing bit-identically. See
# docs/usage_guides/serving.md "Continuous deployment".
publish-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.publish_smoke

# Crash-durability game day: the ENTIRE serving stack — train gang
# committing a verified checkpoint, journaled disagg engine with autoscaler
# and tracing attached, WeightPublisher — under one seeded chaos schedule
# that tears a journal append and then hard-kills the engine (os._exit 78)
# mid-trace. The parent plays supervisor (classify_exit -> "serving-crash"
# -> zero-backoff relaunch); the resumed child recovers the write-ahead
# journal: every request reaches an explicit terminal status exactly once
# (cached pre-crash completions never re-execute, in-flight rows replay
# bit-equal to an uninterrupted reference), the publisher still promotes
# post-recovery, decode stays ONE executable, and a second seeded round
# replays bit-identically. See docs/usage_guides/serving.md
# "Surviving engine crashes".
gameday-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.gameday_smoke

# Elastic-serving gate: a seeded diurnal trace (10x rate swing, shifting
# prompt:decode mix) replays through a disagg engine that starts on half
# the mesh with an AutoscaleController polling every tick; mid-trace a
# device is reported dead. Every request must end ok, every row bit-equal
# to a fixed 8-device reference, the controller must grow AND shrink-on-
# death within a bounded resize count, the injected flap must be damped
# (no resize), decode keeps 0 steady recompiles across every layout, p95
# TTFT holds the smoke SLO on both load plateaus, and a second seeded run
# replays decisions/faults/rows bit-identically. See
# docs/usage_guides/serving.md "Autoscaling".
autoscale-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.autoscale_smoke

# Auto-parallelism gate: plan a tiny Llama on the 8-device CPU mesh —
# search must be deterministic (byte-identical JSON), every candidate must
# satisfy the divisibility constraints, 10 training steps run under the
# chosen layout with measured peak HBM within 2x of the prediction, and a
# second run loads the cached plan (no re-search) and records calibration
# deltas into it. See docs/usage_guides/auto_parallelism.md.
plan-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.plan_smoke

# Fault-tolerance gate: SIGTERM a training worker mid-epoch (preemption
# auto-save + resumable exit code), relaunch with ACCELERATE_RESTART_ATTEMPT=1
# and assert the resumed step equals the preemption-save step and the final
# loss matches an uninterrupted run. See docs/usage_guides/fault_tolerance.md.
faulttol-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.faulttol_smoke

# Elastic-resharding gate: preempt a 4-way training worker, then resume its
# checkpoint on 2-way AND 8-way meshes with ACCELERATE_RESTART_ATTEMPT=1.
# Each resume must restore through the planned collective schedule (no
# host-staged leaves within the staging budget), report the telemetry
# `reshard` block, and finish with the uninterrupted run's final loss. See
# docs/usage_guides/elastic_resharding.md. (The driver pins each child's
# device count itself, so this target sets no XLA_FLAGS.)
reshard-smoke:
	JAX_PLATFORMS=cpu python -m accelerate_tpu.test_utils.scripts.reshard_smoke

# Silent-data-corruption gate: the sdc.py sentinel end to end. A 4-rank
# gloo gang draws a transient train_step/bit_flip on a vote tick — the
# cross-replica integrity vote isolates the outlier, the redundant-compute
# probe on the cached golden batch clears the silicon, and the majority
# broadcast repairs in place (final loss bit-equal to a fault-free
# reference, jit cache flat). A 2-rank gang draws the same flip sticky —
# the probe reproduces it, the convicted rank quarantines itself on disk
# and exits 79, classify_exit maps it to "sdc", and GangSupervisor orders
# the zero-backoff SHRUNK relaunch that resumes from the newest verified
# checkpoint with the host still excluded. A decode canary (known prompt,
# pinned RNG, journal/poll-invisible) catches an injected decode_tick
# bit_flip and shrinks the engine around the device via mark_device_dead.
# Every leg replays bit-identically on its second seeded round. See
# docs/usage_guides/fault_tolerance.md "Silent data corruption".
sdc-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.sdc_smoke

# Device-time attribution + flight-recorder gate: a dp-sharded train run
# and a chaos-seeded serving replay with the profiler on must emit
# exactly-summing attribution terms (5% bar), an overlap ratio, per-axis
# bandwidth residuals, and a flat jit cache; a hard-killed child (rc 78)
# and an SDC-convicted gang rank (rc 79) must each leave a readable
# flight_<exit_class>.json whose newest ring entries identify the dying
# tick/step. See docs/usage_guides/observability.md.
profile-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.profile_smoke

# Whole-cell-loss game day: a FleetRouter over two journaled cells drains a
# seeded Poisson trace; chaos partitions cell 0 (terminals pile up
# journaled but unreported) then hard-kills it mid-trace. The router adopts
# the dead cell's journal and drains it onto the survivor — cached
# terminals re-emit without re-executing, in-flight requests resubmit by
# client_request_id — with every request ok exactly once, rows bit-equal
# to an uninterrupted reference, the survivor executing exactly N minus
# what the dead cell already ran, 1 decode executable / 0 steady
# recompiles per survivor, and scale_up + a cell-granular publish canary
# promoting fleet-wide afterwards. A second seeded round replays
# bit-identically. See docs/usage_guides/serving.md "Fleet serving".
fleet-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.fleet_smoke

# Speculative-decoding + quantized-KV gate: a seeded 24-request trace runs
# non-speculative, speculative (n-gram self-draft, k=4 verified in ONE
# batched forward), int8-KV colocated, and int8-KV disagg with speculation
# on. Speculative greedy rows must be BIT-EQUAL to the reference (exact
# rejection sampling), decode must stay ONE executable with 0 steady
# recompiles with speculation AND int8 KV enabled, int8 disagg rows must be
# bit-equal to int8 colocated (lossless quantized handoff) with the byte
# accounting showing >= 40% handoff savings, and int8 output must stay
# within the documented cross-dtype tolerance of the float reference. See
# docs/usage_guides/serving.md "Speculative decoding".
spec-smoke:
	$(ENV) python -m accelerate_tpu.test_utils.scripts.spec_smoke

# Every acceptance gate back to back with a one-line pass/fail table and a
# nonzero exit if any gate failed. Serial on purpose: the gates share the
# CPU cores and several launch their own subprocess gangs.
SMOKES = telemetry warmup serving plan reshard disagg chaos chaos-train \
         publish autoscale trace faulttol gameday sdc profile fleet spec
smoke-all:
	@fail=0; \
	for s in $(SMOKES); do \
	    start=$$(date +%s); \
	    if $(MAKE) -s $$s-smoke >/tmp/smoke_$$s.log 2>&1; then \
	        printf 'PASS  %-14s %4ss\n' $$s $$(( $$(date +%s) - start )); \
	    else \
	        printf 'FAIL  %-14s %4ss  (tail: /tmp/smoke_%s.log)\n' \
	            $$s $$(( $$(date +%s) - start )) $$s; \
	        fail=1; \
	    fi; \
	done; \
	exit $$fail

# Relay-recovery sequence: kernel health first (~3 min, skips cleanly if the
# relay dropped again), then the full ladder (1B seq 2048/8192 + fp8 + int8
# decode rows, 16-min budget). One command = all on-chip evidence.
chip_evidence: test_tpu bench
