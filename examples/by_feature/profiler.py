"""Feature: profiling the train step with accelerator.profile() over
jax.profiler (reference: examples/by_feature/profiler.py wrapping
torch.profiler)."""

import glob
import os

import optax

from _base import LoaderSpec, build_model_and_data, classifier_loss, make_parser


def main():
    args = make_parser(epochs=1).parse_args()
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import ProfileKwargs, set_seed

    set_seed(args.seed)
    trace_dir = "/tmp/accelerate_tpu_profile_example"
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        kwargs_handlers=[ProfileKwargs(output_trace_dir=trace_dir)],
    )
    module, model, train_ds, eval_ds = build_model_and_data(args, n_train=256)
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr), LoaderSpec(train_ds, args.batch_size),
        LoaderSpec(eval_ds, args.batch_size, shuffle=False),
    )
    step_fn = accelerator.prepare_train_step(classifier_loss(module))
    state = accelerator.train_state

    with accelerator.profile() as prof:
        for batch in train_dl:
            state, metrics = step_fn(state, batch)

    traces = glob.glob(os.path.join(trace_dir, "**", "*"), recursive=True)
    accelerator.print(f"profiler OK: {len(traces)} trace artifacts under {trace_dir}")
    assert traces, "no profiler output written"


if __name__ == "__main__":
    main()
