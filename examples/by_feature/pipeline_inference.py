"""Feature: pipeline-parallel inference (accelerate_tpu.prepare_pippy) —
the compiled GPipe schedule over the pp mesh axis (reference:
examples/inference/pippy)."""

import numpy as np

from _base import make_parser  # noqa: F401  (path setup)

import jax
import jax.numpy as jnp


def main():
    args = make_parser().parse_args()
    from accelerate_tpu import Model, ParallelismConfig, prepare_pippy
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    pp = 2 if len(jax.devices()) % 2 == 0 and len(jax.devices()) > 1 else 1
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(args.seed)
    ids = rng.integers(0, cfg.vocab_size, size=(8, 16), dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(args.seed), ids)
    want = np.asarray(model(ids))

    mesh = ParallelismConfig(pp_size=pp).build_mesh()
    piped = prepare_pippy(model, mesh=mesh, gather_output=True)
    got = np.asarray(piped(ids))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
    print(f"pipeline inference over pp={pp} OK (logits match unpipelined)")


if __name__ == "__main__":
    main()
