"""Feature: automatic gradient accumulation — combine
find_executable_batch_size with gradient_accumulation_steps so the OOM-retry
loop keeps the EFFECTIVE batch constant by accumulating what no longer fits
(reference: examples/by_feature/automatic_gradient_accumulation.py)."""

import optax

from _base import LoaderSpec, build_model_and_data, classifier_loss, evaluate, make_parser


def main():
    args = make_parser(epochs=1).parse_args()
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import find_executable_batch_size, set_seed

    observed_batch_size = args.batch_size * 2  # pretend this is what we want
    attempts = []

    @find_executable_batch_size(starting_batch_size=observed_batch_size)
    def inner_training_loop(batch_size):
        attempts.append(batch_size)
        from accelerate_tpu.state import AcceleratorState, GradientState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        set_seed(args.seed)
        # The feature: as the per-step batch halves, accumulation doubles, so
        # every attempt optimizes with the same effective batch.
        accum = observed_batch_size // batch_size
        accelerator = Accelerator(
            mixed_precision=args.mixed_precision, gradient_accumulation_steps=accum
        )
        # Simulate an OOM on the first (oversized) attempt so the retry loop
        # is exercised even on hosts with plenty of memory.
        if batch_size > args.batch_size:
            raise RuntimeError("RESOURCE_EXHAUSTED: Ran out of memory (simulated)")
        module, model, train_ds, eval_ds = build_model_and_data(args)
        model, optimizer, train_dl, eval_dl = accelerator.prepare(
            model, optax.adamw(args.lr), LoaderSpec(train_ds, batch_size),
            LoaderSpec(eval_ds, batch_size, shuffle=False),
        )
        step_fn = accelerator.prepare_train_step(classifier_loss(module))
        state = accelerator.train_state
        for batch in train_dl:
            state, metrics = step_fn(state, batch)
        return evaluate(accelerator, model, eval_dl), accum, accelerator

    acc, accum, accelerator = inner_training_loop()
    accelerator.print(
        f"auto grad-accum OK: tried {attempts}, settled on accumulation x{accum}, "
        f"accuracy {acc:.3f}"
    )
    assert accum == 2, f"expected accumulation 2 after one halving, got {accum}"


if __name__ == "__main__":
    main()
