"""Feature: schedule-free training (optax.contrib.schedule_free_adamw) — no
LR schedule to tune; evaluation uses the averaged iterate via
schedule_free_eval_params (reference: examples/by_feature/schedule_free.py,
which uses the schedulefree package's AdamWScheduleFree)."""

import optax

from _base import LoaderSpec, build_model_and_data, classifier_loss, evaluate, make_parser


def main():
    args = make_parser(epochs=2).parse_args()
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import set_seed

    set_seed(args.seed)
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    module, model, train_ds, eval_ds = build_model_and_data(args)
    # The feature: the schedule-free optimizer replaces warmup+decay schedules
    # with on-line iterate averaging (Defazio et al.); the reference flips
    # optimizer.train()/.eval(), here the split is explicit in the state.
    tx = optax.contrib.schedule_free_adamw(args.lr, warmup_steps=16)
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, tx, LoaderSpec(train_ds, args.batch_size),
        LoaderSpec(eval_ds, args.batch_size, shuffle=False),
    )
    step_fn = accelerator.prepare_train_step(classifier_loss(module))
    state = accelerator.train_state
    for _ in range(args.epochs):
        for batch in train_dl:
            state, metrics = step_fn(state, batch)

    # Evaluate at the averaged point, then restore the training iterate.
    train_params = state.params
    eval_params = optax.contrib.schedule_free_eval_params(state.opt_state, train_params)
    model.params = eval_params
    acc = evaluate(accelerator, model, eval_dl)
    model.params = train_params
    accelerator.print(f"schedule_free OK: eval accuracy {acc:.3f}")
    assert acc > 0.5, f"schedule-free run failed to learn (accuracy {acc:.3f})"


if __name__ == "__main__":
    main()
