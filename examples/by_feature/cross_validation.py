"""Feature: k-fold cross-validation — train one model per fold on the
non-held-out shards, evaluate on the held-out fold, and report the mean
accuracy across folds (reference: examples/by_feature/cross_validation.py,
which folds with `datasets` + StratifiedKFold; the fold arithmetic here is
plain index slicing over the same base dataset)."""

import numpy as np
import optax

from _base import LoaderSpec, build_model_and_data, classifier_loss, evaluate, make_parser


def fold_split(n, k, fold):
    """Contiguous k-fold: returns (train_idx, eval_idx) for this fold."""
    edges = np.linspace(0, n, k + 1, dtype=int)
    lo, hi = edges[fold], edges[fold + 1]
    eval_idx = np.arange(lo, hi)
    train_idx = np.concatenate([np.arange(0, lo), np.arange(hi, n)])
    return train_idx, eval_idx


def main():
    args = make_parser(epochs=1).parse_args()
    args.num_folds = 3
    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils import set_seed

    fold_accuracies = []
    for fold in range(args.num_folds):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        set_seed(args.seed)
        accelerator = Accelerator(mixed_precision=args.mixed_precision)
        module, model, full_ds, _ = build_model_and_data(args, n_train=768, n_eval=1)
        train_idx, eval_idx = fold_split(len(full_ds), args.num_folds, fold)
        train_ds = [full_ds[i] for i in train_idx]
        eval_ds = [full_ds[i] for i in eval_idx]
        model, optimizer, train_dl, eval_dl = accelerator.prepare(
            model, optax.adamw(args.lr), LoaderSpec(train_ds, args.batch_size),
            LoaderSpec(eval_ds, args.batch_size, shuffle=False),
        )
        step_fn = accelerator.prepare_train_step(classifier_loss(module))
        state = accelerator.train_state
        for _ in range(args.epochs):
            for batch in train_dl:
                state, _ = step_fn(state, batch)
        acc = evaluate(accelerator, model, eval_dl)
        fold_accuracies.append(acc)
        accelerator.print(f"fold {fold}: accuracy {acc:.3f}")

    mean_acc = float(np.mean(fold_accuracies))
    accelerator.print(f"cross-validation OK: mean accuracy {mean_acc:.3f} over {args.num_folds} folds")
    assert mean_acc > 0.5, f"cross-validated model failed to learn ({mean_acc:.3f})"


if __name__ == "__main__":
    main()
