"""Feature: automatic OOM-retrying batch size via find_executable_batch_size
(reference: examples/by_feature/memory.py, utils/memory.py:119-187)."""

import optax

from _base import LoaderSpec, build_model_and_data, classifier_loss, evaluate, make_parser


def main():
    args = make_parser(epochs=1).parse_args()
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import find_executable_batch_size, set_seed

    attempts = []

    @find_executable_batch_size(starting_batch_size=args.batch_size * 4)
    def inner_training_loop(batch_size):
        attempts.append(batch_size)
        from accelerate_tpu.state import AcceleratorState, GradientState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        set_seed(args.seed)
        accelerator = Accelerator(mixed_precision=args.mixed_precision)
        # Simulate an OOM for oversized batches so the decorator's halving
        # loop is exercised even on hosts with plenty of memory.
        if batch_size > args.batch_size:
            raise RuntimeError("RESOURCE_EXHAUSTED: Ran out of memory (simulated)")
        module, model, train_ds, eval_ds = build_model_and_data(args)
        model, optimizer, train_dl, eval_dl = accelerator.prepare(
            model, optax.adamw(args.lr), LoaderSpec(train_ds, batch_size),
            LoaderSpec(eval_ds, batch_size, shuffle=False),
        )
        step_fn = accelerator.prepare_train_step(classifier_loss(module))
        state = accelerator.train_state
        for batch in train_dl:
            state, metrics = step_fn(state, batch)
        return evaluate(accelerator, model, eval_dl), accelerator

    acc, accelerator = inner_training_loop()
    accelerator.print(f"memory OK: batch sizes tried {attempts}, accuracy {acc:.3f}")
    assert len(attempts) > 1, "the halving loop should have retried"


if __name__ == "__main__":
    main()
