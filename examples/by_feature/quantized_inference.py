"""Feature: int8 / NF4 weight-only quantized inference (reference:
bitsandbytes integration, utils/bnb.py)."""

import numpy as np

from _base import make_parser  # noqa: F401  (path setup)

import jax
import jax.numpy as jnp


def main():
    args = make_parser().parse_args()
    from accelerate_tpu import Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.utils import (
        QuantizationConfig, load_and_quantize_model, quantized_nbytes,
    )

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(args.seed)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 16), dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(args.seed), ids)
    full = sum(l.nbytes for l in jax.tree.leaves(model.params))
    ref = np.asarray(model(ids), np.float32)

    for name, kwargs in [("int8", {"load_in_8bit": True}), ("nf4", {"load_in_4bit": True})]:
        qm = load_and_quantize_model(
            model, QuantizationConfig(compute_dtype=jnp.float32, **kwargs)
        )
        out = np.asarray(qm(ids), np.float32)
        cos = float(np.sum(out * ref) / (np.linalg.norm(out) * np.linalg.norm(ref)))
        ratio = quantized_nbytes(qm.params) / full
        print(f"{name}: {ratio:.2f}x storage, logits cosine {cos:.4f}")
        assert cos > 0.9
    print("quantized inference OK")


if __name__ == "__main__":
    main()
