"""Feature: FSDP training with peak device-memory tracking — HBM
peak/in-use snapshots around prepare and each epoch, logged through the
tracking API (reference: examples/by_feature/fsdp_with_peak_mem_tracking.py,
which uses a TorchTracemalloc context; here the device runtime's own
memory_stats are the source)."""

import tempfile

import jax
import optax

from _base import LoaderSpec, build_model_and_data, classifier_loss, evaluate, make_parser


def device_memory_gb():
    """(in-use, peak) bytes for device 0, zeros where the backend has no
    allocator stats (virtual CPU mesh)."""
    stats = jax.local_devices()[0].memory_stats() or {}
    return (
        stats.get("bytes_in_use", 0) / 2**30,
        stats.get("peak_bytes_in_use", 0) / 2**30,
    )


def main():
    args = make_parser(epochs=1).parse_args()
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

    set_seed(args.seed)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        fsdp_plugin=FullyShardedDataParallelPlugin(),  # FULL_SHARD over dp_shard
        log_with="json", project_dir=tempfile.mkdtemp(prefix="fsdp_peak_mem_"),
    )
    accelerator.init_trackers("fsdp_peak_mem")
    module, model, train_ds, eval_ds = build_model_and_data(args)

    used0, peak0 = device_memory_gb()
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr), LoaderSpec(train_ds, args.batch_size),
        LoaderSpec(eval_ds, args.batch_size, shuffle=False),
    )
    used1, peak1 = device_memory_gb()
    accelerator.print(
        f"prepare: {used0:.3f} -> {used1:.3f} GB in use (sharded params + opt state)"
    )

    step_fn = accelerator.prepare_train_step(classifier_loss(module))
    state = accelerator.train_state
    for epoch in range(args.epochs):
        for batch in train_dl:
            state, metrics = step_fn(state, batch)
        used, peak = device_memory_gb()
        accelerator.log(
            {"epoch": epoch, "hbm_in_use_gb": used, "hbm_peak_gb": peak,
             "loss": float(metrics["loss"])},
        )
        accelerator.print(f"epoch {epoch}: peak {peak:.3f} GB, in use {used:.3f} GB")

    acc = evaluate(accelerator, model, eval_dl)
    accelerator.end_training()
    accelerator.print(f"fsdp peak-mem OK: accuracy {acc:.3f}")


if __name__ == "__main__":
    main()
