"""Feature: ZeRO/FSDP-style parameter sharding of a Llama decoder over the
dp_shard mesh axis (reference: FSDP2 examples + benchmarks/fsdp2)."""

import numpy as np
import optax

from _base import make_parser  # noqa: F401  (path setup)

import jax
import jax.numpy as jnp


def main():
    parser = make_parser(epochs=1, batch_size=8)
    parser.add_argument("--seq", type=int, default=128)
    args = parser.parse_args()
    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, cross_entropy_loss
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

    set_seed(args.seed)
    accelerator = Accelerator(
        mixed_precision="bf16",
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size_to_shard=0),
    )
    cfg = LlamaConfig.tiny(dtype=jnp.bfloat16)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(args.seed)
    ids = rng.integers(0, cfg.vocab_size, size=(args.batch_size, args.seq + 1), dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(args.seed), ids[:, :-1])
    model, optimizer = accelerator.prepare(model, optax.adamw(args.lr, weight_decay=0.1))

    def loss_fn(params, b):
        return cross_entropy_loss(module.apply({"params": params}, b["x"]), b["y"])

    step_fn = accelerator.prepare_train_step(loss_fn, max_grad_norm=1.0)
    state = accelerator.train_state

    # Every ≥min-size param is sharded over dp_shard: check one.
    kernel = state.params["model"]["layers"]["block"]["self_attn"]["q_proj"]["kernel"]
    spec = kernel.sharding.spec
    accelerator.print(f"q_proj kernel sharding: {spec}")

    b = {"x": ids[:, :-1], "y": ids[:, 1:]}
    losses = []
    for i in range(10):
        state, metrics = step_fn(state, b)
        losses.append(float(np.asarray(metrics["loss"])))
    accelerator.print(f"fsdp OK: loss {losses[0]:.3f} -> {losses[-1]:.3f} "
                      f"on mesh {dict(accelerator.mesh.shape)}")
    assert losses[-1] < losses[0]


if __name__ == "__main__":
    main()
