"""Feature: token-weighted gradient accumulation for autoregressive models
(reference: examples/by_feature/gradient_accumulation_for_autoregressive_models.py).

With padded variable-length documents, microbatches carry different numbers
of real tokens. Averaging per-microbatch MEAN losses (the classifier recipe)
weights a token in a short-doc microbatch more than one in a long-doc
microbatch. The fix: each microbatch contributes its token-loss SUM divided
by ``total_tokens / accum_steps`` — the accumulated gradient is then exactly
the global per-token mean, independent of how tokens fall into microbatches.
"""

import numpy as np

import jax
import jax.numpy as jnp
import optax

from _base import make_parser


def main():
    parser = make_parser(epochs=1, batch_size=4)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=4)
    args = parser.parse_args()
    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.utils import set_seed

    set_seed(args.seed)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
    )
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    accum = args.gradient_accumulation_steps
    opt_batch = args.batch_size * accum  # rows per optimizer step
    rng = np.random.default_rng(args.seed)
    n_docs, seq = 16 * opt_batch, 33
    ids = rng.integers(1, cfg.vocab_size, size=(n_docs, seq), dtype=np.int32)
    lengths = rng.integers(8, seq + 1, size=(n_docs,))
    for i, ln in enumerate(lengths):
        ids[i, ln:] = 0  # pad id 0 — docs genuinely vary in token count

    model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
    model, optimizer = accelerator.prepare(model, optax.adamw(args.lr))

    def loss_fn(params, batch):
        logits = module.apply({"params": params}, batch["x"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        valid = batch["y"] != 0
        safe = jnp.where(valid, batch["y"], 0)
        tok = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        # THE feature: normalize this microbatch's token-loss SUM by the
        # optimizer batch's tokens/accum (batch["norm"], same value on every
        # row) — NOT by this microbatch's own token count.
        return jnp.where(valid, tok, 0.0).sum() / batch["norm"][0]

    step_fn = accelerator.prepare_train_step(loss_fn, max_grad_norm=1.0)
    state = accelerator.train_state
    losses = []
    for epoch in range(args.epochs):
        for start in range(0, n_docs, opt_batch):
            rows = ids[start : start + opt_batch]
            x, y = rows[:, :-1], rows[:, 1:]
            total_tokens = int((y != 0).sum())
            batch = {
                "x": x,
                "y": y,
                # per-row so the microbatch split can carry it; all rows equal
                "norm": np.full((opt_batch,), total_tokens / accum, np.float32),
            }
            state, metrics = step_fn(state, batch)
            losses.append(float(np.asarray(metrics["loss"])))
    accelerator.print(
        f"auto-regressive grad-accum OK: token-weighted loss "
        f"{losses[0]:.3f} -> {losses[-1]:.3f} over {len(losses)} steps"
    )


if __name__ == "__main__":
    main()
