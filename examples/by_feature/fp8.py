"""Feature: fp8 matmul training via the QDQ recipe (reference:
benchmarks/fp8 + TERecipeKwargs)."""

import numpy as np
import optax

from _base import make_parser  # noqa: F401  (path setup)

import jax
import jax.numpy as jnp


def main():
    args = make_parser(epochs=1, batch_size=8).parse_args()
    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, cross_entropy_loss
    from accelerate_tpu.utils import FP8RecipeKwargs, set_seed

    set_seed(args.seed)
    accelerator = Accelerator(
        mixed_precision="fp8",
        kwargs_handlers=[FP8RecipeKwargs(fp8_format="HYBRID")],
    )
    cfg = LlamaConfig.tiny(dtype=jnp.bfloat16, fp8=True)  # fp8 QDQ projections
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(args.seed)
    ids = rng.integers(0, cfg.vocab_size, size=(args.batch_size, 65), dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(args.seed), ids[:, :-1])
    model, optimizer = accelerator.prepare(model, optax.adamw(args.lr))

    def loss_fn(params, b):
        return cross_entropy_loss(module.apply({"params": params}, b["x"]), b["y"])

    step_fn = accelerator.prepare_train_step(loss_fn, max_grad_norm=1.0)
    state = accelerator.train_state
    b = {"x": ids[:, :-1], "y": ids[:, 1:]}
    losses = []
    for _ in range(8):
        state, metrics = step_fn(state, b)
        losses.append(float(np.asarray(metrics["loss"])))
    accelerator.print(f"fp8 OK: loss {losses[0]:.3f} -> {losses[-1]:.3f}")
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]


if __name__ == "__main__":
    main()
