"""Feature: DDP gradient-compression comm hooks — fp16/bf16 wire compression
or PowerSGD low-rank reduction on the data-parallel gradient sync
(reference: examples/by_feature/ddp_comm_hook.py)."""

import jax
import optax

from _base import LoaderSpec, build_model_and_data, classifier_loss, evaluate, make_parser


def main():
    parser = make_parser(epochs=2)
    parser.add_argument("--comm_hook", default="powersgd",
                        choices=["no", "fp16", "bf16", "powersgd"])
    parser.add_argument("--powersgd_rank", type=int, default=8)
    args = parser.parse_args()
    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.utils import set_seed
    from accelerate_tpu.utils.dataclasses import DistributedDataParallelKwargs

    set_seed(args.seed)
    # Comm hooks require DDP topology: replicated params over dp_replicate
    # (the default dp_shard axis ZeRO-shards params, which hooks reject).
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        parallelism_config=ParallelismConfig(dp_replicate_size=jax.device_count()),
        kwargs_handlers=[DistributedDataParallelKwargs(
            comm_hook=args.comm_hook, powersgd_rank=args.powersgd_rank,
        )],
    )
    module, model, train_ds, eval_ds = build_model_and_data(args)
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr), LoaderSpec(train_ds, args.batch_size),
        LoaderSpec(eval_ds, args.batch_size, shuffle=False),
    )
    step_fn = accelerator.prepare_train_step(classifier_loss(module))
    state = accelerator.train_state
    for epoch in range(args.epochs):
        for batch in train_dl:
            state, metrics = step_fn(state, batch)
    acc = evaluate(accelerator, model, eval_dl)
    accelerator.print(f"ddp_comm_hook OK: accuracy {acc:.3f} "
                      f"(hook={args.comm_hook}, rank={args.powersgd_rank})")


if __name__ == "__main__":
    main()
