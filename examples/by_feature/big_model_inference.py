"""Feature: big-model inference — meta-shape init, auto device map with
budgets, layer-streamed forward with CPU offload (reference:
examples/big_model_inference + big_modeling.py)."""

import os
import tempfile

import numpy as np

from _base import make_parser  # noqa: F401  (path setup)

import jax
import jax.numpy as jnp


def main():
    args = make_parser().parse_args()
    from accelerate_tpu import Model, load_checkpoint_and_dispatch
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.utils import (
        compute_abstract_params,
        compute_module_sizes,
        infer_auto_device_map,
    )
    from accelerate_tpu.utils.other import flatten_state_dict, save_sharded_safetensors

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(args.seed)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 16), dtype=np.int32)

    # Export a sharded checkpoint to stream from.
    model = Model.from_flax(module, jax.random.key(args.seed), ids)
    expected = np.asarray(model(ids))
    ckpt = tempfile.mkdtemp(prefix="big_model_ckpt_")
    save_sharded_safetensors(
        {k: np.asarray(v) for k, v in flatten_state_dict(model.params).items()},
        ckpt, max_shard_size=50_000,
    )

    # Abstract-shape init (no memory), auto device map under a tight budget →
    # blocks land on "cpu", embeddings/head on device.
    abstract = compute_abstract_params(module, ids)
    sizes = compute_module_sizes(abstract)
    budget = {0: sizes[""] // 3, "cpu": sizes[""] * 2}
    device_map = infer_auto_device_map(abstract, budget)
    placements = {str(v) for v in device_map.values()}
    off = load_checkpoint_and_dispatch(module, ckpt, ids, device_map=device_map)
    got = np.asarray(off(ids))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)
    print(f"big-model inference OK: {len(device_map)} map entries over {placements}, "
          f"HBM-resident {off.hbm_resident_bytes()}/{sizes['']} bytes")


if __name__ == "__main__":
    main()
