from _base import build_model_and_data, classifier_loss, evaluate, make_parser
import numpy as np, optax, jax
import jax; jax.config.update("jax_platforms", "cpu")

def main():
    args = make_parser(epochs=2).parse_args()
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import ProjectConfiguration, set_seed
    from accelerate_tpu.utils.other import load_sharded_safetensors, flatten_state_dict
    from accelerate_tpu.utils.operations import to_global_host
    import shutil; shutil.rmtree("/tmp/accelerate_tpu_ckpt_example", ignore_errors=True)

    set_seed(args.seed)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        project_config=ProjectConfiguration(
            project_dir="/tmp/accelerate_tpu_ckpt_example", automatic_checkpoint_naming=True
        ),
    )
    module, model, train_ds, eval_ds = build_model_and_data(args)
    from _base import LoaderSpec
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr), LoaderSpec(train_ds, args.batch_size),
        LoaderSpec(eval_ds, args.batch_size, shuffle=False),
    )
    step_fn = accelerator.prepare_train_step(classifier_loss(module))
    state = accelerator.train_state
    for epoch in range(args.epochs):
        for batch in train_dl:
            state, metrics = step_fn(state, batch)
        accelerator.save_state()
    # Compare live params vs what's on disk in the LAST checkpoint.
    live = {k: np.asarray(v) for k, v in flatten_state_dict(to_global_host(accelerator.train_state.params)).items()}
    disk = load_sharded_safetensors("/tmp/accelerate_tpu_ckpt_example/checkpoints/checkpoint_1", weights_name="model.safetensors")
    print("keys equal:", set(live) == set(disk))
    diffs = {k: float(np.abs(live[k] - disk[k]).max()) for k in live}
    bad = {k: v for k, v in diffs.items() if v > 1e-6}
    print("SAVE divergence:", dict(list(bad.items())[:4]) or "none")
    d0 = load_sharded_safetensors("/tmp/accelerate_tpu_ckpt_example/checkpoints/checkpoint_0", weights_name="model.safetensors")
    diffs0 = {k: float(np.abs(live[k] - d0[k]).max()) for k in live}
    print("ckpt0 vs live max:", max(diffs0.values()))

if __name__ == "__main__":
    main()
