"""Feature: correct multi-process metrics with gather_for_metrics — the
gather trims duplicate samples that even_batches padding added on the final
uneven batch, so every eval sample is counted exactly once
(reference: examples/by_feature/multi_process_metrics.py)."""

import jax.numpy as jnp
import numpy as np
import optax

from _base import LoaderSpec, build_model_and_data, classifier_loss, make_parser


def main():
    args = make_parser(epochs=1).parse_args()
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import set_seed

    set_seed(args.seed)
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    # 250 eval samples with batch 32: the final batch is short and padded
    # across ranks — exactly the case gather_for_metrics exists for.
    module, model, train_ds, eval_ds = build_model_and_data(args, n_eval=250)
    eval_spec = LoaderSpec(eval_ds, args.batch_size, shuffle=False)
    eval_spec.drop_last = False  # keep the short batch; even_batches pads it
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr), LoaderSpec(train_ds, args.batch_size), eval_spec,
    )
    step_fn = accelerator.prepare_train_step(classifier_loss(module))
    state = accelerator.train_state
    for batch in train_dl:
        state, _ = step_fn(state, batch)

    correct = total = 0
    for batch in eval_dl:
        preds = jnp.argmax(model(batch["input_ids"], batch["attention_mask"]), -1)
        # The feature: gather across processes AND drop the padded remainder.
        preds, labels = accelerator.gather_for_metrics((preds, batch["labels"]))
        correct += int((np.asarray(preds) == np.asarray(labels)).sum())
        total += len(np.asarray(preds))

    assert total == 250, f"gather_for_metrics must count each sample once, got {total}"
    accelerator.print(f"multi-process metrics OK: {total} samples, accuracy {correct / total:.3f}")


if __name__ == "__main__":
    main()
