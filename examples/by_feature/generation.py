"""Feature: KV-cache autoregressive generation (accelerate_tpu.generate) —
greedy vs sampled continuations from a causal model, plus the
encoder-decoder path (T5): encoder runs once, cross-attention K/V is
precomputed, and the decode loop reuses the same cache contract."""

import numpy as np

from _base import make_parser  # noqa: F401  (path setup)

import jax
import jax.numpy as jnp


def main():
    args = make_parser().parse_args()
    from accelerate_tpu import Model, generate
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(args.seed)
    prompt = rng.integers(0, cfg.vocab_size, size=(2, 8), dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(args.seed), prompt)

    greedy = generate(model, prompt, max_new_tokens=12)
    sampled = generate(
        model, prompt, max_new_tokens=12, temperature=0.8, top_p=0.9,
        rng=jax.random.key(args.seed),
    )
    assert greedy.shape == sampled.shape == (2, 20)
    # Greedy continuation must equal the argmax of a full re-forward.
    full = module.apply({"params": model.params}, greedy[:, :-1])
    nxt = jnp.argmax(full[:, -1].astype(jnp.float32), -1)
    assert bool((greedy[:, -1] == nxt).all())
    print(f"greedy tail: {np.asarray(greedy[0, 8:]).tolist()}")
    print(f"sampled tail: {np.asarray(sampled[0, 8:]).tolist()}")

    # Encoder-decoder: input_ids feed the ENCODER; generation returns the
    # decoder sequence starting from decoder_start_token_id.
    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration

    t5_cfg = T5Config.tiny(dtype=jnp.float32)
    t5 = T5ForConditionalGeneration(t5_cfg)
    enc_ids = rng.integers(1, t5_cfg.vocab_size, size=(2, 10), dtype=np.int32)
    t5_model = Model.from_flax(t5, jax.random.key(args.seed), enc_ids, enc_ids[:, :1])
    dec = generate(t5_model, enc_ids, max_new_tokens=8)
    assert dec.shape == (2, 9)  # start token + 8 generated
    print(f"t5 decode: {np.asarray(dec[0]).tolist()}")
    print("generation OK")


if __name__ == "__main__":
    main()
