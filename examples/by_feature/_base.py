"""Shared scaffolding for the by_feature examples: each script is the
nlp_example training loop plus exactly one feature (the reference enforces
this with an AST diff, tests/test_examples.py:70 — here the base is imported
so the delta is visible directly)."""

import os
import sys

_EXAMPLES = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _EXAMPLES)
sys.path.insert(0, os.path.dirname(_EXAMPLES))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from nlp_example import NUM_CLASSES, EncoderClassifier, LoaderSpec, build_dataset


def make_parser(**overrides):
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default=None, choices=[None, "no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=overrides.get("batch_size", 32))
    parser.add_argument("--epochs", type=int, default=overrides.get("epochs", 2))
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--seed", type=int, default=42)
    return parser


def build_model_and_data(args, n_train=1024, n_eval=256):
    module = EncoderClassifier()
    train_ds = build_dataset(n_train, seed=0)
    eval_ds = build_dataset(n_eval, seed=1)
    sample = train_ds[0]
    from accelerate_tpu import Model

    model = Model.from_flax(
        module, jax.random.key(args.seed),
        sample["input_ids"][None], sample["attention_mask"][None],
    )
    return module, model, train_ds, eval_ds


def classifier_loss(module):
    def loss_fn(params, batch):
        logits = module.apply({"params": params}, batch["input_ids"], batch["attention_mask"])
        return optax.softmax_cross_entropy(
            logits, jax.nn.one_hot(batch["labels"], NUM_CLASSES)
        ).mean()

    return loss_fn


def evaluate(accelerator, model, eval_dl):
    correct = total = 0
    for batch in eval_dl:
        preds = jnp.argmax(model(batch["input_ids"], batch["attention_mask"]), -1)
        g = accelerator.gather_for_metrics((preds, batch["labels"]))
        correct += int((np.asarray(g[0]) == np.asarray(g[1])).sum())
        total += len(np.asarray(g[0]))
    return correct / max(total, 1)
