"""Feature: LocalSGD — K local steps between cross-process parameter
averages (reference: examples/by_feature/local_sgd.py)."""

import optax

from _base import LoaderSpec, build_model_and_data, classifier_loss, evaluate, make_parser


def main():
    parser = make_parser(epochs=2)
    parser.add_argument("--local_sgd_steps", type=int, default=8)
    args = parser.parse_args()
    from accelerate_tpu import Accelerator, LocalSGD
    from accelerate_tpu.utils import set_seed

    set_seed(args.seed)
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    module, model, train_ds, eval_ds = build_model_and_data(args)
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr), LoaderSpec(train_ds, args.batch_size),
        LoaderSpec(eval_ds, args.batch_size, shuffle=False),
    )
    step_fn = accelerator.prepare_train_step(classifier_loss(module))
    state = accelerator.train_state
    with LocalSGD(accelerator, model, local_sgd_steps=args.local_sgd_steps) as lsgd:
        for epoch in range(args.epochs):
            for batch in train_dl:
                state, metrics = step_fn(state, batch)
                state = lsgd.step(state)
    acc = evaluate(accelerator, model, eval_dl)
    accelerator.print(f"local_sgd OK: accuracy {acc:.3f} "
                      f"({'averaging active' if lsgd.enabled else 'single process, no-op'})")


if __name__ == "__main__":
    main()
