"""Feature: save_state / load_state round-trip with automatic checkpoint
naming and mid-training resume (reference: examples/by_feature/checkpointing.py)."""

import numpy as np
import optax

from _base import build_model_and_data, classifier_loss, evaluate, make_parser


def main():
    args = make_parser(epochs=2).parse_args()
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import ProjectConfiguration, set_seed

    set_seed(args.seed)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        project_config=ProjectConfiguration(
            project_dir="/tmp/accelerate_tpu_ckpt_example", automatic_checkpoint_naming=True
        ),
    )
    module, model, train_ds, eval_ds = build_model_and_data(args)
    from _base import LoaderSpec

    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr), LoaderSpec(train_ds, args.batch_size),
        LoaderSpec(eval_ds, args.batch_size, shuffle=False),
    )
    step_fn = accelerator.prepare_train_step(classifier_loss(module))
    state = accelerator.train_state

    for epoch in range(args.epochs):
        for batch in train_dl:
            state, metrics = step_fn(state, batch)
        accelerator.save_state()  # checkpoints/checkpoint_<epoch>

    step_before = int(np.asarray(accelerator.train_state.step))
    acc_before = evaluate(accelerator, model, eval_dl)

    # Restore the latest checkpoint and prove the state round-trips.
    accelerator.load_state()
    assert int(np.asarray(accelerator.train_state.step)) == step_before
    acc_after = evaluate(accelerator, model, eval_dl)
    assert abs(acc_before - acc_after) < 1e-6, (acc_before, acc_after)
    accelerator.print(f"checkpointing OK: accuracy {acc_after:.3f} at step {step_before}")


if __name__ == "__main__":
    main()
