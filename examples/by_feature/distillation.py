"""Feature: multi-model training — frozen-teacher distillation. Two models
share one Accelerator, each with its own TrainState slot; the student steps
through prepare_train_step(loss_fn, model=student) while the optimizer-less
teacher stays frozen (docs/usage_guides/multiple_models.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from _base import LoaderSpec, build_model_and_data, make_parser


def main():
    args = make_parser(epochs=4).parse_args()
    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.utils import set_seed

    set_seed(args.seed)
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    module, teacher, train_ds, eval_ds = build_model_and_data(args)
    sample = train_ds[0]
    student = Model.from_flax(
        module, jax.random.key(args.seed + 1),
        sample["input_ids"][None], sample["attention_mask"][None],
    )
    # Order pairs the optimizer with the student; the teacher gets no
    # optimizer and its slot stays frozen.
    student, opt, teacher, train_dl, agree_dl = accelerator.prepare(
        student, optax.adamw(args.lr), teacher,
        LoaderSpec(train_ds, args.batch_size),
        # Agreement is measured on the distillation inputs themselves — a
        # randomly-initialized teacher's function has no structure to
        # generalize from; the demo is the multi-model mechanics.
        LoaderSpec(train_ds, args.batch_size, shuffle=False),
    )
    assert accelerator._train_states[teacher._state_slot].tx is None

    teacher_frozen = jax.tree.map(np.asarray, teacher.params)

    def distill_loss(params, batch):
        t_logits = teacher(batch["input_ids"], batch["attention_mask"])
        s_logits = module.apply(
            {"params": params}, batch["input_ids"], batch["attention_mask"]
        )
        # Logit matching (Ba & Caruana style): a randomly-initialized teacher
        # has near-uniform softmax, so KL gradients vanish — regressing the
        # logits themselves keeps the signal strong for the demo.
        return jnp.mean((s_logits - jax.lax.stop_gradient(t_logits)) ** 2)

    step_fn = accelerator.prepare_train_step(distill_loss, model=student)
    state = accelerator._train_states[student._state_slot]
    for _ in range(args.epochs):
        for batch in train_dl:
            state, metrics = step_fn(state, batch)

    # Teacher untouched; student moved toward it (agreement on eval set).
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        teacher.params, teacher_frozen,
    )
    agree = total = 0
    for batch in agree_dl:
        t = jnp.argmax(teacher(batch["input_ids"], batch["attention_mask"]), -1)
        s = jnp.argmax(student(batch["input_ids"], batch["attention_mask"]), -1)
        g = accelerator.gather_for_metrics((t, s))
        agree += int((np.asarray(g[0]) == np.asarray(g[1])).sum())
        total += len(np.asarray(g[0]))
    accelerator.print(
        f"distillation OK: teacher frozen, student agreement {agree / total:.3f}"
    )
    assert agree / total > 0.7, f"student failed to match teacher ({agree / total:.3f})"


if __name__ == "__main__":
    main()
