"""Feature: long-context generation with cp_generate — the prompt sequence
shards over the ``cp`` mesh axis (ring-attention prefill, flash-decoding
over the sequence-sharded prefix cache), so reachable prompt length scales
with the cp degree. Beyond the reference: its context parallelism is
training-only."""

import numpy as np

from _base import make_parser  # noqa: F401  (path setup)

import jax
import jax.numpy as jnp


def main():
    args = make_parser().parse_args()
    from accelerate_tpu import Accelerator, Model, ParallelismConfig, generate
    from accelerate_tpu.cp_generation import cp_generate
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.utils import set_seed

    set_seed(args.seed)
    n = len(jax.devices())
    cp = 2 if n % 2 == 0 else 1
    acc = Accelerator(
        parallelism_config=ParallelismConfig(cp_size=cp, dp_shard_size=n // cp)
    )
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(args.seed)
    prompt = rng.integers(0, cfg.vocab_size, size=(2, 32), dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(args.seed), prompt)

    out = cp_generate(model, prompt, max_new_tokens=8, mesh=acc.mesh)
    # The single-chip path produces the identical greedy continuation.
    ref = generate(model, prompt, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    acc.print(
        f"long-context generation OK: prompt 32 tokens sharded over cp={cp}, "
        f"output {out.shape}, token-identical to the single-chip path"
    )


if __name__ == "__main__":
    main()
