"""Feature: Megatron-style tensor parallelism from the in-framework rule
table (reference: examples/torch_native_parallelism, transformers tp_plan)."""

import numpy as np
import optax

from _base import make_parser  # noqa: F401  (path setup)

import jax
import jax.numpy as jnp


def main():
    parser = make_parser(epochs=1, batch_size=8)
    parser.add_argument("--tp_size", type=int, default=2)
    args = parser.parse_args()
    from accelerate_tpu import Accelerator, Model, ParallelismConfig
    from accelerate_tpu.models import (
        LlamaConfig, LlamaForCausalLM, cross_entropy_loss, llama_tp_rules,
    )
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

    set_seed(args.seed)
    n = len(jax.devices())
    pc = ParallelismConfig(tp_size=args.tp_size, dp_shard_size=max(1, n // args.tp_size))
    accelerator = Accelerator(
        parallelism_config=pc, mixed_precision="bf16",
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size_to_shard=0),
    )
    cfg = LlamaConfig.tiny(dtype=jnp.bfloat16)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(args.seed)
    ids = rng.integers(0, cfg.vocab_size, size=(args.batch_size, 65), dtype=np.int32)
    model = Model.from_flax(
        module, jax.random.key(args.seed), ids[:, :-1],
        tp_rules=llama_tp_rules(cfg.scan_layers),
    )
    model, optimizer = accelerator.prepare(model, optax.adamw(args.lr))

    def loss_fn(params, b):
        return cross_entropy_loss(module.apply({"params": params}, b["x"]), b["y"])

    step_fn = accelerator.prepare_train_step(loss_fn)
    state = accelerator.train_state
    kernel = state.params["model"]["layers"]["block"]["mlp"]["gate_proj"]["kernel"]
    accelerator.print(f"gate_proj sharding: {kernel.sharding.spec} on mesh {dict(accelerator.mesh.shape)}")

    b = {"x": ids[:, :-1], "y": ids[:, 1:]}
    losses = []
    for _ in range(5):
        state, metrics = step_fn(state, b)
        losses.append(float(np.asarray(metrics["loss"])))
    accelerator.print(f"tp OK: loss {losses[0]:.3f} -> {losses[-1]:.3f}")
    assert losses[-1] < losses[0]


if __name__ == "__main__":
    main()
