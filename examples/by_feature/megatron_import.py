"""Feature: import a Megatron-LM (megatron-core) checkpoint and generate.

Builds a tiny native Llama, writes it out as a synthetic megatron-core
checkpoint directory (fused per-group QKV, SwiGLU gate/up halves, TP=2
shards with rank-local fc1 layout), then round-trips: load -> merge TP
shards -> convert -> logit parity + generation.
"""

import os

import numpy as np

from _base import make_parser  # noqa: F401  (path setup)

import jax
import jax.numpy as jnp


def main():
    args = make_parser().parse_args()
    import torch

    from accelerate_tpu import Model, generate
    from accelerate_tpu.models import (
        LlamaConfig,
        LlamaForCausalLM,
        load_megatron_checkpoint,
        megatron_params_to_llama,
        merge_megatron_tp_shards,
    )

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(args.seed)
    ids = rng.integers(1, cfg.vocab_size, size=(2, 8), dtype=np.int32)
    native = Model.from_flax(module, jax.random.key(args.seed), ids)
    want = np.asarray(native(ids))

    # --- write a synthetic megatron-core checkpoint (what Megatron saves) ---
    from accelerate_tpu.models.megatron import llama_params_to_megatron_core

    sd = llama_params_to_megatron_core(cfg, native.params)
    root = "/tmp/megatron_ckpt_example"
    it = os.path.join(root, "iter_0000042")
    for rank in (0, 1):
        d = os.path.join(it, f"mp_rank_{rank:02d}")
        os.makedirs(d, exist_ok=True)
    with open(os.path.join(root, "latest_checkpointed_iteration.txt"), "w") as f:
        f.write("42")

    def tp_split(name, arr):
        if name.endswith("linear_fc1.weight"):
            gate, up = np.split(arr, 2, axis=0)
            g0, g1 = np.split(gate, 2, axis=0)
            u0, u1 = np.split(up, 2, axis=0)
            return [np.concatenate([g0, u0]), np.concatenate([g1, u1])]
        if name.endswith(("linear_qkv.weight", "word_embeddings.weight", "output_layer.weight")):
            return np.split(arr, 2, axis=0)
        if name.endswith(("linear_proj.weight", "linear_fc2.weight")):
            return np.split(arr, 2, axis=1)
        return [arr, arr]

    shards = [{}, {}]
    for name, arr in sd.items():
        a, b = tp_split(name, arr)
        shards[0][name], shards[1][name] = a, b
    for rank, shard in enumerate(shards):
        torch.save(
            {"model": {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in shard.items()},
             "args": {"tensor_model_parallel_size": 2}},
            os.path.join(it, f"mp_rank_{rank:02d}", "model_optim_rng.pt"),
        )

    # --- import ---
    loaded_shards, meg_args = load_megatron_checkpoint(root)
    assert meg_args["tensor_model_parallel_size"] == 2
    merged = merge_megatron_tp_shards(loaded_shards)
    params = jax.tree.map(jnp.asarray, megatron_params_to_llama(cfg, merged))
    imported = Model(module=module, params=params)

    got = np.asarray(imported(ids))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    out = generate(imported, ids, max_new_tokens=6)
    assert out.shape == (2, 14)
    print(f"imported logits max|diff| = {np.max(np.abs(got - want)):.2e}")
    print(f"generated: {np.asarray(out[0, 8:]).tolist()}")
    print("megatron import OK")


if __name__ == "__main__":
    main()
