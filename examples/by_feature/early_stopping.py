"""Feature: cross-process early stopping via set_trigger / check_trigger —
any rank can flag a stop and ALL ranks see it (reference:
examples/by_feature/early_stopping.py, accelerator.py:2852-2909)."""

import numpy as np
import optax

from _base import LoaderSpec, build_model_and_data, classifier_loss, evaluate, make_parser


def main():
    args = make_parser(epochs=10).parse_args()
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import set_seed

    set_seed(args.seed)
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    module, model, train_ds, eval_ds = build_model_and_data(args)
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr), LoaderSpec(train_ds, args.batch_size),
        LoaderSpec(eval_ds, args.batch_size, shuffle=False),
    )
    step_fn = accelerator.prepare_train_step(classifier_loss(module))
    state = accelerator.train_state

    target_loss = 0.15
    stopped_epoch = None
    for epoch in range(args.epochs):
        for batch in train_dl:
            state, metrics = step_fn(state, batch)
            # Local condition on this rank…
            if float(np.asarray(metrics["loss"])) < target_loss:
                accelerator.set_trigger()
        # …checked collectively: stops every rank together.
        if accelerator.check_trigger():
            stopped_epoch = epoch
            break
    acc = evaluate(accelerator, model, eval_dl)
    accelerator.print(f"early stopping OK: stopped at epoch {stopped_epoch}, accuracy {acc:.3f}")
    assert stopped_epoch is not None and stopped_epoch < args.epochs - 1


if __name__ == "__main__":
    main()
