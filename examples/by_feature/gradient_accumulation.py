"""Feature: gradient accumulation folded into the jitted step as a
lax.scan over microbatches (reference: examples/by_feature/gradient_accumulation.py
wraps each step in accelerator.accumulate)."""

import numpy as np
import optax

from _base import LoaderSpec, build_model_and_data, classifier_loss, evaluate, make_parser


def main():
    parser = make_parser(epochs=2)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=4)
    args = parser.parse_args()
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import set_seed

    set_seed(args.seed)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
    )
    module, model, train_ds, eval_ds = build_model_and_data(args)
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr), LoaderSpec(train_ds, args.batch_size),
        LoaderSpec(eval_ds, args.batch_size, shuffle=False),
    )
    # One call consumes the FULL optimizer batch; microbatching happens
    # inside jit (no no_sync bookkeeping needed — SURVEY.md §2.9 DDP row).
    step_fn = accelerator.prepare_train_step(classifier_loss(module))
    state = accelerator.train_state
    for epoch in range(args.epochs):
        for batch in train_dl:
            state, metrics = step_fn(state, batch)
    acc = evaluate(accelerator, model, eval_dl)
    opt_steps = int(np.asarray(state.step))
    accelerator.print(f"grad-accum OK: accuracy {acc:.3f} after {opt_steps} optimizer steps")
    assert acc > 0.6


if __name__ == "__main__":
    main()
