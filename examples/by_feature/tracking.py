"""Feature: experiment tracking via init_trackers / log / end_training
(reference: examples/by_feature/tracking.py)."""

import json
import os

import optax

from _base import LoaderSpec, build_model_and_data, classifier_loss, evaluate, make_parser


def main():
    args = make_parser(epochs=1).parse_args()
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import set_seed

    set_seed(args.seed)
    project_dir = "/tmp/accelerate_tpu_tracking_example"
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision, log_with="all", project_dir=project_dir
    )
    accelerator.init_trackers("tracking_example", config=vars(args))
    module, model, train_ds, eval_ds = build_model_and_data(args)
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr), LoaderSpec(train_ds, args.batch_size),
        LoaderSpec(eval_ds, args.batch_size, shuffle=False),
    )
    step_fn = accelerator.prepare_train_step(classifier_loss(module))
    state = accelerator.train_state
    for epoch in range(args.epochs):
        for batch in train_dl:
            state, metrics = step_fn(state, batch)
        acc = evaluate(accelerator, model, eval_dl)
        accelerator.log({"accuracy": acc, "loss": float(metrics["loss"])}, step=epoch)
    accelerator.end_training()

    metrics_file = os.path.join(project_dir, "tracking_example.metrics.jsonl")
    if accelerator.is_main_process and os.path.exists(metrics_file):
        rows = [json.loads(l) for l in open(metrics_file)]
        accelerator.print(f"tracking OK: {len(rows)} logged rows, last={rows[-1]}")


if __name__ == "__main__":
    main()
