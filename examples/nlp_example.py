"""NLP example — the framework's minimum end-to-end slice.

Mirrors the reference's ``examples/nlp_example.py`` (BERT-base on GLUE/MRPC):
a small transformer encoder classifier, sequence-pair classification, padded
batches, ``accelerator.prepare``, gradient accumulation, mixed precision,
``gather_for_metrics`` for eval, tracker logging. Data is synthetic MRPC-like
(paraphrase detection on token sequences) so the example runs hermetically on
any host; swap ``build_dataset`` for HF datasets for the real thing.

Run:
    python examples/nlp_example.py                 # single device / all local devices
    ACCELERATE_MIXED_PRECISION=bf16 python examples/nlp_example.py
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from accelerate_tpu import Accelerator, Model
from accelerate_tpu.utils import set_seed

VOCAB, SEQ, NUM_CLASSES = 1024, 64, 2


class EncoderClassifier(nn.Module):
    """Small BERT-shaped encoder: embeddings + N self-attention blocks + CLS head."""

    hidden: int = 128
    layers: int = 2
    heads: int = 4

    @nn.compact
    def __call__(self, input_ids, attention_mask):
        x = nn.Embed(VOCAB, self.hidden, name="tok")(input_ids)
        x = x + nn.Embed(SEQ, self.hidden, name="pos")(jnp.arange(input_ids.shape[-1]))
        mask = attention_mask[:, None, None, :].astype(bool)
        for i in range(self.layers):
            h = nn.LayerNorm()(x)
            h = nn.MultiHeadDotProductAttention(num_heads=self.heads, name=f"attn_{i}")(
                h, h, mask=mask
            )
            x = x + h
            h = nn.LayerNorm()(x)
            h = nn.Dense(self.hidden * 4)(h)
            h = nn.gelu(h)
            x = x + nn.Dense(self.hidden)(h)
        cls = nn.LayerNorm()(x[:, 0])
        return nn.Dense(NUM_CLASSES, name="classifier")(cls)


def build_dataset(n, seed):
    """Synthetic sentence classification: the class is carried by which marker
    token (0 or 1) appears at one random position in an otherwise random
    sequence — the model must learn to attend to find it."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(2, VOCAB, size=(n, SEQ), dtype=np.int32)
    labels = rng.integers(0, 2, size=n).astype(np.int32)
    pos = rng.integers(1, SEQ, size=n)
    ids[np.arange(n), pos] = labels
    mask = np.ones_like(ids)

    class DS:
        def __len__(self):
            return n

        def __getitem__(self, i):
            return {"input_ids": ids[i], "attention_mask": mask[i], "labels": labels[i]}

    return DS()


class LoaderSpec:
    def __init__(self, dataset, batch_size, shuffle=True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = type("S", (), {"__name__": "RandomSampler"})() if shuffle else None
        self.drop_last = True


def training_function(args):
    set_seed(args.seed)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        log_with="json" if args.project_dir else None,
        project_dir=args.project_dir,
    )
    if args.project_dir:
        accelerator.init_trackers("nlp_example", config=vars(args))

    module = EncoderClassifier()
    train_ds = build_dataset(2048, seed=0)
    eval_ds = build_dataset(512, seed=1)
    sample = train_ds[0]
    model = Model.from_flax(
        module,
        jax.random.key(args.seed),
        sample["input_ids"][None],
        sample["attention_mask"][None],
    )
    schedule = optax.linear_schedule(args.lr, 0.0, args.epochs * (2048 // args.batch_size))
    tx = optax.adamw(schedule, weight_decay=0.01)

    model, optimizer, train_dl, eval_dl, lr_sched = accelerator.prepare(
        model, tx, LoaderSpec(train_ds, args.batch_size),
        LoaderSpec(eval_ds, args.batch_size, shuffle=False), schedule,
    )

    def loss_fn(params, batch):
        logits = module.apply({"params": params}, batch["input_ids"], batch["attention_mask"])
        labels = jax.nn.one_hot(batch["labels"], NUM_CLASSES)
        return optax.softmax_cross_entropy(logits, labels).mean()

    step_fn = accelerator.prepare_train_step(loss_fn, max_grad_norm=1.0)
    state = accelerator.train_state

    for epoch in range(args.epochs):
        t0, seen = time.time(), 0
        for batch in train_dl:
            state, metrics = step_fn(state, batch)
            seen += args.batch_size
        accelerator._train_state = state
        # Drain the async pipeline before eval (CPU-mesh stuck-detector guard)
        # — also makes step_time honest.
        jax.block_until_ready(state.params)
        step_time = (time.time() - t0) / max(1, seen // args.batch_size)

        # Eval with gather_for_metrics (drops duplicated tail samples).
        correct = total = 0
        for batch in eval_dl:
            logits = model(batch["input_ids"], batch["attention_mask"])
            preds = jnp.argmax(logits, -1)
            gathered = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct += int((np.asarray(gathered[0]) == np.asarray(gathered[1])).sum())
            total += len(np.asarray(gathered[0]))
        acc_val = correct / max(total, 1)
        accelerator.print(
            f"epoch {epoch}: accuracy {acc_val:.3f} loss {float(metrics['loss']):.4f} "
            f"step_time {step_time*1e3:.1f}ms"
        )
        accelerator.log({"accuracy": acc_val, "loss": float(metrics["loss"]), "step_time_ms": step_time * 1e3}, step=epoch)

    accelerator.end_training()
    return acc_val


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", type=str, default=None, choices=[None, "no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--project_dir", type=str, default=None)
    args = parser.parse_args()
    final_acc = training_function(args)
    assert final_acc > 0.65, f"example failed to learn (accuracy {final_acc})"
    print(f"final_accuracy={final_acc:.3f}")


if __name__ == "__main__":
    main()
