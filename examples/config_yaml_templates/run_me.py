"""Prints the topology the active config resolves to — run it through the
launcher with any template (reference: config_yaml_templates/run_me.py):

    accelerate-tpu launch --config_file fsdp.yaml run_me.py
"""

from accelerate_tpu import Accelerator


def main():
    acc = Accelerator()
    acc.print(
        f"processes={acc.num_processes} rank={acc.process_index} "
        f"mesh={dict(acc.mesh.shape)} mixed_precision={acc.mixed_precision} "
        f"fsdp={'on (' + acc.fsdp_plugin.sharding_strategy + ')' if acc.fsdp_plugin else 'off'}"
    )


if __name__ == "__main__":
    main()
