"""CV example — image classification with a small conv net.

Mirrors the reference's ``examples/cv_example.py`` (ResNet-50 on a pets image
folder): image batches, channels-last conv stack, mixed precision,
``accelerator.prepare``, eval with ``gather_for_metrics``. Data is synthetic
(class = dominant blob color, so a conv net must pool spatial evidence) to
keep the example hermetic; swap ``build_dataset`` for a real image folder +
torchvision transforms for the real thing.

Run:
    python examples/cv_example.py
    ACCELERATE_MIXED_PRECISION=bf16 python examples/cv_example.py
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from accelerate_tpu import Accelerator, Model
from accelerate_tpu.utils import set_seed

IMG, NUM_CLASSES = 32, 4


class ConvNet(nn.Module):
    """Small ResNet-shaped stack: stem + residual conv blocks + pooled head.
    Channels-last (NHWC) — the layout XLA:TPU prefers for convolutions."""

    width: int = 32
    blocks: int = 2

    @nn.compact
    def __call__(self, images):
        x = nn.Conv(self.width, (3, 3), name="stem")(images)
        x = nn.relu(x)
        for i in range(self.blocks):
            h = nn.Conv(self.width, (3, 3), name=f"conv{i}a")(x)
            h = nn.relu(h)
            h = nn.Conv(self.width, (3, 3), name=f"conv{i}b")(h)
            x = nn.relu(x + h)
            x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.mean(axis=(1, 2))  # global average pool
        return nn.Dense(NUM_CLASSES, name="classifier")(x)


def build_dataset(n, seed):
    """Synthetic images: class k paints a bright blob in color channel
    pattern k at a random location over noise."""
    rng = np.random.default_rng(seed)
    images = rng.normal(0.0, 0.3, size=(n, IMG, IMG, 3)).astype(np.float32)
    labels = rng.integers(0, NUM_CLASSES, size=n).astype(np.int32)
    patterns = np.array(
        [[1.0, 0, 0], [0, 1.0, 0], [0, 0, 1.0], [0.7, 0.7, 0]], dtype=np.float32
    )
    for i in range(n):
        cy, cx = rng.integers(4, IMG - 4, size=2)
        images[i, cy - 3: cy + 3, cx - 3: cx + 3] += patterns[labels[i]]

    class DS:
        def __len__(self):
            return n

        def __getitem__(self, i):
            return {"images": images[i], "labels": labels[i]}

    return DS()


class LoaderSpec:
    def __init__(self, dataset, batch_size, shuffle=True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = type("S", (), {"__name__": "RandomSampler"})() if shuffle else None
        self.drop_last = True


def training_function(args):
    set_seed(args.seed)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        log_with="json" if args.project_dir else None,
        project_dir=args.project_dir,
    )
    if args.project_dir:
        accelerator.init_trackers("cv_example", config=vars(args))

    if args.arch == "resnet":
        # The real CV family (models/resnet.py — ResNet-50 shape, BatchNorm
        # with sync-BN semantics under the dp-sharded batch). tiny() keeps the
        # example fast; swap for ResNetConfig.resnet50() on real data.
        from accelerate_tpu.models import ResNet, ResNetConfig

        module = ResNet(ResNetConfig.tiny(num_classes=NUM_CLASSES))
    else:
        module = ConvNet()
    train_ds = build_dataset(2048, seed=0)
    eval_ds = build_dataset(512, seed=1)
    sample = train_ds[0]
    model = Model.from_flax(module, jax.random.key(args.seed), sample["images"][None])
    schedule = optax.cosine_decay_schedule(args.lr, args.epochs * (2048 // args.batch_size))
    tx = optax.adamw(schedule, weight_decay=1e-4)

    model, optimizer, train_dl, eval_dl, lr_sched = accelerator.prepare(
        model, tx, LoaderSpec(train_ds, args.batch_size),
        LoaderSpec(eval_ds, args.batch_size, shuffle=False), schedule,
    )

    if args.arch == "resnet":
        from accelerate_tpu.models import resnet_loss

        def loss_fn(params, extra, batch):
            return resnet_loss(module, params, extra, batch["images"], batch["labels"])

        step_fn = accelerator.prepare_train_step(
            loss_fn, mutable_state=True, max_grad_norm=1.0
        )
    else:
        def loss_fn(params, batch):
            logits = module.apply({"params": params}, batch["images"])
            labels = jax.nn.one_hot(batch["labels"], NUM_CLASSES)
            return optax.softmax_cross_entropy(logits, labels).mean()

        step_fn = accelerator.prepare_train_step(loss_fn, max_grad_norm=1.0)
    state = accelerator.train_state

    for epoch in range(args.epochs):
        t0, steps = time.time(), 0
        for batch in train_dl:
            state, metrics = step_fn(state, batch)
            steps += 1
        accelerator._train_state = state
        # Drain the async pipeline before eval: on the CPU mesh a deep queue
        # of in-flight steps can trip XLA's collective stuck-detector when the
        # eval program's all-gather waits behind a straggler device.
        jax.block_until_ready(state.params)
        step_time = (time.time() - t0) / max(1, steps)

        correct = total = 0
        for batch in eval_dl:
            preds = jnp.argmax(model(batch["images"]), -1)
            gathered = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct += int((np.asarray(gathered[0]) == np.asarray(gathered[1])).sum())
            total += len(np.asarray(gathered[0]))
        acc_val = correct / max(total, 1)
        accelerator.print(
            f"epoch {epoch}: accuracy {acc_val:.3f} loss {float(metrics['loss']):.4f} "
            f"step_time {step_time*1e3:.1f}ms"
        )
        accelerator.log(
            {"accuracy": acc_val, "loss": float(metrics["loss"])}, step=epoch
        )

    accelerator.end_training()
    return acc_val


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", type=str, default=None, choices=[None, "no", "bf16", "fp16"])
    parser.add_argument("--arch", type=str, default="convnet", choices=["convnet", "resnet"])
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--project_dir", type=str, default=None)
    args = parser.parse_args()
    final_acc = training_function(args)
    assert final_acc > 0.6, f"example failed to learn (accuracy {final_acc})"
    print(f"final_accuracy={final_acc:.3f}")


if __name__ == "__main__":
    main()
