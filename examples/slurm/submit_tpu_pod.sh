#!/bin/bash
# SLURM template for a multi-host TPU pod job (parity surface for
# /root/reference/examples/slurm/submit_multinode.sh, TPU-flavored:
# ONE task per host — a single JAX process drives all of a host's chips).

#SBATCH --job-name=accelerate-tpu
#SBATCH -D .
#SBATCH --output=O-%x.%j
#SBATCH --error=E-%x.%j
#SBATCH --nodes=4                   # TPU hosts in the slice
#SBATCH --ntasks-per-node=1         # one JAX process per host (SPMD)
#SBATCH --cpus-per-task=96
#SBATCH --time=01:59:00

######################
### Set environment ##
######################
source activate_environment.sh      # your venv with accelerate-tpu

######################
#### Set network #####
######################
head_node_ip=$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n 1)

# NOTE: \$SLURM_NODEID is escaped — it must expand inside each srun task
# (where it is the host's index), not in this batch shell (where it is 0).
export LAUNCHER="accelerate-tpu launch \
    --num_processes $SLURM_NNODES \
    --num_machines $SLURM_NNODES \
    --machine_rank \$SLURM_NODEID \
    --main_process_ip $head_node_ip \
    --main_process_port 8476 \
    --mixed_precision bf16 \
    --use_fsdp --fsdp_sharding_strategy FULL_SHARD \
    "
export SCRIPT="examples/complete_nlp_example.py"
export SCRIPT_ARGS="--epochs 3 --project_dir runs/$SLURM_JOB_ID"

# srun starts one launcher per host; each brings up its local JAX process
# and they rendezvous at the coordinator on the head node.
srun bash -c "$LAUNCHER $SCRIPT $SCRIPT_ARGS"
