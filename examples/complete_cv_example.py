"""Complete CV example — everything at once (reference:
examples/complete_cv_example.py): conv-net image classification with mixed
precision + gradient accumulation, checkpointing every N steps/epoch with
resume, experiment tracking, and eval via gather_for_metrics.

Run:
    python examples/complete_cv_example.py --checkpointing_steps epoch \
        --project_dir /tmp/complete_cv --with_tracking
    python examples/complete_cv_example.py --resume_from_checkpoint \
        /tmp/complete_cv/checkpoints/checkpoint_0 --project_dir /tmp/complete_cv
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator, Model
from accelerate_tpu.utils import ProjectConfiguration, set_seed
from cv_example import NUM_CLASSES, ConvNet, LoaderSpec, build_dataset


def training_function(args):
    set_seed(args.seed)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        log_with="json" if args.with_tracking else None,
        project_config=ProjectConfiguration(
            project_dir=args.project_dir, automatic_checkpoint_naming=True, total_limit=3
        ),
    )
    if args.with_tracking:
        accelerator.init_trackers("complete_cv_example", config=vars(args))

    module = ConvNet()
    train_ds = build_dataset(1024, seed=0)
    eval_ds = build_dataset(256, seed=1)
    sample = train_ds[0]
    model = Model.from_flax(module, jax.random.key(args.seed), sample["images"][None])
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr, weight_decay=1e-4),
        LoaderSpec(train_ds, args.batch_size),
        LoaderSpec(eval_ds, args.batch_size, shuffle=False),
    )

    def loss_fn(params, batch):
        logits = module.apply({"params": params}, batch["images"])
        return optax.softmax_cross_entropy(
            logits, jax.nn.one_hot(batch["labels"], NUM_CLASSES)
        ).mean()

    step_fn = accelerator.prepare_train_step(loss_fn, max_grad_norm=1.0)

    starting_epoch = 0
    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)
        starting_epoch = int(np.asarray(accelerator.train_state.step)) // len(train_dl)
        accelerator.print(f"Resumed from {args.resume_from_checkpoint} at epoch {starting_epoch}")

    def _evaluate():
        correct = total = 0
        for batch in eval_dl:
            preds = jnp.argmax(model(batch["images"]), -1)
            g = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct += int((np.asarray(g[0]) == np.asarray(g[1])).sum())
            total += len(np.asarray(g[0]))
        return correct / max(total, 1)

    state = accelerator.train_state
    acc_val = _evaluate() if starting_epoch >= args.epochs else 0.0
    for epoch in range(starting_epoch, args.epochs):
        for step, batch in enumerate(train_dl):
            state, metrics = step_fn(state, batch)
            if args.checkpointing_steps.isdigit() and (step + 1) % int(args.checkpointing_steps) == 0:
                accelerator.save_state()
        jax.block_until_ready(state.params)  # drain before eval (CPU-mesh guard)
        if args.checkpointing_steps == "epoch":
            accelerator.save_state()

        acc_val = _evaluate()
        accelerator.print(f"epoch {epoch}: accuracy {acc_val:.3f}")
        if args.with_tracking:
            accelerator.log({"accuracy": acc_val, "loss": float(metrics["loss"])}, step=epoch)

    accelerator.end_training()
    return acc_val


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default=None, choices=[None, "no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--checkpointing_steps", type=str, default="epoch")
    parser.add_argument("--resume_from_checkpoint", type=str, default=None)
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--project_dir", type=str, default="/tmp/accelerate_tpu_complete_cv")
    args = parser.parse_args()
    acc = training_function(args)
    print(f"final_accuracy={acc:.3f}")


if __name__ == "__main__":
    main()
