"""Crash-durable request journal (journal.py) + ServingEngine.recover().

Two layers:

- RequestJournal internals: the checksummed line codec, torn-tail
  truncation, corrupt-line skip-with-count, segment rotation, compaction
  (terminal rows survive, working records of finished requests retire,
  unfinished requests pass through verbatim), the fsync policy knobs, and
  the chaos torn_write hooks at journal_append / journal_compact.
- Engine integration: submit() journaling + client_request_id idempotency
  dedupe, exactly-once crash-restart recovery (cached terminal rows never
  re-executed, in-flight requests replayed bit-equal without spending the
  retry budget), monotonic deadline re-anchoring across the restart, and
  the attempt/recovered poll-row fields.

All CPU-only, tier-1 fast. The full-stack crash (a REAL os._exit mid-trace
plus supervisor relaunch) lives in `make gameday-smoke`
(test_utils/scripts/gameday_smoke.py); here the "crash" is an engine simply
abandoned without close() — same on-disk state, no subprocess.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import (
    FaultInjector,
    JournalAdoptionError,
    Model,
    RequestJournal,
    ServingConfig,
    ServingEngine,
)
from accelerate_tpu.journal import JOURNAL_FSYNC_POLICIES, _decode, _encode
from accelerate_tpu.utils import set_seed


@pytest.fixture(scope="module")
def llama():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    probe = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 8),
                                              dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), probe)
    return cfg, model


def _prompts(cfg, lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, (n,), dtype=np.int32)
            for n in lengths]


def _drain(engine, guard=5000):
    results = {}
    ticks = 0
    while engine.pending:
        engine.tick()
        for r in engine.poll():
            results[r["id"]] = r
        ticks += 1
        assert ticks < guard, "drain guard tripped"
    for r in engine.poll():
        results[r["id"]] = r
    return results


# ---------------------------------------------------------------------------
# RequestJournal internals
# ---------------------------------------------------------------------------


def test_codec_roundtrip_and_corruption():
    rec = {"t": "admit", "rid": 3, "tokens": [1, 2, 3]}
    line = _encode(rec)
    assert line.endswith("\n")
    assert _decode(line.rstrip("\n")) == rec
    # Any byte flip fails the crc.
    assert _decode(line.rstrip("\n").replace("3", "4", 1)) is None
    assert _decode("nonsense") is None
    assert _decode("deadbeef not-json") is None


def test_append_replay_roundtrip(tmp_path):
    j = RequestJournal(str(tmp_path), fsync="os")
    recs = [{"t": "admit", "rid": i, "tokens": [i]} for i in range(5)]
    for r in recs:
        j.append(r)
    j.close()  # seals the active segment
    j2 = RequestJournal(str(tmp_path))
    out, scan = j2.replay()
    assert out == recs
    assert scan["records"] == 5 and scan["segments"] == 1
    assert scan["torn_tails"] == 0 and scan["corrupt_skipped"] == 0
    # New appends land in a FRESH segment index — no collision.
    j2.append({"t": "admit", "rid": 9})
    j2.close()
    out2, scan2 = RequestJournal(str(tmp_path)).replay()
    assert len(out2) == 6 and scan2["segments"] == 2


def test_torn_tail_truncated_and_repaired(tmp_path):
    j = RequestJournal(str(tmp_path), fsync="os")
    j.append({"t": "admit", "rid": 0})
    j.append({"t": "admit", "rid": 1})
    j.close()
    # Simulate the crash-interrupted write: a partial final line.
    path = [p for _, p in j._segments()][0]
    with open(path, "a", encoding="utf-8") as f:
        f.write(_encode({"t": "admit", "rid": 2})[:17])  # no newline
    j2 = RequestJournal(str(tmp_path))
    out, scan = j2.replay()
    assert [r["rid"] for r in out] == [0, 1]
    assert scan["torn_tails"] == 1 and scan["corrupt_skipped"] == 0
    # replay() repaired the file in place: clean on the next read.
    out2, scan2 = RequestJournal(str(tmp_path)).replay()
    assert [r["rid"] for r in out2] == [0, 1] and scan2["torn_tails"] == 0


def test_corrupt_line_skipped_with_count(tmp_path):
    j = RequestJournal(str(tmp_path), fsync="os")
    for i in range(3):
        j.append({"t": "admit", "rid": i})
    j.close()
    path = [p for _, p in j._segments()][0]
    lines = open(path, encoding="utf-8").read().splitlines(keepends=True)
    lines[1] = "0badc0de " + lines[1].split(" ", 1)[1]  # break the middle crc
    with open(path, "w", encoding="utf-8") as f:
        f.writelines(lines)
    out, scan = RequestJournal(str(tmp_path)).replay()
    assert [r["rid"] for r in out] == [0, 2]  # the neighbors survive
    assert scan["corrupt_skipped"] == 1 and scan["torn_tails"] == 0


def test_fsync_policy_knobs(tmp_path):
    with pytest.raises(ValueError):
        RequestJournal(str(tmp_path / "x"), fsync="sometimes")
    with pytest.raises(ValueError):
        RequestJournal(str(tmp_path / "x"), segment_records=0)
    assert JOURNAL_FSYNC_POLICIES == ("every_record", "every_tick", "os")

    j = RequestJournal(str(tmp_path / "rec"), fsync="every_record")
    j.append({"t": "admit", "rid": 0})
    j.append({"t": "admit", "rid": 1})
    assert j.stats()["syncs"] == 2  # one fsync per append
    j.tick_flush()
    assert j.stats()["syncs"] == 2  # nothing buffered

    j = RequestJournal(str(tmp_path / "tick"), fsync="every_tick")
    j.append({"t": "admit", "rid": 0})
    j.append({"t": "admit", "rid": 1})
    assert j.stats()["syncs"] == 0  # buffered
    j.tick_flush()
    assert j.stats()["syncs"] == 1  # one fsync per tick
    j.tick_flush()
    assert j.stats()["syncs"] == 1  # not dirty: no-op

    j = RequestJournal(str(tmp_path / "os"), fsync="os")
    j.append({"t": "admit", "rid": 0})
    j.tick_flush()
    assert j.stats()["syncs"] == 0  # flush to page cache, never fsync
    # The data still reached the OS: another process/object can read it.
    out, _ = RequestJournal(str(tmp_path / "os")).replay()
    assert out == [{"t": "admit", "rid": 0}]


def test_rotation_and_compaction_preserve_unfinished(tmp_path):
    # segment_records=4 forces rotation (+ compaction) mid-stream.
    j = RequestJournal(str(tmp_path), fsync="os", segment_records=4)
    # rid 0 finishes; rid 1 stays in flight.
    j.append({"t": "admit", "rid": 0, "cid": "a", "tokens": [1]})
    j.append({"t": "admit", "rid": 1, "cid": "b", "tokens": [2]})
    j.append({"t": "bind", "rid": 0, "weights_version": 0})
    j.append({"t": "bind", "rid": 1, "weights_version": 0})
    j.append({"t": "progress", "tick": 0, "toks": {"0": [7], "1": [8]}})
    j.append({"t": "terminal", "rid": 0, "cid": "a", "status": "ok",
              "row": [1, 7]})
    j.append({"t": "admit", "rid": 2, "tokens": [3]})
    j.append({"t": "admit", "rid": 3, "tokens": [4]})  # triggers 2nd seal
    j.close()
    st = j.stats()
    assert st["rotations"] >= 2 and st["compactions"] >= 1
    assert st["records_retired"] > 0
    out, _ = RequestJournal(str(tmp_path)).replay()
    by_type = {}
    for r in out:
        by_type.setdefault(r["t"], []).append(r)
    # rid 0 retired: its admit/bind gone, its TERMINAL row kept (dedupe +
    # cached replies must survive compaction).
    assert sorted(r["rid"] for r in by_type["admit"]) == [1, 2, 3]
    assert [r["rid"] for r in by_type["bind"]] == [1]
    assert [r["rid"] for r in by_type["terminal"]] == [0]
    assert by_type["terminal"][0]["row"] == [1, 7]
    # The progress record dropped only the retired rid's tokens.
    assert by_type["progress"][0]["toks"] == {"1": [8]}
    assert j.stats()["pending"] == 3


def test_chaos_torn_append_rewrites_record(tmp_path):
    chaos = FaultInjector(seed=1, schedule=[
        {"point": "journal_append", "kind": "torn_write", "tick": 0,
         "unit": 1}])
    j = RequestJournal(str(tmp_path), fsync="os", chaos=chaos)
    j.append({"t": "admit", "rid": 0}, unit=0)
    j.append({"t": "admit", "rid": 1}, unit=1)  # torn, then re-written whole
    j.append({"t": "admit", "rid": 2}, unit=2)
    j.close()
    assert j.stats()["torn_writes"] == 1
    out, scan = RequestJournal(str(tmp_path)).replay()
    # Durability holds — every record replays — and the garbage fragment
    # exercised the checksum-skip path.
    assert [r["rid"] for r in out] == [0, 1, 2]
    assert scan["corrupt_skipped"] == 1


def test_chaos_torn_compact_aborts_cleanly(tmp_path):
    chaos = FaultInjector(seed=1, schedule=[
        {"point": "journal_compact", "kind": "torn_write", "tick": 0}])
    j = RequestJournal(str(tmp_path), fsync="os", segment_records=3,
                       chaos=chaos)
    j.append({"t": "admit", "rid": 0, "tokens": [1]})
    j.append({"t": "terminal", "rid": 0, "status": "ok", "row": [1]})
    j.append({"t": "admit", "rid": 1, "tokens": [2]})  # seal -> compact(torn)
    j.close()
    st = j.stats()
    assert st["compact_aborts"] == 1 and st["compactions"] == 0
    assert not os.path.exists(os.path.join(str(tmp_path), "compact.jsonl.tmp"))
    # The sealed segments are untouched: everything still replays.
    out, _ = RequestJournal(str(tmp_path)).replay()
    assert [r["rid"] for r in out] == [0, 0, 1]
    # A later compaction (no fault scheduled) succeeds over the same dir.
    j2 = RequestJournal(str(tmp_path))
    j2.replay()
    assert j2.compact() > 0


# ---------------------------------------------------------------------------
# Cross-process adoption (PR 18): exactly one party drains a dead WAL
# ---------------------------------------------------------------------------


def test_adoption_sentinel_refuses_double_adoption(tmp_path):
    """The double-adoption refusal regression: a recovering fleet router
    and a restarting supervisor racing for the same dead engine's journal
    must resolve to exactly ONE adopter — double adoption is double
    execution."""
    d = str(tmp_path)
    j1 = RequestJournal.adopt(d, "fleet-router:tick=3:cell=cell0")
    assert j1.adopted
    with pytest.raises(JournalAdoptionError, match="already adopted"):
        RequestJournal.adopt(d, "supervisor:pid=999")
    # The sentinel names the holder for the loser's error path.
    assert RequestJournal(d).adoption_holder()["owner"].startswith(
        "fleet-router")
    # The sentinel is invisible to segment scans and replay.
    j1.append({"t": "admit", "rid": 0})
    j1.close()  # close releases the claim
    assert RequestJournal(d).adoption_holder() is None
    out, scan = RequestJournal(d).replay()
    assert [r["rid"] for r in out] == [0] and scan["segments"] == 1
    # Released: the next adopter wins; force= evicts a stale claim.
    j2 = RequestJournal.adopt(d, "supervisor:pid=999")
    j3 = RequestJournal.adopt(d, "forced", force=True)
    assert j3.adopted
    j2.release_adoption()  # holder already evicted: a no-op either way
    j3.release_adoption()


def test_recover_over_foreign_dir_takes_the_adoption_lock(llama, tmp_path):
    """``recover(journal_dir=)`` on a dir some DEAD engine owned claims the
    sentinel: a second engine trying the same dir refuses, and a restart
    over its own configured dir refuses while a router holds the claim."""
    cfg, model = llama
    wal = str(tmp_path / "wal")
    mk = lambda **kw: ServingConfig(  # noqa: E731
        n_slots=2, max_len=32, prefill_chunks=[4, 8], **kw)
    (p,) = _prompts(cfg, [5])
    e1 = ServingEngine(model, mk(journal_dir=wal))
    rid = e1.submit(p, max_new_tokens=3, client_request_id="req-0")
    e1.journal.tick_flush()
    del e1  # dead: unsealed .open segment, no sentinel

    # The router-style takeover: a journal-less engine adopts the dir.
    e2 = ServingEngine(model, mk())
    assert e2.recover(journal_dir=wal)["recovered_inflight"] == 1
    assert e2.journal.adopted
    # A second adopter — engine or raw journal — refuses while it's held.
    e3 = ServingEngine(model, mk())
    with pytest.raises(JournalAdoptionError, match="already adopted"):
        e3.recover(journal_dir=wal)
    # A restarting supervisor's engine over its OWN configured dir also
    # refuses: these requests are being drained elsewhere.
    e4 = ServingEngine(model, mk(journal_dir=wal))
    with pytest.raises(JournalAdoptionError, match="drained elsewhere"):
        e4.recover()
    # The adopter drains the replay bit-for-bit as usual...
    rows = _drain(e2)
    assert rows[rid]["status"] == "ok" and rows[rid]["recovered"] is True
    # ...and close() releases the claim for the next owner.
    e2.close()
    assert RequestJournal(wal).adoption_holder() is None


# ---------------------------------------------------------------------------
# Compaction racing a crash (PR 18): only the happy path was pinned before
# ---------------------------------------------------------------------------


def test_compaction_commit_crash_duplicates_replay_exactly_once(
        llama, tmp_path, monkeypatch):
    """A crash BETWEEN compaction's two commit steps (the merged segment
    has replaced sealed[0], the stale sealed[1:] not yet unlinked) leaves
    duplicate records on disk — journal.py documents them as idempotently
    re-read. Pin that: recovery over the duplicated WAL is still
    exactly-once, bit-equal."""
    cfg, model = llama
    wal = str(tmp_path / "wal")
    mk = lambda: ServingConfig(  # noqa: E731
        n_slots=2, max_len=32, prefill_chunks=[4, 8],
        journal_dir=wal, journal_segment_records=4)
    prompts = _prompts(cfg, [5, 7, 6, 8])

    real_remove = os.remove

    def crashy_remove(path):
        # The unlink step of compaction "crashes": stale sealed segments
        # stay on disk. compact() treats the OSError as best-effort.
        if os.path.basename(path).startswith("wal_") and wal in path:
            raise OSError("injected crash between commit steps")
        real_remove(path)

    monkeypatch.setattr(os, "remove", crashy_remove)
    e1 = ServingEngine(model, mk())
    ref = {}
    for i, p in enumerate(prompts[:3]):
        ref[i] = e1.submit(p, max_new_tokens=4, client_request_id=f"req-{i}")
    done = _drain(e1)
    assert e1.stats()["journal"]["compactions"] >= 1
    rid_inflight = e1.submit(prompts[3], max_new_tokens=4,
                             client_request_id="req-3")
    e1.journal.tick_flush()
    del e1  # crash: duplicates + an in-flight admit on disk

    # The duplicates are really there: more admit records than rids.
    recs, _ = RequestJournal(wal).replay()
    admit_rids = [r["rid"] for r in recs if r["t"] == "admit"]
    assert len(admit_rids) > len(set(admit_rids))

    e2 = ServingEngine(model, mk())
    summary = e2.recover()
    # Exactly-once despite the duplicated records: each terminal re-emits
    # ONE cached row, the in-flight request replays ONCE.
    assert summary["recovered_terminal"] == 3
    assert summary["recovered_inflight"] == 1
    rows = {r["id"]: r for r in e2.poll()}
    assert sorted(rows) == sorted(ref.values())
    for i in (0, 1, 2):
        np.testing.assert_array_equal(rows[ref[i]]["tokens"],
                                      done[ref[i]]["tokens"])
    rows.update(_drain(e2))
    assert rows[rid_inflight]["status"] == "ok"
    assert e2.stats()["requests_completed"] == 1  # only the replay ran


def test_segment_sealed_mid_compaction_replays_exactly_once(llama, tmp_path):
    """The other side of the race: segments keep SEALING while every
    compaction pass aborts mid-write (chaos torn_write at journal_compact),
    then the process dies. The accumulated sealed-but-never-compacted
    history must still recover exactly-once."""
    cfg, model = llama
    wal = str(tmp_path / "wal")
    chaos = FaultInjector(seed=2, rates={"journal_compact": {"torn_write": 1.0}})
    mk = lambda ch: ServingConfig(  # noqa: E731
        n_slots=2, max_len=32, prefill_chunks=[4, 8],
        journal_dir=wal, journal_segment_records=4)
    e1 = ServingEngine(model, mk(chaos), chaos=chaos)
    prompts = _prompts(cfg, [5, 7, 6, 8])
    ref = {}
    for i, p in enumerate(prompts[:3]):
        ref[i] = e1.submit(p, max_new_tokens=4, client_request_id=f"req-{i}")
    done = _drain(e1)
    js = e1.stats()["journal"]
    assert js["compact_aborts"] >= 1 and js["compactions"] == 0
    assert js["rotations"] >= 2  # segments sealed while compaction failed
    rid_inflight = e1.submit(prompts[3], max_new_tokens=4,
                             client_request_id="req-3")
    e1.journal.tick_flush()
    del e1  # crash mid-flight, un-compacted multi-segment history behind

    e2 = ServingEngine(model, mk(None))
    summary = e2.recover()
    assert summary["recovered_terminal"] == 3
    assert summary["recovered_inflight"] == 1
    assert summary["segments"] >= 3
    rows = {r["id"]: r for r in e2.poll()}
    for i in range(3):
        np.testing.assert_array_equal(rows[ref[i]]["tokens"],
                                      done[ref[i]]["tokens"])
    rows.update(_drain(e2))
    assert rows[rid_inflight]["status"] == "ok"
    assert e2.stats()["requests_completed"] == 1
    # The un-compacted history compacts fine under the new owner.
    e2.journal.replay()
    assert e2.journal.compact() > 0


# ---------------------------------------------------------------------------
# ServingEngine integration
# ---------------------------------------------------------------------------


def test_submit_dedupes_on_client_request_id(llama, tmp_path):
    cfg, model = llama
    engine = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8],
                             journal_dir=str(tmp_path / "wal")))
    (p,) = _prompts(cfg, [5])
    rid = engine.submit(p, max_new_tokens=3, client_request_id="req-0")
    assert engine.submit(p, max_new_tokens=3,
                         client_request_id="req-0") == rid  # queued: same id
    res = _drain(engine)
    assert len(res) == 1 and res[rid]["status"] == "ok"
    # Finished: the duplicate re-emits the CACHED row, nothing re-runs.
    completed = engine.stats()["requests_completed"]
    assert engine.submit(p, max_new_tokens=3,
                         client_request_id="req-0") == rid
    rows = engine.poll()
    assert len(rows) == 1 and rows[0]["id"] == rid
    np.testing.assert_array_equal(rows[0]["tokens"], res[rid]["tokens"])
    assert engine.stats()["requests_completed"] == completed
    assert engine.stats()["journal"]["deduped"] == 2


def test_recover_exactly_once_and_bit_equal(llama, tmp_path):
    cfg, model = llama
    prompts = _prompts(cfg, [5, 9, 7])
    mk = lambda sub: ServingConfig(  # noqa: E731
        n_slots=2, max_len=32, prefill_chunks=[4, 8],
        journal_dir=str(tmp_path / sub))

    # Reference: the same trace, never interrupted.
    ref_engine = ServingEngine(model, mk("ref"))
    ref = {}
    for i, p in enumerate(prompts):
        ref[i] = ref_engine.submit(p, max_new_tokens=4,
                                   client_request_id=f"req-{i}")
    ref_rows = _drain(ref_engine)

    # "Crashing" run: finish req-0, leave req-1/req-2 queued, then abandon
    # the engine without close() — exactly the state a process death leaves.
    e1 = ServingEngine(model, mk("wal"))
    r0 = e1.submit(prompts[0], max_new_tokens=4, client_request_id="req-0")
    ticks = 0
    done = {}
    while r0 not in done:
        e1.tick()
        done.update({r["id"]: r for r in e1.poll()})
        ticks += 1
        assert ticks < 500
    e1.submit(prompts[1], max_new_tokens=4, client_request_id="req-1")
    e1.submit(prompts[2], max_new_tokens=4, client_request_id="req-2")
    e1.journal.tick_flush()
    del e1  # no close(): the .open segment's torn state is the test

    e2 = ServingEngine(model, mk("wal"))
    summary = e2.recover()
    assert summary["recovered_terminal"] == 1
    assert summary["recovered_inflight"] == 2
    # The cached terminal row surfaces through poll(), flagged recovered,
    # and was NOT re-executed.
    rows = {r["id"]: r for r in e2.poll()}
    assert rows[r0]["status"] == "ok" and rows[r0]["recovered"] is True
    np.testing.assert_array_equal(rows[r0]["tokens"], done[r0]["tokens"])
    assert e2.stats()["requests_completed"] == 0
    # A duplicate submit for the completed request dedupes post-crash.
    assert e2.submit(prompts[0], max_new_tokens=4,
                     client_request_id="req-0") == r0
    assert e2.stats()["journal"]["deduped"] == 1
    # The in-flight requests replay BIT-EQUAL to the uninterrupted
    # reference, without spending the retry budget.
    rows.update(_drain(e2))
    for i in (1, 2):
        rec = rows[ref[i]]
        np.testing.assert_array_equal(rec["tokens"], ref_rows[ref[i]]["tokens"])
        assert rec["status"] == "ok"
        assert rec["recovered"] is True and rec["attempt"] == 2
    assert e2.stats()["requests_completed"] == 2  # only the replays ran
    # One decode executable, zero steady-state recompiles across recovery.
    assert e2.stats()["decode_executables"] == 1
    assert e2.stats()["steady_recompiles"] == 0
    # Fresh ids never collide with journaled ones.
    assert e2.submit(prompts[0], max_new_tokens=2) > max(ref.values())


def test_recover_replays_speculative_requests_bit_equal(llama, tmp_path):
    """Crash-restart with speculation on: recovered in-flight requests
    replay bit-equal to a NON-speculative reference (exact-distribution
    verification holds across the journal replay path too), and the
    terminal rows carry the drafted/accepted provenance."""
    cfg, model = llama
    prompts = _prompts(cfg, [5, 9, 7])

    ref_engine = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=48, prefill_chunks=[4, 8]))
    ref = {}
    for i, p in enumerate(prompts):
        ref[i] = ref_engine.submit(p, max_new_tokens=8)
    ref_rows = _drain(ref_engine)

    mk = lambda: ServingConfig(  # noqa: E731
        n_slots=2, max_len=48, prefill_chunks=[4, 8],
        speculate_k=2, speculate_ngram=8,
        journal_dir=str(tmp_path / "wal"))
    e1 = ServingEngine(model, mk())
    r0 = e1.submit(prompts[0], max_new_tokens=8, client_request_id="req-0")
    done = {}
    ticks = 0
    while r0 not in done:
        e1.tick()
        done.update({r["id"]: r for r in e1.poll()})
        ticks += 1
        assert ticks < 500
    r1 = e1.submit(prompts[1], max_new_tokens=8, client_request_id="req-1")
    r2 = e1.submit(prompts[2], max_new_tokens=8, client_request_id="req-2")
    e1.journal.tick_flush()
    del e1  # abandoned without close(): the crash

    e2 = ServingEngine(model, mk())
    summary = e2.recover()
    assert summary["recovered_terminal"] == 1
    assert summary["recovered_inflight"] == 2
    rows = {r["id"]: r for r in e2.poll()}
    np.testing.assert_array_equal(rows[r0]["tokens"], done[r0]["tokens"])
    rows.update(_drain(e2))
    for i, rid in ((1, r1), (2, r2)):
        rec = rows[rid]
        np.testing.assert_array_equal(rec["tokens"],
                                      ref_rows[ref[i]]["tokens"])
        assert rec["status"] == "ok" and rec["recovered"] is True
        assert rec["drafted"] > 0 and rec["drafted"] >= rec["accepted"]
    spec = e2.stats()["speculation"]
    assert spec["k"] == 2 and spec["drafted"] > 0
    assert e2.stats()["decode_executables"] == 1
    assert e2.stats()["steady_recompiles"] == 0


def test_recover_requires_a_journal(llama):
    cfg, model = llama
    engine = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8]))
    with pytest.raises(ValueError, match="needs a journal"):
        engine.recover()


def test_recover_deadline_rebased_on_monotonic_clock(llama, tmp_path):
    """Satellite regression: remaining deadline budget must survive the
    restart as a MONOTONIC delta — elapsed pre-crash runtime is charged,
    but absolute wall time never enters the journal, so a wall-clock step
    during the outage cannot expire (or extend) recovered requests."""
    cfg, model = llama
    wal = str(tmp_path / "wal")
    j = RequestJournal(wal, fsync="os")
    # Hand-written history in the dead process's own monotonic epoch:
    # admitted at t=1000 with a 100s budget, last journal activity at
    # t=1030 -> 30s were spent, 70s remain after however long the outage.
    j.append({"t": "admit", "rid": 0, "cid": None, "tokens": [1, 2, 3],
              "budget": 2, "rng": [0, 0], "deadline_s": 100.0,
              "t_mono": 1000.0, "weights_version": 0})
    j.append({"t": "progress", "tick": 5, "toks": {}, "t_mono": 1030.0})
    j.close()
    engine = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8],
                             journal_dir=wal))
    import time as _time

    engine.recover()
    (req,) = list(engine._queue)
    remaining = req.deadline - _time.perf_counter()
    assert 65.0 < remaining <= 70.0
    # An over-spent budget clamps to "due now", never negative chaos.
    j2 = RequestJournal(str(tmp_path / "wal2"), fsync="os")
    j2.append({"t": "admit", "rid": 0, "cid": None, "tokens": [1],
               "budget": 2, "rng": [0, 0], "deadline_s": 10.0,
               "t_mono": 1000.0, "weights_version": 0})
    j2.append({"t": "progress", "tick": 9, "toks": {}, "t_mono": 1500.0})
    j2.close()
    e2 = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8],
                             journal_dir=str(tmp_path / "wal2")))
    e2.recover()
    (req2,) = list(e2._queue)
    assert req2.deadline - _time.perf_counter() <= 0.5


def test_repeated_crashes_accumulate_attempts(llama, tmp_path):
    cfg, model = llama
    mk = lambda: ServingConfig(  # noqa: E731
        n_slots=2, max_len=32, prefill_chunks=[4, 8],
        journal_dir=str(tmp_path / "wal"))
    (p,) = _prompts(cfg, [5])
    e1 = ServingEngine(model, mk())
    rid = e1.submit(p, max_new_tokens=3, client_request_id="req-0")
    e1.journal.tick_flush()
    del e1
    e2 = ServingEngine(model, mk())
    assert e2.recover()["recovered_inflight"] == 1
    del e2  # second crash before the replay ran
    e3 = ServingEngine(model, mk())
    assert e3.recover()["recovered_inflight"] == 1
    rows = _drain(e3)
    # attempt = 1 + retries(0) + recoveries(2); the retry budget untouched.
    assert rows[rid]["attempt"] == 3 and rows[rid]["recovered"] is True
    assert e3.stats()["faults"]["retries"] == 0


def test_engine_crash_chaos_flushes_and_exits(llama, tmp_path, monkeypatch):
    """The injected engine_crash dies through os._exit AFTER pushing the
    telemetry crash event + the injector's full log — and the draw sits
    after the journal's tick flush, so what the fsync policy promised
    durable IS on disk when the process dies."""
    import accelerate_tpu.serving as serving_mod

    cfg, model = llama

    class _Tel:
        def __init__(self):
            self.events = []
            self.closed = False

        def record_event(self, event, **fields):
            self.events.append((event, fields))

        def close(self):
            self.closed = True

    class _Exit(BaseException):
        pass

    codes = []

    def fake_exit(code):
        codes.append(code)
        raise _Exit()

    monkeypatch.setattr(serving_mod.os, "_exit", fake_exit)
    tel = _Tel()
    chaos = FaultInjector(seed=1, schedule=[
        {"point": "engine_crash", "kind": "crash", "tick": 0}])
    engine = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8],
                             journal_dir=str(tmp_path / "wal")),
        telemetry=tel, chaos=chaos)
    (p,) = _prompts(cfg, [5])
    engine.submit(p, max_new_tokens=3, client_request_id="req-0")
    with pytest.raises(_Exit):
        engine.tick()
    assert codes == [78]  # SERVING_CRASH_EXIT_CODE
    names = [e for e, _ in tel.events]
    assert "serving_engine_crash" in names and "chaos_injected_log" in names
    assert tel.closed
    # The admission was durable: a fresh engine recovers the request.
    e2 = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8],
                             journal_dir=str(tmp_path / "wal")))
    assert e2.recover()["recovered_inflight"] == 1
