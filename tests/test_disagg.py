"""Disaggregated serving (disagg.py + planner slice sizing): planner split
math, KV-page handoff bit-equality, router parity with the colocated engine
and with generate(), the one-executable decode steady state across slot AND
lane reuse, the sharded-decode opt-in's flat census, handoff byte/latency
accounting, warmup/reset_metrics, and the Accelerator wiring (off by
default). All CPU-only on the forced 8-device host platform, tier-1 fast."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import (
    DisaggConfig,
    DisaggServingEngine,
    Model,
    ServingConfig,
    ServingEngine,
    generate,
    replay_trace,
)
from accelerate_tpu.planner import (
    BandwidthTable,
    PlannerError,
    kv_bytes_per_token,
    plan_disagg_slices,
)
from accelerate_tpu.utils import set_seed


@pytest.fixture(scope="module")
def llama():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    probe = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 8),
                                              dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), probe)
    return cfg, model


def _prompts(cfg, lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, (n,), dtype=np.int32)
            for n in lengths]


# ---------------------------------------------------------------------------
# Planner slice sizing (pure math)
# ---------------------------------------------------------------------------


def test_plan_disagg_slices_balances_flop_ratio():
    # ratio 1 on 8 devices: 4/4 is optimal (makespan 0.25 both sides).
    plan = plan_disagg_slices(8, prefill_decode_flop_ratio=1.0)
    assert (plan.n_prefill, plan.n_decode) == (4, 4)
    assert plan.bottleneck == "balanced"
    # Prefill-heavy traffic pulls devices into the prefill slice.
    heavy = plan_disagg_slices(8, prefill_decode_flop_ratio=3.0)
    assert heavy.n_prefill == 6
    # Decode-heavy traffic keeps the prefill slice minimal.
    light = plan_disagg_slices(8, prefill_decode_flop_ratio=1.0 / 7.0)
    assert light.n_prefill == 1 and light.n_decode == 7
    assert light.bottleneck == "balanced"  # 1/7 vs 1/7 exactly


def test_plan_disagg_slices_ties_prefer_decode():
    # On 2 devices every ratio splits 1/1; on 4 with ratio 1, 2/2 wins, but a
    # ratio where p=2 and p=3 tie must keep the SMALLER prefill slice.
    plan = plan_disagg_slices(4, prefill_decode_flop_ratio=1.0)
    assert (plan.n_prefill, plan.n_decode) == (2, 2)
    tie = plan_disagg_slices(3, prefill_decode_flop_ratio=0.5)
    assert tie.n_prefill == 1  # makespan(1)=0.5 == makespan(2)=0.5 -> p=1


def test_plan_disagg_slices_pin_and_errors():
    plan = plan_disagg_slices(8, prefill_decode_flop_ratio=1.0, n_prefill=6)
    assert (plan.n_prefill, plan.n_decode) == (6, 2)
    # The pin is clamped into [1, n-1].
    assert plan_disagg_slices(4, prefill_decode_flop_ratio=1.0,
                              n_prefill=99).n_prefill == 3
    with pytest.raises(PlannerError):
        plan_disagg_slices(1, prefill_decode_flop_ratio=1.0)
    with pytest.raises(PlannerError):
        plan_disagg_slices(8, prefill_decode_flop_ratio=0.0)


def test_plan_disagg_prices_handoff(llama):
    cfg, _ = llama
    kvb = kv_bytes_per_token(cfg, dtype=np.float32)
    # 2 (K and V) * layers * kv_heads * head_dim * itemsize.
    from accelerate_tpu.generation import _cache_dims

    layers, kv_heads, head_dim, _ = _cache_dims(cfg)
    assert kvb == 2 * layers * kv_heads * head_dim * 4
    bw = BandwidthTable()
    plan = plan_disagg_slices(8, prefill_decode_flop_ratio=2.0, bw=bw,
                              kv_bytes_per_token=kvb)
    assert plan.handoff_gbps == pytest.approx(bw.handoff_gbps(8), rel=1e-6)
    assert plan.handoff_s_per_ktoken == pytest.approx(
        1000.0 * kvb / (bw.handoff_gbps(8) * 1e9), rel=1e-4)
    d = plan.to_dict()
    assert list(d) == sorted(d)  # deterministic artifact ordering


def test_plan_disagg_prices_int8_pages_at_half_bf16(llama):
    # Quantized KV pages move ~half the bytes of bf16 pages (int8 payload
    # plus one f32 absmax scale per page), and the slice plan's handoff
    # seconds must reprice accordingly. The method on BandwidthTable and
    # the module function are the same pricing.
    cfg, _ = llama
    bw = BandwidthTable()
    kvb_bf16 = bw.kv_bytes_per_token(cfg, dtype=np.dtype("bfloat16"))
    kvb_int8 = bw.kv_bytes_per_token(cfg, dtype=np.int8)
    assert kvb_bf16 == kv_bytes_per_token(cfg, dtype=np.dtype("bfloat16"))
    assert kvb_int8 == kv_bytes_per_token(cfg, dtype=np.int8)
    # "~half": exactly (head_dim + 4) / (2 * head_dim) — the +4-byte f32
    # absmax scale per page keeps it just over 0.5.
    from accelerate_tpu.generation import _cache_dims

    _, _, head_dim, _ = _cache_dims(cfg)
    assert kvb_int8 / kvb_bf16 == (head_dim + 4) / (2 * head_dim)
    assert kvb_int8 / kvb_bf16 == pytest.approx(0.5, rel=0.15)
    assert kvb_int8 < kvb_bf16 < kv_bytes_per_token(cfg, dtype=np.float32)
    p16 = plan_disagg_slices(8, prefill_decode_flop_ratio=2.0, bw=bw,
                             kv_bytes_per_token=kvb_bf16)
    p8 = plan_disagg_slices(8, prefill_decode_flop_ratio=2.0, bw=bw,
                            kv_bytes_per_token=kvb_int8)
    assert p8.handoff_s_per_ktoken == pytest.approx(
        0.5 * p16.handoff_s_per_ktoken, rel=0.15)
    # No dtype override: the config's own dtype prices the link.
    assert bw.kv_bytes_per_token(cfg) == kv_bytes_per_token(cfg)


def test_disagg_config_validation():
    with pytest.raises(ValueError):
        DisaggConfig(n_prefill_lanes=0)
    with pytest.raises(ValueError):
        DisaggConfig(handoff_depth=0)
    with pytest.raises(ValueError):
        DisaggConfig(prefill_decode_flop_ratio=-1.0)
    with pytest.raises(ValueError):
        DisaggConfig(expected_prompt_tokens=0)
    with pytest.raises(ValueError):
        DisaggConfig(n_prefill_devices=0)
    with pytest.raises(ValueError):
        DisaggConfig(handoff_sample_every=0)


# ---------------------------------------------------------------------------
# Router correctness: bit-equality across the handoff
# ---------------------------------------------------------------------------


def _engines(model, **disagg_kw):
    sc = ServingConfig(n_slots=3, max_len=64, prefill_chunks=[4, 8])
    colo = ServingEngine(model, sc)
    dis = DisaggServingEngine(model, sc, disagg=DisaggConfig(**disagg_kw))
    return colo, dis


def test_transferred_pages_bit_equal_to_in_place(llama):
    """The core handoff invariant: after prefilling the same prompt, the
    decode-side cache slot holds byte-identical K/V pages to the colocated
    engine's in-place prefill — pad tail and all committed lengths
    included."""
    cfg, model = llama
    colo, dis = _engines(model, n_prefill_lanes=1)
    (prompt,) = _prompts(cfg, [13], seed=5)
    colo.run([prompt], max_new_tokens=1)
    dis.run([prompt], max_new_tokens=1)
    ck, dk = np.asarray(colo._cache.k), np.asarray(dis._cache.k)
    cv, dv = np.asarray(colo._cache.v), np.asarray(dis._cache.v)
    # Both engines granted slot ids from the same policy; compare the whole
    # committed region of the request's slot (slot allocation is LIFO from
    # the same free list, so the single request took the same slot).
    np.testing.assert_array_equal(
        np.asarray(colo._cache.length), np.asarray(dis._cache.length))
    n = int(np.asarray(colo._cache.length).max())
    slot = int(np.argmax(np.asarray(colo._cache.length)))
    np.testing.assert_array_equal(ck[:, slot, :n], dk[:, slot, :n])
    np.testing.assert_array_equal(cv[:, slot, :n], dv[:, slot, :n])


def test_router_bit_equal_greedy_two_waves(llama):
    """Router output == colocated engine == batch-1 generate(), across two
    request waves through the same engines (slot AND lane reuse, donated
    buffers recycled mid-flight)."""
    cfg, model = llama
    colo, dis = _engines(model, n_prefill_lanes=2)
    for seed in (3, 11):  # second wave reuses every slot and lane
        prompts = _prompts(cfg, [3, 7, 12, 20, 5, 9], seed=seed)
        budgets = [6, 4, 8, 3, 5, 7]
        got_c = colo.run(prompts, max_new_tokens=budgets)
        got_d = dis.run(prompts, max_new_tokens=budgets)
        for prompt, budget, c, d in zip(prompts, budgets, got_c, got_d):
            np.testing.assert_array_equal(c, d)
            want = np.asarray(
                generate(model, prompt[None], max_new_tokens=budget))[0]
            np.testing.assert_array_equal(d, want)


def test_router_bit_equal_sampled(llama):
    """Sampled decoding: per-request PRNG streams survive the two-mesh split
    (the rng carry crosses with the final page's arm payload)."""
    cfg, model = llama
    sc = ServingConfig(n_slots=2, max_len=64, prefill_chunks=[4, 8],
                       temperature=0.8, top_k=20)
    colo = ServingEngine(model, sc)
    dis = DisaggServingEngine(model, sc, disagg=DisaggConfig(n_prefill_lanes=2))
    prompts = _prompts(cfg, [5, 11, 3, 17], seed=8)
    keys = [jax.random.key(40 + i) for i in range(4)]
    got_c = colo.run(prompts, max_new_tokens=6, rngs=keys)
    got_d = dis.run(prompts, max_new_tokens=6, rngs=keys)
    for c, d in zip(got_c, got_d):
        np.testing.assert_array_equal(c, d)


def test_decode_steady_state_one_executable(llama):
    """The zero-recompile invariant survives the split: the decode program's
    dispatch census stays at exactly 1 across waves on the default (fixed
    single-device) decode placement."""
    cfg, model = llama
    _, dis = _engines(model, n_prefill_lanes=2)
    for seed in (3, 11):
        dis.run(_prompts(cfg, [3, 12, 7, 20], seed=seed), max_new_tokens=5)
    s = dis.stats()
    assert s["decode_executables"] == 1
    assert s["steady_recompiles"] == 0
    execs = dis.executable_counts()
    # Data-plane programs are rung/placement-bounded, never per-request.
    assert execs["handoff_extract"] <= len(dis.ladder) * len(
        {l.device for l in dis._lanes})
    assert execs["slot_arm"] == 1


def test_shard_decode_slots_optin_flat_census(llama):
    """The opt-in slot-sharded decode placement keeps a FLAT dispatch census
    (pre-warmed at init — jax 0.4.37 holds two dispatch entries for one
    compiled typed-key program under a multi-device NamedSharding) and zero
    steady recompiles; outputs stay bit-equal to the colocated engine."""
    cfg, model = llama
    sc = ServingConfig(n_slots=4, max_len=64, prefill_chunks=[4, 8])
    colo = ServingEngine(model, sc)
    dis = DisaggServingEngine(
        model, sc,
        disagg=DisaggConfig(n_prefill_lanes=2, n_prefill_devices=4,
                            shard_decode_slots=True),
    )
    assert dis._decode_mesh is not None  # 4 slots over 4 decode devices
    prompts = _prompts(cfg, [3, 9, 14, 6], seed=4)
    got_c = colo.run(prompts, max_new_tokens=4)
    got_d = dis.run(prompts, max_new_tokens=4)
    for c, d in zip(got_c, got_d):
        np.testing.assert_array_equal(c, d)
    assert dis.stats()["steady_recompiles"] == 0


def test_shard_decode_slots_indivisible_falls_back(llama):
    cfg, model = llama
    sc = ServingConfig(n_slots=3, max_len=64, prefill_chunks=[4, 8])
    dis = DisaggServingEngine(
        model, sc,
        disagg=DisaggConfig(n_prefill_devices=4, shard_decode_slots=True),
    )
    assert dis._decode_mesh is None  # 3 slots % 4 devices -> single-device
    outs = dis.run(_prompts(cfg, [5, 8], seed=2), max_new_tokens=3)
    assert len(outs) == 2


def test_single_device_rejected(llama):
    cfg, model = llama
    with pytest.raises(ValueError, match="needs >= 2 devices"):
        DisaggServingEngine(model, ServingConfig(n_slots=2, max_len=32),
                            devices=[jax.devices()[0]])


# ---------------------------------------------------------------------------
# Handoff accounting + stats/telemetry
# ---------------------------------------------------------------------------


def test_handoff_byte_accounting(llama):
    """handoff_bytes is exactly the K+V page bytes the chunks committed:
    per chunk 2 * layers * chunk_size * kv_heads * head_dim * itemsize."""
    cfg, model = llama
    _, dis = _engines(model, n_prefill_lanes=1)
    prompts = _prompts(cfg, [13, 4], seed=6)  # chunks: [8,4,4(pad)] + [4]
    dis.run(prompts, max_new_tokens=2)
    d = dis.stats()["disagg"]
    kvb = kv_bytes_per_token(cfg, dtype=np.float32)
    from accelerate_tpu.serving import plan_chunks

    chunk_tokens = sum(
        size for p in prompts for size, _ in plan_chunks(len(p), dis.ladder))
    assert d["handoff_bytes"] == chunk_tokens * kvb
    assert d["handoff_transfers"] == sum(
        len(plan_chunks(len(p), dis.ladder)) for p in prompts)
    assert d["handoff_inserts"] == d["handoff_transfers"]
    assert d["handoff_final_flushes"] == len(prompts)


def test_disagg_stats_block(llama):
    cfg, model = llama
    _, dis = _engines(model, n_prefill_lanes=2, handoff_sample_every=2)
    dis.run(_prompts(cfg, [9, 13, 5], seed=7), max_new_tokens=4)
    s = dis.stats()
    d = s["disagg"]
    assert d["n_prefill_devices"] + d["n_decode_devices"] == len(jax.devices())
    assert d["slice_plan"]["n_prefill"] == d["n_prefill_devices"]
    assert d["handoff_lat_sampled"] >= 1
    assert d["handoff_lat_mean_s"] > 0
    assert d["measured_flop_ratio"] == pytest.approx(
        s["prompt_tokens_in"] / s["tokens_out"], rel=1e-5)


def test_warmup_and_reset_metrics(llama):
    """warmup() compiles every lane's full ladder and resets the counters:
    a measured run starts at zero with all programs already compiled."""
    cfg, model = llama
    _, dis = _engines(model, n_prefill_lanes=2)
    dis.warmup()
    s = dis.stats()
    assert s["requests_completed"] == 0 and s["ticks"] == 0
    assert s["disagg"]["handoff_transfers"] == 0
    lane_devs = {l.device for l in dis._lanes}
    assert dis.executable_counts()["prefill"] == len(dis.ladder) * len(lane_devs)
    # A post-warmup run never grows the decode census.
    dis.run(_prompts(cfg, [6, 10], seed=9), max_new_tokens=3)
    assert dis.stats()["steady_recompiles"] == 0
    assert dis.stats()["decode_executables"] == 1


def test_replay_trace_open_loop(llama):
    """replay_trace submits on the arrival clock and returns rows in input
    order — and the same trace is bit-stable across engines."""
    cfg, model = llama
    colo, dis = _engines(model, n_prefill_lanes=2)
    prompts = _prompts(cfg, [7, 3, 12], seed=10)
    arrivals = [0.0, 0.0, 0.005]
    rows_c, _ = replay_trace(colo, prompts, arrivals=arrivals,
                             max_new_tokens=4)
    rows_d, _ = replay_trace(dis, prompts, arrivals=arrivals,
                             max_new_tokens=4)
    for c, d in zip(rows_c, rows_d):
        np.testing.assert_array_equal(c, d)
    with pytest.raises(ValueError, match="arrivals"):
        replay_trace(colo, prompts, arrivals=[0.0], max_new_tokens=2)


# ---------------------------------------------------------------------------
# Accelerator wiring (off by default)
# ---------------------------------------------------------------------------


def _accelerator(tmp_path, handlers):
    import optax  # noqa: F401

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    set_seed(0)
    return Accelerator(project_dir=str(tmp_path), kwargs_handlers=handlers)


def test_accelerator_disagg_off_by_default(tmp_path, llama):
    cfg, model = llama
    sc = ServingConfig(n_slots=2, max_len=64)
    acc = _accelerator(tmp_path, [sc])
    assert acc.disagg_config is None
    engine = acc.build_serving_engine(model)
    assert not isinstance(engine, DisaggServingEngine)


def test_accelerator_builds_disagg_engine(tmp_path, llama):
    """DisaggConfig in kwargs_handlers upgrades build_serving_engine to the
    two-mesh router and streams the `disagg` block through telemetry."""
    import json
    import os

    from accelerate_tpu.utils import TelemetryKwargs

    cfg, model = llama
    sc = ServingConfig(n_slots=2, max_len=64, prefill_chunks=[4, 8])
    dc = DisaggConfig(n_prefill_lanes=1)
    acc = _accelerator(
        tmp_path,
        [sc, dc, TelemetryKwargs(straggler_probe_every=0, log_every=0)],
    )
    assert acc.disagg_config is dc
    engine = acc.build_serving_engine(model)
    assert isinstance(engine, DisaggServingEngine)
    engine.run(_prompts(cfg, [5, 9], seed=4), max_new_tokens=3)
    summary = acc.telemetry.summary()
    assert summary["serving"]["requests_completed"] == 2
    assert summary["disagg"]["handoff_transfers"] > 0
    acc.telemetry.close()
    report = os.path.join(str(tmp_path), "telemetry", "rank_0.jsonl")
    events = [json.loads(line) for line in open(report)]
    kinds = {e["event"] for e in events}
    assert "disagg_summary" in kinds


def test_accelerator_disagg_disabled_handler(tmp_path, llama):
    """enabled=False keeps the colocated engine even with the handler
    present — the one-flag rollback path."""
    cfg, model = llama
    sc = ServingConfig(n_slots=2, max_len=64)
    acc = _accelerator(tmp_path, [sc, DisaggConfig(enabled=False)])
    engine = acc.build_serving_engine(model)
    assert not isinstance(engine, DisaggServingEngine)


# ---------------------------------------------------------------------------
# Robustness surface (the full fault matrix lives in tests/test_chaos.py)
# ---------------------------------------------------------------------------


def test_lane_quarantine_survives_on_remaining_lane(llama):
    """Killing ONE of two prefill lanes quarantines it without degrading:
    the survivor carries the whole trace, rows stay bit-equal to generate(),
    and the decode census stays 1."""
    from accelerate_tpu import FaultInjector, generate

    cfg, model = llama
    chaos = FaultInjector(
        seed=3,
        schedule=[{"point": "lane_health", "kind": "dead_lane", "unit": 0}],
    )
    eng = DisaggServingEngine(
        model,
        ServingConfig(n_slots=4, max_len=64, prefill_chunks=[4, 8]),
        disagg=DisaggConfig(n_prefill_lanes=2),
        chaos=chaos,
    )
    prompts = _prompts(cfg, [3, 7, 12, 20, 5, 9])
    budgets = [6, 4, 8, 3, 5, 6]
    outs = eng.run(prompts, max_new_tokens=budgets)
    for p, b, got in zip(prompts, budgets, outs):
        want = np.asarray(generate(model, p[None], max_new_tokens=b))[0]
        np.testing.assert_array_equal(got, want)
    s = eng.stats()
    assert s["faults"]["lane_quarantines"] == 1
    assert s["disagg"]["quarantined_lanes"] == [0]
    assert s["disagg"]["healthy_lanes"] == 1
    assert s["disagg"]["degraded"] is False
    assert s["decode_executables"] == 1
    assert s["steady_recompiles"] == 0
