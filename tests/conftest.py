"""Test fixtures: virtual 8-device CPU mesh + singleton reset.

Mirrors the reference's test strategy (SURVEY.md §4): a CPU multi-device
fake-mesh path for CI (`xla_force_host_platform_device_count`) and
singleton-reset fixtures (the reference's `AccelerateTestCase`,
test_utils/testing.py:667-679).
"""

import os

# Must run before jax initializes its backend (jax may already be *imported*
# by a sitecustomize hook, so set the config knob too, not just the env).
# Tests always target the virtual CPU mesh (set ACCELERATE_TEST_USE_TPU=1 to
# run against real chips).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
if not os.environ.get("ACCELERATE_TEST_USE_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    # Persistent compilation cache: the suite's wall-clock is dominated by
    # XLA compiles of the same tiny models over and over (across tests AND
    # across the launched-subprocess gangs). Cache them on disk — second and
    # later runs skip straight to execution. Guarded: older jaxlibs may not
    # support caching on the CPU backend.
    try:
        cache_dir = os.environ.get("ACCELERATE_TEST_COMPILE_CACHE", "/tmp/accelerate_tpu_test_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_enable_xla_caches", "all")
        # Launched-subprocess gangs don't import this conftest — hand the
        # cache to them through the env (jax reads these at import).
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
        os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
        os.environ.setdefault("JAX_PERSISTENT_CACHE_ENABLE_XLA_CACHES", "all")
    except Exception:
        pass

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def reset_accelerate_state():
    yield
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
