"""Test fixtures: virtual 8-device CPU mesh + singleton reset.

Mirrors the reference's test strategy (SURVEY.md §4): a CPU multi-device
fake-mesh path for CI (`xla_force_host_platform_device_count`) and
singleton-reset fixtures (the reference's `AccelerateTestCase`,
test_utils/testing.py:667-679).
"""

import os

# Must run before jax initializes its backend (jax may already be *imported*
# by a sitecustomize hook, so set the config knob too, not just the env).
# Tests always target the virtual CPU mesh (set ACCELERATE_TEST_USE_TPU=1 to
# run against real chips).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
if not os.environ.get("ACCELERATE_TEST_USE_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    # Persistent XLA compilation cache: tried (2.6x on warm model-file
    # reruns) and REVERTED — cache-hit replays of the ring-attention
    # (shard_map/ppermute) executables SIGABRT the CPU backend, with or
    # without jax_persistent_cache_enable_xla_caches. Opt in explicitly via
    # ACCELERATE_TEST_COMPILE_CACHE for suites that skip the cp/ring tests.
    cache_dir = os.environ.get("ACCELERATE_TEST_COMPILE_CACHE")
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
        os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import pytest  # noqa: E402


_test_counter = {"n": 0}


@pytest.fixture(autouse=True)
def reset_accelerate_state():
    yield
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    # Periodically drop live compiled executables: the full suite compiles
    # thousands of tiny programs in ONE process, and jaxlib's CPU backend
    # nondeterministically SIGSEGVs inside backend_compile_and_load late in
    # such runs (observed ~test 290+ at varying tests). Bounding the live
    # executables (and their JIT code mappings) is the mitigation; the
    # recompile cost is small because most tests build fresh modules anyway.
    _test_counter["n"] += 1
    if _test_counter["n"] % 40 == 0 and not os.environ.get("ACCELERATE_TEST_USE_TPU"):
        import jax as _jax

        _jax.clear_caches()
