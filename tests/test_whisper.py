"""Whisper family: shapes, TP sharding, HF logit parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Model
from accelerate_tpu.models import (
    WhisperConfig, WhisperForConditionalGeneration, whisper_tp_rules,
)
from accelerate_tpu.utils import set_seed


def _inputs(cfg, b=2, t=24, s=6, seed=0):
    rng = np.random.default_rng(seed)
    feats = rng.normal(size=(b, t, cfg.num_mel_bins)).astype(np.float32)
    dec = rng.integers(0, cfg.vocab_size, (b, s)).astype(np.int32)
    return jnp.asarray(feats), jnp.asarray(dec)


def test_whisper_forward_shape():
    set_seed(0)
    cfg = WhisperConfig.tiny()
    module = WhisperForConditionalGeneration(cfg)
    feats, dec = _inputs(cfg)
    params = module.init(jax.random.key(0), feats, dec)["params"]
    logits = module.apply({"params": params}, feats, dec)
    assert logits.shape == (2, 6, cfg.vocab_size)


def test_whisper_tp_sharded_logits_match():
    import optax

    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    set_seed(0)
    cfg = WhisperConfig.tiny(dtype=jnp.float32)
    module = WhisperForConditionalGeneration(cfg)
    feats, dec = _inputs(cfg, b=4)
    single = Model.from_flax(module, jax.random.key(0), feats, dec)
    want = np.asarray(single(feats, dec))

    acc = Accelerator(parallelism_config=ParallelismConfig(tp_size=4, dp_shard_size=2))
    model = Model.from_flax(module, jax.random.key(0), feats, dec,
                            tp_rules=whisper_tp_rules())
    model, _ = acc.prepare(model, optax.adam(1e-3))
    np.testing.assert_allclose(np.asarray(model(feats, dec)), want, rtol=2e-4, atol=2e-4)
    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()


def test_whisper_hf_logit_parity():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from accelerate_tpu.models import model_from_pretrained

    hf_cfg = transformers.WhisperConfig(
        vocab_size=128, num_mel_bins=16, d_model=64, encoder_layers=2,
        decoder_layers=2, encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=128, decoder_ffn_dim=128,
        max_source_positions=24, max_target_positions=32,
        pad_token_id=0, bos_token_id=1, eos_token_id=2, decoder_start_token_id=1,
        suppress_tokens=None, begin_suppress_tokens=None,
    )
    torch.manual_seed(0)
    hf = transformers.WhisperForConditionalGeneration(hf_cfg)
    hf.eval()
    rng = np.random.default_rng(0)
    # HF takes (B, mel, T) with T = 2 * max_source_positions.
    feats = rng.normal(size=(2, 16, 48)).astype(np.float32)
    dec = rng.integers(0, 128, (2, 7)).astype(np.int64)
    with torch.no_grad():
        want = hf(
            input_features=torch.from_numpy(feats),
            decoder_input_ids=torch.from_numpy(dec),
        ).logits.numpy()
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    got = np.asarray(ours(jnp.asarray(feats.transpose(0, 2, 1)), jnp.asarray(dec.astype(np.int32))))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_whisper_hf_parity_asymmetric_heads_and_unscanned():
    """decoder_attention_heads != encoder_attention_heads must reshape with
    each stack's OWN head count (review finding), and the unscanned
    (layer_{i}) layout must load too."""
    import dataclasses

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from accelerate_tpu.models import load_pretrained
    from accelerate_tpu.models.hub import whisper_params_from_hf

    hf_cfg = transformers.WhisperConfig(
        vocab_size=128, num_mel_bins=16, d_model=64, encoder_layers=2,
        decoder_layers=2, encoder_attention_heads=4, decoder_attention_heads=2,
        encoder_ffn_dim=128, decoder_ffn_dim=128,
        max_source_positions=24, max_target_positions=32,
        pad_token_id=0, bos_token_id=1, eos_token_id=2, decoder_start_token_id=1,
        suppress_tokens=None, begin_suppress_tokens=None,
    )
    torch.manual_seed(1)
    hf = transformers.WhisperForConditionalGeneration(hf_cfg)
    hf.eval()
    rng = np.random.default_rng(1)
    feats = rng.normal(size=(1, 16, 48)).astype(np.float32)
    dec = rng.integers(0, 128, (1, 5)).astype(np.int64)
    with torch.no_grad():
        want = hf(
            input_features=torch.from_numpy(feats),
            decoder_input_ids=torch.from_numpy(dec),
        ).logits.numpy()

    cfg, params, cls = load_pretrained(hf, dtype=jnp.float32)
    got = np.asarray(Model(module=cls(cfg), params=params)(
        jnp.asarray(feats.transpose(0, 2, 1)), jnp.asarray(dec.astype(np.int32))
    ))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)

    # Unscanned layout: same checkpoint, layer_{i} names.
    un_cfg = dataclasses.replace(cfg, scan_layers=False)
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    un_params = whisper_params_from_hf(un_cfg, sd)
    got2 = np.asarray(Model(module=cls(un_cfg), params=un_params)(
        jnp.asarray(feats.transpose(0, 2, 1)), jnp.asarray(dec.astype(np.int32))
    ))
    np.testing.assert_allclose(got2, want, rtol=3e-4, atol=3e-4)


def test_whisper_remat_flag_changes_nothing_numerically():
    set_seed(0)
    cfg = WhisperConfig.tiny(dtype=jnp.float32)
    module = WhisperForConditionalGeneration(cfg)
    feats, dec = _inputs(cfg)
    params = module.init(jax.random.key(0), feats, dec)["params"]
    base = module.apply({"params": params}, feats, dec)

    import dataclasses

    rcfg = dataclasses.replace(cfg, remat=True)
    rmodule = WhisperForConditionalGeneration(rcfg)
    import numpy as _np

    _np.testing.assert_allclose(
        _np.asarray(rmodule.apply({"params": params}, feats, dec)),
        _np.asarray(base), rtol=1e-6,
    )
