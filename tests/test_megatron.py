"""Megatron-LM checkpoint importer: layout conversion + TP-shard merging.

Inverse-roundtrip strategy: build a synthetic megatron-core checkpoint FROM
native Llama params (using the documented fused layouts), import it, and
require logit parity — pins both directions of the layout math.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.models.megatron import (
    llama_params_to_megatron_core,
    megatron_config_from_args,
    megatron_core_params_to_llama,
    merge_megatron_tp_shards,
)


def _native_llama(gqa=True, attention_bias=False):
    kw = dict(dtype=jnp.float32, scan_layers=True, attention_bias=attention_bias)
    if gqa:
        kw["num_key_value_heads"] = 2
    cfg = LlamaConfig.tiny(**kw)
    module = LlamaForCausalLM(cfg)
    ids = np.arange(2 * 12, dtype=np.int32).reshape(2, 12) % cfg.vocab_size
    params = module.init(jax.random.key(0), ids)["params"]
    return cfg, module, params, ids


@pytest.mark.parametrize("gqa", [False, True])
def test_megatron_core_import_logit_parity(gqa):
    cfg, module, params, ids = _native_llama(gqa)
    want = module.apply({"params": params}, ids)

    sd = llama_params_to_megatron_core(cfg, params)
    got_params = megatron_core_params_to_llama(cfg, sd)
    got = module.apply({"params": jax.tree.map(jnp.asarray, got_params)}, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_megatron_tp_shard_merge_roundtrip():
    """Split the synthetic checkpoint per Megatron partition rules into two
    TP shards, merge, convert — parity must survive."""
    cfg, module, params, ids = _native_llama(gqa=False)
    want = module.apply({"params": params}, ids)
    sd = llama_params_to_megatron_core(cfg, params)

    def split(name, arr):
        if name.endswith("linear_fc1.weight"):
            # Megatron's per-rank SwiGLU layout: each rank stores its OWN
            # [gate_r; up_r] halves, not a slice of the global [gate; up].
            gate, up = np.split(arr, 2, axis=0)
            g0, g1 = np.split(gate, 2, axis=0)
            u0, u1 = np.split(up, 2, axis=0)
            return [np.concatenate([g0, u0]), np.concatenate([g1, u1])]
        if name.endswith("linear_qkv.weight") or (
            name.endswith("word_embeddings.weight") or name.endswith("output_layer.weight")
        ):
            return np.split(arr, 2, axis=0)
        if name.endswith("linear_proj.weight") or name.endswith("linear_fc2.weight"):
            return np.split(arr, 2, axis=1)
        return [arr, arr]  # replicated

    shard0, shard1 = {}, {}
    for nme, arr in sd.items():
        a, b = split(nme, arr)
        shard0[nme], shard1[nme] = a, b
    merged = merge_megatron_tp_shards([shard0, shard1])
    got_params = megatron_core_params_to_llama(cfg, merged)
    got = module.apply({"params": jax.tree.map(jnp.asarray, got_params)}, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_megatron_config_from_args():
    cfg = megatron_config_from_args(
        dict(
            padded_vocab_size=50304, hidden_size=128, ffn_hidden_size=512,
            num_layers=4, num_attention_heads=8, num_query_groups=2,
            max_position_embeddings=2048, norm_epsilon=1e-6, rotary_base=1e6,
            untie_embeddings_and_output_weights=True,
        )
    )
    assert cfg.vocab_size == 50304
    assert cfg.num_key_value_heads == 2
    assert cfg.intermediate_size == 512
    assert cfg.rope_theta == 1e6
    assert cfg.tie_word_embeddings is False


def test_load_megatron_checkpoint_dir(tmp_path):
    """End-to-end: torch-save a fake layout, resolve iteration, load, merge."""
    torch = pytest.importorskip("torch")

    cfg, module, params, ids = _native_llama(gqa=False)
    sd = llama_params_to_megatron_core(cfg, params)
    it_dir = tmp_path / "iter_0000100" / "mp_rank_00"
    it_dir.mkdir(parents=True)
    payload = {
        "model": {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in sd.items()},
        "args": {"num_layers": cfg.num_hidden_layers},
    }
    torch.save(payload, it_dir / "model_optim_rng.pt")
    (tmp_path / "latest_checkpointed_iteration.txt").write_text("100")

    from accelerate_tpu.models.megatron import load_megatron_checkpoint

    shards, args = load_megatron_checkpoint(str(tmp_path))
    assert len(shards) == 1
    assert args == {"num_layers": cfg.num_hidden_layers}
    got_params = megatron_core_params_to_llama(cfg, merge_megatron_tp_shards(shards))
    want = module.apply({"params": params}, ids)
    got = module.apply({"params": jax.tree.map(jnp.asarray, got_params)}, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_megatron_qkv_bias_roundtrip():
    """add_qkv_bias checkpoints: fused bias slices into q/k/v biases."""
    cfg, module, params, ids = _native_llama(gqa=True, attention_bias=True)
    want = module.apply({"params": params}, ids)
    sd = llama_params_to_megatron_core(cfg, params)
    assert any(k.endswith("linear_qkv.bias") for k in sd)
    got_params = megatron_core_params_to_llama(cfg, sd)
    got = module.apply({"params": jax.tree.map(jnp.asarray, got_params)}, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_megatron_pp_checkpoint_rejected(tmp_path):
    pytest.importorskip("torch")
    from accelerate_tpu.models.megatron import load_megatron_checkpoint

    (tmp_path / "iter_0000005" / "mp_rank_00_000").mkdir(parents=True)
    (tmp_path / "iter_0000005" / "mp_rank_00_001").mkdir(parents=True)
    (tmp_path / "latest_checkpointed_iteration.txt").write_text("5")
    with pytest.raises(NotImplementedError, match="pipeline-parallel"):
        load_megatron_checkpoint(str(tmp_path))
