"""Megatron-LM checkpoint importer: layout conversion + TP-shard merging.

Inverse-roundtrip strategy: build a synthetic megatron-core checkpoint FROM
native Llama params (using the documented fused layouts), import it, and
require logit parity — pins both directions of the layout math.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.models.megatron import (
    llama_params_to_megatron_core,
    megatron_config_from_args,
    megatron_core_params_to_llama,
    merge_megatron_tp_shards,
)


def _native_llama(gqa=True, attention_bias=False):
    kw = dict(dtype=jnp.float32, scan_layers=True, attention_bias=attention_bias)
    if gqa:
        kw["num_key_value_heads"] = 2
    cfg = LlamaConfig.tiny(**kw)
    module = LlamaForCausalLM(cfg)
    ids = np.arange(2 * 12, dtype=np.int32).reshape(2, 12) % cfg.vocab_size
    params = module.init(jax.random.key(0), ids)["params"]
    return cfg, module, params, ids


@pytest.mark.parametrize("gqa", [False, True])
def test_megatron_core_import_logit_parity(gqa):
    cfg, module, params, ids = _native_llama(gqa)
    want = module.apply({"params": params}, ids)

    sd = llama_params_to_megatron_core(cfg, params)
    got_params = megatron_core_params_to_llama(cfg, sd)
    got = module.apply({"params": jax.tree.map(jnp.asarray, got_params)}, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_megatron_tp_shard_merge_roundtrip():
    """Split the synthetic checkpoint per Megatron partition rules into two
    TP shards, merge, convert — parity must survive."""
    cfg, module, params, ids = _native_llama(gqa=False)
    want = module.apply({"params": params}, ids)
    sd = llama_params_to_megatron_core(cfg, params)

    def split(name, arr):
        if name.endswith("linear_fc1.weight"):
            # Megatron's per-rank SwiGLU layout: each rank stores its OWN
            # [gate_r; up_r] halves, not a slice of the global [gate; up].
            gate, up = np.split(arr, 2, axis=0)
            g0, g1 = np.split(gate, 2, axis=0)
            u0, u1 = np.split(up, 2, axis=0)
            return [np.concatenate([g0, u0]), np.concatenate([g1, u1])]
        if name.endswith("linear_qkv.weight") or (
            name.endswith("word_embeddings.weight") or name.endswith("output_layer.weight")
        ):
            return np.split(arr, 2, axis=0)
        if name.endswith("linear_proj.weight") or name.endswith("linear_fc2.weight"):
            return np.split(arr, 2, axis=1)
        return [arr, arr]  # replicated

    shard0, shard1 = {}, {}
    for nme, arr in sd.items():
        a, b = split(nme, arr)
        shard0[nme], shard1[nme] = a, b
    merged = merge_megatron_tp_shards([shard0, shard1])
    got_params = megatron_core_params_to_llama(cfg, merged)
    got = module.apply({"params": jax.tree.map(jnp.asarray, got_params)}, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_megatron_config_from_args():
    cfg = megatron_config_from_args(
        dict(
            padded_vocab_size=50304, hidden_size=128, ffn_hidden_size=512,
            num_layers=4, num_attention_heads=8, num_query_groups=2,
            max_position_embeddings=2048, norm_epsilon=1e-6, rotary_base=1e6,
            untie_embeddings_and_output_weights=True,
        )
    )
    assert cfg.vocab_size == 50304
    assert cfg.num_key_value_heads == 2
    assert cfg.intermediate_size == 512
    assert cfg.rope_theta == 1e6
    assert cfg.tie_word_embeddings is False


def test_load_megatron_checkpoint_dir(tmp_path):
    """End-to-end: torch-save a fake layout, resolve iteration, load, merge."""
    torch = pytest.importorskip("torch")

    cfg, module, params, ids = _native_llama(gqa=False)
    sd = llama_params_to_megatron_core(cfg, params)
    it_dir = tmp_path / "iter_0000100" / "mp_rank_00"
    it_dir.mkdir(parents=True)
    payload = {
        "model": {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in sd.items()},
        "args": {"num_layers": cfg.num_hidden_layers},
    }
    torch.save(payload, it_dir / "model_optim_rng.pt")
    (tmp_path / "latest_checkpointed_iteration.txt").write_text("100")

    from accelerate_tpu.models.megatron import load_megatron_checkpoint

    shards, args = load_megatron_checkpoint(str(tmp_path))
    assert len(shards) == 1
    assert args == {"num_layers": cfg.num_hidden_layers}
    got_params = megatron_core_params_to_llama(cfg, merge_megatron_tp_shards(shards))
    want = module.apply({"params": params}, ids)
    got = module.apply({"params": jax.tree.map(jnp.asarray, got_params)}, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_megatron_qkv_bias_roundtrip():
    """add_qkv_bias checkpoints: fused bias slices into q/k/v biases."""
    cfg, module, params, ids = _native_llama(gqa=True, attention_bias=True)
    want = module.apply({"params": params}, ids)
    sd = llama_params_to_megatron_core(cfg, params)
    assert any(k.endswith("linear_qkv.bias") for k in sd)
    got_params = megatron_core_params_to_llama(cfg, sd)
    got = module.apply({"params": jax.tree.map(jnp.asarray, got_params)}, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_megatron_pp_dirs_without_files_raise(tmp_path):
    """PP-sharded dirs now LOAD (round 4); empty rank dirs still fail loudly."""
    pytest.importorskip("torch")
    from accelerate_tpu.models.megatron import load_megatron_checkpoint

    (tmp_path / "iter_0000005" / "mp_rank_00_000").mkdir(parents=True)
    (tmp_path / "iter_0000005" / "mp_rank_00_001").mkdir(parents=True)
    (tmp_path / "latest_checkpointed_iteration.txt").write_text("5")
    with pytest.raises(FileNotFoundError, match="mp_rank_00_000"):
        load_megatron_checkpoint(str(tmp_path))


# ---------------------------------------------------------------------------
# round 4: legacy layout + PP-sharded checkpoint dirs
# ---------------------------------------------------------------------------


def _core_to_legacy_names(sd):
    """Rename a core flat dict to the legacy language_model.encoder.* layout
    (inverse of megatron_legacy_to_core, for synthetic-checkpoint tests)."""
    out = {}
    for k, v in sd.items():
        name = k
        name = name.replace("decoder.layers.", "encoder.layers.")
        name = name.replace(".self_attention.linear_qkv.layer_norm_weight", "#ILN#")
        name = name.replace(".mlp.linear_fc1.layer_norm_weight", "#PLN#")
        name = name.replace(".self_attention.linear_qkv.", ".self_attention.query_key_value.")
        name = name.replace(".self_attention.linear_proj.", ".self_attention.dense.")
        name = name.replace(".mlp.linear_fc1.", ".mlp.dense_h_to_4h.")
        name = name.replace(".mlp.linear_fc2.", ".mlp.dense_4h_to_h.")
        name = name.replace("#ILN#", ".input_layernorm.weight")
        name = name.replace("#PLN#", ".post_attention_layernorm.weight")
        name = name.replace("decoder.final_layernorm.", "encoder.final_layernorm.")
        if name.startswith("encoder.") or name.startswith("embedding.") or name.startswith(
            "output_layer."
        ):
            name = "language_model." + name
        out[name] = v
    return out


def test_megatron_legacy_import_logit_parity():
    """legacy language_model.encoder.* layout converts with logit parity."""
    from accelerate_tpu.models.megatron import megatron_params_to_llama

    cfg, module, params, ids = _native_llama(gqa=True)
    want = module.apply({"params": params}, ids)
    legacy = _core_to_legacy_names(llama_params_to_megatron_core(cfg, params))
    assert any(k.startswith("language_model.encoder.") for k in legacy)
    got_params = megatron_params_to_llama(cfg, legacy)
    got = module.apply({"params": jax.tree.map(jnp.asarray, got_params)}, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_megatron_legacy_position_embeddings_rejected():
    from accelerate_tpu.models.megatron import megatron_legacy_to_core

    sd = {"language_model.embedding.position_embeddings.weight": np.zeros((4, 8))}
    with pytest.raises(ValueError, match="position embeddings"):
        megatron_legacy_to_core(sd)


def test_megatron_pp_sharded_checkpoint_loads(tmp_path):
    """mp_rank_XX_YYY dirs: stages renumber + union; logit parity end-to-end;
    the tied word_embeddings_for_head copy on the last stage is dropped."""
    torch = pytest.importorskip("torch")
    from accelerate_tpu.models.megatron import (
        load_megatron_checkpoint,
        megatron_params_to_llama,
    )

    cfg, module, params, ids = _native_llama(gqa=False)  # 2 layers -> pp=2
    want = module.apply({"params": params}, ids)
    sd = _core_to_legacy_names(llama_params_to_megatron_core(cfg, params))

    def stage_dict(stage):
        out = {}
        for k, v in sd.items():
            m = __import__("re").match(
                r"(language_model\.encoder\.layers\.)(\d+)(\..+)", k
            )
            if m:
                idx = int(m.group(2))
                if idx == stage:  # one layer per stage
                    out[f"{m.group(1)}0{m.group(3)}"] = v
            elif k.startswith("language_model.embedding."):
                if stage == 0:
                    out[k] = v
            else:  # final norm / output layer -> last stage
                if stage == 1:
                    out[k] = v
        if stage == 1:
            # Megatron's tied-embedding copy on the last PP stage
            out["word_embeddings_for_head.word_embeddings.weight"] = sd[
                "language_model.embedding.word_embeddings.weight"
            ]
        return out

    it = tmp_path / "iter_0000007"
    for pp in range(2):
        d = it / f"mp_rank_00_{pp:03d}"
        d.mkdir(parents=True)
        payload = {
            "model": {
                k: torch.from_numpy(np.ascontiguousarray(v))
                for k, v in stage_dict(pp).items()
            },
            "checkpoint_version": 3.0,
        }
        torch.save(payload, d / "model_optim_rng.pt")
    (tmp_path / "latest_checkpointed_iteration.txt").write_text("7")

    shards, _ = load_megatron_checkpoint(str(tmp_path))
    assert len(shards) == 1
    merged = merge_megatron_tp_shards(shards)
    assert not any("word_embeddings_for_head" in k for k in merged)
    got_params = megatron_params_to_llama(cfg, merged)
    got = module.apply({"params": jax.tree.map(jnp.asarray, got_params)}, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_megatron_pp_tp_sharded_checkpoint_loads(tmp_path):
    """TP=2 x PP=2 grid of mp_rank_0T_00P dirs loads, merges, converts."""
    torch = pytest.importorskip("torch")
    from accelerate_tpu.models.megatron import (
        load_megatron_checkpoint,
        megatron_params_to_llama,
    )
    import re as _re

    cfg, module, params, ids = _native_llama(gqa=False)
    want = module.apply({"params": params}, ids)
    sd = llama_params_to_megatron_core(cfg, params)

    def tp_split(name, arr):
        if name.endswith("linear_fc1.weight"):
            gate, up = np.split(arr, 2, axis=0)
            g0, g1 = np.split(gate, 2, axis=0)
            u0, u1 = np.split(up, 2, axis=0)
            return [np.concatenate([g0, u0]), np.concatenate([g1, u1])]
        if name.endswith("linear_qkv.weight") or name.endswith(
            "word_embeddings.weight"
        ) or name.endswith("output_layer.weight"):
            return np.split(arr, 2, axis=0)
        if name.endswith("linear_proj.weight") or name.endswith("linear_fc2.weight"):
            return np.split(arr, 2, axis=1)
        return [arr, arr]

    it = tmp_path / "iter_0000003"
    for tp in range(2):
        for pp in range(2):
            d = it / f"mp_rank_{tp:02d}_{pp:03d}"
            d.mkdir(parents=True)
            stage = {}
            for k, v in sd.items():
                m = _re.match(r"(decoder\.layers\.)(\d+)(\..+)", k)
                local = tp_split(k, v)[tp]
                if m:
                    if int(m.group(2)) == pp:
                        stage[f"{m.group(1)}0{m.group(3)}"] = local
                elif k.startswith("embedding."):
                    if pp == 0:
                        stage[k] = local
                elif pp == 1:
                    stage[k] = local
            torch.save(
                {"model": {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in stage.items()},
                 "checkpoint_version": 3.0},
                d / "model_optim_rng.pt",
            )
    (tmp_path / "latest_checkpointed_iteration.txt").write_text("3")

    shards, _ = load_megatron_checkpoint(str(tmp_path))
    assert len(shards) == 2
    got_params = megatron_params_to_llama(cfg, merge_megatron_tp_shards(shards))
    got = module.apply({"params": jax.tree.map(jnp.asarray, got_params)}, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_megatron_old_checkpoint_version_rejected(tmp_path):
    torch = pytest.importorskip("torch")
    from accelerate_tpu.models.megatron import load_megatron_checkpoint

    d = tmp_path / "iter_0000001" / "mp_rank_00"
    d.mkdir(parents=True)
    torch.save(
        {"model": {"x": torch.zeros(2)}, "checkpoint_version": 0},
        d / "model_optim_rng.pt",
    )
    (tmp_path / "latest_checkpointed_iteration.txt").write_text("1")
    with pytest.raises(NotImplementedError, match="checkpoint_version"):
        load_megatron_checkpoint(str(tmp_path))
