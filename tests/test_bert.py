"""BERT family (models/bert.py): shapes, scan/loop parity, masking, TP, training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _inputs(cfg, batch=2, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, size=(batch, seq), dtype=np.int32)
    mask = np.ones_like(ids)
    return ids, mask


def test_sequence_classifier_shapes():
    from accelerate_tpu.models import BertConfig, BertForSequenceClassification

    cfg = BertConfig.tiny(num_labels=3)
    ids, mask = _inputs(cfg)
    module = BertForSequenceClassification(cfg)
    params = module.init(jax.random.key(0), ids, mask)["params"]
    logits = module.apply({"params": params}, ids, mask)
    assert logits.shape == (2, 3)
    assert np.isfinite(np.asarray(logits, np.float32)).all()


def test_scan_vs_loop_same_output():
    from accelerate_tpu.models import BertConfig, BertForSequenceClassification

    ids, mask = _inputs(BertConfig.tiny())
    outs = []
    for scan in (True, False):
        cfg = BertConfig.tiny(scan_layers=scan, dtype=jnp.float32)
        module = BertForSequenceClassification(cfg)
        params = module.init(jax.random.key(0), ids, mask)["params"]
        # Same per-layer params: copy scanned stack into loop layout and
        # vice versa is fiddly — instead check both run and have equal
        # param COUNTS, and that the scanned one is deterministic.
        outs.append(sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params)))
    assert outs[0] == outs[1], f"param count differs scan vs loop: {outs}"


def test_attention_mask_blocks_padding():
    """Padded positions must not affect the CLS representation."""
    from accelerate_tpu.models import BertConfig, BertForSequenceClassification

    cfg = BertConfig.tiny(dtype=jnp.float32, hidden_dropout_prob=0.0)
    module = BertForSequenceClassification(cfg)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.vocab_size, size=(1, 16), dtype=np.int32)
    mask = np.ones_like(ids)
    mask[:, 8:] = 0
    params = module.init(jax.random.key(0), ids, mask)["params"]
    out1 = np.asarray(module.apply({"params": params}, ids, mask))
    ids2 = ids.copy()
    ids2[:, 8:] = (ids2[:, 8:] + 7) % cfg.vocab_size  # scramble padding tokens
    out2 = np.asarray(module.apply({"params": params}, ids2, mask))
    np.testing.assert_allclose(out1, out2, rtol=1e-5, atol=1e-5)


def test_masked_lm_tied_head_and_loss():
    from accelerate_tpu.models import BertConfig, BertForMaskedLM, masked_lm_loss

    cfg = BertConfig.tiny(dtype=jnp.float32)
    ids, mask = _inputs(cfg)
    module = BertForMaskedLM(cfg)
    params = module.init(jax.random.key(0), ids, mask)["params"]
    logits = module.apply({"params": params}, ids, mask)
    assert logits.shape == (2, 16, cfg.vocab_size)
    labels = np.full_like(ids, -100)
    labels[:, 3] = ids[:, 3]
    loss = masked_lm_loss(logits, labels)
    assert np.isfinite(float(loss)) and float(loss) > 0
    # All-ignored labels → zero loss, no NaN.
    assert float(masked_lm_loss(logits, np.full_like(ids, -100))) == 0.0


def test_bert_tp_sharded_matches_single_device():
    """TP=2 over the rule table reproduces single-device logits."""
    import optax

    from accelerate_tpu import Accelerator, Model, ParallelismConfig
    from accelerate_tpu.models import BertConfig, BertForSequenceClassification, bert_tp_rules
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils import set_seed

    cfg = BertConfig.tiny(dtype=jnp.float32)
    ids, mask = _inputs(cfg, batch=4)
    module = BertForSequenceClassification(cfg)

    def run(pc, tp):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        set_seed(0)
        acc = Accelerator(parallelism_config=pc)
        model = Model.from_flax(
            module, jax.random.key(0), ids, mask,
            tp_rules=bert_tp_rules(cfg.scan_layers) if tp else None,
        )
        model, _ = acc.prepare(model, optax.sgd(1e-2))
        return np.asarray(model(ids, mask), np.float32)

    ref = run(ParallelismConfig(dp_shard_size=8), tp=False)
    tp = run(ParallelismConfig(dp_shard_size=4, tp_size=2), tp=True)
    np.testing.assert_allclose(ref, tp, rtol=1e-4, atol=1e-4)


def test_bert_trains_on_synthetic_task():
    """The marker-token task from nlp_example: loss must fall sharply."""
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils import set_seed

    from accelerate_tpu.models import BertConfig, BertForSequenceClassification

    AcceleratorState._reset_state()
    GradientState._reset_state()
    set_seed(0)
    cfg = BertConfig.tiny(dtype=jnp.float32, num_labels=2)
    module = BertForSequenceClassification(cfg)
    rng = np.random.default_rng(0)
    n, seq = 64, 16
    ids = rng.integers(2, cfg.vocab_size, size=(n, seq), dtype=np.int32)
    labels = rng.integers(0, 2, size=n).astype(np.int32)
    ids[np.arange(n), 1] = labels  # marker at fixed position: easy task
    mask = np.ones_like(ids)

    acc = Accelerator()
    model = Model.from_flax(module, jax.random.key(0), ids[:8], mask[:8])
    model, _ = acc.prepare(model, optax.adam(1e-3))

    def loss_fn(params, batch):
        logits = module.apply({"params": params}, batch["ids"], batch["mask"])
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, batch["y"][:, None], axis=-1))

    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    batch = {"ids": ids, "mask": mask, "y": labels}
    losses = []
    for _ in range(30):
        state, m = step(state, batch)
        losses.append(float(np.asarray(m["loss"])))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
