"""Tracker registry + the 6 round-2 trackers (Trackio, CometML, Aim, ClearML,
DVCLive, SwanLab) behind availability probes, tested against mock SDK modules
(reference: tracking.py:418-1246, registry :1247)."""

import sys
import types
from unittest import mock

import numpy as np
import pytest


def test_registry_lists_all_reference_trackers():
    from accelerate_tpu.tracking import LOGGER_TYPE_TO_CLASS

    # Reference ships 9 trackers (tracking.py:1247); we add "json".
    expected = {"json", "tensorboard", "wandb", "mlflow", "trackio", "comet_ml",
                "aim", "clearml", "dvclive", "swanlab"}
    assert expected <= set(LOGGER_TYPE_TO_CLASS)
    assert len(LOGGER_TYPE_TO_CLASS) >= 10


def test_every_tracker_has_availability_probe():
    from accelerate_tpu.tracking import _AVAILABILITY, LOGGER_TYPE_TO_CLASS

    assert set(LOGGER_TYPE_TO_CLASS) <= set(_AVAILABILITY)


def _mock_module(name, **attrs):
    m = types.ModuleType(name)
    for k, v in attrs.items():
        setattr(m, k, v)
    return m


def test_trackio_tracker_logs_via_mock():
    from accelerate_tpu.tracking import TrackioTracker

    run = mock.MagicMock()
    mod = _mock_module("trackio", init=mock.MagicMock(return_value=run),
                       config=mock.MagicMock(), finish=mock.MagicMock())
    with mock.patch.dict(sys.modules, {"trackio": mod}):
        t = TrackioTracker("proj")
        t.store_init_configuration({"lr": 0.1})
        t.log({"loss": 1.0}, step=3)
        t.finish()
    mod.init.assert_called_once()
    run.log.assert_called_once_with({"loss": 1.0}, step=3)
    mod.finish.assert_called_once()


def test_comet_ml_tracker_logs_via_mock():
    from accelerate_tpu.tracking import CometMLTracker

    exp = mock.MagicMock()
    mod = _mock_module("comet_ml", start=mock.MagicMock(return_value=exp))
    with mock.patch.dict(sys.modules, {"comet_ml": mod}):
        t = CometMLTracker("proj")
        t.store_init_configuration({"lr": 0.1})
        t.log({"loss": 2.0, "note": "hi", "nested": {"a": 1.0}}, step=5)
        t.finish()
    exp.log_parameters.assert_called_once_with({"lr": 0.1})
    exp.log_metric.assert_called_once_with("loss", 2.0, step=5)
    exp.log_other.assert_called_once_with("note", "hi")
    exp.log_metrics.assert_called_once_with({"a": 1.0}, step=5)
    exp.end.assert_called_once()


def test_aim_tracker_logs_via_mock(tmp_path):
    from accelerate_tpu.tracking import AimTracker

    run = mock.MagicMock()
    mod = _mock_module("aim", Run=mock.MagicMock(return_value=run))
    with mock.patch.dict(sys.modules, {"aim": mod}):
        t = AimTracker("run1", logging_dir=str(tmp_path))
        t.store_init_configuration({"lr": 0.1})
        t.log({"loss": 1.5}, step=2)
        t.finish()
    mod.Run.assert_called_once_with(repo=str(tmp_path))
    run.track.assert_called_once_with(1.5, name="loss", step=2)
    run.close.assert_called_once()


def test_clearml_tracker_logs_via_mock():
    from accelerate_tpu.tracking import ClearMLTracker

    task = mock.MagicMock()
    Task = mock.MagicMock()
    Task.current_task.return_value = None
    Task.init.return_value = task
    mod = _mock_module("clearml", Task=Task)
    with mock.patch.dict(sys.modules, {"clearml": mod}):
        t = ClearMLTracker("proj")
        t.store_init_configuration({"lr": 0.1})
        t.log({"train/loss": 0.5, "acc": 0.9}, step=7)
        t.finish()
    logger_ = task.get_logger.return_value
    logger_.report_scalar.assert_any_call(
        title="train", series="loss", value=0.5, iteration=7
    )
    logger_.report_scalar.assert_any_call(title="acc", series="acc", value=0.9, iteration=7)
    task.close.assert_called_once()


def test_dvclive_tracker_logs_via_mock():
    from accelerate_tpu.tracking import DVCLiveTracker

    live = mock.MagicMock()
    mod = _mock_module("dvclive", Live=mock.MagicMock(return_value=live))
    with mock.patch.dict(sys.modules, {"dvclive": mod}):
        t = DVCLiveTracker("run")
        t.store_init_configuration({"lr": 0.1})
        t.log({"loss": 0.25}, step=4)
        t.finish()
    live.log_params.assert_called_once_with({"lr": 0.1})
    live.log_metric.assert_called_once_with("loss", 0.25)
    assert live.step == 4
    live.next_step.assert_called_once()
    live.end.assert_called_once()


def test_swanlab_tracker_logs_via_mock():
    from accelerate_tpu.tracking import SwanLabTracker

    run = mock.MagicMock()
    mod = _mock_module("swanlab", init=mock.MagicMock(return_value=run),
                       config=mock.MagicMock(), finish=mock.MagicMock())
    with mock.patch.dict(sys.modules, {"swanlab": mod}):
        t = SwanLabTracker("proj")
        t.log({"loss": 0.1}, step=1)
        t.finish()
    run.log.assert_called_once_with({"loss": 0.1}, step=1)
    mod.finish.assert_called_once()


def test_filter_trackers_drops_unavailable(caplog):
    from accelerate_tpu import PartialState
    from accelerate_tpu.tracking import filter_trackers

    PartialState()  # logging requires initialized state
    chosen = filter_trackers(["json", "comet_ml"], logging_dir="/tmp/x")
    names = [c if isinstance(c, str) else getattr(c, "name", c) for c in chosen]
    # comet_ml is not installed in this image → dropped with a warning.
    assert any("json" in str(n) for n in names)
    assert not any("comet" in str(n) for n in names)


# ---------------------------------------------------------------------------
# profile() honoring ProfileKwargs (VERDICT r1 weak-item 7)
# ---------------------------------------------------------------------------


def test_profile_schedule_traces_active_windows(tmp_path):
    import jax.numpy as jnp

    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import ProfileKwargs

    ready = []
    handler = ProfileKwargs(
        schedule_option={"wait": 1, "warmup": 1, "active": 2, "repeat": 2},
        output_trace_dir=str(tmp_path),
        on_trace_ready=lambda sess: ready.append(sess.trace_dirs[-1]),
    )
    acc = Accelerator()
    with acc.profile(handler) as prof:
        for _ in range(10):
            (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
            prof.step()
    assert prof.cycles_done == 2
    assert ready == [str(tmp_path / "cycle_0"), str(tmp_path / "cycle_1")]
    for d in ready:
        # jax writes plugins/profile/<ts>/ under the trace dir
        assert any("profile" in r for r, _, _ in ((r, d_, f) for r, d_, f in __import__("os").walk(d))), d


def test_profile_unscheduled_traces_whole_context(tmp_path):
    import os

    import jax.numpy as jnp

    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import ProfileKwargs

    acc = Accelerator()
    with acc.profile(ProfileKwargs(output_trace_dir=str(tmp_path))) as prof:
        (jnp.ones((4, 4)) * 2).block_until_ready()
    assert prof.trace_dirs == [str(tmp_path)]
    assert os.path.isdir(os.path.join(str(tmp_path), "plugins"))


def test_clearml_external_task_not_closed():
    """When a ClearML task already exists (e.g. pipeline-managed), finish()
    must NOT close it."""
    from accelerate_tpu.tracking import ClearMLTracker

    task = mock.MagicMock()
    Task = mock.MagicMock()
    Task.current_task.return_value = task  # pre-existing task
    mod = _mock_module("clearml", Task=Task)
    with mock.patch.dict(sys.modules, {"clearml": mod}):
        t = ClearMLTracker("proj")
        t.finish()
    Task.init.assert_not_called()
    task.close.assert_not_called()


def test_dvclive_mixed_value_log_does_not_crash():
    from accelerate_tpu.tracking import DVCLiveTracker

    live = mock.MagicMock()
    mod = _mock_module("dvclive", Live=mock.MagicMock(return_value=live))
    with mock.patch.dict(sys.modules, {"dvclive": mod}):
        t = DVCLiveTracker("run")
        t.log({"loss": 0.25, "stage": "eval"}, step=1)
    live.log_metric.assert_called_once_with("loss", 0.25)
    live.log_param.assert_called_once_with("stage", "eval")


def test_profile_schedule_active_one(tmp_path):
    """active=1: start and stop land on the same step — every cycle must
    still produce its own trace."""
    import jax.numpy as jnp

    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import ProfileKwargs

    handler = ProfileKwargs(
        schedule_option={"wait": 1, "warmup": 1, "active": 1, "repeat": 2},
        output_trace_dir=str(tmp_path),
    )
    acc = Accelerator()
    with acc.profile(handler) as prof:
        for _ in range(8):
            (jnp.ones((4, 4)) * 2).block_until_ready()
            prof.step()
    assert prof.cycles_done == 2
    assert prof.trace_dirs == [str(tmp_path / "cycle_0"), str(tmp_path / "cycle_1")]


def test_profile_schedule_window_covers_active_steps(tmp_path):
    """The trace must open BEFORE the cycle's active steps run and close
    after the last one (step() is called post-step) — verified with stubbed
    start/stop ordering."""
    from unittest import mock as _mock

    import accelerate_tpu.utils.profiling as P
    from accelerate_tpu.utils import ProfileKwargs

    events = []
    handler = ProfileKwargs(
        schedule_option={"wait": 1, "warmup": 1, "active": 2, "repeat": 2},
        output_trace_dir=str(tmp_path),
    )
    with _mock.patch.object(P.jax.profiler, "start_trace",
                            lambda d: events.append(("start", d))), \
         _mock.patch.object(P.jax.profiler, "stop_trace",
                            lambda: events.append(("stop",))):
        s = P.ProfileSession(handler, str(tmp_path))
        s.enter()
        for i in range(1, 11):
            events.append(("work", i))
            s.step()
        s.exit()
    i0 = events.index(("start", str(tmp_path / "cycle_0")))
    j0 = events.index(("stop",))
    assert [e[1] for e in events[i0:j0] if e[0] == "work"] == [3, 4]
    i1 = events.index(("start", str(tmp_path / "cycle_1")))
    j1 = events.index(("stop",), i1)
    assert [e[1] for e in events[i1:j1] if e[0] == "work"] == [7, 8]


def test_clearml_warns_on_non_scalar(caplog):
    import logging

    from accelerate_tpu.tracking import ClearMLTracker

    task = mock.MagicMock()
    Task = mock.MagicMock()
    Task.current_task.return_value = None
    Task.init.return_value = task
    mod = _mock_module("clearml", Task=Task)
    with mock.patch.dict(sys.modules, {"clearml": mod}):
        t = ClearMLTracker("proj")
        with caplog.at_level(logging.WARNING):
            t.log({"stage": "eval", "loss": 0.5}, step=1)
    assert any("stage" in r.message for r in caplog.records)
