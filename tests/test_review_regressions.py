"""Regressions for code-review findings."""

import numpy as np
import pytest


def test_batch_sampler_tail_distinct_chunks():
    """10 samples, batch 2, 4 procs: final round fillers must yield DISTINCT
    chunks, not P copies of initial_data[:2]."""
    from accelerate_tpu.data_loader import BatchSampler, BatchSamplerShard, SequentialSampler

    inner = BatchSampler(SequentialSampler(10), batch_size=2, drop_last=False)
    shards = [
        BatchSamplerShard(inner, num_processes=4, process_index=i, even_batches=True)
        for i in range(4)
    ]
    rows = [list(s) for s in shards]
    lengths = {len(r) for r in rows}
    assert lengths == {2}
    final_round = [r[-1] for r in rows]
    # proc0 got the real batch [8,9]; fillers must be pairwise distinct.
    assert final_round[0] == [8, 9]
    filled = [tuple(b) for b in final_round[1:]]
    assert len(set(filled)) == len(filled), f"duplicate filler chunks: {final_round}"


def test_rng_stream_hash_deterministic():
    import subprocess
    import sys

    code = (
        "from accelerate_tpu.utils.random import set_seed, next_rng_key\n"
        "import jax\n"
        "jax.config.update('jax_platforms','cpu')\n"
        "set_seed(7)\n"
        "print(jax.random.key_data(next_rng_key('dropout')).tolist())\n"
    )
    outs = {
        subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            env={"PYTHONHASHSEED": str(i), "PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": "/root/repo"},
        ).stdout.strip()
        for i in (1, 2)
    }
    assert len(outs) == 1, f"stream key differs across hash seeds: {outs}"


def test_pp_mesh_builds():
    from accelerate_tpu import ParallelismConfig

    cfg = ParallelismConfig(tp_size=4, pp_size=2)
    mesh = cfg.build_mesh()
    assert mesh.shape["pp"] == 2
    assert mesh.shape["tp"] == 4


def test_dispatcher_partial_final_batch():
    from accelerate_tpu import AcceleratorState
    from accelerate_tpu.data_loader import prepare_data_loader

    AcceleratorState()

    class DS:
        def __len__(self):
            return 10

        def __getitem__(self, i):
            return {"x": np.float32([i])}

    class Spec:
        dataset = DS()
        batch_size = 4
        sampler = None
        drop_last = False

    dl = prepare_data_loader(Spec(), dispatch_batches=True, put_on_device=False)
    batches = list(dl)
    assert len(batches) == 3
    assert batches[-1]["x"].shape[0] == 2  # single process: partial tail kept


def test_reduce_global_array_identity_scale():
    import jax.numpy as jnp

    from accelerate_tpu import AcceleratorState
    from accelerate_tpu.utils import reduce

    AcceleratorState()
    out = reduce(jnp.asarray(3.0), reduction="sum", scale=2.0)
    assert float(out) == 6.0


def test_llama_ring_attention_training():
    """cp=4 mesh, attention_impl='ring': one fused train step on the tiny
    llama with the sequence sharded — loss finite and decreasing."""
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator, Model, ParallelismConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, cross_entropy_loss
    from accelerate_tpu.utils import set_seed

    set_seed(0)
    pc = ParallelismConfig(dp_shard_size=2, cp_size=4)
    acc = Accelerator(parallelism_config=pc)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="ring")
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(4, 65), dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
    model, _ = acc.prepare(model, optax.adam(1e-3))

    def loss_fn(params, batch):
        logits = module.apply({"params": params}, batch["x"])
        return cross_entropy_loss(logits, batch["y"])

    step = acc.prepare_train_step(loss_fn)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(acc.mesh, P(pc.batch_axes, ("cp",)))
    batch = {
        "x": jax.device_put(ids[:, :-1], sharding),
        "y": jax.device_put(ids[:, 1:], sharding),
    }
    state = acc.train_state
    losses = []
    for _ in range(3):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_llama_ring_matches_native_loss():
    """Ring attention loss must equal native attention loss on the same data."""
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator, Model, ParallelismConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, cross_entropy_loss
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils import set_seed

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, size=(8, 65), dtype=np.int32)

    def run(impl, pc):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        set_seed(0)
        acc = Accelerator(parallelism_config=pc)
        cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl=impl)
        module = LlamaForCausalLM(cfg)
        model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
        model, _ = acc.prepare(model, optax.sgd(1e-2))

        def loss_fn(params, batch):
            logits = module.apply({"params": params}, batch["x"])
            return cross_entropy_loss(logits, batch["y"])

        step = acc.prepare_train_step(loss_fn)
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(acc.mesh, P(pc.batch_axes))
        batch = {"x": jax.device_put(ids[:, :-1], sharding), "y": jax.device_put(ids[:, 1:], sharding)}
        _, m = step(acc.train_state, batch)
        return float(m["loss"])

    l_native = run("native", ParallelismConfig(dp_shard_size=8))
    l_ring = run("ring", ParallelismConfig(dp_shard_size=2, cp_size=4))
    np.testing.assert_allclose(l_native, l_ring, rtol=1e-5)


def test_verify_device_map_detects_multi_placement():
    """VERDICT r2 weak #5: verify_device_map was a stub returning False."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu import Accelerator, Model, dispatch_model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    ids = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % cfg.vocab_size
    model = Model.from_flax(module, jax.random.key(0), ids)
    acc = Accelerator()
    assert acc.verify_device_map(model) is False  # plain model: no device map
    split = dispatch_model(model, {"model": 0, "lm_head": "cpu"})
    assert acc.verify_device_map(split) is True
    single = dispatch_model(model, {"": 0})
    assert acc.verify_device_map(single) is False


def test_autocast_warns_once_and_is_noop():
    import logging as _logging

    from accelerate_tpu import Accelerator
    from accelerate_tpu.logging import _WARNED_ONCE
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    acc = Accelerator()
    # warning_once dedups per-process: clear so earlier tests can't have
    # consumed this warning already.
    _WARNED_ONCE.clear()
    logger = _logging.getLogger("accelerate_tpu.accelerator")
    records = []
    handler = _logging.Handler()
    handler.emit = records.append
    logger.addHandler(handler)
    logger.setLevel(_logging.WARNING)
    try:
        with acc.autocast():
            pass
        first_count = len(records)
        with acc.autocast():  # once-ness: no second record
            pass
    finally:
        logger.removeHandler(handler)
    assert any("no-op" in r.getMessage() for r in records)
    assert len(records) == first_count


def test_prepare_rejects_dispatched_model():
    """Reference parity: a multi-placement dispatched model can't be prepared."""
    import jax
    import jax.numpy as jnp
    import optax
    import pytest as _pytest

    from accelerate_tpu import Accelerator, Model, dispatch_model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    ids = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % cfg.vocab_size
    model = Model.from_flax(module, jax.random.key(0), ids)
    split = dispatch_model(model, {"model": 0, "lm_head": "cpu"})
    acc = Accelerator()
    with _pytest.raises(ValueError, match="device_map"):
        acc.prepare(split, optax.sgd(1e-3))


def test_prepare_optimizer_adjacency_pairing():
    """Round-4 advisor (medium): prepare(frozen_teacher, student, tx) must
    bind tx to the *student* (nearest preceding model), not models[0]."""
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    ids = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % cfg.vocab_size
    teacher = Model.from_flax(LlamaForCausalLM(cfg), jax.random.key(0), ids)
    student = Model.from_flax(LlamaForCausalLM(cfg), jax.random.key(1), ids)
    acc = Accelerator()
    teacher, student, opt = acc.prepare(teacher, student, optax.adam(1e-3))
    # tx bound to the student's slot, leaving the teacher optimizer-less.
    assert opt._state_slot == student._state_slot
    assert acc._train_states[student._state_slot or 0].tx is not None
    t_state = acc._train_states[teacher._state_slot or 0]
    assert t_state.opt_state is None or t_state.tx is None


def test_prepare_optimizer_pairing_ambiguity_raises():
    """Two optimizers after the same model is ambiguous -> ValueError; an
    optimizer before any model -> ValueError."""
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    ids = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % cfg.vocab_size
    m = Model.from_flax(LlamaForCausalLM(cfg), jax.random.key(0), ids)
    acc = Accelerator()
    with pytest.raises(ValueError, match="ambiguous"):
        acc.prepare(m, optax.adam(1e-3), optax.sgd(1e-3))
    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    m2 = Model.from_flax(LlamaForCausalLM(cfg), jax.random.key(0), ids)
    acc2 = Accelerator()
    with pytest.raises(ValueError, match="before any model"):
        acc2.prepare(optax.adam(1e-3), m2)


def test_pp_virtual_stages_explicit_validation():
    """Round-4 advisor (low): explicit virtual_stages=0/-1 must raise, not
    silently fall back to the plain GPipe schedule."""
    from accelerate_tpu.parallel.pp import _resolve_virtual_stages

    with pytest.raises(ValueError, match="virtual_stages"):
        _resolve_virtual_stages(0)
    with pytest.raises(ValueError, match="virtual_stages"):
        _resolve_virtual_stages(-2)
    assert _resolve_virtual_stages(2) == 2


def test_cp_generate_zero_new_tokens_returns_prompt():
    """Round-4 advisor (low): max_new_tokens=0 returns the prompt unchanged
    (the documented (B, S + max_new_tokens) contract), matching generate()."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu import Model
    from accelerate_tpu.cp_generation import cp_generate
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    cfg = LlamaConfig.tiny(dtype=jnp.float32, scan_layers=True)
    ids = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % cfg.vocab_size
    model = Model.from_flax(LlamaForCausalLM(cfg), jax.random.key(0), ids)
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("cp",))
    out = cp_generate(model, ids, 0, mesh=mesh)
    assert out.shape == (2, 8)
    np.testing.assert_array_equal(np.asarray(out), ids)


def test_scheduler_get_last_lr_fallbacks():
    """Round-4 VERDICT weak#7: get_last_lr must report a value for constant
    lrs and optax-chain-embedded (inject_hyperparams) schedules, not None."""
    import optax

    from accelerate_tpu.scheduler import AcceleratedScheduler, extract_lr_info
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    from accelerate_tpu import Accelerator

    Accelerator()  # AcceleratorState for num_processes

    # callable schedule: evaluated at the wrapper count
    sched = AcceleratedScheduler(optax.linear_schedule(1e-3, 0.0, 100))
    assert sched.get_last_lr() == pytest.approx(1e-3)
    # constant lr
    assert AcceleratedScheduler(3e-4).get_last_lr() == pytest.approx(3e-4)

    # embedded in the chain via inject_hyperparams: read from opt_state
    tx = optax.inject_hyperparams(optax.sgd)(
        learning_rate=optax.linear_schedule(2e-3, 0.0, 10)
    )
    import jax.numpy as jnp

    params = {"w": jnp.ones((2,))}
    state = tx.init(params)
    info = extract_lr_info(state)
    assert info.get("lr") == pytest.approx(2e-3)

    class FakeOpt:
        pass

    opt = FakeOpt()
    opt.state = state
    wrapped = AcceleratedScheduler(object(), optimizers=[opt])
    assert wrapped.get_last_lr() == pytest.approx(2e-3)
