"""Fault-tolerance subsystem: atomic verified checkpoints, torn-checkpoint
skip on load, save retry/backoff + fallback, preemption auto-save, and the
divergence sentinel (fault_tolerance.py)."""

import json
import os
import shutil
import signal

import numpy as np
import pytest


def _reset_state():
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()


def _setup(tmpdir, kwargs_handlers=None, total_limit=None):
    import jax
    import optax
    import flax.linen as nn

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.utils import ProjectConfiguration, set_seed

    _reset_state()
    set_seed(3)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(1)(x)

    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmpdir),
            automatic_checkpoint_naming=True,
            total_limit=total_limit,
        ),
        kwargs_handlers=kwargs_handlers,
    )
    module = Net()
    x = np.random.default_rng(0).normal(size=(32, 8)).astype(np.float32)
    y = x.sum(-1, keepdims=True).astype(np.float32)
    model = Model.from_flax(module, jax.random.key(0), x[:1])
    model, opt = acc.prepare(model, optax.adam(1e-2))

    def loss_fn(params, batch):
        import jax.numpy as jnp

        pred = module.apply({"params": params}, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    from jax.sharding import NamedSharding, PartitionSpec

    sh = NamedSharding(acc.mesh, PartitionSpec())
    batch = {"x": jax.device_put(x, sh), "y": jax.device_put(y, sh)}
    return acc, loss_fn, batch


def _ft(**kw):
    from accelerate_tpu.utils import FaultToleranceKwargs

    kw.setdefault("sentinel", "off")
    return FaultToleranceKwargs(**kw)


# ---------------------------------------------------------------------------
# Manifest + atomic commit
# ---------------------------------------------------------------------------


def test_manifest_roundtrip_and_corruption(tmp_path):
    from accelerate_tpu.fault_tolerance import verify_checkpoint, write_manifest

    d = tmp_path / "ck"
    (d / "sub").mkdir(parents=True)
    (d / "a.bin").write_bytes(b"hello world")
    (d / "sub" / "b.bin").write_bytes(b"\x00" * 128)
    manifest = write_manifest(str(d), step=7, world_size=2)
    assert manifest["step"] == 7 and manifest["world_size"] == 2
    assert set(manifest["files"]) == {"a.bin", os.path.join("sub", "b.bin")}
    ok, reason = verify_checkpoint(str(d))
    assert ok, reason

    # Same-size corruption is only caught by the checksum...
    (d / "a.bin").write_bytes(b"hello w0rld")
    ok, reason = verify_checkpoint(str(d))
    assert not ok and "checksum mismatch" in reason
    # ... and ignored in size-only mode.
    ok, _ = verify_checkpoint(str(d), check_hashes=False)
    assert ok

    (d / "a.bin").unlink()
    ok, reason = verify_checkpoint(str(d))
    assert not ok and "missing file" in reason

    shutil.rmtree(d)
    d.mkdir()
    assert verify_checkpoint(str(d)) == (False, "no-manifest")


def test_atomic_save_layout_vs_default_off(tmp_path):
    # Fault tolerance ON: committed dir carries a verifying manifest and no
    # staging leftovers.
    acc, loss_fn, batch = _setup(tmp_path / "ft", kwargs_handlers=[_ft()])
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    d0 = acc.save_state()
    from accelerate_tpu.fault_tolerance import verify_checkpoint

    assert os.path.basename(d0) == "checkpoint_0"
    assert verify_checkpoint(d0) == (True, "ok")
    base = os.path.dirname(d0)
    assert not any(f.endswith(".tmp") for f in os.listdir(base))
    manifest = json.load(open(os.path.join(d0, "manifest.json")))
    assert manifest["step"] == 1
    assert "model.safetensors" in manifest["files"]
    assert "optimizer.bin" in manifest["files"]

    # Default OFF: byte layout unchanged — no manifest, no staging.
    acc2, loss_fn2, batch2 = _setup(tmp_path / "off")
    step2 = acc2.prepare_train_step(loss_fn2)
    step2(acc2.train_state, batch2)
    d1 = acc2.save_state()
    assert not os.path.exists(os.path.join(d1, "manifest.json"))
    assert not any(f.endswith(".tmp") for f in os.listdir(os.path.dirname(d1)))


def test_torn_checkpoint_skipped_on_load(tmp_path):
    """Kill-during-save simulation: a deliberately torn staging dir plus a
    corrupted newest checkpoint — load resolves the older verified one and
    telemetry records the skip."""
    from accelerate_tpu.utils import TelemetryKwargs

    acc, loss_fn, batch = _setup(
        tmp_path,
        kwargs_handlers=[_ft(), TelemetryKwargs(log_every=0, straggler_probe_every=0)],
    )
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    d0 = acc.save_state()
    good_params = {
        k: np.asarray(v)
        for k, v in enumerate_leaves(acc.train_state.params)
    }
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    d1 = acc.save_state()

    # Tear the newest commit (bit corruption inside a listed file)...
    with open(os.path.join(d1, "optimizer.bin"), "r+b") as f:
        f.write(b"\xff\xff\xff\xff")
    # ... and fake an interrupted staging dir from a killed save.
    torn = os.path.join(os.path.dirname(d1), "checkpoint_7.tmp")
    os.makedirs(torn)
    open(os.path.join(torn, "model.safetensors"), "wb").write(b"partial")

    loaded = acc.load_state()
    assert loaded == d0, (loaded, d0)
    for k, v in enumerate_leaves(acc.train_state.params):
        np.testing.assert_allclose(np.asarray(v), good_params[k], rtol=1e-6)

    acc.end_training()
    tel = os.path.join(str(tmp_path), "telemetry", "rank_0.jsonl")
    events = [json.loads(line) for line in open(tel)]
    skips = [e for e in events if e["event"] == "checkpoint_torn_skipped"]
    assert len(skips) == 1 and skips[0]["dir"] == d1
    summary = events[-1]
    assert summary["event"] == "summary"
    ck = summary["checkpoint"]
    assert ck["torn_skipped"] == 1 and ck["saves"] == 2 and ck["loads"] == 1
    assert ck["save_s"] > 0 and ck["verify_s"] > 0


def enumerate_leaves(tree, prefix=""):
    import jax

    return [
        (jax.tree_util.keystr(path), leaf)
        for path, leaf in jax.tree_util.tree_leaves_with_path(tree)
    ]


def test_explicit_torn_dir_refused(tmp_path):
    """load_state(explicit_path) on a torn checkpoint raises BEFORE touching
    any state (the automatic resolver would have fallen back instead)."""
    acc, loss_fn, batch = _setup(tmp_path, kwargs_handlers=[_ft()])
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    d0 = acc.save_state()
    with open(os.path.join(d0, "optimizer.bin"), "r+b") as f:
        f.write(b"\x00\x00\x00\x00")
    with pytest.raises(RuntimeError, match="torn checkpoint"):
        acc.load_state(d0)


def test_elastic_resume_starts_fresh_with_only_staging_dir(tmp_path, monkeypatch):
    """A restart whose only artifact is an interrupted .tmp staging dir must
    start fresh (warning), not crash load_state on an empty resolver."""
    base = tmp_path / "checkpoints" / "checkpoint_0.tmp"
    base.mkdir(parents=True)
    (base / "model.safetensors").write_bytes(b"partial")
    monkeypatch.setenv("ACCELERATE_RESTART_ATTEMPT", "1")

    import jax
    import optax
    import flax.linen as nn

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.utils import ProjectConfiguration, set_seed

    _reset_state()
    set_seed(0)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(x)

    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path),
            automatic_checkpoint_naming=True,
            automatic_resume=True,
        ),
        kwargs_handlers=[_ft()],
    )
    model = Model.from_flax(Net(), jax.random.key(0), np.zeros((2, 4), np.float32))
    acc.prepare(model, optax.adam(1e-2))  # must not raise
    assert int(np.asarray(acc.train_state.step)) == 0
    acc.end_training()


def test_interrupted_atomic_save_never_selected(tmp_path):
    """The acceptance contract: a save killed before manifest commit leaves
    only a .tmp staging dir, which the load resolver never selects — even
    with verification disabled."""
    acc, loss_fn, batch = _setup(tmp_path, kwargs_handlers=[_ft()])
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    d0 = acc.save_state()
    # Simulate a kill mid-save of checkpoint_1: staging exists, commit never
    # happened.
    staging = os.path.join(os.path.dirname(d0), "checkpoint_1.tmp")
    shutil.copytree(d0, staging)
    os.remove(os.path.join(staging, "manifest.json"))
    assert acc.load_state() == d0


# ---------------------------------------------------------------------------
# Satellite fixes: non-numeric dirs, missing optimizer.bin
# ---------------------------------------------------------------------------


def test_nonnumeric_checkpoint_entries_skipped_without_ft(tmp_path):
    """The load resolver and the total_limit pruner both used
    int(f.split('_')[1]) and crashed on stray dirs — with NO fault-tolerance
    handler they must now skip them."""
    acc, loss_fn, batch = _setup(tmp_path, total_limit=2)
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    base = os.path.join(str(tmp_path), "checkpoints")
    os.makedirs(os.path.join(base, "checkpoint_tmp"))
    os.makedirs(os.path.join(base, "checkpoint_3.tmp"))
    d0 = acc.save_state()
    d1 = acc.save_state()
    d2 = acc.save_state()  # pruning walks the stray entries without crashing
    names = sorted(os.listdir(base))
    assert "checkpoint_tmp" in names and "checkpoint_3.tmp" in names
    assert [n for n in names if n in ("checkpoint_1", "checkpoint_2")] == [
        "checkpoint_1", "checkpoint_2",
    ]
    assert not os.path.exists(d0)  # pruned (total_limit=2)
    assert acc.load_state() == d2


def test_missing_optimizer_bin_descriptive_error(tmp_path):
    acc, loss_fn, batch = _setup(tmp_path)
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    d0 = acc.save_state()
    os.remove(os.path.join(d0, "optimizer.bin"))
    with pytest.raises(FileNotFoundError, match=r"optimizer\.bin.*FaultToleranceKwargs"):
        acc.load_state(d0)


# ---------------------------------------------------------------------------
# Save retry / fallback / pruning-after-commit
# ---------------------------------------------------------------------------


def test_failed_save_cannot_destroy_only_good_checkpoint(tmp_path, monkeypatch):
    """total_limit=1 + a save that dies mid-write: legacy pruning would have
    already deleted the only good checkpoint; atomic saves prune only after
    the commit."""
    from accelerate_tpu.fault_tolerance import CheckpointSaveError, verify_checkpoint

    acc, loss_fn, batch = _setup(
        tmp_path, kwargs_handlers=[_ft(save_retries=0)], total_limit=1
    )
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    d0 = acc.save_state()
    assert verify_checkpoint(d0) == (True, "ok")

    import accelerate_tpu.checkpointing as ckpt_mod

    def boom(*a, **kw):
        raise OSError("disk on fire")

    monkeypatch.setattr(ckpt_mod, "save_sharded_safetensors", boom)
    with pytest.raises(CheckpointSaveError):
        acc.save_state()
    # The only good checkpoint survived the failed save AND no staging
    # leftovers remain.
    assert verify_checkpoint(d0) == (True, "ok")
    assert not any(f.endswith(".tmp") for f in os.listdir(os.path.dirname(d0)))
    assert acc.load_state() == d0


def test_save_retry_then_success(tmp_path, monkeypatch):
    from accelerate_tpu.utils import TelemetryKwargs

    acc, loss_fn, batch = _setup(
        tmp_path,
        kwargs_handlers=[
            _ft(save_retries=3, retry_backoff_s=0.01, retry_backoff_max_s=0.02),
            TelemetryKwargs(log_every=0, straggler_probe_every=0),
        ],
    )
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state

    import accelerate_tpu.checkpointing as ckpt_mod

    real = ckpt_mod.save_sharded_safetensors
    calls = {"n": 0}

    def flaky(*a, **kw):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError("transient storage hiccup")
        return real(*a, **kw)

    monkeypatch.setattr(ckpt_mod, "save_sharded_safetensors", flaky)
    d0 = acc.save_state()
    from accelerate_tpu.fault_tolerance import verify_checkpoint

    assert verify_checkpoint(d0) == (True, "ok")
    assert calls["n"] == 3
    assert acc.fault_tolerance.save_retries_total == 2
    acc.end_training()
    tel = os.path.join(str(tmp_path), "telemetry", "rank_0.jsonl")
    events = [json.loads(line) for line in open(tel)]
    assert sum(e["event"] == "checkpoint_save_retry" for e in events) == 2
    assert events[-1]["checkpoint"]["retries"] == 2


def test_fallback_dir_after_retries_exhausted(tmp_path, monkeypatch):
    from accelerate_tpu.fault_tolerance import verify_checkpoint

    fallback = str(tmp_path / "fallback")
    acc, loss_fn, batch = _setup(
        tmp_path / "primary",
        kwargs_handlers=[
            _ft(save_retries=1, retry_backoff_s=0.01, fallback_dir=fallback)
        ],
    )
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state

    import accelerate_tpu.checkpointing as ckpt_mod

    real = ckpt_mod.save_sharded_safetensors
    primary_base = os.path.join(str(tmp_path / "primary"), "checkpoints")

    def primary_dead(flat, out_dir, **kw):
        if os.path.abspath(out_dir).startswith(os.path.abspath(primary_base)):
            raise OSError("primary volume gone")
        return real(flat, out_dir, **kw)

    monkeypatch.setattr(ckpt_mod, "save_sharded_safetensors", primary_dead)
    out = acc.save_state()
    assert os.path.abspath(out).startswith(os.path.abspath(fallback))
    assert os.path.basename(out) == "checkpoint_0"
    assert verify_checkpoint(out) == (True, "ok")


# ---------------------------------------------------------------------------
# Preemption auto-save
# ---------------------------------------------------------------------------


def test_preemption_signal_flag_save_and_resume(tmp_path, monkeypatch):
    """SIGUSR1 (the in-process-safe preemption signal) sets the flag, the
    save while preempted records a preemption_save event, and a restart
    (ACCELERATE_RESTART_ATTEMPT=1 + automatic_resume) resumes at exactly the
    preemption-save step — zero lost steps past the last commit."""
    from accelerate_tpu.utils import ProjectConfiguration, TelemetryKwargs
    from accelerate_tpu.utils.constants import PREEMPTION_EXIT_CODE

    # Earlier tests' accelerators may have left their handlers installed
    # (install happens at prepare(); only end_training/close restores) —
    # pin a known baseline so the restore assertion below is meaningful.
    signal.signal(signal.SIGUSR1, signal.SIG_DFL)
    acc, loss_fn, batch = _setup(
        tmp_path,
        kwargs_handlers=[_ft(), TelemetryKwargs(log_every=0, straggler_probe_every=0)],
    )
    acc.project_configuration.automatic_resume = True
    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    assert not acc.should_checkpoint() and not acc.check_preemption()

    state, _ = step(state, batch)
    state, _ = step(state, batch)
    acc._train_state = state
    os.kill(os.getpid(), signal.SIGUSR1)
    assert acc.should_checkpoint()
    assert acc.check_preemption()
    assert acc.fault_tolerance.preemption_signal == "SIGUSR1"
    assert acc.preemption_exit_code == PREEMPTION_EXIT_CODE
    saved_step = int(np.asarray(state.step))
    acc.save_state()
    acc.end_training()  # drains: handlers restored
    assert signal.getsignal(signal.SIGUSR1) in (signal.SIG_DFL, signal.Handlers.SIG_DFL)

    tel = os.path.join(str(tmp_path), "telemetry", "rank_0.jsonl")
    events = [json.loads(line) for line in open(tel)]
    pre = [e for e in events if e["event"] == "preemption_save"]
    assert len(pre) == 1 and pre[0]["signal"] == "SIGUSR1"
    assert events[-1]["checkpoint"]["preemption_saves"] == 1

    # Relaunch analog: fresh process state + restart attempt env.
    import jax
    import optax
    import flax.linen as nn

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.utils import set_seed

    _reset_state()
    monkeypatch.setenv("ACCELERATE_RESTART_ATTEMPT", "1")
    set_seed(3)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(1)(x)

    acc2 = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path),
            automatic_checkpoint_naming=True,
            automatic_resume=True,
        ),
        kwargs_handlers=[_ft()],
    )
    x = np.random.default_rng(0).normal(size=(32, 8)).astype(np.float32)
    model2 = Model.from_flax(Net(), jax.random.key(0), x[:1])
    acc2.prepare(model2, optax.adam(1e-2))
    assert int(np.asarray(acc2.train_state.step)) == saved_step
    acc2.end_training()


# ---------------------------------------------------------------------------
# Divergence sentinel
# ---------------------------------------------------------------------------


def test_sentinel_unit_streaks():
    from accelerate_tpu.fault_tolerance import DivergenceSentinel

    s = DivergenceSentinel(window=3, explode_factor=10.0, ema_alpha=0.5)
    assert s.observe(1.0, 0.5) == ("ok", "")
    # Two bad steps stay below the window...
    assert s.observe(float("nan"), 0.5)[0] == "warn"
    assert s.observe(1.0e9, 0.5)[0] == "warn"  # explosion vs EMA ~1.0
    # ... a good step resets the streak ...
    assert s.observe(1.1, 0.5)[0] == "ok"
    assert s.streak == 0
    # ... three consecutive trip it.
    assert s.observe(float("inf"), 0.5)[0] == "warn"
    assert s.observe(1.0, float("nan"))[0] == "warn"  # nonfinite grad norm
    verdict, reason = s.observe(float("nan"), 0.5)
    assert verdict == "trip" and "nonfinite" in reason
    # EMA never absorbed the bad samples.
    assert s.ema_loss == pytest.approx(1.05)


def test_sentinel_warn_policy_keeps_training(tmp_path):
    acc, loss_fn, batch = _setup(
        tmp_path, kwargs_handlers=[_ft(sentinel="warn", sentinel_window=2)]
    )
    ft = acc.fault_tolerance
    bad = {"loss": np.float32("nan"), "grad_norm": np.float32(1.0)}
    # Lagged evaluation: call N sees call N-1's metrics.
    for _ in range(4):
        assert ft.observe_step(bad) is None
    assert ft.sentinel.episode_warned


def test_sentinel_halt_policy_raises_through_step(tmp_path):
    """Integration: a nonfinite loss produced by the real jitted step trips
    the sentinel (one step lagged) and policy 'halt' raises."""
    import jax

    from accelerate_tpu.fault_tolerance import DivergenceError

    acc, loss_fn, batch = _setup(
        tmp_path, kwargs_handlers=[_ft(sentinel="halt", sentinel_window=1)]
    )
    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    state, _ = step(state, batch)
    poisoned = dict(batch)
    poisoned["x"] = batch["x"] * np.float32("nan")
    state, _ = step(state, poisoned)  # bad metrics become pending here
    with pytest.raises(DivergenceError, match="diverged"):
        step(state, batch)  # lagged fetch evaluates the poisoned step


def test_sentinel_rollback_restores_verified_checkpoint(tmp_path):
    from accelerate_tpu.fault_tolerance import DivergenceError

    acc, loss_fn, batch = _setup(
        tmp_path,
        kwargs_handlers=[_ft(sentinel="rollback", sentinel_window=2, max_rollbacks=1)],
    )
    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    state, _ = step(state, batch)
    state, _ = step(state, batch)
    acc._train_state = state
    ckpt = acc.save_state()
    want = {k: np.asarray(v) for k, v in enumerate_leaves(acc.train_state.params)}
    saved_step = int(np.asarray(state.step))

    ft = acc.fault_tolerance
    bad = {"loss": np.float32("inf"), "grad_norm": np.float32(1.0)}
    ft.observe_step(bad)  # becomes pending
    assert ft.observe_step(bad) is None  # streak 1 (lagged)
    restored = ft.observe_step(bad)  # streak 2 == window -> rollback
    assert restored is not None
    assert int(np.asarray(restored.step)) == saved_step
    for k, v in enumerate_leaves(restored.params):
        np.testing.assert_allclose(np.asarray(v), want[k], rtol=1e-6)
    assert ft.rollbacks_done == 1

    # Second divergence exhausts max_rollbacks -> escalates to halt.
    ft.observe_step(bad)
    ft.observe_step(bad)
    with pytest.raises(DivergenceError, match="max_rollbacks"):
        ft.observe_step(bad)


def test_save_state_pre_hook_rides_atomic_commit(tmp_path):
    """Pre-save hooks write into the STAGING dir under atomic saves; their
    sidecar files must land in the committed checkpoint AND in the manifest
    (not be wiped as stale staging)."""
    from accelerate_tpu.fault_tolerance import verify_checkpoint

    acc, loss_fn, batch = _setup(tmp_path, kwargs_handlers=[_ft()])
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state

    def hook(models, train_state, out_dir):
        with open(os.path.join(out_dir, "sidecar.json"), "w") as f:
            json.dump({"note": "written by pre-hook"}, f)

    acc.register_save_state_pre_hook(hook)
    d0 = acc.save_state()
    assert os.path.exists(os.path.join(d0, "sidecar.json"))
    manifest = json.load(open(os.path.join(d0, "manifest.json")))
    assert "sidecar.json" in manifest["files"]
    assert verify_checkpoint(d0) == (True, "ok")


def test_kwargs_validation():
    from accelerate_tpu.utils import FaultToleranceKwargs

    with pytest.raises(ValueError):
        FaultToleranceKwargs(checksum="md5")
    with pytest.raises(ValueError):
        FaultToleranceKwargs(sentinel="panic")
    with pytest.raises(ValueError):
        FaultToleranceKwargs(sentinel_window=0)
