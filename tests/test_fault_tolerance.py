"""Fault-tolerance subsystem: atomic verified checkpoints, torn-checkpoint
skip on load, save retry/backoff + fallback, preemption auto-save, and the
divergence sentinel (fault_tolerance.py)."""

import json
import os
import shutil
import signal

import numpy as np
import pytest


def _reset_state():
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()


def _setup(tmpdir, kwargs_handlers=None, total_limit=None):
    import jax
    import optax
    import flax.linen as nn

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.utils import ProjectConfiguration, set_seed

    _reset_state()
    set_seed(3)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(1)(x)

    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmpdir),
            automatic_checkpoint_naming=True,
            total_limit=total_limit,
        ),
        kwargs_handlers=kwargs_handlers,
    )
    module = Net()
    x = np.random.default_rng(0).normal(size=(32, 8)).astype(np.float32)
    y = x.sum(-1, keepdims=True).astype(np.float32)
    model = Model.from_flax(module, jax.random.key(0), x[:1])
    model, opt = acc.prepare(model, optax.adam(1e-2))

    def loss_fn(params, batch):
        import jax.numpy as jnp

        pred = module.apply({"params": params}, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    from jax.sharding import NamedSharding, PartitionSpec

    sh = NamedSharding(acc.mesh, PartitionSpec())
    batch = {"x": jax.device_put(x, sh), "y": jax.device_put(y, sh)}
    return acc, loss_fn, batch


def _ft(**kw):
    from accelerate_tpu.utils import FaultToleranceKwargs

    kw.setdefault("sentinel", "off")
    return FaultToleranceKwargs(**kw)


# ---------------------------------------------------------------------------
# Manifest + atomic commit
# ---------------------------------------------------------------------------


def test_manifest_roundtrip_and_corruption(tmp_path):
    from accelerate_tpu.fault_tolerance import verify_checkpoint, write_manifest

    d = tmp_path / "ck"
    (d / "sub").mkdir(parents=True)
    (d / "a.bin").write_bytes(b"hello world")
    (d / "sub" / "b.bin").write_bytes(b"\x00" * 128)
    manifest = write_manifest(str(d), step=7, world_size=2)
    assert manifest["step"] == 7 and manifest["world_size"] == 2
    assert set(manifest["files"]) == {"a.bin", os.path.join("sub", "b.bin")}
    ok, reason = verify_checkpoint(str(d))
    assert ok, reason

    # Same-size corruption is only caught by the checksum...
    (d / "a.bin").write_bytes(b"hello w0rld")
    ok, reason = verify_checkpoint(str(d))
    assert not ok and "checksum mismatch" in reason
    # ... and ignored in size-only mode.
    ok, _ = verify_checkpoint(str(d), check_hashes=False)
    assert ok

    (d / "a.bin").unlink()
    ok, reason = verify_checkpoint(str(d))
    assert not ok and "missing file" in reason

    shutil.rmtree(d)
    d.mkdir()
    assert verify_checkpoint(str(d)) == (False, "no-manifest")


def test_atomic_save_layout_vs_default_off(tmp_path):
    # Fault tolerance ON: committed dir carries a verifying manifest and no
    # staging leftovers.
    acc, loss_fn, batch = _setup(tmp_path / "ft", kwargs_handlers=[_ft()])
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    d0 = acc.save_state()
    from accelerate_tpu.fault_tolerance import verify_checkpoint

    assert os.path.basename(d0) == "checkpoint_0"
    assert verify_checkpoint(d0) == (True, "ok")
    base = os.path.dirname(d0)
    assert not any(f.endswith(".tmp") for f in os.listdir(base))
    manifest = json.load(open(os.path.join(d0, "manifest.json")))
    assert manifest["step"] == 1
    assert "model.safetensors" in manifest["files"]
    assert "optimizer.bin" in manifest["files"]

    # Default OFF: byte layout unchanged — no manifest, no staging.
    acc2, loss_fn2, batch2 = _setup(tmp_path / "off")
    step2 = acc2.prepare_train_step(loss_fn2)
    step2(acc2.train_state, batch2)
    d1 = acc2.save_state()
    assert not os.path.exists(os.path.join(d1, "manifest.json"))
    assert not any(f.endswith(".tmp") for f in os.listdir(os.path.dirname(d1)))


def test_torn_checkpoint_skipped_on_load(tmp_path):
    """Kill-during-save simulation: a deliberately torn staging dir plus a
    corrupted newest checkpoint — load resolves the older verified one and
    telemetry records the skip."""
    from accelerate_tpu.utils import TelemetryKwargs

    acc, loss_fn, batch = _setup(
        tmp_path,
        kwargs_handlers=[_ft(), TelemetryKwargs(log_every=0, straggler_probe_every=0)],
    )
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    d0 = acc.save_state()
    good_params = {
        k: np.asarray(v)
        for k, v in enumerate_leaves(acc.train_state.params)
    }
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    d1 = acc.save_state()

    # Tear the newest commit (bit corruption inside a listed file)...
    with open(os.path.join(d1, "optimizer.bin"), "r+b") as f:
        f.write(b"\xff\xff\xff\xff")
    # ... and fake an interrupted staging dir from a killed save.
    torn = os.path.join(os.path.dirname(d1), "checkpoint_7.tmp")
    os.makedirs(torn)
    open(os.path.join(torn, "model.safetensors"), "wb").write(b"partial")

    loaded = acc.load_state()
    assert loaded == d0, (loaded, d0)
    for k, v in enumerate_leaves(acc.train_state.params):
        np.testing.assert_allclose(np.asarray(v), good_params[k], rtol=1e-6)

    acc.end_training()
    tel = os.path.join(str(tmp_path), "telemetry", "rank_0.jsonl")
    events = [json.loads(line) for line in open(tel)]
    skips = [e for e in events if e["event"] == "checkpoint_torn_skipped"]
    assert len(skips) == 1 and skips[0]["dir"] == d1
    summary = events[-1]
    assert summary["event"] == "summary"
    ck = summary["checkpoint"]
    assert ck["torn_skipped"] == 1 and ck["saves"] == 2 and ck["loads"] == 1
    assert ck["save_s"] > 0 and ck["verify_s"] > 0


def enumerate_leaves(tree, prefix=""):
    import jax

    return [
        (jax.tree_util.keystr(path), leaf)
        for path, leaf in jax.tree_util.tree_leaves_with_path(tree)
    ]


def test_explicit_torn_dir_refused(tmp_path):
    """load_state(explicit_path) on a torn checkpoint raises BEFORE touching
    any state (the automatic resolver would have fallen back instead)."""
    acc, loss_fn, batch = _setup(tmp_path, kwargs_handlers=[_ft()])
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    d0 = acc.save_state()
    with open(os.path.join(d0, "optimizer.bin"), "r+b") as f:
        f.write(b"\x00\x00\x00\x00")
    with pytest.raises(RuntimeError, match="torn checkpoint"):
        acc.load_state(d0)


def test_elastic_resume_starts_fresh_with_only_staging_dir(tmp_path, monkeypatch):
    """A restart whose only artifact is an interrupted .tmp staging dir must
    start fresh (warning), not crash load_state on an empty resolver."""
    base = tmp_path / "checkpoints" / "checkpoint_0.tmp"
    base.mkdir(parents=True)
    (base / "model.safetensors").write_bytes(b"partial")
    monkeypatch.setenv("ACCELERATE_RESTART_ATTEMPT", "1")

    import jax
    import optax
    import flax.linen as nn

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.utils import ProjectConfiguration, set_seed

    _reset_state()
    set_seed(0)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(x)

    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path),
            automatic_checkpoint_naming=True,
            automatic_resume=True,
        ),
        kwargs_handlers=[_ft()],
    )
    model = Model.from_flax(Net(), jax.random.key(0), np.zeros((2, 4), np.float32))
    acc.prepare(model, optax.adam(1e-2))  # must not raise
    assert int(np.asarray(acc.train_state.step)) == 0
    acc.end_training()


def test_interrupted_atomic_save_never_selected(tmp_path):
    """The acceptance contract: a save killed before manifest commit leaves
    only a .tmp staging dir, which the load resolver never selects — even
    with verification disabled."""
    acc, loss_fn, batch = _setup(tmp_path, kwargs_handlers=[_ft()])
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    d0 = acc.save_state()
    # Simulate a kill mid-save of checkpoint_1: staging exists, commit never
    # happened.
    staging = os.path.join(os.path.dirname(d0), "checkpoint_1.tmp")
    shutil.copytree(d0, staging)
    os.remove(os.path.join(staging, "manifest.json"))
    assert acc.load_state() == d0


# ---------------------------------------------------------------------------
# Satellite fixes: non-numeric dirs, missing optimizer.bin
# ---------------------------------------------------------------------------


def test_nonnumeric_checkpoint_entries_skipped_without_ft(tmp_path):
    """The load resolver and the total_limit pruner both used
    int(f.split('_')[1]) and crashed on stray dirs — with NO fault-tolerance
    handler they must now skip them."""
    acc, loss_fn, batch = _setup(tmp_path, total_limit=2)
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    base = os.path.join(str(tmp_path), "checkpoints")
    os.makedirs(os.path.join(base, "checkpoint_tmp"))
    os.makedirs(os.path.join(base, "checkpoint_3.tmp"))
    d0 = acc.save_state()
    d1 = acc.save_state()
    d2 = acc.save_state()  # pruning walks the stray entries without crashing
    names = sorted(os.listdir(base))
    assert "checkpoint_tmp" in names and "checkpoint_3.tmp" in names
    assert [n for n in names if n in ("checkpoint_1", "checkpoint_2")] == [
        "checkpoint_1", "checkpoint_2",
    ]
    assert not os.path.exists(d0)  # pruned (total_limit=2)
    assert acc.load_state() == d2


def test_missing_optimizer_bin_descriptive_error(tmp_path):
    acc, loss_fn, batch = _setup(tmp_path)
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    d0 = acc.save_state()
    os.remove(os.path.join(d0, "optimizer.bin"))
    with pytest.raises(FileNotFoundError, match=r"optimizer\.bin.*FaultToleranceKwargs"):
        acc.load_state(d0)


# ---------------------------------------------------------------------------
# Save retry / fallback / pruning-after-commit
# ---------------------------------------------------------------------------


def test_failed_save_cannot_destroy_only_good_checkpoint(tmp_path, monkeypatch):
    """total_limit=1 + a save that dies mid-write: legacy pruning would have
    already deleted the only good checkpoint; atomic saves prune only after
    the commit."""
    from accelerate_tpu.fault_tolerance import CheckpointSaveError, verify_checkpoint

    acc, loss_fn, batch = _setup(
        tmp_path, kwargs_handlers=[_ft(save_retries=0)], total_limit=1
    )
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    d0 = acc.save_state()
    assert verify_checkpoint(d0) == (True, "ok")

    import accelerate_tpu.checkpointing as ckpt_mod

    def boom(*a, **kw):
        raise OSError("disk on fire")

    monkeypatch.setattr(ckpt_mod, "save_sharded_safetensors", boom)
    with pytest.raises(CheckpointSaveError):
        acc.save_state()
    # The only good checkpoint survived the failed save AND no staging
    # leftovers remain.
    assert verify_checkpoint(d0) == (True, "ok")
    assert not any(f.endswith(".tmp") for f in os.listdir(os.path.dirname(d0)))
    assert acc.load_state() == d0


def test_save_retry_then_success(tmp_path, monkeypatch):
    from accelerate_tpu.utils import TelemetryKwargs

    acc, loss_fn, batch = _setup(
        tmp_path,
        kwargs_handlers=[
            _ft(save_retries=3, retry_backoff_s=0.01, retry_backoff_max_s=0.02),
            TelemetryKwargs(log_every=0, straggler_probe_every=0),
        ],
    )
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state

    import accelerate_tpu.checkpointing as ckpt_mod

    real = ckpt_mod.save_sharded_safetensors
    calls = {"n": 0}

    def flaky(*a, **kw):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError("transient storage hiccup")
        return real(*a, **kw)

    monkeypatch.setattr(ckpt_mod, "save_sharded_safetensors", flaky)
    d0 = acc.save_state()
    from accelerate_tpu.fault_tolerance import verify_checkpoint

    assert verify_checkpoint(d0) == (True, "ok")
    assert calls["n"] == 3
    assert acc.fault_tolerance.save_retries_total == 2
    acc.end_training()
    tel = os.path.join(str(tmp_path), "telemetry", "rank_0.jsonl")
    events = [json.loads(line) for line in open(tel)]
    assert sum(e["event"] == "checkpoint_save_retry" for e in events) == 2
    assert events[-1]["checkpoint"]["retries"] == 2


def test_fallback_dir_after_retries_exhausted(tmp_path, monkeypatch):
    from accelerate_tpu.fault_tolerance import verify_checkpoint

    fallback = str(tmp_path / "fallback")
    acc, loss_fn, batch = _setup(
        tmp_path / "primary",
        kwargs_handlers=[
            _ft(save_retries=1, retry_backoff_s=0.01, fallback_dir=fallback)
        ],
    )
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state

    import accelerate_tpu.checkpointing as ckpt_mod

    real = ckpt_mod.save_sharded_safetensors
    primary_base = os.path.join(str(tmp_path / "primary"), "checkpoints")

    def primary_dead(flat, out_dir, **kw):
        if os.path.abspath(out_dir).startswith(os.path.abspath(primary_base)):
            raise OSError("primary volume gone")
        return real(flat, out_dir, **kw)

    monkeypatch.setattr(ckpt_mod, "save_sharded_safetensors", primary_dead)
    out = acc.save_state()
    assert os.path.abspath(out).startswith(os.path.abspath(fallback))
    assert os.path.basename(out) == "checkpoint_0"
    assert verify_checkpoint(out) == (True, "ok")


# ---------------------------------------------------------------------------
# Preemption auto-save
# ---------------------------------------------------------------------------


def test_preemption_signal_flag_save_and_resume(tmp_path, monkeypatch):
    """SIGUSR1 (the in-process-safe preemption signal) sets the flag, the
    save while preempted records a preemption_save event, and a restart
    (ACCELERATE_RESTART_ATTEMPT=1 + automatic_resume) resumes at exactly the
    preemption-save step — zero lost steps past the last commit."""
    from accelerate_tpu.utils import ProjectConfiguration, TelemetryKwargs
    from accelerate_tpu.utils.constants import PREEMPTION_EXIT_CODE

    # Earlier tests' accelerators may have left their handlers installed
    # (install happens at prepare(); only end_training/close restores) —
    # pin a known baseline so the restore assertion below is meaningful.
    signal.signal(signal.SIGUSR1, signal.SIG_DFL)
    acc, loss_fn, batch = _setup(
        tmp_path,
        kwargs_handlers=[_ft(), TelemetryKwargs(log_every=0, straggler_probe_every=0)],
    )
    acc.project_configuration.automatic_resume = True
    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    assert not acc.should_checkpoint() and not acc.check_preemption()

    state, _ = step(state, batch)
    state, _ = step(state, batch)
    acc._train_state = state
    os.kill(os.getpid(), signal.SIGUSR1)
    assert acc.should_checkpoint()
    assert acc.check_preemption()
    assert acc.fault_tolerance.preemption_signal == "SIGUSR1"
    assert acc.preemption_exit_code == PREEMPTION_EXIT_CODE
    saved_step = int(np.asarray(state.step))
    acc.save_state()
    acc.end_training()  # drains: handlers restored
    assert signal.getsignal(signal.SIGUSR1) in (signal.SIG_DFL, signal.Handlers.SIG_DFL)

    tel = os.path.join(str(tmp_path), "telemetry", "rank_0.jsonl")
    events = [json.loads(line) for line in open(tel)]
    pre = [e for e in events if e["event"] == "preemption_save"]
    assert len(pre) == 1 and pre[0]["signal"] == "SIGUSR1"
    assert events[-1]["checkpoint"]["preemption_saves"] == 1

    # Relaunch analog: fresh process state + restart attempt env.
    import jax
    import optax
    import flax.linen as nn

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.utils import set_seed

    _reset_state()
    monkeypatch.setenv("ACCELERATE_RESTART_ATTEMPT", "1")
    set_seed(3)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(1)(x)

    acc2 = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path),
            automatic_checkpoint_naming=True,
            automatic_resume=True,
        ),
        kwargs_handlers=[_ft()],
    )
    x = np.random.default_rng(0).normal(size=(32, 8)).astype(np.float32)
    model2 = Model.from_flax(Net(), jax.random.key(0), x[:1])
    acc2.prepare(model2, optax.adam(1e-2))
    assert int(np.asarray(acc2.train_state.step)) == saved_step
    acc2.end_training()


# ---------------------------------------------------------------------------
# Divergence sentinel
# ---------------------------------------------------------------------------


def test_sentinel_unit_streaks():
    from accelerate_tpu.fault_tolerance import DivergenceSentinel

    s = DivergenceSentinel(window=3, explode_factor=10.0, ema_alpha=0.5)
    assert s.observe(1.0, 0.5) == ("ok", "")
    # Two bad steps stay below the window...
    assert s.observe(float("nan"), 0.5)[0] == "warn"
    assert s.observe(1.0e9, 0.5)[0] == "warn"  # explosion vs EMA ~1.0
    # ... a good step resets the streak ...
    assert s.observe(1.1, 0.5)[0] == "ok"
    assert s.streak == 0
    # ... three consecutive trip it.
    assert s.observe(float("inf"), 0.5)[0] == "warn"
    assert s.observe(1.0, float("nan"))[0] == "warn"  # nonfinite grad norm
    verdict, reason = s.observe(float("nan"), 0.5)
    assert verdict == "trip" and "nonfinite" in reason
    # EMA never absorbed the bad samples.
    assert s.ema_loss == pytest.approx(1.05)


def test_sentinel_warn_policy_keeps_training(tmp_path):
    acc, loss_fn, batch = _setup(
        tmp_path, kwargs_handlers=[_ft(sentinel="warn", sentinel_window=2)]
    )
    ft = acc.fault_tolerance
    bad = {"loss": np.float32("nan"), "grad_norm": np.float32(1.0)}
    # Lagged evaluation: call N sees call N-1's metrics.
    for _ in range(4):
        assert ft.observe_step(bad) is None
    assert ft.sentinel.episode_warned


def test_sentinel_halt_policy_raises_through_step(tmp_path):
    """Integration: a nonfinite loss produced by the real jitted step trips
    the sentinel (one step lagged) and policy 'halt' raises."""
    import jax

    from accelerate_tpu.fault_tolerance import DivergenceError

    acc, loss_fn, batch = _setup(
        tmp_path, kwargs_handlers=[_ft(sentinel="halt", sentinel_window=1)]
    )
    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    state, _ = step(state, batch)
    poisoned = dict(batch)
    poisoned["x"] = batch["x"] * np.float32("nan")
    state, _ = step(state, poisoned)  # bad metrics become pending here
    with pytest.raises(DivergenceError, match="diverged"):
        step(state, batch)  # lagged fetch evaluates the poisoned step


def test_sentinel_rollback_restores_verified_checkpoint(tmp_path):
    from accelerate_tpu.fault_tolerance import DivergenceError

    acc, loss_fn, batch = _setup(
        tmp_path,
        kwargs_handlers=[_ft(sentinel="rollback", sentinel_window=2, max_rollbacks=1)],
    )
    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    state, _ = step(state, batch)
    state, _ = step(state, batch)
    acc._train_state = state
    ckpt = acc.save_state()
    want = {k: np.asarray(v) for k, v in enumerate_leaves(acc.train_state.params)}
    saved_step = int(np.asarray(state.step))

    ft = acc.fault_tolerance
    bad = {"loss": np.float32("inf"), "grad_norm": np.float32(1.0)}
    ft.observe_step(bad)  # becomes pending
    assert ft.observe_step(bad) is None  # streak 1 (lagged)
    restored = ft.observe_step(bad)  # streak 2 == window -> rollback
    assert restored is not None
    assert int(np.asarray(restored.step)) == saved_step
    for k, v in enumerate_leaves(restored.params):
        np.testing.assert_allclose(np.asarray(v), want[k], rtol=1e-6)
    assert ft.rollbacks_done == 1

    # Second divergence exhausts max_rollbacks -> escalates to halt.
    ft.observe_step(bad)
    ft.observe_step(bad)
    with pytest.raises(DivergenceError, match="max_rollbacks"):
        ft.observe_step(bad)


def test_save_state_pre_hook_rides_atomic_commit(tmp_path):
    """Pre-save hooks write into the STAGING dir under atomic saves; their
    sidecar files must land in the committed checkpoint AND in the manifest
    (not be wiped as stale staging)."""
    from accelerate_tpu.fault_tolerance import verify_checkpoint

    acc, loss_fn, batch = _setup(tmp_path, kwargs_handlers=[_ft()])
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state

    def hook(models, train_state, out_dir):
        with open(os.path.join(out_dir, "sidecar.json"), "w") as f:
            json.dump({"note": "written by pre-hook"}, f)

    acc.register_save_state_pre_hook(hook)
    d0 = acc.save_state()
    assert os.path.exists(os.path.join(d0, "sidecar.json"))
    manifest = json.load(open(os.path.join(d0, "manifest.json")))
    assert "sidecar.json" in manifest["files"]
    assert verify_checkpoint(d0) == (True, "ok")


def test_kwargs_validation():
    from accelerate_tpu.utils import FaultToleranceKwargs

    with pytest.raises(ValueError):
        FaultToleranceKwargs(checksum="md5")
    with pytest.raises(ValueError):
        FaultToleranceKwargs(sentinel="panic")
    with pytest.raises(ValueError):
        FaultToleranceKwargs(sentinel_window=0)
    with pytest.raises(ValueError):
        FaultToleranceKwargs(watchdog="panic")
    with pytest.raises(ValueError):
        FaultToleranceKwargs(watchdog_warn_s=0)
    with pytest.raises(ValueError):
        FaultToleranceKwargs(watchdog_warn_s=10.0, watchdog_stall_s=5.0)
    with pytest.raises(ValueError):
        FaultToleranceKwargs(watchdog_poll_s=0)
    with pytest.raises(ValueError):
        FaultToleranceKwargs(watchdog_heartbeat_every=-1)


# ---------------------------------------------------------------------------
# Chaos injection (training side) + step watchdog
# ---------------------------------------------------------------------------


class _FakeAcc:
    """The slice of Accelerator the manager's host-side hooks touch."""

    process_index = 0
    num_processes = 1
    step = 0
    telemetry = None


def _manager(**kw):
    from accelerate_tpu.fault_tolerance import FaultToleranceManager
    from accelerate_tpu.state import PartialState

    PartialState()  # the manager's logger requires an initialized state
    return FaultToleranceManager(_FakeAcc(), _ft(**kw))


def test_chaos_from_dict_and_nonfinite_grad_poisons_metrics_only():
    """A chaos dict builds a FaultInjector; nonfinite_grad poisons the
    SENTINEL's lagged sample (never model state) and counts as injected."""
    ft = _manager(
        sentinel="warn", sentinel_window=1,
        chaos=dict(seed=1, schedule=[
            {"point": "train_step", "kind": "nonfinite_grad", "tick": 0}]),
    )
    from accelerate_tpu.chaos import FaultInjector

    assert isinstance(ft.chaos, FaultInjector)
    good = {"loss": np.float32(1.0), "grad_norm": np.float32(0.5)}
    assert ft.observe_step(good) is None  # tick 0: poisoned pending
    assert ft.faults_injected == 1
    assert ft.observe_step(good) is None  # lagged fetch sees the NaN
    assert ft.sentinel.episode_warned  # the sentinel tripped on the poison
    assert ft.observe_step(good) is None
    assert ft.faults_injected == 1  # one-shot schedule never re-fires


def test_chaos_ticks_monotonic_not_step():
    """Chaos ticks count observe calls, never the training step — a
    rollback rewinds the step but must not re-fire an injected fault."""
    ft = _manager(chaos=dict(seed=1, schedule=[
        {"point": "train_step", "kind": "nonfinite_grad", "tick": 1}]))
    m = {"loss": np.float32(1.0), "grad_norm": np.float32(0.5)}
    for _ in range(4):  # the fake accelerator's step never advances
        ft.observe_step(m)
    assert ft._step_ticks == 4
    assert ft.faults_injected == 1


def test_chaos_slow_step_sleeps():
    import time as _time

    ft = _manager(chaos=dict(seed=1, schedule=[
        {"point": "train_step", "kind": "slow_step", "tick": 0,
         "seconds": 0.12}]))
    m = {"loss": np.float32(1.0), "grad_norm": np.float32(0.5)}
    t0 = _time.monotonic()
    ft.observe_step(m)
    assert _time.monotonic() - t0 >= 0.12
    t0 = _time.monotonic()
    ft.observe_step(m)  # no fault: no delay
    assert _time.monotonic() - t0 < 0.1


def test_chaos_torn_write_drives_save_retry(tmp_path):
    """An injected torn_write raises inside the retry loop, per (save,
    attempt): the first attempt tears, the second commits."""
    ft = _manager(
        save_retries=2, retry_backoff_s=0.01, retry_backoff_max_s=0.02,
        chaos=dict(seed=1, schedule=[
            {"point": "checkpoint_save", "kind": "torn_write",
             "tick": 0, "unit": 0}]),
    )
    calls = []

    def do_save(target):
        calls.append(target)
        os.makedirs(target, exist_ok=True)
        return target

    out = ft.run_save_with_retry(do_save, str(tmp_path / "ck"))
    assert out == str(tmp_path / "ck") and len(calls) == 1
    assert ft.save_retries_total == 1 and ft.faults_injected == 1
    # The next save draws a fresh tick — clean.
    out2 = ft.run_save_with_retry(do_save, str(tmp_path / "ck2"))
    assert out2 == str(tmp_path / "ck2")
    assert ft.save_retries_total == 1


def test_chaos_dead_host_exits_with_chosen_code(monkeypatch):
    ft = _manager(chaos=dict(seed=1, schedule=[
        {"point": "host_heartbeat", "kind": "dead_host", "tick": 0,
         "exit_code": 91}]))

    class _Exit(BaseException):
        pass

    codes = []

    def fake_exit(code):
        codes.append(code)
        raise _Exit()

    monkeypatch.setattr(os, "_exit", fake_exit)
    with pytest.raises(_Exit):
        ft.observe_step({"loss": np.float32(1.0)})
    assert codes == [91]
    assert ft.faults_injected == 1


def test_chaos_dead_host_flushes_injected_log(monkeypatch):
    """os._exit skips every atexit/finally, so the dead_host path must push
    the injector's FULL injected log through telemetry (and close the
    stream) before dying — the post-mortem keeps the fault schedule that
    killed the run."""
    ft = _manager(chaos=dict(seed=1, schedule=[
        {"point": "host_heartbeat", "kind": "dead_host", "tick": 0}]))

    class _Tel:
        def __init__(self):
            self.events = []
            self.closed = False

        def record_event(self, event, **fields):
            self.events.append((event, fields))

        def close(self):
            self.closed = True

    tel = _Tel()
    ft.accelerator.telemetry = tel

    class _Exit(BaseException):
        pass

    monkeypatch.setattr(
        os, "_exit", lambda code: (_ for _ in ()).throw(_Exit()))
    with pytest.raises(_Exit):
        ft.observe_step({"loss": np.float32(1.0)})
    logs = [f for e, f in tel.events if e == "chaos_injected_log"]
    assert len(logs) == 1
    assert logs[0]["injected"] and logs[0]["injected"][0]["kind"] == "dead_host"
    assert logs[0]["summary"]["injected"] == 1
    assert tel.closed  # the stream reached disk before the exit


def test_chaos_dead_host_rank_targeting(monkeypatch):
    """A unit-pinned dead_host entry only kills the named rank."""
    ft = _manager(chaos=dict(seed=1, schedule=[
        {"point": "host_heartbeat", "kind": "dead_host", "unit": 3}]))
    monkeypatch.setattr(
        os, "_exit", lambda code: (_ for _ in ()).throw(AssertionError))
    for _ in range(5):  # rank 0 never matches unit 3
        ft.observe_step({"loss": np.float32(1.0)})
    assert ft.faults_injected == 0


def test_draw_batch_fault_monotonic():
    ft = _manager(chaos=dict(seed=1, schedule=[
        {"point": "dataloader_batch", "kind": "corrupt_batch", "tick": 1}]))
    assert ft.draw_batch_fault() is None
    f = ft.draw_batch_fault()
    assert f is not None and f.kind == "corrupt_batch"
    assert ft.draw_batch_fault() is None
    assert ft._batch_ticks == 3
    # No injector armed: the hook is a cheap None.
    assert _manager().draw_batch_fault() is None


def test_watchdog_warn_policy_records_straggler():
    import time as _time

    ft = _manager(watchdog="warn", watchdog_warn_s=0.05,
                  watchdog_stall_s=0.15, watchdog_poll_s=0.01)
    ft.start_watchdog()
    try:
        ft.observe_step({"loss": np.float32(1.0)})
        _time.sleep(0.3)  # well past stall_s: warn once, stall once
        wd = ft.watchdog
        assert wd.warnings >= 1 and wd.stalls >= 1
        assert wd.escalations == 0  # policy warn never escalates
        # A completed step re-arms the episode.
        ft.observe_step({"loss": np.float32(1.0)})
        assert wd.age() < 0.05
        s = wd.summary()
        assert s["policy"] == "warn" and s["last_ages_s"] is not None
        assert 0 in {int(r) for r in s["last_ages_s"]}  # straggler named
    finally:
        ft.close()


def test_watchdog_error_policy_raises_at_next_step():
    import time as _time

    from accelerate_tpu.fault_tolerance import TrainingStalledError
    from accelerate_tpu.utils.constants import TRAINING_STALLED_EXIT_CODE

    ft = _manager(watchdog="error", watchdog_warn_s=0.03,
                  watchdog_stall_s=0.08, watchdog_poll_s=0.01)
    ft.start_watchdog()
    try:
        ft.observe_step({"loss": np.float32(1.0)})
        _time.sleep(0.25)
        with pytest.raises(TrainingStalledError, match="stalled") as ei:
            ft.observe_step({"loss": np.float32(1.0)})
        assert ei.value.exit_code == TRAINING_STALLED_EXIT_CODE
        assert ei.value.straggler == 0 and 0 in ei.value.ages
    finally:
        ft.close()


def test_watchdog_preempt_policy_sigterms_self():
    import time as _time

    ft = _manager(watchdog="preempt", watchdog_warn_s=0.03,
                  watchdog_stall_s=0.08, watchdog_poll_s=0.01,
                  watchdog_grace_s=60.0)
    ft.install_signal_handlers()
    ft.start_watchdog()
    try:
        ft.observe_step({"loss": np.float32(1.0)})
        deadline = _time.monotonic() + 2.0
        while not ft.preempted and _time.monotonic() < deadline:
            _time.sleep(0.01)
        # The watchdog SIGTERM'd this process; the preemption handler
        # latched the flag — the loop would now take a final save and exit
        # with the resumable code, exactly like a real preemption.
        assert ft.preempted and ft.preemption_signal == "SIGTERM"
        assert ft.watchdog.escalations == 1
    finally:
        ft.close()


def test_watchdog_off_by_default():
    ft = _manager()
    assert ft.watchdog is None and ft.chaos is None
    ft.start_watchdog()  # harmless no-op
    ft.close()


def test_allgather_host_floats_single_process():
    from accelerate_tpu.state import PartialState

    table = PartialState().allgather_host_floats([3.0, 0.25])
    assert table.shape == (1, 2)
    np.testing.assert_allclose(np.asarray(table[0]), [3.0, 0.25])


def test_divergence_error_exit_code():
    from accelerate_tpu.fault_tolerance import DivergenceError
    from accelerate_tpu.utils.constants import POISONED_CHECKPOINT_EXIT_CODE

    assert DivergenceError("x").exit_code == POISONED_CHECKPOINT_EXIT_CODE
