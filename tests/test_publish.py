"""Zero-downtime weight publication (publish.py + the serving swap seam):
guarded swap validation, double-buffered mid-flight swaps with version-tagged
rows, the 0-recompile executable census across a swap, exact error-diffusion
canary routing, promote/rollback + version GC, the checkpoint trust boundary
(committed + manifest-verified + monotonic), rollback quarantine, the three
publication chaos points, and cross-topology publish bit-equality through the
reshard planner. All CPU-only, tier-1 fast."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import (
    Model,
    PublishConfig,
    ServingConfig,
    ServingEngine,
    WeightPublisher,
    generate,
)
from accelerate_tpu.chaos import FaultInjector
from accelerate_tpu.fault_tolerance import write_manifest
from accelerate_tpu.utils import set_seed
from accelerate_tpu.utils.constants import PLAN_MANIFEST_NAME
from accelerate_tpu.utils.other import flatten_state_dict, save_sharded_safetensors


@pytest.fixture(scope="module")
def llama():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    probe = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 8), dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), probe)
    return cfg, model


def _prompts(cfg, lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, (n,), dtype=np.int32) for n in lengths]


def _engine(model, n_slots=2, **kw):
    return ServingEngine(
        model, ServingConfig(n_slots=n_slots, max_len=64, prefill_chunks=[4, 8], **kw)
    )


def _variant(params, scale=1.25):
    """A host (numpy) tree with the same structure/shapes/dtypes but
    different values — a stand-in for a further-trained checkpoint."""
    return jax.tree.map(
        lambda a: (np.asarray(a) * scale).astype(np.asarray(a).dtype), params
    )


def _device_tree(host_tree):
    return jax.tree.map(jax.device_put, host_tree)


def _drain(engine, ids, publisher=None, max_ticks=400):
    """Tick until every id has a terminal row; optionally poll the publisher
    between ticks (the smoke's loop shape). Returns (rows_by_id, actions)."""
    rows, actions = {}, []
    for _ in range(max_ticks):
        engine.tick()
        for r in engine.poll():
            rows[r["id"]] = r
        if publisher is not None:
            rec = publisher.poll()
            if rec is not None:
                actions.append(rec)
        if all(i in rows for i in ids):
            break
    assert all(i in rows for i in ids), "requests did not drain"
    return rows, actions


def _write_ckpt(root, host_tree, step, *, manifest=True, plan=None, name=None):
    """A committed checkpoint_N dir the way the trainer writes one: sharded
    safetensors, optional plan-manifest sidecar, fault-tolerance manifest
    LAST (it hashes and certifies everything already in the dir)."""
    d = os.path.join(str(root), name or f"checkpoint_{step}")
    os.makedirs(d, exist_ok=True)
    flat = {k: np.asarray(v) for k, v in flatten_state_dict(host_tree).items()}
    save_sharded_safetensors(flat, d)
    if plan is not None:
        with open(os.path.join(d, PLAN_MANIFEST_NAME), "w") as f:
            json.dump(plan, f)
    if manifest:
        write_manifest(d, step=step, world_size=1)
    return d


# ---------------------------------------------------------------------------
# PublishConfig + guarded swap seam (satellite: descriptive validation)
# ---------------------------------------------------------------------------


def test_publish_config_validation():
    with pytest.raises(ValueError, match="canary_fraction"):
        PublishConfig(canary_fraction=0.0)
    with pytest.raises(ValueError, match="canary_fraction"):
        PublishConfig(canary_fraction=1.5)
    with pytest.raises(ValueError, match="min_cohort"):
        PublishConfig(min_cohort=0)
    with pytest.raises(ValueError, match="ratios"):
        PublishConfig(max_ttft_ratio=0.0)
    with pytest.raises(ValueError, match="transfer_retries"):
        PublishConfig(transfer_retries=-1)


def test_swap_validation_names_the_offending_leaf(llama):
    cfg, model = llama
    engine = _engine(model)
    good = _device_tree(model.params)

    # Structure mismatch: a tree from a different model config.
    with pytest.raises(ValueError, match="structure"):
        engine.swap_params({"params": {}}, weights_version=1)

    # Host leaf: redistribution skipped.
    host = jax.tree.map(np.asarray, model.params)
    with pytest.raises(ValueError, match="not\n?.*a committed jax.Array|jax.Array"):
        engine.swap_params(host, weights_version=1)

    # Shape mismatch on one leaf, named in the error.
    def grow_first(tree):
        flat, treedef = jax.tree_util.tree_flatten(tree)
        flat = list(flat)
        flat[0] = jnp.zeros((int(flat[0].shape[0]) + 1,) + flat[0].shape[1:],
                            flat[0].dtype)
        return jax.tree_util.tree_unflatten(treedef, flat)

    with pytest.raises(ValueError, match="serving expects"):
        engine.swap_params(grow_first(good), weights_version=1)

    # Dtype mismatch.
    bad_dtype = jax.tree.map(lambda a: a.astype(jnp.float16), good)
    with pytest.raises(ValueError, match="serving expects"):
        engine.swap_params(bad_dtype, weights_version=1)

    # Monotonic version guard: 0 is not newer than the construction tree.
    with pytest.raises(ValueError, match="not newer"):
        engine.swap_params(good, weights_version=0)

    # No swap while a canary window is open.
    engine.begin_canary(good, weights_version=1, fraction=0.5)
    with pytest.raises(ValueError, match="canary"):
        engine.swap_params(good, weights_version=2)
    engine.rollback_canary()

    # Nothing above mutated the serving state.
    assert engine.weights_version == 0


# ---------------------------------------------------------------------------
# Double-buffered hot swap (tentpole: in-flight on old, admissions on new)
# ---------------------------------------------------------------------------


def test_mid_flight_swap_version_tags_and_bit_equality(llama):
    cfg, model = llama
    variant_host = _variant(model.params)
    variant_model = Model(module=model.module, params=_device_tree(variant_host))
    engine = _engine(model, n_slots=2)
    prompts = _prompts(cfg, [5, 5, 5, 5], seed=7)
    budget = 6

    old_ids = [engine.submit(p, max_new_tokens=budget) for p in prompts[:2]]
    engine.tick()  # grant the old requests BEFORE the swap: they bind v0
    engine.swap_params(_device_tree(variant_host), weights_version=3)
    new_ids = [engine.submit(p, max_new_tokens=budget) for p in prompts[2:]]
    rows, _ = _drain(engine, old_ids + new_ids)

    for i, rid in enumerate(old_ids):
        assert rows[rid]["status"] == "ok"
        assert rows[rid]["weights_version"] == 0
        want = np.asarray(generate(model, prompts[i][None], max_new_tokens=budget))[0]
        np.testing.assert_array_equal(rows[rid]["tokens"], want)
    for i, rid in enumerate(new_ids):
        assert rows[rid]["status"] == "ok"
        assert rows[rid]["weights_version"] == 3
        want = np.asarray(
            generate(variant_model, prompts[2 + i][None], max_new_tokens=budget)
        )[0]
        np.testing.assert_array_equal(rows[rid]["tokens"], want)

    # The old version's buffers are GC'd once its last request drains.
    assert engine.weights_version == 3
    assert set(engine._params_by_version) == {3}


def test_swap_keeps_one_decode_executable(llama):
    """The executable census across a hot swap: decode stays ONE executable
    with zero steady-state recompiles (satellite: 0-recompile census)."""
    cfg, model = llama
    engine = _engine(model, n_slots=2)
    prompts = _prompts(cfg, [4, 6, 4, 6], seed=11)
    ids = [engine.submit(p, max_new_tokens=4) for p in prompts[:2]]
    _drain(engine, ids)
    warm = engine.stats()
    assert warm["decode_executables"] == 1

    engine.swap_params(_device_tree(_variant(model.params)), weights_version=1)
    ids = [engine.submit(p, max_new_tokens=4) for p in prompts[2:]]
    _drain(engine, ids)
    stats = engine.stats()
    assert stats["decode_executables"] == 1
    assert stats["steady_recompiles"] == 0


# ---------------------------------------------------------------------------
# Canary routing + decision plumbing on the engine
# ---------------------------------------------------------------------------


def test_canary_error_diffusion_routes_exact_fraction(llama):
    cfg, model = llama
    engine = _engine(model, n_slots=2)
    engine.begin_canary(
        _device_tree(_variant(model.params)), weights_version=1, fraction=0.5
    )
    ids = [engine.submit(p, max_new_tokens=3)
           for p in _prompts(cfg, [4] * 8, seed=5)]
    rows, _ = _drain(engine, ids)

    status = engine.canary_status()
    assert status["routed_candidate"] == 4 and status["routed_primary"] == 4
    # Error diffusion is deterministic and alternating at 0.5 — admission
    # order (submit order here) alternates primary, candidate, primary, ...
    versions = [rows[i]["weights_version"] for i in ids]
    assert versions == [0, 1, 0, 1, 0, 1, 0, 1]

    prim = engine.cohort_stats(0)
    cand = engine.cohort_stats(1)
    assert prim["completed"] == 4 and cand["completed"] == 4
    assert prim["ok"] == 4 and cand["ok"] == 4
    # Warmup trims that cohort's first terminal events from the window.
    assert engine.cohort_stats(1, warmup=3)["completed"] == 1
    assert engine.cohort_stats(2) is None  # no cohort for that version

    window = engine.promote_canary()
    assert window["routed_candidate"] == 4
    assert engine.weights_version == 1
    assert engine.stats()["faults"]["promoted"] == 1


def test_rollback_is_bit_equal_to_never_publishing(llama):
    cfg, model = llama
    prompts = _prompts(cfg, [5, 5], seed=9)
    want = [np.asarray(generate(model, p[None], max_new_tokens=5))[0]
            for p in prompts]

    engine = _engine(model)
    engine.begin_canary(
        _device_tree(_variant(model.params)), weights_version=4, fraction=0.5
    )
    engine.rollback_canary()
    assert engine.weights_version == 0
    assert engine.stats()["faults"]["rolled_back"] == 1
    assert set(engine._params_by_version) == {0}  # candidate buffers GC'd

    ids = [engine.submit(p, max_new_tokens=5) for p in prompts]
    rows, _ = _drain(engine, ids)
    for rid, w in zip(ids, want):
        assert rows[rid]["weights_version"] == 0
        np.testing.assert_array_equal(rows[rid]["tokens"], w)


# ---------------------------------------------------------------------------
# The trust boundary: scan over a checkpoint root
# ---------------------------------------------------------------------------


def test_scan_refuses_torn_corrupt_and_legacy_dirs(llama, tmp_path):
    cfg, model = llama
    host = jax.tree.map(np.asarray, model.params)
    good = _write_ckpt(tmp_path, host, 1)
    # A torn staging dir never parses as a checkpoint name.
    os.makedirs(tmp_path / "checkpoint_4.tmp")
    # A legacy dir with no manifest is refused (newer index, but untrusted).
    _write_ckpt(tmp_path, _variant(host), 3, manifest=False)
    # A committed dir whose bytes rotted after the manifest hash fails verify.
    corrupt = _write_ckpt(tmp_path, _variant(host), 2)
    with open(os.path.join(corrupt, "model.safetensors"), "r+b") as f:
        f.seek(-1, os.SEEK_END)
        f.write(b"\x00")

    pub = WeightPublisher(
        _engine(model), PublishConfig(checkpoint_dir=str(tmp_path))
    )
    found = pub.scan()
    assert found == (good, 1)
    assert pub.stats()["skipped_unverified"] == 2  # legacy + corrupt


def test_scan_refuses_stale_and_duplicate_versions(llama, tmp_path):
    cfg, model = llama
    _write_ckpt(tmp_path, _variant(model.params), 2)
    engine = _engine(model)
    pub = WeightPublisher(
        engine,
        PublishConfig(checkpoint_dir=str(tmp_path), canary_fraction=1.0),
    )
    rec = pub.poll()
    assert rec["action"] == "published" and rec["mode"] == "cutover"
    assert rec["version"] == 2 and engine.weights_version == 2
    # The same newest-on-disk version is now a duplicate: refused, once.
    assert pub.poll() is None
    assert pub.stats()["skipped_stale"] == 1


def test_manifest_version_precedence(tmp_path):
    d = tmp_path / "checkpoint_7"
    os.makedirs(d)
    with open(d / "manifest.json", "w") as f:
        json.dump({"weights_version": 42, "step": 9}, f)
    assert WeightPublisher._manifest_version(str(d), 7) == 42
    with open(d / "manifest.json", "w") as f:
        json.dump({"step": 9}, f)
    assert WeightPublisher._manifest_version(str(d), 7) == 9
    with open(d / "manifest.json", "w") as f:
        json.dump({}, f)
    assert WeightPublisher._manifest_version(str(d), 7) == 7
    os.remove(d / "manifest.json")
    assert WeightPublisher._manifest_version(str(d), 7) == 7


# ---------------------------------------------------------------------------
# The full publish pipeline
# ---------------------------------------------------------------------------


def test_cutover_publish_is_bit_equal_to_direct_load(llama, tmp_path):
    cfg, model = llama
    variant_host = _variant(model.params)
    _write_ckpt(tmp_path, variant_host, 5)
    engine = _engine(model)
    pub = WeightPublisher(
        engine,
        PublishConfig(checkpoint_dir=str(tmp_path), canary_fraction=1.0),
    )
    rec = pub.poll()
    assert rec["version"] == 5 and rec["mode"] == "cutover"

    prompt = _prompts(cfg, [6], seed=13)[0]
    got = engine.run([prompt], max_new_tokens=5)[0]
    variant_model = Model(module=model.module, params=_device_tree(variant_host))
    want = np.asarray(generate(variant_model, prompt[None], max_new_tokens=5))[0]
    np.testing.assert_array_equal(got, want)

    stats = pub.stats()
    assert stats["published"] == 1 and stats["weights_version"] == 5
    assert stats["reshard"] is not None


def test_canary_publish_promotes_on_healthy_slo(llama, tmp_path):
    cfg, model = llama
    _write_ckpt(tmp_path, _variant(model.params), 3)
    engine = _engine(model)
    pub = WeightPublisher(
        engine,
        PublishConfig(
            checkpoint_dir=str(tmp_path), canary_fraction=0.5,
            canary_warmup=0, min_cohort=3,
            # Wide gates: wall-clock noise on a busy CI box must not flip
            # the decision — only a seeded slo_regression can.
            max_ttft_ratio=100.0, max_tpot_ratio=100.0, max_rate_increase=1.0,
        ),
    )
    ids = [engine.submit(p, max_new_tokens=3)
           for p in _prompts(cfg, [4] * 8, seed=17)]
    rows, actions = _drain(engine, ids, publisher=pub)
    assert [a["action"] for a in actions] == ["published", "promoted"]
    assert actions[1]["reasons"] == []
    assert actions[1]["cohorts"]["candidate"]["completed"] >= 3
    assert engine.weights_version == 3
    assert {rows[i]["weights_version"] for i in ids} == {0, 3}
    assert pub.stats()["promoted"] == 1


# ---------------------------------------------------------------------------
# Publication chaos: the three injection points
# ---------------------------------------------------------------------------


def test_chaos_torn_manifest_and_version_mismatch_refuse(llama, tmp_path):
    cfg, model = llama
    _write_ckpt(tmp_path, _variant(model.params), 2)
    for kind, counter in (("torn_write", "skipped_unverified"),
                          ("version_mismatch", "skipped_stale")):
        engine = _engine(model)
        pub = WeightPublisher(
            engine,
            PublishConfig(checkpoint_dir=str(tmp_path), canary_fraction=1.0),
            chaos=FaultInjector(
                seed=3, schedule=[{"point": "publish_manifest", "kind": kind}]
            ),
        )
        assert pub.poll() is None
        assert pub.stats()[counter] == 1
        assert engine.weights_version == 0  # old version keeps serving
        # The schedule entry is spent: the next poll publishes cleanly.
        assert pub.poll()["action"] == "published"
        assert engine.weights_version == 2


def _transfer_u(seed, version):
    """The residual uniform publish's transfer draw sees for publish seq 0:
    fresh injector per probe so the schedule entry is unspent."""
    inj = FaultInjector(
        seed=seed,
        schedule=[{"point": "publish_transfer", "kind": "transfer_error"}],
    )
    return inj.draw("publish_transfer", 0, unit=version).u


def test_chaos_transfer_transient_retries_then_succeeds(llama, tmp_path):
    cfg, model = llama
    variant_host = _variant(model.params)
    _write_ckpt(tmp_path, variant_host, 2)
    # u < 0.75 is the transient convention: exactly one failed attempt.
    seed = next(s for s in range(64) if _transfer_u(s, 2) < 0.75)
    engine = _engine(model)
    pub = WeightPublisher(
        engine,
        PublishConfig(checkpoint_dir=str(tmp_path), canary_fraction=1.0,
                      backoff_s=0.0, backoff_cap_s=0.0),
        chaos=FaultInjector(
            seed=seed,
            schedule=[{"point": "publish_transfer", "kind": "transfer_error"}],
        ),
    )
    rec = pub.poll()
    assert rec is not None and rec["action"] == "published"
    assert engine.weights_version == 2
    assert pub.stats()["aborted"] == 0
    prompt = _prompts(cfg, [5], seed=19)[0]
    variant_model = Model(module=model.module, params=_device_tree(variant_host))
    np.testing.assert_array_equal(
        engine.run([prompt], max_new_tokens=4)[0],
        np.asarray(generate(variant_model, prompt[None], max_new_tokens=4))[0],
    )


def test_chaos_transfer_persistent_aborts_publish(llama, tmp_path):
    cfg, model = llama
    _write_ckpt(tmp_path, _variant(model.params), 2)
    # u >= 0.75 is persistent: every retry fails, the publish aborts.
    seed = next(s for s in range(64) if _transfer_u(s, 2) >= 0.75)
    engine = _engine(model)
    pub = WeightPublisher(
        engine,
        PublishConfig(checkpoint_dir=str(tmp_path), canary_fraction=1.0,
                      transfer_retries=1, backoff_s=0.0, backoff_cap_s=0.0),
        chaos=FaultInjector(
            seed=seed,
            schedule=[{"point": "publish_transfer", "kind": "transfer_error"}],
        ),
    )
    assert pub.poll() is None
    stats = pub.stats()
    assert stats["aborted"] == 1 and stats["published"] == 0
    assert engine.weights_version == 0  # nothing half-bound
    assert pub.history[-1]["action"] == "aborted"
    assert pub.history[-1]["attempts"] == 2
    assert "transfer_error" in pub.history[-1]["reason"]


def test_chaos_slo_regression_rolls_back_and_quarantines(llama, tmp_path):
    cfg, model = llama
    _write_ckpt(tmp_path, _variant(model.params), 4)
    engine = _engine(model)
    pub = WeightPublisher(
        engine,
        PublishConfig(
            checkpoint_dir=str(tmp_path), canary_fraction=0.5,
            canary_warmup=0, min_cohort=2,
            max_ttft_ratio=100.0, max_tpot_ratio=100.0, max_rate_increase=1.0,
        ),
        chaos=FaultInjector(
            seed=1,
            schedule=[{"point": "canary_window", "kind": "slo_regression"}],
        ),
    )
    prompts = _prompts(cfg, [4] * 8, seed=23)
    ids = [engine.submit(p, max_new_tokens=3) for p in prompts]
    rows, actions = _drain(engine, ids, publisher=pub)
    assert [a["action"] for a in actions] == ["published", "rolled_back"]
    assert actions[1]["reasons"] == ["injected slo_regression"]
    assert engine.weights_version == 0
    assert pub.stats()["rolled_back"] == 1

    # The rolled-back version is quarantined: the still-newest-on-disk bad
    # checkpoint is never republished; recovery needs a NEWER committed step.
    for _ in range(3):
        assert pub.poll() is None
    assert pub.stats()["skipped_vetoed"] >= 1

    # Post-rollback admissions are bit-equal to never having published.
    check = _prompts(cfg, [5], seed=29)[0]
    np.testing.assert_array_equal(
        engine.run([check], max_new_tokens=4)[0],
        np.asarray(generate(model, check[None], max_new_tokens=4))[0],
    )

    # A newer committed step recovers.
    _write_ckpt(tmp_path, _variant(model.params, scale=1.5), 6)
    rec = pub.poll()
    assert rec["action"] == "published" and rec["version"] == 6


# ---------------------------------------------------------------------------
# Cross-topology publish (satellite: train 2x4 -> serving placement)
# ---------------------------------------------------------------------------


def test_cross_topology_publish_bit_equal(llama, tmp_path):
    """A checkpoint carrying a 2x4 train-mesh plan manifest (dp_shard-sharded
    leaves) publishes onto the serving placement through the reshard
    planner's schedule and decodes bit-equal to a direct load."""
    cfg, model = llama
    variant_host = _variant(model.params)
    flat = flatten_state_dict(variant_host)
    leaves = {}
    for name, arr in flat.items():
        spec = ["dp_shard"] if arr.ndim >= 1 and arr.shape[0] % 2 == 0 else []
        leaves[f"slot0/params/{name}"] = {
            "shape": [int(d) for d in arr.shape],
            "dtype": str(arr.dtype),
            "spec": spec,
        }
    plan = {
        "version": 1,
        "weights_version": 2,
        "world_size": 1,
        "n_devices": 8,
        "layout": {"dp_shard": 2, "tp": 4},
        "mesh_axes": {"dp_shard": 2, "tp": 4},
        "leaves": leaves,
    }
    _write_ckpt(tmp_path, variant_host, 2, plan=plan)

    engine = _engine(model)
    pub = WeightPublisher(
        engine,
        PublishConfig(checkpoint_dir=str(tmp_path), canary_fraction=1.0),
    )
    rec = pub.poll()
    assert rec["action"] == "published" and rec["version"] == 2
    # The topology gap is real: source-sharded leaves cost planned bytes.
    assert rec["bytes"] > 0
    stats = pub.stats()
    assert stats["bytes_planned"] > 0
    assert stats["bytes_moved"] > 0
    assert stats["predicted_transfer_s"] > 0

    prompt = _prompts(cfg, [6], seed=31)[0]
    variant_model = Model(module=model.module, params=_device_tree(variant_host))
    np.testing.assert_array_equal(
        engine.run([prompt], max_new_tokens=5)[0],
        np.asarray(generate(variant_model, prompt[None], max_new_tokens=5))[0],
    )
    assert engine.stats()["decode_executables"] == 1


# ---------------------------------------------------------------------------
# Telemetry + chaos registry
# ---------------------------------------------------------------------------


class _StubTelemetry:
    def __init__(self):
        self.events = []

    def record_event(self, name, **fields):
        self.events.append((name, fields))


def test_publish_emits_weights_published_events(llama, tmp_path):
    cfg, model = llama
    _write_ckpt(tmp_path, _variant(model.params), 2)
    telem = _StubTelemetry()
    engine = _engine(model)
    pub = WeightPublisher(
        engine,
        PublishConfig(checkpoint_dir=str(tmp_path), canary_fraction=1.0),
        telemetry=telem,
    )
    pub.poll()
    events = [(n, f) for n, f in telem.events if n == "weights_published"]
    assert len(events) == 1
    assert events[0][1]["outcome"] == "cutover"
    assert events[0][1]["version"] == 2


def test_publish_chaos_points_registered():
    # The three publication points accept their legal kinds...
    FaultInjector(rates={
        "publish_manifest": {"torn_write": 0.1, "version_mismatch": 0.1},
        "publish_transfer": {"transfer_error": 0.1},
        "canary_window": {"slo_regression": 0.1},
    })
    # ...and reject kinds that belong elsewhere.
    with pytest.raises(ValueError):
        FaultInjector(rates={"canary_window": {"torn_write": 0.1}})
    with pytest.raises(ValueError):
        FaultInjector(rates={"publish_transfer": {"slo_regression": 0.1}})
