"""Compile manager (compile_manager.py): bucket-policy math, ragged-stream
executable capping, shapes-manifest round-trip, AOT warmup (zero recompiles
on a warmed run, idempotence), ragged-final-batch padding, persistent-cache
validation + LRU pruning, and the off-by-default zero-overhead contract.
All CPU-only, tier-1 fast."""

import itertools
import json
import logging
import os
import time

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# Toy ragged-batch harness
# ---------------------------------------------------------------------------

N_ITEMS, DIM = 128, 4
# 8 distinct raw sequence lengths -> pow2 buckets {8, 16, 32, 64} (4 buckets).
RAGGED_LENGTHS = [5, 7, 9, 12, 17, 24, 33, 47]


def _data(seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(N_ITEMS, 64, DIM)).astype(np.float32)
    ys = rng.normal(size=(N_ITEMS, 64, 1)).astype(np.float32)
    return xs, ys


class _Dataset:
    def __init__(self, xs, ys):
        self.xs, self.ys = xs, ys

    def __len__(self):
        return len(self.xs)

    def __getitem__(self, i):
        return {"x": self.xs[i], "y": self.ys[i]}


def _ragged_collate(lengths):
    """Collate that trims each successive batch to the next raw length —
    a deterministic ragged stream through the real loader path."""
    counter = itertools.count()

    def collate(samples):
        s = lengths[next(counter) % len(lengths)]
        return {
            "x": np.stack([it["x"][:s] for it in samples]),
            "y": np.stack([it["y"][:s] for it in samples]),
        }

    return collate


class _Spec:
    def __init__(self, dataset, batch_size, collate_fn=None, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = None
        self.drop_last = drop_last
        if collate_fn is not None:
            self.collate_fn = collate_fn


def _accelerator(tmp_path, compile_kwargs=None, telemetry=True, **acc_kw):
    import optax  # noqa: F401 - ensures optax present before Accelerator

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import CompileKwargs, TelemetryKwargs, set_seed

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    set_seed(0)
    handlers = []
    if compile_kwargs is not None:
        handlers.append(
            compile_kwargs if isinstance(compile_kwargs, CompileKwargs) else CompileKwargs(**compile_kwargs)
        )
    if telemetry:
        handlers.append(
            TelemetryKwargs(sync_timing=True, straggler_probe_every=0, log_every=0)
        )
    return Accelerator(project_dir=str(tmp_path), kwargs_handlers=handlers, **acc_kw)


def _prepare(acc, spec):
    import jax
    import optax
    import flax.linen as nn

    from accelerate_tpu import Model

    module = nn.Dense(1)
    model = Model.from_flax(module, jax.random.key(0), np.zeros((1, 8, DIM), np.float32))
    model, opt, dl = acc.prepare(model, optax.sgd(0.01), spec)

    def loss_fn(params, batch):
        pred = module.apply({"params": params}, batch["x"])
        return ((pred - batch["y"]) ** 2).mean()

    return model, dl, loss_fn


def _run_epoch(acc, dl, loss_fn, step=None):
    step = step or acc.prepare_train_step(loss_fn)
    state = acc.train_state
    for batch in dl:
        state, _ = step(state, batch)
    return step


# ---------------------------------------------------------------------------
# Bucket-policy math
# ---------------------------------------------------------------------------


def test_pow2_bucket_ladder_edges():
    from accelerate_tpu.compile_manager import ladder_bucket, pow2_bucket

    assert pow2_bucket(1, min_bucket=8) == 8
    assert pow2_bucket(8, min_bucket=8) == 8
    assert pow2_bucket(9, min_bucket=8) == 16
    assert pow2_bucket(16, min_bucket=8) == 16
    assert pow2_bucket(17, min_bucket=8) == 32
    assert pow2_bucket(1, min_bucket=1) == 1
    # Cap: past max_bucket is the oversize fall-through (None).
    assert pow2_bucket(33, min_bucket=8, max_bucket=32) is None
    assert pow2_bucket(32, min_bucket=8, max_bucket=32) == 32
    # Fixed ladders.
    assert ladder_bucket(5, [8, 16]) == 8
    assert ladder_bucket(8, [16, 8]) == 8  # unsorted input is fine
    assert ladder_bucket(9, [8, 16]) == 16
    assert ladder_bucket(17, [8, 16]) is None


def test_oversize_falls_through_with_warning(tmp_path, caplog):
    acc = _accelerator(
        tmp_path, compile_kwargs={"buckets": "pow2", "max_bucket": 16}, telemetry=False
    )
    cm = acc.compile_manager
    with caplog.at_level(logging.WARNING):
        assert cm.bucket_for(33, "seq") == 33  # true shape ships
    assert any("exceeds the largest bucket" in r.getMessage() for r in caplog.records)
    assert cm.oversize_events == 1
    assert cm.bucket_for(9, "seq") == 16  # in-range dims still bucket


def test_auto_policy_builds_ladder_from_manifest(tmp_path):
    from accelerate_tpu.compile_manager import tree_to_spec

    acc = _accelerator(tmp_path, compile_kwargs={"buckets": "auto"}, telemetry=False)
    cm = acc.compile_manager
    cm.manifest.record("d1", tree_to_spec({"x": np.zeros((16, 24, 4), np.float32)}))
    cm.manifest.record("d2", tree_to_spec({"x": np.zeros((16, 48, 4), np.float32)}))
    assert cm.bucket_for(20, "seq") == 24  # smallest observed rung >= n
    assert cm.bucket_for(30, "seq") == 48
    # Past the observed ladder: falls back to the pow2 ladder, not a crash.
    assert cm.bucket_for(50, "seq") == 64


# ---------------------------------------------------------------------------
# Bucket padding at the device boundary
# ---------------------------------------------------------------------------


def test_ragged_stream_caps_executables(tmp_path):
    """>= 8 distinct raw sequence lengths, pow2 buckets -> at most 4
    executables, and a second epoch over the same stream adds zero
    recompiles (the acceptance bar)."""
    xs, ys = _data()
    acc = _accelerator(tmp_path, compile_kwargs={"buckets": "pow2"})
    spec = _Spec(_Dataset(xs, ys), 16, collate_fn=_ragged_collate(RAGGED_LENGTHS))
    _, dl, loss_fn = _prepare(acc, spec)
    step = _run_epoch(acc, dl, loss_fn)
    assert acc.compile_manager.executable_count() <= 4
    recompiles_after_first_epoch = acc.telemetry.recompiles
    _run_epoch(acc, dl, loss_fn, step=step)  # same buckets: fully warm
    assert acc.telemetry.recompiles == recompiles_after_first_epoch
    assert acc.compile_manager.executable_count() <= 4
    # The manifest recorded one signature per bucket.
    assert len(acc.compile_manager.manifest) == 4
    acc.end_training()


def test_ragged_final_batch_padded_to_batch_bucket(tmp_path):
    """drop_last=False + even_batches=False ships a ragged 8-sample tail
    (40 % 16) without the manager; under the manager it pads to the full
    batch-size bucket, so every epoch compiles the same single shape."""
    from accelerate_tpu.utils import DataLoaderConfiguration

    xs, ys = _data()
    cfg = DataLoaderConfiguration(even_batches=False)
    acc = _accelerator(
        tmp_path, compile_kwargs={"buckets": "pow2"}, dataloader_config=cfg
    )
    spec = _Spec(_Dataset(xs[:40], ys[:40]), 16)
    _, dl, loss_fn = _prepare(acc, spec)
    shapes = [batch["x"].shape for batch in dl]
    assert len(shapes) == 3
    assert all(s[0] == 16 for s in shapes), shapes  # tail padded 8 -> 16
    acc.end_training()

    # Control: same loader without the manager ships the true ragged tail.
    acc2 = _accelerator(tmp_path, compile_kwargs=None, dataloader_config=cfg)
    _, dl2, _ = _prepare(acc2, _Spec(_Dataset(xs[:40], ys[:40]), 16))
    tail = [batch["x"].shape for batch in dl2][-1]
    assert tail[0] == 8
    acc2.end_training()


def test_emit_mask_constant_structure(tmp_path):
    """emit_mask adds the mask leaf to EVERY batch (padded or not) — a
    mask that appeared only on padded batches would change the compiled
    signature and reintroduce the recompile it exists to prevent."""
    from accelerate_tpu.utils import CompileKwargs

    acc = _accelerator(
        tmp_path,
        compile_kwargs=CompileKwargs(buckets="pow2", emit_mask=True, batch_pad_mode="zero"),
        telemetry=False,
    )
    cm = acc.compile_manager
    full = {"x": np.ones((16, 16, DIM), np.float32)}
    ragged = {"x": np.ones((10, 13, DIM), np.float32)}
    p_full = cm.bucket_pad(full, batch_size_hint=16)
    p_ragged = cm.bucket_pad(ragged, batch_size_hint=16)
    assert set(p_full) == set(p_ragged) == {"x", "pad_mask"}
    assert p_ragged["x"].shape == (16, 16, DIM)
    assert p_full["pad_mask"].shape == p_ragged["pad_mask"].shape == (16, 16)
    assert p_full["pad_mask"].all()
    assert p_ragged["pad_mask"][:10, :13].all()
    assert not p_ragged["pad_mask"][10:].any()
    assert not p_ragged["pad_mask"][:, 13:].any()
    # zero pad mode: padded region really is zeros.
    assert not p_ragged["x"][10:].any()


def test_repeat_pad_cycles_real_samples(tmp_path):
    acc = _accelerator(
        tmp_path, compile_kwargs={"buckets": "pow2", "bucket_seq": False}, telemetry=False
    )
    cm = acc.compile_manager
    arr = np.arange(3, dtype=np.float32)[:, None]
    out = cm.bucket_pad({"x": arr}, batch_size_hint=8)["x"]
    assert out.shape == (8, 1)
    np.testing.assert_array_equal(out.ravel(), [0, 1, 2, 0, 1, 2, 0, 1])


def test_seq_padding_only_touches_reference_aligned_leaves(tmp_path):
    """Axis 1 is only a 'sequence' for leaves agreeing with the batch's
    reference length (first rank>=2 leaf): a (B, 1) target or (B, 10)
    class-score leaf riding in the same dict must NOT be stretched."""
    acc = _accelerator(tmp_path, compile_kwargs={"buckets": "pow2"}, telemetry=False)
    cm = acc.compile_manager
    batch = {
        "x": np.ones((16, 13, DIM), np.float32),   # reference: seq 13 -> 16
        "pos": np.ones((16, 13), np.int32),        # aligned: padded in lockstep
        "y": np.ones((16, 1), np.float32),         # NOT a sequence: untouched
        "scores": np.ones((16, 10), np.float32),   # NOT a sequence: untouched
    }
    out = cm.bucket_pad(batch, batch_size_hint=16)
    assert out["x"].shape == (16, 16, DIM)
    assert out["pos"].shape == (16, 16)
    assert out["y"].shape == (16, 1)
    assert out["scores"].shape == (16, 10)


# ---------------------------------------------------------------------------
# Shapes manifest
# ---------------------------------------------------------------------------


def test_manifest_roundtrip(tmp_path):
    from accelerate_tpu.compile_manager import (
        ShapesManifest,
        spec_map_leaves,
        tree_to_spec,
    )

    batch = {
        "ids": np.zeros((16, 32), np.int32),
        "nested": (np.zeros((16, 32, 8), np.float32), np.zeros((16,), np.float64)),
    }
    spec = tree_to_spec(batch)
    path = str(tmp_path / "manifest.jsonl")
    m = ShapesManifest(path)
    assert m.record("digest-a", spec) is True
    assert m.record("digest-a", spec) is False  # dedup
    # Every line on disk is one self-contained JSON object.
    with open(path) as fh:
        lines = [json.loads(l) for l in fh]
    assert len(lines) == 1 and lines[0]["digest"] == "digest-a"
    # A fresh load reconstructs the same abstract batch.
    m2 = ShapesManifest(path)
    assert "digest-a" in m2 and len(m2) == 1
    rebuilt = spec_map_leaves(
        m2.entries[0]["spec"], lambda shape, dtype: np.zeros(shape, np.dtype(dtype))
    )
    assert rebuilt["ids"].shape == (16, 32) and rebuilt["ids"].dtype == np.int32
    assert isinstance(rebuilt["nested"], tuple)
    assert rebuilt["nested"][0].shape == (16, 32, 8)
    assert rebuilt["nested"][1].dtype == np.float64


def test_manifest_survives_torn_tail_line(tmp_path):
    from accelerate_tpu.compile_manager import ShapesManifest, tree_to_spec

    path = str(tmp_path / "manifest.jsonl")
    m = ShapesManifest(path)
    m.record("ok", tree_to_spec({"x": np.zeros((4, 4), np.float32)}))
    with open(path, "a") as fh:
        fh.write('{"digest": "torn", "spec"')  # preempted mid-write
    m2 = ShapesManifest(path)
    assert len(m2) == 1 and "ok" in m2


# ---------------------------------------------------------------------------
# Warmup
# ---------------------------------------------------------------------------


def test_warmup_zero_recompiles_on_restart(tmp_path, caplog):
    """Run 1 (cold) populates the manifest; run 2 warms every signature at
    prepare_train_step time, so the whole ragged epoch replays with ZERO
    recompiles and no watchdog warnings — the restart acceptance bar."""
    xs, ys = _data()
    acc = _accelerator(tmp_path, compile_kwargs={"buckets": "pow2"})
    spec = _Spec(_Dataset(xs, ys), 16, collate_fn=_ragged_collate(RAGGED_LENGTHS))
    _, dl, loss_fn = _prepare(acc, spec)
    _run_epoch(acc, dl, loss_fn)
    assert len(acc.compile_manager.manifest) == 4
    acc.end_training()

    acc2 = _accelerator(tmp_path, compile_kwargs={"buckets": "pow2"})
    spec2 = _Spec(_Dataset(xs, ys), 16, collate_fn=_ragged_collate(RAGGED_LENGTHS))
    _, dl2, loss_fn2 = _prepare(acc2, spec2)
    caplog.clear()  # drop run 1's expected cold-compile warnings
    with caplog.at_level(logging.WARNING):
        step = acc2.prepare_train_step(loss_fn2)  # warmup fires here
        warmed = dict(acc2.compile_manager.warmup_stats)
        _run_epoch(acc2, dl2, loss_fn2, step=step)
    assert warmed["signatures_compiled"] == 4
    assert warmed["seconds"] > 0
    assert acc2.telemetry.recompiles == 0
    assert acc2.compile_manager.executable_count() <= 4
    assert not any("recompiled" in r.getMessage() for r in caplog.records)
    summary = acc2.telemetry.summary()
    assert summary["executables"] <= 4
    assert summary["compile"]["warmup"]["signatures_compiled"] == 4
    acc2.end_training()


def test_warmup_idempotent(tmp_path):
    """A second warmup pass compiles nothing and leaves the executable
    count unchanged."""
    xs, ys = _data()
    acc = _accelerator(tmp_path, compile_kwargs={"buckets": "pow2"})
    spec = _Spec(_Dataset(xs, ys), 16, collate_fn=_ragged_collate(RAGGED_LENGTHS))
    _, dl, loss_fn = _prepare(acc, spec)
    _run_epoch(acc, dl, loss_fn)
    acc.end_training()

    acc2 = _accelerator(tmp_path, compile_kwargs={"buckets": "pow2"})
    spec2 = _Spec(_Dataset(xs, ys), 16, collate_fn=_ragged_collate(RAGGED_LENGTHS))
    _, _, loss_fn2 = _prepare(acc2, spec2)
    acc2.prepare_train_step(loss_fn2)
    first = acc2.compile_manager.warmup_stats["signatures_compiled"]
    count = acc2.compile_manager.executable_count()
    assert first == 4
    stats = acc2.warmup_compile()  # explicit re-warm: all signatures cached
    assert stats["signatures_compiled"] == first
    assert acc2.compile_manager.executable_count() == count
    acc2.end_training()


def test_telemetry_only_run_writes_manifest_for_future_warmup(tmp_path):
    """Satellite: the recompile watchdog's digests persist to the shapes
    manifest even when the compile manager is OFF, so a later managed run
    can warm from them."""
    xs, ys = _data()
    acc = _accelerator(tmp_path, compile_kwargs=None)
    assert acc.compile_manager is None
    spec = _Spec(_Dataset(xs, ys), 16)
    _, dl, loss_fn = _prepare(acc, spec)
    _run_epoch(acc, dl, loss_fn)
    acc.end_training()
    path = os.path.join(str(tmp_path), "compile_cache", "shapes_manifest.jsonl")
    assert os.path.exists(path)
    with open(path) as fh:
        entries = [json.loads(l) for l in fh]
    assert len(entries) == 1  # one fixed shape all epoch
    assert entries[0]["spec"]["kind"] == "dict"


# ---------------------------------------------------------------------------
# Off-by-default zero overhead
# ---------------------------------------------------------------------------


def test_disabled_by_default_no_manager_no_padding(tmp_path):
    xs, ys = _data()
    acc = _accelerator(tmp_path, compile_kwargs=None, telemetry=False)
    assert acc.compile_manager is None
    assert acc.compile_handler is None
    spec = _Spec(_Dataset(xs, ys), 16, collate_fn=_ragged_collate([13]))
    _, dl, loss_fn = _prepare(acc, spec)
    assert dl._compile_manager is None
    # Batches ship their TRUE (unbucketed) shapes.
    batch = next(iter(dl))
    assert batch["x"].shape == (16, 13, DIM)


# ---------------------------------------------------------------------------
# Persistent-cache control
# ---------------------------------------------------------------------------


def test_persistent_cache_dir_created_and_validated(tmp_path):
    import jax

    from accelerate_tpu.utils import JitConfig

    target = tmp_path / "jit_cache" / "nested"
    prev = jax.config.jax_compilation_cache_dir
    try:
        acc = _accelerator(
            tmp_path,
            compile_kwargs={"buckets": None},
            telemetry=False,
            jit_config=JitConfig(persistent_cache_dir=str(target)),
        )
        assert os.path.isdir(str(target))
        assert acc.jit_config.persistent_cache_dir == str(target)
        assert acc.compile_manager.cache is not None
        stats = acc.compile_manager.cache_stats()
        assert stats["files"] == 0 and stats["misses"] == 0
    finally:
        # The validated path lands in global jax config — restore it so later
        # tests in this process don't compile into this test's tmp dir.
        jax.config.update("jax_compilation_cache_dir", prev)


def test_persistent_cache_unwritable_warns_and_disables(tmp_path, caplog):
    from accelerate_tpu.utils import JitConfig

    blocker = tmp_path / "file"
    blocker.write_text("not a dir")
    bad = str(blocker / "cache")  # mkdir under a regular file must fail
    with caplog.at_level(logging.WARNING):
        acc = _accelerator(
            tmp_path,
            compile_kwargs=None,
            telemetry=False,
            jit_config=JitConfig(persistent_cache_dir=bad),
        )
    assert acc.jit_config.persistent_cache_dir is None
    assert any("persistent compilation cache DISABLED" in r.getMessage() for r in caplog.records)


def test_cache_prune_lru_respects_budget_and_hot_set(tmp_path):
    from accelerate_tpu import PartialState
    from accelerate_tpu.compile_manager import ManagedPersistentCache

    PartialState()  # the multi-process logger needs an initialized state
    cache_dir = tmp_path / "cache"
    cache_dir.mkdir()
    # Three pre-existing 100-byte entries, oldest first.
    for i, name in enumerate(["old_a", "old_b", "old_c"]):
        p = cache_dir / name
        p.write_bytes(b"x" * 100)
        t = time.time() - 1000 + i
        os.utime(p, (t, t))
    cache = ManagedPersistentCache(str(cache_dir), budget_bytes=250)
    # A file created by THIS run (after baseline) is never evicted.
    (cache_dir / "hot").write_bytes(b"x" * 100)
    removed = cache.prune()
    assert removed["removed_files"] == 2  # oldest two go; 200 bytes remain
    assert not (cache_dir / "old_a").exists()
    assert not (cache_dir / "old_b").exists()
    assert (cache_dir / "old_c").exists()
    assert (cache_dir / "hot").exists()
    stats = cache.stats(compile_events=3)
    assert stats["misses"] == 1  # the hot file appeared this run
    assert stats["estimated_hits"] == 2


def test_compile_kwargs_validation():
    from accelerate_tpu.utils import CompileKwargs

    with pytest.raises(ValueError):
        CompileKwargs(buckets="fib")
    with pytest.raises(ValueError):
        CompileKwargs(batch_pad_mode="mirror")
    with pytest.raises(ValueError):
        CompileKwargs(warmup="later")
    CompileKwargs(buckets=None, warmup="off")  # valid combos construct
