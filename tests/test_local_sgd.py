"""LocalSGD (local_sgd.py) — single-process no-op + launched 2-process averaging."""

import os

import numpy as np
import pytest


def test_local_sgd_single_process_noop():
    import jax
    import optax

    from accelerate_tpu import Accelerator, LocalSGD, Model
    from accelerate_tpu.test_utils.training import make_regression_model
    from accelerate_tpu.utils import set_seed

    set_seed(0)
    module, loss_fn = make_regression_model()
    acc = Accelerator()
    model = Model.from_flax(module, jax.random.key(0), np.zeros((4,), np.float32))
    model, _ = acc.prepare(model, optax.sgd(0.1))
    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    x = np.linspace(-1, 1, 8).astype(np.float32)
    batch = {"x": x, "y": (2 * x + 1).astype(np.float32)}
    with LocalSGD(acc, model, local_sgd_steps=2) as lsgd:
        assert not lsgd.enabled  # one process → disabled, like the reference
        for _ in range(6):
            state, _ = step(state, batch)
            lsgd.step()
    assert float(np.asarray(state.params["a"])) != 0.0


@pytest.mark.slow
def test_local_sgd_multiprocess_averages():
    from accelerate_tpu.test_utils import execute_subprocess, get_launch_command

    cmd = get_launch_command(num_processes=2) + [
        "--cpu", "-m", "accelerate_tpu.test_utils.scripts.test_local_sgd"
    ]
    out = execute_subprocess(cmd, env={"PYTHONPATH": os.getcwd()})
    assert "LOCALSGD OK" in out
