"""Checkpoint/resume round-trip — the reference's checkpointing suite
(test_utils/scripts/external_deps/test_checkpointing.py)."""

import numpy as np
import pytest


def _setup(tmpdir, accum=1, shuffle=False):
    import jax
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.utils import ProjectConfiguration, set_seed
    import flax.linen as nn

    set_seed(3)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(1)(x)

    acc = Accelerator(
        gradient_accumulation_steps=accum,
        project_config=ProjectConfiguration(project_dir=str(tmpdir), automatic_checkpoint_naming=True),
    )
    module = Net()
    x = np.random.default_rng(0).normal(size=(64, 8)).astype(np.float32)
    y = x.sum(-1, keepdims=True).astype(np.float32)

    class DS:
        def __len__(self):
            return 64

        def __getitem__(self, i):
            return {"x": x[i], "y": y[i]}

    class RandomSampler:  # name triggers shuffle inference (seedable sampler)
        pass

    class Spec:
        dataset = DS()
        batch_size = 16
        sampler = RandomSampler() if shuffle else None
        drop_last = False

    model = Model.from_flax(module, jax.random.key(0), x[:1])
    tx = optax.adam(1e-2)
    model, opt, dl = acc.prepare(model, tx, Spec())

    def loss_fn(params, batch):
        import jax.numpy as jnp

        pred = module.apply({"params": params}, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    return acc, model, opt, dl, loss_fn


def test_save_load_roundtrip(tmp_path):
    import jax

    acc, model, opt, dl, loss_fn = _setup(tmp_path)
    step_fn = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    for batch in dl:
        state, m = step_fn(state, batch)
    acc._train_state = state
    params_before = jax.device_get(state.params)
    ckpt_dir = acc.save_state()

    # Perturb, then load back.
    acc._train_state = state.replace(
        params=jax.tree.map(lambda p: p * 0, state.params)
    )
    acc.load_state(ckpt_dir)
    params_after = jax.device_get(acc.train_state.params)
    for a, b in zip(jax.tree.leaves(params_before), jax.tree.leaves(params_after)):
        np.testing.assert_allclose(a, b, rtol=1e-6)
    assert int(np.asarray(acc.train_state.step)) == 4


def test_resume_training_equivalence(tmp_path):
    """Train 4 steps straight vs train 2 + checkpoint + resume + 2 — params
    must match exactly (includes optimizer state + RNG restore)."""
    import jax

    acc, model, opt, dl, loss_fn = _setup(tmp_path)
    step_fn = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    batches = list(dl) + list(dl)  # two epochs' worth deterministic
    for b in batches[:4]:
        state, _ = step_fn(state, b)
    straight = jax.device_get(state.params)

    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()

    acc2, model2, opt2, dl2, loss_fn2 = _setup(tmp_path / "b")
    step_fn2 = acc2.prepare_train_step(loss_fn2)
    state2 = acc2.train_state
    batches2 = list(dl2) + list(dl2)
    for b in batches2[:2]:
        state2, _ = step_fn2(state2, b)
    acc2._train_state = state2
    ckpt = acc2.save_state()
    acc2.load_state(ckpt)
    state2 = acc2.train_state
    for b in batches2[2:4]:
        state2, _ = step_fn2(state2, b)
    resumed = jax.device_get(state2.params)

    for a, b in zip(jax.tree.leaves(straight), jax.tree.leaves(resumed)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_custom_object_checkpointing(tmp_path):
    acc, model, opt, dl, loss_fn = _setup(tmp_path)

    class Counter:
        def __init__(self):
            self.n = 0

        def state_dict(self):
            return {"n": self.n}

        def load_state_dict(self, sd):
            self.n = sd["n"]

    c = Counter()
    c.n = 7
    acc.register_for_checkpointing(c)
    ckpt = acc.save_state()
    c.n = 0
    acc.load_state(ckpt)
    assert c.n == 7


def test_total_limit_pruning(tmp_path):
    import os

    acc, model, opt, dl, loss_fn = _setup(tmp_path)
    acc.project_configuration.total_limit = 2
    for _ in range(3):
        acc.save_state()
    base = os.path.join(str(tmp_path), "checkpoints")
    assert len(os.listdir(base)) == 2


def test_save_safetensors_noncontiguous_view():
    """Non-C-contiguous host views (TPU device layouts surface this way) must
    round-trip exactly — safetensors writes raw buffers without strides
    (regression: silent checkpoint corruption of 3-D kernels on TPU)."""
    import numpy as np

    from accelerate_tpu.utils.other import load_safetensors, save_safetensors

    base = np.arange(2 * 3 * 4, dtype=np.float32).reshape(4, 3, 2)
    view = np.transpose(base, (2, 1, 0))  # strided, not C-contiguous
    assert not view.flags.c_contiguous
    import tempfile, os

    path = os.path.join(tempfile.mkdtemp(), "t.safetensors")
    save_safetensors({"k": view}, path)
    back = load_safetensors(path)
    np.testing.assert_array_equal(back["k"], view)


def test_mid_epoch_resume_matches_uninterrupted(tmp_path):
    """Kill training mid-epoch, resume from the checkpoint, and the resumed
    run must consume the SAME remaining batches, sample-for-sample, as the
    uninterrupted run (VERDICT r1 item 5; reference contract:
    checkpointing.py:107-153 + data_loader.py:416-508)."""
    import jax

    from accelerate_tpu.state import AcceleratorState, GradientState

    # --- uninterrupted: record every batch of epoch 0 + epoch 1 -------------
    acc, model, opt, dl, loss_fn = _setup(tmp_path, shuffle=True)
    dl.set_epoch(0)
    full = [jax.device_get(b) for b in dl]
    dl.set_epoch(1)
    full += [jax.device_get(b) for b in dl]

    AcceleratorState._reset_state()
    GradientState._reset_state()

    # --- interrupted: stop after 2 batches of epoch 0, save, resume ---------
    acc2, model2, opt2, dl2, loss_fn2 = _setup(tmp_path / "b", shuffle=True)
    dl2.set_epoch(0)
    seen = []
    it = iter(dl2)
    for _ in range(2):
        seen.append(jax.device_get(next(it)))
    assert dl2.batches_yielded == 2
    ckpt = acc2.save_state()
    del it  # training "killed" mid-epoch here

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc3, model3, opt3, dl3, loss_fn3 = _setup(tmp_path / "c", shuffle=True)
    acc3.load_state(ckpt)
    # Resumed loader: finishes epoch 0 from batch 2, then a fresh epoch 1.
    seen += [jax.device_get(b) for b in dl3]
    dl3.set_epoch(1)
    seen += [jax.device_get(b) for b in dl3]

    # Sanity: the sampler really shuffles differently across epochs — the
    # equality below is only meaningful then.
    e0 = [np.asarray(b["x"]) for b in full[:4]]
    e1 = [np.asarray(b["x"]) for b in full[4:8]]
    assert not all(np.array_equal(a, c) for a, c in zip(e0, e1))
    assert len(seen) == len(full), (len(seen), len(full))
    for i, (a, b) in enumerate(zip(full, seen)):
        for k in a:
            np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                          err_msg=f"batch {i} key {k}")


@pytest.mark.slow
def test_checkpointing_multiprocess():
    """Launched 2-process save/load/resume equivalence (reference:
    test_utils/scripts/external_deps/test_checkpointing.py)."""
    import os

    from accelerate_tpu.test_utils import execute_subprocess, get_launch_command

    cmd = get_launch_command(num_processes=2) + [
        "--cpu", "-m", "accelerate_tpu.test_utils.scripts.test_checkpointing"
    ]
    out = execute_subprocess(cmd, env={"PYTHONPATH": os.getcwd(), "XLA_FLAGS": ""})
    assert "TEST_CHECKPOINTING OK" in out


def test_distributed_orbax_checkpoint_roundtrip(tmp_path):
    """DISTRIBUTED_STATE_DICT: orbax/TensorStore shards written without a host
    gather; restore lands on the live shardings (reference role: torch-DCP
    sharded-state-dict dirs, utils/fsdp_utils.py:103-337)."""
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, cross_entropy_loss
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

    import jax
    import jax.numpy as jnp

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 16), dtype=np.int32)

    acc = Accelerator(
        fsdp_plugin=FullyShardedDataParallelPlugin(state_dict_type="DISTRIBUTED_STATE_DICT"),
    )
    model = Model.from_flax(module, jax.random.key(0), ids)
    model, _ = acc.prepare(model, optax.adamw(1e-3))

    def loss_fn(params, batch):
        return cross_entropy_loss(module.apply({"params": params}, batch["x"]), batch["y"])

    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, {"x": jnp.asarray(ids[:, :-1]), "y": jnp.asarray(ids[:, 1:])})
    want_params = jax.tree.map(np.asarray, state.params)
    want_opt = jax.tree.map(lambda x: np.asarray(x) if hasattr(x, "shape") else x, state.opt_state)

    out = acc.save_state(str(tmp_path / "ckpt"))
    assert (tmp_path / "ckpt" / "distributed_state").is_dir()
    # No gathered model.safetensors in this format.
    assert not (tmp_path / "ckpt" / "model.safetensors").exists()

    # Clobber, reload, compare — shardings preserved.
    acc._train_state = state.replace(
        params=jax.tree.map(jnp.zeros_like, state.params),
        step=jnp.zeros_like(state.step),
    )
    acc.load_state(out)
    got = acc.train_state
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), b, rtol=1e-6),
        got.params, want_params,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
        if hasattr(b, "shape") else None,
        got.opt_state, want_opt,
    )
    assert int(got.step) == int(state.step)
    # Restored leaves land on the accelerator's PLANNED shardings (the
    # post-step layouts may differ where GSPMD chose its own): check that a
    # big leaf really is dp-sharded, not gathered-replicated.
    def _same_layout(a, s):
        assert a.sharding.is_equivalent_to(s, a.ndim), (a.sharding, s)

    jax.tree.map(_same_layout, got.params, acc._state_shardings.params)
    embed = got.params["model"]["embed_tokens"]["embedding"]
    assert not embed.sharding.is_fully_replicated
    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()


def test_async_distributed_checkpoint(tmp_path):
    """save_state(block=False) on the orbax path: returns while bytes persist
    in background; wait_for_checkpoint drains; load matches; a second async
    save serializes behind the first. (Async tier — the reference has none.)"""
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, cross_entropy_loss
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

    import jax
    import jax.numpy as jnp

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 16), dtype=np.int32)
    acc = Accelerator(
        fsdp_plugin=FullyShardedDataParallelPlugin(state_dict_type="DISTRIBUTED_STATE_DICT"),
    )
    model = Model.from_flax(module, jax.random.key(0), ids)
    model, _ = acc.prepare(model, optax.adamw(1e-3))

    def loss_fn(params, batch):
        return cross_entropy_loss(module.apply({"params": params}, batch["x"]), batch["y"])

    step = acc.prepare_train_step(loss_fn)
    batch = {"x": jnp.asarray(ids[:, :-1]), "y": jnp.asarray(ids[:, 1:])}
    state, _ = step(acc.train_state, batch)
    want = jax.tree.map(np.asarray, state.params)

    out = acc.save_state(str(tmp_path / "async_ckpt"), block=False)
    # Training continues while the save persists (donated buffers are safe:
    # the snapshot was copied to host before save_state returned).
    state2, _ = step(state, batch)
    acc.wait_for_checkpoint()

    # Second async save into another dir serializes behind the first.
    acc.save_state(str(tmp_path / "async_ckpt2"), block=False)
    acc.wait_for_checkpoint()

    acc._train_state = state2.replace(params=jax.tree.map(jnp.zeros_like, state2.params))
    acc.load_state(out)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), b, rtol=1e-6),
        acc.train_state.params, want,
    )
    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()


# ---------------------------------------------------------------------------
# Cross-topology reshard-on-load (round-3: SURVEY hard-part #5)
# ---------------------------------------------------------------------------


def _reshard_run(tmp_path, pc_factory, loss_fn_factory, n_before, n_after, save_dir=None,
                 load_dir=None):
    """Train n_before steps (optionally saving after them), then n_after more
    (optionally loading first); returns the per-step losses."""
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, llama_tp_rules
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

    import jax
    import jax.numpy as jnp

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, num_hidden_layers=4, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 17), dtype=np.int32)

    acc = Accelerator(
        parallelism_config=pc_factory(),
        fsdp_plugin=FullyShardedDataParallelPlugin(
            state_dict_type="DISTRIBUTED_STATE_DICT", min_weight_size_to_shard=0
        ),
    )
    model = Model.from_flax(module, jax.random.key(0), ids[:, :-1], tp_rules=llama_tp_rules(True))
    model, _ = acc.prepare(model, optax.adamw(1e-3))
    step = acc.prepare_train_step(loss_fn_factory(cfg, module, acc))
    batch = {"x": jnp.asarray(ids[:, :-1]), "y": jnp.asarray(ids[:, 1:])}

    losses = []
    state = acc.train_state
    for _ in range(n_before):
        state, m = step(state, batch)
        losses.append(float(np.asarray(m["loss"])))
    acc._train_state = state
    if save_dir is not None:
        acc.save_state(str(save_dir))
    if load_dir is not None:
        acc.load_state(str(load_dir))
        state = acc.train_state
    for _ in range(n_after):
        state, m = step(state, batch)
        losses.append(float(np.asarray(m["loss"])))
    return losses


def _plain_loss(cfg, module, acc):
    from accelerate_tpu.models import cross_entropy_loss

    def loss_fn(params, batch):
        return cross_entropy_loss(module.apply({"params": params}, batch["x"]), batch["y"])

    return loss_fn


def _pp_loss(cfg, module, acc):
    from accelerate_tpu.models import cross_entropy_loss
    from accelerate_tpu.parallel.pp import llama_pipeline_forward

    def loss_fn(params, batch):
        logits = llama_pipeline_forward(cfg, params, batch["x"], mesh=acc.mesh, n_microbatches=4)
        return cross_entropy_loss(logits, batch["y"])

    return loss_fn


@pytest.mark.parametrize(
    "target_pc, target_loss",
    [
        ("hsdp_tp", "plain"),   # dp_replicate=2 x dp_shard=2 x tp=2
        ("pp", "pp"),           # dp_shard=4 x pp=2
    ],
)
def test_orbax_reshard_on_load_matches_uninterrupted(tmp_path, target_pc, target_loss):
    """Save under dp_shard=8; load under a DIFFERENT mesh topology; the
    resumed loss curve must continue exactly like the uninterrupted dp8 run
    (reference role: DCP sharded-state + merge_fsdp_weights,
    utils/fsdp_utils.py:103-420)."""
    from accelerate_tpu import ParallelismConfig

    pcs = {
        "dp8": lambda: ParallelismConfig(dp_shard_size=8),
        "hsdp_tp": lambda: ParallelismConfig(dp_replicate_size=2, dp_shard_size=2, tp_size=2),
        "pp": lambda: ParallelismConfig(dp_shard_size=4, pp_size=2),
    }
    losses_full = _reshard_run(tmp_path, pcs["dp8"], _plain_loss, 2, 2)
    ckpt = tmp_path / "ckpt_dp8"
    _reshard_run(tmp_path, pcs["dp8"], _plain_loss, 2, 0, save_dir=ckpt)

    loss_factory = {"plain": _plain_loss, "pp": _pp_loss}[target_loss]
    losses_resumed = _reshard_run(
        tmp_path, pcs[target_pc], loss_factory, 0, 2, load_dir=ckpt
    )
    np.testing.assert_allclose(losses_resumed, losses_full[2:], rtol=2e-4)


def test_merge_fsdp_weights_both_formats(tmp_path):
    """merge_fsdp_weights consolidates BOTH checkpoint formats into portable
    safetensors (reference: utils/fsdp_utils.py:338-420)."""
    import optax

    import jax
    import jax.numpy as jnp

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, merge_fsdp_weights, set_seed
    from accelerate_tpu.utils.other import flatten_state_dict, load_safetensors

    for fmt in ("SHARDED_STATE_DICT", "DISTRIBUTED_STATE_DICT"):
        AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
        set_seed(0)
        cfg = LlamaConfig.tiny(dtype=jnp.float32)
        module = LlamaForCausalLM(cfg)
        ids = np.arange(4 * 8, dtype=np.int32).reshape(4, 8) % cfg.vocab_size
        acc = Accelerator(
            fsdp_plugin=FullyShardedDataParallelPlugin(state_dict_type=fmt),
        )
        model = Model.from_flax(module, jax.random.key(0), ids)
        model, _ = acc.prepare(model, optax.sgd(1e-2))
        ck = tmp_path / f"ck_{fmt}"
        acc.save_state(str(ck))

        out = merge_fsdp_weights(str(ck), str(tmp_path / f"merged_{fmt}"))
        flat = load_safetensors(out)
        want = {k: np.asarray(v) for k, v in
                flatten_state_dict(acc.train_state.params).items()}
        assert set(flat) == set(want)
        for k in want:
            np.testing.assert_allclose(flat[k], want[k], rtol=1e-6)


def test_iteration_continues_past_restored_checkpoint(tmp_path):
    """load_state from an automatic checkpoint must continue the numbering
    (iteration = restored + 1) — a fresh process that resumes and then saves
    must NOT clobber checkpoint_0 (the elastic-resume ordering contract)."""
    import os

    import jax
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils import ProjectConfiguration, set_seed
    import flax.linen as nn

    AcceleratorState._reset_state()
    GradientState._reset_state()
    set_seed(0)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(x)

    def fresh_acc():
        AcceleratorState._reset_state()
        GradientState._reset_state()
        acc = Accelerator(project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True,
        ))
        module = Net()
        model = Model.from_flax(module, jax.random.key(0), np.zeros((2, 4), np.float32))
        model, _ = acc.prepare(model, optax.adam(1e-2))
        return acc

    acc = fresh_acc()
    acc.save_state()  # checkpoint_0
    acc.save_state()  # checkpoint_1

    # Fresh process analog: iteration starts at 0 again.
    acc2 = fresh_acc()
    assert acc2.project_configuration.iteration == 0
    acc2.load_state()  # resolves checkpoint_1
    assert acc2.project_configuration.iteration == 2
    acc2.save_state()  # must create checkpoint_2, not overwrite checkpoint_0
    ckpts = sorted(os.listdir(os.path.join(str(tmp_path), "checkpoints")))
    assert ckpts == ["checkpoint_0", "checkpoint_1", "checkpoint_2"], ckpts
