"""CLI layer tests (reference analog: tests/test_cli.py).

The launched-subprocess tests follow the reference's central trick: assertions
run inside processes spawned by the product's own launcher (SURVEY.md §4).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest


def _run_cli(*argv, timeout=600):
    result = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli", *argv],
        capture_output=True,
        text=True,
        timeout=timeout,
        env={**os.environ, "PYTHONPATH": os.getcwd()},
    )
    assert result.returncode == 0, (
        f"CLI {' '.join(argv)} failed:\n{result.stdout}\n{result.stderr}"
    )
    return result.stdout


def test_config_default(tmp_path):
    path = str(tmp_path / "cfg.json")
    out = _run_cli("config", "--default", "--config_file", path, "--mixed_precision", "bf16")
    assert "saved" in out
    with open(path) as f:
        cfg = json.load(f)
    assert cfg["mixed_precision"] == "bf16"
    assert cfg["num_processes"] == 1


def test_config_env_encoding():
    from accelerate_tpu.commands.config_args import LaunchConfig

    cfg = LaunchConfig(
        mixed_precision="bf16",
        dp_shard_size=4,
        tp_size=2,
        use_fsdp=True,
        gradient_accumulation_steps=3,
        debug=True,
        virtual_devices=8,
    )
    env = cfg.to_env()
    assert env["ACCELERATE_MIXED_PRECISION"] == "bf16"
    assert env["PARALLELISM_CONFIG_DP_SHARD_SIZE"] == "4"
    assert env["PARALLELISM_CONFIG_TP_SIZE"] == "2"
    assert env["ACCELERATE_USE_FSDP"] == "true"
    assert env["ACCELERATE_GRADIENT_ACCUMULATION_STEPS"] == "3"
    assert env["ACCELERATE_DEBUG_MODE"] == "true"
    assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
    assert env["JAX_PLATFORMS"] == "cpu"


def test_env_command():
    out = _run_cli("env")
    assert "accelerate_tpu version" in out
    assert "JAX version" in out


def test_estimate_memory_builtin():
    out = _run_cli("estimate-memory", "llama:tiny", "--json", "--dtypes", "fp32", "bf16")
    rows = json.loads(out.strip().splitlines()[-1])
    fp32, bf16 = rows
    assert fp32["dtype"] == "fp32"
    # bf16 inference weights are half the fp32 size.
    assert abs(bf16["inference_total"] * 2 - fp32["inference_total"]) <= 2
    # Training adds grads + Adam moments (+ master for low precision).
    assert fp32["training_total"] == fp32["inference_total"] * 4


def test_merge_weights(tmp_path):
    from accelerate_tpu.utils.other import (
        load_safetensors,
        save_sharded_safetensors,
    )

    flat = {
        "layer1/kernel": np.arange(12, dtype=np.float32).reshape(3, 4),
        "layer2/kernel": np.ones((2, 2), dtype=np.float32),
    }
    src = tmp_path / "ckpt"
    src.mkdir()
    # Force two shards with a tiny max size.
    save_sharded_safetensors(flat, str(src), weights_name="model.safetensors", max_shard_size=40)
    out = tmp_path / "merged"
    _run_cli("merge-weights", str(src), str(out))
    merged = load_safetensors(str(out / "model.safetensors"))
    assert set(merged) == set(flat)
    np.testing.assert_array_equal(merged["layer1/kernel"], flat["layer1/kernel"])


@pytest.mark.slow
def test_launched_test_script_multiprocess():
    """The reference's flagship pattern: `launch --num_processes=2 <script>`
    with assertions inside (tests/test_multidevice.py:41-60 analog)."""
    from accelerate_tpu.test_utils import execute_subprocess, get_launch_command

    cmd = get_launch_command(num_processes=2, virtual_devices=2) + [
        "-m", "accelerate_tpu.test_utils.scripts.test_script"
    ]
    out = execute_subprocess(cmd, env={"PYTHONPATH": os.getcwd()})
    assert "All launched checks passed" in out


def test_launched_elastic_auto_resume(tmp_path):
    """Kill one rank mid-run → the launcher restarts the gang → attempt 1
    auto-resumes from the latest automatic checkpoint (assertions inside
    test_utils/scripts/test_elastic.py)."""
    from accelerate_tpu.test_utils import execute_subprocess, get_launch_command

    cmd = get_launch_command(
        num_processes=2, virtual_devices=2, max_restarts=1
    ) + ["-m", "accelerate_tpu.test_utils.scripts.test_elastic"]
    out = execute_subprocess(
        cmd, env={"PYTHONPATH": os.getcwd(), "ELASTIC_TEST_DIR": str(tmp_path)}
    )
    assert "Elastic resume test passed" in out


def test_launch_single_process_env(tmp_path):
    script = tmp_path / "show_env.py"
    script.write_text(
        "import os, json\n"
        "print(json.dumps({k: os.environ.get(k) for k in "
        "('ACCELERATE_MIXED_PRECISION', 'PARALLELISM_CONFIG_TP_SIZE')}))\n"
    )
    out = _run_cli(
        "launch", "--mixed_precision", "fp16", "--tp_size", "2", "--dp_shard_size", "4",
        str(script),
    )
    env = json.loads(out.strip().splitlines()[-1])
    assert env["ACCELERATE_MIXED_PRECISION"] == "fp16"
    assert env["PARALLELISM_CONFIG_TP_SIZE"] == "2"


def _square(x):
    assert x == 3


def test_notebook_launcher_single():
    from accelerate_tpu import notebook_launcher

    notebook_launcher(_square, (3,), num_processes=1)


def test_pod_launch_dry_run_ssh(capsys):
    """Pod fan-out (reference tpu_pod_launcher, commands/launch.py:1117-1173):
    dry-run prints one ssh command per host with computed ranks and the
    coordinator pinned to host 0."""
    import sys
    from unittest import mock

    from accelerate_tpu.commands.accelerate_cli import main

    argv = ["accelerate-tpu", "launch",
            "--pod_hosts", "tpu-w0,tpu-w1,tpu-w2",
            "--pod_working_dir", "/srv/job",
            "--pod_dry_run", "--tp_size", "4", "--mixed_precision", "bf16",
            "train.py", "--lr", "1e-4"]
    with mock.patch.object(sys, "argv", argv):
        rc = main()
    assert not rc
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 3
    for rank, line in enumerate(out):
        assert line.startswith(f"[tpu-w{rank}] ssh ")
        assert f"--machine_rank={rank}" in line
        assert "--num_machines=3" in line
        assert "--main_process_ip=tpu-w0" in line
        assert "--main_process_port=8476" in line
        assert "cd /srv/job &&" in line
        assert "--tp_size=4" in line
        assert "--mixed_precision=bf16" in line
        assert "train.py --lr 1e-4" in line


def test_pod_launch_dry_run_gcloud(capsys):
    import sys
    from unittest import mock

    from accelerate_tpu.commands.accelerate_cli import main

    argv = ["accelerate-tpu", "launch",
            "--pod_hosts", "gcloud:my-pod:us-central2-b",
            "--num_machines", "2", "--pod_dry_run", "train.py"]
    with mock.patch.object(sys, "argv", argv):
        rc = main()
    assert not rc
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2
    for rank, line in enumerate(out):
        assert "gcloud compute tpus tpu-vm ssh my-pod" in line
        assert f"--worker={rank}" in line
        assert "--zone=us-central2-b" in line
        assert f"--machine_rank={rank}" in line
        assert "--main_process_ip=auto" in line  # jax TPU-metadata rendezvous


def test_estimate_memory_hub_config_meta_init(tmp_path):
    """Hub-model sizing via transformers meta-device init (reference:
    commands/estimate.py:66-318) — a config.json-only directory must size
    through AutoModel.from_config on the meta device, no weights."""
    import json as _json

    from accelerate_tpu.commands.estimate import estimate_memory

    cfg = {"architectures": ["LlamaForCausalLM"], "model_type": "llama",
           "hidden_size": 256, "intermediate_size": 688, "num_hidden_layers": 2,
           "num_attention_heads": 4, "num_key_value_heads": 4, "vocab_size": 1000,
           "max_position_embeddings": 128}
    (tmp_path / "config.json").write_text(_json.dumps(cfg))
    rows = estimate_memory(str(tmp_path), ["bf16", "fp32"])
    assert rows[0]["inference_total"] > 1_000_000  # ~2.1M params * 2 bytes
    assert rows[0]["training_total"] > rows[0]["inference_total"]


def test_pod_launch_forwards_all_config_flags(capsys):
    """Every launch-config flag must reach the per-host command — a dropped
    flag silently diverges worker configs."""
    import sys
    from unittest import mock

    from accelerate_tpu.commands.accelerate_cli import main

    argv = ["accelerate-tpu", "launch",
            "--pod_hosts", "h0,h1", "--pod_dry_run",
            "--gradient_accumulation_steps", "4",
            "--use_fsdp", "--fsdp_sharding_strategy", "SHARD_GRAD_OP",
            "--fsdp_activation_checkpointing", "--remat_policy", "full",
            "--no_scan_layers", "--debug", "--jit_cache_dir", "/tmp/jc",
            "train.py"]
    with mock.patch.object(sys, "argv", argv):
        assert not main()
    out = capsys.readouterr().out
    for frag in ("--gradient_accumulation_steps=4", "--use_fsdp",
                 "--fsdp_sharding_strategy=SHARD_GRAD_OP",
                 "--fsdp_activation_checkpointing", "--remat_policy=full",
                 "--no_scan_layers", "--debug", "--jit_cache_dir=/tmp/jc"):
        assert frag in out, frag


def test_elastic_restart_recovers(tmp_path):
    """--max_restarts: the gang restarts after a worker failure and the retry
    succeeds (reference: torch elastic max_restarts passthrough,
    commands/launch.py:998-1030)."""
    import subprocess
    import sys

    script = tmp_path / "flaky.py"
    script.write_text(
        "import os, sys\n"
        "attempt = int(os.environ.get('ACCELERATE_RESTART_ATTEMPT', '0'))\n"
        "rank = os.environ.get('ACCELERATE_PROCESS_INDEX', '0')\n"
        "if attempt == 0 and rank == '1':\n"
        "    sys.exit(17)  # simulated worker crash on first attempt\n"
        "print(f'attempt={attempt} rank={rank} ok')\n"
    )
    base = [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli", "launch",
            "--num_processes=2", "--cpu"]
    env = {**os.environ, "PYTHONPATH": os.getcwd(), "XLA_FLAGS": ""}

    # Without restarts: fails.
    r = subprocess.run(base + [str(script)], env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 17, (r.returncode, r.stdout, r.stderr)

    # With one restart: recovers.
    r = subprocess.run(base + ["--max_restarts=1", str(script)], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    assert "restarting gang" in r.stderr
    assert "attempt=1 rank=0 ok" in r.stdout


def test_classify_exit_table():
    """The supervisor's failure classes, pinned (commands/launch.py)."""
    import signal as _signal

    from accelerate_tpu.commands.launch import classify_exit

    assert classify_exit(0) == "ok"
    assert classify_exit(130) == "interrupted"
    assert classify_exit(-_signal.SIGINT) == "interrupted"
    assert classify_exit(75) == "preempted"  # PREEMPTION_EXIT_CODE
    assert classify_exit(76) == "stalled"  # TRAINING_STALLED_EXIT_CODE
    assert classify_exit(77) == "poisoned"  # POISONED_CHECKPOINT_EXIT_CODE
    assert classify_exit(78) == "serving-crash"  # SERVING_CRASH_EXIT_CODE
    assert classify_exit(137) == "oom"
    assert classify_exit(-_signal.SIGKILL) == "oom"
    assert classify_exit(139) == "dead-host"  # chaos dead_host default
    assert classify_exit(-_signal.SIGSEGV) == "dead-host"
    assert classify_exit(134) == "dead-host"  # 128 + SIGABRT
    assert classify_exit(79) == "sdc"  # SDC_EXIT_CODE
    assert classify_exit(80) == "cell-dead"  # CELL_DEAD_EXIT_CODE
    assert classify_exit(81) == "fleet-degraded"  # FLEET_DEGRADED_EXIT_CODE
    assert classify_exit(1) == "fatal"
    assert classify_exit(17) == "fatal"


def test_exit_code_table_is_single_source_of_truth():
    """EXIT_CODE_TABLE (utils/constants.py) is what classify_exit and the
    docs render from: every row's classification must round-trip through
    the classifier, and every protocol constant must appear exactly once."""
    from accelerate_tpu.commands.launch import classify_exit
    from accelerate_tpu.utils import constants

    codes = [row["code"] for row in constants.EXIT_CODE_TABLE]
    assert codes == sorted(codes), "table rows must stay sorted by code"
    assert len(codes) == len(set(codes)), "duplicate exit code rows"
    for row in constants.EXIT_CODE_TABLE:
        assert classify_exit(row["code"]) == row["classification"], row
        assert row["response"], row
        if row["constant"] is not None and row["constant"].isidentifier():
            assert getattr(constants, row["constant"]) == row["code"], row
    # The resumable protocol subset the classifier resolves table-first.
    assert constants.PROTOCOL_EXIT_CLASSES == {
        75: "preempted", 76: "stalled", 77: "poisoned",
        78: "serving-crash", 79: "sdc", 80: "cell-dead",
        81: "fleet-degraded"}


def test_supervisor_sdc_shrinks_with_zero_backoff():
    """A sticky-SDC conviction (exit 79) relaunches immediately and SHRUNK:
    waiting cannot heal bad silicon, and the convicted host is already
    quarantined on disk by the worker."""
    from accelerate_tpu.commands.launch import GangSupervisor
    from accelerate_tpu.utils.constants import SDC_EXIT_CODE

    sup = GangSupervisor(max_restarts=3)
    d = sup.decide(SDC_EXIT_CODE, uptime_s=100.0, num_processes=4)
    assert d.action == "restart" and d.classification == "sdc"
    assert d.delay_s == 0.0
    assert d.num_processes == 2  # largest power of two <= 4 - 1
    # Unlike dead-host, sdc shrinks on the FIRST conviction — correctness,
    # not a death streak — and does not disturb the dead-host streak logic.
    sup2 = GangSupervisor(max_restarts=9, shrink_after=2)
    assert sup2.decide(139, uptime_s=5.0, num_processes=4).num_processes is None
    d2 = sup2.decide(SDC_EXIT_CODE, uptime_s=5.0, num_processes=4)
    assert d2.num_processes == 2 and d2.delay_s == 0.0
    assert sup2._dead_streak == 0


def test_supervisor_fleet_exit_codes():
    """The fleet classes (PR 18): a dead CELL relaunches with zero backoff
    (the router already drained its journal onto survivors, so the restart
    is immediately productive with a fresh WAL dir); a degraded FLEET backs
    off — every cell is breaching, so a hot relaunch would just shed."""
    from accelerate_tpu.commands.launch import GangSupervisor
    from accelerate_tpu.utils.constants import (
        CELL_DEAD_EXIT_CODE, FLEET_DEGRADED_EXIT_CODE)

    sup = GangSupervisor(max_restarts=3, backoff_s=0.5)
    d = sup.decide(CELL_DEAD_EXIT_CODE, uptime_s=100.0, num_processes=4)
    assert d.action == "restart" and d.classification == "cell-dead"
    assert d.delay_s == 0.0
    d = sup.decide(FLEET_DEGRADED_EXIT_CODE, uptime_s=100.0, num_processes=4)
    assert d.action == "restart" and d.classification == "fleet-degraded"
    assert d.delay_s > 0


def test_restart_backoff_deterministic_and_capped():
    from accelerate_tpu.commands.launch import _backoff_s

    # Replayable: no RNG, same inputs -> same sleep.
    assert _backoff_s(2, 1.0, 30.0) == _backoff_s(2, 1.0, 30.0)
    # Exponential until the cap; jitter stays within +-25%.
    for n in range(8):
        d = _backoff_s(n, 1.0, 30.0)
        raw = min(30.0, 2.0**n)
        assert 0.75 * raw <= d <= 1.25 * raw
    assert _backoff_s(3, 0.0, 30.0) == 0.0


def test_supervisor_budget_poisoned_and_preempted():
    from accelerate_tpu.commands.launch import GangSupervisor

    sup = GangSupervisor(max_restarts=1, backoff_s=0.5)
    d = sup.decide(139, uptime_s=5.0, num_processes=4)
    assert d.action == "restart" and d.classification == "dead-host"
    assert d.delay_s > 0
    d = sup.decide(139, uptime_s=5.0, num_processes=4)
    assert d.action == "stop" and "budget exhausted" in d.reason

    # Preempted workers saved on the way out: relaunch immediately.
    sup = GangSupervisor(max_restarts=3)
    d = sup.decide(75, uptime_s=100.0, num_processes=4)
    assert d.action == "restart" and d.classification == "preempted"
    assert d.delay_s == 0.0

    # A poisoned checkpoint replays the same divergence — never relaunch,
    # even with budget left.
    d = sup.decide(77, uptime_s=100.0, num_processes=4)
    assert d.action == "refuse" and d.classification == "poisoned"

    d = GangSupervisor(max_restarts=3).decide(0, uptime_s=10.0, num_processes=4)
    assert d.action == "stop" and d.classification == "ok"


def test_supervisor_serving_crash_zero_backoff():
    """A serving-engine death (rc 78) relaunches with ZERO backoff: the
    request journal makes the relaunch immediately productive, so any sleep
    only burns the SLO budget of the requests recover() will replay."""
    from accelerate_tpu.commands.launch import GangSupervisor

    sup = GangSupervisor(max_restarts=3, backoff_s=5.0)
    d = sup.decide(78, uptime_s=2.0, num_processes=1)
    assert d.action == "restart" and d.classification == "serving-crash"
    assert d.delay_s == 0.0
    # Still spends the restart budget — a crash-looping engine must stop.
    sup.decide(78, uptime_s=2.0, num_processes=1)
    sup.decide(78, uptime_s=2.0, num_processes=1)
    d = sup.decide(78, uptime_s=2.0, num_processes=1)
    assert d.action == "stop" and "budget exhausted" in d.reason


def test_supervisor_refuses_deterministic_fatal():
    from accelerate_tpu.commands.launch import GangSupervisor

    # The same fatal rc twice in quick succession is a deterministic crash.
    sup = GangSupervisor(max_restarts=10)
    assert sup.decide(17, uptime_s=2.0, num_processes=4).action == "restart"
    d = sup.decide(17, uptime_s=2.0, num_processes=4)
    assert d.action == "refuse" and "deterministic" in d.reason

    # A slow crash between them breaks the streak (it made progress).
    sup = GangSupervisor(max_restarts=10)
    assert sup.decide(17, uptime_s=2.0, num_processes=4).action == "restart"
    assert sup.decide(17, uptime_s=600.0, num_processes=4).action == "restart"
    assert sup.decide(17, uptime_s=2.0, num_processes=4).action == "restart"


def test_supervisor_dead_host_shrink():
    from accelerate_tpu.commands.launch import GangSupervisor

    sup = GangSupervisor(max_restarts=10, backoff_s=0.0, shrink_after=2)
    d = sup.decide(139, uptime_s=5.0, num_processes=8)
    assert d.action == "restart" and d.num_processes is None
    d = sup.decide(-11, uptime_s=5.0, num_processes=8)  # second dead host
    assert d.action == "restart" and d.num_processes == 4  # pow2 below 8-1
    # The streak reset: the next dead host starts counting again.
    d = sup.decide(139, uptime_s=5.0, num_processes=4)
    assert d.num_processes is None
    # A planner layout constrains the shrink to validated sizes.
    sup = GangSupervisor(
        max_restarts=10, backoff_s=0.0, shrink_after=1,
        layout={"tp": 2, "dp_shard": 4},
    )
    d = sup.decide(139, uptime_s=5.0, num_processes=8)
    assert d.num_processes == 6  # tp=2 must still divide: 6 = 3x2 works


def test_shrink_world_size():
    from accelerate_tpu.resharding import shrink_world_size

    assert shrink_world_size(8) == 4  # largest pow2 <= 7
    assert shrink_world_size(9) == 8
    assert shrink_world_size(2) == 1
    assert shrink_world_size(1) is None
    assert shrink_world_size(8, lost=7) == 1
    assert shrink_world_size(8, layout={"tp": 4, "dp_shard": 2}) == 4
    assert shrink_world_size(4, lost=1, layout={"tp": 4}) is None
    # Edge cases: losing everything (or more) leaves nothing to shrink to,
    # and a layout whose fixed axes validate NO smaller size refuses.
    assert shrink_world_size(8, lost=8) is None
    assert shrink_world_size(8, lost=20) is None
    assert shrink_world_size(0) is None
    assert shrink_world_size(3, layout={"tp": 4, "dp_shard": 2}) is None
    assert shrink_world_size(2, lost=1) == 1  # shrink-to-1 is legal bare...
    assert shrink_world_size(2, lost=1, layout={"tp": 2}) is None  # ...not under tp=2


def test_grow_world_size():
    """The shrink helper's inverse (autoscale.py scale-up): largest viable
    size in (current, current+gained], never sideways or down."""
    from accelerate_tpu.resharding import grow_world_size

    assert grow_world_size(4, gained=4) == 8
    assert grow_world_size(4, gained=3) is None  # 7,6,5 hold no pow2 > 4
    assert grow_world_size(4, gained=12) == 16
    assert grow_world_size(1, gained=1) == 2
    assert grow_world_size(0) is None
    # A planner layout admits non-pow2 targets its fixed axes divide.
    assert grow_world_size(4, gained=2, layout={"tp": 2}) == 6
    assert grow_world_size(4, gained=2, layout={"tp": 4, "dp_shard": 2}) is None
    # dp_shard is the rescalable axis: 12 = tp4 x dp_shard3 is viable.
    assert grow_world_size(8, gained=4, layout={"tp": 4, "dp_shard": 2}) == 12
    assert grow_world_size(8, gained=8, layout={"tp": 4, "dp_shard": 2}) == 16


def test_world_size_validation_shared_helper(monkeypatch):
    """Both shrink_world_size (the GangSupervisor's dead-host path) and
    grow_world_size (the autoscaler's scale-up) route layout validation
    through planner.validate_world_size — ONE topology gate, pinned so the
    two callers can't drift apart."""
    from accelerate_tpu import planner, resharding

    assert planner.validate_world_size(8) is True
    assert planner.validate_world_size(0) is False
    assert planner.validate_world_size(6, {"tp": 2}) is True
    assert planner.validate_world_size(6, {"tp": 4}) is False

    seen = []
    real = planner.validate_world_size

    def spy(n, layout=None):
        seen.append(n)
        return real(n, layout)

    monkeypatch.setattr(planner, "validate_world_size", spy)
    resharding.shrink_world_size(8, layout={"tp": 2})
    assert seen, "shrink_world_size bypassed the shared planner gate"
    shrink_calls = list(seen)
    seen.clear()
    resharding.grow_world_size(4, gained=2, layout={"tp": 2})
    assert seen, "grow_world_size bypassed the shared planner gate"
    assert max(seen) <= 6 and max(shrink_calls) <= 7


def test_launched_dead_host_chaos_supervisor(tmp_path):
    """Satellite of the chaos-training pillar: a chaos-injected dead_host
    (exit 139 on every rank at the 4th step) must be classified dead-host by
    the supervisor, relaunched with backoff, and attempt 1 must resume from
    the newest verified checkpoint (assertions inside test_elastic.py)."""
    import subprocess
    import sys as _sys

    from accelerate_tpu.test_utils import get_launch_command

    cmd = get_launch_command(
        num_processes=2, virtual_devices=2, max_restarts=1,
        restart_backoff=0.05,
    ) + ["-m", "accelerate_tpu.test_utils.scripts.test_elastic"]
    r = subprocess.run(
        cmd,
        env={**os.environ, "PYTHONPATH": os.getcwd(),
             "ELASTIC_TEST_DIR": str(tmp_path),
             "ELASTIC_CHAOS": "dead_host"},
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    assert "Elastic resume test passed" in r.stdout
    assert "rc=139, dead-host" in r.stderr
    assert "restarting gang" in r.stderr


def test_convert_config_fsdp(tmp_path, capsys):
    """Reference FSDP yaml → our LaunchConfig yaml (to-fsdp2 migration role)."""
    import yaml

    from accelerate_tpu.commands.accelerate_cli import main

    ref = {
        "distributed_type": "FSDP",
        "mixed_precision": "bf16",
        "num_processes": 8,
        "fsdp_config": {
            "fsdp_sharding_strategy": "FULL_SHARD",
            "fsdp_activation_checkpointing": True,
            "fsdp_offload_params": False,
            "fsdp_state_dict_type": "SHARDED_STATE_DICT",
            "fsdp_auto_wrap_policy": "TRANSFORMER_BASED_WRAP",
        },
    }
    src = tmp_path / "ref.yaml"
    src.write_text(yaml.safe_dump(ref))
    out = tmp_path / "ours.yaml"
    assert main(["convert-config", str(src), "-o", str(out)]) == 0
    got = yaml.safe_load(out.read_text())
    assert got["use_fsdp"] is True
    assert got["dp_shard_size"] == 8
    assert got["mixed_precision"] == "bf16"
    assert got["fsdp_activation_checkpointing"] is True
    assert got["remat_policy"] == "dots"
    notes = capsys.readouterr().err
    assert "fsdp_auto_wrap_policy" in notes  # dropped keys are reported (stderr)


def test_convert_config_deepspeed_and_hybrid(tmp_path):
    import yaml

    from accelerate_tpu.commands.convert import convert_reference_config

    cfg, notes = convert_reference_config({
        "distributed_type": "DEEPSPEED",
        "num_processes": 16,
        "deepspeed_config": {"zero_stage": 2, "offload_optimizer_device": "cpu"},
    })
    assert cfg.use_fsdp and cfg.fsdp_sharding_strategy == "SHARD_GRAD_OP"
    assert cfg.dp_shard_size == 16 and cfg.fsdp_offload_params

    cfg, _ = convert_reference_config({
        "distributed_type": "FSDP",
        "num_processes": 16,
        "num_machines": 2,
        "fsdp_config": {"fsdp_sharding_strategy": "HYBRID_SHARD"},
    })
    assert cfg.dp_shard_size == 8 and cfg.dp_replicate_size == 2

    cfg, _ = convert_reference_config({
        "distributed_type": "MULTI_GPU", "num_processes": 4,
    })
    assert cfg.dp_replicate_size == 4 and not cfg.use_fsdp


def test_convert_config_fsdp2_and_unknown_subkeys():
    from accelerate_tpu.commands.convert import convert_reference_config

    cfg, notes = convert_reference_config({
        "distributed_type": "FSDP",
        "num_processes": 4,
        "fsdp_config": {"fsdp_version": 2, "fsdp_reshard_after_forward": False,
                        "fsdp_mystery_knob": 1},
    })
    assert cfg.fsdp_sharding_strategy == "SHARD_GRAD_OP"
    joined = "\n".join(notes)
    assert "fsdp_mystery_knob" in joined  # unknown sub-keys reported


def test_estimate_memory_new_builtin_families(capsys):
    from accelerate_tpu.commands.accelerate_cli import main

    for spec in ("opt:tiny", "neox:tiny", "gpt2:tiny"):
        assert main(["estimate-memory", spec]) == 0
        assert "Memory estimate" in capsys.readouterr().out
