"""CLIP family: shapes, contrastive loss trains, TP sharding, HF parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Model
from accelerate_tpu.models import (
    CLIPConfig,
    CLIPModel,
    clip_contrastive_loss,
    clip_tp_rules,
)
from accelerate_tpu.utils import set_seed


def _batch(n=4, cfg=None, seed=0):
    cfg = cfg or CLIPConfig.tiny()
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, cfg.vocab_size, size=(n, cfg.max_position_embeddings // 2))
    # EOT convention: pooled feature reads the max-id position; force it last.
    ids[:, -1] = cfg.vocab_size - 1
    imgs = rng.normal(size=(n, cfg.image_size, cfg.image_size, 3)).astype(np.float32)
    return jnp.asarray(ids, jnp.int32), jnp.asarray(imgs)


def test_clip_forward_shapes():
    set_seed(0)
    cfg = CLIPConfig.tiny(dtype=jnp.float32)
    module = CLIPModel(cfg)
    ids, imgs = _batch(3, cfg)
    variables = module.init(jax.random.key(0), ids, imgs)
    lpi, lpt, img_e, txt_e = module.apply(variables, ids, imgs)
    assert lpi.shape == (3, 3) and lpt.shape == (3, 3)
    assert img_e.shape == (3, cfg.projection_dim)
    assert txt_e.shape == (3, cfg.projection_dim)
    np.testing.assert_allclose(np.asarray(lpi), np.asarray(lpt).T, rtol=1e-6)


def test_clip_contrastive_training_decreases_loss():
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    set_seed(0)
    cfg = CLIPConfig.tiny(dtype=jnp.float32)
    module = CLIPModel(cfg)
    ids, imgs = _batch(8, cfg)
    acc = Accelerator()
    model = Model.from_flax(module, jax.random.key(0), ids, imgs)
    model, _ = acc.prepare(model, optax.adam(1e-3))

    def loss_fn(params, batch):
        return clip_contrastive_loss(module, params, batch["ids"], batch["imgs"])

    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    first = None
    for _ in range(8):
        state, metrics = step(state, {"ids": ids, "imgs": imgs})
        first = first if first is not None else float(metrics["loss"])
    assert float(metrics["loss"]) < first
    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()


def test_clip_tp_sharded_embeds_match():
    import optax

    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    set_seed(0)
    cfg = CLIPConfig.tiny(dtype=jnp.float32)
    module = CLIPModel(cfg)
    ids, imgs = _batch(4, cfg)
    single = Model.from_flax(module, jax.random.key(0), ids, imgs)
    _, _, want_img, want_txt = single(ids, imgs)

    acc = Accelerator(parallelism_config=ParallelismConfig(tp_size=2, dp_shard_size=4))
    model = Model.from_flax(module, jax.random.key(0), ids, imgs, tp_rules=clip_tp_rules())
    model, _ = acc.prepare(model, optax.adam(1e-3))
    _, _, got_img, got_txt = model(ids, imgs)
    np.testing.assert_allclose(np.asarray(got_img), np.asarray(want_img), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(got_txt), np.asarray(want_txt), rtol=2e-4, atol=2e-4)
    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()


def test_clip_hf_parity():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from accelerate_tpu.models import model_from_pretrained

    hf_cfg = transformers.CLIPConfig(
        text_config_dict=dict(
            vocab_size=99, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=2, intermediate_size=64,
            max_position_embeddings=16, eos_token_id=98,
        ),
        vision_config_dict=dict(
            image_size=32, patch_size=8, hidden_size=48, num_hidden_layers=2,
            num_attention_heads=2, intermediate_size=96,
        ),
        projection_dim=24,
    )
    torch.manual_seed(0)
    hf = transformers.CLIPModel(hf_cfg)
    hf.eval()
    rng = np.random.default_rng(0)
    ids = rng.integers(1, 98, size=(2, 12)).astype(np.int64)
    ids[:, -1] = 98  # EOT = max id
    imgs = rng.normal(size=(2, 3, 32, 32)).astype(np.float32)
    with torch.no_grad():
        out = hf(input_ids=torch.from_numpy(ids), pixel_values=torch.from_numpy(imgs))
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    lpi, lpt, img_e, txt_e = ours(jnp.asarray(ids, jnp.int32),
                                  jnp.asarray(imgs.transpose(0, 2, 3, 1)))
    np.testing.assert_allclose(np.asarray(img_e), out.image_embeds.numpy(), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(txt_e), out.text_embeds.numpy(), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(lpi), out.logits_per_image.numpy(), rtol=2e-4, atol=2e-4
    )
