"""Serving under fire (chaos.py + the robustness layer in serving.py /
disagg.py): deterministic fault schedules, explicit terminal statuses for
every fault kind, bit-equal survivors, slot/lane quarantine with the decode
census pinned at 1, degraded colocated fallback, admission control +
deadlines, the hang guard, and the preemption drain. All CPU-only on the
forced 8-device host platform, tier-1 fast."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import (
    DisaggConfig,
    DisaggServingEngine,
    FaultInjector,
    InjectedFaultError,
    Model,
    ServingConfig,
    ServingEngine,
    ServingStalledError,
    generate,
)
from accelerate_tpu.chaos import INJECTION_POINTS, deterministic_jitter
from accelerate_tpu.utils import set_seed


@pytest.fixture(scope="module")
def llama():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    probe = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 8),
                                              dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), probe)
    return cfg, model


def _prompts(cfg, lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, (n,), dtype=np.int32)
            for n in lengths]


def _drain(engine, ids, guard=5000):
    """Tick until every submitted id has a result; return {id: result}."""
    results = {}
    ticks = 0
    while engine.pending:
        engine.tick()
        for r in engine.poll():
            results[r["id"]] = r
        ticks += 1
        assert ticks < guard, "drain guard tripped"
    assert set(ids) <= set(results), "a request vanished without a status"
    return results


# ---------------------------------------------------------------------------
# FaultInjector (pure host logic)
# ---------------------------------------------------------------------------


def test_injector_deterministic_and_seed_sensitive():
    spec = dict(rates={"handoff_device_put": {"transfer_error": 0.3,
                                              "delay": 0.2}})
    a, b = FaultInjector(seed=9, **spec), FaultInjector(seed=9, **spec)
    c = FaultInjector(seed=10, **spec)
    grid = [(t, u) for t in range(50) for u in range(3)]
    draws_a = [a.draw("handoff_device_put", t, u) for t, u in grid]
    draws_b = [b.draw("handoff_device_put", t, u) for t, u in grid]
    draws_c = [c.draw("handoff_device_put", t, u) for t, u in grid]
    assert draws_a == draws_b
    assert a.injected == b.injected and len(a.injected) > 0
    assert draws_a != draws_c  # a different seed must move the schedule
    # Call ORDER must not matter: the draw is a pure function of its inputs.
    d = FaultInjector(seed=9, **spec)
    draws_d = [d.draw("handoff_device_put", t, u) for t, u in reversed(grid)]
    assert list(reversed(draws_d)) == draws_a
    # Faults carry the residual uniform for sub-decisions.
    for f in draws_a:
        if f is not None:
            assert f.kind in ("transfer_error", "delay")
            assert 0.0 <= f.u < 1.0
    s = a.summary()
    assert s["injected"] == len(a.injected)
    assert sum(s["by_site"].values()) == s["injected"]


def test_injector_schedule_entries():
    chaos = FaultInjector(seed=0, schedule=[
        {"point": "lane_health", "kind": "dead_lane", "unit": 1},
        {"point": "decode_tick", "kind": "poison", "tick": 5, "count": 2},
    ])
    # Unit-pinned entry fires on the first matching unit only, once.
    assert chaos.draw("lane_health", 0, unit=0) is None
    f = chaos.draw("lane_health", 0, unit=1)
    assert f is not None and f.kind == "dead_lane"
    assert chaos.draw("lane_health", 1, unit=1) is None  # consumed
    # Tick-pinned entry with count=2 fires exactly twice at that tick.
    assert chaos.draw("decode_tick", 4) is None
    assert chaos.draw("decode_tick", 5).kind == "poison"
    assert chaos.draw("decode_tick", 5).kind == "poison"
    assert chaos.draw("decode_tick", 5) is None


def test_injector_validation():
    with pytest.raises(ValueError):
        FaultInjector(rates={"nope": 0.1})
    with pytest.raises(ValueError):
        FaultInjector(rates={"decode_tick": {"dead_lane": 0.1}})  # illegal kind
    with pytest.raises(ValueError):
        FaultInjector(rates={"handoff_device_put": {"transfer_error": 1.5}})
    with pytest.raises(ValueError):
        FaultInjector(rates={"handoff_device_put": {"transfer_error": 0.6,
                                                    "delay": 0.6}})  # sum > 1
    with pytest.raises(ValueError):
        FaultInjector(schedule=[{"point": "lane_health", "kind": "poison"}])
    with pytest.raises(ValueError):
        FaultInjector(delay_ticks=0)
    # Scalar rate shorthand takes the point's first legal kind.
    chaos = FaultInjector(seed=1, rates={"prefill_dispatch": 1.0})
    assert chaos.draw("prefill_dispatch", 0).kind == "transfer_error"
    assert set(INJECTION_POINTS) == {
        # serving
        "prefill_dispatch", "decode_tick", "handoff_device_put", "lane_health",
        # training
        "train_step", "collective_op", "checkpoint_save", "dataloader_batch",
        "host_heartbeat",
        # weight publication
        "publish_manifest", "publish_transfer", "canary_window",
        # autoscaling
        "autoscale_decide", "resize_transfer", "load_spike",
        # crash durability
        "journal_append", "journal_compact", "engine_crash",
        # fleet routing
        "cell_crash", "cell_partition", "router_heartbeat",
        # speculative decoding + quantized KV pages
        "draft_mismatch", "page_dequant",
    }


def test_training_points_and_extras():
    """Training-side points: kind legality, schedule pass-through fields on
    Fault.extra, slow_step_s validation, and the point-name-keyed hash —
    adding the training points must not have moved any serving schedule."""
    with pytest.raises(ValueError):
        FaultInjector(rates={"train_step": {"torn_write": 0.1}})  # wrong point
    with pytest.raises(ValueError):
        FaultInjector(slow_step_s=-1.0)
    chaos = FaultInjector(seed=4, schedule=[
        {"point": "train_step", "kind": "slow_step", "tick": 2, "seconds": 0.5},
        {"point": "host_heartbeat", "kind": "dead_host", "tick": 3, "unit": 1,
         "exit_code": 77},
        {"point": "checkpoint_save", "kind": "torn_write", "tick": 0},
    ])
    f = chaos.draw("train_step", 2)
    assert f.kind == "slow_step" and f.extra == {"seconds": 0.5}
    assert chaos.draw("host_heartbeat", 3, unit=0) is None  # wrong rank
    f = chaos.draw("host_heartbeat", 3, unit=1)
    assert f.kind == "dead_host" and f.extra == {"exit_code": 77}
    f = chaos.draw("checkpoint_save", 0, unit=0)
    assert f.kind == "torn_write" and f.extra is None
    # Rate-driven training faults carry no extra.
    rated = FaultInjector(seed=4, rates={"train_step": 1.0})
    f = rated.draw("train_step", 0)
    assert f.kind == "nonfinite_grad" and f.extra is None  # first legal kind
    # Point-name keying: a serving-point draw grid is identical whether or
    # not training rates exist on the same injector.
    a = FaultInjector(seed=9, rates={"decode_tick": {"poison": 0.3}})
    b = FaultInjector(seed=9, rates={"decode_tick": {"poison": 0.3},
                                     "train_step": {"slow_step": 0.5}})
    grid = [(t, u) for t in range(40) for u in range(2)]
    assert [a.draw("decode_tick", t, u) for t, u in grid] == \
           [b.draw("decode_tick", t, u) for t, u in grid]


def test_deterministic_jitter():
    vals = [deterministic_jitter(3, t, a) for t in range(20) for a in range(3)]
    assert all(0.5 <= v < 1.0 for v in vals)
    assert vals == [deterministic_jitter(3, t, a)
                    for t in range(20) for a in range(3)]
    assert len(set(vals)) > 10  # actually jitters


def test_injected_fault_error_carries_fault():
    f = FaultInjector(seed=1, rates={"prefill_dispatch": 1.0}).draw(
        "prefill_dispatch", 7, unit=2)
    err = InjectedFaultError(f)
    assert err.fault is f and isinstance(err, RuntimeError)
    assert "prefill_dispatch" in str(err) and "tick 7" in str(err)


# ---------------------------------------------------------------------------
# Engine-level fault handling (colocated)
# ---------------------------------------------------------------------------


def test_poison_quarantines_slot_and_replays_bit_equal(llama):
    """A poisoned KV page mid-decode: the sentinel catches it, the slot is
    quarantined, the request replays idempotently, and EVERY output —
    including the replayed one — stays bit-equal to generate()."""
    cfg, model = llama
    prompts = _prompts(cfg, [3, 7, 12, 20, 5, 9])
    budgets = [6, 4, 8, 3, 5, 6]

    def run(seed):
        chaos = FaultInjector(seed=seed, schedule=[
            {"point": "decode_tick", "kind": "poison", "tick": 8}])
        eng = ServingEngine(
            model, ServingConfig(n_slots=3, max_len=64, prefill_chunks=[4, 8]),
            chaos=chaos)
        ids = [eng.submit(p, max_new_tokens=b)
               for p, b in zip(prompts, budgets)]
        res = _drain(eng, ids)
        return [res[i] for i in ids], eng.stats(), chaos

    res, stats, chaos = run(7)
    assert [r["status"] for r in res] == ["ok"] * len(prompts)
    assert stats["faults"]["slot_quarantines"] == 1
    assert stats["faults"]["retries"] == 1
    assert stats["faults"]["quarantined_slots"] == 1
    assert stats["decode_executables"] == 1  # census survives quarantine
    for p, b, r in zip(prompts, budgets, res):
        want = np.asarray(generate(model, p[None], max_new_tokens=b))[0]
        np.testing.assert_array_equal(r["tokens"], want)
    # Same seed => identical fault schedule, statuses, and rows.
    res2, stats2, chaos2 = run(7)
    assert chaos.injected == chaos2.injected
    assert stats2["faults"] == stats["faults"]
    for a, b_ in zip(res, res2):
        assert a["status"] == b_["status"]
        np.testing.assert_array_equal(a["tokens"], b_["tokens"])


def test_prefill_transfer_error_retries_then_fails(llama):
    """Every injected transfer error at prefill dispatch burns one retry;
    with the budget exhausted the request terminates `failed` — explicitly,
    never silently."""
    cfg, model = llama
    prompts = _prompts(cfg, [5, 9])
    chaos = FaultInjector(seed=2, rates={"prefill_dispatch": 1.0})  # always
    eng = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=64, prefill_chunks=[4, 8],
                             max_retries=2),
        chaos=chaos)
    ids = [eng.submit(p, max_new_tokens=4) for p in prompts]
    res = _drain(eng, ids)
    assert [res[i]["status"] for i in ids] == ["failed", "failed"]
    assert eng.stats()["faults"]["failed"] == 2
    assert eng.stats()["faults"]["retries"] == 4  # 2 per request
    assert eng.stats()["requests_completed"] == 0


def test_hang_guard_raises_stalled(llama):
    """Once every slot is quarantined nothing can ever progress — the idle
    guard must raise ServingStalledError naming the stuck request instead of
    spinning forever (the failure mode this PR exists to kill)."""
    cfg, model = llama
    chaos = FaultInjector(seed=3, rates={"decode_tick": {"poison": 1.0}})
    eng = ServingEngine(
        model, ServingConfig(n_slots=1, max_len=64, prefill_chunks=[4, 8],
                             max_retries=50, max_idle_ticks=10),
        chaos=chaos)
    rid = eng.submit(_prompts(cfg, [5])[0], max_new_tokens=4)
    with pytest.raises(ServingStalledError, match=f"{rid}:queued"):
        for _ in range(500):
            eng.tick()
    assert eng.stats()["faults"]["quarantined_slots"] == 1


def test_deadline_timeout_frees_slot(llama):
    """A request that misses its deadline terminates `timeout` and frees its
    slot the same tick — the next request reuses it and completes ok."""
    cfg, model = llama
    prompts = _prompts(cfg, [5, 7])
    eng = ServingEngine(
        model, ServingConfig(n_slots=1, max_len=64, prefill_chunks=[4, 8]))
    import time as _time

    doomed = eng.submit(prompts[0], max_new_tokens=30, deadline_s=1e-4)
    eng.tick()
    _time.sleep(0.001)
    healthy = eng.submit(prompts[1], max_new_tokens=3)
    res = _drain(eng, [doomed, healthy])
    assert res[doomed]["status"] == "timeout"
    assert res[healthy]["status"] == "ok"
    want = np.asarray(generate(model, prompts[1][None], max_new_tokens=3))[0]
    np.testing.assert_array_equal(res[healthy]["tokens"], want)
    assert eng.stats()["faults"]["timeouts"] == 1
    # The timed-out partial row is still returned, padded to prompt+budget.
    assert res[doomed]["tokens"].shape == (len(prompts[0]) + 30,)


def test_admission_reject_and_shed_oldest(llama):
    cfg, model = llama
    prompts = _prompts(cfg, [5, 6, 7, 8, 9, 10])
    sc = dict(n_slots=1, max_len=64, prefill_chunks=[4, 8],
              max_queue_depth=2)
    # reject: the NEW request is shed.
    eng = ServingEngine(model, ServingConfig(**sc, overload_policy="reject"))
    ids = [eng.submit(p, max_new_tokens=3) for p in prompts]
    res = _drain(eng, ids)
    statuses = [res[i]["status"] for i in ids]
    assert statuses.count("shed") >= 1 and statuses.count("ok") >= 1
    assert res[ids[-1]]["status"] == "shed"  # last in, rejected
    assert eng.stats()["faults"]["sheds"] == statuses.count("shed")
    # shed_oldest: the OLDEST queued request is shed, the new one queues.
    eng2 = ServingEngine(model,
                         ServingConfig(**sc, overload_policy="shed_oldest"))
    ids2 = [eng2.submit(p, max_new_tokens=3) for p in prompts]
    res2 = _drain(eng2, ids2)
    assert res2[ids2[-1]]["status"] == "ok"  # newest survived
    assert [res2[i]["status"] for i in ids2].count("shed") >= 1


def test_admission_block_applies_backpressure(llama):
    cfg, model = llama
    prompts = _prompts(cfg, [5, 6, 7, 8])
    eng = ServingEngine(model, ServingConfig(
        n_slots=1, max_len=64, prefill_chunks=[4, 8],
        max_queue_depth=1, overload_policy="block"))
    ids = [eng.submit(p, max_new_tokens=3) for p in prompts]  # blocks inline
    res = _drain(eng, ids)
    assert [res[i]["status"] for i in ids] == ["ok"] * 4  # nobody shed
    assert eng.stats()["faults"]["sheds"] == 0


def test_preemption_drain(llama):
    """SIGTERM mid-serving (modeled by the manager's latch): in-flight
    requests finish ok, queued ones are shed, nothing new admits, and the
    engine reports the resumable exit code 75."""
    cfg, model = llama

    class _FakeFT:
        preempted = False

    ft = _FakeFT()
    prompts = _prompts(cfg, [5, 6, 7, 8])
    eng = ServingEngine(
        model, ServingConfig(n_slots=1, max_len=64, prefill_chunks=[4, 8]),
        fault_tolerance=ft)
    ids = [eng.submit(p, max_new_tokens=6) for p in prompts]
    for _ in range(3):  # let request 0 reach decode
        eng.tick()
    ft.preempted = True
    res = _drain(eng, ids)
    assert res[ids[0]]["status"] == "ok"  # in flight: drained to completion
    assert all(res[i]["status"] == "shed" for i in ids[1:])  # queued: shed
    late = eng.submit(prompts[0], max_new_tokens=2)
    assert {r["id"]: r for r in eng.poll()}[late]["status"] == "shed"
    assert eng.preempted is True
    assert eng.preemption_exit_code == 75
    assert eng.stats()["faults"]["preempted"] is True


# ---------------------------------------------------------------------------
# Disagg: lane faults, handoff faults, degraded fallback
# ---------------------------------------------------------------------------


def test_dead_lanes_degrade_to_colocated_bit_equal(llama):
    """Killing EVERY prefill lane mid-flight flips the engine degraded: it
    falls back to colocated prefill on the decode mesh, keeps serving, stays
    bit-equal to generate(), and the decode census stays 1."""
    cfg, model = llama
    prompts = _prompts(cfg, [3, 7, 12, 20, 5, 9])
    budgets = [6, 4, 8, 3, 5, 6]
    chaos = FaultInjector(seed=1, schedule=[
        {"point": "lane_health", "kind": "dead_lane", "unit": 0},
        {"point": "lane_health", "kind": "dead_lane", "unit": 1},
    ])
    eng = DisaggServingEngine(
        model, ServingConfig(n_slots=4, max_len=64, prefill_chunks=[4, 8]),
        disagg=DisaggConfig(n_prefill_lanes=2), chaos=chaos)
    outs = eng.run(prompts, max_new_tokens=budgets)
    for p, b, got in zip(prompts, budgets, outs):
        want = np.asarray(generate(model, p[None], max_new_tokens=b))[0]
        np.testing.assert_array_equal(got, want)
    s = eng.stats()
    assert s["disagg"]["degraded"] is True
    assert s["disagg"]["healthy_lanes"] == 0
    assert s["faults"]["lane_quarantines"] == 2
    assert s["faults"]["degraded"] is True
    assert s["decode_executables"] == 1
    assert s["steady_recompiles"] == 0


def test_handoff_transfer_error_transient_vs_persistent(llama):
    """An injected handoff transfer error with residual u < 0.75 is
    transient (one failed attempt, the retry lands); u >= 0.75 is persistent
    (every retry fails, the lane is quarantined, the request re-queues and
    replays bit-equal on another lane)."""
    cfg, model = llama
    prompts = _prompts(cfg, [3, 7, 12, 20, 5, 9])
    budgets = [6, 4, 8, 3, 5, 6]
    chaos = FaultInjector(
        seed=5, rates={"handoff_device_put": {"transfer_error": 0.25}})
    eng = DisaggServingEngine(
        model, ServingConfig(n_slots=4, max_len=64, prefill_chunks=[4, 8],
                             max_retries=4),
        disagg=DisaggConfig(n_prefill_lanes=2, handoff_retries=1),
        chaos=chaos)
    ids = [eng.submit(p, max_new_tokens=b) for p, b in zip(prompts, budgets)]
    res = _drain(eng, ids)
    f = eng.stats()["faults"]
    kinds = {(e["point"], e["kind"]) for e in chaos.injected}
    assert ("handoff_device_put", "transfer_error") in kinds
    assert f["handoff_retries"] >= 1  # at least one transient retry happened
    for p, b, i in zip(prompts, budgets, ids):
        if res[i]["status"] == "ok":
            want = np.asarray(generate(model, p[None], max_new_tokens=b))[0]
            np.testing.assert_array_equal(res[i]["tokens"], want)
    assert eng.stats()["decode_executables"] == 1


def test_handoff_delay_and_poison(llama):
    """A straggler handoff defers the background insert but never corrupts
    output; a poisoned handoff page is caught by the decode sentinel after
    the slot arms, and the request replays bit-equal."""
    cfg, model = llama
    prompts = _prompts(cfg, [3, 7, 12, 20, 5, 9])
    budgets = [6, 4, 8, 3, 5, 6]
    chaos = FaultInjector(
        seed=13,
        rates={"handoff_device_put": {"delay": 0.15, "poison": 0.08}},
        delay_ticks=4)
    eng = DisaggServingEngine(
        model, ServingConfig(n_slots=4, max_len=64, prefill_chunks=[4, 8],
                             max_retries=4),
        disagg=DisaggConfig(n_prefill_lanes=2), chaos=chaos)
    ids = [eng.submit(p, max_new_tokens=b) for p, b in zip(prompts, budgets)]
    res = _drain(eng, ids)
    f = eng.stats()["faults"]
    kinds = {(e["point"], e["kind"]) for e in chaos.injected}
    assert ("handoff_device_put", "delay") in kinds
    assert f["handoff_delays"] >= 1
    for p, b, i in zip(prompts, budgets, ids):
        if res[i]["status"] == "ok":
            want = np.asarray(generate(model, p[None], max_new_tokens=b))[0]
            np.testing.assert_array_equal(res[i]["tokens"], want)
    if ("handoff_device_put", "poison") in kinds:
        assert f["slot_quarantines"] >= 1  # the sentinel caught it
    assert eng.stats()["decode_executables"] == 1


# ---------------------------------------------------------------------------
# Off-by-default contract
# ---------------------------------------------------------------------------


def test_off_by_default_no_chaos_no_faults(llama):
    """Without an injector or robustness config the engine behaves exactly
    as before: ok statuses, zero fault counters, unchanged result keys."""
    cfg, model = llama
    prompts = _prompts(cfg, [5, 9])
    eng = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=64, prefill_chunks=[4, 8]))
    ids = [eng.submit(p, max_new_tokens=3) for p in prompts]
    res = _drain(eng, ids)
    for i in ids:
        assert res[i]["status"] == "ok"
        assert set(res[i]) == {"id", "status", "tokens", "new_tokens",
                               "ttft_s", "tpot_s", "weights_version",
                               "attempt", "recovered", "drafted", "accepted"}
        assert res[i]["attempt"] == 1 and res[i]["recovered"] is False
    f = eng.stats()["faults"]
    assert f["injected"] == 0 and f["degraded"] is False
    assert all(v in (0, False) for v in f.values())


def test_serving_config_robustness_validation():
    with pytest.raises(ValueError):
        ServingConfig(overload_policy="drop_everything")
    with pytest.raises(ValueError):
        ServingConfig(max_queue_depth=0)
    with pytest.raises(ValueError):
        ServingConfig(deadline_s=0.0)
    with pytest.raises(ValueError):
        ServingConfig(max_retries=-1)
    with pytest.raises(ValueError):
        ServingConfig(max_idle_ticks=0)
    with pytest.raises(ValueError):
        DisaggConfig(handoff_retries=-1)
    with pytest.raises(ValueError):
        DisaggConfig(handoff_backoff_s=0.2, handoff_backoff_cap_s=0.1)
    c = ServingConfig()
    assert c.max_queue_depth is None and c.deadline_s is None
    assert c.overload_policy == "reject"
    assert c.max_retries == 2 and c.max_idle_ticks == 100
