"""Examples run end-to-end on the virtual CPU mesh (reference analog:
tests/test_examples.py FeatureExamplesTests). Each example self-asserts; the
test just requires a clean exit."""

import os
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


def _run_example(rel_path, *args, timeout=420):
    env = {
        **os.environ,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": os.pathsep.join(
            p for p in (os.environ.get("PYTHONPATH"), os.getcwd()) if p
        ),
    }
    path = os.path.join(EXAMPLES, rel_path)
    # Pin the CPU mesh via jax.config BEFORE the example imports anything —
    # env vars alone lose to site hooks that pre-register a device backend.
    bootstrap = (
        "import jax, runpy, sys; jax.config.update('jax_platforms', 'cpu'); "
        f"sys.argv = [sys.argv[1]] + sys.argv[2:]; "
        "runpy.run_path(sys.argv[0], run_name='__main__')"
    )
    result = subprocess.run(
        [sys.executable, "-c", bootstrap, path, *args],
        cwd=os.path.dirname(path),
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert result.returncode == 0, (
        f"{rel_path} failed:\n--- stdout ---\n{result.stdout}\n--- stderr ---\n{result.stderr}"
    )
    return result.stdout


@pytest.mark.slow
def test_checkpointing_example():
    out = _run_example("by_feature/checkpointing.py")
    assert "checkpointing OK" in out


@pytest.mark.slow
def test_big_model_inference_example():
    out = _run_example("by_feature/big_model_inference.py")
    assert "big-model inference OK" in out


@pytest.mark.slow
def test_gradient_accumulation_example():
    out = _run_example("by_feature/gradient_accumulation.py")
    assert "grad-accum OK" in out
