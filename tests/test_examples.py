"""Examples run end-to-end on the virtual CPU mesh (reference analog:
tests/test_examples.py FeatureExamplesTests). Each example self-asserts; the
test just requires a clean exit."""

import os
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


def _run_example(rel_path, *args, timeout=420):
    env = {
        **os.environ,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": os.pathsep.join(
            p for p in (os.environ.get("PYTHONPATH"), os.getcwd()) if p
        ),
    }
    path = os.path.join(EXAMPLES, rel_path)
    # Pin the CPU mesh via jax.config BEFORE the example imports anything —
    # env vars alone lose to site hooks that pre-register a device backend.
    bootstrap = (
        "import jax, runpy, sys; jax.config.update('jax_platforms', 'cpu'); "
        f"sys.argv = [sys.argv[1]] + sys.argv[2:]; "
        "runpy.run_path(sys.argv[0], run_name='__main__')"
    )
    result = subprocess.run(
        [sys.executable, "-c", bootstrap, path, *args],
        cwd=os.path.dirname(path),
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert result.returncode == 0, (
        f"{rel_path} failed:\n--- stdout ---\n{result.stdout}\n--- stderr ---\n{result.stderr}"
    )
    return result.stdout


@pytest.mark.slow
def test_checkpointing_example():
    out = _run_example("by_feature/checkpointing.py")
    assert "checkpointing OK" in out


@pytest.mark.slow
def test_big_model_inference_example():
    out = _run_example("by_feature/big_model_inference.py")
    assert "big-model inference OK" in out


@pytest.mark.slow
def test_gradient_accumulation_example():
    out = _run_example("by_feature/gradient_accumulation.py")
    assert "grad-accum OK" in out


# ---------------------------------------------------------------------------
# Round 2: every by_feature script runs in CI (VERDICT r1 weak-item 8) + the
# CV examples + a structure test proving each by_feature script is
# base + exactly its feature (reference: tests/test_examples.py:70
# ExampleDifferenceTests).
# ---------------------------------------------------------------------------

_BY_FEATURE_OK = {
    "early_stopping.py": "early stopping OK",
    "fp8.py": "fp8 OK",
    "fsdp_llama.py": "fsdp OK",
    "local_sgd.py": "local_sgd OK",
    "memory.py": "memory OK",
    "profiler.py": "profiler OK",
    "quantized_inference.py": "quantized inference OK",
    "tensor_parallel.py": "tp OK",
    "tracking.py": "tracking OK",
    "generation.py": "generation OK",
    "megatron_import.py": "megatron import OK",
    "pipeline_inference.py": "pipeline inference over",
    "automatic_gradient_accumulation.py": "auto grad-accum OK",
    "multi_process_metrics.py": "multi-process metrics OK",
    "schedule_free.py": "schedule_free OK",
    "cross_validation.py": "cross-validation OK",
    "fsdp_with_peak_mem_tracking.py": "fsdp peak-mem OK",
    "long_context_generation.py": "long-context generation OK",
    "distillation.py": "distillation OK",
    "ddp_comm_hook.py": "ddp_comm_hook OK",
    "gradient_accumulation_for_autoregressive_models.py": "auto-regressive grad-accum OK",
}


@pytest.mark.slow
@pytest.mark.parametrize("script,marker", sorted(_BY_FEATURE_OK.items()))
def test_by_feature_example(script, marker):
    out = _run_example(f"by_feature/{script}")
    assert marker in out


@pytest.mark.slow
def test_cv_example():
    out = _run_example("cv_example.py", "--epochs", "3")
    assert "final_accuracy=" in out
    assert float(out.rsplit("final_accuracy=", 1)[1].strip()) > 0.6


@pytest.mark.slow
def test_complete_cv_example_with_resume(tmp_path):
    out = _run_example(
        "complete_cv_example.py", "--epochs", "2", "--with_tracking",
        "--project_dir", str(tmp_path),
    )
    assert "final_accuracy=" in out
    # Resume from the last auto-named checkpoint: skips straight to eval.
    ckpts = sorted((tmp_path / "checkpoints").iterdir())
    out = _run_example(
        "complete_cv_example.py", "--epochs", "2",
        "--resume_from_checkpoint", str(ckpts[-1]),
        "--project_dir", str(tmp_path / "resume"),
    )
    assert "Resumed from" in out


@pytest.mark.slow
def test_complete_nlp_example_runs(tmp_path):
    out = _run_example(
        "complete_nlp_example.py", "--epochs", "1", "--project_dir", str(tmp_path)
    )
    assert "final_accuracy=" in out


# Feature markers: API surface that IS the feature. A by_feature script must
# import the shared base (so it adds nothing else) and contain its marker.
_FEATURE_MARKERS = {
    "checkpointing.py": ["save_state", "load_state"],
    "early_stopping.py": ["set_trigger", "check_trigger"],
    "fp8.py": ["fp8"],
    "fsdp_llama.py": ["FullyShardedDataParallelPlugin"],
    "gradient_accumulation.py": ["gradient_accumulation_steps"],
    "local_sgd.py": ["LocalSGD"],
    "memory.py": ["find_executable_batch_size"],
    "profiler.py": ["profile"],
    "quantized_inference.py": ["quantiz"],
    "tensor_parallel.py": ["tp_rules"],
    "tracking.py": ["init_trackers", "log"],
    "big_model_inference.py": ["dispatch", "device_map"],
    "generation.py": ["generate"],
    "megatron_import.py": ["load_megatron_checkpoint", "merge_megatron_tp_shards"],
    "pipeline_inference.py": ["prepare_pippy"],
    "automatic_gradient_accumulation.py": ["find_executable_batch_size", "gradient_accumulation_steps"],
    "multi_process_metrics.py": ["gather_for_metrics"],
    "schedule_free.py": ["schedule_free_adamw", "schedule_free_eval_params"],
    "cross_validation.py": ["fold_split"],
    "fsdp_with_peak_mem_tracking.py": ["FullyShardedDataParallelPlugin", "memory_stats"],
    "long_context_generation.py": ["cp_generate"],
    "distillation.py": ["model=student", "_state_slot"],
    "ddp_comm_hook.py": ["DistributedDataParallelKwargs", "comm_hook"],
    "gradient_accumulation_for_autoregressive_models.py": ["gradient_accumulation_steps", "norm"],
}


def test_by_feature_examples_are_base_plus_one_feature():
    """Structural analog of the reference's example-diff test: each
    by_feature script must build on the shared scaffolding (_base /
    nlp_example) and contain its feature's API calls."""
    by_feature = os.path.join(EXAMPLES, "by_feature")
    scripts = [f for f in os.listdir(by_feature) if f.endswith(".py") and not f.startswith("_")]
    assert set(scripts) == set(_FEATURE_MARKERS), (
        f"by_feature drifted: {sorted(set(scripts) ^ set(_FEATURE_MARKERS))}"
    )
    for script in scripts:
        src = open(os.path.join(by_feature, script)).read()
        assert "_base" in src or "nlp_example" in src, f"{script} does not reuse the base"
        assert len(src.splitlines()) < 200, f"{script} grew beyond base+one-feature size"
        for marker in _FEATURE_MARKERS[script]:
            assert marker in src, f"{script} missing its feature marker {marker!r}"
