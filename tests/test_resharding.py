"""Elastic resharding (resharding.py): spec serialization, collective
classification, budget-batched schedules, the plan-manifest sidecar, the
topology-mismatch guard, safetensors restore across layouts (N->M->N
bit-equal), host-staged fallback, and live plan migration."""

import json
import os

import numpy as np
import pytest


def _reset_state():
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


# ---------------------------------------------------------------------------
# Spec serialization + op classification (pure host-side units)
# ---------------------------------------------------------------------------


def test_spec_jsonable_roundtrip():
    from jax.sharding import PartitionSpec

    from accelerate_tpu.resharding import spec_from_jsonable, spec_to_jsonable

    for spec in (
        PartitionSpec(),
        PartitionSpec("dp_shard"),
        PartitionSpec("dp_shard", None, "tp"),
        PartitionSpec(("dp_replicate", "dp_shard"), "tp"),
        None,
    ):
        entries = spec_to_jsonable(spec)
        # Survives a JSON round-trip (the manifest is JSON on disk).
        entries = json.loads(json.dumps(entries))
        back = spec_to_jsonable(spec_from_jsonable(entries))
        assert back == entries
    assert spec_to_jsonable(None) == []
    assert tuple(spec_from_jsonable([])) == ()


def test_normalize_spec_drops_dead_axes():
    from accelerate_tpu.resharding import normalize_spec

    sizes = {"dp_shard": 4, "tp": 1}
    # A size-1 axis shards nothing; trailing unsharded dims are noise.
    assert normalize_spec(["tp", "dp_shard", None], sizes) == ((), ("dp_shard",))
    assert normalize_spec([None, None], sizes) == ()
    assert normalize_spec([["dp_shard", "tp"]], sizes) == (("dp_shard",),)


def test_classify_op_matrix():
    from accelerate_tpu.resharding import classify_op

    eight = {"dp_shard": 8, "tp": 1}
    four = {"dp_shard": 4, "tp": 2}
    two = {"dp_shard": 2, "tp": 1}

    # Identical spec + degree: nothing to do.
    assert classify_op(["dp_shard"], ["dp_shard"], eight, eight) == "noop"
    # Replicated -> replicated (any device count): broadcast, not a re-tile.
    assert classify_op([], [None], eight, two) == "noop"
    # Replicated -> sharded: every device keeps a slice.
    assert classify_op([], ["dp_shard"], eight, eight) == "slice"
    # Sharded -> replicated: gather.
    assert classify_op(["dp_shard"], [], eight, eight) == "all_gather"
    # Same axis, different degree (shrink 8->2 and grow 2->8): re-tile.
    assert classify_op(["dp_shard"], ["dp_shard"], eight, two) == "all_to_all"
    assert classify_op(["dp_shard"], ["dp_shard"], two, eight) == "all_to_all"
    # Axis permutation (dim0 tp -> dim1 tp): re-tile.
    assert classify_op(["tp", None], [None, "tp"], four, four) == "all_to_all"
    # dp_shard -> tp on the same dim: re-tile.
    assert classify_op(["dp_shard"], ["tp"], eight, four) == "all_to_all"


def test_plan_leaf_transfer_and_budget_batching():
    from accelerate_tpu.resharding import build_schedule, plan_leaf_transfer

    eight = {"dp_shard": 8}
    two = {"dp_shard": 2}
    # Odd leaf shape: bytes math must not assume divisibility.
    t = plan_leaf_transfer("w", (17, 3), "float32", ["dp_shard"], ["dp_shard"], eight, two, 0)
    assert t.nbytes == 17 * 3 * 4 and t.op == "all_to_all"
    # Footprint while transferring = ingest copy + destination shard.
    assert t.device_bytes == 2 * (t.nbytes // 2)

    transfers = [
        plan_leaf_transfer(f"leaf{i}", (64,), "float32", [], ["dp_shard"], eight, two, i)
        for i in range(6)
    ]
    # 64 floats repl->2-way: 256B + 128B = 384B footprint each. A 800B budget
    # fits two per batch; six leaves -> three batches.
    sched = build_schedule(list(transfers), 800)
    assert sched.depth == 3
    assert sched.peak_batch_bytes <= 800
    covered = sorted(i for b in sched.batches for i in b)
    assert covered == list(range(6))
    assert sched.summary()["ops"] == {"slice": 6}

    # A leaf alone over budget is host-staged into its own batch, and its
    # footprint drops to the destination shard alone.
    big = plan_leaf_transfer("zz_big", (1024,), "float32", [], ["dp_shard"], eight, two, 6)
    sched = build_schedule(list(transfers) + [big], 800)
    staged = [t for t in sched.transfers if t.host_staged]
    assert [t.name for t in staged] == ["zz_big"]
    assert staged[0].device_bytes == staged[0].dst_bytes
    assert [6] in sched.batches
    # With host staging off, the oversize leaf stays a device transfer.
    big2 = plan_leaf_transfer("zz_big", (1024,), "float32", [], ["dp_shard"], eight, two, 6)
    sched = build_schedule(list(transfers) + [big2], 800, host_stage_oversize=False)
    assert sched.host_staged_leaves == 0


def test_schedule_from_manifest_and_predict():
    from accelerate_tpu.planner import BandwidthTable
    from accelerate_tpu.resharding import predict_transfer_s, schedule_from_manifest

    manifest = {
        "version": 1,
        "n_devices": 8,
        "layout": {"dp_shard": 8},
        "leaves": {
            "slot0/params/w": {"shape": [256, 64], "dtype": "float32", "spec": ["dp_shard"]},
            "slot0/params/b": {"shape": [64], "dtype": "float32", "spec": []},
            "slot0/opt_state/mu/w": {"shape": [256, 64], "dtype": "float32", "spec": ["dp_shard"]},
        },
    }
    sched = schedule_from_manifest(manifest, {"dp_shard": 2}, 1 << 20)
    s = sched.summary()
    assert s["leaves"] == 3
    # Sharded leaves re-tile 8->2; the replicated bias is untouched.
    assert s["ops"]["all_to_all"] == 2 and s["ops"]["noop"] == 1
    assert s["bytes_transferred"] == 2 * 256 * 64 * 4
    t = predict_transfer_s(sched, BandwidthTable(), 2)
    assert t > 0
    table = sched.format_table()
    assert "slot0/params/w" in table and "all_to_all" in table


# ---------------------------------------------------------------------------
# Integration: manifest sidecar + elastic restore on the safetensors path
# ---------------------------------------------------------------------------


def _setup(pc=None, handlers=None, width=32, seed=3):
    """Small FSDP-sharded dense model; returns (acc, module, loss_fn, batch)."""
    import jax
    import optax
    import flax.linen as nn

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

    _reset_state()
    set_seed(seed)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(width)(x)
            x = nn.relu(x)
            return nn.Dense(1)(x)

    acc = Accelerator(
        parallelism_config=pc,
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size_to_shard=0),
        kwargs_handlers=handlers,
    )
    module = Net()
    x = np.random.default_rng(0).normal(size=(32, 8)).astype(np.float32)
    y = x.sum(-1, keepdims=True).astype(np.float32)
    model = Model.from_flax(module, jax.random.key(0), x[:1])
    model, _ = acc.prepare(model, optax.adam(1e-2))

    def loss_fn(params, batch):
        import jax.numpy as jnp

        pred = module.apply({"params": params}, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    return acc, module, loss_fn, {"x": x, "y": y}


def _host_leaves(state):
    import jax

    return jax.tree.map(
        lambda a: np.asarray(a) if hasattr(a, "shape") else a, state
    )


def _assert_trees_bit_equal(got, want):
    # Compare leaf lists, not the trees: two TrainStates built by different
    # Accelerator instances differ in static aux data (apply_fn, tx).
    import jax

    got_leaves = jax.tree_util.tree_leaves(got)
    want_leaves = jax.tree_util.tree_leaves(want)
    assert len(got_leaves) == len(want_leaves)
    for a, b in zip(got_leaves, want_leaves):
        if hasattr(b, "shape") or hasattr(a, "shape"):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _elastic(**kw):
    from accelerate_tpu.utils import ElasticKwargs

    return ElasticKwargs(**kw)


def test_plan_manifest_sidecar_written_and_matches(tmp_path):
    from accelerate_tpu import ParallelismConfig
    from accelerate_tpu.resharding import read_plan_manifest, topology_matches
    from accelerate_tpu.utils.constants import PLAN_MANIFEST_NAME

    acc, module, loss_fn, batch = _setup(
        ParallelismConfig(dp_shard_size=8), handlers=[_elastic()]
    )
    step = acc.prepare_train_step(loss_fn)
    acc._train_state, _ = step(acc.train_state, batch)
    out = acc.save_state(str(tmp_path / "ck"))
    assert os.path.isfile(os.path.join(out, PLAN_MANIFEST_NAME))

    manifest = read_plan_manifest(out)
    assert manifest is not None
    assert manifest["n_devices"] == 8
    assert manifest["layout"]["dp_shard"] == 8
    # Per-leaf specs recorded under slot-qualified names.
    names = list(manifest["leaves"])
    assert any(n.startswith("slot0/params/") for n in names)
    assert any(n.startswith("slot0/opt_state/") for n in names)
    kernel = manifest["leaves"]["slot0/params/Dense_0/kernel"]
    assert kernel["spec"], "fsdp-sharded kernel must record a non-empty spec"

    live = acc.state.parallelism_config.layout_dict()
    assert topology_matches(manifest, 8, live)
    assert not topology_matches(manifest, 4, live)
    assert not topology_matches(manifest, 8, dict(live, dp_shard=2, tp=4))

    # Without fault tolerance or elastic, the byte layout is unchanged: no
    # sidecar appears.
    acc2, module2, loss_fn2, batch2 = _setup(ParallelismConfig(dp_shard_size=8))
    out2 = acc2.save_state(str(tmp_path / "ck_plain"))
    assert not os.path.exists(os.path.join(out2, PLAN_MANIFEST_NAME))


@pytest.mark.parametrize("direction", ["shrink", "grow"])
def test_topology_mismatch_raises_without_elastic(tmp_path, direction):
    """Mismatch with elastic OFF fails fast, naming both topologies and the
    opt-in — in both directions (N->M and M->N)."""
    from accelerate_tpu import ParallelismConfig
    from accelerate_tpu.resharding import TopologyMismatchError

    wide = ParallelismConfig(dp_shard_size=8)
    narrow = ParallelismConfig(dp_replicate_size=4, dp_shard_size=2)
    src_pc, dst_pc = (wide, narrow) if direction == "shrink" else (narrow, wide)

    acc, module, loss_fn, batch = _setup(src_pc, handlers=[_elastic()])
    out = acc.save_state(str(tmp_path / "ck"))

    acc2, module2, loss_fn2, batch2 = _setup(dst_pc)  # no ElasticKwargs
    with pytest.raises(TopologyMismatchError) as ei:
        acc2.load_state(out)
    msg = str(ei.value)
    assert "dp_shard=8" in msg
    assert "dp_replicate=4" in msg and "dp_shard=2" in msg
    assert "ElasticKwargs" in msg


def test_resize_policy_fail_raises_even_with_elastic(tmp_path):
    from accelerate_tpu import ParallelismConfig
    from accelerate_tpu.resharding import TopologyMismatchError

    acc, *_ = _setup(ParallelismConfig(dp_shard_size=8), handlers=[_elastic()])
    out = acc.save_state(str(tmp_path / "ck"))
    acc2, *_ = _setup(
        ParallelismConfig(dp_replicate_size=2, dp_shard_size=4),
        handlers=[_elastic(resize_policy="fail")],
    )
    with pytest.raises(TopologyMismatchError):
        acc2.load_state(out)


def test_safetensors_reshard_roundtrip_bit_equal(tmp_path):
    """N->M->N: save under dp_shard=8, restore under dp_replicate=2 x
    dp_shard=4 (elastic), save again, restore under dp_shard=8 — every
    TrainState leaf bit-equal throughout, nothing host-staged (every shard
    fits the budget), telemetry carries the reshard block."""
    import jax

    from accelerate_tpu import ParallelismConfig

    wide = lambda: ParallelismConfig(dp_shard_size=8)
    narrow = lambda: ParallelismConfig(dp_replicate_size=2, dp_shard_size=4)

    acc, module, loss_fn, batch = _setup(wide(), handlers=[_elastic()])
    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    for _ in range(2):
        state, _ = step(state, batch)
    acc._train_state = state
    want = _host_leaves(state)
    ck1 = acc.save_state(str(tmp_path / "ck1"))

    # N -> M
    acc2, module2, loss_fn2, batch2 = _setup(narrow(), handlers=[_elastic()])
    acc2.load_state(ck1)
    _assert_trees_bit_equal(acc2.train_state, want)
    # The restore went through the reshard path and reported it.
    stats = acc2.elastic.last_stats
    assert stats is not None and stats["kind"] == "restore"
    assert stats["moved_leaves"] > 0
    assert stats["host_staged"] == 0, "fitting leaves must not gather to host"
    assert stats["peak_batch_bytes"] <= acc2.elastic.staging_budget_bytes
    tel = getattr(acc2, "telemetry", None)
    if tel is not None:
        assert "reshard" in tel.summary()
    # Restored leaves landed on the NARROW plan's shardings (dp_shard=4).
    kernel = acc2.train_state.params["Dense_0"]["kernel"]
    from accelerate_tpu.resharding import mesh_axis_sizes

    assert mesh_axis_sizes(kernel.sharding.mesh)["dp_shard"] == 4
    ck2 = acc2.save_state(str(tmp_path / "ck2"))

    # M -> N
    acc3, module3, loss_fn3, batch3 = _setup(wide(), handlers=[_elastic()])
    acc3.load_state(ck2)
    _assert_trees_bit_equal(acc3.train_state, want)
    # Training continues from the restored state.
    step3 = acc3.prepare_train_step(loss_fn3)
    state3, m3 = step3(acc3.train_state, batch3)
    assert np.isfinite(float(np.asarray(m3["loss"])))


def test_tiny_budget_forces_host_staging_still_exact(tmp_path):
    """A staging budget smaller than any shard demotes every moving leaf to
    the host-staged path — slower, but still bit-exact."""
    from accelerate_tpu import ParallelismConfig

    acc, module, loss_fn, batch = _setup(
        ParallelismConfig(dp_shard_size=8), handlers=[_elastic()], width=256
    )
    want = _host_leaves(acc.train_state)
    out = acc.save_state(str(tmp_path / "ck"))

    acc2, *_ = _setup(
        ParallelismConfig(dp_replicate_size=2, dp_shard_size=4),
        handlers=[_elastic(staging_budget_mb=0.001)],
        width=256,
    )
    acc2.load_state(out)
    _assert_trees_bit_equal(acc2.train_state, want)
    stats = acc2.elastic.last_stats
    assert stats["host_staged"] > 0


def test_tp_axis_change_reshard_bit_equal(tmp_path):
    """Layout change that moves leaves BETWEEN axes (fsdp -> tp): restored
    values bit-equal and tp-sharded params land sharded, not replicated."""
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator, Model, ParallelismConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, llama_tp_rules
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

    ids = np.random.default_rng(0).integers(0, 256, (8, 16), dtype=np.int32)

    def build(pc):
        _reset_state()
        set_seed(0)
        cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
        module = LlamaForCausalLM(cfg)
        acc = Accelerator(
            parallelism_config=pc,
            fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size_to_shard=0),
            kwargs_handlers=[_elastic()],
        )
        model = Model.from_flax(
            module, jax.random.key(0), ids, tp_rules=llama_tp_rules(True)
        )
        acc.prepare(model, optax.adamw(1e-3))
        return acc

    acc = build(ParallelismConfig(dp_shard_size=8))
    want = _host_leaves(acc.train_state)
    out = acc.save_state(str(tmp_path / "ck"))

    acc2 = build(ParallelismConfig(dp_shard_size=4, tp_size=2))
    acc2.load_state(out)
    _assert_trees_bit_equal(acc2.train_state, want)
    stats = acc2.elastic.last_stats
    assert stats["moved_leaves"] > 0 and stats["host_staged"] == 0


# ---------------------------------------------------------------------------
# Live plan migration
# ---------------------------------------------------------------------------


def test_migrate_plan_requires_elastic():
    from accelerate_tpu import ParallelismConfig

    acc, *_ = _setup(ParallelismConfig(dp_shard_size=8))
    with pytest.raises(RuntimeError, match="ElasticKwargs"):
        acc.migrate_plan(ParallelismConfig(dp_shard_size=4, dp_replicate_size=2))


def test_migrate_plan_live_bit_equal_and_trainable():
    """migrate_plan reshards the live TrainState in place: values bit-equal,
    new mesh installed, training continues under the new layout."""
    import jax

    from accelerate_tpu import ParallelismConfig
    from accelerate_tpu.resharding import mesh_axis_sizes

    acc, module, loss_fn, batch = _setup(
        ParallelismConfig(dp_shard_size=8), handlers=[_elastic()]
    )
    step = acc.prepare_train_step(loss_fn)
    state, _ = step(acc.train_state, batch)
    acc._train_state = state
    want = _host_leaves(state)
    old_step = int(np.asarray(state.step))

    stats = acc.migrate_plan(ParallelismConfig(dp_replicate_size=2, dp_shard_size=4))
    assert stats["moved_leaves"] > 0
    assert stats["peak_batch_bytes"] <= acc.elastic.staging_budget_bytes

    pc = acc.state.parallelism_config
    assert pc.dp_shard_size == 4 and pc.dp_replicate_size == 2
    got = acc.train_state
    _assert_trees_bit_equal(got, want)
    assert int(np.asarray(got.step)) == old_step
    kernel = got.params["Dense_0"]["kernel"]
    sizes = mesh_axis_sizes(kernel.sharding.mesh)
    assert sizes["dp_shard"] == 4 and sizes["dp_replicate"] == 2

    # The step fn keeps working on the migrated layout (jit retraces).
    step2 = acc.prepare_train_step(loss_fn)
    state2, m2 = step2(acc.train_state, batch)
    assert np.isfinite(float(np.asarray(m2["loss"])))
    assert int(np.asarray(state2.step)) == old_step + 1

    # A failed migration must roll back to the old mesh.
    with pytest.raises(Exception):
        acc.migrate_plan(ParallelismConfig(dp_shard_size=5))  # 5 does not divide 8
    assert acc.state.parallelism_config.dp_shard_size == 4
