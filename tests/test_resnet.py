"""ResNet family: forward shapes, sync-BN training via mutable_state, eval."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator, Model
from accelerate_tpu.models import ResNet, ResNetConfig, resnet_loss
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _data(n=16, img=32, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, img, img, 3)).astype(np.float32)
    y = rng.integers(0, classes, n).astype(np.int32)
    # Make the task learnable: brighten a quadrant per class.
    for i in range(n):
        q = int(y[i])
        r, c = divmod(q, 2)
        x[i, r * 16:(r + 1) * 16, c * 16:(c + 1) * 16] += 2.0
    return jnp.asarray(x), jnp.asarray(y)


def test_resnet_forward_shapes_and_dtype():
    _reset()
    set_seed(0)
    cfg = ResNetConfig.tiny()
    module = ResNet(cfg)
    x, _ = _data(4)
    variables = module.init(jax.random.key(0), x)
    logits = module.apply(variables, x)
    assert logits.shape == (4, cfg.num_classes)
    assert logits.dtype == jnp.float32
    assert "batch_stats" in variables


def test_resnet50_parameter_count():
    """ResNet-50 must be the real architecture: ~25.6M params."""
    cfg = ResNetConfig.resnet50()
    module = ResNet(cfg)
    shapes = jax.eval_shape(
        lambda: module.init(jax.random.key(0), jnp.zeros((1, 224, 224, 3)))
    )
    n = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(shapes["params"]))
    assert 25.0e6 < n < 26.2e6, n


def test_resnet_trains_with_mutable_batch_stats():
    """Fused step with mutable_state=True: loss decreases AND running stats
    move (sync-BN under the dp-sharded batch axis)."""
    _reset()
    set_seed(0)
    cfg = ResNetConfig.tiny(dtype=jnp.float32)
    module = ResNet(cfg)
    x, y = _data(16)

    acc = Accelerator(fsdp_plugin=FullyShardedDataParallelPlugin())
    model = Model.from_flax(module, jax.random.key(0), x)
    assert model.extra_state and "batch_stats" in model.extra_state
    model, _ = acc.prepare(model, optax.adam(1e-2))

    def loss_fn(params, extra, batch):
        return resnet_loss(module, params, extra, batch["x"], batch["y"])

    step = acc.prepare_train_step(loss_fn, mutable_state=True)
    state = acc.train_state
    stats0 = jax.tree.map(np.asarray, state.extra_state)
    losses = []
    for _ in range(12):
        state, metrics = step(state, {"x": x, "y": y})
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses
    moved = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(np.abs(np.asarray(a) - b).max()), state.extra_state, stats0
    ))
    assert max(moved) > 1e-3, "batch_stats must update through the fused step"

    # Eval path consumes the trained running stats.
    logits = module.apply({"params": state.params, **state.extra_state}, x, train=False)
    acc_eval = float((jnp.argmax(logits, -1) == y).mean())
    assert acc_eval > 0.5, acc_eval


def test_resnet_mutable_state_with_grad_accum():
    _reset()
    set_seed(0)
    cfg = ResNetConfig.tiny(dtype=jnp.float32)
    module = ResNet(cfg)
    x, y = _data(16)
    acc = Accelerator(gradient_accumulation_steps=2)
    model = Model.from_flax(module, jax.random.key(0), x)
    model, _ = acc.prepare(model, optax.adam(1e-2))

    def loss_fn(params, extra, batch):
        return resnet_loss(module, params, extra, batch["x"], batch["y"])

    step = acc.prepare_train_step(loss_fn, mutable_state=True)
    state = acc.train_state
    state, metrics = step(state, {"x": x, "y": y})
    assert np.isfinite(float(metrics["loss"]))


def test_resnet_batch_stats_survive_save_load(tmp_path):
    """save_state/load_state round-trips extra_state (running BN stats)."""
    _reset()
    set_seed(0)
    cfg = ResNetConfig.tiny(dtype=jnp.float32)
    module = ResNet(cfg)
    x, y = _data(16)
    acc = Accelerator(project_dir=str(tmp_path))
    model = Model.from_flax(module, jax.random.key(0), x)
    model, _ = acc.prepare(model, optax.adam(1e-2))

    def loss_fn(params, extra, batch):
        return resnet_loss(module, params, extra, batch["x"], batch["y"])

    step = acc.prepare_train_step(loss_fn, mutable_state=True)
    state, _ = step(acc.train_state, {"x": x, "y": y})
    trained_stats = jax.tree.map(np.asarray, state.extra_state)
    out = acc.save_state(str(tmp_path / "ckpt"))

    # Clobber the live stats, then restore.
    acc._train_state = acc.train_state.replace(
        extra_state=jax.tree.map(jnp.zeros_like, acc.train_state.extra_state)
    )
    acc.load_state(out)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), b, rtol=1e-6),
        acc.train_state.extra_state, trained_stats,
    )
