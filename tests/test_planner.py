"""Auto-parallelism planner: enumeration constraints, cost-model
monotonicity, the remat/microbatch escalation ladder, plan artifacts
(determinism, roundtrip, version guard, calibration write-back), the CLI
table, and the Accelerator auto path (resolution, cache, default-off)."""

import argparse
import dataclasses
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.parallelism_config import ParallelismConfig
from accelerate_tpu.planner import (
    BandwidthTable,
    ModelProfile,
    ParallelPlan,
    Planner,
    PlannerError,
    PlanVersionError,
    enumerate_layouts,
    layout_str,
    predict_step_time,
    record_calibration,
)

TINY_PROFILE = ModelProfile(
    params=500_000, hidden=128, heads=4, kv_heads=2, layers=2,
    intermediate=384, vocab=256, label="tiny",
)


def _tiny_planner(**kw):
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    defaults = dict(n_devices=8, hbm_gib=16.0, seq=64, per_chip_batch=1,
                    label="llama:tiny")
    defaults.update(kw)
    return Planner(module, cfg, **defaults)


# ----------------------------------------------------------------------
# Enumeration
# ----------------------------------------------------------------------

def test_enumerate_covers_devices_and_divisibility():
    cands = enumerate_layouts(8, TINY_PROFILE, seq=64)
    assert cands, "no candidates on 8 devices"
    for pc in cands:
        assert pc.total_size == 8
        assert TINY_PROFILE.heads % pc.tp_size == 0
        assert TINY_PROFILE.kv_heads % pc.tp_size == 0
        assert TINY_PROFILE.layers % pc.pp_size == 0
        assert 64 % pc.cp_size == 0


def test_enumerate_head_constraint_prunes_tp():
    # kv_heads=2 → tp>2 impossible even though heads=4 would allow tp=4.
    tps = {pc.tp_size for pc in enumerate_layouts(8, TINY_PROFILE, seq=64)}
    assert tps == {1, 2}


def test_enumerate_layer_constraint_prunes_pp():
    # layers=2 → pp in {1, 2}; pp=4/8 pruned.
    pps = {pc.pp_size for pc in enumerate_layouts(8, TINY_PROFILE, seq=64)}
    assert pps == {1, 2}


def test_enumerate_seq_constraint_prunes_cp():
    # seq=4 → cp in {1, 2, 4}; cp=8 pruned.
    cps = {pc.cp_size for pc in enumerate_layouts(8, TINY_PROFILE, seq=4)}
    assert cps == {1, 2, 4}


def test_enumerate_expert_constraint():
    moe = dataclasses.replace(TINY_PROFILE, experts=4)
    cands = enumerate_layouts(8, moe, seq=64)
    eps = {pc.ep_size for pc in cands}
    assert eps == {1, 2, 4}
    for pc in cands:
        assert moe.experts % pc.ep_size == 0
        pc.ep_axes  # must be expressible as whole axes (raises otherwise)
    # Dense model: ep never enumerated.
    assert {pc.ep_size for pc in enumerate_layouts(8, TINY_PROFILE, seq=64)} == {1}


def test_enumerate_pinned_axis():
    cands = enumerate_layouts(8, TINY_PROFILE, seq=64, pinned={"tp": 2})
    assert cands and all(pc.tp_size == 2 for pc in cands)
    # Impossible pin → dedicated error naming the constraint context.
    with pytest.raises(PlannerError):
        enumerate_layouts(8, TINY_PROFILE, seq=64, pinned={"tp": 8})
    with pytest.raises(PlannerError):
        enumerate_layouts(8, TINY_PROFILE, seq=64, pinned={"bogus": 2})


def test_enumerate_restricted_axes():
    cands = enumerate_layouts(8, TINY_PROFILE, seq=64,
                              axes=("dp_replicate", "dp_shard"))
    assert all(pc.tp_size == 1 and pc.cp_size == 1 and pc.pp_size == 1
               for pc in cands)
    layouts = {(pc.dp_replicate_size, pc.dp_shard_size) for pc in cands}
    assert (1, 8) in layouts and (8, 1) in layouts and (2, 4) in layouts


# ----------------------------------------------------------------------
# Cost model
# ----------------------------------------------------------------------

def test_cost_more_tp_more_collective_bytes():
    bw = BandwidthTable()
    prof = dataclasses.replace(TINY_PROFILE, heads=8, kv_heads=8)
    byte_counts = []
    for tp in (1, 2, 4, 8):
        pc = ParallelismConfig(tp_size=tp)
        cost = predict_step_time(prof, pc, bw, seq=64, per_chip_batch=1)
        byte_counts.append(cost.tp_bytes)
    assert byte_counts[0] == 0
    assert byte_counts == sorted(byte_counts)
    assert byte_counts[-1] > byte_counts[1] > 0


def test_cost_more_dp_shard_less_hbm():
    planner = _tiny_planner()
    rows2 = planner._memory_estimate(
        ParallelismConfig(dp_replicate_size=4, dp_shard_size=2), False, "flash", 1
    )
    rows8 = planner._memory_estimate(
        ParallelismConfig(dp_shard_size=8), False, "flash", 1
    )
    assert rows8["params_gib"] < rows2["params_gib"]
    assert rows8["opt_state_gib"] < rows2["opt_state_gib"]
    assert rows8["total_gib"] < rows2["total_gib"]


def test_cost_pp_bubble_shrinks_with_microbatches():
    bw = BandwidthTable(microbatch_overhead_s=0.0)
    pc = ParallelismConfig(dp_shard_size=4, pp_size=2)
    prof = dataclasses.replace(TINY_PROFILE, params=10**9)
    costs = [
        predict_step_time(prof, pc, bw, seq=64, per_chip_batch=1, microbatches=m)
        for m in (2, 4, 8)
    ]
    bubbles = [c.bubble_fraction for c in costs]
    assert bubbles == sorted(bubbles, reverse=True)
    assert costs[0].step_s > costs[-1].step_s  # bubble dominates at m=pp
    # With per-microbatch overhead, m → ∞ stops paying.
    bw2 = BandwidthTable(microbatch_overhead_s=1.0)
    c_small = predict_step_time(prof, pc, bw2, seq=64, per_chip_batch=1, microbatches=2)
    c_huge = predict_step_time(prof, pc, bw2, seq=64, per_chip_batch=1, microbatches=64)
    assert c_huge.microbatch_overhead_s > c_small.microbatch_overhead_s


def test_cost_compute_is_layout_invariant():
    bw = BandwidthTable()
    prof = dataclasses.replace(TINY_PROFILE, heads=8, kv_heads=8)
    c1 = predict_step_time(prof, ParallelismConfig(dp_shard_size=8), bw,
                           seq=64, per_chip_batch=1)
    c2 = predict_step_time(prof, ParallelismConfig(dp_shard_size=4, tp_size=2),
                           bw, seq=64, per_chip_batch=1)
    assert c1.compute_s == pytest.approx(c2.compute_s)


def test_bandwidth_table_roundtrip_and_validation():
    bw = BandwidthTable(ici_gbps=45.0, mfu=0.35)
    assert BandwidthTable.from_dict(bw.to_dict()) == bw
    assert BandwidthTable.from_dict(None) == BandwidthTable()
    with pytest.raises(ValueError, match="unknown BandwidthTable field"):
        BandwidthTable.from_dict({"warp_speed": 9})


# ----------------------------------------------------------------------
# Escalation ladder & over-budget
# ----------------------------------------------------------------------

def test_remat_escalation_ladder():
    """Tighter budgets escalate: no remat → selective → full; an absurd
    budget leaves every rung over budget (best-effort plan)."""
    generous = _tiny_planner(hbm_gib=16.0).search()
    assert generous.remat is False and not generous.over_budget

    planner = _tiny_planner()
    pc = ParallelismConfig(dp_shard_size=8)
    none_rows = planner._memory_estimate(pc, False, "flash", 1)
    sel_rows = planner._memory_estimate(pc, True, "flash", 1)
    full_rows = planner._memory_estimate(pc, True, "minimal", 1)
    assert full_rows["activations_gib"] < sel_rows["activations_gib"] \
        < none_rows["activations_gib"]

    # Budget squeezed between the selective and no-remat activation rows →
    # the ladder lands on a remat rung for this layout.
    squeeze = sel_rows["total_gib"] + (
        none_rows["total_gib"] - sel_rows["total_gib"]
    ) / 2
    tight = _tiny_planner(hbm_gib=squeeze, axes=("dp_shard",),
                          pinned={"dp_shard": 8}).search()
    assert tight.remat is True and not tight.over_budget


def test_over_budget_best_effort_plan(caplog):
    import logging

    with caplog.at_level(logging.WARNING, logger="accelerate_tpu.planner"):
        plan = _tiny_planner(hbm_gib=1e-9).search()
    assert plan.over_budget is True
    assert any("best-effort" in r.message for r in caplog.records)
    # Best effort = the minimum-HBM point; every rejection is over budget too.
    for rej in plan.rejections:
        if rej.get("layout") is not None:
            assert "over_budget" in rej["reason"]
            assert rej["predicted_hbm_gib"] >= plan.predicted_hbm_gib


def test_microbatch_escalation_subdivides_batch():
    planner = _tiny_planner(per_chip_batch=8)
    pc = ParallelismConfig(dp_shard_size=8)
    m1 = planner._memory_estimate(pc, True, "minimal", 1)
    m8 = planner._memory_estimate(pc, True, "minimal", 8)
    assert m8["activations_gib"] < m1["activations_gib"]
    assert 8 in planner._microbatch_ladder(pc)


# ----------------------------------------------------------------------
# Plan artifact
# ----------------------------------------------------------------------

def test_plan_json_roundtrip_and_determinism():
    p1 = _tiny_planner().search()
    p2 = _tiny_planner().search()
    assert p1.to_json() == p2.to_json()  # byte-identical
    rt = ParallelPlan.from_json(p1.to_json())
    assert rt == p1
    assert rt.to_parallelism_config().total_size == 8


def test_plan_version_guard():
    plan = _tiny_planner().search()
    d = plan.to_json_dict()
    d["version"] = 99
    with pytest.raises(PlanVersionError, match="version 99"):
        ParallelPlan.from_json_dict(d)


def test_plan_cache_roundtrip_no_research(tmp_path):
    planner = _tiny_planner()
    plan1, path1, cached1 = planner.resolve(str(tmp_path))
    assert cached1 is False and planner.searches == 1
    assert os.path.exists(path1)

    planner2 = _tiny_planner()
    plan2, path2, cached2 = planner2.resolve(str(tmp_path))
    assert cached2 is True and planner2.searches == 0  # no re-search
    assert path2 == path1 and plan2.layout == plan1.layout

    # Different inputs → different key → fresh search.
    planner3 = _tiny_planner(seq=128)
    _, path3, cached3 = planner3.resolve(str(tmp_path))
    assert cached3 is False and path3 != path1


def test_calibration_write_back(tmp_path):
    planner = _tiny_planner()
    plan, path, _ = planner.resolve(str(tmp_path))
    cal = record_calibration(
        path, measured_step_s=plan.predicted_step_s * 2,
        measured_peak_hbm_gib=plan.predicted_hbm_gib * 0.5, steps=10,
    )
    assert cal["runs"] == 1 and cal["steps"] == 10
    assert cal["step_time_ratio"] == pytest.approx(2.0)
    assert cal["hbm_ratio"] == pytest.approx(0.5)
    # 2x slower than predicted → the effective MFU halves.
    assert cal["mfu_effective"] == pytest.approx(
        plan.bandwidths["mfu"] / 2, rel=1e-4
    )
    # Second run blends (running mean) and increments runs.
    cal2 = record_calibration(
        path, measured_step_s=plan.predicted_step_s * 4, steps=10,
    )
    assert cal2["runs"] == 2 and cal2["steps"] == 20
    assert cal2["step_time_ratio"] == pytest.approx(3.0)
    # The artifact on disk carries it and a cache hit feeds mfu back.
    reloaded = ParallelPlan.load(path)
    assert reloaded.calibration["runs"] == 2
    planner4 = _tiny_planner()
    planner4.resolve(str(tmp_path))
    assert planner4.bandwidths.mfu == pytest.approx(cal2["mfu_effective"])

    # Calibration on a missing file is a no-op, not a crash.
    assert record_calibration(str(tmp_path / "nope.json"),
                              measured_step_s=1.0) is None


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------

def _run_cli(argv):
    from accelerate_tpu.commands.accelerate_cli import build_parser

    args = build_parser().parse_args(argv)
    return args.func(args)


def test_cli_plan_table(capsys):
    rc = _run_cli(["plan", "llama:tiny", "--devices", "8", "--seq", "64"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "chosen" in out and "rank" in out and "HBM (GiB)" in out
    assert "slower" in out or "over_budget" in out


def test_cli_plan_json_and_artifact(tmp_path, capsys):
    out_path = str(tmp_path / "plan.json")
    rc = _run_cli(["plan", "llama:tiny", "--devices", "8", "--seq", "64",
                   "--json", "--out", out_path])
    printed = capsys.readouterr().out
    assert rc == 0
    payload = json.loads(printed)
    assert payload["version"] == 1 and payload["n_devices"] == 8
    # The artifact is loadable and identical to stdout.
    plan = ParallelPlan.load(out_path)
    assert plan.to_json_dict() == payload


def test_cli_plan_pinned_axis_override(tmp_path, capsys):
    out_path = str(tmp_path / "plan.json")
    rc = _run_cli(["plan", "llama:tiny", "--devices", "8", "--seq", "64",
                   "--pin", "tp=2", "--json", "--out", out_path])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["layout"]["tp"] == 2
    for rej in payload["rejections"]:
        if rej.get("layout") is not None:
            assert rej["layout"]["tp"] == 2
    # Impossible pin → clean CLI error, not a traceback.
    rc2 = _run_cli(["plan", "llama:tiny", "--devices", "8", "--seq", "64",
                    "--pin", "tp=8"])
    assert rc2 == 2


def test_cli_estimate_memory_plan_flag(tmp_path, capsys):
    out_path = str(tmp_path / "plan.json")
    _run_cli(["plan", "llama:tiny", "--devices", "8", "--seq", "64",
              "--out", out_path])
    capsys.readouterr()
    rc = _run_cli(["estimate-memory", "llama:tiny", "--dtypes", "fp32",
                   "--plan", out_path, "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["seq"] == 64  # shape came from the plan, not the default
    assert payload["per_chip"]["fits"] is True
    # Colon syntax + dp alias (satellite): 'dp:2,tp:4' parses.
    from accelerate_tpu.commands.estimate import _parse_parallelism

    pc = _parse_parallelism("dp:2,tp:4")
    assert pc.dp_shard_size == 2 and pc.tp_size == 4


# ----------------------------------------------------------------------
# Accelerator wiring
# ----------------------------------------------------------------------

def _reset_state():
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()


def _prepare_auto(tmp_path, **handler_kw):
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.utils import AutoPlanKwargs, set_seed

    _reset_state()
    set_seed(0)
    defaults = dict(hbm_gib=16.0, seq=32, per_chip_batch=1)
    defaults.update(handler_kw)
    acc = Accelerator(
        parallelism_config="auto",
        project_dir=str(tmp_path),
        kwargs_handlers=[AutoPlanKwargs(**defaults)],
    )
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    ids = np.zeros((8, 9), np.int32)
    model = Model.from_flax(module, jax.random.key(0), ids)
    model, _ = acc.prepare(model, optax.adamw(1e-3))
    return acc, model


def test_accelerator_auto_resolves_and_caches(tmp_path):
    acc, _ = _prepare_auto(tmp_path)
    assert acc.active_plan is not None
    assert acc.active_plan_meta["from_cache"] is False
    assert os.path.exists(acc.active_plan_meta["path"])
    assert acc.parallelism_config is not None
    assert acc.parallelism_config.total_size == 8
    assert acc.mesh is not None
    # The installed mesh matches the plan's layout.
    for ax in ("dp_shard", "tp"):
        assert acc.mesh.shape[ax] == acc.active_plan.layout[ax]

    acc2, _ = _prepare_auto(tmp_path)
    assert acc2.active_plan_meta["from_cache"] is True
    assert acc2.active_plan.layout == acc.active_plan.layout


def test_accelerator_auto_pinned(tmp_path):
    acc, model = _prepare_auto(tmp_path, pinned={"tp": 2})
    assert acc.active_plan.layout["tp"] == 2
    assert acc.mesh.shape["tp"] == 2
    # The plan's TP rule table was installed so params really shard.
    assert model.tp_rules


def test_accelerator_default_off(tmp_path):
    """No AutoPlanKwargs, no "auto": the planner never runs — no plans dir,
    no active plan, parallelism_config untouched (the pinned default-off
    contract every subsystem follows)."""
    import optax

    from accelerate_tpu import Accelerator, Model

    _reset_state()
    acc = Accelerator(project_dir=str(tmp_path))
    assert acc.active_plan is None and acc.active_plan_meta is None
    assert acc.auto_plan_handler is None and acc._auto_plan_pending is False
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = Model.from_flax(
        LlamaForCausalLM(cfg), jax.random.key(0), np.zeros((8, 9), np.int32)
    )
    acc.prepare(model, optax.adamw(1e-3))
    assert acc.active_plan is None
    assert not os.path.exists(os.path.join(str(tmp_path), "plans"))


def test_accelerator_explicit_config_wins(tmp_path):
    """AutoPlanKwargs + an explicit ParallelismConfig → the explicit config
    is honored and the planner defers (warning, no artifact)."""
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.utils import AutoPlanKwargs

    _reset_state()
    acc = Accelerator(
        project_dir=str(tmp_path),
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        kwargs_handlers=[AutoPlanKwargs(seq=32)],
    )
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = Model.from_flax(
        LlamaForCausalLM(cfg), jax.random.key(0), np.zeros((8, 9), np.int32)
    )
    acc.prepare(model, optax.adamw(1e-3))
    assert acc.active_plan is None
    assert acc.parallelism_config.dp_shard_size == 8


def test_accelerator_bad_auto_string():
    from accelerate_tpu import Accelerator

    _reset_state()
    with pytest.raises(ValueError, match="'auto'"):
        Accelerator(parallelism_config="automagic")


def test_auto_plan_kwargs_validation():
    from accelerate_tpu.utils import AutoPlanKwargs

    with pytest.raises(ValueError):
        AutoPlanKwargs(hbm_gib=0)
    with pytest.raises(ValueError):
        AutoPlanKwargs(seq=0)
    with pytest.raises(ValueError, match="unknown search axes"):
        AutoPlanKwargs(axes=("dp_shard", "warp"))


def test_telemetry_plan_block_and_calibration(tmp_path):
    """note_plan → summary 'plan' block; calibration lands in the artifact
    after calibrate_after steps (driven through the real recorder)."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.telemetry import TelemetryRecorder
    from accelerate_tpu.utils import TelemetryKwargs

    _reset_state()
    acc = Accelerator(project_dir=str(tmp_path))
    rec = TelemetryRecorder(
        acc, TelemetryKwargs(log_every=0, straggler_probe_every=0)
    )
    plan, path, _ = _tiny_planner().resolve(str(tmp_path))
    rec.note_plan(plan.to_json_dict(), path, calibrate_after=3)

    def fake_step(state, batch):
        return state

    batch = {"input_ids": np.zeros((8, 32), np.int32)}
    for _ in range(4):
        rec.on_train_step(fake_step, batch, wall_s=0.01)
    block = rec.summary()["plan"]
    assert block["layout"] == plan.layout
    assert block["calibrated"] is True
    assert block["measured_step_p50_s"] == pytest.approx(0.01)
    cal = ParallelPlan.load(path).calibration
    assert cal and cal["runs"] == 1 and cal["measured_step_s"] == pytest.approx(0.01)
    rec.close()


def test_layout_str():
    assert layout_str({"dp_shard": 8, "tp": 1}) == "dp_shard=8"
    assert layout_str({"tp": 1}) == "single-device"
