"""CP (sequence-sharded) generation: flash-decoding over the ring.

Pins cp_generate's greedy output token-for-token to the single-chip
generation.generate path, on a cp=2 x dp mesh — the long-context inference
capability the reference's (training-only) context parallelism lacks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Model, generate
from accelerate_tpu.cp_generation import cp_generate
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.parallelism_config import ParallelismConfig
from accelerate_tpu.utils import set_seed


@pytest.fixture(autouse=True)
def _reset():
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    yield


def _cp_mesh(cp=2):
    from accelerate_tpu.state import AcceleratorState

    n = len(jax.devices())
    pc = ParallelismConfig(cp_size=cp, dp_shard_size=n // cp)
    state = AcceleratorState(parallelism_config=pc)
    return state.mesh


def _model(seq_budget=64):
    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 8), dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), ids)
    return cfg, model


def test_cp_greedy_matches_single_chip():
    mesh = _cp_mesh(cp=2)
    cfg, model = _model()
    prompt = np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 16), dtype=np.int32)
    ref = generate(model, prompt, max_new_tokens=8)
    got = cp_generate(model, prompt, max_new_tokens=8, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_cp_prefix_cache_is_sequence_sharded():
    mesh = _cp_mesh(cp=2)
    cfg, model = _model()
    from accelerate_tpu.cp_generation import _prefill
    from jax.sharding import NamedSharding, PartitionSpec as P

    prompt = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab_size, (2, 16)), jnp.int32
    )
    ids = jax.device_put(prompt, NamedSharding(mesh, P(None, "cp")))

    @jax.jit
    def run(p, i):
        logits, pk, pv = _prefill(cfg, p, i, mesh)
        pk = jax.lax.with_sharding_constraint(
            pk, NamedSharding(mesh, P(None, None, "cp", None, None))
        )
        return logits, pk

    _, pk = run(model.params, ids)
    # Seq axis (dim 2) split over cp=2: each shard holds 8 of 16 positions.
    shard_shapes = {s.data.shape for s in pk.addressable_shards}
    assert all(shape[2] == 8 for shape in shard_shapes), shard_shapes


def test_cp_generate_eos_padding():
    mesh = _cp_mesh(cp=2)
    cfg, model = _model()
    prompt = np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 8), dtype=np.int32)
    ref = generate(model, prompt, max_new_tokens=6)
    eos = int(np.asarray(ref)[0, 8 + 2])  # force an early EOS on row 0
    got = cp_generate(model, prompt, max_new_tokens=6, eos_token_id=eos,
                      pad_token_id=0, mesh=mesh)
    ref_eos = generate(model, prompt, max_new_tokens=6, eos_token_id=eos, pad_token_id=0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref_eos))


def test_cp_first_token_eos_pads_rest():
    """EOS on the very first generated token must pad everything after —
    the finished0 wiring between prefill and the decode loop."""
    mesh = _cp_mesh(cp=2)
    cfg, model = _model()
    prompt = np.random.default_rng(5).integers(0, cfg.vocab_size, (2, 8), dtype=np.int32)
    free = generate(model, prompt, max_new_tokens=4)
    eos = int(np.asarray(free)[0, 8])  # row 0's first generated token
    ref = generate(model, prompt, max_new_tokens=4, eos_token_id=eos, pad_token_id=1)
    got = cp_generate(model, prompt, max_new_tokens=4, eos_token_id=eos,
                      pad_token_id=1, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert list(np.asarray(got)[0, 9:]) == [1, 1, 1]  # padded after first-token EOS


def test_cp_sampling_reproducible():
    mesh = _cp_mesh(cp=2)
    cfg, model = _model()
    prompt = np.random.default_rng(4).integers(0, cfg.vocab_size, (1, 8), dtype=np.int32)
    a = cp_generate(model, prompt, max_new_tokens=5, temperature=0.8,
                    rng=jax.random.key(7), mesh=mesh)
    b = cp_generate(model, prompt, max_new_tokens=5, temperature=0.8,
                    rng=jax.random.key(7), mesh=mesh)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cp_prompt_not_divisible_raises():
    mesh = _cp_mesh(cp=2)
    cfg, model = _model()
    prompt = np.zeros((1, 9), np.int32)
    with pytest.raises(ValueError, match="divide"):
        cp_generate(model, prompt, max_new_tokens=2, mesh=mesh)
