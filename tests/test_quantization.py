"""int8/int4 weight-only quantization (utils/quantization.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_int8_roundtrip_error():
    from accelerate_tpu.utils import dequantize_tensor, quantize_tensor_int8

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
    qt = quantize_tensor_int8(w)
    assert qt.data.dtype == jnp.int8
    back = dequantize_tensor(qt, jnp.float32)
    # int8 per-channel: ~0.5/127 of the channel amax worst case.
    err = float(jnp.max(jnp.abs(back - w)))
    amax = float(jnp.max(jnp.abs(w)))
    assert err <= amax / 127.0 * 1.01, (err, amax)


def test_int4_pack_unpack_exact():
    from accelerate_tpu.utils.quantization import _unpack_int4

    vals = jnp.asarray(np.arange(16, dtype=np.uint8).repeat(2)[:28].reshape(28, 1))
    packed = (vals[1::2] << 4) | vals[0::2]
    unpacked = _unpack_int4(packed)
    np.testing.assert_array_equal(np.asarray(unpacked), np.asarray(vals))


def test_int4_roundtrip_grouped():
    from accelerate_tpu.utils import dequantize_tensor, quantize_tensor_int4

    rng = np.random.default_rng(1)
    for shape in [(130, 48), (2, 128, 48)]:  # pad case + stacked scan-layer case
        w = jnp.asarray(rng.normal(size=shape).astype(np.float32))
        qt = quantize_tensor_int4(w, group_size=64)
        back = dequantize_tensor(qt, jnp.float32)
        assert back.shape == w.shape
        # NF4 is MSE-optimal for gaussian weights: judge by normalized RMS
        # (its max error near the distribution tails is deliberately coarse).
        err = np.asarray(back - w)
        rms = float(np.sqrt((err**2).mean()) / np.abs(np.asarray(w)).max())
        assert rms < 0.05, (shape, rms)
    # Packed storage ~half a byte per weight (+ scales) on group-aligned shapes.
    w = jnp.asarray(rng.normal(size=(128, 48)).astype(np.float32))
    assert quantize_tensor_int4(w, 64).nbytes_packed < w.size * 0.75


def test_quantize_params_filters():
    from accelerate_tpu.utils import QuantizationConfig, quantize_params
    from accelerate_tpu.utils.quantization import is_quantized

    rng = np.random.default_rng(2)
    params = {
        "mlp": {"kernel": jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))},
        "norm": {"scale": jnp.ones((128,), jnp.float32)},            # 1-D: skip
        "small": {"kernel": jnp.ones((4, 4), jnp.float32)},          # tiny: skip
        "head": {"kernel": jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))},
    }
    cfg = QuantizationConfig(load_in_8bit=True, skip_modules=["head"])
    q = quantize_params(params, cfg)
    assert is_quantized(q["mlp"]["kernel"])
    assert not is_quantized(q["norm"]["scale"])
    assert not is_quantized(q["small"]["kernel"])
    assert not is_quantized(q["head"]["kernel"])


def test_mutually_exclusive_bits():
    from accelerate_tpu.utils import QuantizationConfig

    with pytest.raises(ValueError):
        QuantizationConfig(load_in_8bit=True, load_in_4bit=True)
    with pytest.raises(ValueError):
        QuantizationConfig()


@pytest.mark.parametrize("bits", [8, 4])
def test_load_and_quantize_llama(bits):
    """Quantized tiny-Llama forward stays close to fp32 and shrinks storage."""
    from accelerate_tpu import Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.utils import (
        QuantizationConfig,
        load_and_quantize_model,
        quantized_nbytes,
    )

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 16), dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), ids)
    ref_logits = np.asarray(model(ids), np.float32)
    full_bytes = sum(l.nbytes for l in jax.tree.leaves(model.params))

    qcfg = QuantizationConfig(
        load_in_8bit=bits == 8, load_in_4bit=bits == 4, compute_dtype=jnp.float32
    )
    qm = load_and_quantize_model(model, qcfg)
    q_logits = np.asarray(qm(ids), np.float32)
    assert quantized_nbytes(qm.params) < full_bytes * (0.45 if bits == 8 else 0.35)
    # Logits track full precision closely (random tiny nets have near-uniform
    # logits, so cosine similarity is the robust check; argmax agreement is a
    # secondary, looser one).
    cos = np.sum(q_logits * ref_logits) / (
        np.linalg.norm(q_logits) * np.linalg.norm(ref_logits)
    )
    # int8 tracks tightly; NF4 on an UNTRAINED gaussian net is a worst case
    # (no outlier structure, tails dominate) — real checkpoints do better.
    assert cos > (0.999 if bits == 8 else 0.94), cos
    if bits == 8:
        # 32 positions on a near-uniform random net: each flipped argmax moves
        # the rate by 1/32, so the bar must sit off the quantization noise
        # floor — 0.85 sat exactly one flip above typical (27/32 observed).
        agree = np.mean(np.argmax(q_logits, -1) == np.argmax(ref_logits, -1))
        assert agree >= 0.8, agree


def test_int8_decode_quant_token_parity():
    """round 4: DecodeQuant int8 decode path — generate() with int8 stacked
    kernels must be token-identical to generate() with the SAME quantization
    error applied via explicit dequantization (pins the mechanism, not the
    quantization error)."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu import Model, generate
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.utils.quantization import (
        DecodeQuant,
        dequantize_decode_kernel,
        quantize_model_for_decode,
        quantized_nbytes,
    )

    cfg = LlamaConfig.tiny(dtype=jnp.float32, scan_layers=True)
    module = LlamaForCausalLM(cfg)
    ids = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % cfg.vocab_size
    model = Model.from_flax(module, jax.random.key(0), ids)

    qm = quantize_model_for_decode(model)
    # block kernels became DecodeQuant; embed/lm_head/norms stayed arrays
    blk = qm.params["model"]["layers"]["block"]
    assert isinstance(blk["self_attn"]["q_proj"]["kernel"], DecodeQuant)
    assert not isinstance(qm.params["model"]["embed_tokens"]["embedding"], DecodeQuant)
    assert quantized_nbytes(qm.params) < quantized_nbytes(model.params)

    out_q = np.asarray(generate(qm, ids, max_new_tokens=6))

    deq = jax.tree.map(
        lambda x: dequantize_decode_kernel(x, jnp.float32)
        if isinstance(x, DecodeQuant) else x,
        qm.params,
        is_leaf=lambda x: isinstance(x, DecodeQuant),
    )
    ref = Model.__new__(Model)
    ref.__dict__.update(model.__dict__)
    ref.params = deq
    out_ref = np.asarray(generate(ref, ids, max_new_tokens=6))
    np.testing.assert_array_equal(out_q, out_ref)

    # and the quantized path still decodes something coherent vs full precision
    out_full = np.asarray(generate(model, ids, max_new_tokens=6))
    assert out_q.shape == out_full.shape


def test_decode_quant_detaches_from_prepared_state():
    """Quantizing a PREPARED model must not write int8 leaves into the live
    accelerator train state (the params setter writes through), and the
    returned copy is generate-only."""
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils.quantization import (
        DecodeQuant,
        quantize_model_for_decode,
    )

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    cfg = LlamaConfig.tiny(dtype=jnp.float32, scan_layers=True)
    module = LlamaForCausalLM(cfg)
    ids = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % cfg.vocab_size
    model = Model.from_flax(module, jax.random.key(0), ids)
    acc = Accelerator()
    model, _ = acc.prepare(model, optax.adam(1e-3))

    qm = quantize_model_for_decode(model)
    # live train state untouched (full-precision arrays, not DecodeQuant)
    live = acc.train_state.params["model"]["layers"]["block"]["self_attn"]["q_proj"]["kernel"]
    assert not isinstance(live, DecodeQuant)
    assert isinstance(
        qm.params["model"]["layers"]["block"]["self_attn"]["q_proj"]["kernel"], DecodeQuant
    )
    with pytest.raises(ValueError, match="generate"):
        qm(ids)


def test_decode_quant_rejects_non_llama_layout():
    from accelerate_tpu.utils.quantization import quantize_model_for_decode

    class Fake:
        params = {"wte": np.zeros((4, 4))}

    with pytest.raises(ValueError, match="Llama-family"):
        quantize_model_for_decode(Fake())


def test_decode_quant_per_head_scales():
    """q/k/v scales keep per-(head, channel) granularity — one outlier head
    must not coarsen the other heads' int8 codes."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu import Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.utils.quantization import quantize_model_for_decode

    cfg = LlamaConfig.tiny(dtype=jnp.float32, scan_layers=True)
    ids = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % cfg.vocab_size
    model = Model.from_flax(LlamaForCausalLM(cfg), jax.random.key(0), ids)
    qm = quantize_model_for_decode(model)
    blk = qm.params["model"]["layers"]["block"]
    L, H = cfg.num_hidden_layers, cfg.hidden_size
    heads, hn = cfg.num_attention_heads, cfg.head_dim
    assert blk["self_attn"]["q_proj"]["kernel"].scales.shape == (L, 1, heads, hn)
    assert blk["self_attn"]["o_proj"]["kernel"].scales.shape == (L, 1, 1, H)
    assert blk["mlp"]["gate_proj"]["kernel"].scales.shape == (L, 1, cfg.intermediate_size)
