"""Pipeline-parallel inference (`prepare_pippy`) on the virtual CPU mesh.

Mirrors the reference's pippy coverage (reference:
test_utils/scripts/external_deps/test_pippy.py — forward parity + batch
handling) with exact checks against the unpipelined forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Model, ParallelismConfig, prepare_pippy
from accelerate_tpu.inference import pipeline_stage_layers
from accelerate_tpu.models import (
    GPT2Config,
    GPT2LMHeadModel,
    LlamaConfig,
    LlamaForCausalLM,
)
from accelerate_tpu.utils import set_seed


def _mesh(pp):
    return ParallelismConfig(pp_size=pp).build_mesh()


def _llama_model(layers=4):
    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, num_hidden_layers=layers)
    module = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 16), dtype=np.int32)
    return Model.from_flax(module, jax.random.key(0), ids), jnp.asarray(ids)


def test_prepare_pippy_llama_matches_unpipelined():
    model, ids = _llama_model()
    piped = prepare_pippy(model, mesh=_mesh(4))
    np.testing.assert_allclose(
        np.asarray(piped(ids)), np.asarray(model(ids)), rtol=2e-5, atol=2e-5
    )


def test_prepare_pippy_pads_odd_batches():
    model, ids = _llama_model()
    piped = prepare_pippy(model, mesh=_mesh(4), num_chunks=4)
    odd = ids[:6]  # 6 % 4 != 0 — reference pads via pad_input_tensors
    out = piped(odd)
    assert out.shape[0] == 6
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(model(odd)), rtol=2e-5, atol=2e-5
    )


def test_prepare_pippy_gather_output_replicates():
    model, ids = _llama_model()
    mesh = _mesh(4)
    out = prepare_pippy(model, mesh=mesh, gather_output=True)(ids)
    assert out.sharding.is_fully_replicated


def test_prepare_pippy_gpt2_matches_unpipelined():
    set_seed(0)
    cfg = GPT2Config.tiny(dtype=jnp.float32, n_layer=4)
    module = GPT2LMHeadModel(cfg)
    ids = np.random.default_rng(1).integers(0, cfg.vocab_size, (8, 16), dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), ids)
    piped = prepare_pippy(model, mesh=_mesh(2), num_chunks=4)
    np.testing.assert_allclose(
        np.asarray(piped(jnp.asarray(ids))), np.asarray(model(jnp.asarray(ids))),
        rtol=2e-5, atol=2e-5,
    )


def test_prepare_pippy_unknown_model_raises():
    import flax.linen as nn

    class Odd(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x)

    model = Model.from_flax(Odd(), jax.random.key(0), jnp.ones((2, 4)))
    with pytest.raises(ValueError, match="No pipeline plan"):
        prepare_pippy(model, mesh=_mesh(2))


def test_pipeline_stage_layers():
    assert [list(r) for r in pipeline_stage_layers(8, 4)] == [
        [0, 1], [2, 3], [4, 5], [6, 7]
    ]
    with pytest.raises(ValueError):
        pipeline_stage_layers(6, 4)
