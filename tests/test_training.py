"""End-to-end training equivalence tests — the reference's `training_check`
(test_utils/scripts/test_script.py:449): distributed training must match
single-device training bit-for-bit given the same data order, and the fused
and imperative APIs must agree.
"""

import numpy as np
import pytest


def _make_regression_setup(seed=0, n=64, dim=8):
    """y = w.x + b + noise — the reference's RegressionModel/Dataset
    (test_utils/training.py)."""
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(dim, 1)).astype(np.float32)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = x @ w + 0.1 * rng.normal(size=(n, 1)).astype(np.float32)
    return x, y


class _ArrayDataset:
    def __init__(self, x, y):
        self.x, self.y = x, y

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return {"x": self.x[i], "y": self.y[i]}


class _Spec:
    def __init__(self, dataset, batch_size):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = None
        self.drop_last = False


def _linear_model():
    import flax.linen as nn

    class Linear(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(x)

    return Linear()


def _mse(params, batch, apply_fn):
    import jax.numpy as jnp

    pred = apply_fn({"params": params}, batch["x"])
    return jnp.mean((pred - batch["y"]) ** 2)


def _train(parallelism_config=None, fsdp=False, grad_accum=1, fused=True, steps=8, mixed_precision=None):
    import jax
    import optax

    from accelerate_tpu import Accelerator, Model, ParallelismConfig
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

    set_seed(0)
    acc = Accelerator(
        parallelism_config=parallelism_config,
        fsdp_plugin=FullyShardedDataParallelPlugin() if fsdp else None,
        gradient_accumulation_steps=grad_accum,
        mixed_precision=mixed_precision,
    )
    x, y = _make_regression_setup()
    module = _linear_model()
    model = Model.from_flax(module, jax.random.key(0), x[:1])
    tx = optax.sgd(0.1)
    loader = _Spec(_ArrayDataset(x, y), batch_size=16)
    model, opt, dl = acc.prepare(model, tx, loader)

    def loss_fn(params, batch):
        return _mse(params, batch, module.apply)

    losses = []
    if fused:
        step_fn = acc.prepare_train_step(loss_fn)
        state = acc.train_state
        done = 0
        while done < steps:
            for batch in dl:
                state, metrics = step_fn(state, batch)
                losses.append(float(metrics["loss"]))
                done += 1
                if done >= steps:
                    break
        acc._train_state = state
    else:
        done = 0
        while done < steps:
            for batch in dl:
                with acc.accumulate(model):
                    loss = acc.backward(loss_fn, batch)
                    opt.step()
                    opt.zero_grad()
                losses.append(float(loss))
                done += 1
                if done >= steps:
                    break
    params = jax.device_get(acc.train_state.params)
    return losses, params


def test_fused_training_decreases_loss():
    losses, _ = _train(steps=8)
    assert losses[-1] < losses[0]


def test_imperative_matches_fused():
    import jax

    losses_f, params_f = _train(fused=True, steps=4)

    # Reset singletons between runs.
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()

    losses_i, params_i = _train(fused=False, steps=4)
    for a, b in zip(jax.tree.leaves(params_f), jax.tree.leaves(params_i)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_fsdp_matches_replicated():
    """FULL_SHARD over 8 devices must produce identical params to pure DP —
    sharding is a layout choice, not a math choice."""
    import jax

    losses_dp, params_dp = _train(fsdp=False, steps=4)
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    losses_fsdp, params_fsdp = _train(fsdp=True, steps=4)
    for a, b in zip(jax.tree.leaves(params_dp), jax.tree.leaves(params_fsdp)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(losses_dp, losses_fsdp, rtol=1e-5)


def test_gradient_accumulation_equivalence():
    """accum=2 with the fused (scan) step must equal accum=1 with the same
    total batch (SGD linearity) — the reference's test_sync.py contract."""
    import jax

    _, params_1 = _train(grad_accum=1, steps=2)
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    _, params_2 = _train(grad_accum=2, steps=2)
    for a, b in zip(jax.tree.leaves(params_1), jax.tree.leaves(params_2)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_bf16_training_runs():
    losses, _ = _train(steps=4, mixed_precision="bf16")
    assert losses[-1] < losses[0] * 1.5


def test_tensor_parallel_training():
    """tp axis active: params replicated (no tp rules on Dense) but mesh has
    tp dim — training must still be correct."""
    from accelerate_tpu import ParallelismConfig

    losses, _ = _train(parallelism_config=ParallelismConfig(dp_shard_size=4, tp_size=2), steps=4)
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_sync_semantics_multiprocess():
    """Launched 2-process run of test_sync (reference: test_utils/scripts/
    test_sync.py + test_distributed_data_loop.py): accumulate/no_sync update
    gating, end-of-dataloader forced sync, even_batches vs join_uneven."""
    import os

    from accelerate_tpu.test_utils import execute_subprocess, get_launch_command

    cmd = get_launch_command(num_processes=2) + [
        "--cpu", "-m", "accelerate_tpu.test_utils.scripts.test_sync"
    ]
    # One device per process: the script's tiny fixed batches don't divide
    # the 8-virtual-device flag pytest's conftest exports.
    out = execute_subprocess(cmd, env={"PYTHONPATH": os.getcwd(), "XLA_FLAGS": ""})
    assert "TEST_SYNC OK" in out


@pytest.mark.slow
def test_fsdp_facts_multiprocess():
    """Launched 2-process x 2-virtual-device run of test_fsdp: cross-process
    mesh, per-process addressable shards, rank-identical loss, ZeRO-2
    opt-state sharding (reference: tests/test_fsdp.py on live workers)."""
    import os

    from accelerate_tpu.test_utils import execute_subprocess, get_launch_command

    cmd = get_launch_command(num_processes=2, virtual_devices=2) + [
        "-m", "accelerate_tpu.test_utils.scripts.test_fsdp"
    ]
    out = execute_subprocess(cmd, env={"PYTHONPATH": os.getcwd()})
    assert "TEST_FSDP OK" in out
