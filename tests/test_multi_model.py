"""Multi-model training: several prepared models per Accelerator, each with
its own TrainState slot (reference trains multiple models natively — GANs,
distillation, RLHF; see docs/source/usage_guides/deepspeed_multiple_model.md
and accelerator.py _models registry)."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
import flax.linen as nn


class _Dense(nn.Module):
    feats: int

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(16)(x)
        return nn.Dense(self.feats)(nn.relu(h))


@pytest.fixture(autouse=True)
def _reset():
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    yield


def _models_and_data():
    from accelerate_tpu import Model

    x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    m1 = Model.from_flax(_Dense(3), jax.random.key(0), x)
    m2 = Model.from_flax(_Dense(5), jax.random.key(1), x)
    return m1, m2, x


def test_second_prepare_does_not_corrupt_first():
    """Round-3 regression: preparing model B used to repoint model A's
    params view at B's TrainState."""
    from accelerate_tpu import Accelerator

    m1, m2, x = _models_and_data()
    acc = Accelerator()
    m1, _ = acc.prepare(m1, optax.adam(1e-3))
    out1_before = np.asarray(m1(x))
    m2 = acc.prepare(m2)
    assert m1(x).shape == (8, 3)
    assert m2(x).shape == (8, 5)
    np.testing.assert_allclose(np.asarray(m1(x)), out1_before, rtol=1e-6)


def test_two_models_two_optimizers_step_independently():
    """GAN shape: prepare(m1, tx1, m2, tx2); each model steps through its own
    fused step; stepping one leaves the other's params untouched."""
    from accelerate_tpu import Accelerator

    m1, m2, x = _models_and_data()
    y1 = np.zeros((8, 3), np.float32)
    y2 = np.zeros((8, 5), np.float32)
    acc = Accelerator()
    m1, o1, m2, o2 = acc.prepare(m1, optax.adam(1e-2), m2, optax.sgd(1e-2))

    mod1, mod2 = m1.module, m2.module

    def loss1(params, batch):
        return jnp.mean((mod1.apply({"params": params}, batch["x"]) - batch["y1"]) ** 2)

    def loss2(params, batch):
        return jnp.mean((mod2.apply({"params": params}, batch["x"]) - batch["y2"]) ** 2)

    step1 = acc.prepare_train_step(loss1, model=m1)
    step2 = acc.prepare_train_step(loss2, model=m2)
    batch = {"x": x, "y1": y1, "y2": y2}

    p1_init = jax.tree.map(np.asarray, m1.params)
    p2_init = jax.tree.map(np.asarray, m2.params)

    s1 = acc._train_states[m1._state_slot]
    s1, metrics1 = step1(s1, batch)
    # m2 untouched by m1's step.
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b), m2.params, p2_init
    )
    changed = jax.tree.leaves(
        jax.tree.map(lambda a, b: bool(np.any(np.asarray(a) != b)), m1.params, p1_init)
    )
    assert any(changed), "m1 did not train"

    s2 = acc._train_states[m2._state_slot]
    s2, metrics2 = step2(s2, batch)
    changed2 = jax.tree.leaves(
        jax.tree.map(lambda a, b: bool(np.any(np.asarray(a) != b)), m2.params, p2_init)
    )
    assert any(changed2), "m2 did not train"
    # Both losses decrease over a few steps.
    for _ in range(5):
        s1, metrics1b = step1(s1, batch)
        s2, metrics2b = step2(s2, batch)
    assert float(metrics1b["loss"]) < float(metrics1["loss"])
    assert float(metrics2b["loss"]) < float(metrics2["loss"])


def test_teacher_student_distillation():
    """Teacher prepared inference-only (no optimizer); student trains against
    its outputs — the no-tx slot stays frozen."""
    from accelerate_tpu import Accelerator, Model

    x = np.random.default_rng(1).normal(size=(8, 4)).astype(np.float32)
    teacher = Model.from_flax(_Dense(3), jax.random.key(2), x)
    student = Model.from_flax(_Dense(3), jax.random.key(3), x)
    acc = Accelerator()
    # Order: student pairs with the optimizer, teacher gets none.
    student, tx, teacher = acc.prepare(student, optax.adam(1e-2), teacher)
    assert acc._train_states[teacher._state_slot].tx is None

    smod = student.module
    targets = np.asarray(teacher(x))

    def loss(params, batch):
        return jnp.mean((smod.apply({"params": params}, batch["x"]) - batch["t"]) ** 2)

    step = acc.prepare_train_step(loss, model=student)
    s = acc._train_states[student._state_slot]
    first = None
    for _ in range(10):
        s, metrics = step(s, {"x": x, "t": targets})
        first = first if first is not None else float(metrics["loss"])
    assert float(metrics["loss"]) < first
    # Teacher unchanged and still queryable.
    np.testing.assert_allclose(np.asarray(teacher(x)), targets, rtol=1e-6)


def test_multi_model_checkpoint_roundtrip(tmp_path):
    from accelerate_tpu import Accelerator

    m1, m2, x = _models_and_data()
    acc = Accelerator()
    m1, o1, m2, o2 = acc.prepare(m1, optax.adam(1e-2), m2, optax.adam(1e-2))

    mod1, mod2 = m1.module, m2.module

    def loss1(params, batch):
        return jnp.mean(mod1.apply({"params": params}, batch) ** 2)

    def loss2(params, batch):
        return jnp.mean(mod2.apply({"params": params}, batch) ** 2)

    s1 = acc._train_states[m1._state_slot]
    s2 = acc._train_states[m2._state_slot]
    s1, _ = acc.prepare_train_step(loss1, model=m1)(s1, x)
    s2, _ = acc.prepare_train_step(loss2, model=m2)(s2, x)
    p1 = jax.tree.map(np.asarray, m1.params)
    p2 = jax.tree.map(np.asarray, m2.params)

    out = tmp_path / "ckpt"
    acc.save_state(str(out))
    assert (out / "model_1.safetensors").exists()
    assert (out / "optimizer_1.bin").exists()

    # Perturb both, reload, expect both restored.
    m1.params = jax.tree.map(lambda a: a + 1.0, m1.params)
    m2.params = jax.tree.map(lambda a: a + 1.0, m2.params)
    acc.load_state(str(out))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(np.asarray(a), b, rtol=1e-6), m1.params, p1)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(np.asarray(a), b, rtol=1e-6), m2.params, p2)
    assert int(np.asarray(acc._train_states[m2._state_slot].step)) == 1
