"""ZeRO-1/2 (SHARD_GRAD_OP) semantics + previously-dead FSDP plugin knobs.

Reference contract: FSDP sharding_strategy SHARD_GRAD_OP / DeepSpeed stages
1-2 shard gradients + optimizer state over data-parallel ranks while params
stay replicated (reference: utils/dataclasses.py:1584-2190,
utils/deepspeed.py:253-293). Round-1 VERDICT item 4: the flag used to be
parsed and silently ignored.
"""

import numpy as np
import pytest


def _setup(strategy, opt="adam", dp_shard=8, **plugin_kwargs):
    import jax
    import optax

    from accelerate_tpu import Accelerator, Model, ParallelismConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed
    import jax.numpy as jnp

    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(8, 16), dtype=np.int32)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=dp_shard),
        fsdp_plugin=FullyShardedDataParallelPlugin(
            sharding_strategy=strategy, min_weight_size_to_shard=0, **plugin_kwargs
        ),
    )
    model = Model.from_flax(module, jax.random.key(0), ids)
    tx = optax.sgd(0.1) if opt == "sgd" else optax.adam(1e-3)
    model, _ = acc.prepare(model, tx)
    return acc, model, module, cfg, ids


def _sharded_axes(sharding):
    return {a for e in sharding.spec if e for a in (e if isinstance(e, tuple) else (e,))}


def test_shard_grad_op_shards_opt_state_not_params():
    import jax

    acc, model, *_ = _setup("SHARD_GRAD_OP")
    # Params replicated.
    for p in jax.tree.leaves(acc.train_state.params):
        assert "dp_shard" not in _sharded_axes(p.sharding), p.sharding
    # Optimizer moments (params-shaped leaves) sharded over dp_shard.
    big_sharded = 0
    for leaf in jax.tree.leaves(acc.train_state.opt_state):
        if hasattr(leaf, "shape") and leaf.size > 64:
            if "dp_shard" in _sharded_axes(leaf.sharding):
                big_sharded += 1
    assert big_sharded > 0, "no optimizer-state leaf is sharded over dp_shard"
    # Grad constraint recorded for the fused step (the ZeRO-2 reduce-scatter).
    assert acc._grad_shardings is not None


def test_shard_grad_op_trains_and_matches_full_shard():
    """Same seed, same data: SHARD_GRAD_OP and FULL_SHARD must optimize to the
    same params (sharding layout must not change the math)."""
    import jax

    from accelerate_tpu.models import cross_entropy_loss
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    results = {}
    for strategy in ("SHARD_GRAD_OP", "FULL_SHARD"):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        # SGD: linear in grads, so reduction-order noise stays within float
        # tolerance (adam's rsqrt amplifies ~1e-7 grad diffs to ~0.5·lr).
        acc, model, module, cfg, ids = _setup(strategy, opt="sgd")

        def loss_fn(params, b):
            logits = module.apply({"params": params}, b["x"])
            return cross_entropy_loss(logits, b["y"])

        step = acc.prepare_train_step(loss_fn)
        from jax.sharding import NamedSharding, PartitionSpec

        sharding = NamedSharding(acc.mesh, PartitionSpec(("dp_replicate", "dp_shard")))
        b = {
            "x": jax.device_put(ids[:, :-1], sharding),
            "y": jax.device_put(ids[:, 1:], sharding),
        }
        state = acc.train_state
        for _ in range(3):
            state, metrics = step(state, b)
        results[strategy] = jax.tree.map(lambda x: np.asarray(x), state.params)
        assert np.isfinite(float(np.asarray(metrics["loss"])))

    flat_a = jax.tree.leaves(results["SHARD_GRAD_OP"])
    flat_b = jax.tree.leaves(results["FULL_SHARD"])
    for a, b_ in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b_, rtol=2e-5, atol=2e-6)


def test_no_shard_keeps_everything_replicated():
    import jax

    acc, *_ = _setup("NO_SHARD")
    for leaf in jax.tree.leaves(acc.train_state.opt_state):
        if hasattr(leaf, "sharding"):
            assert "dp_shard" not in _sharded_axes(leaf.sharding)
    assert acc._grad_shardings is None


def test_ignored_params_stay_replicated():
    import jax
    from jax.tree_util import tree_flatten_with_path

    from accelerate_tpu.parallel.sharding import _path_to_name

    acc, model, *_ = _setup("FULL_SHARD", ignored_params=[r"embed_tokens"])
    flat, _ = tree_flatten_with_path(acc.train_state.params)
    checked = 0
    for path, leaf in flat:
        name = _path_to_name(path)
        if "embed_tokens" in name:
            assert "dp_shard" not in _sharded_axes(leaf.sharding), name
            checked += 1
    assert checked > 0


def test_activation_checkpointing_flips_module_remat(caplog):
    import logging

    with caplog.at_level(logging.WARNING):
        acc, model, *_ = _setup("FULL_SHARD", activation_checkpointing=True)
    # The module is rebuilt with remat AND the stale-closure hazard is called
    # out — loss_fns must use model.module, not the pre-prepare module object.
    assert model.module.config.remat is True
    assert any("model.module" in r.message for r in caplog.records)


def test_deepspeed_plugin_stage2_maps_to_shard_grad_op():
    from accelerate_tpu.utils import DeepSpeedPlugin

    fsdp = DeepSpeedPlugin(zero_stage=2).to_fsdp_plugin()
    assert fsdp.sharding_strategy == "SHARD_GRAD_OP"
    assert fsdp.shards_grads_and_opt and not fsdp.shards_params


def test_cpu_offload_warns_and_disables_on_cpu_backend(caplog):
    """On backends without a host memory space, cpu_offload must warn loudly
    and leave the offload machinery off (the TPU pinned_host path is covered
    by test_cpu_offload_pins_opt_state_on_tpu below)."""
    import logging

    with caplog.at_level(logging.WARNING):
        acc, *_ = _setup("SHARD_GRAD_OP", cpu_offload=True)
    assert acc._opt_offload is None
    assert any("host memory space" in r.message for r in caplog.records)


def test_cpu_offload_pins_opt_state_on_tpu():
    """Real-chip check: opt-state moments land in pinned_host and the fused
    step streams them through the update."""
    import jax

    from accelerate_tpu.test_utils import require_tpu  # noqa: F401

    if jax.devices()[0].platform not in ("tpu", "axon"):
        pytest.skip("needs a TPU backend")
    import optax

    from accelerate_tpu.models import cross_entropy_loss

    acc, model, module, cfg, ids = _setup("SHARD_GRAD_OP", cpu_offload=True, dp_shard=1)
    kinds = {
        leaf.sharding.memory_kind
        for leaf in jax.tree.leaves(acc.train_state.opt_state)
        if hasattr(leaf, "sharding")
    }
    assert "pinned_host" in kinds
    assert acc._opt_offload is not None

    def loss_fn(params, b):
        return cross_entropy_loss(module.apply({"params": params}, b["x"]), b["y"])

    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    b = {"x": ids[:, :-1], "y": ids[:, 1:]}
    state, m = step(state, b)
    assert np.isfinite(float(np.asarray(m["loss"])))


def test_deepspeed_plugin_from_ds_json(tmp_path):
    """round 4: a raw DeepSpeed ds_config.json (the reference's
    --deepspeed_config_file surface) maps onto the plugin, 'auto' values
    falling back to defaults and engine-only keys ignored."""
    import json

    from accelerate_tpu.utils import DeepSpeedPlugin

    cfg = {
        "bf16": {"enabled": True},
        "zero_optimization": {
            "stage": 3,
            "offload_optimizer": {"device": "cpu", "pin_memory": True},
            "offload_param": {"device": "none"},
            "stage3_gather_16bit_weights_on_model_save": "auto",
        },
        "gradient_accumulation_steps": "auto",
        "gradient_clipping": 1.0,
        "optimizer": {"type": "AdamW", "params": {"lr": "auto"}},
        "scheduler": {"type": "WarmupLR"},
        "train_batch_size": "auto",
    }
    p = tmp_path / "ds_config_zero3.json"
    p.write_text(json.dumps(cfg))
    plugin = DeepSpeedPlugin.from_ds_json(str(p))
    assert plugin.zero_stage == 3
    assert plugin.offload_optimizer_device == "cpu"
    assert plugin.offload_param_device == "none"
    assert plugin.gradient_accumulation_steps == 1  # "auto" -> default
    assert plugin.gradient_clipping == 1.0
    assert plugin.mixed_precision == "bf16"
    fsdp = plugin.to_fsdp_plugin()
    assert fsdp.sharding_strategy == "FULL_SHARD"
    assert fsdp.cpu_offload


def test_deepspeed_from_ds_json_stage_semantics(tmp_path):
    """Absent zero_optimization section = ZeRO DISABLED (stage 0); 'auto'
    offload devices fall back to 'none'."""
    import json

    from accelerate_tpu.utils import DeepSpeedPlugin

    p = tmp_path / "no_zero.json"
    p.write_text(json.dumps({"bf16": {"enabled": True}, "gradient_clipping": 0.5}))
    plugin = DeepSpeedPlugin.from_ds_json(str(p))
    assert plugin.zero_stage == 0
    assert plugin.to_fsdp_plugin().sharding_strategy == "NO_SHARD"

    p2 = tmp_path / "auto_dev.json"
    p2.write_text(json.dumps({
        "zero_optimization": {"stage": "auto", "offload_optimizer": {"device": "auto"}},
    }))
    plugin2 = DeepSpeedPlugin.from_ds_json(str(p2))
    assert plugin2.zero_stage == 2  # "auto" -> engine default
    assert plugin2.offload_optimizer_device == "none"


def test_deepspeed_from_ds_json_mixed_precision_auto(tmp_path):
    """bf16/fp16 {"enabled": "auto"} inherits the accelerate-level setting
    (reference DeepSpeed semantics), instead of silently disabling it."""
    import json

    from accelerate_tpu.utils import DeepSpeedPlugin

    p = tmp_path / "auto_mp.json"
    p.write_text(json.dumps({"bf16": {"enabled": "auto"}}))
    assert DeepSpeedPlugin.from_ds_json(str(p)).mixed_precision is None
    assert (
        DeepSpeedPlugin.from_ds_json(str(p), mixed_precision="bf16").mixed_precision
        == "bf16"
    )
    # An fp16 "auto" does not turn on bf16 and vice versa.
    assert (
        DeepSpeedPlugin.from_ds_json(str(p), mixed_precision="fp16").mixed_precision
        is None
    )
    p2 = tmp_path / "auto_fp16.json"
    p2.write_text(json.dumps({"fp16": {"enabled": "auto"}}))
    assert (
        DeepSpeedPlugin.from_ds_json(str(p2), mixed_precision="fp16").mixed_precision
        == "fp16"
    )


def test_deepspeed_plugin_wires_accum_and_clipping(tmp_path):
    """from_ds_json accumulation/clipping actually apply to the train step
    (they are not decorative fields)."""
    import json

    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, cross_entropy_loss
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import DeepSpeedPlugin

    p = tmp_path / "ds.json"
    p.write_text(json.dumps({
        "zero_optimization": {"stage": 2},
        "gradient_accumulation_steps": 2,
        "gradient_clipping": 1.0,
    }))
    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    plugin = DeepSpeedPlugin.from_ds_json(str(p))
    acc = Accelerator(deepspeed_plugin=plugin)
    assert acc.gradient_state.num_steps == 2
    assert acc._ds_gradient_clipping == 1.0

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    ids = np.arange(16 * 9, dtype=np.int32).reshape(16, 9) % cfg.vocab_size
    model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
    model, _ = acc.prepare(model, optax.sgd(10.0))  # big lr: clipping visible

    def loss_fn(params, batch):
        return cross_entropy_loss(module.apply({"params": params}, batch["x"]), batch["y"])

    step = acc.prepare_train_step(loss_fn)  # no max_grad_norm: ds value applies
    batch = {"x": ids[:, :-1], "y": ids[:, 1:]}
    _, metrics = step(acc.train_state, batch)
    assert np.isfinite(float(np.asarray(metrics["loss"])))
    assert float(np.asarray(metrics["grad_norm"])) >= 0.0
