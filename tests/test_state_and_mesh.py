import numpy as np
import pytest


def test_partial_state_singleton():
    from accelerate_tpu import PartialState

    a = PartialState()
    b = PartialState()
    assert a.__dict__ is b.__dict__
    assert a.num_processes == 1
    assert a.is_main_process
    assert a.num_devices == 8


def test_split_between_processes_single():
    from accelerate_tpu import PartialState

    state = PartialState()
    with state.split_between_processes([1, 2, 3]) as vals:
        assert vals == [1, 2, 3]


def test_parallelism_config_mesh():
    from accelerate_tpu import ParallelismConfig

    cfg = ParallelismConfig(dp_shard_size=4, tp_size=2)
    mesh = cfg.build_mesh()
    assert mesh.shape["dp_shard"] == 4
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp_replicate"] == 1
    assert cfg.total_size == 8


def test_parallelism_config_infer():
    from accelerate_tpu import ParallelismConfig

    cfg = ParallelismConfig(tp_size=2)
    mesh = cfg.build_mesh()
    assert mesh.shape["dp_shard"] == 4  # auto-filled to cover 8 devices


def test_parallelism_config_validation():
    from accelerate_tpu import ParallelismConfig

    with pytest.raises(ValueError):
        ParallelismConfig(cp_size=2, sp_size=2)
    with pytest.raises(ValueError):
        ParallelismConfig(dp_shard_size=0)


def test_parallelism_config_env_roundtrip(monkeypatch):
    from accelerate_tpu import ParallelismConfig

    cfg = ParallelismConfig(dp_shard_size=2, tp_size=4, cp_rotate_method="allgather")
    for k, v in cfg.to_env().items():
        monkeypatch.setenv(k, v)
    decoded = ParallelismConfig.from_env()
    assert decoded == cfg


def test_accelerator_state_mesh_default():
    from accelerate_tpu import AcceleratorState

    state = AcceleratorState()
    mesh = state.mesh
    assert mesh.devices.size == 8
    # Default: everything lands on dp_shard (FSDP-ready pure-DP mesh).
    assert mesh.shape["dp_shard"] == 8


def test_gradient_state_accumulation_flags():
    from accelerate_tpu import Accelerator

    acc = Accelerator(gradient_accumulation_steps=2)
    assert acc.gradient_accumulation_steps == 2
    with acc.accumulate():
        first = acc.sync_gradients
    with acc.accumulate():
        second = acc.sync_gradients
    assert (first, second) == (False, True)
