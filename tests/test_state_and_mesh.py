import numpy as np
import pytest


def test_partial_state_singleton():
    from accelerate_tpu import PartialState

    a = PartialState()
    b = PartialState()
    assert a.__dict__ is b.__dict__
    assert a.num_processes == 1
    assert a.is_main_process
    assert a.num_devices == 8


def test_split_between_processes_single():
    from accelerate_tpu import PartialState

    state = PartialState()
    with state.split_between_processes([1, 2, 3]) as vals:
        assert vals == [1, 2, 3]


def test_parallelism_config_mesh():
    from accelerate_tpu import ParallelismConfig

    cfg = ParallelismConfig(dp_shard_size=4, tp_size=2)
    mesh = cfg.build_mesh()
    assert mesh.shape["dp_shard"] == 4
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp_replicate"] == 1
    assert cfg.total_size == 8


def test_parallelism_config_infer():
    from accelerate_tpu import ParallelismConfig

    cfg = ParallelismConfig(tp_size=2)
    mesh = cfg.build_mesh()
    assert mesh.shape["dp_shard"] == 4  # auto-filled to cover 8 devices


def test_parallelism_config_infer_oversubscribed():
    """Fixed product EXCEEDS the device count → the dedicated
    oversubscription error naming each offending axis and its env var — not
    the misleading 'does not divide' message."""
    from accelerate_tpu import ParallelismConfig, ParallelismOversubscriptionError

    cfg = ParallelismConfig(dp_shard_size=4, tp_size=4)  # 16 > 8 devices
    with pytest.raises(ParallelismOversubscriptionError) as exc:
        cfg.infer_missing_axis(8)
    msg = str(exc.value)
    assert "dp_shard=4" in msg and "tp=4" in msg
    assert "PARALLELISM_CONFIG_DP_SHARD_SIZE" in msg
    assert "PARALLELISM_CONFIG_TP_SIZE" in msg
    assert "does not divide" not in msg
    # Still a ValueError subclass — existing handlers keep working.
    assert isinstance(exc.value, ValueError)


def test_parallelism_config_infer_nondividing():
    """Fixed product below the device count but not dividing it → the
    original 'does not divide' error (NOT the oversubscription one)."""
    from accelerate_tpu import ParallelismConfig, ParallelismOversubscriptionError

    cfg = ParallelismConfig(tp_size=3)
    with pytest.raises(ValueError) as exc:
        cfg.infer_missing_axis(8)
    assert "does not divide" in str(exc.value)
    assert not isinstance(exc.value, ParallelismOversubscriptionError)


def test_parallelism_config_validation():
    from accelerate_tpu import ParallelismConfig

    with pytest.raises(ValueError):
        ParallelismConfig(cp_size=2, sp_size=2)
    with pytest.raises(ValueError):
        ParallelismConfig(dp_shard_size=0)


def test_parallelism_config_env_roundtrip(monkeypatch):
    from accelerate_tpu import ParallelismConfig

    cfg = ParallelismConfig(dp_shard_size=2, tp_size=4, cp_rotate_method="allgather")
    for k, v in cfg.to_env().items():
        monkeypatch.setenv(k, v)
    decoded = ParallelismConfig.from_env()
    assert decoded == cfg


def test_accelerator_state_mesh_default():
    from accelerate_tpu import AcceleratorState

    state = AcceleratorState()
    mesh = state.mesh
    assert mesh.devices.size == 8
    # Default: everything lands on dp_shard (FSDP-ready pure-DP mesh).
    assert mesh.shape["dp_shard"] == 8


def test_gradient_state_accumulation_flags():
    from accelerate_tpu import Accelerator

    acc = Accelerator(gradient_accumulation_steps=2)
    assert acc.gradient_accumulation_steps == 2
    with acc.accumulate():
        first = acc.sync_gradients
    with acc.accumulate():
        second = acc.sync_gradients
    assert (first, second) == (False, True)


def test_axis_rank_properties_single_process():
    """Rank accessors: single process is rank 0 on every axis; the accessors
    exist and agree with the mesh shape (reference parity surface)."""
    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=4, tp_size=2))
    assert acc.data_parallel_rank == 0
    assert acc.data_parallel_shard_rank == 0
    assert acc.tensor_parallel_rank == 0
    assert acc.pipeline_parallel_rank == 0
    assert acc.context_parallel_rank == 0
    assert acc.split_batches in (True, False)
    assert acc.even_batches in (True, False)
    assert acc.non_blocking is True
    assert acc.optimizer_step_was_skipped is False
    assert acc.unscale_gradients() is None


def test_save_load_state_pre_hooks(tmp_path):
    import optax
    import flax.linen as nn
    import jax
    import numpy as np

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()

    class M(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x)

    x = np.ones((2, 4), np.float32)
    acc = Accelerator()
    model = Model.from_flax(M(), jax.random.key(0), x)
    acc.prepare(model, optax.sgd(1e-2))

    calls = []
    h1 = acc.register_save_state_pre_hook(lambda models, state, out: calls.append(("save", out)))
    h2 = acc.register_load_state_pre_hook(lambda models, inp: calls.append(("load", inp)))
    out = acc.save_state(str(tmp_path / "ck"))
    acc.load_state(out)
    assert [c[0] for c in calls] == ["save", "load"]
    h1.remove(); h2.remove()
    acc.save_state(str(tmp_path / "ck2"))
    assert len(calls) == 2  # removed hooks don't fire
