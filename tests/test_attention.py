"""Parity tests: blockwise flash / ring (cp) / Ulysses (sp) attention must all
match the naive reference attention (the reference's CP/SP correctness
contract, SURVEY.md §7 hard-part 4)."""

import numpy as np
import pytest


def _qkv(b=2, s=64, hq=4, hkv=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(b, s, hq, d)).astype(np.float32)
    k = rng.normal(size=(b, s, hkv, d)).astype(np.float32)
    v = rng.normal(size=(b, s, hkv, d)).astype(np.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hkv", [4, 2])
def test_blockwise_matches_naive(causal, hkv):
    from accelerate_tpu.models.llama import naive_attention
    from accelerate_tpu.ops import blockwise_attention

    q, k, v = _qkv(hkv=hkv)
    ref = naive_attention(*map(np.asarray, (q, k, v)), causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_blockwise_unpadded_vs_padded_blocks():
    from accelerate_tpu.ops import blockwise_attention
    from accelerate_tpu.models.llama import naive_attention

    q, k, v = _qkv(s=60)  # 60 not divisible by block 16 → padding path
    ref = naive_attention(q, k, v, causal=True)
    out = blockwise_attention(q, k, v, causal=True, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def _mesh_cfg(cp=1, sp=1):
    from accelerate_tpu import AcceleratorState, ParallelismConfig

    AcceleratorState._reset_state()
    cfg = ParallelismConfig(cp_size=cp, sp_size=sp)
    state = AcceleratorState(parallelism_config=cfg)
    return state.mesh, cfg


@pytest.mark.parametrize("rotate", ["alltoall", "allgather"])
def test_ring_attention_matches_naive(rotate):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu.models.llama import naive_attention
    from accelerate_tpu.parallel.cp import ring_attention

    mesh, _ = _mesh_cfg(cp=4)
    q, k, v = _qkv(s=64)
    ref = naive_attention(q, k, v, causal=True)
    sharding = NamedSharding(mesh, P(None, "cp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, causal=True, mesh=mesh, rotate_method=rotate)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_attention_inside_jit():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu.models.llama import naive_attention
    from accelerate_tpu.parallel.cp import ring_attention

    mesh, _ = _mesh_cfg(cp=4)
    q, k, v = _qkv(s=64)
    ref = naive_attention(q, k, v, causal=True)
    sharding = NamedSharding(mesh, P(None, "cp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))

    fn = jax.jit(lambda a, b, c: ring_attention(a, b, c, causal=True, mesh=mesh))
    out = fn(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ulysses_attention_matches_naive():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu.models.llama import naive_attention
    from accelerate_tpu.parallel.sp import ulysses_attention

    mesh, _ = _mesh_cfg(sp=4)
    q, k, v = _qkv(s=64, hq=8, hkv=8)
    ref = naive_attention(q, k, v, causal=True)
    sharding = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out = ulysses_attention(qs, ks, vs, causal=True, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ulysses_gqa():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu.models.llama import naive_attention
    from accelerate_tpu.parallel.sp import ulysses_attention

    mesh, _ = _mesh_cfg(sp=4)
    q, k, v = _qkv(s=32, hq=8, hkv=2)
    ref = naive_attention(q, k, v, causal=True)
    sharding = NamedSharding(mesh, P(None, "sp", None, None))
    qs = jax.device_put(q, sharding)
    ks = jax.device_put(k, sharding)
    vs = jax.device_put(v, sharding)
    out = ulysses_attention(qs, ks, vs, causal=True, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# Pallas flash kernel (interpret mode on the CPU mesh; compiled on real TPU)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hkv", [4, 2])
def test_pallas_flash_forward_matches_naive(causal, hkv):
    from accelerate_tpu.models.llama import naive_attention
    from accelerate_tpu.ops.pallas_flash import pallas_flash_attention

    q, k, v = _qkv(s=160, hkv=hkv, d=16)  # non-multiple of block → padding path
    ref = naive_attention(*map(np.asarray, (q, k, v)), causal=causal)
    out = pallas_flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_pallas_flash_offsets_match_blockwise():
    from accelerate_tpu.ops import blockwise_attention
    from accelerate_tpu.ops.pallas_flash import pallas_flash_attention

    q, k, v = _qkv(s=128, d=16)
    # ring-chunk semantics: q is the second chunk, k the first → fully visible
    ref = blockwise_attention(q, k, v, causal=True, q_offset=128, k_offset=0, block_k=32)
    out = pallas_flash_attention(q, k, v, causal=True, q_offset=128, k_offset=0,
                                 block_q=128, block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    # future chunk: q before every key → empty attention, exact zeros
    out = pallas_flash_attention(q, k, v, causal=True, q_offset=0, k_offset=128,
                                 block_q=128, block_k=128, interpret=True)
    assert float(np.max(np.abs(np.asarray(out)))) == 0.0


def test_pallas_flash_gradients_match_blockwise():
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.ops import blockwise_attention
    from accelerate_tpu.ops.pallas_flash import pallas_flash_attention

    q, k, v = _qkv(s=128, hq=4, hkv=2, d=16)

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.square(fn(q, k, v)))

    g_ref = jax.grad(loss(lambda q, k, v: blockwise_attention(q, k, v, causal=True, block_k=32)),
                     argnums=(0, 1, 2))(q, k, v)
    g_pf = jax.grad(loss(lambda q, k, v: pallas_flash_attention(
        q, k, v, causal=True, block_q=128, block_k=128, interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_pf):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-3, atol=1e-4,
                                   err_msg=f"d{name}")


def test_merge_flash_chunks_exact():
    """Splitting keys into two chunks and merging (out, lse) must equal
    single-shot attention — the invariant ring attention rests on."""
    import jax.numpy as jnp

    from accelerate_tpu.ops import blockwise_attention
    from accelerate_tpu.ops.pallas_flash import (
        merge_flash_chunks,
        pallas_flash_attention_with_lse,
    )

    q, k, v = _qkv(s=128, d=16)
    ref = blockwise_attention(q, k, v, causal=True, block_k=32)
    o1, l1 = pallas_flash_attention_with_lse(
        q, k[:, :64], v[:, :64], causal=True, q_offset=0, k_offset=0,
        block_q=128, block_k=64, interpret=True)
    o2, l2 = pallas_flash_attention_with_lse(
        q, k[:, 64:], v[:, 64:], causal=True, q_offset=0, k_offset=64,
        block_q=128, block_k=64, interpret=True)
    out, _ = merge_flash_chunks(o1, l1, o2, l2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_pallas_flash_under_shard_map_dp_tp():
    """The Mosaic kernel has no GSPMD partition rule, so multi-device meshes
    run it inside shard_map (ops.flash_attention.auto_flash_attention). This
    exercises exactly that wrapper wiring on the virtual mesh with the kernel
    interpreted per-shard."""
    import functools

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu import AcceleratorState, ParallelismConfig
    from accelerate_tpu.ops import blockwise_attention
    from accelerate_tpu.ops.pallas_flash import pallas_flash_attention

    AcceleratorState._reset_state()
    state = AcceleratorState(parallelism_config=ParallelismConfig(dp_shard_size=4, tp_size=2))
    mesh = state.mesh
    q, k, v = _qkv(b=4, s=128, hq=4, hkv=4, d=16)
    spec = P(("dp_replicate", "dp_shard"), None, "tp", None)
    fn = functools.partial(pallas_flash_attention, causal=True, block_q=64, block_k=64,
                           interpret=True)
    from accelerate_tpu.utils.environment import shard_map_compat

    sharded = shard_map_compat(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                               check_vma=False)
    q_s = jax.device_put(q, NamedSharding(mesh, spec))
    k_s = jax.device_put(k, NamedSharding(mesh, spec))
    v_s = jax.device_put(v, NamedSharding(mesh, spec))
    out = sharded(q_s, k_s, v_s)
    ref = blockwise_attention(q, k, v, causal=True, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
