"""Parity tests: blockwise flash / ring (cp) / Ulysses (sp) attention must all
match the naive reference attention (the reference's CP/SP correctness
contract, SURVEY.md §7 hard-part 4)."""

import numpy as np
import pytest


def _qkv(b=2, s=64, hq=4, hkv=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(b, s, hq, d)).astype(np.float32)
    k = rng.normal(size=(b, s, hkv, d)).astype(np.float32)
    v = rng.normal(size=(b, s, hkv, d)).astype(np.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hkv", [4, 2])
def test_blockwise_matches_naive(causal, hkv):
    from accelerate_tpu.models.llama import naive_attention
    from accelerate_tpu.ops import blockwise_attention

    q, k, v = _qkv(hkv=hkv)
    ref = naive_attention(*map(np.asarray, (q, k, v)), causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_blockwise_unpadded_vs_padded_blocks():
    from accelerate_tpu.ops import blockwise_attention
    from accelerate_tpu.models.llama import naive_attention

    q, k, v = _qkv(s=60)  # 60 not divisible by block 16 → padding path
    ref = naive_attention(q, k, v, causal=True)
    out = blockwise_attention(q, k, v, causal=True, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def _mesh_cfg(cp=1, sp=1):
    from accelerate_tpu import AcceleratorState, ParallelismConfig

    AcceleratorState._reset_state()
    cfg = ParallelismConfig(cp_size=cp, sp_size=sp)
    state = AcceleratorState(parallelism_config=cfg)
    return state.mesh, cfg


@pytest.mark.parametrize("rotate", ["alltoall", "allgather"])
def test_ring_attention_matches_naive(rotate):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu.models.llama import naive_attention
    from accelerate_tpu.parallel.cp import ring_attention

    mesh, _ = _mesh_cfg(cp=4)
    q, k, v = _qkv(s=64)
    ref = naive_attention(q, k, v, causal=True)
    sharding = NamedSharding(mesh, P(None, "cp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, causal=True, mesh=mesh, rotate_method=rotate)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_attention_inside_jit():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu.models.llama import naive_attention
    from accelerate_tpu.parallel.cp import ring_attention

    mesh, _ = _mesh_cfg(cp=4)
    q, k, v = _qkv(s=64)
    ref = naive_attention(q, k, v, causal=True)
    sharding = NamedSharding(mesh, P(None, "cp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))

    fn = jax.jit(lambda a, b, c: ring_attention(a, b, c, causal=True, mesh=mesh))
    out = fn(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ulysses_attention_matches_naive():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu.models.llama import naive_attention
    from accelerate_tpu.parallel.sp import ulysses_attention

    mesh, _ = _mesh_cfg(sp=4)
    q, k, v = _qkv(s=64, hq=8, hkv=8)
    ref = naive_attention(q, k, v, causal=True)
    sharding = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out = ulysses_attention(qs, ks, vs, causal=True, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ulysses_gqa():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu.models.llama import naive_attention
    from accelerate_tpu.parallel.sp import ulysses_attention

    mesh, _ = _mesh_cfg(sp=4)
    q, k, v = _qkv(s=32, hq=8, hkv=2)
    ref = naive_attention(q, k, v, causal=True)
    sharding = NamedSharding(mesh, P(None, "sp", None, None))
    qs = jax.device_put(q, sharding)
    ks = jax.device_put(k, sharding)
    vs = jax.device_put(v, sharding)
    out = ulysses_attention(qs, ks, vs, causal=True, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
