"""Request-scoped distributed tracing (tracing.py): the explain() telescoping
identity (terms sum to measured TTFT — the pinned acceptance bar), Chrome
trace export validity with cross-lane flow events, seeded tick-domain
determinism under chaos, Prometheus text parity, chaos span annotation, the
TelemetryKwargs wiring, and the off-by-default zero-cost contract. All
CPU-only, tier-1 fast."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import (
    DisaggConfig,
    DisaggServingEngine,
    FaultInjector,
    Model,
    ServingConfig,
    ServingEngine,
    TraceConfig,
    TraceRecorder,
)
from accelerate_tpu.utils import set_seed


@pytest.fixture(scope="module")
def llama():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    probe = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 8),
                                              dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), probe)
    return cfg, model


def _prompts(cfg, lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, (n,), dtype=np.int32)
            for n in lengths]


# ---------------------------------------------------------------------------
# TraceConfig plumbing
# ---------------------------------------------------------------------------


def test_trace_config_from_value():
    assert TraceConfig.from_value(None) is None
    assert TraceConfig.from_value(False) is None
    cfg = TraceConfig.from_value(True)
    assert cfg is not None and cfg.enabled
    cfg = TraceConfig.from_value({"max_spans": 17, "wall_clock": False})
    assert cfg.max_spans == 17 and cfg.wall_clock is False
    same = TraceConfig(max_spans=5)
    assert TraceConfig.from_value(same) is same
    with pytest.raises(TypeError):
        TraceConfig.from_value("yes")


def test_tracing_off_by_default(llama):
    cfg, model = llama
    engine = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8]))
    assert engine.tracing is None
    outs = engine.run(_prompts(cfg, [5, 9]), max_new_tokens=3)
    assert len(outs) == 2  # hooks are inert None-checks when off


# ---------------------------------------------------------------------------
# Consumer 1: explain() — the telescoping identity (pinned acceptance bar)
# ---------------------------------------------------------------------------


def test_explain_terms_sum_to_measured_ttft(llama):
    cfg, model = llama
    tr = TraceRecorder(TraceConfig())
    engine = ServingEngine(
        model,
        ServingConfig(n_slots=2, max_len=64, prefill_chunks=[4, 8]),
        tracing=tr,
    )
    prompts = _prompts(cfg, [3, 7, 12, 20, 5], seed=7)
    engine.run(prompts, max_new_tokens=4)
    assert len(tr.request_ids()) == len(prompts)
    for rid in tr.request_ids():
        rep = tr.explain(rid)
        assert rep["status"] == "ok"
        terms = rep["terms"]
        assert set(terms) == {"queue_wait_s", "prefill_s", "handoff_s",
                              "backoff_s", "stall_s"}
        # The pinned identity: disjoint sub-intervals telescope to the
        # measured TTFT exactly (float-add tolerance only).
        assert sum(terms.values()) == pytest.approx(rep["ttft_s"],
                                                    abs=1e-9, rel=1e-9)
        assert rep["dominant"] in terms
        assert terms[rep["dominant"]] == max(terms.values())
        # Colocated engine: no handoff, no chaos backoff.
        assert terms["handoff_s"] == 0.0 and terms["backoff_s"] == 0.0
        assert rep["total_s"] >= rep["ttft_s"]
        assert rep["decode_s"] == pytest.approx(
            rep["total_s"] - rep["ttft_s"], abs=1e-9)
        assert rep["n_spans"] > 0 and rep["decode_ticks"] > 0


def test_explain_untraced_request_raises():
    tr = TraceRecorder(TraceConfig())
    with pytest.raises(KeyError):
        tr.explain(12345)


def test_explain_disagg_includes_handoff_terms(llama):
    cfg, model = llama
    tr = TraceRecorder(TraceConfig())
    engine = DisaggServingEngine(
        model,
        ServingConfig(n_slots=2, max_len=64, prefill_chunks=[4, 8]),
        disagg=DisaggConfig(n_prefill_lanes=2),
        tracing=tr,
    )
    engine.run(_prompts(cfg, [6, 11, 17], seed=5), max_new_tokens=3)
    saw_handoff = False
    for rid in tr.request_ids():
        rep = tr.explain(rid)
        terms = rep["terms"]
        assert sum(terms.values()) == pytest.approx(rep["ttft_s"],
                                                    abs=1e-9, rel=1e-9)
        assert rep["lanes"], "disagg request must record its prefill lane"
        saw_handoff = saw_handoff or terms["handoff_s"] > 0
    assert saw_handoff  # final flushes are measured walls, not zeros


# ---------------------------------------------------------------------------
# Consumer 2: Chrome trace export (Perfetto)
# ---------------------------------------------------------------------------


def test_chrome_trace_exports_valid_json_with_flows(llama, tmp_path):
    cfg, model = llama
    tr = TraceRecorder(TraceConfig())
    engine = DisaggServingEngine(
        model,
        ServingConfig(n_slots=2, max_len=64, prefill_chunks=[4, 8]),
        disagg=DisaggConfig(n_prefill_lanes=2),
        tracing=tr,
    )
    engine.run(_prompts(cfg, [6, 11, 17, 9], seed=5), max_new_tokens=3)
    path = str(tmp_path / "trace.json")
    tr.export_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    phases = {e["ph"] for e in events}
    assert {"M", "X"} <= phases
    # Process metadata names every subsystem that emitted spans.
    names = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert {"serving", "prefill", "handoff", "decode"} <= names
    # Flow events stitch the KV handoff from prefill lane to decode slot:
    # each "s" (on the handoff span) pairs with an "f" (on the kv_insert
    # span) through a shared flow id, across different tids.
    starts = {e["id"]: e for e in events if e["ph"] == "s"}
    finishes = {e["id"]: e for e in events if e["ph"] == "f"}
    assert starts and finishes
    paired = set(starts) & set(finishes)
    assert paired, "at least one handoff must stitch end-to-end"
    for fid in paired:
        assert starts[fid]["ts"] <= finishes[fid]["ts"]
    # X events carry non-negative microsecond walls.
    for e in events:
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0


# ---------------------------------------------------------------------------
# Tick-domain determinism under seeded chaos
# ---------------------------------------------------------------------------


def _chaos_run(llama, seed):
    cfg, model = llama
    tr = TraceRecorder(TraceConfig())
    chaos = FaultInjector(
        seed=seed,
        rates={"handoff_device_put": {"transfer_error": 0.25, "delay": 0.2}},
    )
    engine = DisaggServingEngine(
        model,
        ServingConfig(n_slots=2, max_len=64, prefill_chunks=[4, 8]),
        disagg=DisaggConfig(n_prefill_lanes=2),
        chaos=chaos,
        tracing=tr,
    )
    engine.run(_prompts(cfg, [6, 11, 17, 9, 5], seed=5), max_new_tokens=3)
    return tr


def test_tick_trace_bit_identical_across_seeded_runs(llama):
    a = _chaos_run(llama, seed=1234)
    b = _chaos_run(llama, seed=1234)
    ja = json.dumps(a.tick_trace(), sort_keys=True)
    jb = json.dumps(b.tick_trace(), sort_keys=True)
    assert ja == jb  # the deterministic tick-domain projection replays
    c = _chaos_run(llama, seed=99)
    assert json.dumps(c.tick_trace(), sort_keys=True) != ja


def test_chaos_injections_annotate_spans(llama):
    tr = _chaos_run(llama, seed=1234)
    chaos_spans = [s for s in tr.spans() if s.subsystem == "chaos"]
    assert chaos_spans, "seeded rates must inject at least one fault"
    for s in chaos_spans:
        assert s.attrs.get("injected") is True
        assert "point" in s.attrs and "kind" in s.attrs
        assert s.attrs.get("seed") == 1234


# ---------------------------------------------------------------------------
# Consumer 3: Prometheus text exposition
# ---------------------------------------------------------------------------


def test_metrics_text_matches_stats(llama):
    cfg, model = llama
    tr = TraceRecorder(TraceConfig())
    engine = ServingEngine(
        model,
        ServingConfig(n_slots=2, max_len=64, prefill_chunks=[4, 8]),
        tracing=tr,
    )
    engine.run(_prompts(cfg, [5, 9], seed=2), max_new_tokens=3)
    text = tr.metrics_text()
    stats = engine.stats()
    lines = dict(
        line.rsplit(" ", 1) for line in text.splitlines()
        if line and not line.startswith("#") and "{" not in line
    )
    assert float(lines["accelerate_tpu_serving_requests_completed"]) == (
        stats["requests_completed"])
    assert float(lines["accelerate_tpu_serving_tokens_out"]) == (
        stats["tokens_out"])
    # window_stats parity rides through the nested "window" block.
    assert float(lines["accelerate_tpu_serving_window_requests"]) == (
        stats["window"]["requests"])
    assert "accelerate_tpu_trace_spans_total" in text
    assert float(lines["accelerate_tpu_trace_requests"]) == 2


# ---------------------------------------------------------------------------
# Telemetry wiring (TelemetryKwargs(tracing=...)) + bounded buffers
# ---------------------------------------------------------------------------


def test_telemetry_kwargs_builds_recorder(tmp_path):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import TelemetryKwargs

    acc = Accelerator(
        project_dir=str(tmp_path),
        kwargs_handlers=[TelemetryKwargs(tracing=True, log_every=0)],
    )
    assert isinstance(acc.telemetry.tracing, TraceRecorder)
    assert acc.telemetry.summary()["tracing"]["spans"] == 0


def test_telemetry_kwargs_tracing_off(tmp_path):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import TelemetryKwargs

    acc = Accelerator(
        project_dir=str(tmp_path),
        kwargs_handlers=[TelemetryKwargs(log_every=0)],
    )
    assert acc.telemetry.tracing is None
    assert "tracing" not in acc.telemetry.summary()


def test_span_buffer_bounded():
    tr = TraceRecorder(TraceConfig(max_spans=10))
    for i in range(25):
        tr.instant("serving", "tickle", i)
    assert tr.stats()["spans"] == 10
    assert tr.stats()["dropped_spans"] == 15
