"""Step-level telemetry subsystem (telemetry.py): JSONL schema, recompile
watchdog, collective counters, dataloader-wait accounting, straggler probe,
checkpoint durations — plus the ProfileSession schedule boundaries
(skip_first / wait+warmup / repeat limit) and the logging/tracking
satellites. All CPU-only, tier-1 fast."""

import json
import logging
import os
import time

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# Toy training-loop harness (the test_training.py regression idiom).
# ---------------------------------------------------------------------------


def _setup(seed=0, n=64, dim=8):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (x @ rng.normal(size=(dim, 1))).astype(np.float32)
    return x, y


class _ArrayDataset:
    def __init__(self, x, y, item_delay_s: float = 0.0):
        self.x, self.y = x, y
        self.item_delay_s = item_delay_s

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        if self.item_delay_s:
            time.sleep(self.item_delay_s)
        return {"x": self.x[i], "y": self.y[i]}


class _Spec:
    def __init__(self, dataset, batch_size):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = None
        self.drop_last = False


def _linear_model():
    import flax.linen as nn

    class Linear(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(x)

    return Linear()


def _accelerator(tmp_path, item_delay_s=0.0, dataloader_config=None, **tkw):
    import jax
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.utils import TelemetryKwargs, set_seed

    set_seed(0)
    kwargs = dict(sync_timing=True, straggler_probe_every=0, log_every=0)
    kwargs.update(tkw)
    acc = Accelerator(
        project_dir=str(tmp_path),
        dataloader_config=dataloader_config,
        kwargs_handlers=[TelemetryKwargs(**kwargs)],
    )
    x, y = _setup()
    module = _linear_model()
    model = Model.from_flax(module, jax.random.key(0), x[:1])
    model, opt, dl = acc.prepare(
        model, optax.sgd(0.1), _Spec(_ArrayDataset(x, y, item_delay_s), 16)
    )

    def loss_fn(params, batch):
        pred = module.apply({"params": params}, batch["x"])
        return ((pred - batch["y"]) ** 2).mean()

    return acc, dl, loss_fn, (x, y)


def _run_steps(acc, dl, loss_fn, steps):
    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    done = 0
    while done < steps:
        for batch in dl:
            state, metrics = step(state, batch)
            done += 1
            if done >= steps:
                break
    return step, state


def _records(tmp_path, rank=0):
    path = os.path.join(str(tmp_path), "telemetry", f"rank_{rank}.jsonl")
    assert os.path.exists(path), f"no telemetry report at {path}"
    with open(path) as fh:
        return [json.loads(line) for line in fh]


def _global_batch(acc, x, y, n):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(acc.mesh, PartitionSpec(("dp_replicate", "dp_shard")))
    return {
        "x": jax.device_put(x[:n], sharding),
        "y": jax.device_put(y[:n], sharding),
    }


# ---------------------------------------------------------------------------
# Tentpole: TelemetryRecorder
# ---------------------------------------------------------------------------


def test_step_records_schema_and_summary(tmp_path):
    acc, dl, loss_fn, _ = _accelerator(tmp_path)
    _run_steps(acc, dl, loss_fn, 8)
    acc.end_training()
    records = _records(tmp_path)
    steps = [r for r in records if r["event"] == "step"]
    assert len(steps) == 8
    required = {
        "step", "time", "wall_s", "data_wait_s", "samples", "samples_per_s",
        "tokens_per_s", "ema_samples_per_s", "ema_tokens_per_s", "collectives",
        "hbm_bytes_in_use", "hbm_peak_bytes", "recompiles", "loss",
    }
    for r in steps:
        assert required <= r.keys(), f"missing {required - r.keys()}"
        assert r["wall_s"] > 0
        assert r["samples"] == 16  # loader batch size = global batch dim
    # Step counter is 1-based and monotonic.
    assert [r["step"] for r in steps] == list(range(1, 9))
    summary = records[-1]
    assert summary["event"] == "summary"
    assert summary["steps"] == 8
    assert summary["step_time_p50_s"] <= summary["step_time_p90_s"]
    assert summary["step_time_mean_s"] > 0


def test_recompile_watchdog_fires_once_on_shape_change(tmp_path, caplog):
    acc, dl, loss_fn, (x, y) = _accelerator(tmp_path)
    step, state = _run_steps(acc, dl, loss_fn, 6)
    before = acc.telemetry.recompiles
    with caplog.at_level(logging.WARNING):
        state, _ = step(state, _global_batch(acc, x, y, 8))
        state, _ = step(state, _global_batch(acc, x, y, 8))  # same shape: no new warning
    acc.end_training()
    assert acc.telemetry.recompiles >= before + 1
    watchdog = [
        r for r in caplog.records if "jitted step recompiled" in r.getMessage()
    ]
    assert len(watchdog) == 1, [r.getMessage() for r in watchdog]
    assert "float32[8, 8]" in watchdog[0].getMessage()  # offending digest
    recs = [r for r in _records(tmp_path) if r["event"] == "recompile"]
    shape_changes = [r for r in recs if r["reason"] == "batch shape/dtype change"]
    assert len(shape_changes) == 1
    assert "batch_digest" in shape_changes[0]
    # The cumulative counter in subsequent step records reflects it.
    steps = [r for r in _records(tmp_path) if r["event"] == "step"]
    assert steps[-1]["recompiles"] > steps[0]["recompiles"]


def test_donated_layout_recompile_counted_but_not_warned(tmp_path, caplog):
    """The known cache 1->2 growth on the second call (donated-buffer layout
    specialization) is recorded but must not cry wolf."""
    acc, dl, loss_fn, _ = _accelerator(tmp_path)
    with caplog.at_level(logging.WARNING):
        _run_steps(acc, dl, loss_fn, 6)
    acc.end_training()
    assert not any("recompiled" in r.getMessage() for r in caplog.records)
    recs = [r for r in _records(tmp_path) if r["event"] == "recompile"]
    assert all("layout" in r["reason"] for r in recs)


def test_collective_counters_count_and_bytes(tmp_path):
    from accelerate_tpu.utils.operations import collective_counters

    acc, dl, loss_fn, _ = _accelerator(tmp_path)
    payload = np.ones((4, 2), dtype=np.float32)
    acc.gather(payload)
    acc.reduce(payload)
    acc.pad_across_processes(payload)
    from accelerate_tpu.utils import broadcast

    broadcast(payload)
    snap = collective_counters.snapshot()
    for op in ("gather", "reduce", "pad_across_processes", "broadcast"):
        assert snap[op]["count"] == 1, snap
        assert snap[op]["bytes"] == payload.nbytes, snap
    # The tally rides in every step record.
    _run_steps(acc, dl, loss_fn, 2)
    acc.end_training()
    steps = [r for r in _records(tmp_path) if r["event"] == "step"]
    assert steps[-1]["collectives"]["gather"]["count"] >= 1
    # Recorder teardown disables the process-global counters again.
    assert not collective_counters.enabled


def test_collective_counters_disabled_without_telemetry():
    from accelerate_tpu.utils.operations import collective_counters

    from accelerate_tpu import Accelerator

    Accelerator()
    collective_counters.enabled = False
    collective_counters.reset()
    from accelerate_tpu.utils import gather

    gather(np.ones((2,), dtype=np.float32))
    assert collective_counters.snapshot() == {}


def test_dataloader_wait_accounting(tmp_path):
    from accelerate_tpu.utils import DataLoaderConfiguration

    # prefetch_size=0: collation happens synchronously inside next(), so the
    # per-item sleep must show up as data wait.
    acc, dl, loss_fn, _ = _accelerator(
        tmp_path,
        item_delay_s=0.002,
        dataloader_config=DataLoaderConfiguration(prefetch_size=0),
    )
    _run_steps(acc, dl, loss_fn, 4)
    acc.end_training()
    steps = [r for r in _records(tmp_path) if r["event"] == "step"]
    # 16 items * 2ms each >= 32ms per batch; generous floor for CI jitter.
    assert max(r["data_wait_s"] for r in steps) > 0.01
    summary = [r for r in _records(tmp_path) if r["event"] == "summary"][0]
    assert summary["data_wait_mean_s"] > 0


def test_straggler_probe_records_skew(tmp_path):
    acc, dl, loss_fn, _ = _accelerator(tmp_path, straggler_probe_every=2)
    _run_steps(acc, dl, loss_fn, 4)
    acc.end_training()
    probes = [r for r in _records(tmp_path) if r["event"] == "straggler_probe"]
    assert len(probes) == 2  # steps 2 and 4
    for p in probes:
        assert p["step_time_max_s"] >= p["step_time_min_s"] > 0
        assert p["skew"] >= 0
        assert len(p["rank_times_s"]) == acc.num_processes


def test_checkpoint_durations_recorded(tmp_path):
    acc, dl, loss_fn, _ = _accelerator(tmp_path)
    _run_steps(acc, dl, loss_fn, 2)
    ckpt = str(tmp_path / "ckpt")
    acc.save_state(ckpt)
    acc.load_state(ckpt)
    acc.end_training()
    records = _records(tmp_path)
    saves = [r for r in records if r["event"] == "checkpoint_save"]
    loads = [r for r in records if r["event"] == "checkpoint_load"]
    assert len(saves) == 1 and len(loads) == 1
    assert saves[0]["seconds"] > 0 and saves[0]["dir"] == ckpt
    assert loads[0]["seconds"] > 0
    summary = records[-1]
    assert summary["checkpoint_events"] == 2


def test_imperative_path_records_optimizer_steps(tmp_path):
    acc, dl, loss_fn, _ = _accelerator(tmp_path)
    opt = acc._optimizers[0]
    done = 0
    for batch in dl:
        with acc.accumulate():
            acc.backward(loss_fn, batch)
            opt.step()
            opt.zero_grad()
        done += 1
        if done >= 3:
            break
    acc.end_training()
    steps = [r for r in _records(tmp_path) if r["event"] == "optimizer_step"]
    assert len(steps) == 3
    for r in steps:
        assert r["backward_s"] > 0
        assert r["apply_s"] > 0
        assert r["wall_s"] >= r["backward_s"]


def test_disabled_by_default_no_files_no_recorder(tmp_path):
    import jax
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.utils import set_seed

    set_seed(0)
    acc = Accelerator(project_dir=str(tmp_path))
    assert acc.telemetry is None
    x, y = _setup()
    module = _linear_model()
    model = Model.from_flax(module, jax.random.key(0), x[:1])
    model, opt, dl = acc.prepare(model, optax.sgd(0.1), _Spec(_ArrayDataset(x, y), 16))

    def loss_fn(params, batch):
        pred = module.apply({"params": params}, batch["x"])
        return ((pred - batch["y"]) ** 2).mean()

    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    for batch in dl:
        state, _ = step(state, batch)
        break
    acc.end_training()
    assert not os.path.exists(os.path.join(str(tmp_path), "telemetry"))


def test_tracker_forwarding(tmp_path):
    """Every log_every steps the summary goes through Accelerator.log into
    the tracker stack under the telemetry/ prefix."""
    acc, dl, loss_fn, _ = _accelerator(tmp_path, log_every=2)

    class _Sink:
        name = "sink"
        requires_logging_directory = False
        logged = []

        def store_init_configuration(self, values):
            pass

        def log(self, values, step=None, **kwargs):
            self.logged.append((step, values))

        def finish(self):
            pass

    sink = _Sink()
    acc.trackers = [sink]
    _run_steps(acc, dl, loss_fn, 5)
    acc.end_training()
    assert [s for s, _ in sink.logged] == [2, 4]
    for _, values in sink.logged:
        assert "telemetry/step_time_s" in values
        assert "telemetry/recompiles" in values


# ---------------------------------------------------------------------------
# ProfileSession schedule boundaries (satellite coverage)
# ---------------------------------------------------------------------------


def _stubbed_session(tmp_path, schedule):
    from unittest import mock

    import accelerate_tpu.utils.profiling as P
    from accelerate_tpu.utils import ProfileKwargs

    events = []
    handler = ProfileKwargs(schedule_option=schedule, output_trace_dir=str(tmp_path))
    patches = (
        mock.patch.object(P.jax.profiler, "start_trace", lambda d: events.append(("start", d))),
        mock.patch.object(P.jax.profiler, "stop_trace", lambda: events.append(("stop",))),
    )
    return P.ProfileSession(handler, str(tmp_path)), events, patches


def test_profile_schedule_skip_first(tmp_path):
    """skip_first delays the FIRST cycle only; windows land on the same
    relative steps afterwards (torch.profiler semantics)."""
    s, events, patches = _stubbed_session(
        tmp_path, {"skip_first": 3, "wait": 1, "warmup": 1, "active": 2, "repeat": 1}
    )
    with patches[0], patches[1]:
        s.enter()
        for i in range(1, 12):
            events.append(("work", i))
            s.step()
        s.exit()
    i0 = events.index(("start", str(tmp_path / "cycle_0")))
    j0 = events.index(("stop",))
    # skip 3, wait 1, warmup 1 → active steps are 6 and 7.
    assert [e[1] for e in events[i0:j0] if e[0] == "work"] == [6, 7]


def test_profile_schedule_repeat_limit(tmp_path):
    """repeat=N caps the number of traced cycles no matter how many steps run."""
    s, events, patches = _stubbed_session(
        tmp_path, {"wait": 0, "warmup": 1, "active": 1, "repeat": 2}
    )
    with patches[0], patches[1]:
        s.enter()
        for i in range(1, 21):
            events.append(("work", i))
            s.step()
        s.exit()
    assert sum(1 for e in events if e[0] == "start") == 2
    assert s.cycles_done == 2
    assert s.trace_dirs == [str(tmp_path / "cycle_0"), str(tmp_path / "cycle_1")]


def test_profile_schedule_skip_first_with_zero_wait_warmup(tmp_path):
    """skip_first > 0 with wait+warmup == 0: the first active window starts
    right after the skipped steps, not at enter()."""
    s, events, patches = _stubbed_session(
        tmp_path, {"skip_first": 2, "active": 2, "repeat": 1}
    )
    with patches[0], patches[1]:
        s.enter()
        for i in range(1, 8):
            events.append(("work", i))
            s.step()
        s.exit()
    starts = [e for e in events if e[0] == "start"]
    assert len(starts) == 1
    i0 = events.index(("start", str(tmp_path / "cycle_0")))
    j0 = events.index(("stop",))
    assert [e[1] for e in events[i0:j0] if e[0] == "work"] == [3, 4]


# ---------------------------------------------------------------------------
# Logging satellites: warning_once + root-logger hygiene
# ---------------------------------------------------------------------------


def test_warning_once_dedups_and_handles_unhashable(caplog):
    from accelerate_tpu import PartialState
    from accelerate_tpu.logging import get_logger

    PartialState()
    logger = get_logger("test_warning_once_dedup")
    with caplog.at_level(logging.WARNING, logger="test_warning_once_dedup"):
        logger.warning_once("dup message %s", 1)
        logger.warning_once("dup message %s", 1)
        logger.warning_once("dup message %s", 2)  # different args: new warning
        # Unhashable argument must not crash (the lru_cache version did).
        logger.warning_once("unhashable %s", {"a": [1, 2]})
        logger.warning_once("unhashable %s", {"a": [1, 2]})
    messages = [r.getMessage() for r in caplog.records]
    assert messages.count("dup message 1") == 1
    assert messages.count("dup message 2") == 1
    assert messages.count("unhashable {'a': [1, 2]}") == 1


def test_warning_once_shared_across_adapters(caplog):
    """Two adapters for the same message dedup against ONE module-level set —
    no per-adapter lru_cache leak."""
    from accelerate_tpu import PartialState
    from accelerate_tpu.logging import get_logger

    PartialState()
    a = get_logger("test_warning_once_shared")
    b = get_logger("test_warning_once_shared")
    assert a is not b
    with caplog.at_level(logging.WARNING, logger="test_warning_once_shared"):
        a.warning_once("shared-once")
        b.warning_once("shared-once")
    assert sum(1 for r in caplog.records if r.getMessage() == "shared-once") == 1


def test_get_logger_does_not_clobber_root_level():
    from accelerate_tpu.logging import get_logger

    root = logging.getLogger()
    before = root.level
    logger = get_logger("test_root_untouched", log_level="DEBUG")
    assert logging.getLogger("test_root_untouched").level == logging.DEBUG
    assert root.level == before


# ---------------------------------------------------------------------------
# Tracking satellite: JSONTracker crash-safety
# ---------------------------------------------------------------------------


def test_json_tracker_flushes_each_record(tmp_path):
    from accelerate_tpu import PartialState
    from accelerate_tpu.tracking import JSONTracker

    PartialState()
    t = JSONTracker("run", logging_dir=str(tmp_path))
    t.store_init_configuration({"lr": 0.1})
    t.log({"loss": 1.0}, step=1)
    t.log({"loss": 0.5}, step=2)
    # Read WITHOUT finish(): a preempted run must still have every record.
    with open(t.path) as fh:
        lines = [json.loads(l) for l in fh]
    assert len(lines) == 3
    assert lines[0]["event"] == "config"
    assert [l["step"] for l in lines[1:]] == [1, 2]
    t.finish()
