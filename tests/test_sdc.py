"""Unit tier for the silent-data-corruption sentinel (sdc.py): the pure
voting/flip/digest math, quarantine persistence, config validation, the
chaos bit_flip wiring, and the decode canary's suppression discipline —
all CPU-only and mesh-free (the collective protocol itself is `make
sdc-smoke`'s job)."""

import json
import os

import numpy as np
import pytest

from accelerate_tpu.sdc import (
    DecodeCanary,
    SDCConfig,
    SDCSentinel,
    flip_float32,
    load_quarantine,
    record_quarantine,
    vote,
)


# ---------------------------------------------------------------------------
# vote(): bit-wise majority with the no-majority probe fallback
# ---------------------------------------------------------------------------


def test_vote_all_agree():
    v = vote([1.5, 1.5, 1.5, 1.5])
    assert v["agree"] and v["has_majority"]
    assert v["outliers"] == [] and v["majority_ranks"] == [0, 1, 2, 3]


def test_vote_majority_names_the_outlier():
    v = vote([2.0, 2.0, 7.0, 2.0])
    assert not v["agree"] and v["has_majority"]
    assert v["outliers"] == [2]
    assert v["majority_ranks"] == [0, 1, 3]


def test_vote_two_replica_split_has_no_majority():
    # n=2 disagreement: counting cannot convict either side — every rank is
    # an outlier and the caller falls back to the redundant-compute probe.
    v = vote([1.0, 2.0])
    assert not v["agree"] and not v["has_majority"]
    assert v["outliers"] == [0, 1] and v["majority_ranks"] == []


def test_vote_three_way_tie_has_no_majority():
    v = vote([1.0, 2.0, 3.0])
    assert not v["has_majority"] and v["outliers"] == [0, 1, 2]


def test_vote_is_bitwise_not_approximate():
    # One float32-ulp apart: numerically negligible, but silent corruption
    # is exact or it isn't there — the vote must flag it.
    base = 100.0
    nudged = float(np.nextafter(np.float32(base), np.float32(np.inf)))
    v = vote([base, base, nudged])
    assert not v["agree"] and v["outliers"] == [2]


# ---------------------------------------------------------------------------
# flip_float32(): finite, wrong, and reversible
# ---------------------------------------------------------------------------


def test_flip_float32_is_finite_wrong_and_involutive():
    for value in (0.5, 123.456, -3.25, 1e30):
        for bit in (0, 5, 22):
            flipped = flip_float32(value, bit=bit)
            assert np.isfinite(flipped), (value, bit)
            assert flipped != float(np.float32(value)), (value, bit)
            assert flip_float32(flipped, bit=bit) == float(np.float32(value))


def test_flip_float32_survives_float32_transport():
    # The allgather transport truncates to float32 (the whole reason the
    # flip lives in float32 mantissa space): the corruption must still be
    # visible after a float64 -> float32 -> float64 round trip.
    value = 86.97010040283203
    flipped = flip_float32(value, bit=5)
    assert float(np.float32(flipped)) == flipped
    assert np.float64(np.float32(flipped)).tobytes() != \
        np.float64(np.float32(value)).tobytes()


# ---------------------------------------------------------------------------
# integrity_digest(): leaf-order sensitivity
# ---------------------------------------------------------------------------


def test_integrity_digest_detects_leaf_swap():
    a = np.full((4,), 2.0, np.float32)
    b = np.full((4,), 5.0, np.float32)
    d1 = float(np.asarray(_digest({"a": a, "b": b})))
    d2 = float(np.asarray(_digest({"a": b, "b": a})))
    assert np.isfinite(d1) and np.isfinite(d2)
    # Plain unweighted abs-sums would cancel the swap; the per-leaf weights
    # must not.
    assert d1 != d2


def test_integrity_digest_ignores_integer_leaves():
    a = np.full((4,), 2.0, np.float32)
    step = np.asarray(7, np.int32)
    assert float(np.asarray(_digest({"a": a, "step": step}))) == \
        float(np.asarray(_digest({"a": a, "step": step + 3})))


def _digest(params):
    from accelerate_tpu.sdc import integrity_digest

    return integrity_digest(params, grad_norm=1.0)


# ---------------------------------------------------------------------------
# SDCConfig validation + kwargs arming
# ---------------------------------------------------------------------------


def test_sdc_config_validation():
    assert SDCConfig().vote_every == 8
    with pytest.raises(ValueError):
        SDCConfig(vote_every=0)
    with pytest.raises(ValueError):
        SDCConfig(repair="reboot")
    with pytest.raises(ValueError):
        SDCConfig(probe="maybe")
    with pytest.raises(ValueError):
        SDCConfig(max_repairs=-1)
    with pytest.raises(ValueError):
        SDCConfig(bit=23)  # float32 mantissa bits are 0..22
    with pytest.raises(ValueError):
        SDCConfig(bit=-1)


def test_fault_tolerance_kwargs_sdc_off_by_default():
    from accelerate_tpu.utils import FaultToleranceKwargs

    assert FaultToleranceKwargs().sdc is None
    assert FaultToleranceKwargs(sdc=dict(vote_every=4)).sdc == {"vote_every": 4}
    assert FaultToleranceKwargs(sdc=SDCConfig()).sdc.vote_every == 8
    with pytest.raises(ValueError):
        FaultToleranceKwargs(sdc="yes")


# ---------------------------------------------------------------------------
# Quarantine persistence
# ---------------------------------------------------------------------------


def test_quarantine_roundtrip_and_torn_record(tmp_path):
    d = str(tmp_path)
    assert load_quarantine(d) == {"hosts": []}
    assert load_quarantine(None) == {"hosts": []}
    entry = {"process_index": 3, "host": "tpu-worker-7", "step": 120,
             "tick": 119, "reason": "probe reproduced", "time": 1.0}
    rec = record_quarantine(d, entry)
    assert rec["hosts"] == [entry]
    record_quarantine(d, {**entry, "host": "tpu-worker-9"})
    hosts = [h["host"] for h in load_quarantine(d)["hosts"]]
    assert hosts == ["tpu-worker-7", "tpu-worker-9"]
    # A torn record (partial JSON) must never block a relaunch.
    with open(os.path.join(d, "sdc_quarantine.json"), "w") as f:
        f.write('{"hosts": [{"ho')
    assert load_quarantine(d) == {"hosts": []}


def test_sentinel_loads_quarantine_from_prior_incarnations(tmp_path):
    record_quarantine(str(tmp_path), {"host": "bad-host", "process_index": 1})

    class _Acc:
        project_dir = str(tmp_path)

    class _Mgr:
        accelerator = _Acc()

    s = SDCSentinel(_Mgr(), SDCConfig())
    assert s.summary()["quarantined_hosts"] == ["bad-host"]


# ---------------------------------------------------------------------------
# Chaos wiring: the bit_flip kind and point-name-keyed draws
# ---------------------------------------------------------------------------


def test_chaos_bit_flip_points_and_extras():
    from accelerate_tpu.chaos import _POINT_KINDS, FAULT_KINDS, FaultInjector

    assert "bit_flip" in FAULT_KINDS
    assert "bit_flip" in _POINT_KINDS["train_step"]
    assert "bit_flip" in _POINT_KINDS["decode_tick"]
    inj = FaultInjector(seed=7, schedule=[
        {"point": "train_step", "kind": "bit_flip", "tick": 4, "unit": 0,
         "mode": "sticky", "bit": 9}])
    assert inj.draw("train_step", tick=3) is None
    f = inj.draw("train_step", tick=4, unit=0)
    assert f is not None and f.kind == "bit_flip"
    assert f.extra["mode"] == "sticky" and f.extra["bit"] == 9
    assert inj.injected == [{"tick": 4, "point": "train_step",
                             "kind": "bit_flip", "unit": 0}]
    # One-shot: the schedule entry is spent.
    assert inj.draw("train_step", tick=4, unit=0) is None


def test_chaos_draws_are_point_name_keyed():
    # Adding bit_flip rates at one point must not move another point's
    # draws for the same seed — the u01 stream is (seed, point, tick, unit).
    from accelerate_tpu.chaos import FaultInjector

    base = FaultInjector(seed=11, rates={"train_step": {"slow_step": 0.3}})
    both = FaultInjector(seed=11, rates={"train_step": {"slow_step": 0.3},
                                         "decode_tick": {"bit_flip": 0.5}})
    draws_a = [(f.kind if f else None)
               for f in (base.draw("train_step", t) for t in range(64))]
    draws_b = [(f.kind if f else None)
               for f in (both.draw("train_step", t) for t in range(64))]
    assert draws_a == draws_b


def test_sentinel_note_bit_flip_modes():
    class _Acc:
        project_dir = None

    class _Mgr:
        accelerator = _Acc()

    from accelerate_tpu.chaos import Fault

    s = SDCSentinel(_Mgr(), SDCConfig())
    s.note_bit_flip(Fault("train_step", "bit_flip", 4, 0, 0.1,
                          {"mode": "transient"}))
    assert s._flip is not None and not s._sticky
    s.note_bit_flip(Fault("train_step", "bit_flip", 5, 0, 0.1,
                          {"mode": "sticky"}))
    assert s._sticky


# ---------------------------------------------------------------------------
# DecodeCanary: suppression discipline against a fake engine
# ---------------------------------------------------------------------------


class _FakeEngine:
    """Just enough engine surface for the canary: a finished queue, a tick
    counter, a journal slot, and a submit that records what the journal
    looked like DURING the call."""

    def __init__(self):
        self._stats = {"ticks": 0}
        self._finished = []
        self._journal = "WAL"
        self.decode_devices = ["cpu:4"]
        self._next_id = 0
        self.journal_during_submit = None
        self.canary = None

    def attach_sdc_canary(self, canary):
        self.canary = canary

    def submit(self, prompt, max_new_tokens=None, rng=None):
        self.journal_during_submit = self._journal
        rid = self._next_id
        self._next_id += 1
        return rid

    def tick(self):
        # Complete any inflight probe with a deterministic row, then run
        # the end-of-tick canary hook like the real engine does.
        self._stats["ticks"] += 1
        c = self.canary
        if c is not None and c._inflight is not None:
            self._finished.append(
                {"id": c._inflight, "status": "ok",
                 "tokens": np.asarray([1, 2, 3, 9], np.int64)})
        if c is not None:
            c.on_tick()


class _FakeAutoscaler:
    def __init__(self):
        self.dead = []

    def mark_device_dead(self, dev):
        self.dead.append(dev)


def test_canary_warmup_arms_and_suppresses(tmp_path):
    eng = _FakeEngine()
    canary = DecodeCanary(eng, every=4)
    assert eng.canary is canary  # attach hook ran
    canary.warmup()
    assert canary.armed and canary._golden == [1, 2, 3, 9]
    assert canary.golden_digest is not None
    # The probe row never lingers in the finished queue (poll-invisible)
    # and the journal was detached exactly for the submit call.
    assert eng._finished == []
    assert eng.journal_during_submit is None
    assert eng._journal == "WAL"
    assert canary.probe_rids == [0]
    # Warmup zeroes the counters: steady-state probes count from zero.
    assert canary.summary()["probes"] == 0


def test_canary_periodic_probe_and_mismatch_quarantine():
    eng = _FakeEngine()
    auto = _FakeAutoscaler()
    canary = DecodeCanary(eng, every=4, autoscaler=auto)
    canary.warmup()
    for _ in range(8):
        eng.tick()
    s = canary.summary()
    assert s["probes"] >= 1 and s["mismatches"] == 0 and auto.dead == []

    # Corrupt the next probe's row: one flipped token = silent corruption.
    def corrupt_tick():
        eng._stats["ticks"] += 1
        if canary._inflight is not None:
            eng._finished.append(
                {"id": canary._inflight, "status": "ok",
                 "tokens": np.asarray([1, 2, 3, 8], np.int64)})
        canary.on_tick()

    while canary._inflight is None:
        eng.tick()  # advance until a probe is submitted
    corrupt_tick()
    s = canary.summary()
    assert s["mismatches"] == 1 and s["quarantines"] == 1
    assert auto.dead == ["cpu:4"]
    assert s["suppressed_rows"] == s["probes"]


def test_canary_rejects_empty_prompt():
    with pytest.raises(ValueError):
        DecodeCanary(_FakeEngine(), prompt=np.zeros((0,), np.int32))


def test_canary_reset_counters_keeps_golden():
    eng = _FakeEngine()
    canary = DecodeCanary(eng, every=4)
    canary.warmup()
    for _ in range(8):
        eng.tick()
    assert canary.summary()["probes"] >= 1
    canary.reset_counters()
    s = canary.summary()
    assert s["probes"] == 0 and s["armed"] is True
    assert s["golden_digest"] == canary.golden_digest


# ---------------------------------------------------------------------------
# Exit-code protocol
# ---------------------------------------------------------------------------


def test_sdc_exit_code_in_protocol_table():
    from accelerate_tpu.utils.constants import (
        EXIT_CODE_TABLE,
        PROTOCOL_EXIT_CLASSES,
        SDC_EXIT_CODE,
    )

    assert SDC_EXIT_CODE == 79
    assert PROTOCOL_EXIT_CLASSES[SDC_EXIT_CODE] == "sdc"
    row = next(r for r in EXIT_CODE_TABLE if r["code"] == SDC_EXIT_CODE)
    assert "SHRUNK" in row["response"]


def test_quarantine_file_is_json_on_disk(tmp_path):
    record_quarantine(str(tmp_path), {"host": "h1"})
    with open(os.path.join(str(tmp_path), "sdc_quarantine.json")) as f:
        assert json.load(f)["hosts"] == [{"host": "h1"}]
