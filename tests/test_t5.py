"""T5 encoder-decoder family (models/t5.py) — the reference reaches T5 only
via Megatron's T5TrainStep (utils/megatron_lm.py:640-760); here it is native.
Covers forward shape, scan/unrolled parity, TP-sharded logits parity, and a
training-loss decrease under the fused step."""

import numpy as np
import pytest


def _data(cfg, b=2, se=12, sd=8, seed=0):
    rng = np.random.default_rng(seed)
    enc_ids = rng.integers(1, cfg.vocab_size, size=(b, se), dtype=np.int32)
    labels = rng.integers(1, cfg.vocab_size, size=(b, sd), dtype=np.int32)
    return enc_ids, labels


def test_t5_forward_shape_and_finite():
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration, shift_tokens_right

    cfg = T5Config.tiny(dtype=jnp.float32)
    module = T5ForConditionalGeneration(cfg)
    enc_ids, labels = _data(cfg)
    dec_in = shift_tokens_right(jnp.asarray(labels))
    params = module.init(jax.random.key(0), enc_ids, dec_in)["params"]
    logits = module.apply({"params": params}, enc_ids, dec_in)
    assert logits.shape == (2, 8, cfg.vocab_size)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_t5_scan_matches_unrolled():
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration, shift_tokens_right

    cfg_s = T5Config.tiny(dtype=jnp.float32, num_layers=3, scan_layers=True)
    cfg_u = T5Config.tiny(dtype=jnp.float32, num_layers=3, scan_layers=False)
    enc_ids, labels = _data(cfg_s)
    dec_in = shift_tokens_right(jnp.asarray(labels))

    m_s = T5ForConditionalGeneration(cfg_s)
    p_s = m_s.init(jax.random.key(0), enc_ids, dec_in)["params"]
    m_u = T5ForConditionalGeneration(cfg_u)

    # Map scanned params [L-1, ...] onto the unrolled block_{i+1} names.
    def unstack(tree, idx):
        return jax.tree.map(lambda x: np.asarray(x)[idx], tree)

    pu = {k: v for k, v in p_s.items() if k not in ("encoder", "decoder")}
    for stack in ("encoder", "decoder"):
        src = p_s[stack]
        dst = {k: v for k, v in src.items() if k != "layers"}
        if "layers" in src:
            for i in range(cfg_s.num_layers - 1 if stack == "encoder" else cfg_s.n_dec - 1):
                dst[f"block_{i+1}"] = unstack(src["layers"]["block"], i)
        pu[stack] = dst
    out_s = m_s.apply({"params": p_s}, enc_ids, dec_in)
    out_u = m_u.apply({"params": pu}, enc_ids, dec_in)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_u), rtol=2e-5, atol=2e-5)


def test_t5_tp_sharded_logits_match_replicated():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu import AcceleratorState, ParallelismConfig
    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration, shift_tokens_right, t5_tp_rules
    from accelerate_tpu.parallel import plan_parameter_sharding

    AcceleratorState._reset_state()
    state = AcceleratorState(parallelism_config=ParallelismConfig(tp_size=4, dp_shard_size=2))
    mesh = state.mesh
    cfg = T5Config.tiny(dtype=jnp.float32)
    module = T5ForConditionalGeneration(cfg)
    enc_ids, labels = _data(cfg)
    dec_in = shift_tokens_right(jnp.asarray(labels))
    params = module.init(jax.random.key(0), enc_ids, dec_in)["params"]
    ref = np.asarray(module.apply({"params": params}, enc_ids, dec_in))

    shardings = plan_parameter_sharding(
        params, mesh, parallelism_config=state.parallelism_config,
        tp_rules=t5_tp_rules(cfg.scan_layers), min_size_to_shard=0,
    )
    sharded = jax.tree.map(lambda p, s: jax.device_put(p, s), params, shardings)
    # At least the attention projections must actually be tp-sharded.
    tp_used = [
        s for s in jax.tree.leaves(shardings)
        if any("tp" in (e if isinstance(e, tuple) else (e,)) for e in s.spec if e)
    ]
    assert len(tp_used) >= 8, "tp rules matched too few params"
    out = jax.jit(lambda p: module.apply({"params": p}, enc_ids, dec_in))(sharded)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_t5_trains_loss_decreases():
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import (
        T5Config,
        T5ForConditionalGeneration,
        shift_tokens_right,
        t5_cross_entropy_loss,
        t5_tp_rules,
    )
    from accelerate_tpu.utils import set_seed

    set_seed(0)
    cfg = T5Config.tiny(dtype=jnp.float32)
    module = T5ForConditionalGeneration(cfg)
    enc_ids, labels = _data(cfg, b=8)
    dec_in = shift_tokens_right(jnp.asarray(labels))
    acc = Accelerator()
    model = Model.from_flax(module, jax.random.key(0), enc_ids, np.asarray(dec_in),
                            tp_rules=t5_tp_rules(cfg.scan_layers))
    model, _ = acc.prepare(model, optax.adam(1e-3))

    def loss_fn(params, b):
        logits = module.apply({"params": params}, b["enc"], b["dec_in"])
        return t5_cross_entropy_loss(logits, b["labels"])

    step = acc.prepare_train_step(loss_fn)
    batch = {"enc": jnp.asarray(enc_ids), "dec_in": dec_in, "labels": jnp.asarray(labels)}
    state = acc.train_state
    losses = []
    for _ in range(15):
        state, m = step(state, batch)
        losses.append(float(np.asarray(m["loss"])))
    assert losses[-1] < losses[0] - 0.5, losses


def test_shift_tokens_right_replaces_ignore_index():
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.models import shift_tokens_right

    labels = jnp.asarray([[5, 6, -100, -100]])
    out = np.asarray(shift_tokens_right(labels, decoder_start_token_id=0, pad_token_id=0))
    assert out.tolist() == [[0, 5, 6, 0]]  # -100 never reaches the embedding


def test_t5_tp_rules_cover_unscanned_layers():
    """Unscanned layers are named block_{i}; the scan_layers=False table must
    match them (round-2 review finding: they silently stayed replicated)."""
    import re

    from accelerate_tpu.models import t5_tp_rules

    rules = t5_tp_rules(scan_layers=False)
    path = "encoder/block_3/self_attn/q/kernel"
    assert any(re.search(pat, path) for pat, _ in rules), "block_3 params must shard"
    ffn = "decoder/block_2/ffn/wi/kernel"
    assert any(re.search(pat, ffn) for pat, _ in rules)
