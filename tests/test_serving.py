"""Continuous-batching serving engine (serving.py): chunk-ladder math, slot
alloc/free/reuse, per-slot EOS retirement, chunked-prefill == one-shot cache
equivalence, decode parity with generate(), occupancy accounting, the
single-executable steady state, and the off-by-default contract. All
CPU-only, tier-1 fast."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Model, ServingConfig, ServingEngine, generate
from accelerate_tpu.generation import _llama_forward_cached, init_cache, init_slot_cache
from accelerate_tpu.serving import default_prefill_ladder, plan_chunks
from accelerate_tpu.utils import set_seed


@pytest.fixture(scope="module")
def llama():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    probe = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 8), dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), probe)
    return cfg, model


def _prompts(cfg, lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, (n,), dtype=np.int32) for n in lengths]


# ---------------------------------------------------------------------------
# Pure ladder math
# ---------------------------------------------------------------------------


def test_default_prefill_ladder():
    assert default_prefill_ladder(256, 16, 256) == [16, 32, 64, 128, 256]
    assert default_prefill_ladder(100, 16, 256) == [16, 32, 64, 100]
    assert default_prefill_ladder(8, 16, 256) == [8]  # capacity below min chunk


def test_plan_chunks_greedy_cover():
    ladder = [4, 8, 16]
    assert plan_chunks(16, ladder) == [(16, 16)]
    assert plan_chunks(21, ladder) == [(16, 16), (4, 4), (4, 1)]
    assert plan_chunks(3, ladder) == [(4, 3)]  # short prompt pads the min rung
    # valid counts always cover the prompt exactly
    for p in range(1, 40):
        chunks = plan_chunks(p, ladder)
        assert sum(v for _, v in chunks) == p
        assert all(v <= c and c in ladder for c, v in chunks)


def test_plan_chunks_rejects_empty():
    with pytest.raises(ValueError):
        plan_chunks(0, [8])
    with pytest.raises(ValueError):
        plan_chunks(5, [])


def test_init_slot_cache_per_slot_lengths(llama):
    cfg, _ = llama
    cache = init_slot_cache(cfg, 5, 32)
    assert cache.length.shape == (5,)
    assert cache.k.shape[1] == 5 and cache.k.shape[2] == 32


# ---------------------------------------------------------------------------
# Engine behavior
# ---------------------------------------------------------------------------


def test_engine_greedy_parity_with_generate(llama):
    """The acceptance bar: per-request engine output bit-equal to a batch-1
    generate() for the same prompt/budget, under mixed lengths, chunked
    prefill, and mid-flight slot reuse."""
    cfg, model = llama
    # 8 requests over 4 distinct (length, budget) combos: different token
    # CONTENT per request (mixed retirement order) while the reference
    # generate() calls reuse 4 compiled shapes instead of 8.
    prompts = _prompts(cfg, [3, 7, 12, 20, 3, 7, 12, 20])
    budgets = [6, 4, 8, 3, 6, 4, 8, 3]
    engine = ServingEngine(
        model, ServingConfig(n_slots=3, max_len=64, prefill_chunks=[4, 8])
    )
    outs = engine.run(prompts, max_new_tokens=budgets)
    for prompt, budget, got in zip(prompts, budgets, outs):
        want = np.asarray(generate(model, prompt[None], max_new_tokens=budget))[0]
        np.testing.assert_array_equal(got, want)
    stats = engine.stats()
    assert stats["requests_completed"] == len(prompts)
    assert stats["slot_reuses"] >= len(prompts) - 3  # slots recycled mid-flight


def test_per_slot_eos_retirement(llama):
    """Rows retire at their own EOS; the returned row pads with the pad id
    exactly like generate()."""
    cfg, model = llama
    prompts = _prompts(cfg, [5, 9, 5, 9], seed=9)
    # Use whatever greedy emits first for prompt 0 as the engine-wide EOS:
    # some requests hit it quickly, others run to budget.
    eos = int(np.asarray(generate(model, prompts[0][None], max_new_tokens=1))[0, -1])
    budget = 8
    engine = ServingEngine(
        model,
        ServingConfig(n_slots=2, max_len=64, prefill_chunks=[4, 8],
                      eos_token_id=eos),
    )
    outs = engine.run(prompts, max_new_tokens=budget)
    lengths = []
    for prompt, got in zip(prompts, outs):
        want = np.asarray(
            generate(model, prompt[None], max_new_tokens=budget, eos_token_id=eos)
        )[0]
        np.testing.assert_array_equal(got, want)
        new = got[len(prompt):]
        if eos in new:
            idx = int(np.argmax(new == eos))
            assert (new[idx:] == eos).all()  # post-EOS slots are pad(=eos)
            lengths.append(idx + 1)
        else:
            lengths.append(budget)
    assert len(set(lengths)) > 1  # rows really retired at different times


def test_chunked_prefill_matches_oneshot_prefill(llama):
    """Writing a prompt chunk-by-chunk into a slot must leave the same cache
    contents and next-token logits as one whole-prompt prefill."""
    cfg, model = llama
    prompt = _prompts(cfg, [13], seed=5)[0]
    engine = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8])
    )
    engine.submit(prompt, max_new_tokens=1)
    # Drive prefill only: tick until the request's first token exists.
    while engine._prefilling or engine._queue:
        engine.tick()
    slot_cache = engine._cache
    slot = 0  # first alloc takes slot 0
    one = init_cache(cfg, 1, 32)
    logits, one = _llama_forward_cached(cfg, model.params, prompt[None], one)
    p = len(prompt)
    np.testing.assert_allclose(
        np.asarray(slot_cache.k[:, slot, :p]), np.asarray(one.k[:, 0, :p]),
        rtol=1e-5, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(slot_cache.v[:, slot, :p]), np.asarray(one.v[:, 0, :p]),
        rtol=1e-5, atol=1e-5,
    )
    assert int(slot_cache.length[slot]) == p
    # The first sampled token came from the same logits row.
    want_tok = int(np.argmax(np.asarray(logits)[0]))
    res = engine.poll()
    assert len(res) == 1 and int(res[0]["tokens"][p]) == want_tok


def test_single_decode_executable_steady_state(llama):
    """Zero steady-state recompiles: ONE decode executable and at most
    len(ladder) prefill executables, no matter how requests churn."""
    cfg, model = llama
    engine = ServingEngine(
        model, ServingConfig(n_slots=3, max_len=64, prefill_chunks=[4, 8])
    )
    engine.run(_prompts(cfg, [3, 17, 6, 11, 9, 5]), max_new_tokens=5)
    # Second wave after a drain — still the same executables.
    engine.run(_prompts(cfg, [2, 13, 8], seed=11), max_new_tokens=7)
    stats = engine.stats()
    assert stats["decode_executables"] == 1
    assert stats["prefill_executables"] <= 2
    assert stats["steady_recompiles"] == 0


def test_occupancy_and_token_accounting(llama):
    cfg, model = llama
    budgets = [3, 6, 4, 5, 7, 2]
    engine = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=64, prefill_chunks=[8])
    )
    engine.run(_prompts(cfg, [4, 9, 5, 7, 3, 6], seed=2), max_new_tokens=budgets)
    stats = engine.stats()
    assert stats["requests_submitted"] == stats["requests_completed"] == 6
    assert stats["tokens_out"] == sum(budgets)  # no EOS configured
    assert stats["slot_allocs"] == 6 and stats["slot_reuses"] == 4
    assert 0 < stats["mean_occupancy"] <= 2
    assert stats["peak_occupancy"] <= 2
    assert stats["tokens_per_s"] and stats["tokens_per_s"] > 0
    assert stats["ttft_p50_s"] is not None and stats["ttft_p95_s"] >= stats["ttft_p50_s"]


def test_incremental_submit_poll(llama):
    """The front-end contract: submissions land mid-flight, poll() delivers
    each result exactly once."""
    cfg, model = llama
    prompts = _prompts(cfg, [6, 4, 6, 4], seed=7)
    engine = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=64, prefill_chunks=[4, 8])
    )
    first = [engine.submit(p, max_new_tokens=4) for p in prompts[:2]]
    for _ in range(3):
        engine.tick()
    late = [engine.submit(p, max_new_tokens=4) for p in prompts[2:]]
    seen = {}
    for _ in range(200):
        engine.tick()
        for res in engine.poll():
            assert res["id"] not in seen
            seen[res["id"]] = res
        if not engine.pending:
            break
    assert set(seen) == set(first + late)
    for rid, prompt in zip(first + late, prompts):
        want = np.asarray(generate(model, prompt[None], max_new_tokens=4))[0]
        np.testing.assert_array_equal(seen[rid]["tokens"], want)


def test_sampled_decoding_deterministic_per_request(llama):
    """temperature>0: one PRNG stream per request — identical keys replay
    identical outputs, and distinct keys may diverge."""
    cfg, model = llama
    prompts = _prompts(cfg, [5, 8], seed=13)
    keys = [jax.random.key(i) for i in (1, 2)]

    def run():
        engine = ServingEngine(
            model,
            ServingConfig(n_slots=2, max_len=64, prefill_chunks=[4, 8],
                          temperature=0.8, top_k=20),
        )
        return engine.run(prompts, max_new_tokens=6, rngs=keys)

    a, b = run(), run()
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_submit_validation(llama):
    cfg, model = llama
    engine = ServingEngine(model, ServingConfig(n_slots=2, max_len=16))
    with pytest.raises(ValueError, match="empty"):
        engine.submit(np.zeros((0,), np.int32))
    with pytest.raises(ValueError, match="capacity|max_len"):
        engine.submit(np.ones((12,), np.int32), max_new_tokens=8)
    with pytest.raises(ValueError, match=">= 1"):
        engine.submit(np.ones((4,), np.int32), max_new_tokens=0)


def test_encdec_rejected(llama):
    from accelerate_tpu.utils.dataclasses import ServingConfig as SC

    class FakeT5:
        pass

    FakeT5.__name__ = "T5ForConditionalGeneration"

    class FakeModel:
        module = FakeT5()
        params = {}

    with pytest.raises(ValueError, match="causal"):
        ServingEngine(FakeModel(), SC(n_slots=1, max_len=8))


def test_serving_config_validation():
    with pytest.raises(ValueError):
        ServingConfig(n_slots=0)
    with pytest.raises(ValueError):
        ServingConfig(prefill_chunks_per_tick=0)
    with pytest.raises(ValueError):
        ServingConfig(min_prefill_chunk=32, max_prefill_chunk=16)


# ---------------------------------------------------------------------------
# Integration: accelerator wiring, telemetry block, compile manager
# ---------------------------------------------------------------------------


def _accelerator(tmp_path, handlers):
    import optax  # noqa: F401

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    set_seed(0)
    return Accelerator(project_dir=str(tmp_path), kwargs_handlers=handlers)


def test_serving_off_by_default(tmp_path, llama):
    """No ServingConfig handler -> no serving config, and building an engine
    is an explicit error; the training path never constructs one."""
    cfg, model = llama
    acc = _accelerator(tmp_path, [])
    assert acc.serving_config is None
    with pytest.raises(ValueError, match="serving is off"):
        acc.build_serving_engine(model)


def test_accelerator_builds_wired_engine(tmp_path, llama):
    """ServingConfig in kwargs_handlers + CompileKwargs: the engine sources
    its prefill ladder from the compile manager's fixed seq buckets and
    pushes its summary into the telemetry recorder."""
    import json
    import os

    from accelerate_tpu.utils import CompileKwargs, TelemetryKwargs

    cfg, model = llama
    sc = ServingConfig(n_slots=2, max_len=64)
    acc = _accelerator(
        tmp_path,
        [sc, CompileKwargs(buckets="fixed", seq_buckets=[4, 8], warmup="off"),
         TelemetryKwargs(straggler_probe_every=0, log_every=0)],
    )
    assert acc.serving_config is sc
    engine = acc.build_serving_engine(model)
    assert engine.ladder == [4, 8]
    engine.run(_prompts(cfg, [5, 3, 9], seed=4), max_new_tokens=3)
    summary = acc.telemetry.summary()
    assert summary["serving"]["requests_completed"] == 3
    assert summary["serving"]["steady_recompiles"] == 0
    acc.telemetry.close()
    report = os.path.join(str(tmp_path), "telemetry", "rank_0.jsonl")
    events = [json.loads(l) for l in open(report)]
    kinds = {e["event"] for e in events}
    assert "serving_request_done" in kinds and "serving_summary" in kinds


def test_serving_summary_acceptance_rate_ema(tmp_path):
    """record_serving keeps a cross-push EMA of the speculation acceptance
    rate: first push seeds it, later pushes blend 0.9/0.1, pushes with no
    rate (speculation off / nothing drafted yet) leave it untouched."""
    from accelerate_tpu.utils import TelemetryKwargs

    acc = _accelerator(
        tmp_path, [TelemetryKwargs(straggler_probe_every=0, log_every=0)])
    tele = acc.telemetry
    spec = lambda rate: {"speculation": {  # noqa: E731
        "k": 4, "ngram": 16, "drafted": 100, "accepted": 50,
        "acceptance_rate": rate, "tokens_per_tick": 1.0, "verify_time_s": 0.1}}
    tele.record_serving(spec(None))
    assert tele.summary()["serving"]["speculation"]["acceptance_rate_ema"] is None
    tele.record_serving(spec(0.5))
    assert tele.summary()["serving"]["speculation"]["acceptance_rate_ema"] == 0.5
    tele.record_serving(spec(1.0))
    got = tele.summary()["serving"]["speculation"]["acceptance_rate_ema"]
    assert got == pytest.approx(0.9 * 0.5 + 0.1 * 1.0)
    tele.record_serving(spec(None))  # no new rate: EMA survives unchanged
    assert (tele.summary()["serving"]["speculation"]["acceptance_rate_ema"]
            == pytest.approx(0.55))
    tele.close()


def test_generation_signatures_reach_manifest_and_warm(tmp_path, llama):
    """generate(compile_manager=...) buckets the prompt up the seq ladder,
    records the signature, and warmup_generation() replays it into the
    compiled-loop cache on a fresh process (simulated by clearing it)."""
    from accelerate_tpu import generation as G
    from accelerate_tpu.utils import CompileKwargs

    cfg, model = llama
    acc = _accelerator(
        tmp_path,
        [CompileKwargs(buckets="fixed", seq_buckets=[8, 16], warmup="off")],
    )
    cm = acc.compile_manager
    prompts = _prompts(cfg, [5, 7, 3], seed=6)
    plain = [
        np.asarray(generate(model, p[None], max_new_tokens=4))[0] for p in prompts
    ]
    G.clear_generation_cache()
    outs = [
        np.asarray(
            generate(model, p[None], max_new_tokens=4, compile_manager=cm)
        )[0]
        for p in prompts
    ]
    # Bucketing preserves outputs bit-for-bit (left pads are masked out)...
    for got, want in zip(outs, plain):
        np.testing.assert_array_equal(got, want)
    # ...and all three lengths shared ONE bucketed signature.
    gen_entries = [
        e for e in cm.manifest.entries
        if (e.get("spec") or {}).get("kind") == "generation"
    ]
    assert len(gen_entries) == 1
    assert gen_entries[0]["spec"]["prompt_len"] == 8
    # Restart: a cold loop cache warms from the manifest before any request.
    G.clear_generation_cache()
    assert cm.warmup_generation(model) == 1
    assert len(G._GEN_LOOP_CACHE) == 1
    # Train-step warmup must ignore generation entries (they need a model).
    pending_specs = [e["spec"].get("kind") for e in cm.manifest.entries]
    assert "generation" in pending_specs  # present in the manifest...
    from accelerate_tpu.compile_manager import spec_array_dims

    dims = {"batch": set(), "seq": set()}
    for e in cm.manifest.entries:
        spec_array_dims(e["spec"], dims)
    assert dims == {"batch": set(), "seq": set()}  # ...but never warms a step


# ---------------------------------------------------------------------------
# Robustness surface (the full fault matrix lives in tests/test_chaos.py)
# ---------------------------------------------------------------------------


def test_poll_rows_carry_explicit_status(llama):
    """Every poll() row now names its terminal state; the fault-free path is
    all `ok` and the faults stats block stays zeroed."""
    from accelerate_tpu.serving import REQUEST_STATUSES

    cfg, model = llama
    eng = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=64, prefill_chunks=[4, 8])
    )
    ids = [eng.submit(p, max_new_tokens=3) for p in _prompts(cfg, [5, 9])]
    rows = {}
    while eng.pending:
        eng.tick()
        for r in eng.poll():
            rows[r["id"]] = r
    assert set(rows) == set(ids)
    for r in rows.values():
        assert r["status"] == "ok"
        assert r["status"] in REQUEST_STATUSES
    f = eng.stats()["faults"]
    assert f["injected"] == 0 and f["sheds"] == 0 and f["timeouts"] == 0


def test_submit_deadline_validation(llama):
    cfg, model = llama
    eng = ServingEngine(
        model, ServingConfig(n_slots=1, max_len=64, prefill_chunks=[4, 8])
    )
    with pytest.raises(ValueError):
        eng.submit(_prompts(cfg, [5])[0], max_new_tokens=2, deadline_s=0.0)
    with pytest.raises(ValueError):
        eng.submit(_prompts(cfg, [5])[0], max_new_tokens=2, deadline_s=-1.0)


def test_serving_config_robustness_defaults():
    """The robustness knobs are off by default — no queue cap, no deadline,
    reject-on-overload (inert without a cap), bounded retries."""
    c = ServingConfig()
    assert c.max_queue_depth is None
    assert c.deadline_s is None
    assert c.overload_policy == "reject"
    assert c.max_retries == 2
    assert c.max_idle_ticks == 100
