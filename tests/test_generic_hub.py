"""Generic (declarative-rules) HF ingestion: architectures OUTSIDE the
hand-written family table load and logit-match via ArchSpec rules only.

This is the arbitrary-model on-ramp test: none of starcoder2 / stablelm /
internlm2 has a `*_params_from_hf` function in models/hub.py — they go
through models/generic_hub.py's rule engine (reference counterpart:
utils/modeling.py:1805-2065 load_checkpoint_in_model, which lands weights in
the user's module by name).
"""

import numpy as np
import pytest
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from accelerate_tpu.models import load_pretrained, model_from_pretrained
from accelerate_tpu.models.generic_hub import (
    ArchSpec,
    Const,
    WeightRule,
    _llama_name_rules,
    _LLAMA_STYLE_CONFIG,
    register_arch_spec,
)


def _logits(hf_model, ids):
    hf_model.eval()
    with torch.no_grad():
        return hf_model(torch.from_numpy(np.asarray(ids))).logits.numpy()


def _ids(vocab, shape, seed=0):
    return np.random.default_rng(seed).integers(0, vocab, shape).astype(np.int32)


def test_starcoder2_logit_parity():
    """LayerNorm + plain-gelu MLP + biases everywhere + tied embeddings —
    four chassis knobs away from Llama, zero new mapping code."""
    hf_cfg = transformers.Starcoder2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=None, use_bias=True,
    )
    torch.manual_seed(0)
    hf = transformers.Starcoder2ForCausalLM(hf_cfg)
    ids = _ids(128, (2, 12))
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(ours(ids)), _logits(hf, ids), rtol=2e-4, atol=2e-4
    )


def test_stablelm_logit_parity():
    """Partial rotary (0.25 of head_dim) + LayerNorm-with-bias + gated silu."""
    hf_cfg = transformers.StableLmConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, partial_rotary_factor=0.25,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = transformers.StableLmForCausalLM(hf_cfg)
    ids = _ids(128, (2, 12))
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(ours(ids)), _logits(hf, ids), rtol=2e-4, atol=2e-4
    )


def test_granite_logit_and_generate_parity():
    """Granite = Llama + four scaling constants (embedding/residual/attention
    multipliers, logits divisor) — pure chassis-knob mapping, and the decode
    plan honors the same constants token-for-token."""
    from accelerate_tpu import generate

    hf_cfg = transformers.GraniteConfig(
        vocab_size=96, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        embedding_multiplier=3.0, residual_multiplier=0.5,
        attention_multiplier=0.08, logits_scaling=2.0,
    )
    torch.manual_seed(2)
    hf = transformers.GraniteForCausalLM(hf_cfg)
    hf.eval()
    ids = _ids(96, (2, 10), seed=11)
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(ours(ids)), _logits(hf, ids), rtol=2e-4, atol=2e-4
    )
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(ids[:1].astype(np.int64)), max_new_tokens=5,
            do_sample=False, pad_token_id=0,
        ).numpy()
    got = generate(ours, ids[:1], max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(got), want.astype(np.int32))


def test_granite_with_biases_logit_parity():
    """Biased Granite checkpoints: q/k/v + o_proj + MLP biases all claimed
    and loaded (the bias rules are inert for unbiased checkpoints)."""
    hf_cfg = transformers.GraniteConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=False,
        attention_bias=True, mlp_bias=True,
        # Real Granite checkpoints carry ~1/sqrt(d)-scale multipliers; the
        # config default of 1.0 (unscaled scores) makes the softmax so
        # peaked that fp32 summation-order noise dominates a parity check.
        attention_multiplier=0.25,
    )
    torch.manual_seed(3)
    hf = transformers.GraniteForCausalLM(hf_cfg)
    ids = _ids(64, (2, 8), seed=12)
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(ours(ids)), _logits(hf, ids), rtol=3e-4, atol=3e-4
    )


def test_stablelm_parallel_residual_refuses():
    """A shape-compatible checkpoint with semantics the chassis doesn't
    compute must refuse to load, not load wrong."""
    hf_cfg = transformers.StableLmConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        use_parallel_residual=True,
    )
    torch.manual_seed(0)
    hf = transformers.StableLmForCausalLM(hf_cfg)
    with pytest.raises(ValueError, match="parallel_residual"):
        load_pretrained(hf, dtype=jnp.float32)


def _fuse_qkv_grouped(sd, n_layers, nh, nkv, d):
    """Llama-name sd → InternLM2-style grouped fused wqkv."""
    ratio = nh // nkv
    out = {}
    for key, v in sd.items():
        out[key] = v
    for i in range(n_layers):
        p = f"model.layers.{i}.self_attn."
        q = out.pop(p + "q_proj.weight")
        k = out.pop(p + "k_proj.weight")
        v = out.pop(p + "v_proj.weight")
        h = q.shape[1]
        groups = []
        for g in range(nkv):
            groups.append(q[g * ratio * d:(g + 1) * ratio * d])
            groups.append(k[g * d:(g + 1) * d])
            groups.append(v[g * d:(g + 1) * d])
        out[f"model.layers.{i}.attention.wqkv.weight"] = np.concatenate(groups, 0)
    return out


def test_internlm2_fused_qkv_split():
    """Renames + KV-grouped fused wqkv: build an internlm2-named checkpoint
    from a native Llama export and check exact logit parity after generic
    ingestion (exercises the qkv_split op end to end, with GQA)."""
    import jax
    from accelerate_tpu import Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.models.hub import llama_params_to_hf

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, dtype=jnp.float32,
    )
    module = LlamaForCausalLM(cfg)
    ids = _ids(128, (2, 10))
    native = Model.from_flax(module, jax.random.key(0), ids)
    sd = llama_params_to_hf(cfg, native.params)

    renames = {
        "model.embed_tokens.weight": "model.tok_embeddings.weight",
        "lm_head.weight": "output.weight",
    }
    per_layer = {
        "self_attn.o_proj.weight": "attention.wo.weight",
        "mlp.gate_proj.weight": "feed_forward.w1.weight",
        "mlp.up_proj.weight": "feed_forward.w3.weight",
        "mlp.down_proj.weight": "feed_forward.w2.weight",
        "input_layernorm.weight": "attention_norm.weight",
        "post_attention_layernorm.weight": "ffn_norm.weight",
    }
    fused = _fuse_qkv_grouped(
        sd, cfg.num_hidden_layers, cfg.num_attention_heads,
        cfg.num_key_value_heads, cfg.head_dim,
    )
    ilm_sd = {}
    for key, v in fused.items():
        new = renames.get(key, key)
        for old, repl in per_layer.items():
            if key.endswith(old):
                new = key[: -len(old)] + repl
        ilm_sd[new] = np.asarray(v)

    hf_cfg = {
        "model_type": "internlm2", "vocab_size": 128, "hidden_size": 64,
        "intermediate_size": 96, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2,
        "max_position_embeddings": 64, "rope_theta": 10000.0,
        "tie_word_embeddings": False,
    }
    ours = model_from_pretrained((hf_cfg, ilm_sd), dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(ours(ids)), np.asarray(native(ids)), rtol=1e-5, atol=1e-5
    )


def test_register_arch_spec_user_extension():
    """The public on-ramp: a user registers a spec for an arbitrary
    model_type (here: llama tensors under a renamed prefix) and the
    checkpoint loads with no framework change."""
    import jax
    from accelerate_tpu import Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.models.hub import llama_params_to_hf

    cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, dtype=jnp.float32,
    )
    module = LlamaForCausalLM(cfg)
    ids = _ids(64, (1, 8), seed=3)
    native = Model.from_flax(module, jax.random.key(1), ids)
    sd = {
        k.replace("model.", "backbone.", 1): v
        for k, v in llama_params_to_hf(cfg, native.params).items()
    }

    B = r"backbone\.layers\.(?P<i>\d+)\."
    register_arch_spec("examplelm", ArchSpec(
        target="llama",
        config_map=_LLAMA_STYLE_CONFIG,
        rules=[
            WeightRule(r"backbone\.embed_tokens\.weight", "model/embed_tokens/embedding"),
            WeightRule(r"backbone\.norm\.weight", "model/norm/weight"),
            WeightRule(r"lm_head\.weight", "lm_head/kernel", op="linear"),
            WeightRule(B + r"self_attn\.q_proj\.weight", "self_attn/q_proj/kernel",
                       op="attn_in", heads="q"),
            WeightRule(B + r"self_attn\.k_proj\.weight", "self_attn/k_proj/kernel",
                       op="attn_in", heads="kv"),
            WeightRule(B + r"self_attn\.v_proj\.weight", "self_attn/v_proj/kernel",
                       op="attn_in", heads="kv"),
            WeightRule(B + r"self_attn\.o_proj\.weight", "self_attn/o_proj/kernel",
                       op="attn_out"),
            WeightRule(B + r"mlp\.gate_proj\.weight", "mlp/gate_proj/kernel", op="linear"),
            WeightRule(B + r"mlp\.up_proj\.weight", "mlp/up_proj/kernel", op="linear"),
            WeightRule(B + r"mlp\.down_proj\.weight", "mlp/down_proj/kernel", op="linear"),
            WeightRule(B + r"input_layernorm\.weight", "input_layernorm/weight"),
            WeightRule(B + r"post_attention_layernorm\.weight",
                       "post_attention_layernorm/weight"),
        ],
    ))
    hf_cfg = {
        "model_type": "examplelm", "vocab_size": 64, "hidden_size": 32,
        "intermediate_size": 48, "num_hidden_layers": 2,
        "num_attention_heads": 2, "num_key_value_heads": 2,
        "max_position_embeddings": 32,
    }
    ours = model_from_pretrained((hf_cfg, sd), dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(ours(ids)), np.asarray(native(ids)), rtol=1e-5, atol=1e-5
    )


def test_starcoder2_generates_like_transformers():
    """Ingested chassis variants are first-class for generation too: the
    KV-cache decode plan honors layernorm / plain-gelu MLP / biases."""
    from accelerate_tpu import generate

    hf_cfg = transformers.Starcoder2Config(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=None, use_bias=True,
    )
    torch.manual_seed(1)
    hf = transformers.Starcoder2ForCausalLM(hf_cfg)
    hf.eval()
    ids = np.random.default_rng(5).integers(0, 96, (1, 6)).astype(np.int64)
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(ids), max_new_tokens=5, do_sample=False, pad_token_id=0
        ).numpy()
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    got = generate(ours, ids.astype(np.int32), max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(got), want.astype(np.int32))


def test_stablelm_generates_like_transformers():
    """Partial rotary (0.25 head_dim) through the decode plan."""
    from accelerate_tpu import generate

    hf_cfg = transformers.StableLmConfig(
        vocab_size=96, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, partial_rotary_factor=0.25,
        tie_word_embeddings=False,
    )
    torch.manual_seed(1)
    hf = transformers.StableLmForCausalLM(hf_cfg)
    hf.eval()
    ids = np.random.default_rng(6).integers(0, 96, (1, 6)).astype(np.int64)
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(ids), max_new_tokens=5, do_sample=False, pad_token_id=0
        ).numpy()
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    got = generate(ours, ids.astype(np.int32), max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(got), want.astype(np.int32))


def test_starcoder2_sliding_window_refuses():
    """sliding_window checkpoints compute differently beyond the window —
    the spec must refuse, not load shape-compatibly-but-wrong."""
    hf_cfg = transformers.Starcoder2Config(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        sliding_window=4096, use_bias=True,
    )
    torch.manual_seed(0)
    hf = transformers.Starcoder2ForCausalLM(hf_cfg)
    with pytest.raises(ValueError, match="sliding_window"):
        load_pretrained(hf, dtype=jnp.float32)


def test_layer_count_mismatch_raises():
    hf_cfg = transformers.Starcoder2Config(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        sliding_window=None, use_bias=True,
    )
    torch.manual_seed(0)
    hf = transformers.Starcoder2ForCausalLM(hf_cfg)
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    bad_cfg = hf_cfg.to_dict()
    bad_cfg["num_hidden_layers"] = 1  # sd still has model.layers.1.*
    with pytest.raises(ValueError, match="num_hidden_layers=1"):
        load_pretrained((bad_cfg, sd), dtype=jnp.float32)


def test_ingested_arch_trains_under_fsdp():
    """The full switch-over loop for an architecture with no hand-written
    family: ingest a StarCoder2 checkpoint via rules, prepare under an
    8-way FSDP mesh, and take real train steps (layernorm biases and plain
    MLP must survive the sharding planner; loss must fall)."""
    import jax
    import optax

    from accelerate_tpu import Accelerator, Model, ParallelismConfig
    from accelerate_tpu.models import cross_entropy_loss
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils import set_seed

    AcceleratorState._reset_state()
    GradientState._reset_state()
    set_seed(0)
    hf_cfg = transformers.Starcoder2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=None, use_bias=True,
    )
    torch.manual_seed(0)
    hf = transformers.Starcoder2ForCausalLM(hf_cfg)
    cfg, params, module_cls = load_pretrained(hf, dtype=jnp.float32)
    module = module_cls(cfg)

    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
    model = Model(module=module, params=params)
    model, _ = acc.prepare(model, optax.adamw(3e-3))

    def loss_fn(p, batch):
        return cross_entropy_loss(module.apply({"params": p}, batch["x"]), batch["y"])

    step = acc.prepare_train_step(loss_fn)
    ids = _ids(128, (8, 17), seed=9)
    batch = {"x": jnp.asarray(ids[:, :-1]), "y": jnp.asarray(ids[:, 1:])}
    state, losses = acc.train_state, []
    for _ in range(6):
        state, m = step(state, batch)
        losses.append(float(np.asarray(m["loss"])))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] - 0.3, losses


def test_unmatched_tensor_raises():
    hf_cfg = transformers.Starcoder2Config(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        sliding_window=None, use_bias=True,
    )
    torch.manual_seed(0)
    hf = transformers.Starcoder2ForCausalLM(hf_cfg)
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    sd["model.layers.0.mystery.weight"] = np.zeros((4, 4), np.float32)
    with pytest.raises(ValueError, match="mystery"):
        load_pretrained((hf_cfg.to_dict(), sd), dtype=jnp.float32)


def test_unknown_family_error_lists_generic_specs():
    with pytest.raises(ValueError, match="starcoder2"):
        load_pretrained(({"model_type": "definitely_not_a_model"}, {}))
