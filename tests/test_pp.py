"""Pipeline parallelism: GPipe schedule correctness on the virtual CPU mesh.

Mirrors the reference's pippy/Megatron coverage (SURVEY.md §2.3 PP row) with
exact-equality checks against the unpipelined forward — possible here because
the pipeline is a compiled transformation of the same math, not a separate
runtime.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, Model, ParallelismConfig
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, cross_entropy_loss
from accelerate_tpu.parallel import llama_pipeline_forward, pipeline_apply
from accelerate_tpu.utils import set_seed


def _mesh(pp, rest=()):
    cfg = ParallelismConfig(pp_size=pp, **dict(rest))
    return cfg, cfg.build_mesh()


def test_pipeline_apply_matches_serial():
    """A stack of affine layers through the pipeline == serial scan."""
    L, B, D = 8, 16, 32
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(L, D, D), scale=0.1), jnp.float32)
    b = jnp.asarray(rng.normal(size=(L, D), scale=0.1), jnp.float32)
    x = jnp.asarray(rng.normal(size=(B, D)), jnp.float32)

    def stage_fn(local, h):
        def body(carry, lp):
            wi, bi = lp
            return jnp.tanh(carry @ wi + bi), None

        h, _ = jax.lax.scan(body, h, local)
        return h

    serial = stage_fn((w, b), x)
    _, mesh = _mesh(4)
    piped = pipeline_apply(stage_fn, (w, b), x, mesh=mesh, n_microbatches=4)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(serial), rtol=1e-6, atol=1e-6)


def test_interleaved_pipeline_matches_serial():
    """Megatron-style interleaved schedule (virtual_stages=V): forward parity
    with the serial stack for V in {2, 4}, and V=1 degenerates to GPipe."""
    L, B, D = 16, 16, 32
    rng = np.random.default_rng(3)
    w = jnp.asarray(rng.normal(size=(L, D, D), scale=0.1), jnp.float32)
    x = jnp.asarray(rng.normal(size=(B, D)), jnp.float32)

    def stage_fn(local, h):
        def body(carry, wi):
            return jnp.tanh(carry @ wi), None

        h, _ = jax.lax.scan(body, h, local)
        return h

    serial = stage_fn(w, x)
    _, mesh = _mesh(4)
    for v in (1, 2, 4):
        piped = pipeline_apply(
            stage_fn, w, x, mesh=mesh, n_microbatches=4, virtual_stages=v
        )
        np.testing.assert_allclose(
            np.asarray(piped), np.asarray(serial), rtol=1e-6, atol=1e-6,
            err_msg=f"virtual_stages={v}",
        )


def test_interleaved_pipeline_grads_match_serial():
    """Backward through the interleaved schedule: the device-major layer
    permutation's transpose must scatter gradients back to the caller's
    layout exactly."""
    L, B, D = 8, 8, 16
    rng = np.random.default_rng(4)
    w = jnp.asarray(rng.normal(size=(L, D, D), scale=0.1), jnp.float32)
    x = jnp.asarray(rng.normal(size=(B, D)), jnp.float32)

    def stage_fn(local, h):
        def body(carry, wi):
            return jnp.tanh(carry @ wi), None

        h, _ = jax.lax.scan(body, h, local)
        return h

    _, mesh = _mesh(4)

    def serial_loss(w):
        return jnp.sum(stage_fn(w, x) ** 2)

    def piped_loss(w):
        return jnp.sum(
            pipeline_apply(stage_fn, w, x, mesh=mesh, n_microbatches=4,
                           virtual_stages=2) ** 2
        )

    g_serial = jax.grad(serial_loss)(w)
    g_piped = jax.grad(piped_loss)(w)
    np.testing.assert_allclose(
        np.asarray(g_piped), np.asarray(g_serial), rtol=1e-5, atol=1e-6
    )


def test_interleaved_default_from_parallelism_config():
    """With no explicit virtual_stages, pipeline_apply reads
    ParallelismConfig.pp_virtual_stages off the live AcceleratorState
    (and the knob round-trips through the launcher's env encoding)."""
    from accelerate_tpu.state import AcceleratorState

    from accelerate_tpu.utils import patch_environment

    pc = ParallelismConfig(pp_size=4, dp_shard_size=2, pp_virtual_stages=2)
    assert pc.to_env()["PARALLELISM_CONFIG_PP_VIRTUAL_STAGES"] == "2"
    with patch_environment(**pc.to_env()):
        assert ParallelismConfig.from_env().pp_virtual_stages == 2
    L, B, D = 16, 16, 32
    rng = np.random.default_rng(5)
    w = jnp.asarray(rng.normal(size=(L, D, D), scale=0.1), jnp.float32)
    x = jnp.asarray(rng.normal(size=(B, D)), jnp.float32)

    def stage_fn(local, h):
        def body(carry, wi):
            return jnp.tanh(carry @ wi), None

        h, _ = jax.lax.scan(body, h, local)
        return h

    state = AcceleratorState(parallelism_config=pc)
    piped = pipeline_apply(stage_fn, w, x, mesh=state.mesh, n_microbatches=4)
    np.testing.assert_allclose(
        np.asarray(piped), np.asarray(stage_fn(w, x)), rtol=1e-6, atol=1e-6
    )
    # Prove the interleaved path actually engaged: its m == pp requirement
    # fires only when pp_virtual_stages was consumed (GPipe accepts m=8).
    # (Singleton cleanup is the autouse conftest fixture's job.)
    with pytest.raises(ValueError, match="n_microbatches == pp"):
        pipeline_apply(stage_fn, w, x, mesh=state.mesh, n_microbatches=8)


def test_interleaved_pipeline_validation():
    _, mesh = _mesh(4)
    w = jnp.zeros((16, 8, 8), jnp.float32)
    x = jnp.zeros((16, 8), jnp.float32)

    def stage_fn(local, h):
        return h

    with pytest.raises(ValueError, match="n_microbatches == pp"):
        pipeline_apply(stage_fn, w, x, mesh=mesh, n_microbatches=8, virtual_stages=2)
    with pytest.raises(ValueError, match="divisible by pp"):
        pipeline_apply(
            stage_fn, jnp.zeros((10, 8, 8)), x, mesh=mesh, n_microbatches=4,
            virtual_stages=2,
        )


def test_pipeline_apply_grads_match_serial():
    L, B, D = 4, 8, 16
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(L, D, D), scale=0.1), jnp.float32)
    x = jnp.asarray(rng.normal(size=(B, D)), jnp.float32)

    def stage_fn(local, h):
        def body(carry, wi):
            return jnp.tanh(carry @ wi), None

        h, _ = jax.lax.scan(body, h, local)
        return h

    _, mesh = _mesh(4)

    def serial_loss(w):
        return jnp.sum(stage_fn(w, x) ** 2)

    def piped_loss(w):
        return jnp.sum(pipeline_apply(stage_fn, w, x, mesh=mesh, n_microbatches=2) ** 2)

    g_serial = jax.grad(serial_loss)(w)
    g_piped = jax.grad(piped_loss)(w)
    np.testing.assert_allclose(np.asarray(g_piped), np.asarray(g_serial), rtol=1e-5, atol=1e-5)


def test_llama_pipeline_forward_matches_apply():
    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, num_hidden_layers=4)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(8, 16), dtype=np.int32))
    params = module.init(jax.random.key(0), ids)["params"]

    ref = module.apply({"params": params}, ids)
    _, mesh = _mesh(2)
    piped = llama_pipeline_forward(cfg, params, ids, mesh=mesh, n_microbatches=4)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_pp_composes_with_fsdp_tp_train_step():
    """pp=2 × dp_shard=2 × tp=2 on the 8-device mesh: full train step runs,
    loss finite, stacked block params sharded over pp on the layer dim."""
    set_seed(0)
    pc = ParallelismConfig(pp_size=2, dp_shard_size=2, tp_size=2)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, num_hidden_layers=4)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(3)
    ids = rng.integers(0, cfg.vocab_size, size=(8, 16), dtype=np.int32)

    from accelerate_tpu.models import llama_tp_rules
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin

    acc = Accelerator(
        parallelism_config=pc,
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size_to_shard=0),
    )
    model = Model.from_flax(module, jax.random.key(0), ids, tp_rules=llama_tp_rules(True))
    model, _ = acc.prepare(model, optax.adamw(1e-3))

    block_sharding = jax.tree.leaves(
        acc.state_shardings.params["model"]["layers"]["block"],
        is_leaf=lambda s: hasattr(s, "spec"),
    )
    assert any(s.spec and s.spec[0] == "pp" for s in block_sharding), (
        "stacked block params should shard layer dim over pp"
    )

    def loss_fn(params, batch):
        logits = llama_pipeline_forward(cfg, params, batch["x"], mesh=acc.mesh, n_microbatches=4)
        return cross_entropy_loss(logits, batch["y"])

    step = acc.prepare_train_step(loss_fn, max_grad_norm=1.0)
    batch = {"x": jnp.asarray(ids[:, :-1]), "y": jnp.asarray(ids[:, 1:])}
    state0 = acc.train_state
    l0 = np.asarray(jax.tree.leaves(state0.params)[0])  # copy before donation
    state1, metrics = step(state0, batch)
    assert np.isfinite(float(metrics["loss"]))
    # Params actually changed.
    l1 = np.asarray(jax.tree.leaves(state1.params)[0])
    assert not np.allclose(l0, l1)


def test_pipeline_pp1_fallback():
    """pp=1 mesh: pipeline_apply degrades to the plain serial stage_fn."""
    _, mesh = _mesh(1, rest={"dp_shard_size": 8})
    w = jnp.ones((4, 8, 8)) * 0.1
    x = jnp.ones((4, 8))

    def stage_fn(local, h):
        def body(c, wi):
            return c @ wi, None

        h, _ = jax.lax.scan(body, h, local)
        return h

    out = pipeline_apply(stage_fn, w, x, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(stage_fn(w, x)), rtol=1e-6)
