"""Big-model inference: meta init, device maps, offload, streamed forward.

Covers the reference's test_big_modeling.py / test_modeling_utils.py surface
(reference: tests/test_big_modeling.py, tests/test_modeling_utils.py) on the
virtual CPU mesh.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Model, cpu_offload, disk_offload, dispatch_model, init_empty_weights, load_checkpoint_and_dispatch
from accelerate_tpu.utils import (
    OffloadedWeightsLoader,
    compute_abstract_params,
    compute_module_sizes,
    get_max_memory,
    infer_auto_device_map,
    load_offload_index,
    named_parameter_shapes,
    offload_state_dict,
)
from accelerate_tpu.utils.other import flatten_state_dict, save_sharded_safetensors


def _tiny_llama(scan_layers=False):
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny(dtype=jnp.float32, scan_layers=scan_layers)
    module = LlamaForCausalLM(cfg)
    ids = np.arange(2 * 16, dtype=np.int32).reshape(2, 16) % cfg.vocab_size
    return cfg, module, ids


def test_abstract_init_allocates_nothing():
    cfg, module, ids = _tiny_llama()
    abstract = init_empty_weights(module, ids)
    shapes = named_parameter_shapes(abstract)
    assert all(isinstance(s, jax.ShapeDtypeStruct) for s in shapes.values())
    assert "model/layers_0/self_attn/q_proj/kernel" in shapes
    sizes = compute_module_sizes(abstract)
    n_params = sum(int(np.prod(s.shape)) for s in shapes.values())
    assert sizes[""] == n_params * 4  # fp32


def test_infer_auto_device_map_splits_across_budgets():
    cfg, module, ids = _tiny_llama()
    abstract = compute_abstract_params(module, ids)
    sizes = compute_module_sizes(abstract)
    # Budget sized so device 0 cannot hold everything → spill to 1, then cpu.
    per_dev = sizes[""] // 3
    dm = infer_auto_device_map(abstract, {0: per_dev, 1: per_dev, "cpu": sizes[""]})
    placements = set()
    for v in dm.values():
        placements.add(v if isinstance(v, str) else "device")
    assert "device" in placements and "cpu" in placements
    # Longest-prefix coverage is total and non-overlapping.
    from accelerate_tpu.utils import check_device_map

    check_device_map(abstract, dm)


def test_device_map_respects_no_split():
    cfg, module, ids = _tiny_llama()
    abstract = compute_abstract_params(module, ids)
    sizes = compute_module_sizes(abstract)
    layer = sizes["model/layers_0"]
    # Make budgets too small to hold a full block → blocks must go whole to cpu.
    dm = infer_auto_device_map(
        abstract, {0: layer // 2, "cpu": sizes[""] * 2}, no_split_modules=[r"layers_\d+"]
    )
    for name, p in dm.items():
        if "layers_" in name:
            assert p == "cpu"
            assert name.count("/") <= 1  # never split below the block


def test_dispatch_and_cpu_offload_match_full_forward():
    cfg, module, ids = _tiny_llama()
    model = Model.from_flax(module, jax.random.key(0), ids)
    expected = np.asarray(model(ids))

    off = cpu_offload(model)
    got = np.asarray(off(ids))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)
    assert off.hbm_resident_bytes() == 0


def test_disk_offload_roundtrip(tmp_path):
    cfg, module, ids = _tiny_llama()
    model = Model.from_flax(module, jax.random.key(0), ids)
    expected = np.asarray(model(ids))
    off = disk_offload(model, str(tmp_path / "offload"))
    got = np.asarray(off(ids))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)
    index = load_offload_index(str(tmp_path / "offload"))
    assert any("q_proj" in k for k in index)


@pytest.mark.parametrize("scan_layers", [False, True])
def test_load_checkpoint_and_dispatch_streams_layers(tmp_path, scan_layers):
    cfg, module, ids = _tiny_llama(scan_layers=scan_layers)
    model = Model.from_flax(module, jax.random.key(0), ids)
    expected = np.asarray(model(ids))

    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    flat = {k: np.asarray(v) for k, v in flatten_state_dict(model.params).items()}
    save_sharded_safetensors(flat, ckpt, max_shard_size=50_000)  # force multiple shards
    assert len([f for f in os.listdir(ckpt) if f.endswith(".safetensors")]) > 1

    # Mixed map: embeddings on chip, every block on host, head on chip.
    abstract = compute_abstract_params(module, ids)
    dm = {k: "cpu" for k in abstract["model"]}
    dm = {f"model/{k}": v for k, v in dm.items()}
    dm["model/embed_tokens"] = 0
    dm["model/norm"] = 0
    dm["lm_head"] = 0
    off = load_checkpoint_and_dispatch(module, ckpt, ids, device_map=dm)
    got = np.asarray(off(ids))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)
    # Blocks are host-resident: HBM holds only embed/norm/head.
    sizes = compute_module_sizes(abstract)
    resident = off.hbm_resident_bytes()
    assert resident < sizes[""]
    assert resident >= sizes["model/embed_tokens"]


def test_load_checkpoint_and_dispatch_auto(tmp_path):
    cfg, module, ids = _tiny_llama()
    model = Model.from_flax(module, jax.random.key(0), ids)
    expected = np.asarray(model(ids))
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    flat = {k: np.asarray(v) for k, v in flatten_state_dict(model.params).items()}
    save_sharded_safetensors(flat, ckpt)
    off = load_checkpoint_and_dispatch(module, ckpt, ids, device_map="auto")
    np.testing.assert_allclose(np.asarray(off(ids)), expected, rtol=1e-5, atol=1e-5)


def test_offloaded_weights_loader(tmp_path):
    sd = {"a/w": np.arange(6, dtype=np.float32).reshape(2, 3), "b/w": np.ones((4,), np.float16)}
    offload_state_dict(str(tmp_path), sd)
    loader = OffloadedWeightsLoader(save_folder=str(tmp_path))
    assert sorted(loader) == ["a/w", "b/w"]
    np.testing.assert_array_equal(np.asarray(loader["a/w"]), sd["a/w"])
    assert np.asarray(loader["b/w"]).dtype == np.float16


def test_get_max_memory_budget_keys():
    mm = get_max_memory()
    assert "cpu" in mm and 0 in mm
    mm2 = get_max_memory({0: "1GiB", "cpu": 123})
    assert mm2[0] == 1024**3 and mm2["cpu"] == 123


def test_load_checkpoint_and_dispatch_device_map_none(tmp_path):
    """Root "" device-map entry covers every param (review regression)."""
    cfg, module, ids = _tiny_llama()
    model = Model.from_flax(module, jax.random.key(0), ids)
    expected = np.asarray(model(ids))
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    flat = {k: np.asarray(v) for k, v in flatten_state_dict(model.params).items()}
    save_sharded_safetensors(flat, ckpt)
    off = load_checkpoint_and_dispatch(module, ckpt, ids, device_map=None)
    np.testing.assert_allclose(np.asarray(off(ids)), expected, rtol=1e-5, atol=1e-5)


def test_balanced_memory_spreads_layers():
    """balanced budgets must be tighter than raw caps so layers spread
    (review regression: fallback buffer was ~the whole model)."""
    from accelerate_tpu.utils.modeling import get_balanced_memory

    cfg, module, ids = _tiny_llama()
    abstract = init_empty_weights(module, ids)
    sizes = compute_module_sizes(abstract)
    raw = {0: sizes[""], 1: sizes[""]}  # each device could hold everything
    balanced = get_balanced_memory(abstract, dict(raw))
    assert balanced[0] < raw[0], "balanced budget should cap below the full model"
    dm = infer_auto_device_map(abstract, balanced)
    used_devices = {v for v in dm.values() if not isinstance(v, str)}
    assert len(used_devices) >= 2 or len(jax.local_devices()) < 2


def test_notebook_launcher_refuses_live_backend():
    import pytest as _pytest

    from accelerate_tpu import notebook_launcher

    jax.devices()  # ensure the backend is up in this process
    with _pytest.raises(RuntimeError, match="already initialized"):
        notebook_launcher(lambda: None, num_processes=2)


def test_cpu_offload_with_hook_chaining():
    """Params stay chip-resident between forwards; offload() evicts; chaining
    a prev hook evicts stage i-1 when stage i loads (reference pipeline
    pattern, big_modeling.py:278-314)."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu import Model, cpu_offload_with_hook

    class Mlp(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(8)(nn.relu(nn.Dense(16)(x)))

    x = jnp.ones((2, 8))
    m1 = Model.from_flax(Mlp(), jax.random.key(0), x)
    m2 = Model.from_flax(Mlp(), jax.random.key(1), x)
    dev = jax.devices()[0]
    host = jax.local_devices(backend="cpu")[0]

    m1h, hook1 = cpu_offload_with_hook(m1, execution_device=dev)
    m2h, hook2 = cpu_offload_with_hook(m2, execution_device=dev, prev_module_hook=hook1)

    def device_of(model):
        return next(iter(jax.tree.leaves(model._params)[0].devices()))

    assert device_of(m1h) == host
    y = m2h(m1h(x))
    assert y.shape == (2, 8)
    # m1 was evicted by m2's load; m2 stays resident.
    assert device_of(m1h) == host
    assert device_of(m2h) == dev
    hook2.offload()
    assert device_of(m2h) == host
    # Second pass still works and matches.
    np.testing.assert_allclose(np.asarray(m2h(m1h(x))), np.asarray(y), rtol=1e-6)


def test_init_on_device_places_params_on_host():
    import flax.linen as nn
    import jax

    from accelerate_tpu import init_on_device

    host = jax.local_devices(backend="cpu")[0]

    class M(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x)

    with init_on_device(host):
        params = M().init(jax.random.key(0), jax.numpy.ones((1, 4)))["params"]
    leaf = jax.tree.leaves(params)[0]
    assert next(iter(leaf.devices())) == host


# ---------------------------------------------------------------------------
# Generic layer-streaming (round-3): every family streams, not just Llama/OPT
# ---------------------------------------------------------------------------


def _stream_case(name, scan_layers):
    """(module, inputs) for each streamed family at tiny scale."""
    rng = np.random.default_rng(0)
    if name == "neox":
        from accelerate_tpu.models import GPTNeoXConfig, GPTNeoXForCausalLM

        cfg = GPTNeoXConfig.tiny(dtype=jnp.float32, scan_layers=scan_layers)
        ids = rng.integers(0, cfg.vocab_size, (2, 12)).astype(np.int32)
        return GPTNeoXForCausalLM(cfg), (ids,)
    if name == "gpt2":
        from accelerate_tpu.models import GPT2Config, GPT2LMHeadModel

        cfg = GPT2Config.tiny(dtype=jnp.float32, scan_layers=scan_layers)
        ids = rng.integers(0, cfg.vocab_size, (2, 12)).astype(np.int32)
        return GPT2LMHeadModel(cfg), (ids,)
    if name == "mixtral":
        from accelerate_tpu.models import MixtralConfig, MixtralForCausalLM

        cfg = MixtralConfig.tiny(dtype=jnp.float32, scan_layers=scan_layers)
        ids = rng.integers(0, cfg.vocab_size, (2, 12)).astype(np.int32)
        return MixtralForCausalLM(cfg), (ids,)
    if name == "t5":
        from accelerate_tpu.models import T5Config, T5ForConditionalGeneration

        cfg = T5Config.tiny(dtype=jnp.float32, scan_layers=scan_layers)
        enc = rng.integers(1, cfg.vocab_size, (2, 10)).astype(np.int32)
        dec = rng.integers(1, cfg.vocab_size, (2, 8)).astype(np.int32)
        return T5ForConditionalGeneration(cfg), (enc, dec)
    if name == "whisper":
        from accelerate_tpu.models import WhisperConfig, WhisperForConditionalGeneration

        cfg = WhisperConfig.tiny(dtype=jnp.float32, scan_layers=scan_layers)
        feats = rng.normal(size=(2, 24, cfg.num_mel_bins)).astype(np.float32)
        dec = rng.integers(0, cfg.vocab_size, (2, 6)).astype(np.int32)
        return WhisperForConditionalGeneration(cfg), (feats, dec)
    raise KeyError(name)


@pytest.mark.parametrize("scan_layers", [False, True])
@pytest.mark.parametrize("name", ["neox", "gpt2", "mixtral", "t5", "whisper"])
def test_generic_stream_forward_matches_full(name, scan_layers):
    module, inputs = _stream_case(name, scan_layers)
    model = Model.from_flax(module, jax.random.key(0), *inputs)
    expected = np.asarray(model(*inputs))

    off = cpu_offload(model)
    got = np.asarray(off(*inputs))
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)
    assert off.hbm_resident_bytes() == 0
    # The streamed path ran (fallback materialization never sets this).
    assert getattr(off, "last_stream_peak_bytes", None) is not None


def test_neox_stream_peak_is_o_two_layers():
    """VERDICT r2 'done' criterion: dispatched GPT-NeoX peak HBM is O(2
    layers) + embeddings/head, not O(model)."""
    from accelerate_tpu.models import GPTNeoXConfig, GPTNeoXForCausalLM

    cfg = GPTNeoXConfig.tiny(dtype=jnp.float32, scan_layers=True, num_hidden_layers=8)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (2, 12)).astype(np.int32)
    module = GPTNeoXForCausalLM(cfg)
    model = Model.from_flax(module, jax.random.key(0), ids)

    total = sum(leaf.nbytes for leaf in jax.tree.leaves(model.params))
    layers = sum(leaf.nbytes for leaf in jax.tree.leaves(model.params["gpt_neox"]["layers"]))
    non_layer = total - layers
    per_layer = layers // cfg.num_hidden_layers

    off = cpu_offload(model)
    off(ids)
    peak = off.last_stream_peak_bytes
    assert peak <= non_layer + 3 * per_layer  # double-buffer: <=2 cached + 1 in flight
    assert peak < total  # strictly better than materializing everything


def test_fallback_materialize_warns(caplog):
    """Families without a stream plan must warn, not silently defeat offload."""
    import flax.linen as nn
    import logging as _logging

    class NoPlanNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(8)(x)

    x = np.ones((2, 8), np.float32)
    model = Model.from_flax(NoPlanNet(), jax.random.key(0), x)
    off = cpu_offload(model)
    import accelerate_tpu.big_modeling as bm

    bm._warned_fallback.discard("NoPlanNet")
    with caplog.at_level(_logging.WARNING):
        off(x)
    assert any("no stream plan" in r.message for r in caplog.records)
