"""Public observability schema pins: the exact key sets of ``poll()`` rows,
``stats()`` (including the faults/window/disagg/autoscale blocks), and
``summary()`` top-level blocks. These dicts are consumed by bench rows,
smokes, dashboards, and the autoscaler — a silently renamed or dropped key
breaks them downstream, so additions/removals must update these pins
deliberately. All CPU-only, tier-1 fast."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import (
    DisaggConfig,
    DisaggServingEngine,
    Model,
    ServingConfig,
    ServingEngine,
)
from accelerate_tpu.utils import set_seed


@pytest.fixture(scope="module")
def llama():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    probe = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 8),
                                              dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), probe)
    return cfg, model


def _prompts(cfg, lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, (n,), dtype=np.int32)
            for n in lengths]


POLL_ROW_KEYS = {
    "id", "status", "tokens", "new_tokens", "ttft_s", "tpot_s",
    "weights_version", "attempt", "recovered", "drafted", "accepted",
}

SERVING_STATS_KEYS = {
    "requests_submitted", "requests_completed", "tokens_out",
    "prompt_tokens_in", "elapsed_s", "tokens_per_s",
    "ttft_p50_s", "ttft_p95_s", "ttft_queue_wait_mean_s",
    "ttft_prefill_mean_s", "tpot_mean_s",
    "ticks", "decode_steps", "prefill_chunks", "prefill_pad_tokens",
    "prefill_ladder", "n_slots", "mean_occupancy", "peak_occupancy",
    "mean_queue_depth", "slot_allocs", "slot_reuses", "steady_recompiles",
    "decode_executables", "prefill_executables", "weights_version",
    "canary", "window", "faults", "journal", "sdc", "speculation",
}

# stats()["speculation"] (ServingEngine.speculation_stats): live whether or
# not speculate_k is set — zeros/None when off, so dashboards key off one
# shape. Feeds the hub's accelerate_tpu_spec_* series and the
# serving_speculative bench row.
SPECULATION_KEYS = {
    "k", "ngram", "drafted", "accepted", "acceptance_rate",
    "tokens_per_tick", "verify_time_s",
}

# The engine ``stats()["sdc"]`` block (DecodeCanary.summary; None when no
# canary is attached) and the telemetry ``summary()["sdc"]`` block
# (SDCSentinel.summary) — bench.py embeds the latter next to ``faults``.
SDC_CANARY_KEYS = {
    "every", "armed", "golden_digest", "probes", "mismatches",
    "quarantines", "suppressed_rows",
}

SDC_SUMMARY_KEYS = {
    "vote_every", "repair", "digests", "votes", "mismatches", "probes",
    "probes_failed", "repairs", "quarantines", "quarantined_hosts",
    "peer_quarantined",
}

JOURNAL_KEYS = {
    "dir", "fsync", "appends", "bytes_written", "syncs", "rotations",
    "compactions", "compact_aborts", "records_retired", "torn_writes",
    "torn_tails", "corrupt_skipped", "pending", "retired",
    "recovered_inflight", "recovered_terminal", "deduped",
}

WINDOW_KEYS = {
    "requests", "capacity", "ok", "ttft_p50_s", "ttft_p95_s",
    "tpot_p50_s", "tpot_p95_s", "shed_rate", "timeout_rate", "failed_rate",
    "queue_depth_p95", "prompt_decode_ratio",
}

FAULTS_KEYS = {
    "sheds", "timeouts", "failed", "retries", "slot_quarantines",
    "lane_quarantines", "handoff_retries", "handoff_delays",
    "promoted", "rolled_back",
    "injected", "quarantined_slots", "degraded", "preempted",
}

DISAGG_KEYS = {
    "slice_plan", "n_prefill_devices", "n_decode_devices",
    "decode_slot_sharded", "n_prefill_lanes", "handoff_depth",
    "handoff_transfers", "handoff_inserts", "handoff_bytes",
    "handoff_final_flushes", "handoff_lat_sampled", "handoff_lat_mean_s",
    "handoff_lat_p95_s", "quarantined_lanes", "healthy_lanes", "degraded",
    "measured_flop_ratio", "resize",
}

AUTOSCALE_KEYS = {
    "samples", "decisions", "holds", "grows", "shrinks", "resplits",
    "dead_device_shrinks", "resizes", "aborts", "flap_damped", "spikes",
    "planner_refusals", "active_devices", "pool_devices", "dead_devices",
    "cooldown_until_tick", "breach_over", "breach_under", "last_action",
}

# Fleet-router poll rows are the engine row plus routing provenance; the
# stats() block feeds the MetricsHub ``accelerate_tpu_fleet_*`` series and
# the serving_fleet bench row.
FLEET_POLL_ROW_KEYS = POLL_ROW_KEYS | {"cell", "spilled", "drained_from"}

FLEET_STATS_KEYS = {
    "cells", "healthy", "degraded", "draining", "dead", "ticks",
    "submitted", "deduped", "routed_affinity", "routed_spilled", "shed",
    "completed", "ok", "heartbeat_skips",
    "drains", "drained_cached", "drained_resubmitted", "drain_last_s",
    "publishes", "promoted", "rolled_back", "quarantined_versions",
    "scale_ups", "scale_downs", "per_cell",
}

FLEET_PER_CELL_KEYS = {
    "state", "pending", "weights_version", "queue_depth_p95",
    "requests_completed", "decode_executables", "steady_recompiles",
}

TRACING_STATS_KEYS = {
    "spans", "dropped_spans", "by_kind", "requests", "open_spans", "flows",
}

# Blocks summary() may legally contain; anything else is an unpinned leak.
SUMMARY_ALWAYS = {
    "steps", "recompiles", "peak_hbm_bytes", "collectives",
    "checkpoint_events", "checkpoint",
}
SUMMARY_OPTIONAL = {
    "faults", "watchdog", "serving", "reshard", "disagg", "publish",
    "autoscale", "plan", "tracing", "executables", "compile", "sdc",
    "profile",
    "step_time_mean_s", "step_time_p50_s", "step_time_p90_s",
    "data_wait_mean_s", "ema_samples_per_s", "ema_tokens_per_s",
}

# The summary()["profile"] block (profiler.DeviceTimeProfiler.summary).
PROFILE_SUMMARY_KEYS = {
    "steps", "ticks", "cost_captured", "overlap_ratio_mean",
    "terms_mean_s", "tick_terms_mean_s", "bandwidth_residuals", "ring",
    "flight_dumps",
}

# Prometheus series a fresh profiled+traced telemetry recorder renders from
# the ONE MetricsHub renderer — the pinned accelerate_tpu_<subsystem>_<name>
# scheme plus the one-release legacy aliases. Activity (spans, steps, SLO
# windows) only ADDS names; this is the floor that must never drift.
HUB_BASE_METRIC_NAMES = {
    "accelerate_tpu_telemetry_steps",
    "accelerate_tpu_telemetry_recompiles",
    "accelerate_tpu_telemetry_peak_hbm_bytes",
    "accelerate_tpu_telemetry_checkpoint_events",
    "accelerate_tpu_profile_steps",
    "accelerate_tpu_profile_ticks",
    "accelerate_tpu_profile_cost_captured",
    "accelerate_tpu_profile_ring_capacity",
    "accelerate_tpu_profile_ring_len",
    "accelerate_tpu_profile_flight_dumps",
    "accelerate_tpu_tracing_spans",
    "accelerate_tpu_tracing_dropped_spans",
    "accelerate_tpu_tracing_requests",
    "accelerate_tpu_tracing_open_spans",
    "accelerate_tpu_tracing_flows",
    # deprecated aliases, kept one release (profiler.MetricsHub.alias)
    "accelerate_tpu_trace_dropped_spans_total",
    "accelerate_tpu_trace_requests",
}


def test_poll_row_schema(llama):
    cfg, model = llama
    engine = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8]))
    for p in _prompts(cfg, [5, 9]):
        engine.submit(p, max_new_tokens=2)
    while engine.pending:
        engine.tick()
    rows = engine.poll()
    assert len(rows) == 2
    for row in rows:
        assert set(row) == POLL_ROW_KEYS
        assert row["status"] == "ok"


def test_serving_stats_schema(llama):
    cfg, model = llama
    engine = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8]))
    engine.run(_prompts(cfg, [5, 9]), max_new_tokens=2)
    stats = engine.stats()
    assert set(stats) == SERVING_STATS_KEYS
    assert set(stats["window"]) == WINDOW_KEYS
    assert set(stats["faults"]) == FAULTS_KEYS
    assert stats["journal"] is None  # journaling is off by default
    assert set(stats["speculation"]) == SPECULATION_KEYS
    assert stats["speculation"]["k"] == 0  # speculation is off by default
    assert stats["speculation"]["acceptance_rate"] is None


def test_speculation_stats_and_hub_series(llama):
    """With speculate_k set: the speculation block populates (same pinned
    shape), poll rows carry real drafted/accepted counts, and a hub wired
    via telemetry renders the accelerate_tpu_spec_* series floor."""
    from types import SimpleNamespace

    from accelerate_tpu import MetricsHub

    cfg, model = llama
    hub = MetricsHub()
    engine = ServingEngine(
        model,
        ServingConfig(n_slots=2, max_len=48, prefill_chunks=[4, 8],
                      speculate_k=2, speculate_ngram=8),
        telemetry=SimpleNamespace(hub=hub, record_event=lambda *a, **k: None,
                                  record_serving=lambda *a, **k: None),
    )
    for p in _prompts(cfg, [5, 9]):
        engine.submit(p, max_new_tokens=8)
    rows = []
    while engine.pending:
        engine.tick()
        rows.extend(engine.poll())
    stats = engine.stats()
    assert set(stats) == SERVING_STATS_KEYS
    spec = stats["speculation"]
    assert set(spec) == SPECULATION_KEYS
    assert spec["k"] == 2 and spec["drafted"] > 0
    assert spec["acceptance_rate"] is not None
    assert len(rows) == 2
    for row in rows:
        assert set(row) == POLL_ROW_KEYS
        assert row["drafted"] >= row["accepted"] >= 0
    assert sum(r["drafted"] for r in rows) == spec["drafted"]
    names = hub.metric_names()
    assert {
        "accelerate_tpu_spec_k",
        "accelerate_tpu_spec_drafted",
        "accelerate_tpu_spec_accepted",
        "accelerate_tpu_spec_acceptance_rate",
        "accelerate_tpu_spec_tokens_per_tick",
        "accelerate_tpu_spec_verify_time_s",
    } <= names, f"missing spec series in {sorted(names)}"


def test_journal_stats_schema(llama, tmp_path):
    cfg, model = llama
    engine = ServingEngine(
        model, ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8],
                             journal_dir=str(tmp_path / "wal")))
    engine.run(_prompts(cfg, [5, 9]), max_new_tokens=2)
    stats = engine.stats()
    assert set(stats) == SERVING_STATS_KEYS
    assert set(stats["journal"]) == JOURNAL_KEYS


def test_disagg_stats_schema(llama):
    cfg, model = llama
    engine = DisaggServingEngine(
        model,
        ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8]),
        disagg=DisaggConfig(n_prefill_lanes=2),
    )
    engine.run(_prompts(cfg, [5, 9]), max_new_tokens=2)
    stats = engine.stats()
    assert set(stats) == SERVING_STATS_KEYS | {"disagg"}
    assert set(stats["disagg"]) == DISAGG_KEYS


def test_autoscale_stats_schema(llama):
    from accelerate_tpu import AutoscaleConfig, AutoscaleController

    cfg, model = llama
    engine = DisaggServingEngine(
        model,
        ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8]),
        disagg=DisaggConfig(n_prefill_lanes=1),
    )
    ctl = AutoscaleController(engine, AutoscaleConfig())
    assert set(ctl.stats()) == AUTOSCALE_KEYS


def test_fleet_stats_and_poll_row_schema(llama, tmp_path):
    """The fleet.py observability surface: stats() block keys, per-cell
    sub-block keys, poll rows = engine schema + provenance, and the
    MetricsHub ``accelerate_tpu_fleet_*`` series floor."""
    from types import SimpleNamespace

    from accelerate_tpu import FleetRouter, MetricsHub

    cfg, model = llama
    hub = MetricsHub()
    telemetry = SimpleNamespace(hub=hub, record_event=lambda *a, **k: None)
    cells = {
        f"c{i}": ServingEngine(model, ServingConfig(
            n_slots=2, max_len=32, prefill_chunks=[4, 8],
            journal_dir=str(tmp_path / f"wal{i}")))
        for i in range(2)
    }
    router = FleetRouter(cells, telemetry=telemetry)
    for i, p in enumerate(_prompts(cfg, [5, 9])):
        router.submit(p, max_new_tokens=2, client_request_id=f"r{i}")
    rows = []
    while router.pending:
        router.tick()
        rows.extend(router.poll())
    assert len(rows) == 2
    for row in rows:
        assert set(row) == FLEET_POLL_ROW_KEYS
        assert row["status"] == "ok"
    stats = router.stats()
    assert set(stats) == FLEET_STATS_KEYS
    for name, block in stats["per_cell"].items():
        assert name in cells
        assert set(block) == FLEET_PER_CELL_KEYS
    names = hub.metric_names()
    fleet_names = {n for n in names if n.startswith("accelerate_tpu_fleet_")}
    assert {
        "accelerate_tpu_fleet_cells",
        "accelerate_tpu_fleet_healthy",
        "accelerate_tpu_fleet_submitted",
        "accelerate_tpu_fleet_completed",
        "accelerate_tpu_fleet_drains",
    } <= fleet_names, f"missing fleet series in {sorted(fleet_names)}"
    router.close()


def test_summary_block_schema(tmp_path):
    from accelerate_tpu import Accelerator, TraceRecorder
    from accelerate_tpu.utils import TelemetryKwargs

    acc = Accelerator(
        project_dir=str(tmp_path),
        kwargs_handlers=[TelemetryKwargs(tracing=True, log_every=0)],
    )
    out = acc.telemetry.summary()
    keys = set(out)
    assert SUMMARY_ALWAYS <= keys
    assert keys <= SUMMARY_ALWAYS | SUMMARY_OPTIONAL, (
        f"unpinned summary blocks: {keys - SUMMARY_ALWAYS - SUMMARY_OPTIONAL}")
    assert isinstance(acc.telemetry.tracing, TraceRecorder)
    assert set(out["tracing"]) == TRACING_STATS_KEYS


def test_profile_block_schema_and_hub_metric_names(tmp_path):
    """TelemetryKwargs(profile=True): summary() grows the pinned profile
    block and the MetricsHub renders the pinned base name set (telemetry +
    profile + tracing providers plus the one-release legacy aliases)."""
    from accelerate_tpu import Accelerator, DeviceTimeProfiler
    from accelerate_tpu.utils import TelemetryKwargs

    acc = Accelerator(
        project_dir=str(tmp_path),
        kwargs_handlers=[TelemetryKwargs(tracing=True, profile=True,
                                         log_every=0)],
    )
    assert isinstance(acc.telemetry.profiler, DeviceTimeProfiler)
    out = acc.telemetry.summary()
    assert set(out["profile"]) == PROFILE_SUMMARY_KEYS
    names = acc.telemetry.hub.metric_names()
    assert HUB_BASE_METRIC_NAMES <= names, (
        f"missing pinned series: {HUB_BASE_METRIC_NAMES - names}")
    for name in names:
        assert name.startswith("accelerate_tpu_"), (
            f"series {name} violates the pinned naming scheme")
    # One renderer: the legacy exporter surface is a pure delegation.
    assert acc.telemetry.tracing.metrics_text() == acc.telemetry.hub.render()


def test_profile_off_by_default(tmp_path):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import TelemetryKwargs

    acc = Accelerator(
        project_dir=str(tmp_path),
        kwargs_handlers=[TelemetryKwargs(log_every=0)],
    )
    assert acc.telemetry.profiler is None
    assert "profile" not in acc.telemetry.summary()


def test_sdc_block_schemas(tmp_path):
    """The two sdc.py observability blocks, pinned — and off by default:
    ``stats()["sdc"]`` is None until a DecodeCanary is attached, and
    ``summary()`` grows an ``sdc`` block only when the sentinel is armed."""
    from accelerate_tpu.sdc import DecodeCanary, SDCConfig, SDCSentinel

    class _Eng:  # the canary only touches these at construction time
        def attach_sdc_canary(self, canary):
            self.canary = canary

    canary = DecodeCanary(_Eng(), every=4)
    assert set(canary.summary()) == SDC_CANARY_KEYS
    assert canary.summary()["armed"] is False

    class _Acc:
        project_dir = str(tmp_path)

    class _Mgr:
        accelerator = _Acc()

    sentinel = SDCSentinel(_Mgr(), SDCConfig())
    assert set(sentinel.summary()) == SDC_SUMMARY_KEYS
    assert sentinel.summary()["quarantined_hosts"] == []
