"""Topology-aware memory estimator: the tensor-state categories must match
the real sharded arrays byte-for-byte (same planner → no drift), and the CLI
surface must expose it."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.parallelism_config import ParallelismConfig
from accelerate_tpu.utils.estimate_memory import (
    GiB,
    build_abstract_mesh,
    estimate_per_chip,
    replicated_large_leaves,
    _tree_bytes_per_chip,
)


def _materialized_bytes_on_dev0(tree):
    """Exact bytes device 0 holds for a pytree of sharded jax.Arrays."""
    total = 0
    dev0 = jax.devices()[0]
    for leaf in jax.tree_util.tree_leaves(tree):
        for shard in leaf.addressable_shards:
            if shard.device == dev0:
                total += shard.data.nbytes
    return total


@pytest.mark.parametrize("pc_kwargs", [
    {"dp_shard_size": 8},
    {"dp_shard_size": 4, "tp_size": 2},
    {"dp_replicate_size": 2, "dp_shard_size": 4},
])
def test_param_and_opt_bytes_match_materialized(pc_kwargs):
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils import set_seed

    AcceleratorState._reset_state()
    GradientState._reset_state()
    set_seed(0)
    from accelerate_tpu.models import llama_tp_rules

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    pc = ParallelismConfig(**pc_kwargs)
    rules = llama_tp_rules(cfg.scan_layers) if pc.tp_size > 1 else None
    est, shapes, shardings = estimate_per_chip(
        module, cfg, pc, seq=16, per_chip_batch=1, optimizer="adamw",
        tp_rules=rules,
    )

    acc = Accelerator(parallelism_config=pc)
    ids = np.zeros((8, 9), np.int32)
    model = Model.from_flax(module, jax.random.key(0), ids, tp_rules=rules)
    model, _ = acc.prepare(model, optax.adamw(1e-3))
    got_params = _materialized_bytes_on_dev0(acc.train_state.params)
    want_params = int(est.params_gib * GiB)
    assert got_params == want_params, (got_params, want_params)

    # Adam moments: 2 × params bytes, same shardings (counts are scalars).
    moment_tree = [
        leaf for leaf in jax.tree_util.tree_leaves(acc.train_state.opt_state)
        if hasattr(leaf, "shape") and leaf.ndim > 0
    ]
    got_opt = _materialized_bytes_on_dev0(moment_tree)
    want_opt = int(est.opt_state_gib * GiB)
    assert got_opt == want_opt, (got_opt, want_opt)


def test_replicated_leaf_detector():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    pc = ParallelismConfig(dp_replicate_size=8)  # DDP: everything replicated
    est, shapes, shardings = estimate_per_chip(module, cfg, pc, seq=16)
    mesh = build_abstract_mesh(pc)
    bad = replicated_large_leaves(shapes, shardings, mesh, min_bytes=2 ** 16)
    assert any("embed_tokens" in b for b in bad)  # replication detected

    pc2 = ParallelismConfig(dp_shard_size=8)  # FSDP: large leaves sharded
    _, shapes2, shardings2 = estimate_per_chip(module, cfg, pc2, seq=16)
    assert replicated_large_leaves(
        shapes2, shardings2, build_abstract_mesh(pc2), min_bytes=2 ** 16
    ) == []


def test_7b_v5e64_fits_hbm_abstractly():
    """The BASELINE.md contract shape: 7B FSDP on a v5e-64 — estimated from
    the same planner the trainer uses, no devices required."""
    cfg = LlamaConfig.llama_7b(dtype=jnp.bfloat16, remat=True)
    module = LlamaForCausalLM(cfg)
    pc = ParallelismConfig(dp_shard_size=64)
    est, shapes, shardings = estimate_per_chip(
        module, cfg, pc, seq=2048, per_chip_batch=1,
        master_dtype=jnp.bfloat16, moments_dtype=jnp.bfloat16,
    )
    assert replicated_large_leaves(shapes, shardings, build_abstract_mesh(pc)) == []
    assert est.params_gib * 64 > 11  # ~6.7B params in bf16 ≈ 12.5 GiB global
    assert est.total_gib < 16, est.rows()


def test_estimate_ep_axis_moe_sharding():
    """MoE expert weights shard their expert dim over ep_axes: ep=2 riding
    dp_shard must halve the per-chip expert bytes vs the same layout with
    ep=1 (experts replicated across dp_shard for params... no — FSDP shards
    them anyway; compare against a pure dp_replicate layout where ep is the
    only thing sharding them)."""
    from accelerate_tpu.models import MixtralConfig, MixtralForCausalLM, mixtral_tp_rules

    cfg = MixtralConfig.tiny(dtype=jnp.float32)
    module = MixtralForCausalLM(cfg)

    # Baseline: pure replication (DDP) — experts fully replicated.
    pc0 = ParallelismConfig(dp_replicate_size=8)
    est0, shapes0, _ = estimate_per_chip(module, cfg, pc0, seq=16)

    # ep=2 borrowing the dp_shard axis: expert dim sharded 2-way. Keep FSDP
    # off the comparison by pinning min size high via no tp rules... the
    # dp_shard axis also FSDP-shards, so compare ep=2 rules against the SAME
    # mesh without ep rules: only the rule table differs.
    pc = ParallelismConfig(dp_replicate_size=4, dp_shard_size=2, ep_size=2)
    assert pc.ep_axes == ("dp_shard",)
    rules_ep = mixtral_tp_rules(cfg.scan_layers, ep_axes=pc.ep_axes)
    est_ep, shapes, shardings_ep = estimate_per_chip(
        module, cfg, pc, seq=16, tp_rules=rules_ep
    )
    est_noep, _, _ = estimate_per_chip(module, cfg, pc, seq=16)
    # Expert tensors dominate tiny-mixtral params; ep sharding must shrink
    # per-chip bytes vs both baselines.
    assert est_ep.params_gib < est0.params_gib
    assert est_ep.params_gib <= est_noep.params_gib
    # The expert leaves really carry the ep axis in their spec.
    mesh = build_abstract_mesh(pc)
    from jax.sharding import NamedSharding

    ep_specs = [
        sh.spec for sh in jax.tree_util.tree_leaves(
            shardings_ep, is_leaf=lambda x: isinstance(x, NamedSharding)
        )
        if any("dp_shard" in str(e) for e in sh.spec)
    ]
    assert ep_specs, "no leaf sharded over the ep (dp_shard) axis"


def test_estimate_moments_dtype_override():
    """moments_dtype=bf16 halves optimizer-state bytes vs fp32 masters while
    params/grads stay untouched (the planner's memory ladder leans on it)."""
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    pc = ParallelismConfig(dp_shard_size=8)
    est_fp32, _, _ = estimate_per_chip(
        module, cfg, pc, seq=16, optimizer="adamw", master_dtype=jnp.float32
    )
    est_bf16, _, _ = estimate_per_chip(
        module, cfg, pc, seq=16, optimizer="adamw",
        master_dtype=jnp.float32, moments_dtype=jnp.bfloat16,
    )
    assert est_bf16.params_gib == est_fp32.params_gib
    assert est_bf16.grads_gib == est_fp32.grads_gib
    assert est_bf16.opt_state_gib == pytest.approx(est_fp32.opt_state_gib / 2)


def test_abstract_vs_real_mesh_spec_equality():
    """The deviceless AbstractMesh plan must equal the real-Mesh plan spec
    for spec and bytes — the property that lets a laptop plan a pod."""
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    from accelerate_tpu.models import llama_tp_rules

    pc = ParallelismConfig(dp_shard_size=4, tp_size=2)
    rules = llama_tp_rules(cfg.scan_layers)
    est_abs, shapes_abs, sh_abs = estimate_per_chip(
        module, cfg, pc, seq=16, tp_rules=rules
    )
    real_mesh = pc.build_mesh(jax.devices())
    est_real, shapes_real, sh_real = estimate_per_chip(
        module, cfg, pc, seq=16, tp_rules=rules, mesh=real_mesh
    )
    from jax.sharding import NamedSharding

    leaf = lambda x: isinstance(x, NamedSharding)
    specs_abs = [s.spec for s in jax.tree_util.tree_leaves(sh_abs, is_leaf=leaf)]
    specs_real = [s.spec for s in jax.tree_util.tree_leaves(sh_real, is_leaf=leaf)]
    assert specs_abs == specs_real
    assert est_abs.params_gib == est_real.params_gib
    assert est_abs.opt_state_gib == est_real.opt_state_gib


def test_estimate_cli_parallelism(capsys):
    from accelerate_tpu.commands.estimate import estimate_command

    import argparse

    args = argparse.Namespace(
        model_name="llama:7b", dtypes=["bf16"], json=True,
        parallelism="dp_shard=64", seq=2048, per_chip_batch=1,
        optimizer="adamw", hbm_gib=16.0,
    )
    rc = estimate_command(args)
    out = capsys.readouterr().out
    assert rc == 0
    import json as _json

    payload = _json.loads(out)
    assert payload["per_chip"]["total_gib"] < 16
    assert payload["per_chip"]["fits"] is True
