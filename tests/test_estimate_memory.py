"""Topology-aware memory estimator: the tensor-state categories must match
the real sharded arrays byte-for-byte (same planner → no drift), and the CLI
surface must expose it."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.parallelism_config import ParallelismConfig
from accelerate_tpu.utils.estimate_memory import (
    GiB,
    build_abstract_mesh,
    estimate_per_chip,
    replicated_large_leaves,
    _tree_bytes_per_chip,
)


def _materialized_bytes_on_dev0(tree):
    """Exact bytes device 0 holds for a pytree of sharded jax.Arrays."""
    total = 0
    dev0 = jax.devices()[0]
    for leaf in jax.tree_util.tree_leaves(tree):
        for shard in leaf.addressable_shards:
            if shard.device == dev0:
                total += shard.data.nbytes
    return total


@pytest.mark.parametrize("pc_kwargs", [
    {"dp_shard_size": 8},
    {"dp_shard_size": 4, "tp_size": 2},
    {"dp_replicate_size": 2, "dp_shard_size": 4},
])
def test_param_and_opt_bytes_match_materialized(pc_kwargs):
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils import set_seed

    AcceleratorState._reset_state()
    GradientState._reset_state()
    set_seed(0)
    from accelerate_tpu.models import llama_tp_rules

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    pc = ParallelismConfig(**pc_kwargs)
    rules = llama_tp_rules(cfg.scan_layers) if pc.tp_size > 1 else None
    est, shapes, shardings = estimate_per_chip(
        module, cfg, pc, seq=16, per_chip_batch=1, optimizer="adamw",
        tp_rules=rules,
    )

    acc = Accelerator(parallelism_config=pc)
    ids = np.zeros((8, 9), np.int32)
    model = Model.from_flax(module, jax.random.key(0), ids, tp_rules=rules)
    model, _ = acc.prepare(model, optax.adamw(1e-3))
    got_params = _materialized_bytes_on_dev0(acc.train_state.params)
    want_params = int(est.params_gib * GiB)
    assert got_params == want_params, (got_params, want_params)

    # Adam moments: 2 × params bytes, same shardings (counts are scalars).
    moment_tree = [
        leaf for leaf in jax.tree_util.tree_leaves(acc.train_state.opt_state)
        if hasattr(leaf, "shape") and leaf.ndim > 0
    ]
    got_opt = _materialized_bytes_on_dev0(moment_tree)
    want_opt = int(est.opt_state_gib * GiB)
    assert got_opt == want_opt, (got_opt, want_opt)


def test_replicated_leaf_detector():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    pc = ParallelismConfig(dp_replicate_size=8)  # DDP: everything replicated
    est, shapes, shardings = estimate_per_chip(module, cfg, pc, seq=16)
    mesh = build_abstract_mesh(pc)
    bad = replicated_large_leaves(shapes, shardings, mesh, min_bytes=2 ** 16)
    assert any("embed_tokens" in b for b in bad)  # replication detected

    pc2 = ParallelismConfig(dp_shard_size=8)  # FSDP: large leaves sharded
    _, shapes2, shardings2 = estimate_per_chip(module, cfg, pc2, seq=16)
    assert replicated_large_leaves(
        shapes2, shardings2, build_abstract_mesh(pc2), min_bytes=2 ** 16
    ) == []


def test_7b_v5e64_fits_hbm_abstractly():
    """The BASELINE.md contract shape: 7B FSDP on a v5e-64 — estimated from
    the same planner the trainer uses, no devices required."""
    cfg = LlamaConfig.llama_7b(dtype=jnp.bfloat16, remat=True)
    module = LlamaForCausalLM(cfg)
    pc = ParallelismConfig(dp_shard_size=64)
    est, shapes, shardings = estimate_per_chip(
        module, cfg, pc, seq=2048, per_chip_batch=1,
        master_dtype=jnp.bfloat16, moments_dtype=jnp.bfloat16,
    )
    assert replicated_large_leaves(shapes, shardings, build_abstract_mesh(pc)) == []
    assert est.params_gib * 64 > 11  # ~6.7B params in bf16 ≈ 12.5 GiB global
    assert est.total_gib < 16, est.rows()


def test_estimate_cli_parallelism(capsys):
    from accelerate_tpu.commands.estimate import estimate_command

    import argparse

    args = argparse.Namespace(
        model_name="llama:7b", dtypes=["bf16"], json=True,
        parallelism="dp_shard=64", seq=2048, per_chip_batch=1,
        optimizer="adamw", hbm_gib=16.0,
    )
    rc = estimate_command(args)
    out = capsys.readouterr().out
    assert rc == 0
    import json as _json

    payload = _json.loads(out)
    assert payload["per_chip"]["total_gib"] < 16
    assert payload["per_chip"]["fits"] is True
