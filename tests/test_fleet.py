"""Fleet router (fleet.py): cell registry/health, session-affinity routing
with spillover and shed, exactly-once cross-cell drain of a dead cell's
journal, and cell-granular publish/scale lifecycle.

All CPU-only, tier-1 fast. The full game day (hard-kill mid-trace, ok rows
bit-equal to an uninterrupted reference, executable census per survivor,
second seeded round bit-identical) lives in `make fleet-smoke`
(test_utils/scripts/fleet_smoke.py); here cells are in-process engines and
a "crash" is the deterministic `cell_crash` chaos point or an engine
abandoned by the router.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import (
    FaultInjector,
    FleetConfig,
    FleetDegradedError,
    FleetRouter,
    Model,
    ServingConfig,
    ServingEngine,
)
from accelerate_tpu.fleet import CELL_STATES, _affinity_hash
from accelerate_tpu.utils import set_seed


@pytest.fixture(scope="module")
def llama():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    probe = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 8),
                                              dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), probe)
    return cfg, model


def _prompts(cfg, lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, (n,), dtype=np.int32)
            for n in lengths]


def _mk_cell(model, wal, **kw):
    cfg = ServingConfig(n_slots=2, max_len=32, prefill_chunks=[4, 8],
                        journal_dir=str(wal), **kw)
    return ServingEngine(model, cfg)


def _fleet(model, tmp_path, n=2, config=None, chaos=None):
    cells = {f"c{i}": _mk_cell(model, tmp_path / f"wal{i}") for i in range(n)}
    return FleetRouter(cells, config, chaos=chaos)


def _drain_fleet(router, guard=5000):
    rows = {}
    ticks = 0
    while router.pending:
        router.tick()
        for r in router.poll():
            rows[r["id"]] = r
        ticks += 1
        assert ticks < guard, "fleet drain guard tripped"
    for r in router.poll():
        rows[r["id"]] = r
    return rows


def _session_for(cell_index, n_cells, prefix="s"):
    """A session key whose affinity hash lands on cell `cell_index` of an
    all-healthy n-cell fleet (routable order is sorted names c0..cN)."""
    for i in range(1000):
        key = f"{prefix}{i}"
        if _affinity_hash(key) % n_cells == cell_index:
            return key
    raise AssertionError("no session key found")


# ---------------------------------------------------------------------------
# registry + routing
# ---------------------------------------------------------------------------


def test_registry_requires_journal_and_unique_names(llama, tmp_path):
    cfg, model = llama
    bare = ServingEngine(model, ServingConfig(
        n_slots=2, max_len=32, prefill_chunks=[4, 8]))
    with pytest.raises(ValueError, match="no journal"):
        FleetRouter({"c0": bare})
    bare.close()
    with pytest.raises(ValueError, match="at least one cell"):
        FleetRouter({})
    router = _fleet(model, tmp_path, n=2)
    assert router.cell_states() == {"c0": "healthy", "c1": "healthy"}
    assert set(router.cell_states().values()) <= set(CELL_STATES)
    with pytest.raises(ValueError, match="already registered"):
        router.scale_up("c0", engine=router._cells["c0"].engine)
    router.close()


def test_affinity_routing_is_deterministic(llama, tmp_path):
    cfg, model = llama
    router = _fleet(model, tmp_path, n=2)
    prompts = _prompts(cfg, [5, 6, 7, 8])
    placed = {}
    for i, p in enumerate(prompts):
        rid = router.submit(p, max_new_tokens=3, rng=jax.random.key(i),
                            client_request_id=f"r{i}", session_id=f"sess{i}")
        placed[rid] = router._requests[rid]["cell"]
    # Pure function of the session key: matches the hash, and repeats.
    for rid, cell in placed.items():
        key = router._requests[rid]["session"]
        want = f"c{_affinity_hash(key) % 2}"
        assert cell == want
    rows = _drain_fleet(router)
    assert len(rows) == 4
    for rid, row in rows.items():
        assert row["cell"] == placed[rid]
        assert row["spilled"] is False and row["drained_from"] is None
        assert row["status"] == "ok"
    s = router.stats()
    assert s["routed_affinity"] == 4 and s["routed_spilled"] == 0
    assert s["completed"] == 4 and s["ok"] == 4
    router.close()


def test_spillover_when_affinity_target_breaches(llama, tmp_path):
    cfg, model = llama
    # Band of 1.0: the affinity target breaches once its rolling
    # queue-depth p95 exceeds one pending request.
    router = _fleet(model, tmp_path, n=2,
                    config=FleetConfig(queue_depth_band=1.0))
    hot = _session_for(0, 2)
    prompts = _prompts(cfg, [5, 6, 7, 8, 5])
    # Pile work on c0 (no ticks yet: p95 window is empty, nothing spills).
    for i, p in enumerate(prompts[:4]):
        router.submit(p, max_new_tokens=6, rng=jax.random.key(i),
                      session_id=hot)
    assert router.stats()["routed_spilled"] == 0
    router.tick()  # c0's window now samples queue depth > band
    rid = router.submit(prompts[4], max_new_tokens=3,
                        rng=jax.random.key(9), session_id=hot)
    rec = router._requests[rid]
    assert rec["spilled"] is True and rec["cell"] == "c1"
    rows = _drain_fleet(router)
    assert rows[rid]["spilled"] is True and rows[rid]["cell"] == "c1"
    assert router.stats()["routed_spilled"] == 1
    router.close()


def test_shed_only_when_all_cells_breach(llama, tmp_path):
    cfg, model = llama
    router = _fleet(model, tmp_path, n=1,
                    config=FleetConfig(queue_depth_band=1.0))
    prompts = _prompts(cfg, [5, 6, 7, 8, 5])
    for i, p in enumerate(prompts[:4]):
        router.submit(p, max_new_tokens=6, rng=jax.random.key(i),
                      session_id="s")
    router.tick()
    rid = router.submit(prompts[4], max_new_tokens=4,
                        rng=jax.random.key(9), session_id="s")
    row = router._rows[rid]
    assert row["status"] == "shed" and row["cell"] is None
    # The shed row carries the FULL fleet poll schema — engine keys plus
    # provenance — and pads the prompt to budget like an engine shed.
    assert set(row) == {
        "id", "status", "tokens", "new_tokens", "ttft_s", "tpot_s",
        "weights_version", "attempt", "recovered", "drafted", "accepted",
        "cell", "spilled", "drained_from",
    }
    assert row["tokens"].shape == (len(prompts[4]) + 4,)
    rows = _drain_fleet(router)
    assert rows[rid]["status"] == "shed"
    s = router.stats()
    assert s["shed"] == 1 and s["completed"] == 5
    assert s["ok"] == 4
    router.close()


def test_fleetwide_cid_dedupe(llama, tmp_path):
    cfg, model = llama
    router = _fleet(model, tmp_path, n=2)
    (p,) = _prompts(cfg, [5])
    rid = router.submit(p, max_new_tokens=3, rng=jax.random.key(0),
                        client_request_id="dup")
    assert router.submit(p, max_new_tokens=3,
                         client_request_id="dup") == rid
    rows = _drain_fleet(router)
    assert set(rows) == {rid}
    # A duplicate AFTER completion re-emits the finished row.
    assert router.submit(p, max_new_tokens=3,
                         client_request_id="dup") == rid
    (again,) = router.poll()
    assert again["id"] == rid
    assert np.array_equal(again["tokens"], rows[rid]["tokens"])
    s = router.stats()
    assert s["submitted"] == 1 and s["deduped"] == 2
    router.close()


# ---------------------------------------------------------------------------
# health + cross-cell drain
# ---------------------------------------------------------------------------


def test_cell_crash_drains_exactly_once_and_bit_equal(llama, tmp_path):
    cfg, model = llama
    prompts = _prompts(cfg, [5, 6, 7, 8, 5, 6])

    def run(root, chaos):
        router = FleetRouter(
            {f"c{i}": _mk_cell(model, root / f"wal{i}") for i in range(2)},
            chaos=chaos)
        rids = {}
        for i, p in enumerate(prompts):
            rids[f"r{i}"] = router.submit(
                p, max_new_tokens=6, rng=jax.random.key(i),
                client_request_id=f"r{i}", session_id=f"sess{i}")
        rows = _drain_fleet(router)
        by_cid = {cid: rows[rid] for cid, rid in rids.items()}
        stats = router.stats()
        return router, by_cid, stats

    ref_router, ref, _ = run(tmp_path / "ref", None)
    ref_router.close()

    chaos = FaultInjector(seed=29, schedule=[
        {"point": "cell_crash", "kind": "crash", "tick": 1, "unit": 0}])
    router, got, s = run(tmp_path / "chaos", chaos)
    assert router.cell_states()["c0"] == "dead"
    assert s["dead"] == 1 and s["drains"] == 1
    assert s["drained_cached"] + s["drained_resubmitted"] >= 1
    assert s["drain_last_s"] is not None
    # Exactly-once: every request resolves exactly once, bit-equal to the
    # uninterrupted reference under equal weights.
    assert set(got) == set(ref)
    for cid in ref:
        assert got[cid]["status"] == "ok" == ref[cid]["status"]
        assert np.array_equal(got[cid]["tokens"], ref[cid]["tokens"])
    # Provenance: c0's requests carry drained_from and recovered.
    moved = [r for r in got.values() if r["drained_from"] == "c0"]
    assert moved and all(r["recovered"] and r["cell"] != "c0"
                         for r in moved)
    # The survivor kept the zero-recompile invariant through the drain.
    surv = router._cells["c1"].engine
    assert surv.executable_counts()["decode"] == 1
    assert surv._stats["steady_recompiles"] == 0
    # Exactly-once on-device: the survivor EXECUTED only what was not
    # already journaled terminal on the dead cell.
    assert surv._stats["completed"] == len(prompts) - s["drained_cached"]
    # Dedupe survives the cell's death: resubmitting a drained cid
    # re-emits its row instead of re-executing.
    before = router.stats()["completed"]
    rid = router.submit(prompts[0], max_new_tokens=6,
                        client_request_id="r0")
    (row,) = router.poll()
    assert row["id"] == rid
    assert np.array_equal(row["tokens"], got["r0"]["tokens"])
    assert router.stats()["completed"] == before
    assert router.stats()["deduped"] == 1
    router.close()


def test_cell_crash_drain_replays_speculative_cells_bit_equal(llama, tmp_path):
    """Cross-cell drain with speculation on in every cell: the survivor
    re-executes the dead cell's in-flight requests through its own
    speculative decode path and every row stays bit-equal to an
    uninterrupted speculative fleet AND to a non-speculative one (exact
    verification composes with the drain's rng/idempotency replay)."""
    cfg, model = llama
    prompts = _prompts(cfg, [5, 6, 7, 8])
    spec = dict(speculate_k=2, speculate_ngram=8)

    def run(root, chaos, **kw):
        router = FleetRouter(
            {f"c{i}": _mk_cell(model, root / f"wal{i}", **kw)
             for i in range(2)},
            chaos=chaos)
        rids = {}
        for i, p in enumerate(prompts):
            rids[f"r{i}"] = router.submit(
                p, max_new_tokens=6, rng=jax.random.key(i),
                client_request_id=f"r{i}", session_id=f"sess{i}")
        rows = _drain_fleet(router)
        by_cid = {cid: rows[rid] for cid, rid in rids.items()}
        return router, by_cid

    plain_router, plain = run(tmp_path / "plain", None)
    plain_router.close()
    ref_router, ref = run(tmp_path / "ref", None, **spec)
    ref_router.close()

    chaos = FaultInjector(seed=29, schedule=[
        {"point": "cell_crash", "kind": "crash", "tick": 1, "unit": 0}])
    router, got = run(tmp_path / "chaos", chaos, **spec)
    assert router.cell_states()["c0"] == "dead"
    assert set(got) == set(ref) == set(plain)
    for cid in ref:
        assert got[cid]["status"] == "ok"
        # Speculation never changes greedy output: chaos == spec ref ==
        # non-speculative fleet, token for token.
        assert np.array_equal(got[cid]["tokens"], ref[cid]["tokens"])
        assert np.array_equal(got[cid]["tokens"], plain[cid]["tokens"])
    # Requests the survivor re-executed drafted through its own engine.
    resub = [r for r in got.values()
             if r["drained_from"] == "c0" or r["cell"] == "c1"]
    assert any(r["drafted"] > 0 for r in resub)
    surv = router._cells["c1"].engine
    assert surv.executable_counts()["decode"] == 1
    assert surv._stats["steady_recompiles"] == 0
    assert surv.stats()["speculation"]["drafted"] > 0
    router.close()


def test_idle_cell_is_declared_dead_and_drained(llama, tmp_path):
    cfg, model = llama
    router = _fleet(model, tmp_path, n=2,
                    config=FleetConfig(max_idle_ticks=3))
    hot = _session_for(0, 2)
    (p,) = _prompts(cfg, [5])
    rid = router.submit(p, max_new_tokens=4, rng=jax.random.key(0),
                        client_request_id="stuck", session_id=hot)
    assert router._requests[rid]["cell"] == "c0"
    # Wedge c0: it heartbeats but never makes progress.
    router._cells["c0"].engine.tick = lambda: None
    ticks = 0
    while router.cell_states()["c0"] != "dead":
        router.tick()
        ticks += 1
        assert ticks < 20, "idle-death detection never fired"
    assert router._cells["c0"].death_class == "cell-dead"
    rows = _drain_fleet(router)
    assert rows[rid]["status"] == "ok"
    assert rows[rid]["cell"] == "c1" and rows[rid]["drained_from"] == "c0"
    assert router.stats()["drained_resubmitted"] == 1
    router.close()


def test_partition_degrades_then_heals(llama, tmp_path):
    cfg, model = llama
    chaos = FaultInjector(seed=7, schedule=[
        {"point": "cell_partition", "kind": "delay", "tick": 0, "unit": 1,
         "delay_ticks": 3}])
    router = _fleet(model, tmp_path, n=2, chaos=chaos)
    router.tick()
    assert router.cell_states()["c1"] == "degraded"
    # Degraded = unreachable for NEW admissions; routing redirects to c0.
    cold = _session_for(1, 2)
    (p,) = _prompts(cfg, [5])
    rid = router.submit(p, max_new_tokens=3, rng=jax.random.key(0),
                        session_id=cold)
    assert router._requests[rid]["cell"] == "c0"
    while router.cell_states()["c1"] != "healthy":
        router.tick()
    assert router.stats()["degraded"] == 0
    rows = _drain_fleet(router)
    assert rows[rid]["status"] == "ok"
    router.close()


def test_router_heartbeat_chaos_skips_health_pass(llama, tmp_path):
    cfg, model = llama
    chaos = FaultInjector(seed=11, schedule=[
        {"point": "router_heartbeat", "kind": "delay", "tick": 0}])
    router = _fleet(model, tmp_path, n=1, chaos=chaos)
    router.tick()
    assert router.stats()["heartbeat_skips"] == 1
    router.tick()
    assert router.stats()["heartbeat_skips"] == 1
    router.close()


def test_no_healthy_cell_raises_fleet_degraded(llama, tmp_path):
    cfg, model = llama
    from accelerate_tpu.utils.constants import FLEET_DEGRADED_EXIT_CODE

    router = _fleet(model, tmp_path, n=1)
    router._kill_cell(router._cells["c0"], "cell-dead", reason="test")
    (p,) = _prompts(cfg, [5])
    with pytest.raises(FleetDegradedError) as ei:
        router.submit(p, max_new_tokens=3)
    assert ei.value.exit_code == FLEET_DEGRADED_EXIT_CODE
    router.close()


# ---------------------------------------------------------------------------
# cell-granular lifecycle
# ---------------------------------------------------------------------------


def _pump(router, cfg, session, n, budget=3, seed=100, cid_prefix="p",
          deadline_s=None):
    rids = []
    prompts = _prompts(cfg, [5] * n, seed=seed)
    for i, p in enumerate(prompts):
        rids.append(router.submit(
            p, max_new_tokens=budget, rng=jax.random.key(seed + i),
            client_request_id=f"{cid_prefix}{i}", session_id=session,
            deadline_s=deadline_s))
    return rids


def test_publish_canaries_one_cell_then_promotes_fleetwide(llama, tmp_path):
    cfg, model = llama
    router = _fleet(model, tmp_path, n=2,
                    config=FleetConfig(canary_ticks=1, min_canary_cohort=2))
    c0, c1 = _session_for(0, 2), _session_for(1, 2)
    # Baseline traffic on the non-canary cell.
    _pump(router, cfg, c1, 2, cid_prefix="b")
    _drain_fleet(router)
    params = router._cells["c0"].engine._params
    out = router.publish(params, weights_version=7)
    assert out == {"version": 7, "canary_cell": "c0"}
    with pytest.raises(ValueError, match="already in flight"):
        router.publish(params, weights_version=8)
    # Canary-cell admissions bind the candidate at fraction=1.0.
    _pump(router, cfg, c0, 3, cid_prefix="c")
    rows = _drain_fleet(router)
    canary_rows = [r for r in rows.values() if r["cell"] == "c0"]
    assert canary_rows and all(
        r["weights_version"] == 7 for r in canary_rows)
    s = router.stats()
    assert s["publishes"] == 1 and s["promoted"] == 1
    assert s["rolled_back"] == 0 and s["quarantined_versions"] == []
    # Promote-all: every live cell now serves version 7.
    for name in ("c0", "c1"):
        assert router._cells[name].engine.weights_version == 7
    router.close()


def test_publish_rollback_quarantines_the_version(llama, tmp_path):
    cfg, model = llama
    router = _fleet(model, tmp_path, n=2,
                    config=FleetConfig(canary_ticks=1, min_canary_cohort=2,
                                       slo_tolerance=0.05))
    c1 = _session_for(1, 2)
    # Healthy baseline on c1.
    _pump(router, cfg, c1, 3, cid_prefix="b")
    _drain_fleet(router)
    params = router._cells["c0"].engine._params
    router.publish(params, weights_version=9)
    # A candidate that blows the SLO: the canary cohort's terminal events
    # are all timeouts (seeded into the engine's real cohort store — the
    # engine-side accounting itself is test_publish.py's subject), so the
    # canary ok-ratio is 0 against a baseline of 1.
    router._cells["c0"].engine._cohorts[9]["events"].extend(
        {"status": "timeout", "ttft_s": None, "tpot_s": None}
        for _ in range(3))
    for _ in range(3):
        router.tick()
    s = router.stats()
    assert s["rolled_back"] == 1 and s["promoted"] == 0
    assert s["quarantined_versions"] == [9]
    assert router._cells["c1"].engine.weights_version == 0
    with pytest.raises(ValueError, match="quarantined"):
        router.publish(params, weights_version=9)
    # A fresh version is still publishable after the quarantine.
    router.publish(params, weights_version=10)
    router.close()


def test_scale_up_and_drain_down(llama, tmp_path):
    cfg, model = llama
    router = _fleet(model, tmp_path, n=1)
    router.scale_up("c1", engine=_mk_cell(model, tmp_path / "walN"))
    assert router.stats()["cells"] == 2
    assert router.cell_states()["c1"] == "healthy"
    # Requests on the draining cell finish; then it closes + deregisters.
    hot = _session_for(0, 2)
    rids = _pump(router, cfg, hot, 2)
    router.scale_down("c0")
    assert router.cell_states()["c0"] == "draining"
    (p,) = _prompts(cfg, [6], seed=9)
    moved = router.submit(p, max_new_tokens=3, rng=jax.random.key(5),
                          session_id=hot)
    assert router._requests[moved]["cell"] == "c1"
    rows = _drain_fleet(router)
    assert all(rows[r]["status"] == "ok" for r in rids + [moved])
    s = router.stats()
    assert s["cells"] == 1 and s["scale_ups"] == 1 and s["scale_downs"] == 1
    assert "c0" not in router.cell_states()
    with pytest.raises(ValueError, match="no live cell"):
        router.scale_down("c0")
    router.close()


def test_fleet_config_validation():
    with pytest.raises(ValueError, match="max_idle_ticks"):
        FleetConfig(max_idle_ticks=0)
    with pytest.raises(ValueError, match="queue_depth_band"):
        FleetConfig(queue_depth_band=0.0)
    with pytest.raises(ValueError, match="canary_ticks"):
        FleetConfig(canary_ticks=0)
    with pytest.raises(ValueError, match="min_canary_cohort"):
        FleetConfig(min_canary_cohort=0)
    with pytest.raises(ValueError, match="slo_tolerance"):
        FleetConfig(slo_tolerance=1.0)
