"""DDP gradient-compression comm hooks (parallel/comm_hooks.py).

Reference analog: DistributedDataParallelKwargs.register_comm_hook
(utils/dataclasses.py:157-241) + tests via test_ddp_comm_hook.py. Strategy:
train the tiny Llama on the 8-device DP mesh with each hook and require
(a) bf16/fp16 hooks track the uncompressed baseline almost exactly, and
(b) PowerSGD rank-8 with error feedback converges to a comparable loss.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from accelerate_tpu import Accelerator, Model
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, cross_entropy_loss
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils import set_seed
from accelerate_tpu.utils.dataclasses import DistributedDataParallelKwargs


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


import functools


@functools.lru_cache(maxsize=None)  # 3 tests reuse the identical baseline run
def _train(comm_hook, steps=12, accum=1, rank=8):
    _reset()
    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    # batch = 8 devices x accum x 1: the hooked step splits microbatches on
    # each device's LOCAL shard, so per-device batch must divide by accum.
    ids = rng.integers(0, cfg.vocab_size, size=(8 * accum, 17), dtype=np.int32)
    handlers = None
    if comm_hook != "baseline":
        handlers = [DistributedDataParallelKwargs(comm_hook=comm_hook, powersgd_rank=rank)]
    from accelerate_tpu import ParallelismConfig

    # DDP topology: dp_replicate axis => replicated params (the default
    # dp_shard axis ZeRO-shards params, which comm hooks reject).
    acc = Accelerator(
        kwargs_handlers=handlers,
        gradient_accumulation_steps=accum,
        parallelism_config=ParallelismConfig(dp_replicate_size=8),
    )
    model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
    model, _ = acc.prepare(model, optax.adam(3e-3))

    def loss_fn(params, batch):
        return cross_entropy_loss(module.apply({"params": params}, batch["x"]), batch["y"])

    step = acc.prepare_train_step(loss_fn, max_grad_norm=1.0)
    from jax.sharding import NamedSharding, PartitionSpec

    bs = NamedSharding(acc.mesh, PartitionSpec(acc.parallelism_config.batch_axes))
    batch = {
        "x": jax.device_put(ids[:, :-1], bs),
        "y": jax.device_put(ids[:, 1:], bs),
    }
    state = acc.train_state
    losses = []
    for _ in range(steps):
        state, metrics = step(state, batch)
        losses.append(float(np.asarray(metrics["loss"])))
    return losses


@pytest.mark.parametrize("hook", ["bf16", "fp16"])
def test_wire_compress_hook_tracks_baseline(hook):
    base = _train("baseline")
    compressed = _train(hook)
    assert np.isfinite(compressed).all()
    # Wire-compressed mean of identical-magnitude grads: near-identical path.
    assert abs(compressed[-1] - base[-1]) < 0.05 * max(base[-1], 1e-3) + 0.05


def test_powersgd_rank8_convergence_parity():
    """VERDICT r3 next#8 contract: opt-in hook, convergence parity at rank 8."""
    base = _train("baseline")
    psgd = _train("powersgd", rank=8)
    assert np.isfinite(psgd).all()
    # Both must actually learn...
    assert base[-1] < base[0] - 0.5
    assert psgd[-1] < psgd[0] - 0.5
    # ...and land in the same neighborhood (low-rank + error feedback).
    assert psgd[-1] < base[-1] + 0.35, (psgd[-1], base[-1])


def test_powersgd_composes_with_grad_accumulation():
    losses = _train("powersgd", steps=6, accum=2)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_comm_hook_rejects_fsdp_sharded_params():
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin

    _reset()
    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(8, 17), dtype=np.int32)
    acc = Accelerator(
        kwargs_handlers=[DistributedDataParallelKwargs(comm_hook="powersgd")],
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size_to_shard=0),
    )
    model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
    model, _ = acc.prepare(model, optax.adam(1e-3))

    def loss_fn(params, batch):
        return cross_entropy_loss(module.apply({"params": params}, batch["x"]), batch["y"])

    with pytest.raises(ValueError, match="replicated"):
        acc.prepare_train_step(loss_fn)


def test_unknown_comm_hook_rejected():
    from accelerate_tpu.parallel.comm_hooks import make_comm_hook_reducer

    with pytest.raises(ValueError, match="comm_hook"):
        make_comm_hook_reducer("gzip", ())


def test_powersgd_compression_is_low_rank():
    """The reduced gradient of a compressible leaf must have rank <= r."""
    from accelerate_tpu.parallel.comm_hooks import (
        init_powersgd_state,
        make_comm_hook_reducer,
    )

    g = {"w": jnp.asarray(np.random.default_rng(0).normal(size=(64, 48)), jnp.float32)}
    st = init_powersgd_state(g, rank=4)
    reducer = make_comm_hook_reducer("powersgd", (), rank=4)
    reduced, new_st = reducer(g, st)
    s = np.linalg.svd(np.asarray(reduced["w"]), compute_uv=False)
    assert (s[4:] < 1e-4).all(), "compressed grad must be rank-4"
    # error feedback holds the residual (leading per-worker dp axis)
    assert new_st["w"]["e"].shape == (1, 64, 48)
    np.testing.assert_allclose(
        np.asarray(new_st["w"]["e"][0]), np.asarray(g["w"] - reduced["w"]), atol=1e-5
    )


@pytest.mark.parametrize("poison_pattern", ["all_workers", "one_worker"])
def test_powersgd_survives_overflow_step(poison_pattern):
    """fp16 loss scaling x PowerSGD: an overflowing step must skip the param
    update (existing contract) AND leave the hook's error-feedback state
    unpoisoned — training resumes normally afterwards.

    ``one_worker`` poisons a single DP worker's shard: the reducer pmean's
    P/Q, so one worker's inf grads NaN every worker's candidate state —
    workers whose *local* grads stayed finite must still reject it (the
    finite flag is pmin'd across dp axes in ``_comm_hook_step``)."""
    from accelerate_tpu import ParallelismConfig

    _reset()
    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(8, 17), dtype=np.int32)
    acc = Accelerator(
        mixed_precision="fp16",
        kwargs_handlers=[DistributedDataParallelKwargs(comm_hook="powersgd")],
        parallelism_config=ParallelismConfig(dp_replicate_size=8),
    )
    model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
    model, _ = acc.prepare(model, optax.adam(3e-3))

    def loss_fn(params, batch):
        loss = cross_entropy_loss(
            module.apply({"params": params}, batch["x"]), batch["y"]
        )
        # Multiplicative poison: grads genuinely overflow through the inf
        # factor (a constant-branch `where` would have zero gradient and
        # never exercise the overflow path).
        return loss * jnp.where(batch["poison"].sum() > 0, jnp.inf, 1.0)

    step = acc.prepare_train_step(loss_fn)
    from jax.sharding import NamedSharding, PartitionSpec

    bs = NamedSharding(acc.mesh, PartitionSpec(acc.parallelism_config.batch_axes))

    def make_batch(poison_vec):
        return {
            "x": jax.device_put(ids[:, :-1], bs),
            "y": jax.device_put(ids[:, 1:], bs),
            "poison": jax.device_put(np.asarray(poison_vec, np.int32), bs),
        }

    if poison_pattern == "all_workers":
        poison_vec = np.ones((8,), np.int32)
    else:
        # One sample -> one DP worker's shard (batch 8 over dp=8).
        poison_vec = np.zeros((8,), np.int32)
        poison_vec[0] = 1

    state = acc.train_state
    state, _ = step(state, make_batch(poison_vec))  # overflow step
    losses = []
    for _ in range(10):
        state, metrics = step(state, make_batch(np.zeros((8,), np.int32)))
        losses.append(float(np.asarray(metrics["loss"])))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] - 0.3, losses
