"""bench.py must never rot: it is the only path perf evidence reaches the
driver. CPU smoke of the child (tiny config substitution) — asserts the
final JSON row parses, carries the contract fields, and measures something."""

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_bench_child_cpu_smoke():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)  # single CPU device, like a bare bench run
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--child",
         "--oom-level=0", "--budget-s=240"],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rows = [json.loads(l) for l in r.stdout.splitlines() if l.startswith("{")]
    assert rows, r.stdout[-2000:]
    final = rows[-1]
    assert final["event"] == "final"
    assert final["metric"] == "llama_fsdp_train_tokens_per_sec_per_chip"
    assert final["value"] > 0
    assert {"mfu_2048", "params_b", "device_kind", "platform"} <= final.keys()
    # Off-chip the fp8/int8/8192 phases must be skipped, not attempted.
    assert "tok_s_fp8_2048" not in final and "seq8192_error" not in final
    # Telemetry summary rides in every bench row (step-time distribution,
    # recompiles, peak HBM) so rounds stay comparable.
    tel = final.get("telemetry")
    assert tel, f"telemetry summary missing from final row: {final}"
    assert tel["steps"] > 0
    assert tel["step_time_mean_s"] > 0
    assert "recompiles" in tel and "peak_hbm_bytes" in tel
