"""bench.py must never rot: it is the only path perf evidence reaches the
driver. CPU smoke of the child (tiny config substitution) — asserts the
final JSON row parses, carries the contract fields, and measures something."""

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_bench_child_cpu_smoke():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)  # single CPU device, like a bare bench run
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--child",
         "--oom-level=0", "--budget-s=240"],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rows = [json.loads(l) for l in r.stdout.splitlines() if l.startswith("{")]
    assert rows, r.stdout[-2000:]
    final = rows[-1]
    assert final["event"] == "final"
    assert final["metric"] == "llama_fsdp_train_tokens_per_sec_per_chip"
    assert final["value"] > 0
    assert {"mfu_2048", "params_b", "device_kind", "platform"} <= final.keys()
    # Off-chip the fp8/int8/8192 phases must be skipped, not attempted.
    assert "tok_s_fp8_2048" not in final and "seq8192_error" not in final
    # Telemetry summary rides in every bench row (step-time distribution,
    # recompiles, peak HBM) so rounds stay comparable.
    tel = final.get("telemetry")
    assert tel, f"telemetry summary missing from final row: {final}"
    assert tel["steps"] > 0
    assert tel["step_time_mean_s"] > 0
    assert "recompiles" in tel and "peak_hbm_bytes" in tel


def test_supervisor_cpu_fallback_after_dead_probes(monkeypatch, capsys):
    """A relay that stays dead through the probe cap must yield a measured
    CPU-mesh-ladder row with the reason attached — not an error row."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_fallback_test", os.path.join(repo, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    calls = {"children": 0}

    def fake_probe(timeout_s=90, env=None):
        # The device backend hangs forever; the CPU fallback env answers.
        if env is not None and env.get("JAX_PLATFORMS") == "cpu":
            return True, ""
        return False, "timeout"

    def fake_child(cmd, timeout_s, env=None):
        calls["children"] += 1
        assert env is not None and env.get("JAX_PLATFORMS") == "cpu"
        row = {"metric": bench.METRIC, "value": 12.5, "unit": "tok/s/chip",
               "vs_baseline": 0.1, "event": "final"}
        return 0, row, ""

    monkeypatch.setattr(bench, "_backend_probe", fake_probe)
    monkeypatch.setattr(bench, "_run_child_streaming", fake_child)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)

    rc = bench.supervise()
    out = capsys.readouterr().out
    assert rc == 0
    rows = [json.loads(l) for l in out.splitlines() if l.startswith("{")]
    assert any(r.get("event") == "cpu_fallback" for r in rows)
    final = rows[-1]
    assert final["event"] == "final" and final["value"] == 12.5
    assert final["fallback"] == "cpu-mesh-ladder"
    assert "unreachable" in final["fallback_reason"]
    assert calls["children"] == 1, "fallback must not burn extra child attempts"
