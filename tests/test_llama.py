"""Flagship-model tests: forward correctness, TP/FSDP sharded training on the
8-device CPU mesh, scan vs unrolled equivalence."""

import numpy as np
import pytest


def _data(bs=8, seq=32, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, size=(bs, seq + 1), dtype=np.int32)
    return ids[:, :-1], ids[:, 1:]


def test_scan_matches_unrolled():
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    x, _ = _data(bs=2, seq=16)
    cfg_s = LlamaConfig.tiny(scan_layers=True, dtype=jnp.float32)
    cfg_u = LlamaConfig.tiny(scan_layers=False, dtype=jnp.float32)
    m_s = LlamaForCausalLM(cfg_s)
    m_u = LlamaForCausalLM(cfg_u)
    p_s = m_s.init(jax.random.key(0), x)["params"]
    p_u = m_u.init(jax.random.key(0), x)["params"]

    # Copy scanned params (leading layer dim) into the unrolled structure.
    def unroll(tree):
        import jax

        return tree

    blk = p_s["model"]["layers"]["block"]
    for i in range(cfg_u.num_hidden_layers):
        tgt = p_u["model"][f"layers_{i}"]
        src = jax.tree.map(lambda a: a[i], blk)
        p_u["model"][f"layers_{i}"] = src
    p_u["model"]["embed_tokens"] = p_s["model"]["embed_tokens"]
    p_u["model"]["norm"] = p_s["model"]["norm"]
    p_u["lm_head"] = p_s["lm_head"]

    out_s = m_s.apply({"params": p_s}, x)
    out_u = m_u.apply({"params": p_u}, x)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_u), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("topology", ["fsdp", "tp", "fsdp_tp"])
def test_llama_sharded_training_step(topology):
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator, Model, ParallelismConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, cross_entropy_loss, llama_tp_rules
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed

    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    pc = {
        "fsdp": ParallelismConfig(dp_shard_size=8),
        "tp": ParallelismConfig(tp_size=8),
        "fsdp_tp": ParallelismConfig(dp_shard_size=4, tp_size=2),
    }[topology]
    acc = Accelerator(
        parallelism_config=pc,
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size_to_shard=0)
        if "fsdp" in topology
        else None,
    )
    module = LlamaForCausalLM(cfg)
    x, y = _data(bs=8, seq=32, vocab=cfg.vocab_size)
    model = Model.from_flax(
        module, jax.random.key(0), x, tp_rules=llama_tp_rules(cfg.scan_layers) if "tp" in topology else None
    )
    model, opt = acc.prepare(model, optax.adamw(1e-3))

    def loss_fn(params, batch):
        logits = module.apply({"params": params}, batch["x"])
        return cross_entropy_loss(logits, batch["y"])

    step = acc.prepare_train_step(loss_fn, max_grad_norm=1.0)
    state = acc.train_state
    batch = {
        "x": jax.device_put(
            x, jax.sharding.NamedSharding(acc.mesh, jax.sharding.PartitionSpec(pc.batch_axes))
        ),
        "y": jax.device_put(
            y, jax.sharding.NamedSharding(acc.mesh, jax.sharding.PartitionSpec(pc.batch_axes))
        ),
    }
    losses = []
    for _ in range(3):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_tp_params_actually_sharded():
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator, Model, ParallelismConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, llama_tp_rules

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    acc = Accelerator(parallelism_config=ParallelismConfig(tp_size=8))
    module = LlamaForCausalLM(cfg)
    x, _ = _data(bs=8, seq=16, vocab=cfg.vocab_size)
    model = Model.from_flax(module, jax.random.key(0), x, tp_rules=llama_tp_rules(True))
    model, _ = acc.prepare(model, optax.sgd(0.1))
    gate = acc.train_state.params["model"]["layers"]["block"]["mlp"]["gate_proj"]["kernel"]
    spec = gate.sharding.spec
    assert "tp" in str(spec)


def test_fused_cross_entropy_matches_naive():
    """fused (chunked, logits-free) CE == naive logits CE, values and grads."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.models import (
        LlamaConfig, LlamaForCausalLM, cross_entropy_loss, fused_cross_entropy_loss,
    )
    from accelerate_tpu.utils import set_seed

    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 32), dtype=np.int32))
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 32), dtype=np.int32))
    labels = labels.at[0, -3:].set(-100)
    params = module.init(jax.random.key(0), ids)["params"]

    def naive(p):
        return cross_entropy_loss(module.apply({"params": p}, ids), labels)

    def fused(p):
        return fused_cross_entropy_loss(cfg, p, ids, labels, chunk_size=8)

    v0, g0 = jax.value_and_grad(naive)(params)
    v1, g1 = jax.value_and_grad(fused)(params)
    np.testing.assert_allclose(float(v0), float(v1), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5), g0, g1
    )


def test_fused_cross_entropy_tied_embeddings():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.models import (
        LlamaConfig, LlamaForCausalLM, cross_entropy_loss, fused_cross_entropy_loss,
    )

    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native",
                           tie_word_embeddings=True)
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16), dtype=np.int32))
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16), dtype=np.int32))
    params = module.init(jax.random.key(0), ids)["params"]
    naive = cross_entropy_loss(module.apply({"params": params}, ids), labels)
    fused = fused_cross_entropy_loss(cfg, params, ids, labels, chunk_size=8)
    np.testing.assert_allclose(float(naive), float(fused), rtol=1e-6)
