import numpy as np
import pytest


def _indices(it):
    return list(it)


def test_batch_sampler_shard_round_robin():
    from accelerate_tpu.data_loader import BatchSampler, BatchSamplerShard, SequentialSampler

    inner = BatchSampler(SequentialSampler(24), batch_size=4, drop_last=False)
    shards = [
        BatchSamplerShard(inner, num_processes=2, process_index=i, even_batches=True)
        for i in range(2)
    ]
    b0, b1 = _indices(shards[0]), _indices(shards[1])
    assert len(b0) == len(b1) == 3
    # Round-robin: rank0 gets batches 0,2,4; rank1 gets 1,3,5.
    assert b0[0] == [0, 1, 2, 3]
    assert b1[0] == [4, 5, 6, 7]
    # Together they cover everything exactly once.
    flat = sorted(i for b in b0 + b1 for i in b)
    assert flat == list(range(24))


def test_batch_sampler_shard_uneven_even_batches():
    from accelerate_tpu.data_loader import BatchSampler, BatchSamplerShard, SequentialSampler

    # 21 samples, batch 4 → 6 batches, last has 1 sample.
    inner = BatchSampler(SequentialSampler(21), batch_size=4, drop_last=False)
    shards = [
        BatchSamplerShard(inner, num_processes=2, process_index=i, even_batches=True)
        for i in range(2)
    ]
    b0, b1 = _indices(shards[0]), _indices(shards[1])
    assert len(b0) == len(b1)
    for b in b0 + b1:
        assert len(b) == 4


def test_batch_sampler_shard_split_batches():
    from accelerate_tpu.data_loader import BatchSampler, BatchSamplerShard, SequentialSampler

    inner = BatchSampler(SequentialSampler(16), batch_size=8, drop_last=False)
    shards = [
        BatchSamplerShard(inner, num_processes=2, process_index=i, split_batches=True)
        for i in range(2)
    ]
    b0, b1 = _indices(shards[0]), _indices(shards[1])
    assert b0[0] == [0, 1, 2, 3]
    assert b1[0] == [4, 5, 6, 7]
    assert len(b0) == len(b1) == 2


def test_iterable_dataset_shard():
    from accelerate_tpu.data_loader import IterableDatasetShard

    data = list(range(22))
    shards = [
        IterableDatasetShard(data, batch_size=4, num_processes=2, process_index=i)
        for i in range(2)
    ]
    s0, s1 = list(shards[0]), list(shards[1])
    assert len(s0) == len(s1)
    # First window: rank0 gets 0-3, rank1 gets 4-7.
    assert s0[:4] == [0, 1, 2, 3]
    assert s1[:4] == [4, 5, 6, 7]


def test_seedable_random_sampler_resumable():
    from accelerate_tpu.data_loader import SeedableRandomSampler

    s = SeedableRandomSampler(10, seed=5)
    first = list(s)
    s2 = SeedableRandomSampler(10, seed=5)
    assert list(s2) == first
    second = list(s)  # epoch advanced
    assert second != first
    assert sorted(second) == list(range(10))


class _ToyDataset:
    def __init__(self, n=32, dim=4):
        rng = np.random.default_rng(0)
        self.x = rng.normal(size=(n, dim)).astype(np.float32)
        self.y = (self.x.sum(-1) > 0).astype(np.int32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return {"x": self.x[i], "y": self.y[i]}


class _LoaderSpec:
    """Minimal duck-typed 'dataloader' (dataset + batch_size)."""

    def __init__(self, dataset, batch_size, shuffle=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = None
        self.drop_last = False

    def __iter__(self):
        raise NotImplementedError


def test_prepare_data_loader_shards_batches():
    import jax

    from accelerate_tpu import AcceleratorState, prepare_data_loader

    AcceleratorState()  # builds default 8-device dp mesh
    ds = _ToyDataset(n=32)
    dl = prepare_data_loader(_LoaderSpec(ds, batch_size=16))
    batches = list(dl)
    assert len(batches) == 2
    batch = batches[0]
    assert isinstance(batch["x"], jax.Array)
    assert batch["x"].shape == (16, 4)
    # Batch dim sharded over the 8 dp devices.
    assert len(batch["x"].sharding.device_set) == 8


def test_end_of_dataloader_flag():
    from accelerate_tpu import AcceleratorState, GradientState, prepare_data_loader

    AcceleratorState()
    ds = _ToyDataset(n=32)
    dl = prepare_data_loader(_LoaderSpec(ds, batch_size=8), put_on_device=False)
    flags = []
    for _ in dl:
        flags.append(dl.end_of_dataloader)
    assert flags == [False, False, False, True]


def test_drop_last_loader_sets_no_remainder():
    """drop_last loaders never pad, so gather_for_metrics must not trim the
    final (full) batch — regression for the 6-samples-chopped bug where
    remainder was set to len(ds) % batch even though the short batch had
    been dropped."""
    from accelerate_tpu import AcceleratorState, prepare_data_loader

    AcceleratorState()
    ds = _ToyDataset(n=90)  # batch 32 -> 2 full batches kept, 26 dropped
    spec = _LoaderSpec(ds, batch_size=32)
    spec.drop_last = True
    dl = prepare_data_loader(spec, put_on_device=False)
    remainders, sizes = [], []
    for b in dl:
        remainders.append(dl.remainder)
        sizes.append(len(b["x"]))
    assert sizes == [32, 32]
    assert all(r <= 0 for r in remainders), remainders
    # Without drop_last the padded tail IS trimmed via remainder.
    spec2 = _LoaderSpec(ds, batch_size=32)
    dl2 = prepare_data_loader(spec2, put_on_device=False)
    sizes2 = [len(b["x"]) for b in dl2]
    assert sum(sizes2) == 96 and dl2.remainder == 90 % 32


def test_skip_first_batches():
    from accelerate_tpu import AcceleratorState, prepare_data_loader, skip_first_batches

    AcceleratorState()
    ds = _ToyDataset(n=32)
    dl = prepare_data_loader(_LoaderSpec(ds, batch_size=8), put_on_device=False)
    skipped = skip_first_batches(dl, 2)
    assert len(list(skipped)) == 2


def test_dispatcher_single_process():
    from accelerate_tpu import AcceleratorState
    from accelerate_tpu.data_loader import prepare_data_loader

    AcceleratorState()
    ds = _ToyDataset(n=16)
    dl = prepare_data_loader(_LoaderSpec(ds, batch_size=8), dispatch_batches=True, put_on_device=False)
    batches = list(dl)
    assert len(batches) == 2
    assert batches[0]["x"].shape == (8, 4)


def test_dispatch_group_bytes_cap_pinned():
    """The rank-0 broadcast groups leaves up to a byte cap per collective.
    1 MiB keeps the host-side staging buffer (and the window where a
    preemption tears a partially-dispatched group) small; 8 MiB measurably
    stretched time-to-first-batch on pod-slice hosts. Pin it so a future
    bump is a deliberate, benchmarked decision."""
    from accelerate_tpu import AcceleratorState
    from accelerate_tpu.data_loader import prepare_data_loader

    AcceleratorState()
    dl = prepare_data_loader(
        _LoaderSpec(_ToyDataset(n=16), batch_size=8), dispatch_batches=True,
        put_on_device=False,
    )
    assert dl.dispatch_group_bytes == 1 << 20


def test_chaos_corrupt_batch_hook():
    """A chaos ``corrupt_batch`` draw NaN-poisons the float leaves of exactly
    the faulted batch at the device boundary; integer leaves and clean
    batches pass through untouched."""
    import numpy as np

    from accelerate_tpu import AcceleratorState, prepare_data_loader
    from accelerate_tpu.chaos import FaultInjector

    AcceleratorState()

    class _FT:
        def __init__(self):
            self.chaos = FaultInjector(
                seed=0,
                schedule=[{"point": "dataloader_batch",
                           "kind": "corrupt_batch", "tick": 1}],
            )
            self._ticks = 0

        def draw_batch_fault(self):
            tick = self._ticks
            self._ticks += 1
            return self.chaos.draw("dataloader_batch", tick)

    ds = _ToyDataset(n=32)
    dl = prepare_data_loader(_LoaderSpec(ds, batch_size=8), put_on_device=False)
    dl._fault_tolerance = _FT()
    batches = list(dl)
    assert len(batches) == 4
    assert np.isnan(np.asarray(batches[1]["x"])).all()  # the faulted batch
    assert np.asarray(batches[1]["y"]).dtype == np.int32  # ints untouched
    for i in (0, 2, 3):
        assert not np.isnan(np.asarray(batches[i]["x"])).any(), i


@pytest.mark.slow
def test_dispatcher_batch_semantics_multiprocess():
    """Launched 2-process run of test_dispatch: non-split dispatch hands every
    rank a FULL batch_size batch (reference data_loader.py:804-944); split
    hands batch_size/world."""
    import os

    from accelerate_tpu.test_utils import execute_subprocess, get_launch_command

    cmd = get_launch_command(num_processes=2) + [
        "--cpu", "-m", "accelerate_tpu.test_utils.scripts.test_dispatch"
    ]
    out = execute_subprocess(cmd, env={"PYTHONPATH": os.getcwd()})
    assert "TEST_DISPATCH OK" in out


@pytest.mark.slow
def test_uneven_data_loop_multiprocess():
    """Launched 2-process run of test_data_loop (reference:
    test_utils/scripts/test_distributed_data_loop.py): even_batches cycling vs
    truncation and the join_uneven_inputs override."""
    import os

    from accelerate_tpu.test_utils import execute_subprocess, get_launch_command

    cmd = get_launch_command(num_processes=2) + [
        "--cpu", "-m", "accelerate_tpu.test_utils.scripts.test_data_loop"
    ]
    out = execute_subprocess(cmd, env={"PYTHONPATH": os.getcwd()})
    assert "TEST_DATA_LOOP OK" in out
