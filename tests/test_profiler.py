"""Unit tests for profiler.py: the exactly-summing attribution identity,
the one-step lag, flight-ring eviction and per-exit-class dumps, and the
MetricsHub registration/collision/alias/SLO contracts. All host-side —
no devices, no Accelerator; tier-1 fast."""

import json
import os

import pytest

from accelerate_tpu.profiler import (
    COMM_AXES,
    STEP_TERMS,
    TICK_TERMS,
    DeviceTimeProfiler,
    FlightRecorder,
    MetricsHub,
    ProfilerConfig,
    dump_flight,
    exit_class_name,
    find_flight_bundles,
)
from accelerate_tpu.utils.constants import (
    EXIT_CODE_TABLE,
    FLIGHT_DIR_ENV,
    SDC_EXIT_CODE,
    SERVING_CRASH_EXIT_CODE,
)

# A plan artifact shaped like planner.ParallelPlan.to_json_dict() — enough
# for note_plan to price comm terms and bandwidth residuals.
PLAN = {
    "layout": {"dp_shard": 8},
    "n_devices": 8,
    "predicted_step_s": 0.010,
    "breakdown": {
        "compute_s": 0.006,
        "fsdp_comm_s": 0.003,
        "dp_comm_s": 0.0,
        "tp_comm_s": 0.0,
        "cp_comm_s": 0.0,
        "pp_comm_s": 0.0,
        "fsdp_bytes": 1 << 20,
        "step_s": 0.010,
    },
    "bandwidths": {
        "ici_gbps": 100.0,
        "dcn_gbps": 25.0,
        "flops_per_chip": 1e12,
        "mfu": 0.4,
        "collective_efficiency": 0.8,
        "ici_domain": 64,
        "dp_overlap": 0.8,
    },
}


def _profiler(**cfg):
    cfg.setdefault("capture_cost", False)
    return DeviceTimeProfiler(ProfilerConfig(**cfg))


def _sum_terms(rec):
    return sum(rec["terms"].values())


# ---------------------------------------------------------------------------
# attribution identity + lag
# ---------------------------------------------------------------------------


def test_step_terms_sum_exactly_with_plan():
    prof = _profiler()
    prof.note_plan(PLAN)
    prof.note_straggler(0.001)
    for i in range(5):
        prof.on_step(i, wall_s=0.02, data_wait_s=0.002)
    prof.flush()
    recs = prof.records()
    assert len(recs) == 5
    for rec in recs:
        assert rec["kind"] == "step"
        assert set(rec["terms"]) == set(STEP_TERMS)
        assert abs(_sum_terms(rec) - rec["wall_s"]) < 1e-8
        assert rec["terms"]["data_wait_s"] == pytest.approx(0.002)
        assert rec["terms"]["straggler_skew_s"] == pytest.approx(0.001)
    # fsdp is the only active axis: the comm split and bandwidth samples
    # name it and nothing else.
    assert set(recs[0]["comm_axes_s"]) == {"fsdp"}
    assert set(recs[0]["bandwidth"]) == {"fsdp"}
    assert recs[0]["overlap_ratio"] is not None
    summary = prof.summary()
    assert summary["steps"] == 5
    assert set(summary["bandwidth_residuals"]) == {"fsdp"}
    assert summary["bandwidth_residuals"]["fsdp"]["residual_mean"] > 0
    assert summary["overlap_ratio_mean"] is not None


def test_step_terms_without_plan_degrade_to_residual():
    """No plan, no cost: the decomposition keeps the identity with the
    dispatch residual carrying the unattributed wall, and the overlap
    ratio is withheld rather than invented."""
    prof = _profiler()
    prof.on_step(0, wall_s=0.02, data_wait_s=0.0)
    prof.flush()
    (rec,) = prof.records()
    assert abs(_sum_terms(rec) - rec["wall_s"]) < 1e-8
    assert rec["terms"]["device_compute_s"] == 0.0
    assert rec["terms"]["comm_exposed_s"] == 0.0
    assert rec["overlap_ratio"] is None
    assert rec["bandwidth"] is None
    assert prof.summary()["overlap_ratio_mean"] is None


def test_straggler_skew_capped_to_budget_fraction():
    prof = _profiler(max_skew_fraction=0.5)
    prof.note_straggler(10.0)  # a stale spike far beyond the step wall
    prof.on_step(0, wall_s=0.02, data_wait_s=0.0)
    prof.flush()
    (rec,) = prof.records()
    assert rec["terms"]["straggler_skew_s"] == pytest.approx(0.01)
    assert abs(_sum_terms(rec) - rec["wall_s"]) < 1e-8


def test_lagged_fetch_one_step_behind():
    """on_step(N) finalizes N-1; the pending record only lands at flush."""
    prof = _profiler()
    prof.on_step(0, wall_s=0.01, data_wait_s=0.0)
    assert prof.records() == []
    prof.on_step(1, wall_s=0.01, data_wait_s=0.0)
    assert [r["step"] for r in prof.records()] == [0]
    prof.flush()
    assert [r["step"] for r in prof.records()] == [0, 1]
    prof.flush()  # idempotent: nothing pending
    assert len(prof.records()) == 2


def test_tick_terms_sum_with_bookkeeping_residual():
    prof = _profiler()
    sections = {"admit_s": 0.001, "prefill_s": 0.002, "decode_s": 0.003,
                "host_fetch_s": 0.001, "bookkeeping_s": 0.0005}
    for i in range(3):
        prof.on_tick(i, wall_s=0.010, sections=sections)
    prof.flush()
    recs = prof.records()
    assert len(recs) == 3
    for rec in recs:
        assert rec["kind"] == "tick"
        assert set(rec["terms"]) == set(TICK_TERMS)
        assert abs(_sum_terms(rec) - rec["wall_s"]) < 1e-8
        # residual absorbed the unmeasured 2.5ms on top of its section
        assert rec["terms"]["bookkeeping_s"] == pytest.approx(0.003)
    assert prof.summary()["ticks"] == 3


def test_reset_keeps_pricing_drops_records():
    prof = _profiler()
    prof.note_plan(PLAN)
    prof.on_step(0, wall_s=0.02, data_wait_s=0.0)
    prof.flush()
    assert prof.records()
    prof.reset()
    assert prof.records() == []
    assert prof.summary()["steps"] == 0
    prof.on_step(1, wall_s=0.02, data_wait_s=0.0)
    prof.flush()
    (rec,) = prof.records()
    assert rec["comm_axes_s"], "plan pricing must survive reset()"


# ---------------------------------------------------------------------------
# flight ring
# ---------------------------------------------------------------------------


def test_ring_eviction_keeps_newest():
    prof = _profiler(ring_size=4)
    for i in range(10):
        prof.on_step(i, wall_s=0.01, data_wait_s=0.0)
    prof.flush()
    recs = prof.records()
    assert len(recs) == 4
    assert [r["step"] for r in recs] == [6, 7, 8, 9]
    assert prof.summary()["ring"] == {"capacity": 4, "len": 4}
    assert prof.summary()["steps"] == 10  # aggregates ignore eviction


@pytest.mark.parametrize("code,klass", [
    (SERVING_CRASH_EXIT_CODE, "serving-crash"),
    (SDC_EXIT_CODE, "sdc"),
])
def test_flight_dump_per_exit_class(tmp_path, code, klass):
    prof = DeviceTimeProfiler(ProfilerConfig(capture_cost=False),
                              out_dir=str(tmp_path))
    prof.on_step(7, wall_s=0.01, data_wait_s=0.0)
    prof.note_gauge("journal_lsn", 42)
    path = dump_flight(prof, code, reason="test")
    assert path == str(tmp_path / f"flight_{klass}.json")
    with open(path) as f:
        doc = json.load(f)
    assert doc["exit_class"] == klass
    assert doc["reason"] == "test"
    assert doc["gauges"]["journal_lsn"] == 42
    # dump_flight flushed the lagged record: the ring identifies step 7.
    assert doc["entries"][-1]["step"] == 7
    assert prof.summary()["flight_dumps"] == 1


def test_exit_class_name_covers_table():
    for row in EXIT_CODE_TABLE:
        assert exit_class_name(row["code"]) == row["classification"]
    assert exit_class_name(1) == "1"


def test_flight_dir_env_overrides_out_dir(tmp_path, monkeypatch):
    env_dir = tmp_path / "supervisor"
    monkeypatch.setenv(FLIGHT_DIR_ENV, str(env_dir))
    fr = FlightRecorder(out_dir=str(tmp_path / "out"))
    fr.record("step", step=1)
    path = fr.dump("oom")
    assert path == str(env_dir / "flight_oom.json")
    assert find_flight_bundles()[0] == os.path.abspath(path)


def test_dump_flight_respects_flight_off():
    prof = DeviceTimeProfiler(ProfilerConfig(capture_cost=False,
                                             flight=False))
    prof.on_step(0, wall_s=0.01, data_wait_s=0.0)
    assert dump_flight(prof, SERVING_CRASH_EXIT_CODE) is None
    assert dump_flight(None, SERVING_CRASH_EXIT_CODE) is None


# ---------------------------------------------------------------------------
# MetricsHub
# ---------------------------------------------------------------------------


def test_hub_cross_kind_collision_rejected():
    hub = MetricsHub()
    hub.counter("serving_requests_total")
    with pytest.raises(ValueError, match="cross-kind"):
        hub.gauge("serving_requests_total")
    # same-kind re-registration returns the same instrument
    c = hub.counter("serving_requests_total")
    c.inc(3)
    assert "accelerate_tpu_serving_requests_total 3.0" in hub.render()


def test_hub_rejects_malformed_names():
    hub = MetricsHub()
    for bad in ("Caps", "1leading", "dash-ed", ""):
        with pytest.raises(ValueError):
            hub.counter(bad)
        with pytest.raises(ValueError):
            hub.register_provider(bad, dict)


def test_hub_provider_collision_and_replace():
    hub = MetricsHub()
    a = lambda: {"x": 1}  # noqa: E731
    b = lambda: {"x": 2}  # noqa: E731
    hub.register_provider("sub", a)
    hub.register_provider("sub", a)  # same callable: idempotent
    with pytest.raises(ValueError, match="replace=True"):
        hub.register_provider("sub", b)
    hub.register_provider("sub", b, replace=True)
    assert "accelerate_tpu_sub_x 2" in hub.render()


def test_hub_provider_walk_skips_non_numeric():
    hub = MetricsHub()
    hub.register_provider("j", lambda: {
        "appends": 5, "dir": "/tmp/x", "nested": {"ok": True},
        "none": None, "ratio": float("nan")})
    names = hub.metric_names()
    assert names == {"accelerate_tpu_j_appends", "accelerate_tpu_j_nested_ok"}


def test_hub_alias_duplicates_series():
    hub = MetricsHub()
    hub.register_provider("tracing", lambda: {"requests": 4})
    hub.alias("accelerate_tpu_trace_requests",
              "accelerate_tpu_tracing_requests")
    text = hub.render()
    assert "accelerate_tpu_tracing_requests 4" in text
    assert "accelerate_tpu_trace_requests 4" in text


def test_hub_slo_burn_rate():
    hub = MetricsHub()
    with pytest.raises(ValueError):
        hub.register_slo("bad", 1.5)
    hub.register_slo("avail", 0.9, window=100)
    for _ in range(18):
        hub.observe_slo("avail", True)
    for _ in range(2):
        hub.observe_slo("avail", False)
    rec = hub.burn_rates()["avail"]
    assert rec["events"] == 20
    assert rec["error_rate"] == pytest.approx(0.1)
    assert rec["burn_rate"] == pytest.approx(1.0, abs=1e-6)
    assert rec["alert"] is False  # at budget, not over it
    hub.observe_slo("avail", False)
    assert hub.burn_rates()["avail"]["alert"] is True
    names = hub.metric_names()
    assert "accelerate_tpu_slo_avail_burn_rate" in names
    assert "accelerate_tpu_slo_avail_error_rate" in names


def test_profiler_summary_renders_under_profile_subsystem():
    hub = MetricsHub()
    prof = _profiler()
    hub.register_provider("profile", prof.summary)
    prof.on_step(0, wall_s=0.01, data_wait_s=0.0)
    prof.flush()
    names = hub.metric_names()
    assert "accelerate_tpu_profile_steps" in names
    assert "accelerate_tpu_profile_ring_capacity" in names


def test_comm_axes_cover_planner_axes():
    from accelerate_tpu.planner import CostBreakdown

    bd = CostBreakdown()
    for axis in COMM_AXES:
        assert hasattr(bd, f"{axis}_comm_s")
