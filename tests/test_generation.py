"""KV-cache generation: exact parity with the full re-forward loop.

The cached decode path re-implements the Llama block math on raw param trees;
these tests pin it to ``module.apply`` token for token.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Model, generate, init_cache, sample_logits
from accelerate_tpu.generation import _llama_forward_cached
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.utils import set_seed


@pytest.fixture(scope="module")
def llama():
    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 8), dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), ids)
    return cfg, module, model, jnp.asarray(ids)


def test_prefill_logits_match_full_forward(llama):
    cfg, module, model, ids = llama
    cache = init_cache(cfg, ids.shape[0], 32)
    logits, cache = _llama_forward_cached(cfg, model.params, ids, cache)
    full = module.apply({"params": model.params}, ids)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, -1]), rtol=2e-5, atol=2e-5
    )
    assert int(cache.length) == ids.shape[1]


def test_decode_step_matches_full_forward(llama):
    """Incremental decode at position S == column S of a full forward."""
    cfg, module, model, ids = llama
    nxt = jnp.asarray([[7], [11]], jnp.int32)
    cache = init_cache(cfg, 2, 32)
    _, cache = _llama_forward_cached(cfg, model.params, ids, cache)
    step_logits, _ = _llama_forward_cached(cfg, model.params, nxt, cache)
    full = module.apply({"params": model.params}, jnp.concatenate([ids, nxt], 1))
    np.testing.assert_allclose(
        np.asarray(step_logits), np.asarray(full[:, -1]), rtol=2e-5, atol=2e-5
    )


def test_greedy_generate_matches_naive_loop(llama):
    cfg, module, model, ids = llama
    n = 6
    got = generate(model, ids, max_new_tokens=n)
    assert got.shape == (2, ids.shape[1] + n)

    out = ids
    for _ in range(n):
        logits = module.apply({"params": model.params}, out)
        tok = jnp.argmax(logits[:, -1].astype(jnp.float32), -1).astype(jnp.int32)
        out = jnp.concatenate([out, tok[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(out))


def test_generate_eos_padding(llama):
    cfg, module, model, ids = llama
    # Find what greedy emits first, then declare it EOS: everything after
    # must be EOS too.
    first = generate(model, ids, max_new_tokens=1)[:, -1]
    eos = int(first[0])
    got = generate(model, ids, max_new_tokens=5, eos_token_id=eos)
    row = np.asarray(got[0, ids.shape[1]:])
    assert row[0] == eos and (row == eos).all()


def test_generate_sampling_deterministic_with_key(llama):
    cfg, module, model, ids = llama
    a = generate(model, ids, max_new_tokens=4, temperature=0.8, top_k=20,
                 rng=jax.random.key(3))
    b = generate(model, ids, max_new_tokens=4, temperature=0.8, top_k=20,
                 rng=jax.random.key(3))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert jnp.all(a[:, :ids.shape[1]] == ids)


def test_generate_respects_max_positions(llama):
    cfg, module, model, ids = llama
    with pytest.raises(ValueError, match="max_position_embeddings"):
        generate(model, ids, max_new_tokens=cfg.max_position_embeddings)


def test_sample_logits_top_p_masks_tail():
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    # top_p=0.6: keep {0.5, 0.3}; with a key stuck on the tail region the
    # sample must still come from the kept set.
    for seed in range(8):
        tok = int(sample_logits(logits, jax.random.key(seed), temperature=1.0, top_p=0.6)[0])
        assert tok in (0, 1)


def test_sample_logits_top_k():
    logits = jnp.asarray([[1.0, 5.0, 4.0, -2.0]])
    for seed in range(8):
        tok = int(sample_logits(logits, jax.random.key(seed), temperature=1.0, top_k=2)[0])
        assert tok in (1, 2)


def test_gqa_generation_parity():
    """GQA (Hkv < Hq) through the cache == full forward."""
    set_seed(1)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native",
                           num_attention_heads=4, num_key_value_heads=2)
    module = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(1).integers(0, cfg.vocab_size, (1, 5), dtype=np.int32))
    model = Model.from_flax(module, jax.random.key(0), ids)
    got = generate(model, ids, max_new_tokens=4)
    out = ids
    for _ in range(4):
        logits = module.apply({"params": model.params}, out)
        tok = jnp.argmax(logits[:, -1].astype(jnp.float32), -1).astype(jnp.int32)
        out = jnp.concatenate([out, tok[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(out))


def test_gpt2_greedy_generate_matches_naive_loop():
    from accelerate_tpu.models import GPT2Config, GPT2LMHeadModel

    set_seed(2)
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHeadModel(cfg)
    ids = jnp.asarray(np.random.default_rng(2).integers(0, cfg.vocab_size, (2, 6), dtype=np.int32))
    model = Model.from_flax(module, jax.random.key(0), ids)
    got = generate(model, ids, max_new_tokens=5)
    out = ids
    for _ in range(5):
        logits = module.apply({"params": model.params}, out)
        tok = jnp.argmax(logits[:, -1].astype(jnp.float32), -1).astype(jnp.int32)
        out = jnp.concatenate([out, tok[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(out))


def test_hub_model_generates_like_transformers():
    """tiny HF Llama -> convert -> our greedy generate == HF .generate greedy."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from accelerate_tpu.models import model_from_pretrained

    hf_cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg)
    hf.eval()
    ids = np.random.default_rng(3).integers(0, 96, (1, 6)).astype(np.int64)
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(ids), max_new_tokens=5, do_sample=False,
            pad_token_id=0,
        ).numpy()
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    got = generate(ours, ids.astype(np.int32), max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(got), want.astype(np.int32))


def test_generation_config_and_pad_token(llama):
    from accelerate_tpu import GenerationConfig

    cfg, module, model, ids = llama
    first = generate(model, ids, max_new_tokens=1)[:, -1]
    eos = int(first[0])
    got = generate(
        model, ids,
        config=GenerationConfig(max_new_tokens=5, eos_token_id=eos, pad_token_id=9),
    )
    row = np.asarray(got[0, ids.shape[1]:])
    assert row[0] == eos and (row[1:] == 9).all()


def test_opt_greedy_generate_matches_naive_loop():
    from accelerate_tpu.models import OPTConfig, OPTForCausalLM

    set_seed(3)
    cfg = OPTConfig.tiny(dtype=jnp.float32)
    module = OPTForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 6), dtype=np.int32))
    model = Model.from_flax(module, jax.random.key(0), ids)
    got = generate(model, ids, max_new_tokens=5)
    out = ids
    for _ in range(5):
        logits = module.apply({"params": model.params}, out)
        tok = jnp.argmax(logits[:, -1].astype(jnp.float32), -1).astype(jnp.int32)
        out = jnp.concatenate([out, tok[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(out))


def test_neox_greedy_generate_matches_naive_loop():
    from accelerate_tpu.models import GPTNeoXConfig, GPTNeoXForCausalLM

    for parallel in (True, False):
        set_seed(4)
        cfg = GPTNeoXConfig.tiny(dtype=jnp.float32, use_parallel_residual=parallel)
        module = GPTNeoXForCausalLM(cfg)
        ids = jnp.asarray(np.random.default_rng(4).integers(0, cfg.vocab_size, (2, 6), dtype=np.int32))
        model = Model.from_flax(module, jax.random.key(0), ids)
        got = generate(model, ids, max_new_tokens=4)
        out = ids
        for _ in range(4):
            logits = module.apply({"params": model.params}, out)
            tok = jnp.argmax(logits[:, -1].astype(jnp.float32), -1).astype(jnp.int32)
            out = jnp.concatenate([out, tok[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(out))


def test_mixtral_greedy_generate_matches_naive_loop():
    import dataclasses

    from accelerate_tpu.models import MixtralConfig, MixtralForCausalLM

    set_seed(5)
    # High capacity so the GShard training path is dropless too — then the
    # dense decode dispatch and the training forward agree exactly.
    cfg = dataclasses.replace(
        MixtralConfig.tiny(dtype=jnp.float32), capacity_factor=8.0
    )
    module = MixtralForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(5).integers(0, cfg.vocab_size, (1, 5), dtype=np.int32))
    model = Model.from_flax(module, jax.random.key(0), ids)
    got = generate(model, ids, max_new_tokens=4)
    out = ids
    for _ in range(4):
        logits = module.apply({"params": model.params}, out)
        tok = jnp.argmax(logits[:, -1].astype(jnp.float32), -1).astype(jnp.int32)
        out = jnp.concatenate([out, tok[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(out))


def test_beam_search_beam1_equals_greedy(llama):
    from accelerate_tpu import beam_search

    cfg, module, model, ids = llama
    greedy = generate(model, ids, max_new_tokens=5)
    beamed = beam_search(model, ids, max_new_tokens=5, num_beams=1)
    np.testing.assert_array_equal(np.asarray(beamed), np.asarray(greedy))


def test_beam_search_finds_exhaustive_optimum():
    """vocab=16, 2 new tokens, num_beams=16: the beam covers every first
    token, so the result must be the global-logprob argmax (computed by brute
    force over all 256 continuations)."""
    from accelerate_tpu import beam_search
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    set_seed(7)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native", vocab_size=16)
    module = LlamaForCausalLM(cfg)
    ids = jnp.asarray([[3, 1, 4]], jnp.int32)
    model = Model.from_flax(module, jax.random.key(0), ids)

    got = beam_search(model, ids, max_new_tokens=2, num_beams=16, length_penalty=1.0)

    # Brute force: score every (a, b) continuation by summed logprob.
    best_score, best_pair = -np.inf, None
    logits0 = module.apply({"params": model.params}, ids)
    lp0 = np.asarray(jax.nn.log_softmax(logits0[:, -1].astype(jnp.float32), -1))[0]
    for a in range(16):
        ext = jnp.concatenate([ids, jnp.asarray([[a]], jnp.int32)], 1)
        logits1 = module.apply({"params": model.params}, ext)
        lp1 = np.asarray(jax.nn.log_softmax(logits1[:, -1].astype(jnp.float32), -1))[0]
        for bb in range(16):
            sc = lp0[a] + lp1[bb]
            if sc > best_score:
                best_score, best_pair = sc, (a, bb)
    assert tuple(np.asarray(got[0, 3:]).tolist()) == best_pair


def test_beam_search_eos_freezes_and_pads(llama):
    from accelerate_tpu import beam_search

    cfg, module, model, ids = llama
    first = generate(model, ids, max_new_tokens=1)[:, -1]
    eos = int(first[0])
    out = beam_search(model, ids, max_new_tokens=4, num_beams=3, eos_token_id=eos)
    assert out.shape == (2, ids.shape[1] + 4)
    row = np.asarray(out[0, ids.shape[1]:])
    if row[0] == eos:
        assert (row == eos).all()


def test_speculative_generate_exactly_matches_greedy(llama):
    """Draft-accelerated decoding must reproduce the target's greedy output
    bit-for-bit, whatever the draft proposes."""
    from accelerate_tpu import speculative_generate

    cfg, module, model, ids = llama
    prompt = ids[:1]
    want = generate(model, prompt, max_new_tokens=10)

    # Draft 1: the target itself (all proposals accepted — fastest path).
    got_self = speculative_generate(model, model, prompt, max_new_tokens=10)
    np.testing.assert_array_equal(np.asarray(got_self), np.asarray(want))

    # Draft 2: a DIFFERENT tiny model (frequent rejections).
    set_seed(99)
    other = Model.from_flax(
        type(module)(cfg), jax.random.key(99), np.asarray(prompt)
    )
    got_other = speculative_generate(model, other, prompt, max_new_tokens=10,
                                     num_draft_tokens=3)
    np.testing.assert_array_equal(np.asarray(got_other), np.asarray(want))


def test_speculative_generate_eos(llama):
    from accelerate_tpu import speculative_generate

    cfg, module, model, ids = llama
    prompt = ids[:1]
    eos = int(generate(model, prompt, max_new_tokens=1)[0, -1])
    out = speculative_generate(model, model, prompt, max_new_tokens=6, eos_token_id=eos)
    row = np.asarray(out[0, prompt.shape[1]:])
    assert out.shape == (1, prompt.shape[1] + 6)
    assert row[0] == eos and (row == eos).all()


# ---------------------------------------------------------------------------
# Encoder-decoder generation (T5, Whisper) — round-3
# ---------------------------------------------------------------------------


def _tiny_t5(dtype=jnp.float32):
    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration

    cfg = T5Config.tiny(dtype=dtype, num_layers=3)
    module = T5ForConditionalGeneration(cfg)
    rng = np.random.default_rng(0)
    enc_ids = rng.integers(1, cfg.vocab_size, (2, 10)).astype(np.int32)
    params = module.init(jax.random.key(0), enc_ids, enc_ids[:, :4])["params"]
    return Model(module=module, params=params), cfg, enc_ids


def _tiny_whisper(dtype=jnp.float32):
    from accelerate_tpu.models import WhisperConfig, WhisperForConditionalGeneration

    cfg = WhisperConfig.tiny(dtype=dtype)
    module = WhisperForConditionalGeneration(cfg)
    rng = np.random.default_rng(0)
    feats = rng.normal(size=(2, 24, cfg.num_mel_bins)).astype(np.float32)
    dec0 = np.zeros((2, 1), np.int32)
    params = module.init(jax.random.key(0), feats, dec0)["params"]
    return Model(module=module, params=params), cfg, feats


def test_t5_cached_decode_matches_full_forward():
    from accelerate_tpu.generation import _t5_decode, _t5_encode, init_cache

    model, cfg, enc_ids = _tiny_t5()
    rng = np.random.default_rng(1)
    dec_ids = rng.integers(1, cfg.vocab_size, (2, 7)).astype(np.int32)
    full = model.module.apply({"params": model.params}, enc_ids, dec_ids)

    st = _t5_encode(cfg, model.params, enc_ids)
    logits, _ = _t5_decode(
        cfg, model.params, jnp.asarray(dec_ids), init_cache(cfg, 2, 7), st, return_all=True
    )
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full), rtol=2e-5, atol=2e-5)
    # Token-by-token through the cache must agree with teacher forcing.
    c, outs = init_cache(cfg, 2, 7), []
    for t in range(7):
        lg, c = _t5_decode(cfg, model.params, jnp.asarray(dec_ids[:, t : t + 1]), c, st,
                           return_all=True)
        outs.append(lg[:, 0])
    np.testing.assert_allclose(
        np.asarray(jnp.stack(outs, 1)), np.asarray(full), rtol=2e-5, atol=2e-5
    )


def test_whisper_cached_decode_matches_full_forward():
    from accelerate_tpu.generation import _whisper_decode, _whisper_encode, init_cache

    model, cfg, feats = _tiny_whisper()
    rng = np.random.default_rng(1)
    dec_ids = rng.integers(0, cfg.vocab_size, (2, 6)).astype(np.int32)
    full = model.module.apply({"params": model.params}, feats, dec_ids)

    st = _whisper_encode(cfg, model.params, feats)
    logits, _ = _whisper_decode(
        cfg, model.params, jnp.asarray(dec_ids), init_cache(cfg, 2, 6), st, return_all=True
    )
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full), rtol=2e-5, atol=2e-5)


def test_t5_greedy_generate_matches_naive_loop():
    """generate() == argmax loop over the full (uncached) module forward."""
    model, cfg, enc_ids = _tiny_t5()
    n = 6
    got = generate(model, enc_ids, max_new_tokens=n)

    dec = np.full((2, 1), cfg.decoder_start_token_id, np.int32)
    for _ in range(n):
        logits = model.module.apply({"params": model.params}, enc_ids, jnp.asarray(dec))
        nxt = np.asarray(jnp.argmax(logits[:, -1].astype(jnp.float32), -1))[:, None]
        dec = np.concatenate([dec, nxt.astype(np.int32)], axis=1)
    np.testing.assert_array_equal(np.asarray(got), dec)


def test_whisper_greedy_generate_matches_naive_loop():
    model, cfg, feats = _tiny_whisper()
    n = 5
    prompt = np.asarray([[3], [3]], np.int32)  # a forced SOT-style prompt
    got = generate(model, feats, max_new_tokens=n, decoder_input_ids=prompt)

    dec = prompt.copy()
    for _ in range(n):
        logits = model.module.apply({"params": model.params}, feats, jnp.asarray(dec))
        nxt = np.asarray(jnp.argmax(logits[:, -1].astype(jnp.float32), -1))[:, None]
        dec = np.concatenate([dec, nxt.astype(np.int32)], axis=1)
    np.testing.assert_array_equal(np.asarray(got), dec)


def test_t5_beam1_equals_greedy():
    from accelerate_tpu.generation import beam_search

    model, cfg, enc_ids = _tiny_t5()
    greedy = generate(model, enc_ids, max_new_tokens=5)
    beam = beam_search(model, enc_ids, max_new_tokens=5, num_beams=1)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(beam))


def test_t5_beam_search_runs_multi_beam():
    from accelerate_tpu.generation import beam_search

    model, cfg, enc_ids = _tiny_t5()
    out = beam_search(model, enc_ids, max_new_tokens=4, num_beams=3)
    assert out.shape == (2, 1 + 4)


def test_t5_hub_generates_like_transformers():
    """tiny HF T5 -> convert -> our greedy generate == HF .generate greedy."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from accelerate_tpu.models import model_from_pretrained

    hf_cfg = transformers.T5Config(
        vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2, num_heads=4,
        relative_attention_num_buckets=8, relative_attention_max_distance=16,
        decoder_start_token_id=0, pad_token_id=0, eos_token_id=1,
    )
    torch.manual_seed(0)
    hf = transformers.T5ForConditionalGeneration(hf_cfg)
    hf.eval()
    ids = np.random.default_rng(3).integers(2, 96, (2, 8)).astype(np.int64)
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(ids), max_new_tokens=5, do_sample=False, min_length=0,
        ).numpy()
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    got = generate(ours, ids.astype(np.int32), max_new_tokens=5, eos_token_id=1)
    np.testing.assert_array_equal(np.asarray(got)[:, : want.shape[1]], want.astype(np.int32))


def test_whisper_hub_transcribe_parity():
    """tiny HF Whisper -> convert -> our greedy tokens == HF greedy loop over
    its own forward (HF whisper.generate injects task-token logic; the
    forward loop is the precise contract)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from accelerate_tpu.models import model_from_pretrained

    hf_cfg = transformers.WhisperConfig(
        vocab_size=96, num_mel_bins=16, d_model=32, encoder_layers=2,
        decoder_layers=2, encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64, max_source_positions=24,
        max_target_positions=32, pad_token_id=0, bos_token_id=1, eos_token_id=2,
        decoder_start_token_id=1,
    )
    torch.manual_seed(0)
    hf = transformers.WhisperForConditionalGeneration(hf_cfg)
    hf.eval()
    rng = np.random.default_rng(5)
    feats = rng.normal(size=(1, 16, 48)).astype(np.float32)  # HF layout (B, mel, T)
    prompt = np.asarray([[50]], np.int64)
    dec = prompt.copy()
    with torch.no_grad():
        for _ in range(5):
            logits = hf(
                input_features=torch.from_numpy(feats),
                decoder_input_ids=torch.from_numpy(dec),
            ).logits
            nxt = logits[:, -1].argmax(-1, keepdim=True).numpy()
            dec = np.concatenate([dec, nxt], axis=1)

    ours = model_from_pretrained(hf, dtype=jnp.float32)
    got = generate(
        ours, np.transpose(feats, (0, 2, 1)),  # our layout (B, T, mel)
        max_new_tokens=5, decoder_input_ids=prompt.astype(np.int32),
    )
    np.testing.assert_array_equal(np.asarray(got), dec.astype(np.int32))


def test_llama_padded_batch_matches_transformers():
    """Left-padded batch + attention_mask: greedy tokens match HF exactly
    (the first practical thing a migrating user does with generate)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from accelerate_tpu.models import model_from_pretrained

    hf_cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=False, pad_token_id=0,
    )
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg)
    hf.eval()
    rng = np.random.default_rng(7)
    # Row 0: full 6-token prompt. Row 1: 3 tokens, left-padded with 3 zeros.
    row0 = rng.integers(1, 96, (6,))
    row1 = rng.integers(1, 96, (3,))
    ids = np.stack([row0, np.concatenate([[0, 0, 0], row1])]).astype(np.int64)
    mask = np.asarray([[1] * 6, [0, 0, 0, 1, 1, 1]], np.int64)
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(ids), attention_mask=torch.from_numpy(mask),
            max_new_tokens=5, do_sample=False, pad_token_id=0,
        ).numpy()
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    got = generate(
        ours, ids.astype(np.int32), max_new_tokens=5,
        attention_mask=mask.astype(np.int32),
    )
    np.testing.assert_array_equal(np.asarray(got), want.astype(np.int32))


def test_padded_batch_matches_unpadded_row():
    """A left-padded row must generate the same tokens as the same prompt
    alone (padding must be invisible)."""
    llama_model, cfg, _ = _tiny_llama_for_pad()
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, cfg.vocab_size, (1, 4)).astype(np.int32)
    alone = generate(llama_model, prompt, max_new_tokens=6)

    padded = np.concatenate([np.zeros((1, 3), np.int32), prompt], axis=1)
    mask = np.asarray([[0, 0, 0, 1, 1, 1, 1]], np.int32)
    batched = generate(llama_model, padded, max_new_tokens=6, attention_mask=mask)
    np.testing.assert_array_equal(np.asarray(batched)[:, 7:], np.asarray(alone)[:, 4:])


def _tiny_llama_for_pad():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = LlamaForCausalLM(cfg)
    ids = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % cfg.vocab_size
    model = Model.from_flax(module, jax.random.key(0), ids)
    return model, cfg, ids


def test_right_padded_mask_rejected():
    llama_model, cfg, ids = _tiny_llama_for_pad()
    bad = np.asarray([[1] * 8, [1, 1, 1, 1, 1, 0, 0, 0]], np.int32)
    with pytest.raises(ValueError, match="left-padded"):
        generate(llama_model, ids, max_new_tokens=2, attention_mask=bad)


@pytest.mark.parametrize("family", ["gpt2", "opt", "neox", "mixtral"])
def test_padded_batch_invisible_all_causal_families(family):
    """Left-padding must be invisible for every causal plan, not just Llama."""
    set_seed(11)
    if family == "gpt2":
        from accelerate_tpu.models import GPT2Config, GPT2LMHeadModel

        cfg = GPT2Config.tiny(dtype=jnp.float32)
        module = GPT2LMHeadModel(cfg)
    elif family == "opt":
        from accelerate_tpu.models import OPTConfig, OPTForCausalLM

        cfg = OPTConfig.tiny(dtype=jnp.float32)
        module = OPTForCausalLM(cfg)
    elif family == "neox":
        from accelerate_tpu.models import GPTNeoXConfig, GPTNeoXForCausalLM

        cfg = GPTNeoXConfig.tiny(dtype=jnp.float32)
        module = GPTNeoXForCausalLM(cfg)
    else:
        from accelerate_tpu.models import MixtralConfig, MixtralForCausalLM

        cfg = MixtralConfig.tiny(dtype=jnp.float32)
        module = MixtralForCausalLM(cfg)

    rng = np.random.default_rng(11)
    prompt = rng.integers(1, cfg.vocab_size, (1, 4)).astype(np.int32)
    model = Model.from_flax(module, jax.random.key(0), prompt)
    alone = generate(model, prompt, max_new_tokens=4)

    padded = np.concatenate([np.zeros((1, 2), np.int32), prompt], axis=1)
    mask = np.asarray([[0, 0, 1, 1, 1, 1]], np.int32)
    batched = generate(model, padded, max_new_tokens=4, attention_mask=mask)
    np.testing.assert_array_equal(np.asarray(batched)[:, 6:], np.asarray(alone)[:, 4:])


def test_generate_reuses_compiled_loop(llama):
    """Repeated generate() calls with identical settings must reuse ONE
    compiled loop (closures used to defeat jit's cache — a full recompile
    per call)."""
    from accelerate_tpu import generation as G

    cfg, module, model, ids = llama
    G._GEN_LOOP_CACHE.clear()
    a = generate(model, ids, max_new_tokens=3)
    assert len(G._GEN_LOOP_CACHE) == 1
    b = generate(model, ids, max_new_tokens=3)
    assert len(G._GEN_LOOP_CACHE) == 1  # same key -> same compiled loop
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    generate(model, ids, max_new_tokens=4)  # different settings -> new entry
    assert len(G._GEN_LOOP_CACHE) == 2


def test_suppress_tokens_matches_transformers():
    """suppress_tokens / begin_suppress_tokens: greedy parity with HF."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from accelerate_tpu.models import model_from_pretrained

    hf_cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg)
    hf.eval()
    ids = np.random.default_rng(3).integers(0, 96, (1, 6)).astype(np.int64)
    # Suppress whatever unconstrained greedy picks first, to force divergence.
    with torch.no_grad():
        free = hf.generate(torch.from_numpy(ids), max_new_tokens=1, do_sample=False,
                           pad_token_id=0).numpy()
    banned = int(free[0, -1])
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(ids), max_new_tokens=5, do_sample=False, pad_token_id=0,
            suppress_tokens=[banned], begin_suppress_tokens=[(banned + 1) % 96],
        ).numpy()
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    got = generate(
        ours, ids.astype(np.int32), max_new_tokens=5,
        suppress_tokens=(banned,), begin_suppress_tokens=((banned + 1) % 96,),
    )
    np.testing.assert_array_equal(np.asarray(got), want.astype(np.int32))
    assert banned not in np.asarray(got)[0, 6:]


def test_forced_decoder_ids_whisper_style():
    """forced_decoder_ids pin tokens at absolute decoder positions (HF
    Whisper's [(1, lang), (2, task)] convention); the rest decode greedily."""
    model, cfg, feats = _tiny_whisper()
    prompt = np.asarray([[7], [7]], np.int32)  # decoder position 0
    forced = ((1, 40), (2, 41))
    got = generate(
        model, feats, max_new_tokens=5, decoder_input_ids=prompt,
        forced_decoder_ids=forced,
    )
    out = np.asarray(got)
    assert (out[:, 1] == 40).all() and (out[:, 2] == 41).all()

    # Positions 3+ must continue greedily FROM the forced prefix: the tail
    # equals unforced greedy decoding seeded with [7, 40, 41].
    seeded = generate(
        model, feats, max_new_tokens=3,
        decoder_input_ids=np.asarray([[7, 40, 41], [7, 40, 41]], np.int32),
    )
    np.testing.assert_array_equal(out[:, 3:], np.asarray(seeded)[:, 3:])
