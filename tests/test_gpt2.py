"""GPT-2 family (models/gpt2.py): shapes, causality, tied head, TP, training."""

import jax
import jax.numpy as jnp
import numpy as np


def test_lm_head_shapes_and_tying():
    from accelerate_tpu.models import GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHeadModel(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 12), dtype=np.int32)
    params = module.init(jax.random.key(0), ids)["params"]
    logits = module.apply({"params": params}, ids)
    assert logits.shape == (2, 12, cfg.vocab_size)
    # Tied head: no separate lm_head kernel exists.
    assert "lm_head" not in params


def test_causality():
    """Changing a future token never changes past logits."""
    from accelerate_tpu.models import GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHeadModel(cfg)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.vocab_size, size=(1, 10), dtype=np.int32)
    params = module.init(jax.random.key(0), ids)["params"]
    out1 = np.asarray(module.apply({"params": params}, ids))
    ids2 = ids.copy()
    ids2[0, -1] = (ids2[0, -1] + 5) % cfg.vocab_size
    out2 = np.asarray(module.apply({"params": params}, ids2))
    np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], rtol=1e-5, atol=1e-6)
    assert not np.allclose(out1[:, -1], out2[:, -1])


def test_gpt2_tp_matches_single_device():
    import optax

    from accelerate_tpu import Accelerator, Model, ParallelismConfig
    from accelerate_tpu.models import GPT2Config, GPT2LMHeadModel, gpt2_tp_rules
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils import set_seed

    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHeadModel(cfg)
    rng = np.random.default_rng(2)
    ids = rng.integers(0, cfg.vocab_size, size=(4, 16), dtype=np.int32)

    def run(pc, tp):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        set_seed(0)
        acc = Accelerator(parallelism_config=pc)
        model = Model.from_flax(
            module, jax.random.key(0), ids,
            tp_rules=gpt2_tp_rules(cfg.scan_layers) if tp else None,
        )
        model, _ = acc.prepare(model, optax.sgd(1e-2))
        return np.asarray(model(ids), np.float32)

    ref = run(ParallelismConfig(dp_shard_size=8), tp=False)
    tp = run(ParallelismConfig(dp_shard_size=4, tp_size=2), tp=True)
    np.testing.assert_allclose(ref, tp, rtol=1e-4, atol=1e-4)


def test_gpt2_trains():
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import GPT2Config, GPT2LMHeadModel, cross_entropy_loss
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils import set_seed

    AcceleratorState._reset_state()
    GradientState._reset_state()
    set_seed(0)
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    module = GPT2LMHeadModel(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(8, 17), dtype=np.int32)
    acc = Accelerator()
    model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
    model, _ = acc.prepare(model, optax.adam(1e-3))

    def loss_fn(params, b):
        return cross_entropy_loss(module.apply({"params": params}, b["x"]), b["y"])

    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    b = {"x": ids[:, :-1], "y": ids[:, 1:]}
    losses = []
    for _ in range(10):
        state, m = step(state, b)
        losses.append(float(np.asarray(m["loss"])))
    assert losses[-1] < losses[0], losses
