"""GPT-NeoX family: shapes, parallel residual, TP sharding, HF logit parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Model
from accelerate_tpu.models import GPTNeoXConfig, GPTNeoXForCausalLM, neox_tp_rules
from accelerate_tpu.utils import set_seed


def test_neox_forward_shape():
    set_seed(0)
    cfg = GPTNeoXConfig.tiny()
    module = GPTNeoXForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 12), dtype=np.int32))
    params = module.init(jax.random.key(0), ids)["params"]
    logits = module.apply({"params": params}, ids)
    assert logits.shape == (2, 12, cfg.vocab_size)


def test_neox_tp_sharded_logits_match():
    import optax

    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    set_seed(0)
    cfg = GPTNeoXConfig.tiny(dtype=jnp.float32)
    module = GPTNeoXForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(1).integers(0, cfg.vocab_size, (4, 8), dtype=np.int32))
    single = Model.from_flax(module, jax.random.key(0), ids)
    want = np.asarray(single(ids))

    acc = Accelerator(parallelism_config=ParallelismConfig(tp_size=4, dp_shard_size=2))
    model = Model.from_flax(module, jax.random.key(0), ids, tp_rules=neox_tp_rules())
    model, _ = acc.prepare(model, optax.adam(1e-3))
    np.testing.assert_allclose(np.asarray(model(ids)), want, rtol=2e-4, atol=2e-4)
    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()


@pytest.mark.parametrize("parallel_residual", [True, False])
def test_neox_hf_logit_parity(parallel_residual):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from accelerate_tpu.models import model_from_pretrained

    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=3, num_attention_heads=4,
        intermediate_size=128, rotary_pct=0.25, max_position_embeddings=64,
        use_parallel_residual=parallel_residual,
    )
    torch.manual_seed(0)
    hf = transformers.GPTNeoXForCausalLM(hf_cfg)
    hf.eval()
    ids = np.random.default_rng(0).integers(0, 128, (2, 10)).astype(np.int64)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    got = np.asarray(ours(jnp.asarray(ids.astype(np.int32))))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)
