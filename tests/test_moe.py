"""MoE / expert parallelism: dispatch correctness + ep-sharded training.

The reference only reaches EP through Megatron/DeepSpeed engines
(SURVEY.md §2.3 EP row); here the MoE layer is first-class, so we can check
the dense GShard dispatch against a naive per-token loop exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, Model, ParallelismConfig
from accelerate_tpu.models import (
    MixtralConfig,
    MixtralForCausalLM,
    mixtral_tp_rules,
    moe_cross_entropy_loss,
)
from accelerate_tpu.models.moe import compute_dispatch, load_balance_loss
from accelerate_tpu.utils import FullyShardedDataParallelPlugin, set_seed


def test_compute_dispatch_matches_naive():
    """Dense dispatch/combine == per-token top-k loop when capacity is ample."""
    rng = np.random.default_rng(0)
    T, E, k, C = 16, 4, 2, 16  # capacity = T → nothing drops
    probs = jax.nn.softmax(jnp.asarray(rng.normal(size=(T, E))), axis=-1)
    dispatch, combine = compute_dispatch(probs, k, C)
    dispatch = np.asarray(dispatch)
    combine = np.asarray(combine)

    probs_np = np.asarray(probs)
    for t in range(T):
        top = np.argsort(-probs_np[t])[:k]
        w = probs_np[t][top] / probs_np[t][top].sum()
        # Each selected expert holds exactly one slot for token t with its weight.
        for e in range(E):
            if e in top:
                assert dispatch[t, e].sum() == 1.0
                np.testing.assert_allclose(
                    combine[t, e].sum(), w[list(top).index(e)], rtol=1e-5
                )
            else:
                assert dispatch[t, e].sum() == 0.0
    # No expert slot double-booked.
    for e in range(E):
        assert (dispatch[:, e, :].sum(0) <= 1.0).all()


def test_dispatch_respects_capacity():
    T, E, k, C = 8, 2, 1, 2
    probs = jnp.tile(jnp.asarray([[0.9, 0.1]]), (T, 1))  # all tokens pick expert 0
    dispatch, _ = compute_dispatch(probs, k, C)
    assert float(dispatch[:, 0].sum()) == C  # only C tokens land
    assert float(dispatch[:, 1].sum()) == 0.0
    assert float(load_balance_loss(probs, dispatch)) > 0.0


def test_mixtral_forward_and_grads():
    set_seed(0)
    cfg = MixtralConfig.tiny(dtype=jnp.float32)
    module = MixtralForCausalLM(cfg)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 16), dtype=np.int32))
    params = module.init(jax.random.key(0), ids)["params"]
    logits = module.apply({"params": params}, ids)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()

    def loss(p):
        return moe_cross_entropy_loss(module, p, ids[:, :-1], ids[:, 1:])

    val, grads = jax.value_and_grad(loss)(params)
    assert np.isfinite(float(val))
    # Router and expert weights receive gradient.
    g = grads["model"]["layers"]["block"]["moe"]
    for name in ("router", "w_gate", "w_up", "w_down"):
        assert float(jnp.abs(jax.tree.leaves(g[name])[0]).sum()) > 0.0, name


def test_ep_sharded_train_step():
    """ep=4 over dp_shard=4 (× tp=2 = all 8 devices): expert dim sharded,
    step runs, loss drops."""
    set_seed(0)
    pc = ParallelismConfig(dp_shard_size=4, tp_size=2, ep_size=4)
    assert pc.ep_axes == ("dp_shard",)
    cfg = MixtralConfig.tiny(dtype=jnp.float32)
    module = MixtralForCausalLM(cfg)
    rng = np.random.default_rng(2)
    ids = rng.integers(0, cfg.vocab_size, size=(8, 16), dtype=np.int32)

    acc = Accelerator(
        parallelism_config=pc,
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size_to_shard=0),
    )
    model = Model.from_flax(
        module, jax.random.key(0), ids,
        tp_rules=mixtral_tp_rules(True, ep_axes=pc.ep_axes),
    )
    model, _ = acc.prepare(model, optax.adamw(1e-2))

    moe_shardings = acc.state_shardings.params["model"]["layers"]["block"]["moe"]
    spec = moe_shardings["w_gate"].spec
    assert spec[1] == "dp_shard", f"expert dim should shard over ep axes, got {spec}"

    def loss_fn(params, batch):
        return moe_cross_entropy_loss(module, params, batch["x"], batch["y"])

    step = acc.prepare_train_step(loss_fn, max_grad_norm=1.0)
    from jax.sharding import NamedSharding, PartitionSpec

    bs = NamedSharding(acc.mesh, PartitionSpec(pc.batch_axes))
    batch = {
        "x": jax.device_put(jnp.asarray(ids[:, :-1]), bs),
        "y": jax.device_put(jnp.asarray(ids[:, 1:]), bs),
    }
    state = acc.train_state
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(np.asarray(metrics["loss"])))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], f"loss should drop: {losses}"


def test_ep_axes_validation():
    with pytest.raises(ValueError):
        ParallelismConfig(dp_shard_size=4, ep_size=8).ep_axes  # 8 not a product
    assert ParallelismConfig(dp_shard_size=4, ep_size=4).ep_axes == ("dp_shard",)
    assert ParallelismConfig(ep_size=1).ep_axes == ()
