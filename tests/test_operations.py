import numpy as np
import pytest


def test_recursively_apply_nested():
    from accelerate_tpu.utils import recursively_apply

    data = {"a": np.ones((2, 2)), "b": [np.zeros(3), (np.ones(1),)]}
    out = recursively_apply(lambda t: t + 1, data)
    assert np.allclose(out["a"], 2)
    assert np.allclose(out["b"][1][0], 2)


def test_honor_type_namedtuple():
    import collections

    from accelerate_tpu.utils import recursively_apply

    Point = collections.namedtuple("Point", ["x", "y"])
    p = Point(np.ones(2), np.zeros(2))
    out = recursively_apply(lambda t: t * 3, p)
    assert isinstance(out, Point)
    assert np.allclose(out.x, 3)


def test_gather_single_process():
    from accelerate_tpu.utils import gather
    import jax.numpy as jnp

    out = gather({"x": jnp.arange(4)})
    assert np.allclose(out["x"], np.arange(4))


def test_gather_object_single():
    from accelerate_tpu.utils import gather_object

    assert gather_object({"k": 1}) == [{"k": 1}]


def test_pad_across_processes():
    from accelerate_tpu.utils import pad_across_processes
    import jax.numpy as jnp

    t = jnp.ones((2, 3))
    out = pad_across_processes(t, dim=1)
    assert out.shape == (2, 3)  # single process: no growth


def test_find_batch_size_and_slice():
    from accelerate_tpu.utils import find_batch_size, slice_tensors

    batch = {"input_ids": np.ones((8, 16)), "labels": np.ones(8)}
    assert find_batch_size(batch) == 8
    sliced = slice_tensors(batch, 2, 5)
    assert sliced["input_ids"].shape == (3, 16)


def test_concatenate():
    from accelerate_tpu.utils import concatenate

    batches = [{"x": np.ones((2, 4))}, {"x": np.zeros((3, 4))}]
    out = concatenate(batches)
    assert out["x"].shape == (5, 4)


def test_get_data_structure_initialize():
    from accelerate_tpu.utils import get_data_structure, initialize_tensors

    data = {"a": np.ones((2, 3), dtype=np.float32)}
    info = get_data_structure(data)
    out = initialize_tensors(info)
    assert out["a"].shape == (2, 3)


def test_flatten_unflatten_state_dict():
    from accelerate_tpu.utils import flatten_state_dict, unflatten_state_dict

    tree = {"layer": {"kernel": np.ones((2, 2)), "bias": np.zeros(2)}, "scale": np.ones(1)}
    flat = flatten_state_dict(tree)
    assert set(flat) == {"layer/kernel", "layer/bias", "scale"}
    rt = unflatten_state_dict(flat)
    assert np.allclose(rt["layer"]["kernel"], tree["layer"]["kernel"])


def test_shard_state_dict_index():
    from accelerate_tpu.utils import shard_state_dict

    sd = {f"w{i}": np.ones(100, dtype=np.float32) for i in range(10)}
    named, index = shard_state_dict(sd, max_shard_size=500)
    assert index is not None
    assert sum(len(s) for s in named.values()) == 10


def test_set_seed_deterministic():
    from accelerate_tpu.utils import next_rng_key, set_seed

    import jax

    set_seed(42)
    k1 = jax.random.key_data(next_rng_key("dropout"))
    set_seed(42)
    k2 = jax.random.key_data(next_rng_key("dropout"))
    assert np.array_equal(k1, k2)
    k3 = jax.random.key_data(next_rng_key("dropout"))
    assert not np.array_equal(k2, k3)


def test_convert_bytes_parse_bytes():
    from accelerate_tpu.utils import convert_bytes, parse_bytes

    assert parse_bytes("5GB") == 5 * 10**9
    assert parse_bytes("1KiB") == 1024
    assert "KB" in convert_bytes(2048)


@pytest.mark.slow
def test_ops_multiprocess_shape_preservation():
    """Launched 2-process run of the test_ops assertion script (reference:
    test_utils/scripts/test_ops.py) — 0-d/1-d/nested leaves keep their shapes
    through reduce/broadcast/gather/pad/to_global_host."""
    import os

    from accelerate_tpu.test_utils import execute_subprocess, get_launch_command

    cmd = get_launch_command(num_processes=2) + [
        "--cpu", "-m", "accelerate_tpu.test_utils.scripts.test_ops"
    ]
    out = execute_subprocess(cmd, env={"PYTHONPATH": os.getcwd()})
    assert "TEST_OPS OK" in out


@pytest.mark.slow
def test_metrics_multiprocess():
    """Launched 2-process gather_for_metrics remainder-trim check (reference:
    test_utils/scripts/external_deps/test_metrics.py)."""
    import os

    from accelerate_tpu.test_utils import execute_subprocess, get_launch_command

    cmd = get_launch_command(num_processes=2) + [
        "--cpu", "-m", "accelerate_tpu.test_utils.scripts.test_metrics"
    ]
    out = execute_subprocess(cmd, env={"PYTHONPATH": os.getcwd(), "XLA_FLAGS": ""})
    assert "TEST_METRICS OK" in out


def test_tqdm_main_process_only():
    """utils.tqdm: silent off the main process, live on it (reference:
    utils/tqdm.py main_process_only contract)."""
    from accelerate_tpu import PartialState
    from accelerate_tpu.utils import tqdm

    PartialState()  # single process: IS main
    bar = tqdm(range(3), main_process_only=True)
    assert bar.disable is False
    assert list(bar) == [0, 1, 2]
    bar2 = tqdm(range(3), disable=True)
    assert bar2.disable is True
