"""ViT family: shapes, scan parity, TP sharding, HF logit parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Model
from accelerate_tpu.models import ViTConfig, ViTForImageClassification, vit_tp_rules
from accelerate_tpu.utils import set_seed


def _imgs(n=2, size=32, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=(n, size, size, 3)).astype(np.float32)
    )


def test_vit_forward_shape():
    set_seed(0)
    cfg = ViTConfig.tiny()
    module = ViTForImageClassification(cfg)
    x = _imgs()
    variables = module.init(jax.random.key(0), x)
    logits = module.apply(variables, x)
    assert logits.shape == (2, cfg.num_labels)
    assert logits.dtype == jnp.float32


def test_vit_scan_matches_unrolled():
    set_seed(0)
    x = _imgs()
    outs = []
    for scan in (True, False):
        cfg = ViTConfig.tiny(dtype=jnp.float32, scan_layers=scan)
        module = ViTForImageClassification(cfg)
        params = module.init(jax.random.key(0), x)["params"]
        outs.append((module, params))
    scan_module, scan_params = outs[0]
    unroll_module, unroll_params = outs[1]
    # Restack the unrolled layer params into the scan layout for identical weights.
    stacked = jax.tree.map(
        lambda *leaves: jnp.stack(leaves),
        *[unroll_params["vit"][f"layer_{i}"] for i in range(2)],
    )
    scan_params_same = dict(scan_params)
    vit = dict(scan_params["vit"])
    vit["layers"] = {"block": stacked}
    for k in ("cls_token", "position_embeddings", "patch_embed", "ln_final"):
        vit[k] = unroll_params["vit"][k]
    scan_params_same["vit"] = vit
    scan_params_same["classifier"] = unroll_params["classifier"]
    a = scan_module.apply({"params": scan_params_same}, x)
    b = unroll_module.apply({"params": unroll_params}, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_vit_tp_sharded_logits_match():
    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    import optax

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    set_seed(0)
    cfg = ViTConfig.tiny(dtype=jnp.float32)
    module = ViTForImageClassification(cfg)
    x = _imgs(4)
    single = Model.from_flax(module, jax.random.key(0), x)
    want = np.asarray(single(x))

    acc = Accelerator(parallelism_config=ParallelismConfig(tp_size=4, dp_shard_size=2))
    model = Model.from_flax(module, jax.random.key(0), x, tp_rules=vit_tp_rules())
    model, _ = acc.prepare(model, optax.adam(1e-3))
    got = np.asarray(model(x))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()


def test_vit_hf_logit_parity():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from accelerate_tpu.models import model_from_pretrained

    hf_cfg = transformers.ViTConfig(
        image_size=32, patch_size=8, hidden_size=64, num_hidden_layers=3,
        num_attention_heads=4, intermediate_size=128, num_labels=5,
    )
    torch.manual_seed(0)
    hf = transformers.ViTForImageClassification(hf_cfg)
    hf.eval()
    x = np.random.default_rng(0).normal(size=(2, 3, 32, 32)).astype(np.float32)
    with torch.no_grad():
        want = hf(torch.from_numpy(x)).logits.numpy()
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    got = np.asarray(ours(jnp.asarray(x.transpose(0, 2, 3, 1))))  # NCHW → NHWC
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
