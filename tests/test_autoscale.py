"""Autoscaling (autoscale.py + the serving rolling window): config
validation, the bounded-window SLO signals (serving.py ``window_stats``),
deterministic policy units over a fake engine (hysteresis bands,
consecutive-breach + cooldown flap damping, planner refusals, the resize
budget, dead-device shrinks, injected flap/spike faults), the live-resize
integration on the real disagg engine (a mid-flight grow stays bit-equal
to a fixed-topology reference; persistent injected ``resize_transfer``
faults abort cleanly back to the old layout), telemetry wiring, and the
off-unless-constructed Accelerator factory. CPU-only on the forced
8-device host platform, tier-1."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import (
    AutoscaleConfig,
    AutoscaleController,
    DisaggConfig,
    DisaggServingEngine,
    FaultInjector,
    Model,
    ServingConfig,
    ServingEngine,
    make_diurnal_trace,
)
from accelerate_tpu.planner import plan_disagg_slices
from accelerate_tpu.utils import set_seed


@pytest.fixture(scope="module")
def llama():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    set_seed(0)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="native")
    module = LlamaForCausalLM(cfg)
    probe = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 8),
                                              dtype=np.int32)
    model = Model.from_flax(module, jax.random.key(0), probe)
    return cfg, model


def _prompts(cfg, lengths, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, (n,), dtype=np.int32)
            for n in lengths]


# ---------------------------------------------------------------------------
# A policy-level fake: the controller sees a `resize`-capable engine whose
# window signals the test scripts directly. Mirrors the real engine's
# re-plan-on-resize so ratio drift actually clears after a re-split.
# ---------------------------------------------------------------------------


class _FakePlan:
    flop_ratio = 2.0
    n_prefill = 2


class _FakeEngine:
    def __init__(self, devices):
        self._devices = list(devices)
        self._stats = {"ticks": 0}
        self.slice_plan = _FakePlan()
        self.window = dict(requests=16, capacity=32, ok=16, ttft_p50_s=0.01,
                           ttft_p95_s=0.02, tpot_p50_s=0.001,
                           tpot_p95_s=0.002, shed_rate=0.0, timeout_rate=0.0,
                           failed_rate=0.0, queue_depth_p95=2.0,
                           prompt_decode_ratio=2.0)
        self.resize_calls = []
        self.resize_ok = True

    def window_stats(self):
        return dict(self.window)

    def resize(self, devices=None, *, n_prefill=None, flop_ratio=None,
               dead_devices=()):
        self.resize_calls.append((list(devices), flop_ratio,
                                  set(dead_devices)))
        if not self.resize_ok:
            return {"ok": False, "reason": "injected abort",
                    "seq": len(self.resize_calls)}
        self._devices = list(devices)
        if flop_ratio is not None:
            plan = plan_disagg_slices(len(self._devices),
                                      prefill_decode_flop_ratio=flop_ratio)
            p = _FakePlan()
            p.flop_ratio, p.n_prefill = plan.flop_ratio, plan.n_prefill
            self.slice_plan = p
        return {"ok": True, "seq": len(self.resize_calls),
                "layout_id": len(self.resize_calls),
                "n_devices": len(self._devices), "n_prefill": 1,
                "n_decode": len(self._devices) - 1, "flop_ratio": flop_ratio,
                "rebound": 0, "retried": 0, "draining": 0, "moved_bytes": 0}


_POOL = [f"dev{i}" for i in range(8)]


def _controller(n_start=4, pool=None, chaos=None, **over):
    kw = dict(poll_ticks=4, window_min_requests=8, breach_samples=2,
              cooldown_ticks=16, queue_depth_high=4.0, queue_depth_low=0.5)
    kw.update(over)
    eng = _FakeEngine((pool or _POOL)[:n_start])
    auto = AutoscaleController(eng, AutoscaleConfig(**kw),
                               device_pool=pool or _POOL, chaos=chaos)
    return eng, auto


def _run(eng, auto, ticks):
    for _ in range(ticks):
        eng._stats["ticks"] += 1
        auto.poll()


# ---------------------------------------------------------------------------
# Config + trace (pure)
# ---------------------------------------------------------------------------


def test_autoscale_config_validation():
    AutoscaleConfig()  # defaults are valid
    for bad in [dict(poll_ticks=0), dict(window_min_requests=0),
                dict(queue_depth_low=5.0, queue_depth_high=4.0),
                dict(queue_depth_low=-1.0), dict(shed_rate_high=-0.1),
                dict(breach_samples=0), dict(cooldown_ticks=-1),
                dict(resplit_tolerance=0.0), dict(min_devices=1),
                dict(max_devices=1), dict(max_resizes=-1),
                dict(ttft_p95_slo_s=0.0)]:
        with pytest.raises(ValueError):
            AutoscaleConfig(**bad)


def test_autoscale_requires_resizable_engine():
    class NoResize:
        _devices = _POOL[:2]

    with pytest.raises(ValueError, match="resize"):
        AutoscaleController(NoResize())
    # The pool must cover the active set.
    with pytest.raises(ValueError, match="pool"):
        AutoscaleController(_FakeEngine(_POOL[:4]), device_pool=_POOL[4:])


def test_make_diurnal_trace_deterministic_and_diurnal():
    t1 = make_diurnal_trace(64, seed=5)
    t2 = make_diurnal_trace(64, seed=5)
    assert np.array_equal(t1["arrivals"], t2["arrivals"])
    assert all(np.array_equal(a, b)
               for a, b in zip(t1["prompts"], t2["prompts"]))
    assert t1["budgets"] == t2["budgets"]
    assert not np.array_equal(t1["arrivals"],
                              make_diurnal_trace(64, seed=6)["arrivals"])
    ph = np.asarray(t1["phases"])
    assert set(ph.tolist()) == {0, 1, 2}
    # The high plateau arrives ~10x faster and sends longer prompts with
    # smaller budgets (the prompt:decode mix shifts with the load).
    gaps = np.diff(t1["arrivals"])
    assert np.mean(gaps[ph[1:] == 1]) < np.mean(gaps[ph[1:] == 0])
    mean_len = lambda f: np.mean(  # noqa: E731
        [len(p) for p, q in zip(t1["prompts"], ph) if q == f])
    assert mean_len(1) > mean_len(0)
    with pytest.raises(ValueError):
        make_diurnal_trace(2)


# ---------------------------------------------------------------------------
# Policy units (fake engine — no device work)
# ---------------------------------------------------------------------------


def test_hysteresis_breach_damping_and_cooldown():
    eng, auto = _controller()
    _run(eng, auto, 8)  # two in-band samples
    assert auto._stats["samples"] == 2 and auto._stats["holds"] == 2
    # Overload: one breached sample is damped, the second acts.
    eng.window["queue_depth_p95"] = 9.0
    _run(eng, auto, 8)
    assert auto._stats["grows"] == 1 and len(eng._devices) == 8
    grow = next(h for h in auto.history if h["action"] == "grow")
    assert grow["signal"] == "queue_depth_p95"
    assert any("1/2 consecutive" in h["reason"] for h in auto.history)
    # Cooldown: the breach persists but nothing moves inside the window.
    _run(eng, auto, 8)
    assert auto._stats["resizes"] == 1
    assert any("cooldown" in h["reason"] for h in auto.history)
    # Idle after cooldown: two under-band samples shrink.
    eng.window["queue_depth_p95"] = 0.0
    _run(eng, auto, 40)
    assert auto._stats["shrinks"] >= 1 and len(eng._devices) < 8
    # Every decision is a history record naming the triggering signal.
    assert all(h["signal"] and h["reason"] for h in auto.history)
    assert auto._stats["decisions"] == len(auto.history)


def test_shed_rate_is_an_overload_signal():
    eng, auto = _controller()
    eng.window["shed_rate"] = 0.2
    _run(eng, auto, 8)
    grow = next(h for h in auto.history if h["action"] == "grow")
    assert grow["signal"] == "shed_rate"


def test_thin_window_holds_and_resets_breach():
    eng, auto = _controller()
    eng.window["requests"] = 2  # below window_min_requests=8
    eng.window["queue_depth_p95"] = 9.0
    _run(eng, auto, 16)
    assert auto._stats["resizes"] == 0
    assert all(h["signal"] == "window_thin" for h in auto.history)


def test_min_devices_and_no_spares_hold():
    eng, auto = _controller(n_start=2, pool=_POOL[:2])
    eng.window["queue_depth_p95"] = 0.0
    _run(eng, auto, 16)
    assert auto._stats["resizes"] == 0
    assert any("min_devices" in h["reason"] for h in auto.history)
    eng.window["queue_depth_p95"] = 9.0
    _run(eng, auto, 16)
    assert auto._stats["resizes"] == 0
    assert any("no spare devices" in h["reason"] for h in auto.history)


def test_resize_budget_and_planner_refusal():
    eng, auto = _controller(max_resizes=0)
    eng.window["queue_depth_p95"] = 9.0
    _run(eng, auto, 16)
    assert auto._stats["resizes"] == 0
    assert any("budget" in h["reason"] for h in auto.history)
    # A layout whose fixed axes validate no larger size refuses the grow
    # through the shared planner gate.
    eng2, auto2 = _controller(n_start=4, pool=_POOL[:7],
                              layout={"tp": 4, "dp_shard": 1})
    eng2.window["queue_depth_p95"] = 9.0
    _run(eng2, auto2, 16)
    assert auto2._stats["resizes"] == 0
    assert auto2._stats["planner_refusals"] >= 1


def test_flap_fault_is_damped():
    chaos = FaultInjector(seed=11, schedule=[
        {"point": "autoscale_decide", "kind": "flap", "tick": 4}])
    eng, auto = _controller(chaos=chaos)
    _run(eng, auto, 16)
    assert auto._stats["flap_damped"] >= 1
    assert auto._stats["resizes"] == 0
    flap = next(h for h in auto.history if h["flap_injected"])
    assert flap["signal"].startswith("flap(")


def test_spike_fault_drives_real_grow_path():
    chaos = FaultInjector(seed=11, schedule=[
        {"point": "load_spike", "kind": "spike", "tick": 4},
        {"point": "load_spike", "kind": "spike", "tick": 8}])
    eng, auto = _controller(chaos=chaos)
    _run(eng, auto, 12)
    assert auto._stats["spikes"] == 2
    assert auto._stats["grows"] == 1 and len(eng._devices) == 8


def test_resplit_on_ratio_drift():
    eng, auto = _controller(n_start=8, cooldown_ticks=4,
                            resplit_tolerance=0.5)
    eng.window["prompt_decode_ratio"] = 6.0  # plan says 2.0 -> 3x drift
    _run(eng, auto, 8)
    assert auto._stats["resplits"] == 1
    # The engine re-planned under the observed ratio, so the drift cleared
    # and the controller settles back to holds.
    _run(eng, auto, 16)
    assert auto._stats["resplits"] == 1
    resplit = next(h for h in auto.history if h["action"] == "resplit")
    assert resplit["signal"] == "prompt_decode_ratio"


def test_mark_device_dead_shrinks_immediately():
    eng, auto = _controller()
    rec = auto.mark_device_dead(_POOL[1])
    assert rec["action"] == "shrink" and rec["signal"] == "dead_device"
    assert auto._stats["dead_device_shrinks"] == 1
    assert _POOL[1] not in eng._devices and len(eng._devices) == 3
    # A dead spare only gets recorded.
    rec = auto.mark_device_dead(_POOL[7])
    assert rec["action"] == "hold" and "spare" in rec["reason"]
    assert auto._stats["dead_device_shrinks"] == 1
    # Dead devices never re-enter later targets.
    eng.window["queue_depth_p95"] = 9.0
    _run(eng, auto, 64)
    for devices, _, _ in eng.resize_calls:
        assert _POOL[1] not in devices and _POOL[7] not in devices


def test_aborted_resize_counts_and_holds_layout():
    eng, auto = _controller()
    eng.resize_ok = False
    eng.window["queue_depth_p95"] = 9.0
    _run(eng, auto, 8)
    assert auto._stats["aborts"] == 1 and auto._stats["resizes"] == 0
    assert len(eng._devices) == 4  # nothing half-bound
    assert any(h["action"] == "grow_aborted" for h in auto.history)


def test_stats_shape_and_telemetry_events():
    class Rec:
        events, blocks = [], []

        def record_event(self, event, **fields):
            self.events.append((event, fields))

        def record_autoscale(self, block):
            self.blocks.append(block)

    rec = Rec()
    eng = _FakeEngine(_POOL[:4])
    auto = AutoscaleController(eng, AutoscaleConfig(poll_ticks=4),
                               device_pool=_POOL, telemetry=rec)
    eng.window["queue_depth_p95"] = 9.0
    _run(eng, auto, 12)
    s = auto.stats()
    for k in ("samples", "decisions", "holds", "grows", "shrinks",
              "resplits", "resizes", "aborts", "flap_damped", "spikes",
              "planner_refusals", "active_devices", "pool_devices",
              "dead_devices", "cooldown_until_tick", "last_action"):
        assert k in s, k
    assert s["pool_devices"] == 8
    # EVERY decision (holds included) went out as an explainable event.
    assert len(rec.events) == s["decisions"]
    assert all(e == "autoscale_decision" and f["signal"] and f["reason"]
               for e, f in rec.events)
    auto.close()
    assert rec.blocks and rec.blocks[-1]["decisions"] == s["decisions"]


def test_telemetry_recorder_autoscale_block(tmp_path):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.telemetry import TelemetryRecorder
    from accelerate_tpu.utils import TelemetryKwargs

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc = Accelerator(project_dir=str(tmp_path))
    rec = TelemetryRecorder(
        acc, TelemetryKwargs(log_every=0, straggler_probe_every=0))
    block = {"samples": 3, "decisions": 3, "resizes": 1, "grows": 1}
    rec.record_autoscale(block)
    assert rec.summary()["autoscale"] == block


# ---------------------------------------------------------------------------
# Live resize on the real engine
# ---------------------------------------------------------------------------


def test_live_resize_grow_and_dead_device_bit_equal(llama):
    """The tentpole end to end: a mid-flight grow from half the mesh to all
    of it, then a dead-decode-device shrink through the controller — every
    request ok, every row bit-equal to a fixed 8-device reference, zero
    steady-state recompiles across three layouts."""
    cfg, model = llama
    devs = jax.devices()
    sc = ServingConfig(n_slots=8, max_len=64, prefill_chunks=[16],
                       temperature=0.0, seed=0, max_retries=3,
                       max_idle_ticks=200)
    prompts = _prompts(cfg, (12, 30, 20, 26, 17, 9))

    ref = DisaggServingEngine(model, sc, disagg=DisaggConfig(n_prefill_lanes=2),
                              devices=devs)
    ref.warmup()
    ref_rows = ref.run(prompts, max_new_tokens=6)
    ref.close()

    eng = DisaggServingEngine(model, sc, disagg=DisaggConfig(n_prefill_lanes=2),
                              devices=devs[:4])
    eng.warmup()
    auto = AutoscaleController(
        eng, AutoscaleConfig(poll_ticks=4, cooldown_ticks=8),
        device_pool=devs)
    ids = [eng.submit(p, max_new_tokens=6) for p in prompts]
    rows, tick = {}, 0
    resized = False
    while eng.pending:
        eng.tick()
        tick += 1
        if tick == 3 and not resized:
            rec = eng.resize(devices=devs)
            assert rec["ok"] and rec["n_devices"] == 8
            resized = True
        for r in eng.poll():
            rows[r["id"]] = r
        assert tick < 3000
    assert [rows[i]["status"] for i in ids] == ["ok"] * len(ids)
    for j, i in enumerate(ids):
        np.testing.assert_array_equal(rows[i]["tokens"], ref_rows[j])
    st = eng.stats()
    assert st["steady_recompiles"] == 0
    assert st["disagg"]["resize"]["resizes"] == 1
    assert st["disagg"]["resize"]["draining_requests"] == 0

    # Controller-driven dead-device shrink: correctness path, no cooldown.
    dead = eng.decode_devices[0]
    rec = auto.mark_device_dead(dead)
    assert rec["action"] == "shrink" and rec["resize"]["ok"]
    assert dead not in eng._devices and len(eng._devices) == 7
    rows2 = eng.run(prompts[:2], max_new_tokens=6)
    for j in range(2):
        np.testing.assert_array_equal(rows2[j], ref_rows[j])
    assert eng.stats()["steady_recompiles"] == 0
    eng.close()


def test_resize_transfer_fault_aborts_cleanly(llama):
    """Persistent injected resize_transfer faults: the resize aborts back
    to the old layout with nothing half-bound, and the engine keeps
    serving on it bit-equal."""
    cfg, model = llama
    devs = jax.devices()
    sc = ServingConfig(n_slots=4, max_len=64, prefill_chunks=[16],
                       temperature=0.0, seed=0, max_retries=3,
                       max_idle_ticks=200)
    # handoff_retries=0 => a drawn fault on the single attempt is terminal.
    eng = DisaggServingEngine(
        model, sc, disagg=DisaggConfig(n_prefill_lanes=2, handoff_retries=0),
        devices=devs[:4])
    eng.warmup()
    baseline = eng.run(_prompts(cfg, (10, 14)), max_new_tokens=5)
    eng.chaos = FaultInjector(
        seed=3, rates={"resize_transfer": {"transfer_error": 1.0}})
    rec = eng.resize(devices=devs)
    assert rec["ok"] is False and "resize_transfer" in rec["reason"]
    assert len(eng._devices) == 4  # old layout intact
    st = eng.stats()["disagg"]["resize"]
    assert st["resize_aborts"] == 1 and st["resizes"] == 0
    eng.chaos = None
    again = eng.run(_prompts(cfg, (10, 14)), max_new_tokens=5)
    for a, b in zip(baseline, again):
        np.testing.assert_array_equal(a, b)
    assert eng.stats()["steady_recompiles"] == 0
    eng.close()


# ---------------------------------------------------------------------------
# Rolling window (serving.py satellite)
# ---------------------------------------------------------------------------


def test_window_stats_rolling_and_bounded(llama):
    cfg, model = llama
    with pytest.raises(ValueError):
        ServingConfig(window_requests=0)
    sc = ServingConfig(n_slots=4, max_len=48, prefill_chunks=[16],
                       temperature=0.0, seed=0, window_requests=4)
    eng = ServingEngine(model, sc)
    prompts = _prompts(cfg, (8, 12, 10, 9, 14, 11))
    eng.run(prompts, max_new_tokens=4)
    w = eng.window_stats()
    for k in ("requests", "capacity", "ok", "ttft_p50_s", "ttft_p95_s",
              "tpot_p50_s", "tpot_p95_s", "shed_rate", "timeout_rate",
              "failed_rate", "queue_depth_p95", "prompt_decode_ratio"):
        assert k in w, k
    # The window is BOUNDED: 6 completions through a 4-deep window.
    assert w["capacity"] == 4 and w["requests"] == 4
    assert eng.stats()["requests_completed"] == 6  # lifetime is not
    assert w["ok"] == 4 and w["shed_rate"] == 0.0
    assert w["ttft_p95_s"] >= w["ttft_p50_s"] >= 0.0
    # Ratio of the windowed ok rows: 4 prompts of 8..14 tokens / 4 new each.
    assert 8 / 4 <= w["prompt_decode_ratio"] <= 14 / 4
    assert w["queue_depth_p95"] >= 0.0
    assert eng.stats()["window"] == w  # embedded block matches the method
    eng.reset_metrics()
    assert eng.window_stats()["requests"] == 0
    eng.close()


def test_build_autoscale_controller_wiring(llama, tmp_path):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    acc = Accelerator(project_dir=str(tmp_path))
    eng = _FakeEngine(_POOL[:4])
    eng.chaos = FaultInjector(seed=1)
    auto = acc.build_autoscale_controller(eng, AutoscaleConfig(poll_ticks=4),
                                          device_pool=_POOL)
    assert isinstance(auto, AutoscaleController)
    assert auto.chaos is eng.chaos  # defaults to the engine's injector
    assert auto.telemetry is acc.telemetry
    # Off unless constructed: the colocated engine has no resize actuator.
    cfg, model = llama
    serving = ServingEngine(model, ServingConfig(n_slots=2, max_len=32))
    with pytest.raises(ValueError):
        acc.build_autoscale_controller(serving)
    serving.close()
