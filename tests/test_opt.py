"""OPT family: shapes, training, TP sharding, HF logit parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Model
from accelerate_tpu.models import OPTConfig, OPTForCausalLM, opt_tp_rules
from accelerate_tpu.utils import set_seed


def test_opt_forward_shape():
    set_seed(0)
    cfg = OPTConfig.tiny()
    module = OPTForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 12), dtype=np.int32))
    params = module.init(jax.random.key(0), ids)["params"]
    logits = module.apply({"params": params}, ids)
    assert logits.shape == (2, 12, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_opt_tp_sharded_logits_match():
    import optax

    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    set_seed(0)
    cfg = OPTConfig.tiny(dtype=jnp.float32)
    module = OPTForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(1).integers(0, cfg.vocab_size, (4, 8), dtype=np.int32))
    single = Model.from_flax(module, jax.random.key(0), ids)
    want = np.asarray(single(ids))

    acc = Accelerator(parallelism_config=ParallelismConfig(tp_size=4, dp_shard_size=2))
    model = Model.from_flax(module, jax.random.key(0), ids, tp_rules=opt_tp_rules())
    model, _ = acc.prepare(model, optax.adam(1e-3))
    np.testing.assert_allclose(np.asarray(model(ids)), want, rtol=2e-4, atol=2e-4)
    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()


def test_opt_hf_logit_parity():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from accelerate_tpu.models import model_from_pretrained

    hf_cfg = transformers.OPTConfig(
        vocab_size=128, hidden_size=64, ffn_dim=128, num_hidden_layers=3,
        num_attention_heads=4, max_position_embeddings=64,
        do_layer_norm_before=True, word_embed_proj_dim=64,
    )
    torch.manual_seed(0)
    hf = transformers.OPTForCausalLM(hf_cfg)
    hf.eval()
    ids = np.random.default_rng(0).integers(0, 128, (2, 10)).astype(np.int64)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    got = np.asarray(ours(jnp.asarray(ids.astype(np.int32))))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_opt_disk_offload_streamed_forward(tmp_path):
    """OPT through the big-model layer-streaming path == plain forward
    (the reference's OPT-30B disk-offload benchmark shape)."""
    from accelerate_tpu import Model, disk_offload

    set_seed(0)
    cfg = OPTConfig.tiny(dtype=jnp.float32)
    module = OPTForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(2).integers(0, cfg.vocab_size, (2, 8), dtype=np.int32))
    model = Model.from_flax(module, jax.random.key(0), ids)
    want = np.asarray(model(ids))
    dispatched = disk_offload(model, str(tmp_path / "offload"))
    got = np.asarray(dispatched(ids))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
