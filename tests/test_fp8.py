"""fp8 QDQ matmul path (ops/fp8.py) — numerics, gradients, model integration."""

import os
import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_qdq_e4m3_roundtrip_error():
    from accelerate_tpu.ops import qdq_e4m3

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64, 64)).astype(np.float32))
    y = qdq_e4m3(x)
    # e4m3 has ~2 mantissa-bit relative precision after per-tensor scaling.
    rel = float(jnp.max(jnp.abs(y - x)) / jnp.max(jnp.abs(x)))
    assert rel < 0.1, rel
    # Scale adapts: a tensor with large magnitude round-trips equally well.
    x2 = x * 1e4
    y2 = qdq_e4m3(x2)
    rel2 = float(jnp.max(jnp.abs(y2 - x2)) / jnp.max(jnp.abs(x2)))
    assert rel2 < 0.1, rel2


def test_qdq_zero_tensor():
    from accelerate_tpu.ops import qdq_e4m3

    z = jnp.zeros((8, 8))
    np.testing.assert_array_equal(np.asarray(qdq_e4m3(z)), 0.0)


def test_fp8_dot_general_forward_close_to_fp32():
    from accelerate_tpu.ops import fp8_dot_general

    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(32, 8)).astype(np.float32))
    dn = (((1,), (0,)), ((), ()))
    exact = jax.lax.dot_general(a, b, dn)
    dg = fp8_dot_general("HYBRID")
    got = dg(a, b, dn)
    # fp8 matmul tolerance: per-element relative to the output scale.
    err = float(jnp.max(jnp.abs(got - exact)) / jnp.max(jnp.abs(exact)))
    assert err < 0.15, err


def test_fp8_dot_general_gradients_flow():
    from accelerate_tpu.ops import fp8_dot_general

    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(16, 4)).astype(np.float32))
    dn = (((1,), (0,)), ((), ()))
    dg = fp8_dot_general("HYBRID")

    def f(a, b):
        return jnp.sum(dg(a, b, dn) ** 2)

    ga, gb = jax.grad(f, argnums=(0, 1))(a, b)
    ga_ref, gb_ref = jax.grad(lambda a, b: jnp.sum(jax.lax.dot_general(a, b, dn) ** 2),
                              argnums=(0, 1))(a, b)
    assert np.all(np.isfinite(ga)) and np.all(np.isfinite(gb))
    # e5m2 backward: coarser, but must track the true gradient direction.
    cos = float(jnp.sum(ga * ga_ref) / (jnp.linalg.norm(ga) * jnp.linalg.norm(ga_ref)))
    assert cos > 0.98, cos


def test_native_f8_dots_in_hlo_fwd_and_bwd():
    """backend TE/AO: forward AND both cotangent dots must have true float8
    operand types (the reference gets this from TE fp8 GEMMs; here XLA runs
    them natively on fp8-capable targets and legalizes elsewhere)."""
    from accelerate_tpu.ops import fp8_dot_general

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 8, 16)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(16, 4, 8)).astype(np.float32))
    dn = (((2,), (0,)), ((), ()))
    nat = fp8_dot_general("HYBRID", native=True)
    txt = (
        jax.jit(jax.value_and_grad(lambda x, w: jnp.sum(nat(x, w, dn)), argnums=(0, 1)))
        .lower(x, w)
        .as_text()
    )
    dots = [l for l in txt.splitlines() if "dot_general" in l]
    f8_dots = [l for l in dots if "f8E4M3" in l or "f8E5M2" in l]
    assert len(f8_dots) == 3, (len(f8_dots), dots)
    # HYBRID: cotangent enters the grad dots as e5m2.
    assert sum("f8E5M2" in l for l in f8_dots) == 2, f8_dots


@pytest.mark.parametrize("dn", [
    (((1,), (0,)), ((), ())),                # plain matmul
    (((2,), (0,)), ((), ())),                # DenseGeneral qkv style
    (((2, 3), (0, 1)), ((), ())),            # DenseGeneral o_proj style
    (((0,), (2,)), ((), ())),                # unsorted/odd contraction dims
])
def test_native_f8_grads_match_qdq_shapes_and_direction(dn):
    """The hand-written dot transposes must agree with autodiff's (shape
    exactly; value within fp8 rounding — native quantizes the cotangent
    BEFORE the grad dot, TE-style, QDQ after, so bitwise equality is not
    expected)."""
    from accelerate_tpu.ops import fp8_dot_general

    rng = np.random.default_rng(3)
    (lc, rc), _ = dn
    shapes = {
        ((1,), (0,)): ((8, 16), (16, 4)),
        ((2,), (0,)): ((2, 8, 16), (16, 4, 8)),
        ((2, 3), (0, 1)): ((2, 8, 4, 8), (4, 8, 16)),
        ((0,), (2,)): ((16, 8), (4, 2, 16)),
    }[(lc, rc)]
    x = jnp.asarray(rng.normal(size=shapes[0]).astype(np.float32))
    w = jnp.asarray(rng.normal(size=shapes[1]).astype(np.float32))
    nat = fp8_dot_general("HYBRID", native=True)
    ref = fp8_dot_general("HYBRID", native=False)
    np.testing.assert_allclose(
        np.asarray(nat(x, w, dn)), np.asarray(ref(x, w, dn)), rtol=1e-4, atol=1e-4
    )
    gn = jax.grad(lambda x, w: jnp.sum(nat(x, w, dn) ** 2), argnums=(0, 1))(x, w)
    gr = jax.grad(lambda x, w: jnp.sum(ref(x, w, dn) ** 2), argnums=(0, 1))(x, w)
    for a, b in zip(gn, gr):
        assert a.shape == b.shape
        cos = float(jnp.sum(a * b) / (jnp.linalg.norm(a) * jnp.linalg.norm(b)))
        assert cos > 0.99, cos


def test_native_f8_dots_survive_full_model_lowering():
    """The f8 dots must reach the lowered HLO of the REAL training graph —
    scan-over-layers + remat + value_and_grad could silently legalize or DCE
    them, which would make the fp8 bench phase measure nothing."""
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, cross_entropy_loss

    cfg = LlamaConfig.tiny(dtype=jnp.bfloat16, fp8=True, remat=True)
    module = LlamaForCausalLM(cfg)
    ids = np.zeros((2, 17), np.int32)
    params = jax.eval_shape(
        lambda: module.init(jax.random.key(0), ids[:, :-1])
    )["params"]

    def loss_fn(p):
        return cross_entropy_loss(module.apply({"params": p}, ids[:, :-1]), ids[:, 1:])

    txt = jax.jit(jax.value_and_grad(loss_fn)).lower(params).as_text()
    f8_dots = [
        l for l in txt.splitlines()
        if "dot_general" in l and ("f8E4M3" in l or "f8E5M2" in l)
    ]
    # At least the projections' forward + grad dots; exact count depends on
    # remat scheduling, so assert presence of both operand roles instead.
    assert f8_dots, "no f8-operand dots in the lowered train step"
    assert any("f8E4M3" in l for l in f8_dots), "no e4m3 forward dots"
    assert any("f8E5M2" in l for l in f8_dots), "no e5m2 cotangent dots"


def test_fp8_backend_aliases():
    """Reference parity for the backend surface (accelerator.py:478-503):
    TE/AO → native f8 dots, QDQ → simulation, MSAMP → explicit rejection."""
    from accelerate_tpu.utils import FP8RecipeKwargs

    assert FP8RecipeKwargs(backend="TE").native_dots is True
    assert FP8RecipeKwargs(backend="ao").native_dots is True
    assert FP8RecipeKwargs(backend="QDQ").native_dots is False
    assert FP8RecipeKwargs().native_dots is None  # AUTO → platform default
    with pytest.raises(ValueError, match="MS-AMP"):
        FP8RecipeKwargs(backend="MSAMP")
    with pytest.raises(ValueError, match="AUTO"):
        FP8RecipeKwargs(backend="nonsense")


def test_quantize_params_roundtrip():
    from accelerate_tpu.ops import dequantize_params_fp8, quantize_params_fp8

    rng = np.random.default_rng(3)
    params = {
        "w": jnp.asarray(rng.normal(size=(16, 16)).astype(np.float32)),
        "step": jnp.asarray(3, jnp.int32),  # non-float leaves pass through
    }
    q, s = quantize_params_fp8(params)
    assert q["w"].dtype == jnp.float8_e4m3fn
    assert q["step"].dtype == jnp.int32
    back = dequantize_params_fp8(q, s, dtype=jnp.float32)
    rel = float(jnp.max(jnp.abs(back["w"] - params["w"])) / jnp.max(jnp.abs(params["w"])))
    assert rel < 0.1
    assert int(back["step"]) == 3


def test_llama_fp8_trains_close_to_bf16():
    """A tiny Llama with fp8 projections: losses finite and within a few % of
    the bf16 run after a few steps."""
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, cross_entropy_loss
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils import set_seed

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, size=(4, 33), dtype=np.int32)

    def run(fp8: bool):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        set_seed(0)
        cfg = LlamaConfig.tiny(dtype=jnp.bfloat16, fp8=fp8)
        module = LlamaForCausalLM(cfg)
        acc = Accelerator(mixed_precision="fp8" if fp8 else "bf16")
        model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
        model, _ = acc.prepare(model, optax.adam(1e-3))

        def loss_fn(params, batch):
            logits = module.apply({"params": params}, batch["x"])
            return cross_entropy_loss(logits, batch["y"])

        step = acc.prepare_train_step(loss_fn)
        state = acc.train_state
        batch = {"x": ids[:, :-1], "y": ids[:, 1:]}
        losses = []
        for _ in range(5):
            state, m = step(state, batch)
            losses.append(float(np.asarray(m["loss"])))
        return losses

    l_fp8 = run(True)
    l_bf16 = run(False)
    assert all(np.isfinite(l_fp8)), l_fp8
    assert l_fp8[-1] < l_fp8[0], "fp8 run did not descend"
    np.testing.assert_allclose(l_fp8[0], l_bf16[0], rtol=0.05)


def test_fp16_dynamic_loss_scale_updates():
    """Unit semantics of DynamicLossScale: growth after interval, backoff on
    overflow (reference GradScaler behavior, accelerator.py:577-583)."""
    from accelerate_tpu.train_state import DynamicLossScale

    ls = DynamicLossScale.create(init_scale=1024.0, growth_interval=2)
    ls = ls.update(jnp.asarray(True))
    assert float(ls.scale) == 1024.0 and int(ls.growth_tracker) == 1
    ls = ls.update(jnp.asarray(True))  # hits interval → grow
    assert float(ls.scale) == 2048.0 and int(ls.growth_tracker) == 0
    ls = ls.update(jnp.asarray(False))  # overflow → backoff
    assert float(ls.scale) == 1024.0


def test_fp16_training_skips_overflow_steps():
    """fp16 train step: params unchanged on an overflowing microbatch, scale
    backs off; normal batches still descend."""
    import optax

    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.test_utils.training import make_regression_model
    from accelerate_tpu.utils import set_seed

    AcceleratorState._reset_state()
    GradientState._reset_state()
    set_seed(0)
    module, loss_fn = make_regression_model()
    acc = Accelerator(mixed_precision="fp16")
    model = Model.from_flax(module, jax.random.key(0), np.zeros((8,), np.float32))
    model, _ = acc.prepare(model, optax.sgd(0.05))
    step = acc.prepare_train_step(loss_fn)
    state = acc.train_state
    assert state.loss_scale is not None
    scale0 = float(np.asarray(state.loss_scale.scale))

    x = np.linspace(-1, 1, 8).astype(np.float32)
    good = {"x": x, "y": (2 * x + 1).astype(np.float32)}
    state, m = step(state, good)
    params_before = jax.tree.map(np.asarray, state.params)

    bad = {"x": x, "y": np.full((8,), np.inf, np.float32)}  # non-finite grads
    state, m = step(state, bad)
    params_after = jax.tree.map(np.asarray, state.params)
    # Overflow step: params must be untouched, scale must back off.
    np.testing.assert_array_equal(params_after["a"], params_before["a"])
    assert float(np.asarray(state.loss_scale.scale)) < scale0 * 1.01

    for _ in range(10):
        state, m = step(state, good)
    assert float(np.asarray(m["loss"])) < 1.0


def test_fp8_eval_mode_full_precision():
    """use_during_eval=False (default): inside eval_mode the fp8 dot is exact
    (review regression: the flag was silently ignored)."""
    from accelerate_tpu.ops import fp8_dot_general
    from accelerate_tpu.ops.fp8 import eval_mode

    rng = np.random.default_rng(4)
    a = jnp.asarray(rng.normal(size=(8, 8)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(8, 8)).astype(np.float32))
    dn = (((1,), (0,)), ((), ()))
    exact = jax.lax.dot_general(a, b, dn)
    dg = fp8_dot_general("HYBRID", use_during_eval=False)
    with eval_mode():
        np.testing.assert_array_equal(np.asarray(dg(a, b, dn)), np.asarray(exact))
    assert float(jnp.max(jnp.abs(dg(a, b, dn) - exact))) > 0  # quantized outside
    always = fp8_dot_general("HYBRID", use_during_eval=True)
    with eval_mode():
        assert float(jnp.max(jnp.abs(always(a, b, dn) - exact))) > 0


def test_fp8_qdq_reaches_compiler_ir():
    """The QDQ pattern must survive tracing: the lowered StableHLO contains
    f8e4m3 converts feeding the dot — this is the pattern XLA's fp8 rewriter
    matches (VERDICT r2: 'compiler fuses QDQ' was an article of faith)."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.ops.fp8 import fp8_dot_general

    dg = fp8_dot_general("E4M3", use_during_eval=True)
    dn = (((1,), (0,)), ((), ()))

    def f(a, b):
        return dg(a, b, dn)

    a = jnp.ones((16, 32), jnp.bfloat16)
    b = jnp.ones((32, 8), jnp.bfloat16)
    ir = jax.jit(f).lower(a, b).as_text()
    assert "f8E4M3FN" in ir or "f8e4m3fn" in ir, "fp8 converts missing from lowered IR"
    assert "dot_general" in ir


@pytest.mark.skipif(
    not os.environ.get("ACCELERATE_TEST_USE_TPU"), reason="requires a real TPU compile"
)
def test_fp8_dots_in_tpu_compiled_hlo():
    """On a TPU with fp8 MXU paths (v6e+), the optimized HLO must carry fp8
    dot operands; on earlier generations (v5e) the rewriter legally lowers to
    bf16 — assert whichever contract this chip has so the docs claim stays
    honest (reference fp8 claim: examples/torch_native_parallelism/README.md)."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.ops.fp8 import fp8_dot_general

    dg = fp8_dot_general("E4M3", use_during_eval=True)
    dn = (((1,), (0,)), ((), ()))
    a = jnp.ones((256, 256), jnp.bfloat16)
    b = jnp.ones((256, 256), jnp.bfloat16)
    compiled = jax.jit(lambda a, b: dg(a, b, dn)).lower(a, b).compile()
    hlo = compiled.as_text()
    kind = jax.devices()[0].device_kind.lower()
    has_fp8_dot = "f8e4m3" in hlo
    if "v6" in kind or "v7" in kind:
        assert has_fp8_dot, f"fp8 rewriter should fire on {kind}"
    else:
        # Record the honest outcome for older generations in the test log.
        print(f"fp8-in-compiled-HLO on {kind}: {has_fp8_dot}")
