"""Arrow-key config menu (reference: commands/menu/selection_menu.py) —
key handling, wrap-around, digit jumps, non-TTY fallback, and the
questionnaire end-to-end without typing a single enum value."""

import builtins
import io

import pytest

from accelerate_tpu.commands.menu import choose, select


def _run(keys, choices, default_index=0):
    it = iter(keys)
    out = io.StringIO()
    idx = select("pick one", choices, default_index=default_index,
                 reader=lambda: next(it), out=out)
    return idx, out.getvalue()


def test_select_navigation_and_enter():
    idx, out = _run(["down", "down", "enter"], ["a", "b", "c"])
    assert idx == 2
    assert "pick one" in out and "➔" in out


def test_select_wraps_both_directions():
    idx, _ = _run(["up", "enter"], ["a", "b", "c"])       # up from 0 -> last
    assert idx == 2
    idx, _ = _run(["down", "down", "down", "enter"], ["a", "b", "c"])
    assert idx == 0


def test_select_vim_keys_and_digits():
    idx, _ = _run(["j", "enter"], ["a", "b", "c"])
    assert idx == 1
    idx, _ = _run(["2"], ["a", "b", "c"])  # digit jumps AND selects
    assert idx == 1


def test_select_escape_keeps_default():
    idx, _ = _run(["down", "q"], ["a", "b", "c"], default_index=1)
    assert idx == 1


def test_choose_fallback_numbered(monkeypatch, capsys):
    monkeypatch.setenv("ACCELERATE_NO_MENU", "1")
    answers = iter(["2", "", "bf16"])
    monkeypatch.setattr(builtins, "input", lambda *_: next(answers))
    assert choose("env", ["LOCAL_MACHINE", "TPU_POD"], "LOCAL_MACHINE") == "TPU_POD"
    assert choose("env", ["LOCAL_MACHINE", "TPU_POD"], "LOCAL_MACHINE") == "LOCAL_MACHINE"
    # typing the value (old questionnaire behavior) still works
    assert choose("precision", ["no", "bf16", "fp16"], "no") == "bf16"
    out = capsys.readouterr().out
    assert "1.* LOCAL_MACHINE" in out  # default marked


def _pty_menu(keys: bytes, key_gap_s: float = 0.0):
    """Run select() in a child on a real pty, feed ``keys`` once the menu has
    rendered, return the captured output. Success is judged on output and the
    child is reaped explicitly: the axon site hook can block interpreter
    *shutdown* when the TPU relay is unreachable — unrelated to the menu."""
    import os
    import pty
    import re
    import select as _select
    import subprocess
    import sys
    import time

    code = (
        # Pin CPU before any accelerate_tpu import: the inherited TPU-relay
        # backend would otherwise hang this child at interpreter exit when
        # the relay is down (same pinning every other subprocess test does).
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from accelerate_tpu.commands.menu import select\n"
        "print('IDX', select('t', ['a', 'b', 'c']))\n"
    )
    master, slave = pty.openpty()
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdin=slave, stdout=slave, stderr=subprocess.DEVNULL,
        env={**os.environ, "PYTHONPATH": os.getcwd(), "JAX_PLATFORMS": "cpu"},
    )
    os.close(slave)
    out = b""
    deadline = time.time() + 60
    sent = 0  # keys written so far
    try:
        while not re.search(rb"IDX \d", out) and time.time() < deadline:
            # Only send keys once the menu rendered — writing earlier races
            # the child's tty.setraw and the bytes get canonical-echoed away.
            if sent == 0 and "➔".encode() in out:
                if key_gap_s:
                    # byte-at-a-time with gaps (bare-ESC timing cases)
                    for i in range(len(keys)):
                        os.write(master, keys[i: i + 1])
                        time.sleep(key_gap_s)
                else:
                    os.write(master, keys)
                sent = len(keys)
            r, _, _ = _select.select([master], [], [], 1.0)
            if not r:
                continue
            try:
                chunk = os.read(master, 4096)
            except OSError:
                break
            if not chunk:
                break
            out += chunk
    finally:
        os.close(master)
        proc.kill()
        proc.wait(timeout=30)
    return out


def test_tty_reader_escape_decoding_under_pty():
    out = _pty_menu(b"\x1b[B\x1b[B\x1b[A\r")  # ↓ ↓ ↑ ⏎ -> index 1
    assert b"IDX 1" in out, out[-500:]


def test_tty_reader_ss3_arrows_and_delete_ignored():
    """Application-cursor-mode arrows (\\x1bOB) must navigate, and a Delete
    key (\\x1b[3~) must be ignored — not exit the menu or leave stray bytes
    queued for the next read."""
    out = _pty_menu(b"\x1b[3~\x1bOB\r")  # Delete (ignored), SS3 ↓, ⏎ -> 1
    assert b"IDX 1" in out, out[-500:]


def test_tty_reader_bare_escape_keeps_default():
    """A lone ESC press (no trailing sequence bytes) must return the default
    immediately instead of blocking on a read for bytes that never come."""
    out = _pty_menu(b"\x1b", key_gap_s=0.3)
    assert b"IDX 0" in out, out[-500:]


def test_interactive_config_end_to_end(monkeypatch):
    """Full questionnaire without typing one enum value: numbered picks for
    choices, plain values for free-form ints."""
    from accelerate_tpu.commands.config import interactive_config

    monkeypatch.setenv("ACCELERATE_NO_MENU", "1")
    answers = iter([
        "1",    # compute environment -> LOCAL_MACHINE
        "4",    # num_processes
        "8476", # coordinator port
        "no",   # cpu only?
        "4",    # dp_shard
        "1",    # dp_replicate
        "1",    # tp
        "1",    # cp
        "1",    # sp
        "1",    # pp
        "1",    # ep
        "1",    # sharding strategy -> FULL_SHARD
        "no",   # offload
        "yes",  # activation checkpointing
        "2",    # mixed precision -> bf16
        "2",    # grad accumulation
    ])
    monkeypatch.setattr(builtins, "input", lambda *_: next(answers))
    cfg = interactive_config()
    assert cfg.compute_environment == "LOCAL_MACHINE"
    assert cfg.num_processes == 4
    assert cfg.dp_shard_size == 4
    assert cfg.use_fsdp and cfg.fsdp_sharding_strategy == "FULL_SHARD"
    assert cfg.fsdp_activation_checkpointing
    assert cfg.mixed_precision == "bf16"
    assert cfg.gradient_accumulation_steps == 2
