"""One compiled train step on the real chip (TPU tier).

The cheap end-to-end canary: a ~125M Llama fused train step (bf16 compute,
Pallas flash attention, remat) must compile and produce a finite decreasing
loss on hardware. Catches on-chip-only failures (Mosaic lowering inside the
full model, remote-compile OOM, donation layout) in ~1-2 min, without the
16-minute bench ladder. The 1B ladder itself stays bench.py's job.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax


def test_train_step_125m_smoke():
    from accelerate_tpu import Accelerator, Model
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, cross_entropy_loss
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import set_seed

    for cls in (AcceleratorState, GradientState, PartialState):
        cls._reset_state()
    set_seed(0)
    cfg = LlamaConfig(
        vocab_size=8192, hidden_size=768, intermediate_size=2048,
        num_hidden_layers=12, num_attention_heads=12, num_key_value_heads=12,
        max_position_embeddings=1024, dtype=jnp.bfloat16,
    )
    module = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    # Batch derives from the mesh: the default Accelerator shards batches
    # over every attached device (1 on the axon tunnel, 8 on a full host).
    bsz = max(4, jax.device_count())
    ids = rng.integers(0, cfg.vocab_size, size=(bsz, 513), dtype=np.int32)

    acc = Accelerator(mixed_precision="bf16")
    model = Model.from_flax(module, jax.random.key(0), ids[:, :-1])
    model, _ = acc.prepare(model, optax.adamw(1e-3))

    def loss_fn(params, batch):
        logits = module.apply({"params": params}, batch["x"])
        return cross_entropy_loss(logits, batch["y"])

    step = acc.prepare_train_step(loss_fn)
    batch = {"x": jnp.asarray(ids[:, :-1]), "y": jnp.asarray(ids[:, 1:])}
    state = acc.train_state
    losses = []
    for _ in range(4):
        state, metrics = step(state, batch)
        losses.append(float(np.asarray(metrics["loss"])))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses
