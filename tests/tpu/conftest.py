"""TPU-gated kernel tier — runs ONLY against a real chip.

CI runs the whole suite on the virtual CPU mesh, which exercises the Pallas
kernels in *interpreter* mode only (`ops/pallas_flash.py:default_interpret`).
A compiled-lowering regression (Mosaic tiling, SMEM prefetch, scalar-prefetch
offsets) is invisible to that suite. This tier is the compiled-mode health
check, kept separable from the full `bench.py` ladder so kernel status costs
~2 min of chip time, not 16.

Run via `make test_tpu` (sets ACCELERATE_TEST_USE_TPU=1, serial). Everything
here skips cleanly when no chip is reachable: the axon relay dying makes
`jax.devices()` HANG rather than error, so the availability probe runs in a
subprocess with a hard timeout. Only one TPU process can use the tunnel at a
time — never run this tier concurrently with bench.py.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

_TIER_DIR = Path(__file__).parent

_PROBE = (
    "import jax; d = jax.devices(); "
    "import sys; sys.exit(0 if d and d[0].platform in ('tpu', 'axon') else 1)"
)


def _tpu_reason():
    if not os.environ.get("ACCELERATE_TEST_USE_TPU"):
        return "TPU tier needs ACCELERATE_TEST_USE_TPU=1 (use `make test_tpu`)"
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE],
            timeout=int(os.environ.get("ACCELERATE_TPU_PROBE_TIMEOUT_S", "90")),
            capture_output=True,
        )
    except subprocess.TimeoutExpired:
        return "TPU relay unreachable (probe hung — axon relay down)"
    if r.returncode != 0:
        return f"no TPU device (probe rc={r.returncode})"
    return None


def pytest_configure(config):
    config._tpu_skip_reason = _tpu_reason()


def pytest_collection_modifyitems(config, items):
    reason = getattr(config, "_tpu_skip_reason", None)
    if reason is None:
        return
    marker = pytest.mark.skip(reason=reason)
    # This hook receives EVERY collected item in the session, not just this
    # directory's — mark only the TPU tier or `pytest tests/` would skip the
    # whole CPU suite.
    for item in items:
        if _TIER_DIR in Path(str(item.fspath)).parents:
            item.add_marker(marker)
