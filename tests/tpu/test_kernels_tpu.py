"""Compiled-mode Pallas kernel health checks (real TPU only).

Each test compares the Mosaic-compiled kernel against either the Pallas
interpreter (same math, so tolerances are tight) or the pure-jnp blockwise
reference. These are exactly the pieces the CPU suite can only exercise
interpreted: tiling/SMEM lowering, scalar-prefetched dynamic offsets (the
ring-attention rotation contract), the GQA-folded backward, and the int8
decode dequant-at-matmul path.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.ops.flash_attention import blockwise_attention
from accelerate_tpu.ops.pallas_flash import (
    pallas_flash_attention,
    pallas_flash_attention_with_lse,
)


def _qkv(b=2, sq=256, sk=256, hq=8, hkv=2, d=64, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, sq, hq, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, sk, hkv, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, sk, hkv, d)), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hkv", [8, 2])
def test_flash_fwd_compiled_matches_interpreter(causal, hkv):
    q, k, v = _qkv(hkv=hkv)
    fn = functools.partial(
        pallas_flash_attention_with_lse, causal=causal, block_q=128, block_k=128
    )
    out_c, lse_c = fn(q, k, v, interpret=False)
    out_i, lse_i = fn(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out_c), np.asarray(out_i), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(lse_c), np.asarray(lse_i), rtol=2e-3, atol=2e-3)


def test_flash_fwd_matches_blockwise_reference():
    q, k, v = _qkv()
    out = pallas_flash_attention(q, k, v, causal=True, interpret=False)
    with jax.default_matmul_precision("highest"):
        ref = blockwise_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_flash_bwd_compiled_matches_interpreter():
    """The dQ and GQA-folded dK/dV kernels, compiled vs interpreted."""
    q, k, v = _qkv(hkv=2)
    cot = jnp.asarray(np.random.default_rng(1).standard_normal(q.shape), q.dtype)

    def loss(q, k, v, interpret):
        out = pallas_flash_attention(
            q, k, v, causal=True, block_q=128, block_k=128, interpret=interpret
        )
        return jnp.sum(out * cot)

    gc = jax.grad(functools.partial(loss, interpret=False), argnums=(0, 1, 2))(q, k, v)
    gi = jax.grad(functools.partial(loss, interpret=True), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gc, gi, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3,
            err_msg=f"d{name} compiled/interpreter mismatch",
        )


def test_flash_traced_offsets_compiled():
    """Dynamic q/k offsets via scalar prefetch — what ring attention feeds
    the kernel on rotated KV chunks — must lower and match the reference at
    several traced values without retracing."""
    q, k, v = _qkv(sq=128, sk=256)
    traces = {"n": 0}

    @jax.jit
    def fn(q, k, v, q_off, k_off):
        traces["n"] += 1
        return pallas_flash_attention(
            q, k, v, causal=True, q_offset=q_off, k_offset=k_off,
            block_q=128, block_k=128, interpret=False,
        )

    # Non-degenerate pairs only: a fully-masked chunk (every key after every
    # query) has undefined normalized output — see the fully_masked test.
    for q_off, k_off in [(0, 0), (256, 0), (256, 128)]:
        out = fn(q, k, v, jnp.int32(q_off), jnp.int32(k_off))
        with jax.default_matmul_precision("highest"):
            ref = blockwise_attention(
                q, k, v, causal=True, q_offset=q_off, k_offset=k_off
            )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2,
            err_msg=f"offsets ({q_off}, {k_off})",
        )
    assert traces["n"] == 1, "offsets retraced — not actually dynamic"


def test_fully_masked_chunk_convention():
    """Ring attention hands the kernel fully-masked chunks (causal, all keys
    after all queries). The contract that makes the lse-merge exact: zero
    output and lse == -inf, so the chunk's merge weight is exactly 0."""
    q, k, v = _qkv(sq=128, sk=128)
    out, lse = pallas_flash_attention_with_lse(
        q, k, v, causal=True, q_offset=jnp.int32(0), k_offset=jnp.int32(512),
        block_q=128, block_k=128, interpret=False,
    )
    assert float(jnp.max(jnp.abs(out))) == 0.0
    assert bool(jnp.all(jnp.isneginf(lse) | (lse < -1e29)))


def test_bf16_fwd_smoke():
    """bf16 is the production dtype; assert the compiled kernel lowers and
    stays sane (vs fp32 interpreter ground truth at bf16 tolerance)."""
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = pallas_flash_attention(q, k, v, causal=True, interpret=False)
    ref = pallas_flash_attention(
        jnp.float32(q), jnp.float32(k), jnp.float32(v), causal=True, interpret=True
    )
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=5e-2, atol=5e-2
    )


def test_int8_decode_matmul_parity():
    """DecodeQuant: int8-from-HBM matmul with the scale fused at the dot
    (generation._kernel's decode path) vs the fp32 kernel."""
    from accelerate_tpu.utils.quantization import (
        dequantize_decode_kernel,
        quantize_decode_kernel,
    )

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((4, 512, 256)) * 0.05, jnp.float32)
    x = jnp.asarray(rng.standard_normal((1, 512)), jnp.bfloat16)
    dq = quantize_decode_kernel(w)
    assert dq.data.dtype == jnp.int8

    @jax.jit
    def decode_dot(x, dq):
        wl = dq.data[0].astype(jnp.bfloat16) * dq.scales[0].astype(jnp.bfloat16)
        return x @ wl

    got = decode_dot(x, dq)
    ref = jnp.asarray(x, jnp.float32) @ w[0]
    # Bound: int8 symmetric quant error ~ amax/127 per weight; with 512-dim
    # contraction the relative output error stays well under 2%.
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref), rtol=5e-2, atol=5e-2
    )
    # Round-trip dequant agrees with what the decode dot consumed.
    back = dequantize_decode_kernel(dq, jnp.float32)
    assert float(jnp.max(jnp.abs(back - w))) < float(jnp.max(dq.scales)) * 0.51


def test_fp8_native_matches_qdq_on_chip():
    """The native f8-operand dot path vs the QDQ formulation, compiled on
    real hardware — catches an XLA fp8 legalization producing different
    numerics than the simulation (fwd and both grads)."""
    from accelerate_tpu.ops.fp8 import fp8_dot_general

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((64, 256)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((256, 128)), jnp.float32)
    dn = (((1,), (0,)), ((), ()))
    nat = fp8_dot_general("HYBRID", native=True)
    ref = fp8_dot_general("HYBRID", native=False)
    np.testing.assert_allclose(
        np.asarray(nat(x, w, dn)), np.asarray(ref(x, w, dn)), rtol=2e-3, atol=2e-3
    )
    gn = jax.grad(lambda x, w: jnp.sum(nat(x, w, dn) ** 2), argnums=(0, 1))(x, w)
    gr = jax.grad(lambda x, w: jnp.sum(ref(x, w, dn) ** 2), argnums=(0, 1))(x, w)
    for a, b in zip(gn, gr):
        cos = float(jnp.sum(a * b) / (jnp.linalg.norm(a) * jnp.linalg.norm(b)))
        assert cos > 0.99, cos


def test_fp8_lowering_has_f8_types():
    """The fp8 recipe must actually lower with float8 types on chip (QDQ
    converts at minimum; native f8 dots where the recipe enables them)."""
    from accelerate_tpu.ops.fp8 import fp8_dot_general

    dot = fp8_dot_general("HYBRID")
    x = jnp.zeros((128, 256), jnp.bfloat16)
    w = jnp.zeros((256, 128), jnp.bfloat16)
    txt = (
        jax.jit(lambda a, b: dot(a, b, (((1,), (0,)), ((), ()))))
        .lower(x, w)
        .as_text()
        .lower()
    )
    assert "f8e4m3" in txt or "f8e5m2" in txt, "no float8 types in lowered HLO"
