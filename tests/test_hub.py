"""HF-checkpoint interop: converted weights reproduce transformers logits.

The reference wraps transformers models directly, so the switch-over story
for its users is "your checkpoints load here". Each test builds a tiny
randomly-initialized transformers model on CPU, converts its state dict with
models/hub.py, and asserts fp32 logit parity between the torch forward and
the native flax forward.
"""

import numpy as np
import pytest
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from accelerate_tpu import Model
from accelerate_tpu.models import load_pretrained, model_from_pretrained
from accelerate_tpu.models.hub import llama_params_from_hf, llama_params_to_hf


def _logits(hf_model, *args):
    hf_model.eval()
    with torch.no_grad():
        return hf_model(*[torch.from_numpy(np.asarray(a)) for a in args]).logits.numpy()


def _ids(rng, vocab, shape):
    return rng.integers(0, vocab, shape).astype(np.int32)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0)


def _convert(hf_model, **kw):
    return model_from_pretrained(hf_model, dtype=jnp.float32, **kw)


def test_llama_logit_parity(rng):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg)
    ids = _ids(rng, 128, (2, 12))
    ours = _convert(hf)
    np.testing.assert_allclose(
        np.asarray(ours(ids)), _logits(hf, ids), rtol=2e-4, atol=2e-4
    )


def test_llama_roundtrip_to_hf(rng):
    """to_hf(from_hf(sd)) == sd exactly — export keeps reference-world layout."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, tie_word_embeddings=False,
    )
    torch.manual_seed(1)
    hf = transformers.LlamaForCausalLM(hf_cfg)
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    cfg, params, _ = load_pretrained(hf, dtype=jnp.float32)
    back = llama_params_to_hf(cfg, llama_params_from_hf(cfg, sd))
    for k, v in back.items():
        np.testing.assert_array_equal(v, sd[k], err_msg=k)


def test_gpt2_logit_parity(rng):
    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=64, n_layer=3, n_head=4,
    )
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(hf_cfg)
    ids = _ids(rng, 128, (2, 12))
    ours = _convert(hf)
    np.testing.assert_allclose(
        np.asarray(ours(ids)), _logits(hf, ids), rtol=2e-4, atol=2e-4
    )


def test_bert_logit_parity(rng):
    hf_cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=3, num_attention_heads=4,
        intermediate_size=128, max_position_embeddings=64,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0, num_labels=3,
    )
    torch.manual_seed(0)
    hf = transformers.BertForSequenceClassification(hf_cfg)
    ids = _ids(rng, 128, (2, 12))
    mask = np.ones_like(ids)
    ours = _convert(hf)
    np.testing.assert_allclose(
        np.asarray(ours(ids, mask)), _logits(hf, ids, mask), rtol=2e-4, atol=2e-4
    )


def test_t5_logit_parity(rng):
    hf_cfg = transformers.T5Config(
        vocab_size=128, d_model=64, d_kv=16, d_ff=128, num_layers=2,
        num_decoder_layers=2, num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=32, feed_forward_proj="relu",
        tie_word_embeddings=True, decoder_start_token_id=0,
    )
    torch.manual_seed(0)
    hf = transformers.T5ForConditionalGeneration(hf_cfg)
    ids = _ids(rng, 128, (2, 10))
    dec = _ids(rng, 128, (2, 7))
    ours = _convert(hf)
    hf.eval()
    with torch.no_grad():
        want = hf(
            input_ids=torch.from_numpy(ids.astype(np.int64)),
            decoder_input_ids=torch.from_numpy(dec.astype(np.int64)),
        ).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours(ids, dec)), want, rtol=2e-4, atol=2e-4)


def test_mixtral_logit_parity(rng):
    hf_cfg = transformers.MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, num_local_experts=4,
        num_experts_per_tok=2, max_position_embeddings=64,
    )
    torch.manual_seed(0)
    hf = transformers.MixtralForCausalLM(hf_cfg)
    ids = _ids(rng, 128, (1, 8))
    cfg, params, cls = load_pretrained(hf, dtype=jnp.float32)
    # Capacity must cover every routed token or GShard dispatch drops some and
    # parity with HF's dropless top-k breaks.
    import dataclasses

    cfg = dataclasses.replace(cfg, capacity_factor=float(cfg.num_local_experts))
    ours = Model(module=cls(cfg), params=params)
    np.testing.assert_allclose(
        np.asarray(ours(ids)), _logits(hf, ids), rtol=5e-4, atol=5e-4
    )


def test_load_pretrained_from_directory(tmp_path, rng):
    """config.json + model.safetensors on disk — the checkpoint-dir path."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, tie_word_embeddings=False,
    )
    torch.manual_seed(2)
    hf = transformers.LlamaForCausalLM(hf_cfg)
    hf.save_pretrained(tmp_path, safe_serialization=True)
    ours = model_from_pretrained(str(tmp_path), dtype=jnp.float32)
    ids = _ids(rng, 64, (2, 8))
    np.testing.assert_allclose(
        np.asarray(ours(ids)), _logits(hf, ids), rtol=2e-4, atol=2e-4
    )


def test_unknown_family_raises():
    with pytest.raises(ValueError, match="Unsupported model family"):
        load_pretrained(({"model_type": "umbrellanet"}, {}))


def test_mistral_logit_parity(rng):
    """model_type 'mistral' routes through the Llama family (GQA, no sliding
    window at these lengths)."""
    hf_cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        sliding_window=None,
    )
    torch.manual_seed(0)
    hf = transformers.MistralForCausalLM(hf_cfg)
    ids = _ids(rng, 128, (2, 10))
    ours = _convert(hf)
    np.testing.assert_allclose(
        np.asarray(ours(ids)), _logits(hf, ids), rtol=2e-4, atol=2e-4
    )


def test_phi3_logit_parity(rng):
    """model_type 'phi3' routes through the Llama family after splitting the
    fused qkv_proj / gate_up_proj weights."""
    hf_cfg = transformers.Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        pad_token_id=0,
    )
    torch.manual_seed(0)
    hf = transformers.Phi3ForCausalLM(hf_cfg)
    ids = _ids(rng, 128, (2, 10))
    ours = _convert(hf)
    np.testing.assert_allclose(
        np.asarray(ours(ids)), _logits(hf, ids), rtol=2e-4, atol=2e-4
    )


def test_phi3_longrope_rejected(rng):
    """Phi-3-128k-style rope_scaling must fail loudly, not convert wrong."""
    hf_cfg = transformers.Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        original_max_position_embeddings=32, pad_token_id=0,
        rope_scaling={
            "type": "longrope",
            "short_factor": [1.0] * 8,
            "long_factor": [2.0] * 8,
        },
    )
    torch.manual_seed(0)
    hf = transformers.Phi3ForCausalLM(hf_cfg)
    with pytest.raises(ValueError, match="longrope"):
        _convert(hf)


def test_qwen2_logit_parity_attention_bias(rng):
    """Qwen2 = Llama architecture + q/k/v biases: conversion must carry them."""
    hf_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = transformers.Qwen2ForCausalLM(hf_cfg)
    cfg, params, cls = __import__("accelerate_tpu.models", fromlist=["load_pretrained"]).load_pretrained(
        hf, dtype=jnp.float32
    )
    assert cfg.attention_bias, "Qwen2 conversion must enable attention_bias"
    assert "bias" in params["model"]["layers"]["block"]["self_attn"]["q_proj"]
    ids = _ids(rng, 128, (2, 10))
    got = np.asarray(Model(module=cls(cfg), params=params)(ids))
    np.testing.assert_allclose(got, _logits(hf, ids), rtol=2e-4, atol=2e-4)


def test_qwen2_generates_like_transformers(rng):
    from accelerate_tpu import generate

    hf_cfg = transformers.Qwen2Config(
        vocab_size=96, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=False,
    )
    torch.manual_seed(1)
    hf = transformers.Qwen2ForCausalLM(hf_cfg)
    hf.eval()
    ids = rng.integers(0, 96, (1, 6)).astype(np.int64)
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(ids), max_new_tokens=5, do_sample=False, pad_token_id=0
        ).numpy()
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    got = generate(ours, ids.astype(np.int32), max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(got), want.astype(np.int32))


def test_gemma_logit_parity(rng):
    """Gemma quirks: GeGLU, RMSNorm(1+w), sqrt(hidden)-scaled embeddings,
    head_dim decoupled from hidden/heads, tied head."""
    hf_cfg = transformers.GemmaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        max_position_embeddings=64,
    )
    torch.manual_seed(0)
    hf = transformers.GemmaForCausalLM(hf_cfg)
    ids = _ids(rng, 128, (2, 10))
    ours = _convert(hf)
    assert ours.module.config.rms_norm_plus_one and ours.module.config.scale_embeddings
    np.testing.assert_allclose(
        np.asarray(ours(ids)), _logits(hf, ids), rtol=3e-4, atol=3e-4
    )


def test_gemma_generates_like_transformers(rng):
    from accelerate_tpu import generate

    hf_cfg = transformers.GemmaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=8,
        max_position_embeddings=64,
    )
    torch.manual_seed(2)
    hf = transformers.GemmaForCausalLM(hf_cfg)
    hf.eval()
    ids = rng.integers(1, 96, (1, 6)).astype(np.int64)
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(ids), max_new_tokens=5, do_sample=False, pad_token_id=0
        ).numpy()
    ours = model_from_pretrained(hf, dtype=jnp.float32)
    got = generate(ours, ids.astype(np.int32), max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(got), want.astype(np.int32))
