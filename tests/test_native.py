"""Native host-runtime kernels (native/host_runtime.cpp) + loader wiring."""

import numpy as np
import pytest


def _lib_available():
    from accelerate_tpu import native

    return native.get_lib() is not None


# Only the kernel-vs-numpy comparisons need the compiled library; the
# fallback paths, loaders and prefetcher must stay tested on toolchain-less
# hosts (that is exactly where they run in production).
requires_lib = pytest.mark.skipif(
    not _lib_available(), reason="g++ unavailable — native kernels disabled"
)


@requires_lib
def test_gather_rows_matches_numpy():
    from accelerate_tpu import native

    rng = np.random.default_rng(0)
    src = rng.normal(size=(1000, 33)).astype(np.float32)
    idx = rng.integers(0, 1000, size=257)
    out = native.gather_rows(src, idx, force=True)
    np.testing.assert_array_equal(out, src[idx])


def test_gather_rows_noncontiguous_falls_back():
    from accelerate_tpu import native

    rng = np.random.default_rng(1)
    src = rng.normal(size=(100, 64)).astype(np.float32)[:, ::2]  # not C-contiguous
    idx = np.arange(50)
    out = native.gather_rows(src, idx, force=True)
    np.testing.assert_array_equal(out, src[idx])


@requires_lib
def test_gather_columns_matches_numpy():
    from accelerate_tpu import native

    rng = np.random.default_rng(2)
    cols = {
        "x": rng.normal(size=(500, 16)).astype(np.float32),
        "y": rng.integers(0, 9, size=(500,)).astype(np.int64),
        "z": rng.normal(size=(500, 4, 3)).astype(np.float64),
    }
    idx = rng.integers(0, 500, size=123)
    out = native.gather_columns(cols, idx, force=True)
    for k in cols:
        np.testing.assert_array_equal(out[k], cols[k][idx])


@requires_lib
def test_stack_items_matches_numpy():
    from accelerate_tpu import native

    rng = np.random.default_rng(3)
    items = [rng.normal(size=(17, 5)).astype(np.float32) for _ in range(64)]
    out = native.stack_items(items, force=True)
    np.testing.assert_array_equal(out, np.stack(items))


def test_column_dataset_loader_batches():
    """ColumnDataset assembles identical batches to per-item collation."""
    from accelerate_tpu.data_loader import ColumnDataset, prepare_data_loader

    rng = np.random.default_rng(4)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = rng.integers(0, 2, size=(64,)).astype(np.int32)
    ds = ColumnDataset(x=x, y=y)
    assert len(ds) == 64
    assert set(ds[3]) == {"x", "y"}

    class _Spec:
        def __init__(self, dataset, batch_size):
            self.dataset = dataset
            self.batch_size = batch_size
            self.sampler = None
            self.drop_last = False

    dl = prepare_data_loader(_Spec(ds, 16), put_on_device=False, use_seedable_sampler=False)
    seen_x, seen_y = [], []
    for b in dl:
        assert b["x"].shape == (16, 8)
        seen_x.append(np.asarray(b["x"]))
        seen_y.append(np.asarray(b["y"]))
    np.testing.assert_array_equal(np.concatenate(seen_x), x)
    np.testing.assert_array_equal(np.concatenate(seen_y), y)


def test_ndarray_dataset_fast_path():
    from accelerate_tpu.data_loader import prepare_data_loader

    data = np.arange(32 * 4, dtype=np.float32).reshape(32, 4)

    class _Spec:
        def __init__(self, dataset, batch_size):
            self.dataset = dataset
            self.batch_size = batch_size
            self.sampler = None
            self.drop_last = False

    dl = prepare_data_loader(_Spec(data, 8), put_on_device=False, use_seedable_sampler=False)
    batches = [np.asarray(b) for b in dl]
    np.testing.assert_array_equal(np.concatenate(batches), data)


def test_prefetch_iterator_order_and_errors():
    from accelerate_tpu.data_loader import _PrefetchIterator

    it = _PrefetchIterator(iter(range(100)), prefetch_size=4)
    assert list(it) == list(range(100))

    def boom():
        yield 1
        raise RuntimeError("inner failure")

    it = _PrefetchIterator(boom(), prefetch_size=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="inner failure"):
        next(it)
    it.close()


def test_prefetch_overlaps_producer_with_step():
    """The MpDeviceLoader-role claim, asserted: with the prefetch thread, a
    producer whose cost is a large fraction of the step time adds (almost)
    nothing to wall-clock; without it, the producer serializes. Margins are
    deliberately wide — this is a regression gate on the overlap mechanism,
    not a microbenchmark (numbers: benchmarks/input_pipeline_bench.py)."""
    import time

    from accelerate_tpu.data_loader import _PrefetchIterator

    step_s, produce_s, n = 0.02, 0.012, 25

    def producer():
        for i in range(n):
            time.sleep(produce_s)  # emulates dataset read + collation
            yield i

    def walk(it):
        next(it)
        t0 = time.perf_counter()
        k = 0
        for _ in it:
            time.sleep(step_s)  # emulates a dispatched device step
            k += 1
        return (time.perf_counter() - t0) / k

    overlapped = walk(iter(_PrefetchIterator(producer(), prefetch_size=2)))
    serial = walk(iter(producer()))
    assert overlapped < step_s + 0.6 * produce_s, (overlapped, serial)
    assert serial > step_s + 0.8 * produce_s, (overlapped, serial)
    assert overlapped < serial, (overlapped, serial)


def test_prefetch_close_mid_iteration():
    from accelerate_tpu.data_loader import _PrefetchIterator

    it = _PrefetchIterator(iter(range(10_000)), prefetch_size=2)
    assert next(it) == 0
    it.close()  # must not hang


def test_gather_rows_negative_and_bad_indices():
    """Native path must match numpy semantics for negatives, raise on
    out-of-range, and honor boolean masks (review regression)."""
    from accelerate_tpu import native

    src = np.arange(40, dtype=np.float32).reshape(10, 4)
    np.testing.assert_array_equal(
        native.gather_rows(src, [-1, 0, -10], force=True), src[[-1, 0, -10]]
    )
    with pytest.raises(IndexError):
        native.gather_rows(src, [0, 10], force=True)
    mask = np.zeros(10, bool)
    mask[3] = mask[7] = True
    np.testing.assert_array_equal(native.gather_rows(src, mask, force=True), src[mask])
    cols = {"x": src}
    np.testing.assert_array_equal(
        native.gather_columns(cols, [-2, 1], force=True)["x"], src[[-2, 1]]
    )


def test_dispatcher_disables_prefetch_multiprocess():
    """Dispatch-mode collectives must stay on the main thread (single-process
    here, so prefetch stays on; the guard only fires with >1 process)."""
    from accelerate_tpu.data_loader import DataLoaderDispatcher, prepare_data_loader

    class _Spec:
        def __init__(self, dataset, batch_size):
            self.dataset = dataset
            self.batch_size = batch_size
            self.sampler = None
            self.drop_last = False

    data = np.arange(32, dtype=np.int32)
    dl = prepare_data_loader(
        _Spec(data, 8), dispatch_batches=True, put_on_device=False, prefetch_size=0
    )
    assert isinstance(dl, DataLoaderDispatcher)
    assert dl.prefetch_size == 0  # explicit opt-out plumbs through


@requires_lib
def test_load_safetensors_fast_matches_library(tmp_path):
    """Native parallel pread loader == safetensors lib, all dtypes incl bf16."""
    import ml_dtypes
    from safetensors.numpy import save_file

    from accelerate_tpu.native import load_safetensors_fast

    rng = np.random.default_rng(0)
    tensors = {
        "a/f32": rng.normal(size=(64, 128)).astype(np.float32),
        "b/bf16": rng.normal(size=(32, 16)).astype(ml_dtypes.bfloat16),
        "c/i32": rng.integers(-5, 5, size=(7,)).astype(np.int32),
        "d/scalarish": np.asarray([3.0], np.float32),
    }
    path = str(tmp_path / "m.safetensors")
    save_file(tensors, path)
    out = load_safetensors_fast(path, force=True)
    assert out is not None, "native loader must engage when forced"
    assert set(out) == set(tensors)
    for k in tensors:
        assert out[k].dtype == tensors[k].dtype
        np.testing.assert_array_equal(
            out[k].view(np.uint8), tensors[k].view(np.uint8), err_msg=k
        )


def test_load_safetensors_fast_missing_file():
    from accelerate_tpu.native import load_safetensors_fast

    assert load_safetensors_fast("/nonexistent/x.safetensors", force=True) is None


@requires_lib
def test_save_safetensors_fast_roundtrips(tmp_path):
    """Native parallel pwrite writer: the safetensors lib AND the native
    reader both load it back bit-exact, all dtypes incl bf16."""
    import ml_dtypes
    from safetensors.numpy import load_file

    from accelerate_tpu.native import load_safetensors_fast, save_safetensors_fast

    rng = np.random.default_rng(1)
    tensors = {
        "a/f32": rng.normal(size=(64, 128)).astype(np.float32),
        "b/bf16": rng.normal(size=(32, 16)).astype(ml_dtypes.bfloat16),
        "c/i64": rng.integers(-5, 5, size=(9,)).astype(np.int64),
        "d/bool": np.asarray([True, False, True]),
    }
    path = str(tmp_path / "w.safetensors")
    assert save_safetensors_fast(tensors, path, force=True)
    via_lib = load_file(path)
    via_native = load_safetensors_fast(path, force=True)
    for k in tensors:
        for out in (via_lib, via_native):
            assert out[k].dtype == tensors[k].dtype, k
            np.testing.assert_array_equal(
                out[k].view(np.uint8), tensors[k].view(np.uint8), err_msg=k
            )


@requires_lib
def test_save_safetensors_fast_rejects_object_dtype(tmp_path):
    from accelerate_tpu.native import save_safetensors_fast

    bad = {"x": np.asarray([object()], dtype=object)}
    assert save_safetensors_fast(bad, str(tmp_path / "bad.safetensors"), force=True) is False


def test_save_safetensors_unified_path_uses_writer(tmp_path):
    """utils.other.save_safetensors round-trips through whichever path the
    size gate picks."""
    from safetensors.numpy import load_file

    from accelerate_tpu.utils.other import save_safetensors

    big = {"w": np.arange(2**18, dtype=np.float32).reshape(512, 512)}
    path = str(tmp_path / "u.safetensors")
    save_safetensors(big, path)
    out = load_file(path)
    np.testing.assert_array_equal(out["w"], big["w"])
