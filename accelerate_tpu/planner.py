"""Auto-parallelism planner (layer L11 — decision-making).

Every mechanism below this file already exists: ``ParallelismConfig`` builds
any (dp_replicate, dp_shard, cp, sp, tp, pp) mesh, ``plan_parameter_sharding``
shards a param tree over it, and ``utils/estimate_memory.py`` prices the
per-chip working set of any layout without touching a device. What the user
still had to do by hand was *pick* the layout — and on a new model or a new
slice shape the first pick is usually wrong in one of two expensive ways
(OOM, or an ICI-saturated layout that trains at half speed).

:class:`Planner` automates that choice:

1. **Enumerate** every valid factorization of the device count into
   ``(dp_replicate, dp_shard, tp, cp, pp)`` degrees (plus an ``ep`` degree
   riding the dp_shard/tp axes for MoE models), respecting the model's
   divisibility constraints — ``heads % tp``, ``kv_heads % tp``,
   ``layers % pp``, ``seq % cp``, ``experts % ep`` — and any user-pinned
   axes.
2. **Score** each candidate twice: per-chip HBM through the SAME
   ``estimate_per_chip`` path the trainer and ``estimate-memory`` CLI use
   (no drift possible), and predicted step time through an analytic cost
   model — a compute roofline (layout-invariant for balanced
   factorizations) plus per-axis collective volume (FSDP all-gather +
   reduce-scatter, dp_replicate all-reduce, TP activation all-reduces, CP
   ring rotation, PP activation sends and fill/drain bubble) over a
   configurable ICI/DCN :class:`BandwidthTable`.
3. **Escalate** a candidate that misses the HBM budget through the remat /
   microbatch ladder — no remat → selective ("flash") → full ("minimal") →
   split the step into more microbatches — before rejecting it; deeper
   ``dp_shard`` escalation falls out of the candidate ranking (those
   layouts simply fit where shallower ones don't).
4. **Emit** a versioned :class:`ParallelPlan` JSON artifact: the chosen
   layout + remat policy + microbatch count, the predicted step time and
   per-chip HBM with the full cost breakdown, a rejection log for the
   runner-ups, and a calibration block that telemetry fills in with
   measured step time / peak HBM after N real steps
   (:func:`record_calibration`) so repeated runs tighten the
   bandwidth/efficiency constants.

Plan artifacts are deterministic — same inputs produce byte-identical JSON
(no timestamps, sorted keys, rounded floats) — and cached under
``<project_dir>/plans/`` keyed by a hash of every search input, so a second
launch loads the plan instead of re-searching.

Entry points: ``Accelerator(parallelism_config="auto")`` or an
:class:`~accelerate_tpu.utils.AutoPlanKwargs` handler (resolved at
``prepare()``), the ``accelerate-tpu plan`` CLI, or this module directly.
Related work: arXiv:2004.13336 (cross-replica weight-update sharding as a
memory/communication trade) and arXiv:2112.01075 (collective-based array
redistribution) — both resolve layout choice with cheap analytic models,
which is all a first-launch decision needs.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import tempfile
from typing import Any, Optional

import numpy as np

from .logging import get_logger
from .parallelism_config import ParallelismConfig


class _StateSafeLogger:
    """The planner runs standalone too (`accelerate-tpu plan` builds no
    Accelerator), where the multi-process adapter refuses to log before
    PartialState exists — fall back to a plain stdlib logger there."""

    def __init__(self, name: str):
        self._adapter = get_logger(name)
        import logging as _logging

        self._plain = _logging.getLogger(name)

    def _log(self, level: str, msg, *args, **kwargs):
        try:
            getattr(self._adapter, level)(msg, *args, **kwargs)
        except RuntimeError:  # no PartialState yet
            kwargs.pop("main_process_only", None)
            kwargs.pop("in_order", None)
            getattr(self._plain, level)(msg, *args, **kwargs)

    def info(self, msg, *args, **kwargs):
        self._log("info", msg, *args, **kwargs)

    def warning(self, msg, *args, **kwargs):
        self._log("warning", msg, *args, **kwargs)


logger = _StateSafeLogger(__name__)

PLAN_VERSION = 1
GiB = 1024 ** 3

#: Axes the search may raise above 1 by default. ``cp``/``pp``/``ep`` are
#: enumerable too (the CLI enables them all) but need model/loss support the
#: in-training auto path cannot verify, so AutoPlanKwargs keeps them opt-in.
DEFAULT_SEARCH_AXES = ("dp_replicate", "dp_shard", "tp")
ALL_SEARCH_AXES = ("dp_replicate", "dp_shard", "tp", "cp", "pp", "ep")

#: The remat escalation ladder: none → selective (flash residuals kept) →
#: full (recompute everything). Walked per candidate until it fits.
REMAT_LADDER = ((False, "flash"), (True, "flash"), (True, "minimal"))

#: Backward-pass recompute FLOPs per ladder rung, as a multiplier on the
#: 6·P·T roofline (fwd=2, bwd=4; selective remat re-runs most of the fwd
#: ≈ +1.7/6, full remat re-runs all of it ≈ +2/6).
REMAT_COMPUTE_COST = {
    (False, "flash"): 1.0,
    (True, "flash"): 1.28,
    (True, "minimal"): 1.33,
}


class PlannerError(ValueError):
    """No candidate satisfies the constraints (bad pins, indivisible axes)."""


class PlanVersionError(ValueError):
    """Plan artifact written by an incompatible planner version."""


# ----------------------------------------------------------------------
# Bandwidth / efficiency table
# ----------------------------------------------------------------------


@dataclasses.dataclass
class BandwidthTable:
    """Analytic-model constants. Defaults describe a v5e pod slice; every
    field is overridable (AutoPlanKwargs.bandwidths / ``plan --bandwidth``)
    and ``mfu`` + ``collective_efficiency`` are the two the calibration loop
    tightens from measured steps."""

    ici_gbps: float = 90.0          # per-chip ICI bandwidth, GB/s
    dcn_gbps: float = 6.25          # per-chip DCN bandwidth, GB/s (50 Gb/s)
    flops_per_chip: float = 197e12  # peak bf16 FLOP/s (v5e: 197 TFLOP/s)
    mfu: float = 0.4                # achievable model-FLOPs utilization
    collective_efficiency: float = 0.7   # achieved fraction of link bandwidth
    ici_domain: int = 256           # largest device count one ICI fabric spans
    microbatch_overhead_s: float = 1e-4  # per-microbatch dispatch overhead
    # Fraction of data-parallel comm (FSDP all-gather/reduce-scatter, DP
    # all-reduce) XLA's latency-hiding scheduler hides behind compute. TP/CP
    # collectives sit on the critical path and never overlap here.
    dp_overlap: float = 0.7

    def to_dict(self) -> dict:
        return {f.name: getattr(self, f.name) for f in dataclasses.fields(self)}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "BandwidthTable":
        if not d:
            return cls()
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown BandwidthTable field(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}"
            )
        return cls(**d)

    def axis_gbps(self, axis: str, n_devices: int) -> float:
        """Bandwidth serving collectives over ``axis``. Inner mesh axes
        (tp/sp/cp) are laid on ICI-adjacent chips by build_mesh; the outer
        data-parallel axes spill onto DCN once the slice outgrows one ICI
        domain."""
        if axis in ("tp", "sp", "cp"):
            return self.ici_gbps
        return self.ici_gbps if n_devices <= self.ici_domain else self.dcn_gbps

    def handoff_gbps(self, n_devices: int) -> float:
        """Bandwidth of the prefill→decode KV-page handoff link (disagg.py).
        Both slices of a split that fits one ICI domain are ICI-adjacent;
        a split spanning domains streams pages over DCN."""
        link = self.ici_gbps if n_devices <= self.ici_domain else self.dcn_gbps
        return link * self.collective_efficiency

    def kv_bytes_per_token(self, cfg, dtype=None) -> int:
        """Dtype-aware KV footprint of one token (both K and V, all
        layers). ``dtype`` overrides the config's cache dtype — pass the
        actual page dtype (e.g. int8 quantized pages) so the handoff link
        is priced on the bytes it really moves, not a hard-coded bf16."""
        return kv_bytes_per_token(cfg, dtype=dtype)


# ----------------------------------------------------------------------
# Model profile (the divisibility constraints + roofline dims)
# ----------------------------------------------------------------------


@dataclasses.dataclass
class ModelProfile:
    """The handful of numbers the enumerator and cost model need. Built from
    any config the builtin families produce (``from_config``); ``params`` is
    exact when a module is supplied (one eval_shape) and closed-form
    otherwise."""

    params: int
    hidden: int
    heads: int
    kv_heads: int
    layers: int
    intermediate: int
    vocab: int
    experts: int = 0  # 0 = dense model
    label: str = "model"

    @classmethod
    def from_config(cls, cfg, module=None, label: Optional[str] = None) -> "ModelProfile":
        from .utils.estimate_memory import _decoder_dims, abstract_param_shapes

        try:
            h, nh, L, nkv, d, inter, vocab = _decoder_dims(cfg)
        except AttributeError as e:
            raise PlannerError(
                f"cannot profile {type(cfg).__name__}: it lacks the decoder "
                f"dims the planner constrains on ({e}). Pass an explicit "
                f"ParallelismConfig instead of 'auto' for this model."
            ) from None
        experts = int(getattr(cfg, "num_local_experts", 0) or 0)
        if module is not None:
            import jax

            shapes = abstract_param_shapes(module)
            params = sum(
                math.prod(s.shape)
                for s in jax.tree_util.tree_leaves(shapes)
                if hasattr(s, "shape")
            )
        else:
            mlp = 3 * h * inter if getattr(cfg, "mlp_gated", True) else 2 * h * inter
            if experts:
                mlp = mlp * experts + h * experts  # experts + router
            per_layer = (nh + 2 * nkv) * d * h + nh * d * h + mlp + 2 * h
            tied = getattr(cfg, "tie_word_embeddings", False)
            params = vocab * h * (1 if tied else 2) + L * per_layer + h
        return cls(
            params=int(params), hidden=h, heads=nh, kv_heads=nkv, layers=L,
            intermediate=inter, vocab=vocab, experts=experts,
            label=label or type(cfg).__name__,
        )

    def to_dict(self) -> dict:
        return {f.name: getattr(self, f.name) for f in dataclasses.fields(self)}


def default_tp_rules(module, cfg) -> Optional[list]:
    """Family TP-rule table for a builtin module, or None. Lets the auto
    path price tp>1 candidates with real sharding even when the caller never
    passed ``tp_rules`` (without rules, TP'd layouts look fully replicated
    to the memory model and are penalized out of the race)."""
    name = type(module).__name__
    scan = getattr(cfg, "scan_layers", True)
    try:
        if "Mixtral" in name:
            from .models.moe import mixtral_tp_rules

            return mixtral_tp_rules(scan)
        if "Llama" in name:
            from .models.llama import llama_tp_rules

            return llama_tp_rules(scan)
        if "OPT" in name:
            from .models.opt import opt_tp_rules

            return opt_tp_rules(scan)
        if "NeoX" in name:
            from .models.neox import neox_tp_rules

            return neox_tp_rules(scan)
        if "GPT2" in name:
            from .models.gpt2 import gpt2_tp_rules

            return gpt2_tp_rules(scan)
    except ImportError:  # pragma: no cover
        pass
    return None


# ----------------------------------------------------------------------
# Candidate enumeration
# ----------------------------------------------------------------------


def _divisors(n: int) -> list[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_layouts(
    n_devices: int,
    profile: ModelProfile,
    *,
    seq: int,
    axes: tuple[str, ...] = ALL_SEARCH_AXES,
    pinned: Optional[dict] = None,
) -> list[ParallelismConfig]:
    """Every valid ``ParallelismConfig`` whose mesh covers exactly
    ``n_devices``, in a deterministic order.

    Constraints enforced per candidate:
      - ``dp_replicate * dp_shard * cp * tp * pp == n_devices``
      - ``tp`` divides heads, kv_heads and hidden (Megatron-TP shards all 3)
      - ``pp`` divides layers
      - ``cp`` divides seq
      - ``ep`` divides experts (MoE only) and must be a product of whole
        (dp_shard, tp) axes — ParallelismConfig.ep_axes validates.

    ``pinned`` maps axis name → forced degree (``{"tp": 2}``); an axis not in
    ``axes`` and not pinned stays at 1.
    """
    pinned = dict(pinned or {})
    valid_axes = set(ALL_SEARCH_AXES)
    for ax in pinned:
        if ax not in valid_axes:
            raise PlannerError(
                f"pinned axis {ax!r} is not plannable (valid: {sorted(valid_axes)})"
            )

    def _choices(axis: str, constraint) -> list[int]:
        if axis in pinned:
            v = int(pinned[axis])
            return [v] if constraint(v) else []
        if axis not in axes:
            return [1]
        return [d for d in _divisors(n_devices) if constraint(d)]

    tp_choices = _choices(
        "tp",
        lambda t: profile.heads % t == 0
        and profile.kv_heads % t == 0
        and profile.hidden % t == 0,
    )
    pp_choices = _choices("pp", lambda p: p <= profile.layers and profile.layers % p == 0)
    cp_choices = _choices("cp", lambda c: seq % c == 0)

    out: list[ParallelismConfig] = []
    for pp in pp_choices:
        for tp in tp_choices:
            for cp in cp_choices:
                fixed = pp * tp * cp
                if n_devices % fixed != 0:
                    continue
                dp_total = n_devices // fixed
                for dp_shard in _choices("dp_shard", lambda s: dp_total % s == 0):
                    if dp_total % dp_shard != 0:
                        continue
                    dp_replicate = dp_total // dp_shard
                    if "dp_replicate" in pinned and dp_replicate != int(pinned["dp_replicate"]):
                        continue
                    if "dp_replicate" not in axes and "dp_replicate" not in pinned and dp_replicate != 1:
                        continue
                    ep_choices = [1]
                    if profile.experts:
                        ep_choices = _choices(
                            "ep", lambda e: e <= profile.experts and profile.experts % e == 0
                        )
                    for ep in ep_choices:
                        try:
                            pc = ParallelismConfig(
                                dp_replicate_size=dp_replicate,
                                dp_shard_size=dp_shard,
                                cp_size=cp,
                                tp_size=tp,
                                pp_size=pp,
                                ep_size=ep,
                            )
                            pc.ep_axes  # ep must be a product of whole axes
                        except ValueError:
                            continue
                        out.append(pc)
    if not out:
        raise PlannerError(
            f"no valid layout for {n_devices} devices with pins {pinned or '{}'} "
            f"(heads={profile.heads}, kv_heads={profile.kv_heads}, "
            f"layers={profile.layers}, seq={seq}"
            + (f", experts={profile.experts}" if profile.experts else "")
            + ") — relax a pin or change the device count."
        )
    return out


# ----------------------------------------------------------------------
# Analytic step-time cost model
# ----------------------------------------------------------------------


@dataclasses.dataclass
class CostBreakdown:
    """Per-step predicted seconds and per-axis collective volume (bytes per
    chip per step) — the evidence trail stored in the plan artifact."""

    compute_s: float = 0.0
    fsdp_comm_s: float = 0.0
    dp_comm_s: float = 0.0
    tp_comm_s: float = 0.0
    cp_comm_s: float = 0.0
    pp_comm_s: float = 0.0
    fsdp_bytes: int = 0
    dp_bytes: int = 0
    tp_bytes: int = 0
    cp_bytes: int = 0
    pp_bytes: int = 0
    bubble_fraction: float = 0.0
    microbatch_overhead_s: float = 0.0
    step_s: float = 0.0

    @property
    def comm_s(self) -> float:
        return (self.fsdp_comm_s + self.dp_comm_s + self.tp_comm_s
                + self.cp_comm_s + self.pp_comm_s)

    @property
    def collective_bytes(self) -> int:
        return (self.fsdp_bytes + self.dp_bytes + self.tp_bytes
                + self.cp_bytes + self.pp_bytes)

    def to_dict(self) -> dict:
        d = {f.name: getattr(self, f.name) for f in dataclasses.fields(self)}
        d["comm_s"] = self.comm_s
        d["collective_bytes"] = self.collective_bytes
        return {k: (_round6(v) if isinstance(v, float) else v) for k, v in d.items()}


def _round6(x: float) -> float:
    """Stable float rounding so plan JSON is byte-identical across runs."""
    return float(f"{x:.6g}")


def predict_step_time(
    profile: ModelProfile,
    pc: ParallelismConfig,
    bw: BandwidthTable,
    *,
    seq: int,
    per_chip_batch: int,
    microbatches: int = 1,
    compute_bytes: int = 2,
    master_bytes: int = 4,
    params_sharded: bool = True,
    compute_multiplier: float = 1.0,
) -> CostBreakdown:
    """Predicted seconds for ONE optimizer step of the global batch under
    layout ``pc``.

    Model (documented, deliberately cheap — a ranking function, not a
    simulator):

    - **Compute roofline**: total step FLOPs ≈ 6 · params · global_tokens
      (fwd + bwd), spread evenly over every device — layout-invariant for
      balanced factorizations, discounted by ``bw.mfu``.
    - **FSDP** (dp_shard > 1, sharded params): per step each chip
      all-gathers its parameter shard twice (fwd + bwd) and reduce-scatters
      grads once → 3 · P_local · (d−1)/d bytes at ``master_bytes``.
    - **dp_replicate**: one grad all-reduce → 2 · P_local · (d−1)/d.
    - **TP**: per layer 2 fwd all-reduces of the (B·S_local·H) activation,
      doubled for bwd → 8 · (t−1)/t · B·S_local·H · compute_bytes · layers.
    - **CP ring**: per layer, rotate K+V around the ring —
      2 · B·S_local·kv_dim · (c−1) bytes, doubled for bwd.
    - **PP**: boundary activation sends (per microbatch, per stage edge) and
      the fill/drain bubble: step time scales by
      ``(m + pp − 1)/m`` (bubble fraction ``(pp−1)/(m+pp−1)``), plus a fixed
      per-microbatch dispatch overhead that keeps the microbatch ladder from
      degenerating to m→∞.
    - **Remat**: callers pass ``compute_multiplier`` > 1 for rematerialized
      rungs (the backward recompute FLOPs — see ``REMAT_COMPUTE_COST``) so
      the escalation ladder pays for the memory it saves.
    """
    n = pc.total_size
    dp = pc.dp_size
    # The workload is held CONSTANT across candidates so step times compare:
    # ``per_chip_batch`` means samples/chip at pure data parallelism, i.e. a
    # global batch of per_chip_batch · n samples every layout must process.
    # Each data-parallel rank (a tp×cp×pp group) then carries
    # global_batch / dp samples.
    global_tokens = per_chip_batch * n * seq
    batch_per_rank = per_chip_batch * n / max(1, dp)
    seq_local = seq // max(1, pc.cp_size * pc.sp_size)
    eff_flops = bw.flops_per_chip * bw.mfu
    compute_s = (
        6.0 * profile.params * global_tokens / n / eff_flops * compute_multiplier
    )

    # Params a chip touches after the model-sharding axes split them.
    p_local = profile.params / (pc.tp_size * pc.pp_size)
    coll_eff = bw.collective_efficiency
    out = CostBreakdown(compute_s=compute_s)

    d = pc.dp_shard_size
    if d > 1 and params_sharded:
        vol = 3.0 * p_local * master_bytes * (d - 1) / d
        out.fsdp_bytes = int(vol)
        out.fsdp_comm_s = vol / (bw.axis_gbps("dp_shard", n) * 1e9 * coll_eff)
    elif d > 1:
        # Unsharded params on a dp_shard axis reduce like dp_replicate.
        vol = 2.0 * p_local * master_bytes * (d - 1) / d
        out.dp_bytes += int(vol)
        out.dp_comm_s += vol / (bw.axis_gbps("dp_shard", n) * 1e9 * coll_eff)

    r = pc.dp_replicate_size
    if r > 1:
        vol = 2.0 * p_local * master_bytes * (r - 1) / r
        out.dp_bytes += int(vol)
        out.dp_comm_s += vol / (bw.axis_gbps("dp_replicate", n) * 1e9 * coll_eff)

    t = pc.tp_size
    if t > 1:
        act = batch_per_rank * seq_local * profile.hidden * compute_bytes
        vol = 8.0 * act * (t - 1) / t * profile.layers / pc.pp_size
        out.tp_bytes = int(vol)
        out.tp_comm_s = vol / (bw.axis_gbps("tp", n) * 1e9 * coll_eff)

    c = pc.cp_size
    if c > 1:
        kv_dim = profile.kv_heads * (profile.hidden // profile.heads)
        vol = 4.0 * batch_per_rank * seq_local * kv_dim * compute_bytes \
            * (c - 1) * profile.layers / pc.pp_size
        out.cp_bytes = int(vol)
        out.cp_comm_s = vol / (bw.axis_gbps("cp", n) * 1e9 * coll_eff)

    p = pc.pp_size
    m = max(1, microbatches)
    if p > 1:
        # Per-microbatch boundary sends × m microbatches = the full rank
        # batch's activations crossing each of the (p-1) stage edges, fwd+bwd.
        act = batch_per_rank * seq_local * profile.hidden * compute_bytes
        vol = 2.0 * act * (p - 1)
        out.pp_bytes = int(vol)
        out.pp_comm_s = vol / (bw.axis_gbps("pp", n) * 1e9 * coll_eff)
        out.bubble_fraction = (p - 1) / (m + p - 1)
    out.microbatch_overhead_s = bw.microbatch_overhead_s * m

    # Data-parallel collectives overlap with compute (latency-hiding
    # scheduler); only the spill past ``dp_overlap · compute`` is visible.
    # Model-parallel (tp/cp/pp) collectives sit on the critical path.
    dp_visible = max(0.0, out.fsdp_comm_s + out.dp_comm_s - bw.dp_overlap * compute_s)
    work = compute_s + out.tp_comm_s + out.cp_comm_s + out.pp_comm_s + dp_visible
    out.step_s = work * (m + p - 1) / m + out.microbatch_overhead_s
    return out


# ----------------------------------------------------------------------
# Plan artifact
# ----------------------------------------------------------------------


def _layout_dict(pc: ParallelismConfig) -> dict:
    return pc.layout_dict()


def parallelism_config_from_layout(layout: dict) -> ParallelismConfig:
    return ParallelismConfig(
        dp_replicate_size=int(layout.get("dp_replicate", 1)),
        dp_shard_size=int(layout.get("dp_shard", 1)),
        cp_size=int(layout.get("cp", 1)),
        sp_size=int(layout.get("sp", 1)),
        tp_size=int(layout.get("tp", 1)),
        pp_size=int(layout.get("pp", 1)),
        ep_size=int(layout.get("ep", 1)),
    )


def layout_str(layout: dict) -> str:
    active = {k: v for k, v in layout.items() if v > 1}
    return ",".join(f"{k}={v}" for k, v in active.items()) or "single-device"


def resize_pins(layout: dict, n_devices: int) -> dict:
    """Pins for a planner re-search after an elastic resize (resharding.py).

    The model-parallel axes (tp, cp, pp — sp is not plannable) are what the
    previous run's search — and its calibration data — decided was winning
    for this model; a device-count change shifts the *data*-parallel budget,
    not the model's divisibility constraints. Keep each such axis pinned
    while the running product still divides the new device count (greedy, in
    the order the layout priced them); the dp axes are left free so the
    search absorbs the resize there."""
    pins: dict = {}
    prod = 1
    for ax in ("tp", "cp", "pp"):
        n = int(layout.get(ax, 1))
        if n > 1 and n_devices % (prod * n) == 0:
            pins[ax] = n
            prod *= n
    return pins


def scaled_layout(layout: dict, n_devices: int) -> Optional[dict]:
    """The previous layout with only its data-parallel extent rescaled to
    ``n_devices`` (the elastic ``resize_policy="keep"`` path). Returns None
    when the non-dp axes no longer divide the new device count — callers
    fall back to a pinned re-search."""
    fixed = 1
    for ax in ("tp", "cp", "sp", "pp", "dp_replicate"):
        fixed *= int(layout.get(ax, 1))
    if fixed > n_devices or n_devices % fixed != 0:
        return None
    out = {k: int(v) for k, v in layout.items()}
    out["dp_shard"] = n_devices // fixed
    return out


def validate_world_size(n_devices: int, layout: Optional[dict] = None) -> bool:
    """Whether ``n_devices`` is a viable world size — THE shared topology
    gate for everything that proposes one: ``GangSupervisor
    --shrink_after_dead_hosts`` (via :func:`resharding.shrink_world_size`)
    and the serving autoscaler (autoscale.py, directly and via
    ``grow_world_size``) both route through here so their notions of
    "valid" can't drift. With a recorded ``layout`` the answer is the
    planner's: :func:`scaled_layout` must rescale the data-parallel extent
    to ``n_devices`` with every model-parallel axis still dividing it.
    Without one, any positive count is viable (the pow2 preference the
    grow/shrink helpers apply is policy, not validity)."""
    n = int(n_devices)
    if n < 1:
        return False
    if layout:
        return scaled_layout(layout, n) is not None
    return True


@dataclasses.dataclass
class ParallelPlan:
    """Versioned, deterministic plan artifact. ``to_json`` of two plans built
    from identical inputs is byte-identical (sorted keys, rounded floats, no
    timestamps); only :func:`record_calibration` mutates a written plan."""

    version: int
    key: str                 # hash of every search input (cache identity)
    model: str
    n_devices: int
    seq: int
    per_chip_batch: int
    optimizer: str
    hbm_gib_budget: float
    layout: dict
    remat: bool
    remat_policy: str
    microbatches: int
    predicted_step_s: float
    predicted_hbm_gib: float
    memory_rows: dict        # params/grads/opt/activations/logits GiB
    breakdown: dict          # CostBreakdown.to_dict()
    bandwidths: dict         # BandwidthTable used for the search
    over_budget: bool
    rejections: list         # runner-up log: layout, reason, predictions
    profile: dict            # ModelProfile.to_dict()
    calibration: Optional[dict] = None

    def to_parallelism_config(self) -> ParallelismConfig:
        return parallelism_config_from_layout(self.layout)

    def to_json_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_json_dict(), indent=2, sort_keys=True) + "\n"

    @classmethod
    def from_json_dict(cls, d: dict) -> "ParallelPlan":
        version = d.get("version")
        if version != PLAN_VERSION:
            raise PlanVersionError(
                f"plan artifact has version {version!r}; this planner speaks "
                f"version {PLAN_VERSION}. Re-run the search (delete the plan "
                f"file or pass use_cache=False)."
            )
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def from_json(cls, text: str) -> "ParallelPlan":
        return cls.from_json_dict(json.loads(text))

    def save(self, path: str) -> None:
        _atomic_write(path, self.to_json())

    @classmethod
    def load(cls, path: str) -> "ParallelPlan":
        with open(path) as f:
            return cls.from_json(f.read())


def _atomic_write(path: str, text: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


# ----------------------------------------------------------------------
# The planner
# ----------------------------------------------------------------------


class Planner:
    """Search driver. Construct with a model (module + config) or a bare
    :class:`ModelProfile`; call :meth:`search` for a fresh plan or
    :meth:`resolve` for the cached-artifact path."""

    def __init__(
        self,
        module=None,
        cfg=None,
        *,
        profile: Optional[ModelProfile] = None,
        n_devices: int,
        hbm_gib: float,
        seq: int,
        per_chip_batch: int = 1,
        optimizer: str = "adamw",
        master_dtype: Any = np.float32,
        moments_dtype: Any = None,
        tp_rules: Optional[list] = None,
        axes: tuple[str, ...] = ALL_SEARCH_AXES,
        pinned: Optional[dict] = None,
        bandwidths: Optional[BandwidthTable] = None,
        label: Optional[str] = None,
        max_rejections: int = 16,
    ):
        if module is None and profile is None:
            raise ValueError("Planner needs a module (+cfg) or a ModelProfile")
        self.module = module
        self.cfg = cfg if cfg is not None else getattr(module, "config", None)
        if module is not None and self.cfg is None:
            raise ValueError(
                "Planner needs the module's config (divisibility constraints "
                "+ activation model); pass cfg= explicitly."
            )
        self.profile = profile or ModelProfile.from_config(
            self.cfg, module=module, label=label
        )
        if label:
            self.profile.label = label
        self.n_devices = int(n_devices)
        self.hbm_gib = float(hbm_gib)
        self.seq = int(seq)
        self.per_chip_batch = int(per_chip_batch)
        self.optimizer = optimizer
        self.master_dtype = master_dtype
        self.moments_dtype = moments_dtype
        self.tp_rules = tp_rules
        self.axes = tuple(axes)
        self.pinned = dict(pinned or {})
        self.bandwidths = bandwidths or BandwidthTable()
        self.max_rejections = max_rejections
        self.searches = 0  # incremented by search(); cache hits leave it at 0
        self._param_shapes = None

    # -- cache identity ------------------------------------------------

    def cache_key(self) -> str:
        ident = {
            "version": PLAN_VERSION,
            "profile": self.profile.to_dict(),
            "n_devices": self.n_devices,
            "hbm_gib": self.hbm_gib,
            "seq": self.seq,
            "per_chip_batch": self.per_chip_batch,
            "optimizer": self.optimizer,
            "master_dtype": str(np.dtype(self.master_dtype)),
            "moments_dtype": str(np.dtype(self.moments_dtype or self.master_dtype)),
            "axes": list(self.axes),
            "pinned": {k: self.pinned[k] for k in sorted(self.pinned)},
            "bandwidths": self.bandwidths.to_dict(),
            "has_tp_rules": bool(self.tp_rules),
        }
        blob = json.dumps(ident, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    # -- memory scoring ------------------------------------------------

    def _memory_estimate(self, pc: ParallelismConfig, remat: bool,
                         remat_policy: str, microbatches: int):
        """Per-chip GiB rows for one (layout, remat rung, microbatch) point.
        Tensor state (params/grads/opt) comes from estimate_per_chip — exact,
        remat-invariant, computed once per layout; activations re-priced per
        rung via the closed-form model."""
        from .utils.estimate_memory import (
            activation_bytes,
            estimate_per_chip,
        )

        if self.module is not None:
            if self._param_shapes is None:
                from .utils.estimate_memory import abstract_param_shapes

                self._param_shapes = abstract_param_shapes(self.module)
            est, _, _ = estimate_per_chip(
                self.module, self.cfg, pc,
                seq=self.seq, per_chip_batch=self.per_chip_batch,
                optimizer=self.optimizer, master_dtype=self.master_dtype,
                moments_dtype=self.moments_dtype, tp_rules=self.tp_rules,
                param_shapes=self._param_shapes,
            )
            params_gib, grads_gib, opt_gib = (
                est.params_gib, est.grads_gib, est.opt_state_gib
            )
        else:
            # Profile-only path: closed-form tensor state, evenly sharded
            # over the axes that shard params.
            shard = pc.dp_shard_size * pc.cp_size * pc.tp_size * pc.pp_size
            m_bytes = np.dtype(self.master_dtype).itemsize
            mo_bytes = np.dtype(self.moments_dtype or self.master_dtype).itemsize
            moments = {"adamw": 2, "adam": 2, "sgd": 0, "momentum": 1,
                       "lion": 1, "adafactor": 0}.get(self.optimizer, 2)
            params_gib = self.profile.params * m_bytes / shard / GiB
            grads_gib = params_gib
            opt_gib = self.profile.params * mo_bytes * moments / shard / GiB
        # Per data-parallel rank, the layout carries global_batch/dp samples
        # (global batch = per_chip_batch · n, held constant across
        # candidates); microbatching subdivides that.
        batch_per_rank = self.per_chip_batch * self.n_devices / max(1, pc.dp_size)
        mb_batch = max(1, math.ceil(batch_per_rank / microbatches))
        seq_local = self.seq // max(1, pc.cp_size * pc.sp_size)
        if self.cfg is not None:
            compute_bytes = np.dtype(
                getattr(self.cfg, "dtype", np.dtype("bfloat16"))
            ).itemsize
            act_b, logits_b = activation_bytes(
                self.cfg, mb_batch, seq_local, compute_bytes,
                remat=remat, remat_policy=remat_policy,
            )
            # TP shards the big per-layer intermediates (qkv/ffn outputs,
            # flash residuals) over the tp axis; the unsharded residual
            # stream makes this slightly optimistic for tp > 1.
            act_b = act_b // max(1, pc.tp_size)
        else:
            # Profile-only: carry + flash residuals per layer, full stash
            # without remat.
            H, L = self.profile.hidden, self.profile.layers
            per_layer = mb_batch * seq_local * H * 2
            if not remat:
                per_layer *= 6
            elif remat_policy == "flash":
                per_layer *= 2
            act_b = per_layer * L // max(1, pc.tp_size)
            logits_b = mb_batch * min(256, seq_local) * self.profile.vocab * 4
        rows = {
            "params_gib": params_gib,
            "grads_gib": grads_gib,
            "opt_state_gib": opt_gib,
            "activations_gib": act_b / GiB,
            "logits_gib": logits_b / GiB,
        }
        rows["total_gib"] = sum(rows.values())
        return rows

    # -- per-candidate scoring ----------------------------------------

    def _microbatch_ladder(self, pc: ParallelismConfig) -> list[int]:
        """Microbatch counts worth trying: pp needs ≥ pp in-flight
        microbatches to hide the bubble; memory escalation subdivides the
        per-chip batch while whole samples remain."""
        batch_per_rank = max(
            1, self.per_chip_batch * self.n_devices // max(1, pc.dp_size)
        )
        cap = batch_per_rank * pc.pp_size
        base = [pc.pp_size * k for k in (1, 2, 4, 8)] if pc.pp_size > 1 else [1]
        m = base[-1] * 2
        while m <= cap:
            base.append(m)
            m *= 2
        return sorted({min(b, cap) for b in base})

    def score_candidate(self, pc: ParallelismConfig) -> dict:
        """Walk the remat × microbatch escalation ladder for one layout and
        return its best point: the first rung that fits the HBM budget (or
        the lowest-HBM rung when none does, marked over_budget)."""
        params_sharded = pc.dp_shard_size > 1
        best_fit = None
        min_hbm = None
        for remat, policy in REMAT_LADDER:
            for m in self._microbatch_ladder(pc):
                rows = self._memory_estimate(pc, remat, policy, m)
                cost = predict_step_time(
                    self.profile, pc, self.bandwidths,
                    seq=self.seq, per_chip_batch=self.per_chip_batch,
                    microbatches=m, params_sharded=params_sharded,
                    compute_multiplier=REMAT_COMPUTE_COST[(remat, policy)],
                )
                point = {
                    "layout": _layout_dict(pc),
                    "remat": remat,
                    "remat_policy": policy,
                    "microbatches": m,
                    "hbm_gib": rows["total_gib"],
                    "memory_rows": rows,
                    "cost": cost,
                    "fits": rows["total_gib"] <= self.hbm_gib,
                }
                if min_hbm is None or point["hbm_gib"] < min_hbm["hbm_gib"]:
                    min_hbm = point
                if point["fits"] and (
                    best_fit is None or cost.step_s < best_fit["cost"].step_s
                ):
                    best_fit = point
            if best_fit is not None:
                # A fitting rung exists at this remat level; deeper remat
                # only trades speed for memory we no longer need.
                break
        return best_fit if best_fit is not None else min_hbm

    # -- the search ----------------------------------------------------

    def search(self) -> ParallelPlan:
        self.searches += 1
        candidates = enumerate_layouts(
            self.n_devices, self.profile, seq=self.seq,
            axes=self.axes, pinned=self.pinned,
        )
        scored = [self.score_candidate(pc) for pc in candidates]
        # Rank: fitting plans first, then predicted step time, then less
        # remat, then a stable layout tiebreak for determinism.
        scored.sort(
            key=lambda s: (
                not s["fits"],
                _round6(s["cost"].step_s) if s["fits"] else _round6(s["hbm_gib"]),
                int(s["remat"]),
                s["microbatches"],
                tuple(sorted(s["layout"].items())),
            )
        )
        chosen, rest = scored[0], scored[1:]
        if not chosen["fits"]:
            logger.warning(
                "planner: NO layout fits %.1f GiB/chip for %s on %d devices — "
                "emitting best-effort plan %s (predicted %.2f GiB, over "
                "budget). Expect OOM; lower per_chip_batch/seq or add chips.",
                self.hbm_gib, self.profile.label, self.n_devices,
                layout_str(chosen["layout"]), chosen["hbm_gib"],
            )
        rejections = []
        for s in rest[: self.max_rejections]:
            if not s["fits"]:
                reason = (
                    f"over_budget: {_round6(s['hbm_gib'])} GiB > "
                    f"{_round6(self.hbm_gib)} GiB at full remat"
                )
            else:
                slower = (s["cost"].step_s / chosen["cost"].step_s - 1.0) * 100
                reason = f"slower: +{_round6(slower)}% predicted step time"
            rejections.append({
                "layout": s["layout"],
                "reason": reason,
                "predicted_step_s": _round6(s["cost"].step_s),
                "predicted_hbm_gib": _round6(s["hbm_gib"]),
                "remat": s["remat"],
                "remat_policy": s["remat_policy"],
                "microbatches": s["microbatches"],
            })
        dropped = len(rest) - len(rejections)
        if dropped > 0:
            rejections.append({
                "layout": None,
                "reason": f"... {dropped} more candidates not logged "
                          f"(max_rejections={self.max_rejections})",
            })
        plan = ParallelPlan(
            version=PLAN_VERSION,
            key=self.cache_key(),
            model=self.profile.label,
            n_devices=self.n_devices,
            seq=self.seq,
            per_chip_batch=self.per_chip_batch,
            optimizer=self.optimizer,
            hbm_gib_budget=_round6(self.hbm_gib),
            layout=chosen["layout"],
            remat=chosen["remat"],
            remat_policy=chosen["remat_policy"],
            microbatches=chosen["microbatches"],
            predicted_step_s=_round6(chosen["cost"].step_s),
            predicted_hbm_gib=_round6(chosen["hbm_gib"]),
            memory_rows={k: _round6(v) for k, v in chosen["memory_rows"].items()},
            breakdown=chosen["cost"].to_dict(),
            bandwidths=self.bandwidths.to_dict(),
            over_budget=not chosen["fits"],
            rejections=rejections,
            profile=self.profile.to_dict(),
            calibration=None,
        )
        return plan

    def resolve(
        self, plans_dir: str, *, use_cache: bool = True
    ) -> tuple[ParallelPlan, str, bool]:
        """Load the cached plan for these inputs or search and write one.
        Returns (plan, path, from_cache)."""
        key = self.cache_key()
        path = os.path.join(plans_dir, f"plan_{key}.json")
        if use_cache and os.path.exists(path):
            try:
                plan = ParallelPlan.load(path)
                if plan.key == key:
                    # Calibrated constants feed back into this planner so a
                    # later forced re-search starts from measured reality.
                    cal = plan.calibration or {}
                    if cal.get("mfu_effective"):
                        self.bandwidths.mfu = float(cal["mfu_effective"])
                    return plan, path, True
                logger.warning(
                    "planner: cached plan %s has stale key %s (inputs "
                    "changed); re-searching.", path, plan.key,
                )
            except PlanVersionError as e:
                logger.warning("planner: %s", e)
            except (OSError, ValueError, KeyError) as e:
                logger.warning(
                    "planner: unreadable cached plan %s (%s); re-searching.",
                    path, e,
                )
        plan = self.search()
        plan.save(path)
        return plan, path, False


# ----------------------------------------------------------------------
# Calibration write-back (telemetry → plan artifact)
# ----------------------------------------------------------------------


def record_calibration(
    path: str,
    *,
    measured_step_s: Optional[float] = None,
    measured_peak_hbm_gib: Optional[float] = None,
    steps: int = 0,
) -> Optional[dict]:
    """Fold measured step time / peak HBM back into the plan artifact at
    ``path``. Each calibrated run increments ``runs`` and EMA-blends the
    measurements; ``mfu_effective`` is the MFU the bandwidth table *should*
    have used for predicted == measured — the constant the next cache-miss
    search starts from. Returns the calibration block (None when the file is
    missing/invalid — calibration must never kill training)."""
    try:
        plan = ParallelPlan.load(path)
    except (OSError, ValueError, KeyError) as e:
        logger.warning("planner: calibration skipped — cannot load %s (%s)", path, e)
        return None
    cal = dict(plan.calibration or {})
    runs = int(cal.get("runs", 0)) + 1
    alpha = 1.0 / runs  # running mean across calibrated runs

    def _blend(key, value):
        if value is None:
            return cal.get(key)
        prev = cal.get(key)
        return value if prev is None else (1 - alpha) * prev + alpha * value

    cal["runs"] = runs
    cal["steps"] = int(cal.get("steps", 0)) + int(steps)
    cal["measured_step_s"] = _blend("measured_step_s", measured_step_s)
    cal["measured_peak_hbm_gib"] = _blend("measured_peak_hbm_gib", measured_peak_hbm_gib)
    if cal.get("measured_step_s") and plan.predicted_step_s:
        ratio = cal["measured_step_s"] / plan.predicted_step_s
        cal["step_time_ratio"] = _round6(ratio)
        mfu = float(plan.bandwidths.get("mfu", BandwidthTable.mfu))
        # measured = predicted · ratio and predicted ∝ 1/mfu on the compute
        # term, so the mfu that would have nailed it is mfu/ratio (clamped).
        cal["mfu_effective"] = _round6(min(1.0, max(1e-3, mfu / ratio)))
    if cal.get("measured_peak_hbm_gib") and plan.predicted_hbm_gib:
        cal["hbm_ratio"] = _round6(
            cal["measured_peak_hbm_gib"] / plan.predicted_hbm_gib
        )
    for k in ("measured_step_s", "measured_peak_hbm_gib"):
        if isinstance(cal.get(k), float):
            cal[k] = _round6(cal[k])
    plan.calibration = cal
    try:
        plan.save(path)
    except OSError as e:
        logger.warning("planner: calibration write-back to %s failed: %s", path, e)
        return None
    return cal


# ----------------------------------------------------------------------
# Disaggregated-serving slice sizing (disagg.py)
# ----------------------------------------------------------------------


@dataclasses.dataclass
class DisaggSlicePlan:
    """Planner-sized prefill/decode split for disaggregated serving
    (disagg.py). The same makespan logic as the training cost model, one
    level up: prefill and decode are two heterogeneous programs whose FLOP
    shares are known, so the device set is partitioned to balance them —
    and the KV-page handoff the split creates is priced against the
    BandwidthTable so the artifact records what the link will carry."""

    n_devices: int
    n_prefill: int
    n_decode: int
    flop_ratio: float           # prefill FLOPs : decode FLOPs (per request)
    bottleneck: str             # "prefill" | "decode" | "balanced"
    predicted_speedup: float    # colocated makespan / disagg makespan
    handoff_gbps: float         # effective prefill→decode link bandwidth
    kv_bytes_per_token: int     # one token's K+V pages across all layers
    handoff_s_per_ktoken: float  # predicted handoff seconds per 1k prompt tokens

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return {
            k: (_round6(v) if isinstance(v, float) else v)
            for k, v in sorted(d.items())
        }


def kv_bytes_per_token(cfg, dtype=None) -> int:
    """Bytes one prompt token's committed K+V pages occupy across every
    layer — the unit the handoff link is priced in. int8 pages carry one
    f32 absmax scale per head per layer (QuantPages), included here so
    the quantized handoff is priced on what actually moves."""
    from .generation import _cache_dims

    layers, kv_heads, head_dim, _ = _cache_dims(cfg)
    dt = np.dtype(dtype or getattr(cfg, "dtype", np.float32))
    per_page = head_dim * dt.itemsize
    if dt == np.int8:
        per_page += 4  # the QuantPages f32 dequant scale
    return 2 * layers * kv_heads * per_page


def plan_disagg_slices(
    n_devices: int,
    *,
    prefill_decode_flop_ratio: float,
    bw: Optional[BandwidthTable] = None,
    kv_bytes_per_token: int = 0,
    n_prefill: Optional[int] = None,
) -> DisaggSlicePlan:
    """Partition ``n_devices`` into a prefill slice and a decode slice.

    ``prefill_decode_flop_ratio`` is the measured (or expected) ratio of
    prefill FLOPs to decode FLOPs per request — for a dense causal LM both
    phases cost ~2·P FLOPs/token, so the ratio reduces to
    ``mean_prompt_tokens / mean_new_tokens``. The split minimizes the
    two-phase makespan ``max(ratio / n_p, 1 / n_d)`` (work over devices,
    phases overlapped across requests); ties break toward MORE decode
    devices because decode is the latency-critical, occupancy-bound phase.
    ``n_prefill`` pins the prefill slice size (clamped to [1, n-1]) and
    skips the search.

    The returned plan also prices the handoff the split creates:
    ``handoff_gbps`` from the BandwidthTable's link model (ICI inside one
    domain, DCN across) and ``handoff_s_per_ktoken`` for
    ``kv_bytes_per_token`` (see :func:`kv_bytes_per_token`).
    """
    n = int(n_devices)
    if n < 2:
        raise PlannerError(
            f"disaggregation needs >= 2 devices to split, got {n}"
        )
    r = float(prefill_decode_flop_ratio)
    if not (r > 0):
        raise PlannerError(
            f"prefill_decode_flop_ratio must be > 0, got {prefill_decode_flop_ratio}"
        )
    bw = bw or BandwidthTable()

    def makespan(p: int) -> float:
        return max(r / p, 1.0 / (n - p))

    if n_prefill is not None:
        p_best = min(max(1, int(n_prefill)), n - 1)
    else:
        # Smallest p minimizing the makespan: scanning upward and keeping
        # strict improvement biases ties toward more decode devices.
        p_best, best = 1, makespan(1)
        for p in range(2, n):
            m = makespan(p)
            if m < best - 1e-12:
                p_best, best = p, m
    span = makespan(p_best)
    colocated = (r + 1.0) / n  # both phases time-sliced over every device
    gbps = bw.handoff_gbps(n)
    per_ktoken = (
        1000.0 * kv_bytes_per_token / (gbps * 1e9) if kv_bytes_per_token else 0.0
    )
    prefill_span, decode_span = r / p_best, 1.0 / (n - p_best)
    if abs(prefill_span - decode_span) <= 0.05 * span:
        bottleneck = "balanced"
    else:
        bottleneck = "prefill" if prefill_span > decode_span else "decode"
    return DisaggSlicePlan(
        n_devices=n,
        n_prefill=p_best,
        n_decode=n - p_best,
        flop_ratio=_round6(r),
        bottleneck=bottleneck,
        predicted_speedup=_round6(colocated / span),
        handoff_gbps=_round6(gbps),
        kv_bytes_per_token=int(kv_bytes_per_token),
        handoff_s_per_ktoken=_round6(per_ktoken),
    )
