"""Process/device runtime state singletons (layer L0).

TPU-native re-design of the reference's ``state.py`` (reference:
src/accelerate/state.py:123-1371). The reference's ``PartialState`` wraps
torch.distributed process groups; here the runtime is JAX's single-controller
multi-process model: ``jax.distributed.initialize`` performs the coordinator
rendezvous over DCN, after which every process sees all global devices and all
data-plane collectives are XLA ops placed by GSPMD. What remains host-side is
exactly what the reference's L0 provides: rank/world introspection, process
control (barriers, main-process gating, ``split_between_processes``) and a tiny
out-of-band object channel (see utils/operations.py).
"""

from __future__ import annotations

import enum
import logging
import os
import threading
from contextlib import contextmanager
from functools import wraps
from typing import Any, Callable, Optional

from .parallelism_config import ParallelismConfig
from .utils.environment import parse_choice_from_env, parse_flag_from_env

logger = logging.getLogger(__name__)


class DistributedType(str, enum.Enum):
    """Launch topology. The parallelism *strategy* (FSDP/TP/CP/...) is not a
    distributed type here — unlike the reference (state.py:972-1022), strategy
    lives entirely in :class:`ParallelismConfig`; GSPMD makes the backend zoo
    collapse into sharding choices (SURVEY.md §7)."""

    NO = "NO"                      # single process, single device
    MULTI_DEVICE = "MULTI_DEVICE"  # single process, >1 local devices (one host)
    MULTI_HOST = "MULTI_HOST"      # multi-process JAX over a pod


class ThreadLocalSharedDict(threading.local):
    """Thread-local borg storage (reference: state.py:91-119 — needed there for
    TPU v2/v3 PJRT threads; kept for API parity and notebook safety)."""

    def __init__(self):
        self._storage = {}

    def __get__(self, obj, objtype=None):
        return self._storage

    def __set__(self, obj, value):
        self._storage = value


class SharedDict:
    """Descriptor holding borg shared state at class level."""

    def __init__(self):
        self._storage = {}

    def __get__(self, obj, objtype=None):
        return self._storage

    def __set__(self, obj, value):
        self._storage = value


def _maybe_init_jax_distributed():
    """Multi-host bring-up: rendezvous with the JAX coordinator over DCN.

    Replaces the reference's ``init_process_group`` + MASTER_ADDR/MASTER_PORT
    rendezvous (reference: state.py:215-285). Controlled by env the launcher
    sets (`accelerate launch`, commands/launch.py):

      ACCELERATE_COORDINATOR_ADDRESS  host:port of process 0
      ACCELERATE_NUM_PROCESSES        total process (host) count
      ACCELERATE_PROCESS_INDEX        this process's index
    """
    import jax

    coord = os.environ.get("ACCELERATE_COORDINATOR_ADDRESS")
    if coord is None:
        return
    # Idempotent across PartialState._reset_state(): the coordinator client
    # outlives the borg dicts, and re-initializing after the backend is live
    # is an error.
    if getattr(jax._src.distributed.global_state, "client", None) is not None:
        return
    num = int(os.environ.get("ACCELERATE_NUM_PROCESSES", "1"))
    idx = int(os.environ.get("ACCELERATE_PROCESS_INDEX", "0"))
    if coord == "auto":
        # TPU pod: jax discovers coordinator/ranks from the TPU VM metadata
        # (the gcloud pod launch path sets this — commands/pod.py).
        try:
            jax.distributed.initialize()
        except RuntimeError as e:
            if "already initialized" not in str(e):
                raise
        return
    if num <= 1:
        return
    # Multi-process CPU gangs (--cpu / --virtual_devices) need an explicit
    # cross-process collectives implementation: jax 0.4.37 defaults to
    # "none", and the first device_put/jit that touches a sharding spanning
    # the gang dies with "Multiprocess computations aren't implemented on
    # the CPU backend". Gloo ships in jaxlib; opt in before any backend
    # client exists. (JAX_CPU_COLLECTIVES_IMPLEMENTATION is not read from
    # the environment in this jax version — it must go through jax.config.)
    if "cpu" in (os.environ.get("JAX_PLATFORMS") or ""):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # jaxlib without gloo bindings: keep the default
    try:
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=num, process_id=idx
        )
    except RuntimeError as e:
        # Already initialized (e.g. by the launcher itself) is fine.
        if "already initialized" not in str(e):
            raise


class PartialState:
    """Borg-pattern singleton with rank/device info and process-control helpers.

    (reference: state.py:123-865)
    """

    _shared_state = SharedDict()
    _known_attrs = [
        "_cpu",
        "backend",
        "device",
        "debug",
        "distributed_type",
        "fork_launched",
        "local_process_index",
        "num_processes",
        "process_index",
    ]

    def __init__(self, cpu: bool = False, **kwargs):
        self.__dict__ = self._shared_state
        if self.initialized:
            return
        import jax

        self._cpu = cpu
        self.debug = parse_flag_from_env("ACCELERATE_DEBUG_MODE")
        self.fork_launched = parse_flag_from_env("FORK_LAUNCHED", False)
        if cpu:
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
        # In launcher-spawned workers, make JAX_PLATFORMS win even when a site
        # hook pre-registered another backend via jax.config (registration
        # order would otherwise override the launcher's choice). Never applied
        # in-process, where a user's explicit jax.config.update must stand.
        launched = (
            "ACCELERATE_COORDINATOR_ADDRESS" in os.environ
            or "ACCELERATE_PROCESS_INDEX" in os.environ
            or self.fork_launched
        )
        platforms = os.environ.get("JAX_PLATFORMS")
        if platforms and (launched or cpu):
            try:
                jax.config.update("jax_platforms", platforms)
            except Exception:
                pass  # backend already initialized; keep what we have
        _maybe_init_jax_distributed()

        self.process_index = jax.process_index()
        self.num_processes = jax.process_count()
        self.local_process_index = int(
            os.environ.get("ACCELERATE_LOCAL_PROCESS_INDEX", self.process_index)
        )
        self._devices = jax.devices()
        self._local_devices = jax.local_devices()
        self.device = self._local_devices[0]
        self.backend = self.device.platform

        if self.num_processes > 1:
            self.distributed_type = DistributedType.MULTI_HOST
        elif len(self._devices) > 1:
            self.distributed_type = DistributedType.MULTI_DEVICE
        else:
            self.distributed_type = DistributedType.NO

    def __repr__(self) -> str:
        return (
            f"Distributed environment: {self.distributed_type.value}  Backend: {self.backend}\n"
            f"Num processes: {self.num_processes}\n"
            f"Process index: {self.process_index}\n"
            f"Local process index: {self.local_process_index}\n"
            f"Device: {self.device}\n"
        )

    @staticmethod
    def _reset_state():
        """Reset for testing (reference: state.py:853-857)."""
        PartialState._shared_state.clear()

    @property
    def initialized(self) -> bool:
        return "distributed_type" in self.__dict__

    @property
    def use_distributed(self) -> bool:
        return self.num_processes > 1 or len(self._devices) > 1

    # -- device views ---------------------------------------------------

    @property
    def devices(self):
        """All global devices (every process sees the full pod)."""
        return self._devices

    @property
    def local_devices(self):
        return self._local_devices

    @property
    def num_devices(self) -> int:
        return len(self._devices)

    @property
    def local_device_count(self) -> int:
        return len(self._local_devices)

    # -- process control ------------------------------------------------

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def is_local_main_process(self) -> bool:
        return self.local_process_index == 0

    @property
    def is_last_process(self) -> bool:
        return self.process_index == self.num_processes - 1

    def wait_for_everyone(self):
        """Cross-process barrier (reference: state.py:399-414). Under JAX this
        is a sync over all global devices."""
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("accelerate_tpu.wait_for_everyone")

    def agree_any(self, flag: bool) -> bool:
        """Cross-rank OR of a host-side boolean: True everywhere as soon as
        ANY rank passes True. One tiny int allreduce — the rank-coherence
        primitive behind ``Accelerator.check_preemption()`` (only some hosts
        of a pod get the scheduler's SIGTERM; the whole gang must take the
        same save-and-exit decision) and ``check_trigger()``-style flags."""
        if self.num_processes <= 1:
            return bool(flag)
        import jax.numpy as jnp
        import numpy as np

        from .utils.operations import reduce

        total = reduce(jnp.asarray(1 if flag else 0, jnp.int32), reduction="sum")
        return int(np.asarray(total)) > 0

    def allgather_host_floats(self, values) -> "np.ndarray":
        """Allgather a small host-side float vector across ranks, returning
        a ``(num_processes, len(values))`` numpy array (row r = rank r's
        vector). Single-process returns the ``(1, n)`` input. The
        rank-coherence channel behind the step watchdog's gang heartbeat
        (fault_tolerance.py) — same family as :meth:`agree_any`: one tiny
        collective, every rank sees the same table and takes the same
        decision."""
        import numpy as np

        vec = np.asarray(values, np.float64).reshape(1, -1)
        if self.num_processes <= 1:
            return vec
        from .utils.operations import gather

        out = np.asarray(gather(vec), np.float64)
        return out.reshape(self.num_processes, -1)

    @contextmanager
    def main_process_first(self):
        """Main process runs the body first, others wait then run
        (reference: state.py:416-423)."""
        if not self.is_main_process:
            self.wait_for_everyone()
        yield
        if self.is_main_process:
            self.wait_for_everyone()

    @contextmanager
    def local_main_process_first(self):
        if not self.is_local_main_process:
            self.wait_for_everyone()
        yield
        if self.is_local_main_process:
            self.wait_for_everyone()

    def on_main_process(self, function: Callable = None):
        """Decorator: run only on the main process (reference: state.py:425-460)."""

        @wraps(function)
        def execute_on_main_process(*args, **kwargs):
            if self.is_main_process:
                return function(*args, **kwargs)

        return execute_on_main_process

    def on_local_main_process(self, function: Callable = None):
        @wraps(function)
        def execute_on_local_main_process(*args, **kwargs):
            if self.is_local_main_process:
                return function(*args, **kwargs)

        return execute_on_local_main_process

    def on_process(self, function: Callable = None, process_index: int = None):
        @wraps(function)
        def execute_on_process(*args, **kwargs):
            if self.process_index == process_index:
                return function(*args, **kwargs)

        return execute_on_process

    def on_last_process(self, function: Callable):
        return self.on_process(function, process_index=self.num_processes - 1)

    def on_local_process(self, function: Callable = None, local_process_index: int = None):
        @wraps(function)
        def execute_on_local_process(*args, **kwargs):
            if self.local_process_index == local_process_index:
                return function(*args, **kwargs)

        return execute_on_local_process

    @contextmanager
    def split_between_processes(self, inputs, apply_padding: bool = False):
        """Split a list/dict/array evenly across processes; uneven tails go to
        the first ranks; ``apply_padding`` repeats the final element so all
        ranks get equal length (reference: state.py:465-555)."""
        if self.num_processes == 1:
            yield inputs
            return
        length = len(inputs)
        num_samples_per_process, num_extras = divmod(length, self.num_processes)
        start = self.process_index * num_samples_per_process + min(self.process_index, num_extras)
        end = start + num_samples_per_process + (1 if self.process_index < num_extras else 0)

        if isinstance(inputs, dict):
            result = {k: v[start:end] for k, v in inputs.items()}
            if apply_padding:
                target = num_samples_per_process + (1 if num_extras > 0 else 0)
                for k, v in result.items():
                    while len(result[k]) < target:
                        result[k] = list(result[k]) + [inputs[k][-1]]
            yield result
            return

        result = inputs[start:end]
        if apply_padding:
            target = num_samples_per_process + (1 if num_extras > 0 else 0)
            if hasattr(result, "tolist"):
                result = list(result)
            while len(result) < target:
                result = list(result) + [inputs[-1]]
        yield result

    def print(self, *args, **kwargs):
        if self.is_local_main_process:
            print(*args, **kwargs)

    def destroy_process_group(self):
        import jax

        if self.num_processes > 1:
            try:
                jax.distributed.shutdown()
            except Exception:
                pass

    def __getattr__(self, name: str):
        if name in self._known_attrs:
            raise AttributeError(
                f"`PartialState` object has no attribute `{name}`. "
                "This happens if `PartialState._reset_state()` was called and "
                "an `Accelerator` or `PartialState` was not reinitialized."
            )
        raise AttributeError(f"'PartialState' object has no attribute '{name}'")


class AcceleratorState:
    """PartialState + mixed precision + parallelism/mesh + plugin storage.

    (reference: state.py:868-1228)
    """

    _shared_state = SharedDict()

    def __init__(
        self,
        mixed_precision: str = None,
        cpu: bool = False,
        parallelism_config: Optional[ParallelismConfig] = None,
        **kwargs,
    ):
        self.__dict__ = self._shared_state
        if self.initialized:
            if parallelism_config is not None and parallelism_config != self.parallelism_config:
                raise ValueError(
                    "AcceleratorState is already initialized with a different "
                    "parallelism_config; call AcceleratorState._reset_state() first."
                )
            return
        self._partial = PartialState(cpu, **kwargs)
        if mixed_precision is None:
            mixed_precision = parse_choice_from_env("ACCELERATE_MIXED_PRECISION", "no")
        mixed_precision = str(mixed_precision)
        if mixed_precision not in ("no", "bf16", "fp16", "fp8"):
            raise ValueError(
                f"mixed_precision must be one of no|bf16|fp16|fp8, got {mixed_precision}"
            )
        # bf16 is native on every TPU generation; fp16 requests are honored but
        # bf16 is the idiomatic choice (no loss scaling needed).
        self.mixed_precision = mixed_precision
        if parallelism_config is None and os.environ.get("PARALLELISM_CONFIG_DP_SHARD_SIZE"):
            parallelism_config = ParallelismConfig.from_env()
        self.parallelism_config = parallelism_config
        self._mesh = None

    @staticmethod
    def _reset_state(reset_partial_state: bool = False):
        AcceleratorState._shared_state.clear()
        if reset_partial_state:
            PartialState._reset_state()

    @property
    def initialized(self) -> bool:
        return "_partial" in self.__dict__

    # Delegate PartialState surface.
    def __getattr__(self, name: str):
        partial = self.__dict__.get("_partial")
        if partial is not None and hasattr(partial, name):
            return getattr(partial, name)
        raise AttributeError(f"'AcceleratorState' object has no attribute '{name}'")

    @property
    def mesh(self):
        """The global device mesh, built lazily from parallelism_config (or a
        pure-DP mesh over all devices when no config was given)."""
        if self._mesh is None:
            cfg = self.parallelism_config
            if cfg is None:
                # Lazily inferred config must still honor env knobs that are
                # meaningful without mesh degrees (pp_virtual_stages) — else
                # the first mesh access silently overwrites the env default
                # that pipeline_apply's resolution would otherwise see.
                from .utils.environment import get_int_from_env

                cfg = ParallelismConfig(
                    pp_virtual_stages=get_int_from_env(
                        ["PARALLELISM_CONFIG_PP_VIRTUAL_STAGES"], 1
                    )
                )
            self._mesh = cfg.infer_missing_axis(len(self._partial.devices)).build_mesh(
                self._partial.devices
            )
            self.parallelism_config = cfg.infer_missing_axis(len(self._partial.devices))
        return self._mesh

    def set_mesh(self, mesh):
        self._mesh = mesh

    def destroy_process_group(self):
        self._partial.destroy_process_group()


class GradientState:
    """Singleton tracking gradient accumulation & dataloader-end state.

    (reference: state.py:1231-1371). ``sync_gradients`` flips on accumulation
    boundaries; dataloaders register themselves so the final partial window at
    the end of an epoch still syncs (reference: data_loader.py:402-414).

    Under jit the accumulation itself is folded into the train step
    (``lax.scan`` over microbatches); this host-side object exists for the
    imperative-compat API and for end-of-dataloader handling, which is
    inherently host-side control flow.
    """

    _shared_state = SharedDict()

    def __init__(self, gradient_accumulation_plugin=None):
        self.__dict__ = self._shared_state
        if not self.initialized:
            self.sync_gradients = True
            self.active_dataloader = None
            self.dataloader_references = [None]
            self.plugin_kwargs = {}
            self.step = 0
        if gradient_accumulation_plugin is not None:
            self.plugin_kwargs = gradient_accumulation_plugin.to_kwargs()

    @property
    def initialized(self) -> bool:
        return "sync_gradients" in self.__dict__

    @property
    def num_steps(self) -> int:
        return self.plugin_kwargs.get("num_steps", 1)

    @property
    def adjust_scheduler(self) -> bool:
        return self.plugin_kwargs.get("adjust_scheduler", True)

    @property
    def sync_with_dataloader(self) -> bool:
        return self.plugin_kwargs.get("sync_with_dataloader", True)

    @property
    def end_of_dataloader(self) -> bool:
        if not self.in_dataloader:
            return False
        return self.active_dataloader.end_of_dataloader

    @property
    def remainder(self) -> int:
        if not self.in_dataloader:
            return -1
        return getattr(self.active_dataloader, "remainder", -1)

    @property
    def in_dataloader(self) -> bool:
        return self.active_dataloader is not None

    def _set_sync_gradients(self, sync_gradients: bool):
        self.sync_gradients = sync_gradients

    def _add_dataloader(self, dataloader):
        self.dataloader_references.append(dataloader)

    def _remove_dataloader(self, dataloader):
        if dataloader in self.dataloader_references:
            self.dataloader_references.remove(dataloader)

    @property
    def active_dataloader(self):
        return self.dataloader_references[-1]

    @active_dataloader.setter
    def active_dataloader(self, value):
        if "dataloader_references" not in self.__dict__:
            self.dataloader_references = [None]
        if value is not None:
            self.dataloader_references.append(value)

    @staticmethod
    def _reset_state():
        GradientState._shared_state.clear()

    def __repr__(self):
        return (
            f"Sync Gradients: {self.sync_gradients}\n"
            f"At end of current dataloader: {self.end_of_dataloader}\n"
            f"Extra samples added: {self.remainder}\n"
            f"Gradient accumulation steps: {self.num_steps}\n"
        )


def is_initialized() -> bool:
    return AcceleratorState().initialized
