"""N-D parallelism configuration → one JAX device mesh.

This is the keystone of the TPU-native design. The reference builds a torch
``DeviceMesh`` with canonical dim order ``(dp_replicate, dp_shard, cp, sp, tp)``
plus flattened joint meshes ``dp``, ``dp_shard_cp``, ``dp_cp``
(reference: src/accelerate/parallelism_config.py:34-272). Here the same config
surface produces a :class:`jax.sharding.Mesh`; every parallelism backend in the
reference (DDP, FSDP1/2, HSDP, DeepSpeed-ZeRO, TP, CP, SP) becomes a
``NamedSharding``/``PartitionSpec`` choice over these axes, and XLA's GSPMD
partitioner inserts the collectives over ICI/DCN.

Because JAX ``PartitionSpec`` accepts *tuples* of axis names, the reference's
flattened joint meshes are zero-cost here: ``P(("dp_replicate", "dp_shard"))``
*is* the flattened ``dp`` mesh.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import numpy as np

from .utils.constants import MESH_AXIS_ORDER, PARALLELISM_CONFIG_PREFIX
from .utils.environment import get_int_from_env, parse_choice_from_env


class ParallelismOversubscriptionError(ValueError):
    """The configured axis degrees multiply to MORE than the device count —
    a different (and more common) failure than a non-dividing product, so it
    gets its own message naming each offending axis and the env var that
    sets it."""


@dataclasses.dataclass
class ParallelismConfig:
    """Degrees for every first-class parallelism axis.

    Mirrors the reference's ``ParallelismConfig``
    (reference: parallelism_config.py:34-98) with the same validation rules
    (cp and sp mutually exclusive, reference: parallelism_config.py:328-334)
    and adds ``pp_size`` / ``ep_size`` as first-class citizens (the reference
    reaches pipeline and expert parallelism only through Megatron-LM,
    SURVEY.md §2.3).

    Axis semantics:
      - ``dp_replicate``: pure data parallel (DDP-style replication).
      - ``dp_shard``: ZeRO/FSDP-style parameter+optimizer sharding axis.
      - ``cp``: context parallel (ring attention) — sequence sharded, KV rotated.
      - ``sp``: Ulysses sequence parallel — heads sharded via all-to-all.
      - ``tp``: tensor parallel — hidden dims sharded.
      - ``ep``: expert parallel — experts sharded over the joint (dp_shard, sp, tp)
        axes at MoE layers (no extra mesh dim needed; like torchtitan/DeepSpeed-MoE).
      - ``pp``: pipeline parallel — model stages; implemented as a microbatch
        schedule over mesh sub-slices, not an extra GSPMD dim.
    """

    dp_replicate_size: int = 1
    dp_shard_size: int = 1
    cp_size: int = 1
    sp_size: int = 1
    tp_size: int = 1
    ep_size: int = 1
    pp_size: int = 1

    # "alltoall" = ring rotation of KV blocks; "allgather" = gather full KV
    # (reference: TorchContextParallelConfig.set_rotate_method,
    # utils/dataclasses.py:2205-2231).
    cp_rotate_method: str = "alltoall"

    # Interleaving degree for the pipeline schedule (Megatron's
    # num_layers_per_virtual_pipeline_stage knob, expressed as the virtual
    # multiplier: each device holds this many non-contiguous layer chunks and
    # the fill/drain bubble shrinks by the same factor). Consumed by
    # parallel/pp.py's pipeline_apply / llama_pipeline_forward defaults.
    pp_virtual_stages: int = 1

    def __post_init__(self):
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if f.name.endswith("_size") and (not isinstance(v, int) or v < 1):
                raise ValueError(f"{f.name} must be a positive int, got {v!r}")
        if self.cp_size > 1 and self.sp_size > 1:
            # Same rule as the reference (parallelism_config.py:328-334).
            raise ValueError(
                "cp_size and sp_size cannot both be >1: ring context-parallelism "
                "and Ulysses sequence-parallelism are mutually exclusive."
            )
        if self.cp_rotate_method not in ("alltoall", "allgather"):
            raise ValueError(f"cp_rotate_method must be alltoall|allgather, got {self.cp_rotate_method}")
        if self.ep_size > 1 and self.ep_size > self.dp_shard_size * self.sp_size * self.tp_size:
            raise ValueError(
                "ep_size must divide into dp_shard*sp*tp (experts are sharded over "
                f"those axes); got ep={self.ep_size}"
            )
        if not isinstance(self.pp_virtual_stages, int) or self.pp_virtual_stages < 1:
            raise ValueError(
                f"pp_virtual_stages must be a positive int, got {self.pp_virtual_stages!r}"
            )

    # ------------------------------------------------------------------
    # Size properties (reference: parallelism_config.py:100-164)
    # ------------------------------------------------------------------

    @property
    def dp_size(self) -> int:
        return self.dp_replicate_size * self.dp_shard_size

    @property
    def dp_shard_cp_size(self) -> int:
        return self.dp_shard_size * self.cp_size

    @property
    def dp_cp_size(self) -> int:
        return self.dp_size * self.cp_size

    @property
    def non_pp_size(self) -> int:
        return self.dp_cp_size * self.sp_size * self.tp_size

    @property
    def total_size(self) -> int:
        return self.non_pp_size * self.pp_size

    @property
    def active_mesh_dims(self) -> tuple[str, ...]:
        return tuple(ax for ax in MESH_AXIS_ORDER if self.axis_size(ax) > 1)

    def axis_size(self, axis: str) -> int:
        return getattr(self, f"{axis}_size")

    # ------------------------------------------------------------------
    # Flattened logical axis groups — PartitionSpec-ready tuples.
    # (reference flattens real submeshes, parallelism_config.py:211-272;
    #  in JAX a tuple of axis names is equivalent and free.)
    # ------------------------------------------------------------------

    @property
    def dp_axes(self) -> tuple[str, ...]:
        return ("dp_replicate", "dp_shard")

    @property
    def fsdp_axes(self) -> tuple[str, ...]:
        """Axes FSDP-style param sharding spans: dp_shard joined with cp
        (reference: parallelism_config.py:157-164 ``fsdp_dim_names``)."""
        return ("dp_shard", "cp")

    @property
    def batch_axes(self) -> tuple[str, ...]:
        """Axes the global batch dim is sharded over. TP ranks see identical
        batches (reference: data_loader.py:1127-1163); cp/sp ranks share a batch
        but split the sequence dim."""
        return ("dp_replicate", "dp_shard")

    @property
    def seq_axes(self) -> tuple[str, ...]:
        """Axes the sequence dim is sharded over (cp or sp, never both)."""
        return ("cp", "sp")

    @property
    def ep_axes(self) -> tuple[str, ...]:
        """Mesh axes the expert dim of MoE layers is sharded over.

        ``ep`` borrows capacity from existing axes (no extra mesh dim — the
        DeepSpeed-MoE / torchtitan pattern, SURVEY.md §2.3 EP row): whole axes
        are taken greedily from ``(dp_shard, sp, tp)`` until their product is
        exactly ``ep_size``. Sub-axis sharding is not expressible in a
        PartitionSpec, so ``ep_size`` must be a product of full axis sizes."""
        if self.ep_size == 1:
            return ()
        # Exhaustive subset search (candidate count ≤ 3 so 2^3 subsets):
        # greedy-by-order can wrongly consume an early axis and then fail even
        # though a later subset matches exactly. Prefer earlier axes on ties.
        candidates = [ax for ax in ("dp_shard", "sp", "tp") if self.axis_size(ax) > 1]
        from itertools import combinations

        for r in range(1, len(candidates) + 1):
            for combo in combinations(candidates, r):
                prod = 1
                for ax in combo:
                    prod *= self.axis_size(ax)
                if prod == self.ep_size:
                    return tuple(combo)
        raise ValueError(
            f"ep_size={self.ep_size} is not a product of whole mesh axes from "
            f"(dp_shard={self.dp_shard_size}, sp={self.sp_size}, tp={self.tp_size}); "
            "choose ep equal to such a product."
        )

    @property
    def loss_reduce_axes(self) -> tuple[str, ...]:
        """Axes a scalar loss must be averaged over — dp + cp + sp
        (reference: SP loss averaged across sp+dp ranks, SURVEY.md §2.3)."""
        return ("dp_replicate", "dp_shard", "cp", "sp")

    # ------------------------------------------------------------------
    # Env round-trip (reference: parallelism_config.py:274-289)
    # ------------------------------------------------------------------

    @classmethod
    def from_env(cls) -> "ParallelismConfig":
        p = PARALLELISM_CONFIG_PREFIX
        return cls(
            dp_replicate_size=get_int_from_env([f"{p}DP_REPLICATE_SIZE"], 1),
            dp_shard_size=get_int_from_env([f"{p}DP_SHARD_SIZE"], 1),
            cp_size=get_int_from_env([f"{p}CP_SIZE"], 1),
            sp_size=get_int_from_env([f"{p}SP_SIZE"], 1),
            tp_size=get_int_from_env([f"{p}TP_SIZE"], 1),
            ep_size=get_int_from_env([f"{p}EP_SIZE"], 1),
            pp_size=get_int_from_env([f"{p}PP_SIZE"], 1),
            cp_rotate_method=parse_choice_from_env(f"{p}CP_ROTATE_METHOD", "alltoall"),
            pp_virtual_stages=get_int_from_env([f"{p}PP_VIRTUAL_STAGES"], 1),
        )

    def to_env(self) -> dict[str, str]:
        p = PARALLELISM_CONFIG_PREFIX
        env = {
            f"{p}DP_REPLICATE_SIZE": str(self.dp_replicate_size),
            f"{p}DP_SHARD_SIZE": str(self.dp_shard_size),
            f"{p}CP_SIZE": str(self.cp_size),
            f"{p}SP_SIZE": str(self.sp_size),
            f"{p}TP_SIZE": str(self.tp_size),
            f"{p}EP_SIZE": str(self.ep_size),
            f"{p}PP_SIZE": str(self.pp_size),
            f"{p}CP_ROTATE_METHOD": self.cp_rotate_method,
            f"{p}PP_VIRTUAL_STAGES": str(self.pp_virtual_stages),
        }
        return env

    # ------------------------------------------------------------------
    # Mesh construction
    # ------------------------------------------------------------------

    def infer_missing_axis(self, n_devices: int) -> "ParallelismConfig":
        """Fill ``dp_shard_size`` so the mesh covers all devices when the user
        left it at 1 and the product doesn't match (mirrors the reference's
        auto world-size fill)."""
        fixed = self.total_size
        if fixed == n_devices:
            return self
        if fixed > n_devices:
            # "Product does not divide device count" is actively misleading
            # here — nothing can be filled in; an axis must SHRINK. Name the
            # offending axes and their env vars.
            p = PARALLELISM_CONFIG_PREFIX
            axes = [
                f"{ax}={self.axis_size(ax)} ({p}{ax.upper()}_SIZE)"
                for ax in MESH_AXIS_ORDER + ("pp",)
                if self.axis_size(ax) > 1
            ]
            raise ParallelismOversubscriptionError(
                f"parallelism axes multiply to {fixed} but only {n_devices} "
                f"device(s) are visible: {', '.join(axes) or 'none >1'}. "
                f"Reduce one of these axes (or launch with more devices)."
            )
        if n_devices % fixed != 0:
            raise ValueError(
                f"parallelism product {fixed} does not divide device count {n_devices}"
            )
        return dataclasses.replace(self, dp_shard_size=self.dp_shard_size * (n_devices // fixed))

    def build_mesh(self, devices=None):
        """Build the canonical :class:`jax.sharding.Mesh`.

        Axes are always present (size-1 axes are free in GSPMD) so every
        PartitionSpec in the framework can name any canonical axis without
        branching on the active topology. Device order goes through
        ``mesh_utils.create_device_mesh`` on real TPU slices so the innermost
        axes (tp, sp, cp) land on ICI-adjacent chips; on CPU/virtual devices it
        falls back to a plain reshape.
        """
        import jax
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        n = len(devices)
        cfg = self.infer_missing_axis(n)
        # ``pp`` is a real (leading) mesh axis so stage sub-meshes are
        # contiguous device slices; the canonical GSPMD axes follow in the
        # reference's order. The only tensors whose PartitionSpec names
        # ``pp`` are stacked scanned-layer weights (sharded on the layer dim,
        # parallel/sharding.py) — everything else addresses the pipeline
        # through parallel/pp's shard_map schedule.
        axis_names = ("pp",) + MESH_AXIS_ORDER
        shape = (cfg.pp_size,) + tuple(cfg.axis_size(ax) for ax in MESH_AXIS_ORDER)
        platform = getattr(devices[0], "platform", "cpu")
        if platform in ("tpu", "axon") and n > 1:
            try:
                from jax.experimental import mesh_utils

                dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
            except Exception:
                dev_array = np.asarray(devices).reshape(shape)
        else:
            dev_array = np.asarray(devices).reshape(shape)
        return Mesh(dev_array, axis_names)

    def get_device_mesh(self, devices=None):
        return self.build_mesh(devices)

    def layout_dict(self) -> dict:
        """Axis-degree dict in the planner's artifact schema (planner.py
        plans, resharding.py plan manifests, and the ``plan`` CLI all speak
        this form)."""
        return {
            "dp_replicate": self.dp_replicate_size,
            "dp_shard": self.dp_shard_size,
            "cp": self.cp_size,
            "sp": self.sp_size,
            "tp": self.tp_size,
            "pp": self.pp_size,
            "ep": self.ep_size,
        }

    def __repr__(self) -> str:  # compact, hides size-1 axes
        active = {ax: self.axis_size(ax) for ax in MESH_AXIS_ORDER if self.axis_size(ax) > 1}
        if self.ep_size > 1:
            active["ep"] = self.ep_size
        if self.pp_size > 1:
            active["pp"] = self.pp_size
        if self.pp_virtual_stages > 1:
            active["pp_virtual_stages"] = self.pp_virtual_stages
        return f"ParallelismConfig({active or 'single-device'})"


def build_mesh_from_env(devices=None):
    """Convenience: decode ``PARALLELISM_CONFIG_*`` env and build the mesh."""
    return ParallelismConfig.from_env().build_mesh(devices)
