"""Crash-durable request journal — the serving stack's write-ahead log.

A hard serving-process death (SIGKILL, OOM-137, an injected ``engine_crash``)
loses the admission queue and every in-flight request unless their lifecycle
is durable OUTSIDE the process. This module is the request-plane twin of
fault_tolerance.py's atomic-checkpoint trust boundary: an append-only JSONL
WAL with per-record checksums whose segment seals reuse the same
stage → fsync → ``os.replace`` commit discipline, so what the journal says
happened, happened.

Record format — one record per line, torn-tail tolerant::

    <crc32 hex> <compact json>\\n

A line whose checksum does not match is skipped (and counted); a torn tail
(a partial final line — the write the crash interrupted) is truncated (and
counted) so the journal re-opens appendable. The records themselves are
engine-defined dicts with a ``"t"`` type tag:

- ``admit`` — written at ``submit()``: request id, the caller's
  ``client_request_id`` idempotency key, prompt tokens, budget, the
  serialized per-request PRNG key, the deadline BUDGET in monotonic-clock
  terms (``deadline_s`` + the submit-time ``perf_counter`` — never absolute
  wall time, so a wall-clock step during an outage cannot expire recovered
  requests), and a ``t_mono`` stamp;
- ``bind`` — the param ``weights_version`` the request bound at grant;
- ``progress`` — one batched record per tick with the tokens each live
  request emitted (observability; recovery replays from scratch);
- ``recovered`` — appended by ``ServingEngine.recover()`` per replayed
  in-flight request, so ``attempt`` accounting survives repeated crashes;
- ``terminal`` — the finished row (status, full padded token row, latency
  stats). Self-contained on purpose: compaction can drop a finished
  request's admit/bind/progress records while the terminal row keeps
  serving duplicate-``submit`` dedupe and crash-restart cached replies.

Durability knobs (``ServingConfig.journal_fsync``):

- ``every_record`` — flush + fsync after every append (no admitted request
  is ever lost; highest overhead);
- ``every_tick`` — buffered appends, one flush + fsync per engine tick
  (loses at most one tick on a crash; the default);
- ``os`` — flush to the OS page cache per tick, never fsync (survives a
  process crash, not a host power loss).

Segments rotate every ``segment_records`` appends: the active segment is
``wal_NNNNN.jsonl.open`` and sealing is fsync → ``os.replace`` to
``wal_NNNNN.jsonl`` → directory fsync. Compaction merges the sealed
segments into one, retiring the working records of terminally-statused
requests (their terminal rows survive, see above) while every unfinished
request's records are preserved verbatim.

Off by default everywhere: no journal exists unless you construct one (or
set ``ServingConfig.journal_dir``) — the serving hot path then holds one
``is None`` check per site. Deterministic chaos hooks: an attached
:class:`~accelerate_tpu.chaos.FaultInjector` is drawn at ``journal_append``
(``torn_write``: the append is torn mid-line, then re-written on a fresh
line — the checksum-skip path gets coverage while durability holds) and at
``journal_compact`` (``torn_write``: the compaction aborts cleanly, staging
removed, sealed segments untouched).

Usage::

    from accelerate_tpu import RequestJournal, ServingConfig, ServingEngine

    engine = ServingEngine(model, ServingConfig(journal_dir="wal/"))
    rid = engine.submit(prompt, client_request_id="req-0")
    ...                                   # process dies mid-flight
    engine = ServingEngine(model, ServingConfig(journal_dir="wal/"))
    engine.recover()                      # completed -> cached rows,
    ...                                   # in-flight -> bit-equal replay
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Optional

from .logging import get_logger

logger = get_logger(__name__)


def _log_ok() -> bool:
    """The repo logger needs accelerate state; the journal must also work
    standalone (no Accelerator), where these logs are just skipped."""
    from .state import PartialState

    return bool(PartialState._shared_state)

__all__ = ["RequestJournal", "JournalAdoptionError", "JOURNAL_FSYNC_POLICIES"]

#: Legal ``fsync`` policies, strongest first.
JOURNAL_FSYNC_POLICIES = ("every_record", "every_tick", "os")

_PREFIX = "wal_"
_SEALED = ".jsonl"
_OPEN = ".jsonl.open"
_COMPACT_STAGING = "compact.jsonl.tmp"
_ADOPTION = "adopted.lock"


class JournalAdoptionError(RuntimeError):
    """A second party tried to adopt a journal directory that is already
    claimed. Raised so a recovering fleet router and a restarting gang
    supervisor can never BOTH replay the same dead cell's WAL — double
    adoption is double execution."""


def _fsync_helpers():
    """The atomic-commit primitives are fault_tolerance.py's — ONE
    implementation of "durably on disk" for checkpoints and the journal."""
    from .fault_tolerance import _fsync_dir, _fsync_file

    return _fsync_file, _fsync_dir


def _encode(rec: dict) -> str:
    data = json.dumps(rec, separators=(",", ":"))
    return f"{zlib.crc32(data.encode('utf-8')):08x} {data}\n"


def _decode(line: str) -> Optional[dict]:
    """One checksummed line -> record dict, or None if torn/corrupt."""
    parts = line.split(" ", 1)
    if len(parts) != 2 or len(parts[0]) != 8:
        return None
    try:
        crc = int(parts[0], 16)
    except ValueError:
        return None
    if zlib.crc32(parts[1].encode("utf-8")) != crc:
        return None
    try:
        rec = json.loads(parts[1])
    except ValueError:
        return None
    return rec if isinstance(rec, dict) else None


class RequestJournal:
    """Append-only, checksummed, torn-tail-tolerant request WAL.

    ``fsync`` is one of :data:`JOURNAL_FSYNC_POLICIES`; ``segment_records``
    bounds the active segment before rotation (a seal + a compaction pass
    over the sealed set). ``chaos`` is an optional
    :class:`~accelerate_tpu.chaos.FaultInjector` (the owning engine attaches
    its own so one seeded schedule covers serving and journal faults
    together)."""

    def __init__(self, journal_dir: str, *, fsync: str = "every_tick",
                 segment_records: int = 512, chaos=None):
        if fsync not in JOURNAL_FSYNC_POLICIES:
            raise ValueError(
                f"journal fsync policy must be one of "
                f"{JOURNAL_FSYNC_POLICIES}, got {fsync!r}"
            )
        if int(segment_records) < 1:
            raise ValueError(
                f"segment_records must be >= 1, got {segment_records}"
            )
        self.dir = str(journal_dir)
        self.fsync = fsync
        self.segment_records = int(segment_records)
        self.chaos = chaos
        os.makedirs(self.dir, exist_ok=True)
        self._fh = None
        self._open_path: Optional[str] = None
        self._open_records = 0
        self._next_index = 1 + max(
            [i for i, _ in self._segments()], default=-1)
        self._dirty = False
        # Retirement state: rids with a journaled terminal row. Rebuilt by
        # replay() when this object is opened over an existing directory.
        self._retired: set[int] = set()
        self._admitted: set[int] = set()
        self._adoption_owner: Optional[str] = None
        self._c = {
            "appends": 0, "bytes_written": 0, "syncs": 0, "rotations": 0,
            "compactions": 0, "compact_aborts": 0, "records_retired": 0,
            "torn_writes": 0, "torn_tails": 0, "corrupt_skipped": 0,
        }

    # -- cross-process adoption -------------------------------------------

    @classmethod
    def adopt(cls, journal_dir: str, owner: str, *, force: bool = False,
              fsync: str = "every_tick", segment_records: int = 512,
              chaos=None) -> "RequestJournal":
        """Open another (dead) engine's journal directory for replay, first
        claiming the adoption sentinel so exactly one party drains it.
        Raises :class:`JournalAdoptionError` if someone else already holds
        the claim (``force=True`` evicts a stale sentinel — only safe when
        the previous adopter is known dead)."""
        jr = cls(journal_dir, fsync=fsync,
                 segment_records=segment_records, chaos=chaos)
        jr.acquire_adoption(owner, force=force)
        return jr

    @property
    def adopted(self) -> bool:
        return self._adoption_owner is not None

    def acquire_adoption(self, owner: str, *, force: bool = False) -> None:
        """Atomically claim this directory's adoption sentinel
        (``O_CREAT | O_EXCL`` — the filesystem arbitrates the race). The
        sentinel names the adopter and pid; it is invisible to segment
        scans (no ``wal_`` prefix) and removed by :meth:`release_adoption`
        or a clean :meth:`close`."""
        path = os.path.join(self.dir, _ADOPTION)
        payload = json.dumps({"owner": str(owner), "pid": os.getpid()},
                             separators=(",", ":")) + "\n"
        for attempt in (0, 1):
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                             0o644)
                break
            except FileExistsError:
                holder = None
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        holder = json.loads(f.read() or "{}")
                except (OSError, ValueError):
                    holder = None
                if not force or attempt:
                    who = (holder or {}).get("owner", "<unreadable>")
                    raise JournalAdoptionError(
                        f"journal {self.dir!r} is already adopted by "
                        f"{who!r} — refusing double adoption (pass "
                        f"force=True only if that adopter is known dead)"
                    )
                try:
                    os.remove(path)
                except OSError:
                    pass
        os.write(fd, payload.encode("utf-8"))
        os.fsync(fd)
        os.close(fd)
        _fsync_file, _fsync_dir = _fsync_helpers()
        _fsync_dir(self.dir)
        self._adoption_owner = str(owner)

    def adoption_holder(self) -> Optional[dict]:
        """The adoption sentinel's payload (owner, pid) if the directory is
        claimed, else None. Lets a restarting engine notice that a fleet
        router already drained this WAL before it replays anything."""
        try:
            with open(os.path.join(self.dir, _ADOPTION),
                      "r", encoding="utf-8") as f:
                raw = f.read()
        except OSError:
            return None
        try:
            holder = json.loads(raw or "{}")
        except ValueError:
            return {}
        return holder if isinstance(holder, dict) else {}

    def release_adoption(self) -> None:
        """Drop the adoption claim (no-op if this journal never held it)."""
        if self._adoption_owner is None:
            return
        try:
            os.remove(os.path.join(self.dir, _ADOPTION))
        except OSError:
            pass
        self._adoption_owner = None

    # -- segment bookkeeping ----------------------------------------------

    def _segments(self) -> list[tuple[int, str]]:
        """Every journal segment on disk as sorted ``(index, path)`` —
        sealed and crash-orphaned ``.open`` files alike (an index exists as
        exactly one of the two)."""
        out = []
        for fn in os.listdir(self.dir):
            if not fn.startswith(_PREFIX):
                continue
            if fn.endswith(_OPEN):
                idx = fn[len(_PREFIX):-len(_OPEN)]
            elif fn.endswith(_SEALED):
                idx = fn[len(_PREFIX):-len(_SEALED)]
            else:
                continue
            try:
                out.append((int(idx), os.path.join(self.dir, fn)))
            except ValueError:
                continue
        return sorted(out)

    def _ensure_open(self) -> None:
        if self._fh is None:
            name = f"{_PREFIX}{self._next_index:05d}{_OPEN}"
            self._next_index += 1
            self._open_path = os.path.join(self.dir, name)
            self._fh = open(self._open_path, "a", encoding="utf-8")
            self._open_records = 0

    def _seal(self) -> None:
        """Commit the active segment: fsync its bytes, then atomically
        rename away the ``.open`` suffix, then fsync the directory — the
        same stage→fsync→replace discipline as a checkpoint commit."""
        if self._fh is None:
            return
        _fsync_file, _fsync_dir = _fsync_helpers()
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._fh.close()
        self._fh = None
        self._dirty = False
        sealed = self._open_path[: -len(_OPEN)] + _SEALED
        os.replace(self._open_path, sealed)
        _fsync_dir(self.dir)
        self._open_path = None
        self._c["rotations"] += 1

    # -- the append path ---------------------------------------------------

    def append(self, rec: dict, *, tick: int = 0, unit: int = 0) -> None:
        """Durably (per policy) append one record. ``tick``/``unit`` key
        the deterministic chaos draw at ``journal_append``."""
        self._ensure_open()
        line = _encode(rec)
        if self.chaos is not None:
            fault = self.chaos.draw("journal_append", tick, unit=unit)
            if fault is not None and fault.kind == "torn_write":
                # A torn append: half the line lands, newline-terminated
                # garbage (the checksum-skip path on replay). The journal
                # detects the short write and re-writes the record whole —
                # durability holds, the corruption machinery gets exercised.
                frag = line[: max(1, len(line) // 2)].rstrip("\n") + "\n"
                self._fh.write(frag)
                self._c["torn_writes"] += 1
                self._c["bytes_written"] += len(frag)
        self._fh.write(line)
        self._c["appends"] += 1
        self._c["bytes_written"] += len(line)
        rid = rec.get("rid")
        t = rec.get("t")
        if rid is not None:
            if t == "terminal":
                self._retired.add(int(rid))
            elif t == "admit":
                self._admitted.add(int(rid))
        if self.fsync == "every_record":
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._c["syncs"] += 1
        else:
            self._dirty = True
        self._open_records += 1
        if self._open_records >= self.segment_records:
            self._seal()
            self.compact(tick=tick)

    def tick_flush(self) -> None:
        """The per-tick durability point: flush buffered appends, fsync
        under ``every_tick`` (the ``os`` policy stops at the page cache)."""
        if self._fh is None or not self._dirty:
            return
        self._fh.flush()
        if self.fsync == "every_tick":
            os.fsync(self._fh.fileno())
            self._c["syncs"] += 1
        self._dirty = False

    # -- replay + compaction ----------------------------------------------

    def _read_segment(self, path: str, repair: bool = False) -> list[dict]:
        """Records from one segment, skipping corrupt lines. A torn tail
        (no trailing newline) is counted and — with ``repair`` — truncated
        in place so the file is clean for whatever appends next."""
        with open(path, "rb") as f:
            raw = f.read()
        if not raw:
            return []
        keep = len(raw)
        if not raw.endswith(b"\n"):
            self._c["torn_tails"] += 1
            nl = raw.rfind(b"\n")
            keep = nl + 1 if nl >= 0 else 0
            if repair:
                with open(path, "rb+") as f:
                    f.truncate(keep)
        out = []
        for line in raw[:keep].decode("utf-8", errors="replace").splitlines():
            if not line:
                continue
            rec = _decode(line)
            if rec is None:
                self._c["corrupt_skipped"] += 1
                continue
            out.append(rec)
        return out

    def replay(self) -> tuple[list[dict], dict]:
        """Read every record on disk, in append order, repairing torn tails
        as it goes. Returns ``(records, scan)`` where ``scan`` counts what
        recovery needs to report: segments read, records kept, torn tails
        truncated, corrupt lines skipped. Also rebuilds the retirement sets
        so compaction works on a freshly re-opened directory."""
        torn0 = self._c["torn_tails"]
        corrupt0 = self._c["corrupt_skipped"]
        records: list[dict] = []
        segs = self._segments()
        for _, path in segs:
            records.extend(self._read_segment(path, repair=True))
        for rec in records:
            rid = rec.get("rid")
            if rid is None:
                continue
            if rec.get("t") == "terminal":
                self._retired.add(int(rid))
            elif rec.get("t") == "admit":
                self._admitted.add(int(rid))
        return records, {
            "segments": len(segs),
            "records": len(records),
            "torn_tails": self._c["torn_tails"] - torn0,
            "corrupt_skipped": self._c["corrupt_skipped"] - corrupt0,
        }

    def compact(self, *, tick: int = 0) -> int:
        """Merge the SEALED segments into one, dropping the admit / bind /
        progress / recovered records of terminally-statused requests (their
        self-contained terminal rows are kept — they back duplicate-submit
        dedupe and crash-restart cached replies). Unfinished requests'
        records pass through verbatim. Returns the number of records
        retired; 0 when there is nothing to do or the (chaos-injected)
        staging write tears — the sealed segments are untouched either
        way."""
        sealed = [(i, p) for i, p in self._segments() if p.endswith(_SEALED)]
        if len(sealed) < 2 and not self._retired:
            return 0
        if not sealed:
            return 0
        _fsync_file, _fsync_dir = _fsync_helpers()
        kept: list[dict] = []
        dropped = 0
        for _, path in sealed:
            for rec in self._read_segment(path):
                rid = rec.get("rid")
                t = rec.get("t")
                if t == "progress":
                    toks = {k: v for k, v in (rec.get("toks") or {}).items()
                            if int(k) not in self._retired}
                    if not toks:
                        dropped += 1
                        continue
                    if len(toks) != len(rec.get("toks") or {}):
                        rec = dict(rec, toks=toks)
                elif (rid is not None and int(rid) in self._retired
                        and t != "terminal"):
                    dropped += 1
                    continue
                kept.append(rec)
        staging = os.path.join(self.dir, _COMPACT_STAGING)
        torn = None
        if self.chaos is not None:
            torn = self.chaos.draw("journal_compact", tick)
        try:
            with open(staging, "w", encoding="utf-8") as f:
                for rec in kept:
                    f.write(_encode(rec))
                f.flush()
                if torn is not None and torn.kind == "torn_write":
                    raise OSError("injected torn_write during compaction")
                os.fsync(f.fileno())
        except OSError as e:
            # Abort cleanly: staging removed, every sealed segment intact.
            try:
                os.remove(staging)
            except OSError:
                pass
            self._c["compact_aborts"] += 1
            if _log_ok():
                logger.warning("journal: compaction aborted (%s) — sealed "
                               "segments untouched", e)
            return 0
        # Commit: the merged segment atomically replaces the FIRST sealed
        # segment, then the rest are unlinked. A crash between the two
        # steps leaves duplicate (idempotently re-read) records, never a
        # missing one.
        os.replace(staging, sealed[0][1])
        for _, path in sealed[1:]:
            try:
                os.remove(path)
            except OSError:
                pass
        _fsync_dir(self.dir)
        self._c["compactions"] += 1
        self._c["records_retired"] += dropped
        return dropped

    # -- lifecycle / reporting --------------------------------------------

    def close(self) -> None:
        """Clean shutdown: seal the active segment (full fsync + atomic
        rename) regardless of the append-path fsync policy, and release
        any adoption claim this journal holds."""
        if self._fh is not None:
            self._seal()
        self.release_adoption()

    def stats(self) -> dict:
        """The journal telemetry block (embedded under
        ``ServingEngine.stats()["journal"]``, pinned by
        tests/test_schemas.py)."""
        return {
            "dir": self.dir,
            "fsync": self.fsync,
            "appends": self._c["appends"],
            "bytes_written": self._c["bytes_written"],
            "syncs": self._c["syncs"],
            "rotations": self._c["rotations"],
            "compactions": self._c["compactions"],
            "compact_aborts": self._c["compact_aborts"],
            "records_retired": self._c["records_retired"],
            "torn_writes": self._c["torn_writes"],
            "torn_tails": self._c["torn_tails"],
            "corrupt_skipped": self._c["corrupt_skipped"],
            "pending": len(self._admitted - self._retired),
            "retired": len(self._retired),
        }
